"""Benchmark ladder: one JSON line per BASELINE.json training config.

Configs (BASELINE.json "configs"): ResNet-50/ImageNet, Transformer-big NMT,
BERT-base pretrain — fwd+bwd+optimizer step throughput on one chip.
Each line: {"metric", "value", "unit", "vs_baseline", "detail"}.
vs_baseline = achieved MFU / 0.50 (the north-star target from BASELINE.json:
>=50% MFU on v5e; the reference publishes no TPU training numbers, so the
target ratio is the comparison point). The flagship BERT line prints LAST.
"""

from __future__ import annotations

import json
import sys
import time

import jax

# Fast counter-based PRNG: threefry costs ~25% of the BERT step (dropout
# masks); rbg is the standard choice for TPU training loops.
jax.config.update("jax_default_prng_impl", "unsafe_rbg")

import jax.numpy as jnp  # noqa: E402

# v5e (v5 lite) peak bf16 matmul throughput per chip.
PEAK_FLOPS = {"tpu": 197e12, "cpu": 1e12, "gpu": 100e12}


def _measure(step, state, batch, n_steps):
    """Warmup/compile once, then time n_steps chained steps (the final
    float() forces a host sync — on tunneled backends block_until_ready
    can return before execution)."""
    state, loss = step(state, batch, jax.random.key(2))
    float(loss)
    t0 = time.perf_counter()
    for i in range(n_steps):
        state, loss = step(state, batch, jax.random.key(3 + i))
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    return dt, final_loss


def _emit(metric, sps_chip, mfu, detail):
    print(json.dumps({
        "metric": metric,
        "value": round(sps_chip, 2),
        "unit": "samples/s/chip",
        "vs_baseline": round(mfu / 0.50, 4),
        "detail": detail,
    }), flush=True)


def _run_ladder(metric, batch_sizes, build, flops_per_sample, n_steps,
                n_chips, platform, extra_detail, mesh=None):
    """build(bs) -> (step, state, batch); try batch sizes until one fits.
    Tracing/timing runs under mesh_guard so model-level shard() activation
    constraints see the mesh."""
    from paddle_tpu.parallel import mesh_guard
    import contextlib

    last_err = None
    for bs in batch_sizes:
        try:
            guard = mesh_guard(mesh) if mesh is not None \
                else contextlib.nullcontext()
            with guard:
                step, state, batch = build(bs)
                dt, final_loss = _measure(step, state, batch, n_steps)
            sps = bs * n_steps / dt
            mfu = sps * flops_per_sample / (
                n_chips * PEAK_FLOPS.get(platform, 1e12))
            _emit(metric, sps / n_chips, mfu, {
                "batch_size": bs, "chips": n_chips, "platform": platform,
                "mfu": round(mfu, 4),
                "step_ms": round(1000 * dt / n_steps, 2),
                "final_loss": final_loss, **extra_detail,
            })
            return True
        except Exception as e:  # OOM → try smaller batch
            last_err = e
            continue
    print(json.dumps({"metric": metric, "value": 0.0,
                      "unit": "samples/s/chip", "vs_baseline": 0.0,
                      "error": str(last_err)[:300]}), flush=True)
    return False


def bench_resnet50(mesh, n_chips, platform, on_tpu):
    import optax

    from paddle_tpu.models import resnet
    from paddle_tpu.parallel.train import TrainStrategy, make_train_step

    cfg = resnet.ResNetConfig.resnet50() if on_tpu \
        else resnet.ResNetConfig.tiny()
    hw = 224 if on_tpu else 32
    batch_sizes = [256, 128, 64, 32] if on_tpu else [16]

    def build(bs):
        params, axes = resnet.init(jax.random.key(0), cfg)

        def loss_fn(p, b, r):
            # NHWC end-to-end: a real TPU input pipeline delivers NHWC;
            # the NCHW shim exists for reference-API parity only.
            return resnet.loss_fn(p, cfg, b, r, data_format="NHWC")

        init_state, step = make_train_step(
            loss_fn, optax.sgd(0.1, momentum=0.9), mesh, axes,
            strategy=TrainStrategy(shard_optimizer_states=False),
            has_aux=True)
        state = init_state(params)
        batch = resnet.make_batch(jax.random.key(1), cfg, bs, hw=hw,
                                  data_format="NHWC")
        return step, state, batch

    return _run_ladder(
        "resnet50_train_samples_per_sec_per_chip" if on_tpu
        else "resnet_tiny_cpu_samples_per_sec",
        batch_sizes, build, cfg.flops_per_image(hw),
        20 if on_tpu else 3, n_chips, platform, {"image_hw": hw},
        mesh=mesh)


def bench_transformer_big(mesh, n_chips, platform, on_tpu):
    import optax

    from paddle_tpu.models import transformer
    from paddle_tpu.parallel.train import TrainStrategy, make_train_step

    cfg = transformer.TransformerConfig.big() if on_tpu \
        else transformer.TransformerConfig.tiny()
    src_T = tgt_T = 128 if on_tpu else 16
    batch_sizes = [128, 64, 32, 16] if on_tpu else [8]

    def build(bs):
        params, axes = transformer.init(jax.random.key(0), cfg)

        def loss_fn(p, b, r):
            return transformer.nmt_loss(p, cfg, b, rng=r)

        init_state, step = make_train_step(
            loss_fn, optax.adam(1e-4), mesh, axes,
            strategy=TrainStrategy(shard_optimizer_states=True))
        state = init_state(params)
        batch = transformer.make_batch(jax.random.key(1), cfg, bs,
                                       src_T=src_T, tgt_T=tgt_T)
        return step, state, batch

    return _run_ladder(
        "transformer_big_nmt_train_samples_per_sec_per_chip" if on_tpu
        else "transformer_tiny_cpu_samples_per_sec",
        batch_sizes, build, cfg.train_flops_per_seq(src_T, tgt_T),
        20 if on_tpu else 3, n_chips, platform,
        {"src_len": src_T, "tgt_len": tgt_T,
         "tokens_per_sample": src_T + tgt_T}, mesh=mesh)


def bench_bert(mesh, n_chips, platform, on_tpu):
    import optax

    from paddle_tpu.models import bert
    from paddle_tpu.parallel.train import TrainStrategy, make_train_step

    cfg = bert.BertConfig.base() if on_tpu else bert.BertConfig.tiny()
    seq_len = 128 if on_tpu else 64
    batch_sizes = [256, 512, 128, 64, 32] if on_tpu else [16]

    def build(bs):
        params, axes = bert.init(jax.random.key(0), cfg)

        def loss_fn(p, b, r):
            return bert.pretrain_loss(p, cfg, b, rng=r, deterministic=False)

        init_state, step = make_train_step(
            loss_fn, optax.adamw(1e-4), mesh, axes,
            strategy=TrainStrategy(shard_optimizer_states=True))
        state = init_state(params)
        batch = bert.make_batch(jax.random.key(1), cfg, batch_size=bs,
                                seq_len=seq_len)
        return step, state, batch

    # n_masked is a function of seq_len alone (make_batch masks a fixed
    # fraction) — read it off a tiny probe batch for the FLOPs model
    probe = bert.make_batch(jax.random.key(1), cfg, batch_size=2,
                            seq_len=seq_len)
    n_masked = probe["masked_positions"].shape[1]
    return _run_ladder(
        "bert_base_train_samples_per_sec_per_chip" if on_tpu
        else "bert_tiny_cpu_samples_per_sec",
        batch_sizes, build, cfg.train_flops_per_seq(seq_len, n_masked),
        20 if on_tpu else 3, n_chips, platform, {"seq_len": seq_len},
        mesh=mesh)


def bench_bert_long(mesh, n_chips, platform, on_tpu):
    """Long-sequence config (T=4096): measures the production attention
    path (auto gate = XLA bf16-scores at every single-chip shape;
    PROFILE.md round 3) and A/Bs the Pallas flash kernel at the same
    shape, making the gate decision reproducible from BENCH output."""
    if not on_tpu:
        return True  # flash path is TPU-only; CPU ladder covers tiny BERT
    import optax

    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.models import bert
    from paddle_tpu.parallel.train import TrainStrategy, make_train_step

    seq_len = 4096
    cfg = bert.BertConfig(max_len=seq_len, dropout=0.0)

    def build_with(mode):
        def build(bs):
            set_flags({"FLAGS_flash_attention": mode})
            params, axes = bert.init(jax.random.key(0), cfg)

            def loss_fn(p, b, r):
                return bert.pretrain_loss(p, cfg, b, rng=r,
                                          deterministic=True)

            init_state, step = make_train_step(
                loss_fn, optax.adamw(1e-4), mesh, axes,
                strategy=TrainStrategy(shard_optimizer_states=True))
            state = init_state(params)
            batch = bert.make_batch(jax.random.key(1), cfg, batch_size=bs,
                                    seq_len=seq_len)
            return step, state, batch
        return build

    probe = bert.make_batch(jax.random.key(1), cfg, batch_size=2,
                            seq_len=seq_len)
    n_masked = probe["masked_positions"].shape[1]
    flops = cfg.train_flops_per_seq(seq_len, n_masked)

    # A/B the Pallas flash kernel at a fixed shape (bs=2): its per-sample
    # time vs the production path below keeps the never-flash auto-gate
    # decision reproducible from BENCH output alone. Guarded like the
    # ladder (shard() constraints need the mesh) and dropped before the
    # ladder runs so its params/moments/batch don't hold HBM.
    from paddle_tpu.parallel import mesh_guard

    flash_detail = "not_measured"
    try:
        with mesh_guard(mesh):
            step, state, batch = build_with("on")(2)
            dt, _ = _measure(step, state, batch, 5)
        flash_detail = round(1000 * dt / 5, 2)
        del step, state, batch
    except Exception as e:
        flash_detail = f"fail: {str(e)[:120]}"
    jax.clear_caches()

    ok = _run_ladder(
        "bert_long_seq4096_train_samples_per_sec_per_chip",
        [8, 4, 2, 1], build_with("auto"), flops, 5, n_chips, platform,
        {"seq_len": seq_len, "attention": "xla_bf16_scores(auto gate)",
         "pallas_flash_step_ms_bs2": flash_detail}, mesh=mesh)
    set_flags({"FLAGS_flash_attention": "auto"})
    return ok


def main():
    from paddle_tpu.parallel import MeshConfig, make_mesh

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    mesh = make_mesh(MeshConfig(dp=-1), devices=jax.devices()[:1]) \
        if len(jax.devices()) == 1 else make_mesh(MeshConfig(dp=-1))
    n_chips = mesh.devices.size

    ok = True
    for bench in (bench_resnet50, bench_transformer_big, bench_bert_long,
                  bench_bert):
        ok = bench(mesh, n_chips, platform, on_tpu) and ok
        jax.clear_caches()  # free compiled executables between configs
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
