"""Benchmark ladder: one JSON line per BASELINE.json training config.

Configs (BASELINE.json "configs"): MNIST LeNet Program-surface smoke,
ResNet-50/ImageNet, Transformer-big NMT, BERT long-sequence (T=4096),
BERT-base pretrain — fwd+bwd+optimizer step throughput on one chip.
Each line: {"metric", "value", "unit", "vs_baseline", "detail"}.
vs_baseline = achieved MFU / 0.50 for the training configs (the
north-star from BASELINE.json: >=50% MFU on v5e; the reference
publishes no TPU training numbers, so the target ratio is the
comparison point); the LeNet smoke line instead reports a 0/1
convergence flag (unit samples/s through the fluid Program/Executor
pipeline). BASELINE config 5 (ResNet-50 DP on v5e-8) needs 8 real
chips and is validated by dryrun_multichip + the ParallelExecutor
parity tests instead. The flagship BERT line prints LAST.
"""

from __future__ import annotations

import json
import sys
import time

import jax

# Fast counter-based PRNG: threefry costs ~25% of the BERT step (dropout
# masks); rbg is the standard choice for TPU training loops.
jax.config.update("jax_default_prng_impl", "unsafe_rbg")

import jax.numpy as jnp  # noqa: E402

# v5e (v5 lite) peak bf16 matmul throughput per chip.
PEAK_FLOPS = {"tpu": 197e12, "cpu": 1e12, "gpu": 100e12}


def _measure(step, state, batch, n_steps):
    """Warmup/compile once, then time n_steps chained steps (the final
    float() forces a host sync — on tunneled backends block_until_ready
    can return before execution)."""
    state, loss = step(state, batch, jax.random.key(2))
    float(loss)
    t0 = time.perf_counter()
    for i in range(n_steps):
        state, loss = step(state, batch, jax.random.key(3 + i))
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    return dt, final_loss


def _emit_raw(metric, value, unit, vs_baseline, detail):
    print(json.dumps({"metric": metric, "value": round(value, 2),
                      "unit": unit, "vs_baseline": round(vs_baseline, 4),
                      "detail": detail}), flush=True)


def _emit(metric, sps_chip, mfu, detail):
    _emit_raw(metric, sps_chip, "samples/s/chip", mfu / 0.50, detail)


def _run_ladder(metric, batch_sizes, build, flops_per_sample, n_steps,
                n_chips, platform, extra_detail, mesh=None):
    """build(bs) -> (step, state, batch); try batch sizes until one fits.
    Tracing/timing runs under mesh_guard so model-level shard() activation
    constraints see the mesh."""
    from paddle_tpu.parallel import mesh_guard
    import contextlib

    last_err = None
    for bs in batch_sizes:
        try:
            guard = mesh_guard(mesh) if mesh is not None \
                else contextlib.nullcontext()
            with guard:
                step, state, batch = build(bs)
                dt, final_loss = _measure(step, state, batch, n_steps)
            sps = bs * n_steps / dt
            mfu = sps * flops_per_sample / (
                n_chips * PEAK_FLOPS.get(platform, 1e12))
            _emit(metric, sps / n_chips, mfu, {
                "batch_size": bs, "chips": n_chips, "platform": platform,
                "mfu": round(mfu, 4),
                "step_ms": round(1000 * dt / n_steps, 2),
                "final_loss": final_loss, **extra_detail,
            })
            return True
        except Exception as e:  # OOM → try smaller batch
            last_err = e
            continue
    print(json.dumps({"metric": metric, "value": 0.0,
                      "unit": "samples/s/chip", "vs_baseline": 0.0,
                      "error": str(last_err)[:300]}), flush=True)
    return False


def bench_lenet_smoke(mesh, n_chips, platform, on_tpu):
    """BASELINE config 1: MNIST LeNet single-chip smoke — the fluid
    Program/Executor surface itself on the chip (feed numpy, fetch a
    converging loss), not the jax-native path. Value is samples/s
    through the FULL Program pipeline; vs_baseline=1.0 marks
    convergence (loss halved), 0.0 otherwise."""
    import numpy as np

    import paddle_tpu as pt

    rng = np.random.RandomState(0)
    X = rng.rand(256, 1, 28, 28).astype("float32")
    Y = rng.randint(0, 10, (256, 1)).astype("int64")
    main, startup = pt.Program(), pt.Program()
    with pt.framework.unique_name.guard(), pt.program_guard(main, startup):
        x = pt.layers.data(name="x", shape=[1, 28, 28], dtype="float32")
        y = pt.layers.data(name="y", shape=[1], dtype="int64")
        c = pt.layers.conv2d(x, num_filters=6, filter_size=5, act="relu")
        c = pt.layers.pool2d(c, pool_size=2, pool_stride=2)
        c = pt.layers.conv2d(c, num_filters=16, filter_size=5, act="relu")
        c = pt.layers.pool2d(c, pool_size=2, pool_stride=2)
        h = pt.layers.fc(c, size=120, act="relu")
        h = pt.layers.fc(h, size=84, act="relu")
        logits = pt.layers.fc(h, size=10)
        loss = pt.layers.mean(
            pt.layers.softmax_with_cross_entropy(logits, y))
        pt.optimizer.Adam(learning_rate=2e-3).minimize(loss)
    place = pt.TPUPlace() if on_tpu else pt.CPUPlace()
    exe = pt.Executor(place)
    try:
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            losses = [float(np.asarray(
                exe.run(main, feed={"x": X, "y": Y},
                        fetch_list=[loss])[0]).reshape(()))]
            n_steps = 80
            t0 = time.perf_counter()
            for _ in range(n_steps):
                losses.append(float(np.asarray(
                    exe.run(main, feed={"x": X, "y": Y},
                            fetch_list=[loss])[0]).reshape(())))
            dt = time.perf_counter() - t0
    except Exception as e:  # a fluid-path failure must not kill the ladder
        _emit_raw("lenet_mnist_program_smoke_samples_per_sec", 0.0,
                  "samples/s", 0.0, {"error": str(e)[:300]})
        return False
    converged = losses[-1] < losses[0] * 0.5
    _emit_raw("lenet_mnist_program_smoke_samples_per_sec",
              256 * n_steps / dt, "samples/s",
              1.0 if converged else 0.0,
              {"platform": platform, "first_loss": round(losses[0], 4),
               "final_loss": round(losses[-1], 4),
               "steps": n_steps, "batch_size": 256,
               "note": "fluid Program/Executor surface end to end "
                       "(per-call host round trip included)"})
    return converged


def bench_resnet50(mesh, n_chips, platform, on_tpu):
    import optax

    from paddle_tpu.models import resnet
    from paddle_tpu.parallel.train import TrainStrategy, make_train_step

    cfg = resnet.ResNetConfig.resnet50() if on_tpu \
        else resnet.ResNetConfig.tiny()
    hw = 224 if on_tpu else 32
    batch_sizes = [256, 128, 64, 32] if on_tpu else [16]

    def build(bs):
        params, axes = resnet.init(jax.random.key(0), cfg)

        def loss_fn(p, b, r):
            # NHWC end-to-end: a real TPU input pipeline delivers NHWC;
            # the NCHW shim exists for reference-API parity only.
            return resnet.loss_fn(p, cfg, b, r, data_format="NHWC")

        init_state, step = make_train_step(
            loss_fn, optax.sgd(0.1, momentum=0.9), mesh, axes,
            strategy=TrainStrategy(shard_optimizer_states=False),
            has_aux=True)
        state = init_state(params)
        batch = resnet.make_batch(jax.random.key(1), cfg, bs, hw=hw,
                                  data_format="NHWC")
        return step, state, batch

    return _run_ladder(
        "resnet50_train_samples_per_sec_per_chip" if on_tpu
        else "resnet_tiny_cpu_samples_per_sec",
        batch_sizes, build, cfg.flops_per_image(hw),
        20 if on_tpu else 3, n_chips, platform, {"image_hw": hw},
        mesh=mesh)


def bench_transformer_big(mesh, n_chips, platform, on_tpu):
    import optax

    from paddle_tpu.models import transformer
    from paddle_tpu.parallel.train import TrainStrategy, make_train_step

    cfg = transformer.TransformerConfig.big() if on_tpu \
        else transformer.TransformerConfig.tiny()
    src_T = tgt_T = 128 if on_tpu else 16
    batch_sizes = [128, 64, 32, 16] if on_tpu else [8]

    def build(bs):
        params, axes = transformer.init(jax.random.key(0), cfg)

        def loss_fn(p, b, r):
            return transformer.nmt_loss(p, cfg, b, rng=r)

        init_state, step = make_train_step(
            loss_fn, optax.adam(1e-4), mesh, axes,
            strategy=TrainStrategy(shard_optimizer_states=True))
        state = init_state(params)
        batch = transformer.make_batch(jax.random.key(1), cfg, bs,
                                       src_T=src_T, tgt_T=tgt_T)
        return step, state, batch

    return _run_ladder(
        "transformer_big_nmt_train_samples_per_sec_per_chip" if on_tpu
        else "transformer_tiny_cpu_samples_per_sec",
        batch_sizes, build, cfg.train_flops_per_seq(src_T, tgt_T),
        20 if on_tpu else 3, n_chips, platform,
        {"src_len": src_T, "tgt_len": tgt_T,
         "tokens_per_sample": src_T + tgt_T}, mesh=mesh)


def bench_bert(mesh, n_chips, platform, on_tpu):
    import optax

    from paddle_tpu.models import bert
    from paddle_tpu.parallel.train import TrainStrategy, make_train_step

    cfg = bert.BertConfig.base() if on_tpu else bert.BertConfig.tiny()
    seq_len = 128 if on_tpu else 64
    batch_sizes = [256, 512, 128, 64, 32] if on_tpu else [16]

    def build(bs):
        params, axes = bert.init(jax.random.key(0), cfg)

        def loss_fn(p, b, r):
            return bert.pretrain_loss(p, cfg, b, rng=r, deterministic=False)

        init_state, step = make_train_step(
            loss_fn, optax.adamw(1e-4), mesh, axes,
            strategy=TrainStrategy(shard_optimizer_states=True))
        state = init_state(params)
        batch = bert.make_batch(jax.random.key(1), cfg, batch_size=bs,
                                seq_len=seq_len)
        return step, state, batch

    # n_masked is a function of seq_len alone (make_batch masks a fixed
    # fraction) — read it off a tiny probe batch for the FLOPs model
    probe = bert.make_batch(jax.random.key(1), cfg, batch_size=2,
                            seq_len=seq_len)
    n_masked = probe["masked_positions"].shape[1]
    return _run_ladder(
        "bert_base_train_samples_per_sec_per_chip" if on_tpu
        else "bert_tiny_cpu_samples_per_sec",
        batch_sizes, build, cfg.train_flops_per_seq(seq_len, n_masked),
        20 if on_tpu else 3, n_chips, platform, {"seq_len": seq_len},
        mesh=mesh)


def bench_bert_long(mesh, n_chips, platform, on_tpu):
    """Long-sequence config (T=4096): measures the production attention
    path (auto gate = splash_attention with v5e-tuned blocks for
    T>=1024; PROFILE.md round 4) and A/Bs the XLA bf16-scores path at
    the same shape, making the gate decision reproducible from BENCH
    output."""
    if not on_tpu:
        return True  # flash path is TPU-only; CPU ladder covers tiny BERT
    import optax

    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.models import bert
    from paddle_tpu.parallel.train import TrainStrategy, make_train_step

    seq_len = 4096
    cfg = bert.BertConfig(max_len=seq_len, dropout=0.0)

    def build_with(mode):
        def build(bs):
            set_flags({"FLAGS_flash_attention": mode})
            params, axes = bert.init(jax.random.key(0), cfg)

            def loss_fn(p, b, r):
                return bert.pretrain_loss(p, cfg, b, rng=r,
                                          deterministic=True)

            init_state, step = make_train_step(
                loss_fn, optax.adamw(1e-4), mesh, axes,
                strategy=TrainStrategy(shard_optimizer_states=True))
            state = init_state(params)
            batch = bert.make_batch(jax.random.key(1), cfg, batch_size=bs,
                                    seq_len=seq_len)
            return step, state, batch
        return build

    probe = bert.make_batch(jax.random.key(1), cfg, batch_size=2,
                            seq_len=seq_len)
    n_masked = probe["masked_positions"].shape[1]
    flops = cfg.train_flops_per_seq(seq_len, n_masked)

    # A/B the XLA bf16-scores path at a fixed shape (bs=8): its per-step
    # time vs the production (splash) ladder below keeps the auto-gate
    # decision reproducible from BENCH output alone. Guarded like the
    # ladder (shard() constraints need the mesh) and dropped before the
    # ladder runs so its params/moments/batch don't hold HBM.
    from paddle_tpu.parallel import mesh_guard

    xla_detail = "not_measured"
    try:
        with mesh_guard(mesh):
            step, state, batch = build_with("off")(8)
            dt, _ = _measure(step, state, batch, 5)
        xla_detail = round(1000 * dt / 5, 2)
        del step, state, batch
    except Exception as e:
        xla_detail = f"fail: {str(e)[:120]}"
    jax.clear_caches()

    # what the auto gate actually selects at this mesh size: splash is
    # single-chip/manual-region only (pallas_call is not GSPMD-
    # partitionable — attention.py _mesh_partitionable)
    attn_label = ("splash(auto gate)" if mesh.devices.size == 1
                  else "xla_bf16_scores(auto gate: multi-chip GSPMD)")
    ok = _run_ladder(
        "bert_long_seq4096_train_samples_per_sec_per_chip",
        [8, 4, 2, 1], build_with("auto"), flops, 5, n_chips,
        platform,
        {"seq_len": seq_len, "attention": attn_label,
         "xla_bf16_step_ms_bs8": xla_detail}, mesh=mesh)
    set_flags({"FLAGS_flash_attention": "auto"})
    return ok


def main():
    from paddle_tpu.parallel import MeshConfig, make_mesh

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    mesh = make_mesh(MeshConfig(dp=-1), devices=jax.devices()[:1]) \
        if len(jax.devices()) == 1 else make_mesh(MeshConfig(dp=-1))
    n_chips = mesh.devices.size

    ok = True
    for bench in (bench_lenet_smoke, bench_resnet50, bench_transformer_big,
                  bench_bert_long, bench_bert):
        ok = bench(mesh, n_chips, platform, on_tpu) and ok
        jax.clear_caches()  # free compiled executables between configs
    # BASELINE config 5 (ResNet-50 data-parallel on v5e-8) needs 8 real
    # chips; its sharded step is validated by __graft_entry__.dryrun and
    # the ParallelExecutor parity tests on the virtual mesh.
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
