"""Benchmark ladder: one JSON line per BASELINE.json training config.

Configs (BASELINE.json "configs"): MNIST LeNet Program-surface smoke,
ResNet-50/ImageNet, Transformer-big NMT, BERT long-sequence (T=4096),
BERT-base pretrain — fwd+bwd+optimizer step throughput on one chip.
Each line: {"metric", "value", "unit", "vs_baseline", "detail"}.
vs_baseline = achieved MFU / 0.50 for the training configs (the
north-star from BASELINE.json: >=50% MFU on v5e; the reference
publishes no TPU training numbers, so the target ratio is the
comparison point); the LeNet smoke line instead reports a 0/1
convergence flag (unit samples/s through the fluid Program/Executor
pipeline). BASELINE config 5 (ResNet-50 DP on v5e-8) needs 8 real
chips and is validated by dryrun_multichip + the ParallelExecutor
parity tests instead. The flagship BERT line prints LAST.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

# Fast counter-based PRNG: threefry costs ~25% of the BERT step (dropout
# masks); rbg is the standard choice for TPU training loops.
jax.config.update("jax_default_prng_impl", "unsafe_rbg")

import jax.numpy as jnp  # noqa: E402

from paddle_tpu.observability import device_peaks as _peaks  # noqa: E402

# Per-platform peak bf16 matmul throughput per chip — the shared table
# (observability/device_peaks.py) the live MFU gauge uses too, so the
# offline bench MFU and paddle_tpu_mfu agree by construction.
PEAK_FLOPS = _peaks.PLATFORM_PEAK_FLOPS


def _measure(step, state, batch, n_steps):
    """Warmup/compile once, then time n_steps chained steps (the final
    float() forces a host sync — on tunneled backends block_until_ready
    can return before execution)."""
    state, loss = step(state, batch, jax.random.key(2))
    float(loss)
    t0 = time.perf_counter()
    for i in range(n_steps):
        state, loss = step(state, batch, jax.random.key(3 + i))
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    return dt, final_loss


# Structured run environment attached to EVERY metric line (ROADMAP
# item 5 / VERDICT weak #7): rc=1 with env.fallback_reason recorded on
# the lines means "chip wedged, CPU fallback recorded" — the evidence
# lint (tools/refresh_evidence.py bench_fallback_recorded) can then
# tell that apart from "harness crashed" (no structured lines at all).
# The parent fills the probe verdict into PADDLE_TPU_BENCH_* env vars
# so measurement children agree with it.
_BENCH_ENV = {"platform": None, "tpu_reachable": None,
              "fallback_reason": None}


def _init_bench_env(platform=None):
    reach = os.environ.get("PADDLE_TPU_BENCH_TPU_REACHABLE")
    _BENCH_ENV["platform"] = platform or \
        os.environ.get("PADDLE_TPU_BENCH_PLATFORM")
    _BENCH_ENV["tpu_reachable"] = None if reach is None else reach == "1"
    _BENCH_ENV["fallback_reason"] = \
        os.environ.get("PADDLE_TPU_BENCH_FALLBACK_REASON") or None


def _emit_raw(metric, value, unit, vs_baseline, detail):
    print(json.dumps({"metric": metric, "value": round(value, 2),
                      "unit": unit, "vs_baseline": round(vs_baseline, 4),
                      "env": dict(_BENCH_ENV),
                      "detail": detail}), flush=True)


def _emit(metric, sps_chip, mfu, detail):
    _emit_raw(metric, sps_chip, "samples/s/chip", mfu / 0.50, detail)


def _run_ladder(metric, batch_sizes, build, flops_per_sample, n_steps,
                n_chips, platform, extra_detail, mesh=None):
    """build(bs) -> (step, state, batch); try batch sizes until one fits.
    Tracing/timing runs under mesh_guard so model-level shard() activation
    constraints see the mesh."""
    from paddle_tpu.parallel import mesh_guard
    import contextlib

    last_err = None
    for bs in batch_sizes:
        try:
            guard = mesh_guard(mesh) if mesh is not None \
                else contextlib.nullcontext()
            with guard:
                step, state, batch = build(bs)
                dt, final_loss = _measure(step, state, batch, n_steps)
            sps = bs * n_steps / dt
            mfu = sps * flops_per_sample / (
                n_chips * PEAK_FLOPS.get(platform, 1e12))
            # feed the continuous-attribution layer with the measured
            # window so the LIVE gauge (paddle_tpu_mfu{kind="bench"})
            # and this offline number come from the same sample — the
            # within-10% cross-check PROFILE.md documents
            from paddle_tpu.observability import memwatch as _memwatch
            from paddle_tpu.observability import perfwatch as _perfwatch

            _perfwatch.record_step(
                "bench", dt, flops=bs * n_steps * flops_per_sample,
                n_devices=n_chips,
                device_kind=getattr(jax.devices()[0], "device_kind",
                                    platform))
            mem = _memwatch.sweep(force=True) or {}
            _emit(metric, sps / n_chips, mfu, {
                "batch_size": bs, "chips": n_chips, "platform": platform,
                "mfu": round(mfu, 4),
                "mfu_live": round(_perfwatch.mfu("bench"), 4),
                "hbm_peak_bytes": int(_memwatch.watermark_bytes()),
                "hbm_live_bytes": int(mem.get("total_bytes", 0)),
                "step_ms": round(1000 * dt / n_steps, 2),
                "final_loss": final_loss, **extra_detail,
            })
            return True
        except Exception as e:  # OOM → try smaller batch
            last_err = e
            continue
    print(json.dumps({"metric": metric, "value": 0.0,
                      "unit": "samples/s/chip", "vs_baseline": 0.0,
                      "env": dict(_BENCH_ENV),
                      "error": str(last_err)[:300]}), flush=True)
    return False


def _build_lenet_program(pt):
    """LeNet training Program used by the smoke and pipeline benches."""
    main, startup = pt.Program(), pt.Program()
    with pt.framework.unique_name.guard(), pt.program_guard(main, startup):
        x = pt.layers.data(name="x", shape=[1, 28, 28], dtype="float32")
        y = pt.layers.data(name="y", shape=[1], dtype="int64")
        c = pt.layers.conv2d(x, num_filters=6, filter_size=5, act="relu")
        c = pt.layers.pool2d(c, pool_size=2, pool_stride=2)
        c = pt.layers.conv2d(c, num_filters=16, filter_size=5, act="relu")
        c = pt.layers.pool2d(c, pool_size=2, pool_stride=2)
        h = pt.layers.fc(c, size=120, act="relu")
        h = pt.layers.fc(h, size=84, act="relu")
        logits = pt.layers.fc(h, size=10)
        loss = pt.layers.mean(
            pt.layers.softmax_with_cross_entropy(logits, y))
        pt.optimizer.Adam(learning_rate=2e-3).minimize(loss)
    return main, startup, loss


def bench_lenet_smoke(mesh, n_chips, platform, on_tpu):
    """BASELINE config 1: MNIST LeNet single-chip smoke — the fluid
    Program/Executor surface itself on the chip (feed numpy, fetch a
    converging loss), not the jax-native path. Value is samples/s
    through the FULL Program pipeline; vs_baseline=1.0 marks
    convergence (loss halved), 0.0 otherwise."""
    import numpy as np

    import paddle_tpu as pt

    rng = np.random.RandomState(0)
    X = rng.rand(256, 1, 28, 28).astype("float32")
    Y = rng.randint(0, 10, (256, 1)).astype("int64")
    main, startup, loss = _build_lenet_program(pt)
    place = pt.TPUPlace() if on_tpu else pt.CPUPlace()
    exe = pt.Executor(place)
    try:
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            losses = [float(np.asarray(
                exe.run(main, feed={"x": X, "y": Y},
                        fetch_list=[loss])[0]).reshape(()))]
            n_steps = 80
            t0 = time.perf_counter()
            for _ in range(n_steps):
                losses.append(float(np.asarray(
                    exe.run(main, feed={"x": X, "y": Y},
                            fetch_list=[loss])[0]).reshape(())))
            dt = time.perf_counter() - t0
            cache = exe.cache_stats()
            # chained executable A/B (ROADMAP item 5 / VERDICT weak
            # perf): BENCH_r05 recorded the ROLLED scan-chained path
            # ~2.8x slower per step than per-call on CPU. Profiling
            # showed the while-loop itself is the cost (a pure-jax
            # loop-vs-scan control reproduces 2.6x — XLA-CPU restricts
            # conv parallelism inside while bodies; carry donation was
            # already intact), so run_chained now defaults to "auto":
            # unrolled windows on CPU, rolled scan on TPU. Both sides
            # of the A/B are recorded here: "rolled" is the explicit
            # unroll=False opt-in, "auto" is the new default.
            chain_n = 40

            def time_chained(**kw):
                exe.run_chained(main, feed={"x": X, "y": Y},
                                fetch_list=[loss], n_steps=chain_n,
                                **kw)  # compile
                t0 = time.perf_counter()
                ch = exe.run_chained(main, feed={"x": X, "y": Y},
                                     fetch_list=[loss], n_steps=chain_n,
                                     **kw)
                last = float(np.asarray(ch[0]).ravel()[-1])  # sync
                return time.perf_counter() - t0, last

            rolled_dt, _ = time_chained(unroll=False)
            chain_dt, last = time_chained()  # the "auto" default
    except Exception as e:  # a fluid-path failure must not kill the ladder
        _emit_raw("lenet_mnist_program_smoke_samples_per_sec", 0.0,
                  "samples/s", 0.0, {"error": str(e)[:300]})
        return False
    converged = losses[-1] < losses[0] * 0.5 and last < losses[0] * 0.5
    _emit_raw("lenet_mnist_program_smoke_samples_per_sec",
              256 * n_steps / dt, "samples/s",
              1.0 if converged else 0.0,
              {"platform": platform, "first_loss": round(losses[0], 4),
               "final_loss": round(losses[-1], 4),
               "steps": n_steps, "batch_size": 256,
               "executor_cache": cache,
               "scan_chained_samples_per_sec":
                   round(256 * chain_n / chain_dt, 2),
               "scan_chained_steps": chain_n,
               "chained": {
                   "per_call_samples_per_sec": round(256 * n_steps / dt, 2),
                   "rolled_scan_samples_per_sec":
                       round(256 * chain_n / rolled_dt, 2),
                   "auto_samples_per_sec":
                       round(256 * chain_n / chain_dt, 2),
                   "rolled_slowdown_vs_per_call":
                       round((256 * n_steps / dt)
                             / (256 * chain_n / rolled_dt), 3),
                   "auto_slowdown_vs_per_call":
                       round((256 * n_steps / dt)
                             / (256 * chain_n / chain_dt), 3),
                   "note": "rolled scan (unroll=False) is the BENCH_r05 "
                           "regression, now opt-in on CPU; auto = new "
                           "default (unrolled windows on CPU, rolled "
                           "scan on TPU); donation on the scan carry "
                           "verified intact (pure-jax control "
                           "reproduces the while-loop penalty)"},
               "note": "per-call loop includes the host round trip; "
                       "scan_chained = cached-executable fast path "
                       "(one dispatch covers all steps under "
                       "unroll=auto)"})
    return converged


def bench_pipeline(mesh, n_chips, platform, on_tpu):
    """Host-overlap pipeline block: the SAME LeNet Program trained on
    the same per-step batches by (a) the per-call loop — one dispatch +
    synchronous numpy fetch per step, the pre-async executor rhythm —
    and (b) the streaming driver — run_stream window micro-chaining
    with device prefetch and lazy fetches. Value is streaming
    samples/s; vs_baseline = (streaming/per-call speedup) / 1.5, the
    acceptance bar. detail carries both throughputs plus each phase's
    host-blocked fraction (host_blocked_seconds delta over wall) and
    the final-loss delta proving the drivers compute the same thing."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.observability import telemetry as T

    rng = np.random.RandomState(0)
    # Dispatch-bound regime (the INFER_BENCH/BENCH_r05 failure mode —
    # host round trip ≫ device compute): small per-step batch so the
    # per-call loop's fixed per-step host cost dominates. On TPU the
    # tunnel makes EVERY shape dispatch-bound; on CPU this shape is
    # where the regime lives.
    bs, n_steps, window = 1, 128, 16
    X = rng.rand(n_steps, bs, 1, 28, 28).astype("float32")
    Y = rng.randint(0, 10, (n_steps, bs, 1)).astype("int64")
    feeds = [{"x": X[i], "y": Y[i]} for i in range(n_steps)]
    main, startup, loss = _build_lenet_program(pt)
    place = pt.TPUPlace() if on_tpu else pt.CPUPlace()
    exe = pt.Executor(place)

    try:
        # warm every executable on a throwaway scope so neither timed
        # phase pays a compile (the program cache is scope-independent)
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            exe.run(main, feed=feeds[0], fetch_list=[loss])
            for h in exe.run_stream(main, iter(feeds[:window + 1]),
                                    fetch_list=[loss], window=window):
                h.result()

        def phase(streaming):
            with pt.scope_guard(pt.Scope()):
                exe.run(startup)
                blocked0 = T.host_blocked_total()
                t0 = time.perf_counter()
                if streaming:
                    last = None
                    for h in exe.run_stream(main, iter(feeds),
                                            fetch_list=[loss],
                                            window=window):
                        last = h
                    final = float(np.asarray(last.result()[0]).ravel()[-1])
                else:
                    vals = [exe.run(main, feed=f, fetch_list=[loss])[0]
                            for f in feeds]
                    final = float(np.asarray(vals[-1]).reshape(()))
                dt = time.perf_counter() - t0
                blocked = T.host_blocked_total() - blocked0
            return dt, final, blocked

        # best-of-2 per driver: a noisy-neighbor CPU must not decide
        # the speedup gate; losses are identical across repeats by
        # construction (fresh scope, same seed, same feeds)
        percall_dt, percall_loss, percall_blocked = min(
            (phase(False) for _ in range(2)), key=lambda r: r[0])
        stream_dt, stream_loss, stream_blocked = min(
            (phase(True) for _ in range(2)), key=lambda r: r[0])
    except Exception as e:
        _emit_raw("pipeline_stream_samples_per_sec", 0.0, "samples/s",
                  0.0, {"error": str(e)[:300]})
        return False

    percall_sps = bs * n_steps / percall_dt
    stream_sps = bs * n_steps / stream_dt
    speedup = stream_sps / percall_sps
    loss_delta = abs(stream_loss - percall_loss)
    blocked_percall = percall_blocked / percall_dt
    blocked_stream = stream_blocked / stream_dt
    # acceptance: 1.5x throughput, OR proven overlap where the
    # per-call loop is host-bound (blocked > 70% while streaming
    # stays < 30%) — the TPU-tunnel shape of the win
    ok = (speedup >= 1.5
          or (blocked_percall > 0.7 and blocked_stream < 0.3)) \
        and loss_delta <= 1e-6 * max(1.0, abs(percall_loss))
    _emit_raw("pipeline_stream_samples_per_sec", stream_sps, "samples/s",
              speedup / 1.5,
              {"platform": platform, "batch_size": bs, "steps": n_steps,
               "window": window,
               "per_call_samples_per_sec": round(percall_sps, 2),
               "speedup": round(speedup, 3),
               "host_blocked_frac_per_call": round(blocked_percall, 4),
               "host_blocked_frac_stream": round(blocked_stream, 4),
               "final_loss_per_call": round(percall_loss, 6),
               "final_loss_stream": round(stream_loss, 6),
               "loss_delta": loss_delta,
               "note": "per-call = dispatch + sync numpy fetch per "
                       "step; stream = run_stream unrolled-window "
                       "micro-chaining + lazy fetches (device "
                       "prefetch pays off on real TPU transfers, not "
                       "CPU, so the CPU stream phase feeds host "
                       "arrays)"})
    return ok


# ---------------------------------------------------------------------------
# Coldstart block (ISSUE 6): restart economics of the persistent compile
# cache (PADDLE_TPU_COMPILE_CACHE) and the serving warmstart artifact.
# Unlike every other block this one measures PROCESS BOUNDARIES — a cold
# start IS a fresh process — so all jax work happens in measurement
# children and the block's own process never initializes a backend (on
# TPU it would hold the chip its children need to boot).
# ---------------------------------------------------------------------------


def _coldstart_child(argv):
    """`bench.py --coldstart-child MODE ...`: one fresh-process
    measurement for bench_coldstart.

    prep  --model-dir D      save the small serving softmax model
    train --steps N          LeNet per-call + chained steps under the
                             inherited PADDLE_TPU_COMPILE_CACHE
    serve --model-dir D --buckets B --artifact A [--load-artifact]
                             boot a serving Engine, warm every bucket,
                             answer one fixed batch; cold mode exports
                             the warmstart artifact, warm mode boots
                             from it

    Prints ONE JSON line: compile/cache telemetry deltas plus losses
    (train) or the reply digest (serve). The parent measures child wall
    time itself; in-child timings cover only the phase being claimed
    (serve's warmup window = time-to-first-healthy)."""
    import argparse
    import hashlib

    import numpy as np

    ap = argparse.ArgumentParser(prog="bench --coldstart-child")
    ap.add_argument("mode", choices=("prep", "train", "serve"))
    ap.add_argument("--model-dir")
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--buckets", default="1,2,4,8")
    ap.add_argument("--artifact")
    ap.add_argument("--load-artifact", action="store_true")
    args = ap.parse_args(argv)

    if os.environ.get("PADDLE_TPU_BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt
    from paddle_tpu import observability

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"

    def _telemetry_summary():
        """This process's compile seconds, total and per kind (the
        ISSUE acceptance measure: paddle_tpu_compile_seconds — cache
        hits record NO compile, so a fully-warm process sums to zero),
        plus the compile-cache outcome counts."""
        snap = observability.snapshot()
        comp = snap.get("paddle_tpu_compile_seconds") or {"series": []}
        cache = snap.get("paddle_tpu_compile_cache_total") \
            or {"series": []}
        outcomes = {}
        for s in cache["series"]:
            ev = s["labels"].get("event", "?")
            outcomes[ev] = outcomes.get(ev, 0) + int(s["value"])
        by_kind: dict = {}
        counts_by_kind: dict = {}
        for s in comp["series"]:
            k = s["labels"].get("kind", "?")
            by_kind[k] = round(by_kind.get(k, 0.0) + s["sum"], 4)
            counts_by_kind[k] = counts_by_kind.get(k, 0) + s["count"]
        return {
            "compile_seconds": round(
                sum(s["sum"] for s in comp["series"]), 4),
            "compiles": int(sum(s["count"] for s in comp["series"])),
            "compile_seconds_by_kind": by_kind,
            "compiles_by_kind": counts_by_kind,
            "cache_events": outcomes,
        }

    if args.mode == "prep":
        main, startup = pt.Program(), pt.Program()
        with pt.framework.unique_name.guard(), \
                pt.program_guard(main, startup):
            x = pt.layers.data(name="x", shape=[4], dtype="float32")
            pred = pt.layers.fc(input=x, size=3, act="softmax")
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup)
        pt.io.save_inference_model(args.model_dir, ["x"], [pred], exe,
                                   main_program=main)
        print(json.dumps({"ok": True}), flush=True)
        return 0

    if args.mode == "train":
        rng = np.random.RandomState(0)
        X = rng.rand(64, 1, 28, 28).astype("float32")
        Y = rng.randint(0, 10, (64, 1)).astype("int64")
        main, startup, loss = _build_lenet_program(pt)
        exe = pt.Executor(pt.TPUPlace() if on_tpu else pt.CPUPlace())
        losses = []
        t0 = time.perf_counter()
        with pt.scope_guard(pt.Scope()):
            exe.run(startup)
            for _ in range(args.steps):
                losses.append(float(np.asarray(
                    exe.run(main, feed={"x": X, "y": Y},
                            fetch_list=[loss])[0]).reshape(())))
            ch = exe.run_chained(main, feed={"x": X, "y": Y},
                                 fetch_list=[loss], n_steps=4)
            losses.extend(float(v) for v in np.asarray(ch[0]).ravel())
        wall = time.perf_counter() - t0
        print(json.dumps(dict(_telemetry_summary(), platform=platform,
                              losses=losses,
                              run_wall_seconds=round(wall, 4))),
              flush=True)
        return 0

    # serve: time-to-first-healthy = Engine construction (which adopts
    # the warmstart artifact when --load-artifact) through warmup()
    from paddle_tpu.serving import Engine, ServingConfig

    buckets = tuple(int(b) for b in args.buckets.split(","))
    t0 = time.perf_counter()
    cfg = ServingConfig(args.model_dir, buckets=buckets, use_tpu=on_tpu,
                        warmstart=args.artifact if args.load_artifact
                        else None)
    engine = Engine(cfg)
    ready = engine.warmup()
    ttfh = time.perf_counter() - t0
    if args.artifact and not args.load_artifact:
        engine.export_warmstart(args.artifact)
    # batch 2 rides warmed bucket 2 in both smoke and full bucket
    # sets — the reply must not mint a signature the artifact never
    # carried (real traffic is bucket-shaped by the batcher)
    X = np.random.RandomState(7).rand(2, 4).astype("float32")
    out = engine.run_batch({"x": X})
    digest = hashlib.sha256()
    for name in sorted(out):
        a = np.ascontiguousarray(out[name])
        digest.update(f"{name}:{a.dtype}:{a.shape}".encode())
        digest.update(a.tobytes())
    print(json.dumps(dict(
        _telemetry_summary(), platform=platform, buckets_ready=ready,
        warmstart_adopted=engine.warmstart_adopted,
        ttfh_seconds=round(ttfh, 4),
        reply_sha256=digest.hexdigest())), flush=True)
    return 0


def bench_coldstart(smoke=False):
    """Cold vs warm restart, cold vs warm serving boot — each phase a
    fresh subprocess so "restart" means what an operator means by it.

    Emits two metric lines (value = cold/warm ratio of in-process
    paddle_tpu_compile_seconds; acceptance bar 5x, so vs_baseline =
    speedup / 5):

      coldstart_restart_compile_speedup    training process restart
          against the same PADDLE_TPU_COMPILE_CACHE dir; ok requires
          the warm run to report ZERO fresh compiles and bit-identical
          losses.
      coldstart_serving_warmup_compile_speedup   serving boot, cold
          compile vs warmstart-artifact adoption — the value is the
          warmup compile-seconds ratio (the ISSUE acceptance measure);
          detail carries the time-to-first-healthy walls and their own
          ttfh_speedup ratio (smaller: TTFH includes model load and
          adoption I/O) plus the reply digests proving bit-identical
          answers.
    """
    import shutil
    import tempfile

    here = os.path.abspath(__file__)
    base_env = dict(os.environ)
    # the serving phase must prove the ARTIFACT path on its own — an
    # inherited compile-cache dir would warm its "cold" boot
    base_env.pop("PADDLE_TPU_COMPILE_CACHE", None)
    steps = 3 if smoke else 6
    buckets = "1,2" if smoke else "1,2,4,8"
    tmp = tempfile.mkdtemp(prefix="paddle_tpu_coldstart_")

    def child(argv, extra_env=None, timeout_s=300):
        rc, out, err = _run_bounded(
            [sys.executable, here, "--coldstart-child"] + list(argv),
            timeout_s, env=dict(base_env, **(extra_env or {})))
        if rc != 0:
            raise RuntimeError(
                f"coldstart child {argv[0]} rc={rc}: "
                f"{(err or '')[-500:]}")
        lines = [ln for ln in (out or "").splitlines()
                 if ln.startswith("{")]
        if not lines:
            raise RuntimeError(f"coldstart child {argv[0]} emitted no "
                               f"JSON: {(err or '')[-500:]}")
        return json.loads(lines[-1])

    def speedup(cold_s, warm_s):
        # a fully-warm process records NO compiles, so the denominator
        # floor (1 ms) keeps the ratio finite while preserving "huge"
        return cold_s / max(warm_s, 1e-3)

    train_ok = serve_ok = False
    try:
        try:
            cache_dir = os.path.join(tmp, "cache")
            os.makedirs(cache_dir, exist_ok=True)
            cache_env = {"PADDLE_TPU_COMPILE_CACHE": cache_dir}
            targs = ["train", "--steps", str(steps)]
            t0 = time.perf_counter()
            cold = child(targs, cache_env)
            cold_wall = time.perf_counter() - t0
            t0 = time.perf_counter()
            warm = child(targs, cache_env)
            warm_wall = time.perf_counter() - t0
            ratio = speedup(cold["compile_seconds"],
                            warm["compile_seconds"])
            loss_delta = float(max(
                abs(a - b) for a, b in zip(cold["losses"],
                                           warm["losses"])))
            train_ok = (ratio >= 5.0 and loss_delta == 0.0
                        and warm["compiles"] == 0
                        and warm["cache_events"].get("hit", 0)
                        >= cold["compiles"])
            _emit_raw(
                "coldstart_restart_compile_speedup", ratio, "x",
                ratio / 5.0,
                {"platform": cold["platform"], "steps": steps,
                 "cold_compile_seconds": cold["compile_seconds"],
                 "warm_compile_seconds": warm["compile_seconds"],
                 "cold_compiles": cold["compiles"],
                 "warm_compiles": warm["compiles"],
                 "warm_cache_hits": warm["cache_events"].get("hit", 0),
                 "cold_process_wall_s": round(cold_wall, 2),
                 "warm_process_wall_s": round(warm_wall, 2),
                 "loss_delta": loss_delta,
                 "note": "fresh process per phase, shared "
                         "PADDLE_TPU_COMPILE_CACHE dir; process wall "
                         "includes interpreter+jax import, "
                         "compile_seconds is the ISSUE acceptance "
                         "measure"})
        except Exception as e:
            _emit_raw("coldstart_restart_compile_speedup", 0.0, "x",
                      0.0, {"error": str(e)[:300]})

        try:
            model_dir = os.path.join(tmp, "model")
            child(["prep", "--model-dir", model_dir])
            art = os.path.join(tmp, "warmstart.bin")
            sargs = ["serve", "--model-dir", model_dir,
                     "--buckets", buckets, "--artifact", art]
            t0 = time.perf_counter()
            scold = child(sargs)
            scold_wall = time.perf_counter() - t0
            t0 = time.perf_counter()
            swarm = child(sargs + ["--load-artifact"])
            swarm_wall = time.perf_counter() - t0
            # the artifact targets WARMUP compilation (kind="infer" —
            # one executable per bucket); the model-LOAD step program
            # compiles either way and is reported separately in detail
            cold_infer = scold["compile_seconds_by_kind"].get(
                "infer", 0.0)
            warm_infer = swarm["compile_seconds_by_kind"].get(
                "infer", 0.0)
            ratio = speedup(cold_infer, warm_infer)
            identical = (scold["reply_sha256"] == swarm["reply_sha256"])
            n_buckets = len(buckets.split(","))
            serve_ok = (ratio >= 5.0 and identical
                        and swarm["warmstart_adopted"] == n_buckets
                        and swarm["compiles_by_kind"].get("infer", 0)
                        == 0)
            _emit_raw(
                "coldstart_serving_warmup_compile_speedup", ratio, "x",
                ratio / 5.0,
                {"platform": scold["platform"], "buckets": buckets,
                 "cold_warmup_compile_seconds": cold_infer,
                 "warm_warmup_compile_seconds": warm_infer,
                 "cold_total_compile_seconds": scold["compile_seconds"],
                 "warm_total_compile_seconds": swarm["compile_seconds"],
                 "cold_ttfh_seconds": scold["ttfh_seconds"],
                 "warm_ttfh_seconds": swarm["ttfh_seconds"],
                 "ttfh_speedup": round(
                     scold["ttfh_seconds"]
                     / max(swarm["ttfh_seconds"], 1e-3), 1),
                 "cold_process_wall_s": round(scold_wall, 2),
                 "warm_process_wall_s": round(swarm_wall, 2),
                 "warmstart_adopted": swarm["warmstart_adopted"],
                 "artifact_bytes": os.path.getsize(art),
                 "replies_identical": identical,
                 "note": "cold boot compiles every bucket and exports "
                         "the warmstart artifact; warm boot adopts it "
                         "(ttfh = Engine construction through "
                         "warmup()); totals include the model-LOAD "
                         "step compile, which the artifact does not "
                         "target (enable PADDLE_TPU_COMPILE_CACHE to "
                         "kill that one too)"})
        except Exception as e:
            _emit_raw("coldstart_serving_warmup_compile_speedup", 0.0,
                      "x", 0.0, {"error": str(e)[:300]})
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return train_ok and serve_ok


# ---------------------------------------------------------------------------
# Precision block (ISSUE 7): the mixed-precision + int8 hot paths.
# Train A/B — the SAME LeNet Program trained by the streaming driver
# under f32 vs mixed_bf16 (bf16 feeds end to end, so the hot path pays
# ZERO silent upcasts) with loss parity asserted. Serve A/B — the same
# saved model behind the bucketed Engine at f32 vs int8 (calibrated
# post-training quantization) with per-request p50/p99 and the reply
# accuracy delta. On TPU these are the native-width numbers the
# roadmap's per-chip-speed axis asks for; on CPU the block verifies
# both paths end to end (bf16/int8 emulation makes CPU speedups
# meaningless, so acceptance is parity + zero-upcast, not throughput).
# ---------------------------------------------------------------------------


# stated acceptance bounds (also asserted by the --smoke slow test):
# per-step |loss_mixed - loss_f32| <= 0.05 * max(1, |loss_f32|) with a
# final-loss relative delta <= 0.05; int8 replies within 0.05 absolute
# of f32 on the same bucket set (softmax outputs, so 0.05 is 5 points)
PRECISION_LOSS_REL_BOUND = 0.05
PRECISION_INT8_ABS_BOUND = 0.05


def bench_precision(mesh, n_chips, platform, on_tpu):
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.core import precision as pr
    from paddle_tpu.core.executor import _normalize_feed

    smoke = bool(os.environ.get("PADDLE_TPU_BENCH_SMOKE")
                 or os.environ.get("PADDLE_TPU_COLDSTART_SMOKE"))
    ok_train = ok_serve = False

    # -- train A/B: f32 vs mixed_bf16 through run_stream ----------------
    try:
        import ml_dtypes

        rng = np.random.RandomState(0)
        bs = 8
        n_steps, window = (32, 8) if smoke else (128, 16)
        X = rng.rand(n_steps, bs, 1, 28, 28).astype("float32")
        Y = rng.randint(0, 10, (n_steps, bs, 1)).astype("int64")
        main, startup, loss = _build_lenet_program(pt)
        place = pt.TPUPlace() if on_tpu else pt.CPUPlace()
        exe = pt.Executor(place)

        def feeds_for(policy):
            # the input pipeline delivers the policy's width: bf16
            # feeds under mixed_bf16, proving the hot path never
            # upcasts them (the pre-PR executor astype'd every feed
            # to the declared f32 — core/executor.py _normalize_feed)
            if policy == "mixed_bf16":
                Xp = X.astype(ml_dtypes.bfloat16)
            else:
                Xp = X
            return [{"x": Xp[i], "y": Y[i]} for i in range(n_steps)]

        def phase(policy):
            pr.set_program_precision(main, policy)
            feeds = feeds_for(policy)
            # warm compiles on a throwaway scope
            with pt.scope_guard(pt.Scope()):
                exe.run(startup)
                for h in exe.run_stream(main, iter(feeds[:window + 1]),
                                        fetch_list=[loss], window=window):
                    h.result()
            with pt.scope_guard(pt.Scope()):
                exe.run(startup)
                losses = []
                t0 = time.perf_counter()
                for h in exe.run_stream(main, iter(feeds),
                                        fetch_list=[loss], window=window):
                    losses.extend(
                        float(v) for v in np.asarray(
                            h.result()[0], np.float32).ravel())
                dt = time.perf_counter() - t0
            return dt, losses

        # best-of-2 per policy: noisy-neighbor CPU must not decide the A/B
        f32_dt, f32_losses = min((phase("f32") for _ in range(2)),
                                 key=lambda r: r[0])
        bf16_dt, bf16_losses = min((phase("mixed_bf16")
                                    for _ in range(2)),
                                   key=lambda r: r[0])
        pr.set_program_precision(main, None)

        # zero-upcast probe: a bf16 feed under the mixed policy must
        # come back from feed normalization UNTOUCHED (same buffer, no
        # astype) — the acceptance criterion made checkable
        xb = jnp.asarray(X[0].astype(ml_dtypes.bfloat16))
        probe = _normalize_feed(main, {"x": xb},
                                pr.get_policy("mixed_bf16"))
        upcast_free = probe["x"] is xb and probe["x"].dtype == xb.dtype

        rel = [abs(a - b) / max(1.0, abs(b))
               for a, b in zip(bf16_losses, f32_losses)]
        max_rel = max(rel)
        final_rel = abs(bf16_losses[-1] - f32_losses[-1]) \
            / max(1.0, abs(f32_losses[-1]))
        f32_sps = bs * n_steps / f32_dt
        bf16_sps = bs * n_steps / bf16_dt
        speedup = bf16_sps / f32_sps
        ok_train = (max_rel <= PRECISION_LOSS_REL_BOUND
                    and final_rel <= PRECISION_LOSS_REL_BOUND
                    and upcast_free
                    and f32_losses[-1] < f32_losses[0]
                    and bf16_losses[-1] < bf16_losses[0])
        _emit_raw(
            "precision_bf16_train_samples_per_sec", bf16_sps,
            "samples/s", speedup,
            {"platform": platform, "batch_size": bs, "steps": n_steps,
             "window": window, "policy": "mixed_bf16",
             "f32_samples_per_sec": round(f32_sps, 2),
             "bf16_vs_f32_speedup": round(speedup, 3),
             "loss_rel_delta_max": round(max_rel, 5),
             "loss_rel_delta_final": round(final_rel, 5),
             "loss_rel_bound": PRECISION_LOSS_REL_BOUND,
             "final_loss_f32": round(f32_losses[-1], 5),
             "final_loss_bf16": round(bf16_losses[-1], 5),
             "bf16_feeds_upcast_free": bool(upcast_free),
             "note": "run_stream windowed driver, bf16 feeds end to "
                     "end under mixed_bf16 (zero per-step astype on "
                     "the hot path); CPU emulates bf16 so only TPU "
                     "speedups are meaningful"})
    except Exception as e:
        _emit_raw("precision_bf16_train_samples_per_sec", 0.0,
                  "samples/s", 0.0, {"error": str(e)[:300]})

    # -- serve A/B: f32 vs int8 through the bucketed Engine --------------
    try:
        import shutil
        import tempfile

        from paddle_tpu.serving import Engine, ServingConfig

        tmp = tempfile.mkdtemp(prefix="paddle_tpu_precision_")
        try:
            md = os.path.join(tmp, "model")
            mainm, startm = pt.Program(), pt.Program()
            with pt.framework.unique_name.guard(), \
                    pt.program_guard(mainm, startm):
                x = pt.layers.data(name="x", shape=[64], dtype="float32")
                h = pt.layers.fc(input=x, size=128, act="relu")
                predv = pt.layers.fc(input=h, size=16, act="softmax")
            exe2 = pt.Executor(pt.CPUPlace())
            with pt.scope_guard(pt.Scope()):
                exe2.run(startm)
                pt.io.save_inference_model(md, ["x"], [predv], exe2,
                                           main_program=mainm)
            rngs = np.random.RandomState(1)
            cal = [{"x": rngs.rand(4, 64).astype("float32")}
                   for _ in range(8)]
            buckets = (1, 2, 4)
            n_req = 40 if smoke else 200

            def build(precision):
                cfg = ServingConfig(
                    md, buckets=buckets, use_tpu=on_tpu,
                    precision=precision,
                    calibration=(lambda: iter(cal))
                    if precision == "int8" else None)
                eng = Engine(cfg)
                eng.warmup()
                return eng

            def measure(eng):
                reqs = [{"x": rngs.rand(2, 64).astype("float32")}
                        for _ in range(n_req)]
                eng.run_batch(reqs[0])  # page in the bucket
                lat = []
                outs = []
                for r in reqs:
                    t0 = time.perf_counter()
                    o = eng.run_batch(r)
                    lat.append(time.perf_counter() - t0)
                    outs.append(o)
                ms = np.asarray(lat) * 1000.0
                return (float(np.percentile(ms, 50)),
                        float(np.percentile(ms, 99)), reqs, outs)

            e32 = build("f32")
            p50_f32, p99_f32, reqs, outs_f32 = measure(e32)
            e8 = build("int8")
            # same request stream through int8: accuracy delta measured
            # on identical inputs, latency on its own pass
            lat = []
            max_abs = 0.0
            for r, o32 in zip(reqs, outs_f32):
                t0 = time.perf_counter()
                o8 = e8.run_batch(r)
                lat.append(time.perf_counter() - t0)
                for k in o32:
                    if k in o8:
                        max_abs = max(max_abs, float(np.abs(
                            np.asarray(o8[k], np.float32)
                            - np.asarray(o32[k], np.float32)).max()))
            ms = np.asarray(lat[1:] or lat) * 1000.0
            p50_i8 = float(np.percentile(ms, 50))
            p99_i8 = float(np.percentile(ms, 99))
            ok_serve = (max_abs <= PRECISION_INT8_ABS_BOUND
                        and e8.status()["precision"] == "int8"
                        and e8.accuracy_delta is not None)
            _emit_raw(
                "precision_int8_serving_p50_ms", p50_i8, "ms",
                p50_f32 / max(p50_i8, 1e-6),
                {"platform": platform, "buckets": list(buckets),
                 "requests": n_req,
                 "f32_p50_ms": round(p50_f32, 3),
                 "f32_p99_ms": round(p99_f32, 3),
                 "int8_p50_ms": round(p50_i8, 3),
                 "int8_p99_ms": round(p99_i8, 3),
                 "p50_speedup": round(p50_f32 / max(p50_i8, 1e-6), 3),
                 "accuracy_delta_max_abs": round(max_abs, 6),
                 "accuracy_bound": PRECISION_INT8_ABS_BOUND,
                 "engine_accuracy_delta": e8.accuracy_delta,
                 "note": "per-request Engine.run_batch on the shared "
                         "bucket set; int8 = calibrated post-training "
                         "quantization (quantized_* kernels, f32 "
                         "replies); CPU int8 matmul is emulated so "
                         "only TPU latency wins are meaningful"})
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    except Exception as e:
        _emit_raw("precision_int8_serving_p50_ms", 0.0, "ms", 0.0,
                  {"error": str(e)[:300]})
    return ok_train and ok_serve


def bench_resnet50(mesh, n_chips, platform, on_tpu):
    import dataclasses

    import optax

    from paddle_tpu.models import resnet
    from paddle_tpu.parallel.train import TrainStrategy, make_train_step

    cfg = resnet.ResNetConfig.resnet50() if on_tpu \
        else resnet.ResNetConfig.tiny()
    hw = 224 if on_tpu else 32
    batch_sizes = [256, 128, 64, 32] if on_tpu else [16]

    def build_with(cfg):
        def build(bs):
            params, axes = resnet.init(jax.random.key(0), cfg)

            def loss_fn(p, b, r):
                # NHWC end-to-end: a real TPU input pipeline delivers
                # NHWC; the NCHW shim is reference-API parity only.
                return resnet.loss_fn(p, cfg, b, r, data_format="NHWC")

            init_state, step = make_train_step(
                loss_fn, optax.sgd(0.1, momentum=0.9), mesh, axes,
                strategy=TrainStrategy(shard_optimizer_states=False),
                has_aux=True)
            state = init_state(params)
            batch = resnet.make_batch(jax.random.key(1), cfg, bs, hw=hw,
                                      data_format="NHWC")
            return step, state, batch
        return build

    # A/B the pallas fused-1x1 path (byte-floor attack, PROFILE.md r5)
    # at a fixed shape; failure-isolated so a kernel/compile problem
    # costs only this detail field, never the headline metric.
    fused_ab = "not_measured"
    if on_tpu and mesh.devices.size == 1:
        from paddle_tpu.parallel import mesh_guard

        def _fused_ab():
            # inner function: its locals (params/moments/batch) die on
            # unwind even when _measure raises, so a failed A/B cannot
            # hold HBM through the headline ladder
            cfgf = dataclasses.replace(cfg, fused_1x1=True)
            with mesh_guard(mesh):
                step, state, batch = build_with(cfgf)(128)
                dt, _ = _measure(step, state, batch, 10)
            return {"step_ms_bs128": round(1000 * dt / 10, 2)}

        try:
            fused_ab = _fused_ab()
        except Exception as e:
            fused_ab = f"fail: {str(e)[:120]}"
        jax.clear_caches()

    return _run_ladder(
        "resnet50_train_samples_per_sec_per_chip" if on_tpu
        else "resnet_tiny_cpu_samples_per_sec",
        batch_sizes, build_with(cfg), cfg.flops_per_image(hw),
        20 if on_tpu else 3, n_chips, platform,
        {"image_hw": hw, "fused_1x1_ab": fused_ab}, mesh=mesh)


def bench_transformer_big(mesh, n_chips, platform, on_tpu):
    import optax

    from paddle_tpu.models import transformer
    from paddle_tpu.parallel.train import TrainStrategy, make_train_step

    cfg = transformer.TransformerConfig.big() if on_tpu \
        else transformer.TransformerConfig.tiny()
    src_T = tgt_T = 128 if on_tpu else 16
    batch_sizes = [128, 64, 32, 16] if on_tpu else [8]

    def build(bs):
        params, axes = transformer.init(jax.random.key(0), cfg)

        def loss_fn(p, b, r):
            return transformer.nmt_loss(p, cfg, b, rng=r)

        init_state, step = make_train_step(
            loss_fn, optax.adam(1e-4), mesh, axes,
            strategy=TrainStrategy(shard_optimizer_states=True))
        state = init_state(params)
        batch = transformer.make_batch(jax.random.key(1), cfg, bs,
                                       src_T=src_T, tgt_T=tgt_T)
        return step, state, batch

    return _run_ladder(
        "transformer_big_nmt_train_samples_per_sec_per_chip" if on_tpu
        else "transformer_tiny_cpu_samples_per_sec",
        batch_sizes, build, cfg.train_flops_per_seq(src_T, tgt_T),
        20 if on_tpu else 3, n_chips, platform,
        {"src_len": src_T, "tgt_len": tgt_T,
         "tokens_per_sample": src_T + tgt_T}, mesh=mesh)


def bench_bert(mesh, n_chips, platform, on_tpu):
    import optax

    from paddle_tpu.models import bert
    from paddle_tpu.parallel.train import TrainStrategy, make_train_step

    cfg = bert.BertConfig.base() if on_tpu else bert.BertConfig.tiny()
    seq_len = 128 if on_tpu else 64
    batch_sizes = [256, 512, 128, 64, 32] if on_tpu else [16]

    def build(bs):
        params, axes = bert.init(jax.random.key(0), cfg)

        def loss_fn(p, b, r):
            return bert.pretrain_loss(p, cfg, b, rng=r, deterministic=False)

        init_state, step = make_train_step(
            loss_fn, optax.adamw(1e-4), mesh, axes,
            strategy=TrainStrategy(shard_optimizer_states=True))
        state = init_state(params)
        batch = bert.make_batch(jax.random.key(1), cfg, batch_size=bs,
                                seq_len=seq_len)
        return step, state, batch

    # n_masked is a function of seq_len alone (make_batch masks a fixed
    # fraction) — read it off a tiny probe batch for the FLOPs model
    probe = bert.make_batch(jax.random.key(1), cfg, batch_size=2,
                            seq_len=seq_len)
    n_masked = probe["masked_positions"].shape[1]
    return _run_ladder(
        "bert_base_train_samples_per_sec_per_chip" if on_tpu
        else "bert_tiny_cpu_samples_per_sec",
        batch_sizes, build, cfg.train_flops_per_seq(seq_len, n_masked),
        20 if on_tpu else 3, n_chips, platform, {"seq_len": seq_len},
        mesh=mesh)


def bench_bert_long(mesh, n_chips, platform, on_tpu):
    """Long-sequence config (T=4096): measures the production attention
    path (auto gate = splash_attention with v5e-tuned blocks for
    T>=1024; PROFILE.md round 4) and A/Bs the XLA bf16-scores path at
    the same shape, making the gate decision reproducible from BENCH
    output."""
    if not on_tpu:
        return True  # flash path is TPU-only; CPU ladder covers tiny BERT
    import optax

    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.models import bert
    from paddle_tpu.parallel.train import TrainStrategy, make_train_step

    seq_len = 4096
    cfg = bert.BertConfig(max_len=seq_len, dropout=0.0)

    def build_with(mode):
        def build(bs):
            set_flags({"FLAGS_flash_attention": mode})
            params, axes = bert.init(jax.random.key(0), cfg)

            def loss_fn(p, b, r):
                return bert.pretrain_loss(p, cfg, b, rng=r,
                                          deterministic=True)

            init_state, step = make_train_step(
                loss_fn, optax.adamw(1e-4), mesh, axes,
                strategy=TrainStrategy(shard_optimizer_states=True))
            state = init_state(params)
            batch = bert.make_batch(jax.random.key(1), cfg, batch_size=bs,
                                    seq_len=seq_len)
            return step, state, batch
        return build

    probe = bert.make_batch(jax.random.key(1), cfg, batch_size=2,
                            seq_len=seq_len)
    n_masked = probe["masked_positions"].shape[1]
    flops = cfg.train_flops_per_seq(seq_len, n_masked)

    # A/B the XLA bf16-scores path at a fixed shape (bs=8): its per-step
    # time vs the production (splash) ladder below keeps the auto-gate
    # decision reproducible from BENCH output alone. Guarded like the
    # ladder (shard() constraints need the mesh) and dropped before the
    # ladder runs so its params/moments/batch don't hold HBM.
    from paddle_tpu.parallel import mesh_guard

    xla_detail = "not_measured"
    try:
        with mesh_guard(mesh):
            step, state, batch = build_with("off")(8)
            dt, _ = _measure(step, state, batch, 5)
        xla_detail = round(1000 * dt / 5, 2)
        del step, state, batch
    except Exception as e:
        xla_detail = f"fail: {str(e)[:120]}"
    jax.clear_caches()

    # what the auto gate selects at this mesh size: plain splash on one
    # chip; under multi-chip meshes the r5 compositions ride instead
    # (shard_map splash when seq is unsharded, ring-splash under sp —
    # attention.py _multichip_splash_route)
    attn_label = ("splash(auto gate)" if mesh.devices.size == 1
                  else "splash_multichip(auto gate: shardmap/ring)")
    ok = _run_ladder(
        "bert_long_seq4096_train_samples_per_sec_per_chip",
        [8, 4, 2, 1], build_with("auto"), flops, 5, n_chips,
        platform,
        {"seq_len": seq_len, "attention": attn_label,
         "xla_bf16_step_ms_bs8": xla_detail}, mesh=mesh)
    set_flags({"FLAGS_flash_attention": "auto"})
    return ok


# ---------------------------------------------------------------------------
# Orchestration: the round-4 post-mortem (VERDICT r4) showed a single wedged
# TPU tunnel zeroing the whole file (rc=1, no metrics). The parent process
# below therefore NEVER initializes a jax backend: it probes backend health
# in a bounded subprocess, then runs each metric in its own subprocess with
# its own timeout, forwarding the JSON lines. A hang or crash in one metric
# costs exactly that metric (a structured {"metric":..., "error":...} line),
# never the file.
# ---------------------------------------------------------------------------

# (name, tpu_metric, cpu_metric, timeout_s); bert prints LAST (flagship).
BENCHES = [
    ("lenet", "lenet_mnist_program_smoke_samples_per_sec",
     "lenet_mnist_program_smoke_samples_per_sec", 600),
    ("pipeline", "pipeline_stream_samples_per_sec",
     "pipeline_stream_samples_per_sec", 600),
    ("coldstart", "coldstart_restart_compile_speedup",
     "coldstart_restart_compile_speedup", 900),
    ("precision", "precision_bf16_train_samples_per_sec",
     "precision_bf16_train_samples_per_sec", 900),
    ("resnet50", "resnet50_train_samples_per_sec_per_chip",
     "resnet_tiny_cpu_samples_per_sec", 900),
    ("transformer", "transformer_big_nmt_train_samples_per_sec_per_chip",
     "transformer_tiny_cpu_samples_per_sec", 900),
    ("bert_long", "bert_long_seq4096_train_samples_per_sec_per_chip",
     None, 900),  # CPU ladder covers tiny BERT; long-seq is TPU-only
    ("bert", "bert_base_train_samples_per_sec_per_chip",
     "bert_tiny_cpu_samples_per_sec", 900),
]
_BENCH_FNS = {
    "lenet": bench_lenet_smoke, "pipeline": bench_pipeline,
    "precision": bench_precision, "resnet50": bench_resnet50,
    "transformer": bench_transformer_big, "bert_long": bench_bert_long,
    "bert": bench_bert,
}


def run_one(name):
    """Child mode: run one bench in-process (the only mode that touches jax
    backends)."""
    if os.environ.get("PADDLE_TPU_BENCH_FORCE_CPU"):
        # The baked sitecustomize overrides JAX_PLATFORMS after env
        # parsing; the config update is the only reliable CPU pin.
        jax.config.update("jax_platforms", "cpu")
    if name == "coldstart":
        # subprocess-only block: initializing a backend HERE would hold
        # the TPU its measurement children need to boot cold — the env
        # block takes the parent's probe verdict instead of asking jax
        _init_bench_env()
        return 0 if bench_coldstart(
            smoke=bool(os.environ.get("PADDLE_TPU_COLDSTART_SMOKE"))) \
            else 1
    from paddle_tpu.parallel import MeshConfig, make_mesh

    platform = jax.devices()[0].platform
    _init_bench_env(platform=platform)
    on_tpu = platform == "tpu"
    mesh = make_mesh(MeshConfig(dp=-1), devices=jax.devices()[:1]) \
        if len(jax.devices()) == 1 else make_mesh(MeshConfig(dp=-1))
    ok = _BENCH_FNS[name](mesh, mesh.devices.size, platform, on_tpu)
    return 0 if ok else 1


def _run_bounded(argv, timeout_s, env=None):
    """subprocess.run with HARD bounds: the child runs in its own session
    so a timeout kills the whole process group (a backend helper
    grandchild inheriting the pipes would otherwise hold them open and
    block subprocess.run's post-kill drain forever), and the post-kill
    drain itself is bounded. Returns (rc, stdout, stderr); rc is None on
    timeout."""
    import signal
    import subprocess

    try:
        proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True, env=env,
                                start_new_session=True)
    except OSError as e:
        # spawn failure (fork EAGAIN/ENOMEM on an exhausted host) is the
        # same class of event as a wedged backend: report it structured,
        # don't crash the orchestrator
        return None, "", f"spawn failed: {e}"
    try:
        out, err = proc.communicate(timeout=timeout_s)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            proc.kill()
        try:
            out, err = proc.communicate(timeout=15)
        except (subprocess.TimeoutExpired, OSError):
            out = err = ""
            for stream in (proc.stdout, proc.stderr):
                try:
                    if stream:
                        stream.close()
                except OSError:
                    pass
        return None, out, err


def _probe_backend(timeout_s):
    """Probe default-platform health in a throwaway subprocess (a wedged
    tunnel hangs *inside* backend init — only a killable process
    boundary bounds it). Returns the platform string or None."""
    code = ("import jax, json; d = jax.devices(); import jax.numpy as jnp;"
            " v = float(jnp.ones((128, 128)).sum());"
            " print(json.dumps({'platform': d[0].platform, 'ok': v == 16384.0}))")
    rc, out, _ = _run_bounded([sys.executable, "-c", code], timeout_s)
    if rc == 0:
        try:
            info = json.loads(out.strip().splitlines()[-1])
            if info.get("ok"):
                return info["platform"]
        except (ValueError, IndexError):
            pass
    return None


def _emit_error(metric, error):
    print(json.dumps({"metric": metric, "value": 0.0,
                      "unit": "samples/s/chip", "vs_baseline": 0.0,
                      "env": dict(_BENCH_ENV),
                      "error": error[:300]}), flush=True)


def _forward_child_output(stdout, stderr):
    """Pass the child's JSON metric lines through; anything else (jax
    warnings, tracebacks) goes to stderr. Returns emitted metric names."""
    emitted = []
    for line in (stdout or "").splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            rec = None
        if not isinstance(rec, dict) or "metric" not in rec:
            print(line, file=sys.stderr)
            continue
        print(line, flush=True)
        emitted.append(rec["metric"])
    if stderr:
        sys.stderr.write(stderr[-4000:])
    return emitted


def main():
    from paddle_tpu.core.tpu_lock import tpu_singleflight

    deadline = time.monotonic() + float(
        os.environ.get("PADDLE_TPU_BENCH_DEADLINE_S", "3000"))
    with tpu_singleflight(timeout=600.0):
        if os.environ.get("PADDLE_TPU_BENCH_FORCE_CPU"):
            platform = "cpu"  # explicit CPU run: skip the TPU probe
            probed = False
        else:
            platform = _probe_backend(240) or (time.sleep(20) or
                                               _probe_backend(180))
            probed = True
        env = dict(os.environ)
        # probe verdict → env block on every line, parent and children
        # (an explicit CPU run never probed, so reachability is unknown
        # there — None — and nothing is a "fallback")
        if probed:
            env["PADDLE_TPU_BENCH_TPU_REACHABLE"] = \
                "1" if platform == "tpu" else "0"
        if platform is None:
            env["PADDLE_TPU_BENCH_FALLBACK_REASON"] = (
                "TPU backend probe failed/hung (bounded at 240s+180s); "
                "falling back to CPU")
        env["PADDLE_TPU_BENCH_PLATFORM"] = platform or "cpu"
        os.environ.update({k: env[k] for k in
                           ("PADDLE_TPU_BENCH_TPU_REACHABLE",
                            "PADDLE_TPU_BENCH_FALLBACK_REASON",
                            "PADDLE_TPU_BENCH_PLATFORM") if k in env})
        _init_bench_env(platform=platform or "cpu")
        if platform is None:
            # Wedged/absent default backend: record a structured failure
            # per TPU metric, then still exercise the ladder on CPU so
            # the bench machinery itself stays verified. Metrics whose
            # name is platform-independent (lenet smoke) are skipped
            # here — the CPU fallback emits the real line under the
            # same name and a 0.0 error twin would contradict it.
            for _, tpu_metric, cpu_metric, _ in BENCHES:
                if tpu_metric != cpu_metric:
                    _emit_error(tpu_metric,
                                "TPU backend probe failed/hung (bounded "
                                "at 240s+180s); falling back to CPU")
            env["PADDLE_TPU_BENCH_FORCE_CPU"] = "1"
        on_tpu = platform == "tpu"

        all_ok = platform is not None
        here = os.path.abspath(__file__)
        for name, tpu_metric, cpu_metric, tmo in BENCHES:
            expected = tpu_metric if on_tpu else cpu_metric
            budget = min(tmo, deadline - time.monotonic())
            if budget < 60:
                if expected:
                    _emit_error(expected, "bench deadline exhausted before "
                                "this metric started")
                all_ok = False
                continue
            rc, out, err = _run_bounded(
                [sys.executable, here, "--one", name], budget, env=env)
            emitted = _forward_child_output(out, err)
            if rc is None:
                if expected and expected not in emitted:
                    reason = (err if err.startswith("spawn failed")
                              else f"bench subprocess timed out after "
                                   f"{budget:.0f}s (process group killed)")
                    _emit_error(expected, reason)
                all_ok = False
            elif rc != 0:
                all_ok = False
                if expected and expected not in emitted:
                    _emit_error(expected,
                                f"bench subprocess rc={rc} exited "
                                "without emitting this metric")
            elif expected and expected not in emitted:
                _emit_error(expected,
                            "bench subprocess exited rc=0 without "
                            "emitting this metric")
                all_ok = False
        # BASELINE config 5 (ResNet-50 data-parallel on v5e-8) needs 8
        # real chips; its sharded step is validated by
        # __graft_entry__.dryrun and the ParallelExecutor parity tests.
        return 0 if all_ok else 1


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--coldstart-child":
        sys.exit(_coldstart_child(sys.argv[2:]))
    if len(sys.argv) > 2 and sys.argv[1] == "--one":
        if "--smoke" in sys.argv[3:]:
            # coldstart's measurement children inherit this via env;
            # the precision block reads the generic flag
            os.environ["PADDLE_TPU_COLDSTART_SMOKE"] = "1"
            os.environ["PADDLE_TPU_BENCH_SMOKE"] = "1"
        sys.exit(run_one(sys.argv[2]))
    sys.exit(main())
