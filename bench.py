"""Flagship benchmark: BERT-base pretraining step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = achieved MFU / 0.50 (the north-star target from BASELINE.json:
>=50% MFU on v5e; the reference publishes no TPU numbers, so the target
ratio is the comparison point).
"""

from __future__ import annotations

import json
import sys
import time

import jax

# Fast counter-based PRNG: threefry costs ~25% of the BERT step (dropout
# masks); rbg is the standard choice for TPU training loops.
jax.config.update("jax_default_prng_impl", "unsafe_rbg")

import jax.numpy as jnp  # noqa: E402

# v5e (v5 lite) peak bf16 matmul throughput per chip.
PEAK_FLOPS = {"tpu": 197e12, "cpu": 1e12, "gpu": 100e12}


def main():
    import optax

    from paddle_tpu.models import bert
    from paddle_tpu.parallel import MeshConfig, make_mesh, mesh_guard
    from paddle_tpu.parallel.train import TrainStrategy, make_train_step

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    cfg = bert.BertConfig.base() if on_tpu else bert.BertConfig.tiny()
    seq_len = 128 if on_tpu else 64
    batch_sizes = [256, 512, 128, 64, 32] if on_tpu else [16]

    mesh = make_mesh(MeshConfig(dp=-1), devices=jax.devices()[:1]) \
        if len(jax.devices()) == 1 else make_mesh(MeshConfig(dp=-1))
    n_chips = mesh.devices.size

    params, axes = bert.init(jax.random.key(0), cfg)

    def loss_fn(p, batch, rng):
        return bert.pretrain_loss(p, cfg, batch, rng=rng, deterministic=False)

    last_err = None
    for bs in batch_sizes:
        try:
            with mesh_guard(mesh):
                init_state, step = make_train_step(
                    loss_fn, optax.adamw(1e-4), mesh, axes,
                    strategy=TrainStrategy(shard_optimizer_states=True))
                state = init_state(params)
                batch = bert.make_batch(jax.random.key(1), cfg,
                                        batch_size=bs, seq_len=seq_len)
                # warmup / compile (float() forces host sync — on tunneled
                # backends block_until_ready can return before execution)
                state, loss = step(state, batch, jax.random.key(2))
                float(loss)
                n_steps = 20 if on_tpu else 3
                t0 = time.perf_counter()
                for i in range(n_steps):
                    state, loss = step(state, batch, jax.random.key(3 + i))
                final_loss = float(loss)  # syncs the whole chain
                dt = time.perf_counter() - t0
            samples_per_sec = bs * n_steps / dt
            sps_chip = samples_per_sec / n_chips
            n_masked = batch["masked_positions"].shape[1]
            mfu = (samples_per_sec * cfg.train_flops_per_seq(seq_len, n_masked) /
                   (n_chips * PEAK_FLOPS.get(platform, 1e12)))
            print(json.dumps({
                "metric": "bert_base_train_samples_per_sec_per_chip"
                          if on_tpu else "bert_tiny_cpu_samples_per_sec",
                "value": round(sps_chip, 2),
                "unit": "samples/s/chip",
                "vs_baseline": round(mfu / 0.50, 4),
                "detail": {"batch_size": bs, "seq_len": seq_len,
                           "chips": n_chips, "platform": platform,
                           "mfu": round(mfu, 4),
                           "step_ms": round(1000 * dt / n_steps, 2),
                           "final_loss": final_loss},
            }))
            return 0
        except Exception as e:  # OOM → try smaller batch
            last_err = e
            continue
    print(json.dumps({"metric": "bert_base_train_samples_per_sec_per_chip",
                      "value": 0.0, "unit": "samples/s/chip",
                      "vs_baseline": 0.0,
                      "error": str(last_err)[:200]}))
    return 1


if __name__ == "__main__":
    sys.exit(main())
