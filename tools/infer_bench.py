"""Inference-latency benchmark against the reference's OWN published
numbers (BASELINE.md — the float16 benchmarks in
paddle/contrib/float16/float16_benchmark.md are the only hard perf
numbers the reference ships):

| config                      | reference (V100 fp16) |
| VGG16 ImageNet   mb=1       | 3.32 ms  |
| VGG16 ImageNet   mb=64      | 60.23 ms |
| ResNet50 ImageNet mb=1      | 6.13 ms  |
| ResNet50 ImageNet mb=128    | 64.52 ms |

Prints one JSON line per config; vs_baseline = reference_ms / ours_ms
(>1 means this framework on one v5e chip beats the reference's V100
fp16 number). Run: python tools/infer_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp

REF_MS = {
    ("vgg16", 1): 3.32,
    ("vgg16", 64): 60.23,
    ("resnet50", 1): 6.13,
    ("resnet50", 128): 64.52,
}


def _bench(fn, args, n=30):
    out = fn(*args)
    float(jnp.sum(out))          # sync (tunneled backend)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    float(jnp.sum(out))
    return (time.perf_counter() - t0) / n * 1000


def _tunnel_floor(n=50):
    """Per-call dispatch+sync floor of the (possibly tunneled) backend —
    a scalar add round trip. On the axon tunnel this is ~2 ms, which
    dominates bs=1 latencies; local-chip latency ≈ value - floor."""
    tiny = jax.jit(lambda x: x + 1.0)
    z = jnp.zeros(())
    tiny(z)
    float(tiny(z))
    t0 = time.perf_counter()
    for _ in range(n):
        out = tiny(z)
    float(out)
    return (time.perf_counter() - t0) / n * 1000


def main():
    from paddle_tpu.models import resnet, vgg

    platform = jax.devices()[0].platform
    floor = _tunnel_floor()
    rng = jax.random.key(0)

    vcfg = vgg.VGGConfig.vgg16()
    vparams, _ = vgg.init(rng, vcfg)
    vfn = jax.jit(lambda p, x: vgg.apply(p, vcfg, x))

    rcfg = resnet.ResNetConfig.resnet50()
    rparams, _ = resnet.init(jax.random.key(1), rcfg)
    rfn = jax.jit(lambda p, x: resnet.apply(p, rcfg, x, train=False)[0])

    configs = [("vgg16", vfn, vparams, 1), ("vgg16", vfn, vparams, 64),
               ("resnet50", rfn, rparams, 1),
               ("resnet50", rfn, rparams, 128)]
    for name, fn, params, bs in configs:
        img = jax.random.normal(jax.random.key(2), (bs, 3, 224, 224),
                                jnp.float32)
        ms = _bench(fn, (params, img))
        ref = REF_MS[(name, bs)]
        print(json.dumps({
            "metric": f"{name}_infer_latency_ms_bs{bs}",
            "value": round(ms, 3), "unit": "ms",
            "vs_baseline": round(ref / ms, 3),
            "detail": {"batch_size": bs, "platform": platform,
                       "reference_v100_fp16_ms": ref,
                       "dispatch_floor_ms": round(floor, 3),
                       "compute_ms_minus_floor": round(ms - floor, 3),
                       "source": "contrib/float16/float16_benchmark.md"},
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
