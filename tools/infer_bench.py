"""Inference-latency benchmark against the reference's OWN published
numbers (BASELINE.md — the float16 benchmarks in
paddle/contrib/float16/float16_benchmark.md are the only hard perf
numbers the reference ships):

| config                      | reference (V100 fp16) |
| VGG16 ImageNet   mb=1       | 3.32 ms  |
| VGG16 ImageNet   mb=64      | 60.23 ms |
| ResNet50 ImageNet mb=1      | 6.13 ms  |
| ResNet50 ImageNet mb=128    | 64.52 ms |

Measurement: DEVICE latency via an on-device chain — N model calls
inside one lax.scan, each iteration's input data-dependent on the
previous iteration's logits, so the device executes them strictly
serially and per-call host dispatch is excluded. This matches what the
reference's local harness measures (its host dispatch is ~0.1 ms); the
environment here tunnels to a remote chip whose HOST round trip is
~90 ms per call, which would swamp any per-request measurement and is
reported separately as host_roundtrip_ms for context.

Prints one JSON line per config; vs_baseline = reference_ms / device_ms
(>1 means this framework on one v5e chip beats the reference's V100
fp16 number). Run: python tools/infer_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp
import numpy as np

REF_MS = {
    ("vgg16", 1): 3.32,
    ("vgg16", 64): 60.23,
    ("resnet50", 1): 6.13,
    ("resnet50", 128): 64.52,
}

N_CHAIN = 30


def _device_latency_ms(model_fn, params, img):
    """Serialized on-device per-call latency: scan N_CHAIN model calls,
    each input perturbed by (0 x sum(prev logits)) to force a data
    dependency (no cross-iteration parallelism, no host in the loop)."""

    @jax.jit
    def chain(p, x0):
        def step(x, _):
            logits = model_fn(p, x)
            dep = (jnp.sum(logits) * 0.0).astype(x.dtype)
            return x + dep, ()

        xn, _ = jax.lax.scan(step, x0, None, length=N_CHAIN)
        return jnp.sum(xn)

    float(chain(params, img))           # warmup + compile
    t0 = time.perf_counter()
    float(chain(params, img))
    total = (time.perf_counter() - t0) * 1000
    return total / N_CHAIN


def _host_roundtrip_ms(n=5):
    """Serial host->device->host round trip (the tunnel floor here)."""
    tiny = jax.jit(lambda x: x + 1.0)
    z = jnp.zeros(())
    float(tiny(z))
    t0 = time.perf_counter()
    for _ in range(n):
        float(tiny(z))
    return (time.perf_counter() - t0) / n * 1000


def main():
    from paddle_tpu.models import resnet, vgg

    platform = jax.devices()[0].platform
    rtt = _host_roundtrip_ms()

    vcfg = vgg.VGGConfig.vgg16()
    vparams, _ = vgg.init(jax.random.key(0), vcfg)

    rcfg = resnet.ResNetConfig.resnet50()
    rparams, _ = resnet.init(jax.random.key(1), rcfg)

    def vgg_fn(p, x):
        return vgg.apply(p, vcfg, x)

    def rn_fn(p, x):
        return resnet.apply(p, rcfg, x, train=False)[0]

    # INT8 variants: per-output-channel int8 conv weights + dynamic
    # per-tensor activation scales, int32 MXU accumulation
    # (models/common.quantize_conv_weights_int8; the reference's analogue
    # is mkldnn INT8 inference, mkldnn_quantizer.cc)
    from paddle_tpu.models.common import quantize_conv_weights_int8

    vparams_q = quantize_conv_weights_int8(vparams)
    rparams_q = quantize_conv_weights_int8(rparams)

    configs = [("vgg16", vgg_fn, vparams, 1, "bf16"),
               ("vgg16", vgg_fn, vparams, 64, "bf16"),
               ("resnet50", rn_fn, rparams, 1, "bf16"),
               ("resnet50", rn_fn, rparams, 128, "bf16"),
               ("vgg16_int8", vgg_fn, vparams_q, 64, "int8"),
               ("resnet50_int8", rn_fn, rparams_q, 128, "int8")]
    for name, fn, params, bs, prec in configs:
        img = jax.random.normal(jax.random.key(2), (bs, 3, 224, 224),
                                jnp.float32)
        ms = _device_latency_ms(fn, params, img)
        base = name.replace("_int8", "")
        ref = REF_MS[(base, bs)]
        detail = {"batch_size": bs, "platform": platform,
                  "precision": prec,
                  "reference_v100_fp16_ms": ref,
                  "chained_serial_calls": N_CHAIN,
                  "host_roundtrip_ms": round(rtt, 3),
                  "source": "contrib/float16/float16_benchmark.md"}
        if prec == "int8":
            # accuracy delta vs the bf16 path over 32 probe images (3%
            # top-1 granularity; random-init logits are near-tied, so
            # tiny samples make agreement meaninglessly coarse, while
            # the full 128-image batch costs two more large compiles)
            probe = img[:32]
            fp = np.asarray(jax.jit(fn)(
                vparams if base == "vgg16" else rparams, probe),
                np.float32)
            qt = np.asarray(jax.jit(fn)(params, probe), np.float32)
            detail["int8_vs_bf16_max_abs_logit_delta"] = round(
                float(np.abs(fp - qt).max()), 4)
            detail["int8_vs_bf16_rel_logit_delta"] = round(
                float(np.abs(fp - qt).max() / (np.abs(fp).max() + 1e-9)), 4)
            detail["int8_vs_bf16_top1_agreement"] = round(
                float((fp.argmax(-1) == qt.argmax(-1)).mean()), 4)
        print(json.dumps({
            "metric": f"{name}_infer_device_latency_ms_bs{bs}",
            "value": round(ms, 3), "unit": "ms",
            "vs_baseline": round(ref / ms, 3),
            "detail": detail,
        }), flush=True)
    return 0


if __name__ == "__main__":
    from paddle_tpu.core.tpu_lock import tpu_singleflight

    with tpu_singleflight():  # one real chip: serialize vs bench/tools
        sys.exit(main())
