#!/usr/bin/env python
"""Single-op microbenchmark harness (reference:
operators/benchmark/op_tester.cc + operators/jit/benchmark.cc — time one
registered op from a config).

Usage:
    python tools/op_bench.py --op matmul --inputs X=256x768,Y=768x768 \
        [--attrs '{"transpose_Y": false}'] [--dtype float32] [--repeat 50]
Prints one JSON line with per-call latency.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def parse_inputs(spec: str):
    out = {}
    for part in spec.split(","):
        name, shape = part.split("=")
        out[name] = tuple(int(d) for d in shape.split("x"))
    return out


def main(argv=None):
    ap = argparse.ArgumentParser("op_bench")
    ap.add_argument("--op", required=True)
    ap.add_argument("--inputs", required=True,
                    help="slot=AxBxC,slot2=...")
    ap.add_argument("--attrs", default="{}")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--repeat", type=int, default=50)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    import numpy as np

    import jax
    import paddle_tpu  # registers ops  # noqa: F401
    from paddle_tpu.core import registry
    from paddle_tpu.core.ir import OpDesc
    from paddle_tpu.core.registry import KernelCtx

    rng = np.random.RandomState(args.seed)
    shapes = parse_inputs(args.inputs)
    attrs = json.loads(args.attrs)
    if "int" in args.dtype:
        ins = {k: [jax.numpy.asarray(rng.randint(0, 10, s))]
               for k, s in shapes.items()}
    else:
        ins = {k: [jax.numpy.asarray(rng.randn(*s).astype(args.dtype))]
               for k, s in shapes.items()}
    opdef = registry.get_op_def(args.op)
    op = OpDesc(type=args.op,
                inputs={k: [k] for k in ins},
                outputs={}, attrs=attrs)

    def f(ins):
        ctx = KernelCtx(op, rng_key=jax.random.key(args.seed))
        return opdef.call(ins, attrs, ctx)

    jf = jax.jit(f)
    out = jf(ins)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(args.repeat):
        out = jf(ins)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / args.repeat
    print(json.dumps({"op": args.op, "inputs": args.inputs,
                      "platform": jax.devices()[0].platform,
                      "latency_us": round(dt * 1e6, 2),
                      "repeat": args.repeat}))
    return 0


if __name__ == "__main__":
    from paddle_tpu.core.tpu_lock import tpu_singleflight

    with tpu_singleflight():  # one real chip: serialize vs bench/tools
        sys.exit(main())
