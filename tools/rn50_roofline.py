"""Per-stage roofline for ResNet-50 training on the real chip.

For each stage (stem+maxpool, residual groups g0..g3, head+loss) this
times fwd+bwd in isolation (chained via lax.scan so the device stays
busy and per-call dispatch overhead amortizes), and prints a table of
analytic FLOPs, modeled HBM bytes, measured time, achieved TFLOP/s and
GB/s vs the v5e peaks (197 TFLOP/s bf16, 819 GB/s).

Traffic model (bf16=2B, f32=4B), per training step, per tensor pass:
  fwd conv:   read in_act + read weights + write out_act
  fwd BN:     read out_act (one-pass stats) + read out_act + write normed
              (stats can't fuse with apply: reduction must finish first)
  bwd BN+relu: read grad + read act + write grad
  bwd conv:   dgrad (read grad+W, write dx) and wgrad (read grad + read act)
Residual add reads/writes are folded into the adjacent BN passes where
XLA fuses them; this model is approximate but stated, which is the point.
"""

import time

import jax

jax.config.update("jax_default_prng_impl", "unsafe_rbg")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import importlib.util  # noqa: E402
import os  # noqa: E402


def _load_device_peaks():
    """File-path import of the shared peak table (stdlib-only) — keeps
    this tool runnable as `python tools/rn50_roofline.py` with no
    paddle_tpu on sys.path."""
    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                     "paddle_tpu", "observability", "device_peaks.py")
    spec = importlib.util.spec_from_file_location("_rn50_device_peaks", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_V5E = _load_device_peaks().lookup("TPU v5 lite")
PEAK_TF = _V5E.flops
PEAK_BW = _V5E.hbm_bytes_per_s
BS = 256
BF = 2  # bytes bf16


def conv_flops(n, h, w, cin, cout, kh, kw, stride):
    oh, ow = h // stride, w // stride
    return 2 * n * oh * ow * cin * cout * kh * kw


def timeit_vjp(fn, x, iters=40):
    """Time fwd+bwd of fn at input x: vjp with a RANDOM cotangent passed
    through the scan carry (a closed-over cotangent would be embedded in
    the HLO as a giant constant — the tunnel's remote-compile rejects
    >~100 MB programs — and grad-of-sum lets XLA constant-fold chunks of
    the backward). iters=40 amortizes the ~100 ms fixed per-invocation
    dispatch latency of the tunneled backend to ~2.5 ms/iter."""
    y = jax.eval_shape(fn, x)
    yb = jax.random.normal(jax.random.key(99), y.shape, y.dtype)

    def body(c, _):
        a, yb = c
        _, pull = jax.vjp(fn, a)
        (gx,) = pull(yb)
        return (gx, yb), 0.0

    f = jax.jit(lambda a, yb: jax.lax.scan(body, (a, yb), None,
                                           length=iters)[0][0])
    r = f(x, yb)
    float(jnp.sum(r))
    t0 = time.perf_counter()
    r = f(x, yb)
    float(jnp.sum(r))
    return (time.perf_counter() - t0) / iters


def _convbn(key, kh, kw, cin, cout):
    w = jax.random.normal(key, (kh, kw, cin, cout), jnp.bfloat16) * 0.05
    sc = jnp.ones((cout,), jnp.float32)

    def f(x, st=1, relu=True):
        x = jax.lax.conv_general_dilated(
            x, w, (st, st), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        xf = x.astype(jnp.float32)
        mean = xf.mean((0, 1, 2))
        var = jnp.maximum((xf * xf).mean((0, 1, 2)) - mean * mean, 0.0)
        y = (xf - mean) * jax.lax.rsqrt(var + 1e-5) * sc
        if relu:
            y = jax.nn.relu(y)
        return y.astype(jnp.bfloat16)

    return f


def conv_cost(h, cin, cout, kh, kw, st):
    """fwd+bwd (flops, bytes) for one conv+BN at input [BS,h,h,cin]."""
    f1 = conv_flops(BS, h, h, cin, cout, kh, kw, st)
    oh = h // st
    a_in = BS * h * h * cin * BF
    a_out = BS * oh * oh * cout * BF
    wb = kh * kw * cin * cout * BF
    # fwd: conv(read in + w, write out) + BN stats(read out)
    #      + BN apply(read out, write out)
    # bwd: BN bwd(read g, read act, write g) + dgrad(read g + w, write gx)
    #      + wgrad(read g + read act)
    by = (a_in + wb + a_out) + a_out + 2 * a_out \
        + 3 * a_out + (a_out + wb + a_in) + (a_out + a_in)
    return 3 * f1, by


def make_group(gi, blocks, cin, key):
    """Real bottleneck-group topology (residual adds included)."""
    mid = 64 * (2 ** gi)
    cout = mid * 4
    keys = iter(jax.random.split(key, blocks * 4))
    layers = []
    c = cin
    for bi in range(blocks):
        st = 2 if (bi == 0 and gi > 0) else 1
        l1 = _convbn(next(keys), 1, 1, c, mid)
        l2 = _convbn(next(keys), 3, 3, mid, mid)
        l3 = _convbn(next(keys), 1, 1, mid, cout)
        proj = _convbn(next(keys), 1, 1, c, cout) if bi == 0 else None
        layers.append((l1, l2, l3, proj, st))
        c = cout

    def fn(x):
        for l1, l2, l3, proj, st in layers:
            sc = proj(x, st=st, relu=False) if proj is not None else x
            h = l1(x)
            h = l2(h, st=st)
            h = l3(h, relu=False)
            x = jax.nn.relu(h + sc)
        return x

    return fn, cout


def group_cost(gi, blocks, cin, h):
    fl = by = 0
    mid = 64 * (2 ** gi)
    cout = mid * 4
    c = cin
    for bi in range(blocks):
        st = 2 if (bi == 0 and gi > 0) else 1
        f, b = conv_cost(h, c, mid, 1, 1, 1)
        fl, by = fl + f, by + b
        f, b = conv_cost(h, mid, mid, 3, 3, st)
        fl, by = fl + f, by + b
        oh = h // st
        f, b = conv_cost(oh, mid, cout, 1, 1, 1)
        fl, by = fl + f, by + b
        if bi == 0:
            f, b = conv_cost(h, c, cout, 1, 1, st)
            fl, by = fl + f, by + b
        # residual add + relu: fwd read sc (+h already in BN write) + write,
        # bwd one extra grad pass
        a_out = BS * oh * oh * cout * BF
        by += 3 * a_out
        h, c = oh, cout
    return fl, by


def main():
    rows = []
    # stem: 7x7/2 conv+BN+relu then 3x3/2 maxpool
    stem_cb = _convbn(jax.random.key(1), 7, 7, 3, 64)

    def stem_fn(x):
        x = stem_cb(x, st=2)
        x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
        return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max,
                                     (1, 3, 3, 1), (1, 2, 2, 1), "VALID")

    x = jax.random.normal(jax.random.key(1), (BS, 224, 224, 3), jnp.bfloat16)
    t = timeit_vjp(stem_fn, x)
    fl, by = conv_cost(224, 3, 64, 7, 7, 2)
    # maxpool: fwd read 112^2 + write 56^2, bwd select-and-scatter ~2 passes
    by += BS * (112 * 112 + 56 * 56) * 64 * BF * 3
    rows.append(("stem+maxpool", fl, by, t))

    # group input spatial sizes: stride-2 happens inside g1..g3's block 0
    group_h = {0: 56, 1: 56, 2: 28, 3: 14}
    cin = 64
    for gi, blocks in enumerate((3, 4, 6, 3)):
        h = group_h[gi]
        fn, cout = make_group(gi, blocks, cin, jax.random.key(2 + gi))
        x = jax.random.normal(jax.random.key(2 + gi),
                              (BS, h, h, cin), jnp.bfloat16)
        t = timeit_vjp(fn, x)
        fl, by = group_cost(gi, blocks, cin, h)
        rows.append((f"g{gi} x{blocks}", fl, by, t))
        cin = cout

    # head: global avg pool + fp32 dense 2048->1000 + softmax-CE
    whead = jax.random.normal(jax.random.key(9), (2048, 1000),
                              jnp.float32) * 0.02

    def head_fn(x):
        p = x.mean((1, 2)).astype(jnp.float32)
        lo = p @ whead
        return jax.nn.log_softmax(lo)

    x = jax.random.normal(jax.random.key(10), (BS, 7, 7, 2048), jnp.bfloat16)
    t = timeit_vjp(head_fn, x)
    fl = 3 * 2 * BS * 2048 * 1000
    by = BS * 7 * 7 * 2048 * BF * 2 + BS * 2048 * 4 * 4 + 2048 * 1000 * 4 * 3
    rows.append(("head+loss", fl, by, t))

    tot_t = sum(r[3] for r in rows)
    tot_f = sum(r[1] for r in rows)
    tot_b = sum(r[2] for r in rows)
    print(f"{'stage':<14}{'ms':>8}{'GFLOP':>9}{'GB':>8}"
          f"{'TFLOP/s':>9}{'MFU':>7}{'GB/s':>8}{'%BW':>6}")
    for name, fl, by, t in rows:
        print(f"{name:<14}{1e3 * t:>8.2f}{fl / 1e9:>9.1f}{by / 1e9:>8.2f}"
              f"{fl / t / 1e12:>9.1f}{fl / t / PEAK_TF:>7.1%}"
              f"{by / t / 1e9:>8.0f}{by / t / PEAK_BW:>6.0%}")
    print(f"{'TOTAL':<14}{1e3 * tot_t:>8.2f}{tot_f / 1e9:>9.1f}"
          f"{tot_b / 1e9:>8.2f}{tot_f / tot_t / 1e12:>9.1f}"
          f"{tot_f / tot_t / PEAK_TF:>7.1%}{tot_b / tot_t / 1e9:>8.0f}"
          f"{tot_b / tot_t / PEAK_BW:>6.0%}")
    print(f"\nisolated-stage sum: {1e3 * tot_t:.1f} ms for bs={BS} "
          f"(full step measured ~103 ms)")
    print(f"roofline: bytes-bound step floor = {tot_b / PEAK_BW * 1e3:.1f} ms"
          f"  | flops-bound floor = {tot_f / PEAK_TF * 1e3:.1f} ms")


if __name__ == "__main__":
    from paddle_tpu.core.tpu_lock import tpu_singleflight

    with tpu_singleflight():  # one real chip: serialize vs bench/tools
        main()
