#!/usr/bin/env python
"""obsdump — pretty-print observability dumps and rebuild chrome traces.

Offline companion to paddle_tpu/observability/: the `snapshot` and
`trace` subcommands work on files alone and load ONLY
observability/metrics.py + tracing.py (stdlib-only modules, imported by
file path) — no framework or jax import, so they run in milliseconds on
a CI host or a laptop holding a copied run dir. `snapshot --live`
imports the framework and reads the in-process registry instead.

Usage:
  obsdump.py snapshot METRICS.json          # aligned table of every metric
  obsdump.py snapshot METRICS.json --prom   # Prometheus text exposition
  obsdump.py snapshot --live [--prom]       # current process registry
  obsdump.py trace RUN_DIR -o out.json      # merge spans.json + jax
                                            # *.trace.json(.gz) under
                                            # RUN_DIR into ONE chrome trace
  obsdump.py trace TRACE_DIR --list-traces  # distributed traces found
                                            # in a PADDLE_TPU_TRACE_DIR
                                            # (per-process trace-*.jsonl
                                            # sinks), newest first
  obsdump.py trace TRACE_DIR --trace-id ID  # reassemble ONE request's
                                            # cross-process span TREE
                                            # (router + N replicas + PS
                                            # servers) as an indented
                                            # table; --chrome -o out.json
                                            # writes it as a merged
                                            # chrome trace instead
  obsdump.py events EVENTS.jsonl            # tail the JSONL event log
                                            # (-n N, --kind K, --json,
                                            # --follow)
  obsdump.py cache METRICS.json             # per-kind persistent
                                            # compile-cache hit/miss/
                                            # bytes table (--live,
                                            # --json)
  obsdump.py analysis METRICS.json          # static-analysis findings
                                            # per pass/severity + walk
                                            # counts (--live, --json)
  obsdump.py locks METRICS.json             # lock held-seconds/
                                            # contention tables +
                                            # observed order inversions
                                            # (PADDLE_TPU_LOCKCHECK;
                                            # --live, --json)
  obsdump.py fleet METRICS.json             # serving-fleet summary:
                                            # world size, per-replica
                                            # ejections/retries/breaker
                                            # states, autoscale actions
                                            # (--live, --json,
                                            # --events LOG)
  obsdump.py tenants METRICS.json           # multi-tenant serving
                                            # summary: per-tenant
                                            # outcomes/tokens/p99,
                                            # sheds by tier+kind,
                                            # per-model registry
                                            # versions + hot-swaps
                                            # (--live, --json,
                                            # --events LOG)
  obsdump.py top TS_DIR                     # fleet dashboard from a
                                            # PADDLE_TPU_TS_DIR: rates,
                                            # error %, p50/p99, token
                                            # throughput merged across
                                            # recording pids (--window,
                                            # --watch S, --json)
  obsdump.py slo TS_DIR --spec SLOS.json    # SLO objective table:
                                            # target, current, fast/slow
                                            # burn rates, alert state
                                            # (--window-scale, --json)
  obsdump.py mem METRICS.json               # per-owner HBM attribution
                                            # (kv_pool/params/optimizer/
                                            # other), watermark, budget
                                            # state (--live forces a
                                            # fresh sweep + top-buffer
                                            # ranking, --json)
  obsdump.py profile DIR                    # render a /v1/profile
                                            # capture dir: merged
                                            # chrome-trace summary +
                                            # MFU/memory attribution
                                            # tables (--url URL triggers
                                            # a capture on a live
                                            # server first, --seconds,
                                            # --json)

Mixed-precision runs: `snapshot` surfaces the dynamic loss-scaling
counters (paddle_tpu_amp_total{event=overflow|growth|skip}, the
paddle_tpu_amp_loss_scale gauge) and the quantization-scale histogram
(paddle_tpu_quant_scale{kind}); `events --kind amp_overflow` tails the
scale-thrash timeline and `events --kind quantize` the calibration
story (PROFILE.md §Precision).

The metrics JSON is what the registry's env-gated dumper
(PADDLE_TPU_METRICS_DIR) writes; RUN_DIR is typically the profiler's
profile_path (jax device traces) optionally holding a spans.json from
observability.save_spans().
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_OBS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "paddle_tpu", "observability")


_OBS_CACHE = {}


def _load_obs_module(name: str):
    """Import observability/<name>.py by file path, bypassing the
    paddle_tpu package __init__ (which drags in jax). metrics.py,
    tracing.py, aggregate.py and slo.py are stdlib-only by contract
    (their module docstrings). Memoized: repeated loads (a --watch
    refresh loop) must not re-exec the module each frame."""
    mod = _OBS_CACHE.get(name)
    if mod is None:
        spec = importlib.util.spec_from_file_location(
            f"_obsdump_{name}", os.path.join(_OBS_DIR, f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _OBS_CACHE[name] = mod
    return mod


def _fmt_value(v):
    import math

    if isinstance(v, float):
        # NaN/Inf are legitimate gauge values (a NaN grad-norm is exactly
        # what the health metrics record) — int() would raise on them
        if not math.isfinite(v) or v != int(v):
            return f"{v:.6g}"
        return str(int(v))
    return str(v)


def _fmt_labels(labels):
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) \
        + "}"


def print_snapshot(snap, out=sys.stdout):
    """Aligned table: name{labels}  value   (histograms: count/sum/avg)."""
    rows = []
    for name in sorted(snap):
        m = snap[name]
        for s in m["series"]:
            label = name + _fmt_labels(s.get("labels", {}))
            if m["type"] == "histogram":
                cnt, tot = s["count"], s["sum"]
                avg = tot / cnt if cnt else 0.0
                val = (f"count={cnt} sum={tot:.6g} avg={avg:.6g}")
            else:
                val = _fmt_value(s["value"])
            rows.append((label, m["type"], val))
        if not m["series"]:
            rows.append((name, m["type"], "(no samples)"))
    width = max((len(r[0]) for r in rows), default=0)
    for label, kind, val in rows:
        print(f"{label:{width}s}  {kind:9s}  {val}", file=out)


def cmd_snapshot(args) -> int:
    snap = _load_snap(args)
    if snap is None:
        print("snapshot: need a metrics.json path or --live",
              file=sys.stderr)
        return 2
    if args.prom:
        sys.stdout.write(
            _load_obs_module("metrics").render_prometheus_snapshot(snap))
    else:
        print_snapshot(snap)
    return 0


def _print_trace_tree(tracing, records, trace_id):
    """Indented cross-process tree: name, duration, pid, cat, args."""
    roots = tracing.build_trace_tree(records, trace_id)
    if not roots:
        return False
    import datetime

    t0 = min(r.get("ts", 0.0) for r in records
             if r.get("trace_id") == trace_id)
    print(f"trace {trace_id}  start "
          f"{datetime.datetime.fromtimestamp(t0).isoformat(timespec='milliseconds')}"
          f"  ({len([r for r in records if r.get('trace_id') == trace_id])}"
          f" spans, {len(roots)} root(s))")

    def walk(node, depth):
        args = {k: v for k, v in (node.get("args") or {}).items()}
        detail = " ".join(f"{k}={v}" for k, v in sorted(args.items()))
        off = (node.get("ts", 0.0) - t0) * 1000
        print(f"  {'  ' * depth}{node['name']:<{max(1, 40 - 2 * depth)}}"
              f" {node.get('dur', 0.0) * 1000:9.3f}ms"
              f"  +{off:8.3f}ms  pid={node.get('pid', '?'):<7}"
              f" [{node.get('cat', '?')}]"
              + (f"  {detail}" if detail else ""))
        for c in node["children"]:
            walk(c, depth + 1)

    for root in roots:
        walk(root, 0)
    return True


def cmd_trace(args) -> int:
    if not os.path.isdir(args.run_dir):
        print(f"trace: not a directory: {args.run_dir}", file=sys.stderr)
        return 2
    tracing = _load_obs_module("tracing")
    if args.list_traces or args.trace_id:
        records = tracing.read_trace_dir(args.run_dir)
        if not records:
            print(f"trace: no trace-*.jsonl sinks under {args.run_dir} "
                  f"(is PADDLE_TPU_TRACE_DIR / PADDLE_TPU_TRACE_SAMPLE "
                  f"set on the fleet?)", file=sys.stderr)
            return 1
        if args.list_traces:
            import datetime

            rows = tracing.trace_summaries(records)
            for r in rows:
                r["start"] = datetime.datetime.fromtimestamp(
                    r.pop("start_ts")).isoformat(timespec="milliseconds")
            _print_aligned(rows, ("trace_id", "spans", "processes",
                                  "root", "wall_ms", "start"))
            return 0
        mine = [r for r in records if r.get("trace_id") == args.trace_id]
        if not mine:
            print(f"trace: no spans for trace_id {args.trace_id} under "
                  f"{args.run_dir}", file=sys.stderr)
            return 1
        if args.chrome:
            trace = tracing.merge_chrome_traces(
                [tracing.trace_records_to_chrome(mine)])
            with open(args.output, "w") as f:
                json.dump(trace, f)
            print(f"wrote {args.output}: "
                  f"{len(trace['traceEvents'])} events for trace "
                  f"{args.trace_id}")
            return 0
        return 0 if _print_trace_tree(tracing, records,
                                      args.trace_id) else 1
    lists = []
    spans_json = os.path.join(args.run_dir, "spans.json")
    if os.path.exists(spans_json):
        with open(spans_json) as f:
            spans = [tracing.Span(**s) for s in json.load(f)]
        lists.append(tracing.spans_to_chrome_events(spans))
    for p in tracing.find_device_traces(args.run_dir):
        try:
            lists.append(tracing._load_chrome_trace(p))
        except (OSError, ValueError) as e:
            print(f"trace: skipping unreadable {p}: {e}", file=sys.stderr)
    if not lists:
        print(f"trace: nothing to merge under {args.run_dir} (no "
              f"spans.json or *.trace.json[.gz])", file=sys.stderr)
        return 1
    trace = tracing.merge_chrome_traces(lists)
    with open(args.output, "w") as f:
        json.dump(trace, f)
    print(f"wrote {args.output}: {len(trace['traceEvents'])} events from "
          f"{len(lists)} source(s)")
    return 0


def _fmt_event(ev) -> str:
    import datetime

    ts = ev.get("ts")
    when = datetime.datetime.fromtimestamp(ts).isoformat(
        timespec="milliseconds") if isinstance(ts, (int, float)) else "?"
    rest = {k: v for k, v in ev.items()
            if k not in ("seq", "ts", "kind")}
    detail = " ".join(f"{k}={v}" for k, v in sorted(rest.items()))
    return f"{ev.get('seq', '?'):>6}  {when}  " \
           f"{ev.get('kind', '?'):<13} {detail}"


def _rotated_handle(f, path):
    """Rotation detector for --follow: when the sink was renamed away
    (PADDLE_TPU_EVENT_LOG_MAX_BYTES rollover moved it to <path>.1) or
    truncated, the open handle points at the OLD inode — its readline()
    returns "" forever while fresh events land in a new file. Returns a
    fresh handle (old one closed, reading the new file from the start)
    or None when nothing rotated / the new file isn't there yet."""
    try:
        st = os.stat(path)
        fst = os.fstat(f.fileno())
    except OSError:
        return None  # mid-rotation: the name will reappear next poll
    if (st.st_ino, st.st_dev) == (fst.st_ino, fst.st_dev) \
            and st.st_size >= f.tell():
        return None
    try:
        nf = open(path)
    except OSError:
        return None
    f.close()
    return nf


def cmd_events(args) -> int:
    """Tail/filter the observability JSONL event log (events.py emit
    format). --follow polls for appended lines until interrupted (and
    survives size-capped rotation: a renamed-away sink is detected by
    inode and the fresh file picked up from its start); it is OFF by
    default so scripted callers terminate."""
    if not os.path.isfile(args.path):
        print(f"events: no such file: {args.path}", file=sys.stderr)
        return 2

    def _parse(line):
        try:
            ev = json.loads(line)
        except ValueError:
            return None  # crash mid-append truncates the last line
        if args.kind and ev.get("kind") != args.kind:
            return None
        return ev

    # ONE handle for tail + follow: after read() the position is exactly
    # where the tail ended, so events appended while we print the tail
    # are picked up by the follow loop instead of falling into a gap
    with open(args.path) as f:
        text = f.read()
        # an event being appended RIGHT NOW can straddle the read: carry
        # the unterminated trailing fragment into the follow buffer
        # rather than dropping it as a malformed tail line
        buf = ""
        if text and not text.endswith("\n"):
            nl = text.rfind("\n")
            text, buf = text[:nl + 1], text[nl + 1:]
        evs = [ev for ev in map(_parse, text.splitlines()) if ev]
        if args.n is not None and args.n >= 0:
            evs = evs[-args.n:] if args.n else []
        for ev in evs:
            print(json.dumps(ev) if args.json else _fmt_event(ev))
        if not args.follow:
            return 0
        import time as _time
        try:
            while True:
                chunk = f.readline()
                if not chunk:
                    # EOF: either idle, or the sink rotated underneath
                    # us — finish the old inode first (we just did),
                    # then hop onto the fresh file
                    nf = _rotated_handle(f, args.path)
                    if nf is not None:
                        f, buf = nf, ""
                        continue
                    _time.sleep(0.2)
                    continue
                buf += chunk
                if not buf.endswith("\n"):
                    continue  # line still being written; keep buffering
                line, buf = buf, ""
                ev = _parse(line)
                if ev is not None:
                    print(json.dumps(ev) if args.json else _fmt_event(ev),
                          flush=True)
        except KeyboardInterrupt:
            pass
        finally:
            f.close()  # may be the rotated-onto handle, not the with-target
    return 0


def cmd_cache(args) -> int:
    """Per-kind persistent compile-cache summary from a metrics
    snapshot: hit/miss/corrupt/store/evict counts and the bytes moved,
    i.e. the restart-storm story of PADDLE_TPU_COMPILE_CACHE
    (PROFILE.md §Compile-cache) in one table."""
    snap = _load_snap(args)
    if snap is None:
        print("cache: need a metrics.json path or --live",
              file=sys.stderr)
        return 2

    counts = {}  # (kind, event) -> count
    nbytes = {}  # (kind, event) -> bytes
    for name, dest in (("paddle_tpu_compile_cache_total", counts),
                       ("paddle_tpu_compile_cache_bytes_total", nbytes)):
        for s in (snap.get(name) or {}).get("series", []):
            labels = s.get("labels", {})
            key = (labels.get("kind", "?"), labels.get("event", "?"))
            dest[key] = dest.get(key, 0) + s.get("value", 0)
    kinds = sorted({k for k, _ in list(counts) + list(nbytes)})
    if not kinds:
        print("no compile-cache samples in this snapshot (is "
              "PADDLE_TPU_COMPILE_CACHE set?)")
        return 0

    events = ("hit", "miss", "corrupt", "store", "store_error", "evict")
    rows = []
    for kind in kinds:
        c = {ev: int(counts.get((kind, ev), 0)) for ev in events}
        b = {ev: int(nbytes.get((kind, ev), 0)) for ev in events}
        lookups = c["hit"] + c["miss"] + c["corrupt"]
        rows.append({
            "kind": kind, **c,
            "hit_rate": round(c["hit"] / lookups, 4) if lookups else 0.0,
            "hit_bytes": b["hit"], "store_bytes": b["store"],
            "evict_bytes": b["evict"],
        })
    if args.json:
        print(json.dumps(rows, indent=2))
        return 0
    _print_aligned(rows, ("kind", "hit", "miss", "corrupt", "store",
                          "store_error", "evict", "hit_rate",
                          "hit_bytes", "store_bytes", "evict_bytes"))
    return 0


def _print_aligned(rows, cols):
    """Right-aligned table shared by the cache/analysis summaries."""
    widths = {c: max(len(c), *(len(str(r[c])) for r in rows))
              for c in cols}
    print("  ".join(f"{c:>{widths[c]}}" for c in cols))
    for r in rows:
        print("  ".join(f"{str(r[c]):>{widths[c]}}" for c in cols))


def _load_snap(args):
    """Shared --live/path snapshot loader for summary subcommands."""
    if args.live:
        import paddle_tpu  # noqa: F401 — registers all telemetry metrics

        from paddle_tpu import observability
        return observability.snapshot()
    if not args.path:
        return None
    with open(args.path) as f:
        return json.load(f)


def cmd_analysis(args) -> int:
    """Static-analysis story from a metrics snapshot: how many pass
    walks ran (by wiring site) and the findings per pass/severity
    (paddle_tpu/analysis, PADDLE_TPU_VALIDATE — ANALYSIS.md)."""
    snap = _load_snap(args)
    if snap is None:
        print("analysis: need a metrics.json path or --live",
              file=sys.stderr)
        return 2
    runs = {}
    for s in (snap.get("paddle_tpu_analysis_runs_total") or {}) \
            .get("series", []):
        runs[s.get("labels", {}).get("where", "?")] = int(s["value"])
    counts = {}  # (pass, severity) -> n
    for s in (snap.get("paddle_tpu_analysis_findings_total") or {}) \
            .get("series", []):
        labels = s.get("labels", {})
        key = (labels.get("pass", "?"), labels.get("severity", "?"))
        counts[key] = counts.get(key, 0) + int(s["value"])
    if not runs and not counts:
        print("no analysis samples in this snapshot (is "
              "PADDLE_TPU_VALIDATE set, or did tools/analyze.py run?)")
        return 0
    severities = ("error", "warning", "info")
    rows = []
    for pass_name in sorted({p for p, _ in counts}):
        row = {"pass": pass_name}
        for sev in severities:
            row[sev] = counts.get((pass_name, sev), 0)
        rows.append(row)
    if args.json:
        print(json.dumps({"walks": runs, "findings": rows}, indent=2))
        return 0
    print("walks: " + (", ".join(f"{k}={v}"
                                 for k, v in sorted(runs.items()))
                       or "none"))
    if rows:
        _print_aligned(rows, ("pass",) + severities)
    else:
        print("no findings recorded")
    return 0


def cmd_locks(args) -> int:
    """Concurrency-sanitizer story from a metrics snapshot
    (PADDLE_TPU_LOCKCHECK, ANALYSIS.md §Concurrency): per-site
    held-seconds and contention table, plus the observed lock-order
    inversions against the tools/lock_order.json ledger."""
    snap = _load_snap(args)
    if snap is None:
        print("locks: need a metrics.json path or --live",
              file=sys.stderr)
        return 2

    held = {}  # site -> {count, sum}
    for s in (snap.get("paddle_tpu_lock_held_seconds") or {}) \
            .get("series", []):
        site = s.get("labels", {}).get("site", "?")
        held[site] = {"count": int(s.get("count", 0)),
                      "sum": float(s.get("sum", 0.0))}
    contention = {}
    for s in (snap.get("paddle_tpu_lock_contention_total") or {}) \
            .get("series", []):
        site = s.get("labels", {}).get("site", "?")
        contention[site] = contention.get(site, 0) + int(s["value"])
    inversions = []
    for s in (snap.get("paddle_tpu_lock_inversions_total") or {}) \
            .get("series", []):
        labels = s.get("labels", {})
        inversions.append({"first": labels.get("first", "?"),
                           "second": labels.get("second", "?"),
                           "count": int(s["value"])})
    deadlocks = sum(
        int(s["value"]) for s in
        (snap.get("paddle_tpu_lock_deadlocks_total") or {})
        .get("series", []))

    sites = sorted(set(held) | set(contention))
    if not sites and not inversions and not deadlocks:
        print("no lock_* samples in this snapshot (is "
              "PADDLE_TPU_LOCKCHECK set to 1 or 2?)")
        return 0
    rows = []
    for site in sites:
        h = held.get(site, {"count": 0, "sum": 0.0})
        rows.append({
            "site": site,
            "acquires": h["count"],
            "held_s": round(h["sum"], 4),
            "avg_ms": round(1000.0 * h["sum"] / h["count"], 3)
            if h["count"] else 0.0,
            "contention": contention.get(site, 0),
        })
    out = {"locks": rows, "inversions": inversions,
           "deadlocks": deadlocks}
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    if rows:
        _print_aligned(rows, ("site", "acquires", "held_s", "avg_ms",
                              "contention"))
    print(f"\ndeadlocks detected: {deadlocks}")
    if inversions:
        print("observed inversions (held -> acquired, against "
              "lock_order.json):")
        for inv in inversions:
            print(f"  {inv['first']} -> {inv['second']}  "
                  f"x{inv['count']}")
    else:
        print("observed inversions: none")
    return 0


def cmd_ps(args) -> int:
    """Parameter-server resilience story from a metrics snapshot
    (RESILIENCE.md §Parameter-server fault tolerance): RPC outcomes per
    op, reconnects + breaker state per endpoint, degraded seconds,
    gradient drops per var, and dedup-served retries. With --events it
    also tails the ps_failover events from a JSONL log."""
    snap = _load_snap(args)
    if snap is None:
        print("ps: need a metrics.json path or --live", file=sys.stderr)
        return 2

    def series(name):
        return (snap.get(name) or {}).get("series", [])

    rpc = {}  # (op, outcome) -> count
    for s in series("paddle_tpu_ps_rpc_total"):
        labels = s.get("labels", {})
        key = (labels.get("op", "?"), labels.get("outcome", "?"))
        rpc[key] = rpc.get(key, 0) + int(s["value"])
    endpoints = {}  # ep -> {reconnects, degraded_s, breaker}
    for s in series("paddle_tpu_ps_reconnects_total"):
        ep = s.get("labels", {}).get("endpoint", "?")
        endpoints.setdefault(ep, {})["reconnects"] = int(s["value"])
    for s in series("paddle_tpu_ps_degraded_seconds_total"):
        ep = s.get("labels", {}).get("endpoint", "?")
        endpoints.setdefault(ep, {})["degraded_s"] = round(
            float(s["value"]), 3)
    state_names = {0: "closed", 1: "half_open", 2: "open"}
    for s in series("paddle_tpu_ps_breaker_state"):
        ep = s.get("labels", {}).get("endpoint", "?")
        endpoints.setdefault(ep, {})["breaker"] = state_names.get(
            int(s.get("value", 0)), "?")
    drops = {s.get("labels", {}).get("var", "?"): int(s["value"])
             for s in series("paddle_tpu_ps_grad_drops_total")}
    dedups = sum(int(s["value"])
                 for s in series("paddle_tpu_ps_dedup_replies_total"))

    if not rpc and not endpoints and not drops:
        print("no ps_* samples in this snapshot (did a PS client/server "
              "run in this process?)")
        return 0

    outcomes = ("ok", "error", "retry", "unavailable")
    rpc_rows = []
    for op in sorted({o for o, _ in rpc}):
        row = {"op": op}
        for oc in outcomes:
            row[oc] = rpc.get((op, oc), 0)
        rpc_rows.append(row)
    ep_rows = [{"endpoint": ep,
                "breaker": info.get("breaker", "closed"),
                "reconnects": info.get("reconnects", 0),
                "degraded_s": info.get("degraded_s", 0.0)}
               for ep, info in sorted(endpoints.items())]
    out = {"rpc": rpc_rows, "endpoints": ep_rows,
           "grad_drops": drops, "dedup_replies": dedups}
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    if rpc_rows:
        _print_aligned(rpc_rows, ("op",) + outcomes)
    if ep_rows:
        print()
        _print_aligned(ep_rows, ("endpoint", "breaker", "reconnects",
                                 "degraded_s"))
    print(f"\ndedup-served retries: {dedups}")
    if drops:
        print("grad drops: " + ", ".join(f"{k}={v}"
                                         for k, v in sorted(drops.items())))
    if args.events:
        evs = _load_obs_module("events").read_jsonl(args.events, n=args.n,
                                                    kind="ps_failover")
        print(f"\nlast {len(evs)} ps_failover events:")
        for ev in evs:
            print("  " + _fmt_event(ev))
    return 0


def cmd_fleet(args) -> int:
    """Serving-fleet story from a metrics snapshot (SERVING.md §Fleet):
    world size + replica counts by state, per-endpoint picks/ejections/
    readmissions/breaker state, router request outcomes + retries by
    failure class, autoscaler actions, supervisor respawns, and the
    router latency histogram. With --events it also tails the `fleet`
    events from a JSONL log."""
    snap = _load_snap(args)
    if snap is None:
        print("fleet: need a metrics.json path or --live",
              file=sys.stderr)
        return 2

    def series(name):
        return (snap.get(name) or {}).get("series", [])

    def labeled(name, label):
        out = {}
        for s in series(name):
            key = s.get("labels", {}).get(label, "?")
            out[key] = out.get(key, 0) + s["value"]
        return out

    world = next((int(s["value"]) for s in
                  series("paddle_tpu_fleet_world_size")), None)
    replicas = {k: int(v) for k, v in
                labeled("paddle_tpu_fleet_replicas", "state").items()}
    requests = {k: int(v) for k, v in
                labeled("paddle_tpu_fleet_requests_total",
                        "outcome").items()}
    retries = {k: int(v) for k, v in
               labeled("paddle_tpu_fleet_retries_total",
                       "reason").items()}
    autoscale = {k: int(v) for k, v in
                 labeled("paddle_tpu_fleet_autoscale_total",
                         "direction").items()}
    respawns = sum(int(s["value"]) for s in
                   series("paddle_tpu_fleet_replica_respawns_total"))
    state_names = {0: "closed", 1: "half_open", 2: "open"}
    endpoints = {}  # ep -> {picks, ejections, readmissions, breaker}
    for name, field in (("paddle_tpu_fleet_picks_total", "picks"),
                        ("paddle_tpu_fleet_ejections_total",
                         "ejections"),
                        ("paddle_tpu_fleet_readmissions_total",
                         "readmissions")):
        for ep, v in labeled(name, "endpoint").items():
            endpoints.setdefault(ep, {})[field] = int(v)
    for s in series("paddle_tpu_fleet_breaker_state"):
        ep = s.get("labels", {}).get("endpoint", "?")
        endpoints.setdefault(ep, {})["breaker"] = state_names.get(
            int(s.get("value", 0)), "?")
    lat = _hist_summary(snap, "paddle_tpu_fleet_request_seconds")

    if world is None and not endpoints and not requests:
        print("no fleet_* samples in this snapshot (did a serving "
              "Router run in this process?)")
        return 0
    ep_rows = [{"endpoint": ep,
                "breaker": info.get("breaker", "closed"),
                "picks": info.get("picks", 0),
                "ejections": info.get("ejections", 0),
                "readmissions": info.get("readmissions", 0)}
               for ep, info in sorted(endpoints.items())]
    out = {"world_size": world, "replicas": replicas,
           "requests": requests, "retries": retries,
           "autoscale": autoscale, "respawns": respawns,
           "endpoints": ep_rows, "request_latency": lat}
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    print(f"world size: {world}  replicas: " +
          (", ".join(f"{k}={v}" for k, v in sorted(replicas.items()))
           or "none"))
    print("requests: " + (", ".join(f"{k}={v}" for k, v in
                                    sorted(requests.items()) if v)
                          or "none"))
    print("retries: " + (", ".join(f"{k}={v}" for k, v in
                                   sorted(retries.items()))
                         or "none"))
    print("autoscale: " + (", ".join(f"{k}={v}" for k, v in
                                     sorted(autoscale.items()))
                           or "none") + f"  respawns: {respawns}")
    if ep_rows:
        print()
        _print_aligned(ep_rows, ("endpoint", "breaker", "picks",
                                 "ejections", "readmissions"))
    if lat and lat.get("count"):
        print(f"\nrouter latency: n={lat['count']} "
              f"avg={lat['avg_ms']}ms p50~{lat['p50_ms']}ms "
              f"p99~{lat['p99_ms']}ms")
    if args.events:
        evs = _load_obs_module("events").read_jsonl(args.events,
                                                    n=args.n,
                                                    kind="fleet")
        print(f"\nlast {len(evs)} fleet events:")
        for ev in evs:
            print("  " + _fmt_event(ev))
    return 0


def _hist_summary(snap, name):
    """count / avg / estimated p50+p99 for an (unlabeled) histogram in
    a snapshot, via the ONE shared bucket-interpolation implementation
    (observability.metrics.bucket_quantile — aggregate.py and the SLO
    engine use the same one, so every tool agrees on what p99 means)."""
    series = (snap.get(name) or {}).get("series", [])
    if not series:
        return None
    s = series[0]
    count, total = int(s.get("count", 0)), float(s.get("sum", 0.0))
    if not count:
        return {"count": 0}
    bq = _load_obs_module("metrics").bucket_quantile
    buckets = s.get("buckets", [])
    return {"count": count, "avg_ms": round(1000 * total / count, 3),
            "p50_ms": round(1000 * (bq(0.50, buckets, count) or 0.0), 3),
            "p99_ms": round(1000 * (bq(0.99, buckets, count) or 0.0), 3)}


def cmd_decode(args) -> int:
    """Continuous-batching decode story from a metrics snapshot
    (SERVING.md §Continuous batching): queue depth, slot occupancy,
    KV-block accounting, token/step counters per phase, request
    outcomes, preemptions, and the TTFT / per-step latency histograms.
    With --events it also tails the decode events from a JSONL log."""
    snap = _load_snap(args)
    if snap is None:
        print("decode: need a metrics.json path or --live",
              file=sys.stderr)
        return 2

    def series(name):
        return (snap.get(name) or {}).get("series", [])

    def labeled(name, label):
        return {s.get("labels", {}).get(label, "?"): s["value"]
                for s in series(name)}

    gauges = {
        "queue_depth": next((int(s["value"]) for s in
                             series("paddle_tpu_decode_queue_depth")),
                            None),
        "slots": {k: int(v) for k, v in
                  labeled("paddle_tpu_decode_slots", "state").items()},
        "kv_blocks": {k: int(v) for k, v in
                      labeled("paddle_tpu_decode_kv_blocks",
                              "state").items()},
    }
    tokens = {k: int(v) for k, v in
              labeled("paddle_tpu_decode_tokens_total", "phase").items()}
    steps = {k: int(v) for k, v in
             labeled("paddle_tpu_decode_steps_total", "phase").items()}
    outcomes = {k: int(v) for k, v in
                labeled("paddle_tpu_decode_requests_total",
                        "outcome").items()}
    preempt = sum(int(s["value"]) for s in
                  series("paddle_tpu_decode_preemptions_total"))
    occ = (snap.get("paddle_tpu_decode_slot_occupancy") or {}) \
        .get("series", [])
    occ_avg = None
    if occ and occ[0].get("count"):
        occ_avg = round(float(occ[0]["sum"]) / occ[0]["count"], 3)
    ttft = _hist_summary(snap, "paddle_tpu_decode_ttft_seconds")
    step_h = _hist_summary(snap, "paddle_tpu_decode_step_seconds")
    prefix = {k: int(v) for k, v in
              labeled("paddle_tpu_prefix_cache_total", "event").items()}
    reused = next((int(s["value"]) for s in
                   series("paddle_tpu_decode_blocks_reused")), None)
    accept = next((round(float(s["value"]), 4) for s in
                   series("paddle_tpu_decode_spec_accept_rate")), None)
    kv_reuse = {"prefix_cache": prefix, "blocks_reused": reused,
                "spec_accept_rate": accept}

    if not tokens and not steps and gauges["queue_depth"] is None:
        print("no decode_* samples in this snapshot (did a DecodeEngine "
              "run in this process?)")
        return 0
    out = dict(gauges, tokens=tokens, steps=steps, requests=outcomes,
               preemptions=preempt, slot_occupancy_avg=occ_avg,
               ttft=ttft, step_seconds=step_h, kv_reuse=kv_reuse)
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    print(f"queue depth: {gauges['queue_depth']}")
    print("slots: " + (", ".join(f"{k}={v}" for k, v in
                                 sorted(gauges["slots"].items()))
                       or "none") +
          (f"  (occupancy avg {occ_avg})" if occ_avg is not None else ""))
    print("kv blocks: " + (", ".join(f"{k}={v}" for k, v in
                                     sorted(gauges["kv_blocks"].items()))
                           or "none"))
    print("tokens: " + (", ".join(f"{k}={v}" for k, v in
                                  sorted(tokens.items())) or "none"))
    print("steps: " + (", ".join(f"{k}={v}" for k, v in
                                 sorted(steps.items())) or "none"))
    print("requests: " + (", ".join(f"{k}={v}" for k, v in
                                    sorted(outcomes.items()) if v)
                          or "none"))
    print(f"preemptions: {preempt}")
    if prefix or reused or accept is not None:
        line = "kv reuse: " + (", ".join(
            f"{k}={v}" for k, v in sorted(prefix.items())) or "none")
        if reused is not None:
            line += f"  blocks_reused={reused}"
        if accept is not None:
            line += f"  spec_accept_rate={accept}"
        print(line)
    for label, h in (("ttft", ttft), ("step", step_h)):
        if h and h.get("count"):
            print(f"{label}: n={h['count']} avg={h['avg_ms']}ms "
                  f"p50~{h['p50_ms']}ms p99~{h['p99_ms']}ms")
    if args.events:
        evs = _load_obs_module("events").read_jsonl(args.events, n=args.n,
                                                    kind="decode")
        print(f"\nlast {len(evs)} decode events:")
        for ev in evs:
            print("  " + _fmt_event(ev))
    return 0


def cmd_tenants(args) -> int:
    """Multi-tenant serving story from a metrics snapshot (SERVING.md
    §Multi-tenancy): per-tenant request outcomes, token consumption
    and latency quantiles, shed counts by tier and kind (queue vs
    quota, replica-side and router-side), and the per-model registry
    view (adopted version, hot-swaps, publishes). With --events it
    also tails the shed/model_swap/registry events from a JSONL log."""
    snap = _load_snap(args)
    if snap is None:
        print("tenants: need a metrics.json path or --live",
              file=sys.stderr)
        return 2

    def series(name):
        return (snap.get(name) or {}).get("series", [])

    def labeled(name, label):
        out = {}
        for s in series(name):
            key = s.get("labels", {}).get(label, "?")
            out[key] = out.get(key, 0) + s["value"]
        return out

    bq = _load_obs_module("metrics").bucket_quantile

    def hist_by(name, label):
        """label value -> {count, avg_ms, p50_ms, p99_ms} for a
        labeled histogram."""
        out = {}
        for s in series(name):
            key = s.get("labels", {}).get(label, "?")
            count = int(s.get("count", 0))
            if not count:
                continue
            buckets = s.get("buckets", [])
            out[key] = {
                "count": count,
                "avg_ms": round(1000 * float(s.get("sum", 0.0))
                                / count, 3),
                "p50_ms": round(1000 * (bq(0.50, buckets, count)
                                        or 0.0), 3),
                "p99_ms": round(1000 * (bq(0.99, buckets, count)
                                        or 0.0), 3)}
        return out

    # tenant -> tier and tenant -> outcome counts from the one
    # three-way labeled counter
    tiers, outcomes = {}, {}
    for s in series("paddle_tpu_serving_tenant_requests_total"):
        lab = s.get("labels", {})
        t = lab.get("tenant", "?")
        tiers.setdefault(t, lab.get("tier", "?"))
        outcomes.setdefault(t, {})
        oc = lab.get("outcome", "?")
        outcomes[t][oc] = outcomes[t].get(oc, 0) + int(s["value"])
    tokens = {k: int(v) for k, v in labeled(
        "paddle_tpu_serving_tenant_tokens_total", "tenant").items()}
    lat = hist_by("paddle_tpu_serving_tenant_request_seconds", "tenant")
    ttft = hist_by("paddle_tpu_decode_tenant_ttft_seconds", "tenant")
    sheds = {}  # (tier, kind) -> n, replica-side
    for s in series("paddle_tpu_serving_sheds_total"):
        lab = s.get("labels", {})
        key = (lab.get("tier", "?"), lab.get("kind", "?"))
        sheds[key] = sheds.get(key, 0) + int(s["value"])
    fleet_sheds = {k: int(v) for k, v in labeled(
        "paddle_tpu_fleet_sheds_total", "tier").items()}
    models = {}  # model -> {version, swaps, publishes}
    for name, field in (("paddle_tpu_model_version", "version"),
                        ("paddle_tpu_model_swaps_total", "swaps"),
                        ("paddle_tpu_registry_publishes_total",
                         "publishes")):
        for m, v in labeled(name, "model").items():
            models.setdefault(m, {})[field] = int(v)

    if not outcomes and not sheds and not models:
        print("no tenant/model samples in this snapshot (QoS policy "
              "and per-tenant metrics only record when a policy is "
              "configured — SERVING.md §Multi-tenancy)")
        return 0

    tenant_rows = []
    for t in sorted(set(outcomes) | set(tokens) | set(lat)):
        oc = outcomes.get(t, {})
        row = {"tenant": t, "tier": tiers.get(t, "?"),
               "ok": oc.get("ok", 0),
               "rejected": oc.get("rejected", 0),
               "timeout": oc.get("timeout", 0),
               "error": oc.get("error", 0),
               "tokens": tokens.get(t, 0)}
        h = lat.get(t) or ttft.get(t)
        row["p99_ms"] = h["p99_ms"] if h else None
        tenant_rows.append(row)
    shed_rows = [{"tier": tier, "kind": kind, "sheds": n}
                 for (tier, kind), n in sorted(sheds.items())]
    model_rows = [{"model": m, "version": info.get("version", 0),
                   "swaps": info.get("swaps", 0),
                   "publishes": info.get("publishes", 0)}
                  for m, info in sorted(models.items())]
    out = {"tenants": tenant_rows, "sheds": shed_rows,
           "fleet_sheds": fleet_sheds, "models": model_rows,
           "ttft": ttft}
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    if tenant_rows:
        _print_aligned(tenant_rows, ("tenant", "tier", "ok",
                                     "rejected", "timeout", "error",
                                     "tokens", "p99_ms"))
    if shed_rows:
        print("\nsheds (replica admission):")
        _print_aligned(shed_rows, ("tier", "kind", "sheds"))
    if fleet_sheds:
        print("router shed answers: " + ", ".join(
            f"{k}={v}" for k, v in sorted(fleet_sheds.items())))
    if model_rows:
        print("\nmodels:")
        _print_aligned(model_rows, ("model", "version", "swaps",
                                    "publishes"))
    if args.events:
        evs = [ev for ev in _load_obs_module("events").read_jsonl(
            args.events)
            if ev.get("kind") in ("shed", "model_swap",
                                  "model_swap_failed", "registry")]
        evs = evs[-args.n:]
        print(f"\nlast {len(evs)} tenant/model events:")
        for ev in evs:
            print("  " + _fmt_event(ev))
    return 0


def _top_view(store, window):
    """One frame of the fleet dashboard: windowed rates/quantiles merged
    across every recording pid in the TS dir."""
    req = store.rate("paddle_tpu_fleet_requests_total", window,
                     by="outcome")
    total = sum(req.values())
    bad = sum(v for k, v in req.items() if k != "ok")
    serv = store.rate("paddle_tpu_serving_requests_total", window,
                      by="outcome")
    toks = store.rate("paddle_tpu_decode_tokens_total", window,
                      by="phase")
    ms = 1000.0

    def q(name, p):
        v = store.quantile(p, name, window)
        return None if v is None else round(v * ms, 3)

    return {
        "window_s": window,
        "now": store.latest_ts(),
        "pids": store.pids(),
        "fleet": {
            "req_per_s": round(total, 3),
            "error_rate": round(bad / total, 4) if total else 0.0,
            "outcomes_per_s": {k: round(v, 3) for k, v in
                               sorted(req.items())},
            "retries_per_s": round(store.rate(
                "paddle_tpu_fleet_retries_total", window), 3),
            "p50_ms": q("paddle_tpu_fleet_request_seconds", 0.50),
            "p99_ms": q("paddle_tpu_fleet_request_seconds", 0.99),
            "picks_per_s": {k: round(v, 3) for k, v in sorted(
                store.rate("paddle_tpu_fleet_picks_total", window,
                           by="endpoint").items())},
        },
        "serving": {
            "req_per_s": {k: round(v, 3) for k, v in
                          sorted(serv.items())},
            "p50_ms": q("paddle_tpu_serving_request_seconds", 0.50),
            "p99_ms": q("paddle_tpu_serving_request_seconds", 0.99),
            "queue_depth": store.gauge_latest(
                "paddle_tpu_serving_queue_depth"),
        },
        "decode": {
            "tokens_per_s": {k: round(v, 3) for k, v in
                             sorted(toks.items())},
            "ttft_p50_ms": q("paddle_tpu_decode_ttft_seconds", 0.50),
            "ttft_p99_ms": q("paddle_tpu_decode_ttft_seconds", 0.99),
        },
    }


def _render_top(view):
    f, s, d = view["fleet"], view["serving"], view["decode"]
    print(f"fleet top — window {view['window_s']}s, "
          f"{len(view['pids'])} recording pid(s): "
          f"{','.join(str(p) for p in view['pids'])}")
    print(f"  router: {f['req_per_s']}/s "
          f"(err {100 * f['error_rate']:.2f}%, "
          f"retries {f['retries_per_s']}/s) "
          f"p50~{f['p50_ms']}ms p99~{f['p99_ms']}ms")
    if f["outcomes_per_s"]:
        print("    outcomes: " + ", ".join(
            f"{k}={v}/s" for k, v in f["outcomes_per_s"].items()))
    if f["picks_per_s"]:
        rows = [{"endpoint": k, "picks/s": v}
                for k, v in f["picks_per_s"].items()]
        _print_aligned(rows, ("endpoint", "picks/s"))
    if s["req_per_s"] or s["p99_ms"] is not None:
        print(f"  serving: " + (", ".join(
            f"{k}={v}/s" for k, v in s["req_per_s"].items()) or "idle")
            + f"  p50~{s['p50_ms']}ms p99~{s['p99_ms']}ms "
            f"queue={s['queue_depth']}")
    if d["tokens_per_s"]:
        print("  decode: " + ", ".join(
            f"{k}={v} tok/s" for k, v in d["tokens_per_s"].items())
            + f"  ttft p50~{d['ttft_p50_ms']}ms "
            f"p99~{d['ttft_p99_ms']}ms")


def cmd_top(args) -> int:
    """Terminal fleet dashboard from a PADDLE_TPU_TS_DIR: per-endpoint
    request rates, error rates, latency quantiles and token throughput,
    merged across every recording process; --watch refreshes live."""
    import time as _time

    agg = _load_obs_module("aggregate")
    frames = 0
    while True:
        store = agg.TSStore.load(args.ts_dir)
        if not store.records:
            print(f"top: no ts-*.jsonl records under {args.ts_dir} "
                  f"(is PADDLE_TPU_TS_DIR recording?)", file=sys.stderr)
            return 2
        view = _top_view(store, args.window)
        if frames and not args.json:
            print()
        if args.json:
            print(json.dumps(view))
        else:
            _render_top(view)
        frames += 1
        if not args.watch or (args.frames and frames >= args.frames):
            return 0
        _time.sleep(args.watch)


def cmd_slo(args) -> int:
    """Objective table for a TS dir + SLO spec: target, current good
    fraction, fast/slow burn rates, alert state — the offline view of
    what the in-process evaluator serves at GET /v1/slo."""
    slo = _load_obs_module("slo")
    try:
        slos = slo.load_spec(args.spec)
    except (OSError, ValueError) as e:
        print(f"slo: bad spec: {e}", file=sys.stderr)
        return 2
    eng = slo.SLOEngine(slos, args.ts_dir,
                        window_scale=args.window_scale)
    rows = eng.evaluate()
    if args.json:
        print(json.dumps(rows, indent=2))
        return 0
    table = []
    for r in rows:
        wins = {w["window"]: w for w in r["windows"]}
        fast, slow = wins.get("fast", {}), wins.get("slow", {})

        def burn(w):
            return (f"{w.get('burn_short', 0):.2f}/"
                    f"{w.get('burn_long', 0):.2f}") if w else "-"

        table.append({
            "slo": r["name"], "type": r["type"],
            "target": f"{100 * r['target']:g}%",
            "current": "-" if r["current"] is None
            else f"{100 * r['current']:.3f}%",
            "burn fast": burn(fast), "burn slow": burn(slow),
            "state": r["state"]})
    _print_aligned(table, ("slo", "type", "target", "current",
                           "burn fast", "burn slow", "state"))
    return 0


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TiB"


def cmd_mem(args) -> int:
    """Per-owner HBM attribution: live bytes/buffers by owner
    (kv_pool/params/optimizer/executables/other), the process
    high-watermark, and the PADDLE_TPU_HBM_BUDGET_BYTES state —
    PROFILE.md §Continuous profiling. With --live a fresh forced sweep
    runs (including the ranked top-buffer list); from a snapshot file
    the owner gauges of the LAST sweep are tabled."""
    if args.live:
        import paddle_tpu  # noqa: F401 — registers providers

        from paddle_tpu.observability import memwatch
        rep = memwatch.report(top=True) or {}
        if args.json:
            print(json.dumps(rep, indent=2, default=str))
            return 0
        owners = rep.get("owners") or {}
        rows = [{"owner": o, "bytes": _fmt_bytes(b),
                 "raw_bytes": int(b)} for o, b in owners.items()]
        if rows:
            _print_aligned(rows, ("owner", "bytes", "raw_bytes"))
        else:
            print("no live device buffers")
        print(f"total     {_fmt_bytes(rep.get('total_bytes', 0))} "
              f"in {rep.get('buffers', 0)} buffer(s)")
        print(f"watermark {_fmt_bytes(rep.get('watermark_bytes', 0))}")
        budget = rep.get("budget_bytes") or 0
        print(f"budget    "
              f"{_fmt_bytes(budget) if budget else 'unset'} "
              f"({rep.get('budget_state', 'ok')})")
        print(f"executables {_fmt_bytes(rep.get('executable_bytes', 0))}"
              f" in {rep.get('executables', 0)} executable(s)")
        top = rep.get("top") or []
        if top:
            print("top buffers:")
            _print_aligned(
                [{"owner": t.get("owner", "?"),
                  "bytes": _fmt_bytes(t.get("nbytes", 0)),
                  "shape": str(t.get("shape", "?")),
                  "dtype": str(t.get("dtype", "?"))} for t in top],
                ("owner", "bytes", "shape", "dtype"))
        return 0
    snap = _load_snap(args)
    if snap is None:
        print("mem: need a metrics.json path or --live",
              file=sys.stderr)
        return 2
    owners = {}
    for s in (snap.get("paddle_tpu_hbm_bytes") or {}).get("series", []):
        owners[s.get("labels", {}).get("owner", "?")] = int(s["value"])

    def scalar(name):
        series = (snap.get(name) or {}).get("series", [])
        return int(series[0]["value"]) if series else 0

    rows = [{"owner": o, "bytes": _fmt_bytes(b), "raw_bytes": b}
            for o, b in sorted(owners.items(), key=lambda kv: -kv[1])]
    out = {"owners": owners,
           "watermark_bytes":
               scalar("paddle_tpu_hbm_watermark_bytes"),
           "budget_bytes": scalar("paddle_tpu_hbm_budget_bytes"),
           "executable_bytes": scalar("paddle_tpu_executable_bytes")}
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    if rows:
        _print_aligned(rows, ("owner", "bytes", "raw_bytes"))
    else:
        print("no paddle_tpu_hbm_bytes samples in this snapshot (no "
              "sweep ran, or introspection was off)")
    print(f"watermark {_fmt_bytes(out['watermark_bytes'])}")
    print(f"budget    "
          f"{_fmt_bytes(out['budget_bytes']) if out['budget_bytes'] else 'unset'}")
    print(f"executables {_fmt_bytes(out['executable_bytes'])}")
    return 0


def cmd_profile(args) -> int:
    """Render a /v1/profile capture: the merged chrome trace summary
    plus the attribution tables (per-kind MFU/step rates, per-owner
    HBM) the capture wrote alongside it. With --url, first trigger one
    bounded capture on a live server (a replica's serving port, the
    observability port, or the fleet router — the router reply
    aggregates per-replica artifacts and is printed as JSON)."""
    d = args.dir
    if args.url:
        import urllib.request
        body = json.dumps({"seconds": args.seconds}).encode()
        url = args.url.rstrip("/") + "/v1/profile"
        req = urllib.request.Request(
            url, data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(
                    req, timeout=args.seconds + 60) as r:
                out = json.loads(r.read())
        except OSError as e:
            print(f"profile: POST {url} failed: {e}", file=sys.stderr)
            return 1
        if "replicas" in out:  # router fan-out reply
            print(json.dumps(out, indent=2))
            return 0
        d = out.get("dir")
        print(f"captured {out.get('seconds')}s -> {d}")
    if not d:
        print("profile: need a capture dir or --url", file=sys.stderr)
        return 2
    trace_path = os.path.join(d, "trace.json")
    perf_path = os.path.join(d, "perf.json")
    summary = {"dir": d}
    try:
        with open(trace_path) as f:
            evs = json.load(f).get("traceEvents", [])
        by_name = {}
        for e in evs:
            if e.get("ph") == "X":
                by_name.setdefault(e.get("name", "?"), [0, 0.0])
                by_name[e["name"]][0] += 1
                by_name[e["name"]][1] += float(e.get("dur", 0)) / 1e3
        summary["trace_events"] = len(evs)
        summary["spans_by_name"] = {
            n: {"count": c, "total_ms": round(ms, 3)}
            for n, (c, ms) in sorted(by_name.items(),
                                     key=lambda kv: -kv[1][1])[:15]}
    except (OSError, ValueError) as e:
        summary["trace_error"] = str(e)
    try:
        with open(perf_path) as f:
            summary["perf"] = json.load(f)
    except (OSError, ValueError) as e:
        summary["perf_error"] = str(e)
    if args.json:
        print(json.dumps(summary, indent=2, default=str))
        return 0
    print(f"capture dir: {d}")
    print(f"trace: {summary.get('trace_events', '?')} event(s) "
          f"({trace_path})")
    spans = summary.get("spans_by_name") or {}
    if spans:
        _print_aligned(
            [{"span": n, "count": v["count"],
              "total_ms": v["total_ms"]} for n, v in spans.items()],
            ("span", "count", "total_ms"))
    perf = summary.get("perf") or {}
    kinds = perf.get("perfwatch") or {}
    if kinds:
        print("attribution (window at capture close):")
        _print_aligned(
            [{"kind": k, "mfu": round(v.get("mfu", 0.0), 6),
              "steps/s": round(v.get("steps_per_sec", 0.0), 2),
              "tok/s/chip":
                  round(v.get("tokens_per_sec_per_chip", 0.0), 2),
              "device": v.get("device_kind") or "?"}
             for k, v in sorted(kinds.items())],
            ("kind", "mfu", "steps/s", "tok/s/chip", "device"))
    mem = perf.get("memory") or {}
    owners = mem.get("owners") or {}
    if owners:
        print("memory owners:")
        _print_aligned(
            [{"owner": o, "bytes": _fmt_bytes(b)}
             for o, b in sorted(owners.items(), key=lambda kv: -kv[1])],
            ("owner", "bytes"))
        print(f"watermark {_fmt_bytes(mem.get('watermark_bytes', 0))}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="obsdump", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("snapshot", help="pretty-print a metrics snapshot")
    sp.add_argument("path", nargs="?", help="metrics.json from "
                    "PADDLE_TPU_METRICS_DIR (omit with --live)")
    sp.add_argument("--live", action="store_true",
                    help="read this process's registry instead of a file")
    sp.add_argument("--prom", action="store_true",
                    help="emit Prometheus text exposition")
    sp.set_defaults(fn=cmd_snapshot)

    tp = sub.add_parser("trace", help="merge a run dir into one chrome "
                        "trace, or reassemble a distributed trace tree "
                        "from a PADDLE_TPU_TRACE_DIR")
    tp.add_argument("run_dir")
    tp.add_argument("-o", "--output", default="trace.json")
    tp.add_argument("--trace-id", default=None,
                    help="reassemble ONE trace's cross-process span "
                    "tree from the dir's trace-*.jsonl sinks (the "
                    "X-Request-Id response header is the trace id)")
    tp.add_argument("--list-traces", action="store_true",
                    help="list the distributed traces found in the "
                    "dir's trace-*.jsonl sinks, newest first")
    tp.add_argument("--chrome", action="store_true",
                    help="with --trace-id: write the trace as a merged "
                    "chrome trace to -o instead of printing the tree")
    tp.set_defaults(fn=cmd_trace)

    ep = sub.add_parser("events", help="tail/filter a JSONL event log")
    ep.add_argument("path", help="events.jsonl (PADDLE_TPU_EVENT_LOG)")
    ep.add_argument("-n", type=int, default=20,
                    help="show the last N events (default 20)")
    ep.add_argument("--kind", default=None,
                    help="only events of this kind (compile|step_summary|"
                    "anomaly|checkpoint|amp_overflow|quantize|...)")
    ep.add_argument("--json", action="store_true",
                    help="raw JSON objects instead of the aligned table")
    ep.add_argument("--follow", action="store_true",
                    help="keep polling for appended events (default off)")
    ep.set_defaults(fn=cmd_events)

    cp = sub.add_parser("cache", help="per-kind compile-cache "
                        "hit/miss/bytes from a metrics snapshot")
    cp.add_argument("path", nargs="?", help="metrics.json from "
                    "PADDLE_TPU_METRICS_DIR (omit with --live)")
    cp.add_argument("--live", action="store_true",
                    help="read this process's registry instead of a file")
    cp.add_argument("--json", action="store_true",
                    help="rows as JSON instead of the aligned table")
    cp.set_defaults(fn=cmd_cache)

    anp = sub.add_parser("analysis", help="static-analysis walks + "
                         "findings per pass/severity from a metrics "
                         "snapshot")
    anp.add_argument("path", nargs="?", help="metrics.json from "
                     "PADDLE_TPU_METRICS_DIR (omit with --live)")
    anp.add_argument("--live", action="store_true",
                     help="read this process's registry instead of a "
                     "file")
    anp.add_argument("--json", action="store_true",
                     help="JSON instead of the aligned table")
    anp.set_defaults(fn=cmd_analysis)

    lkp = sub.add_parser("locks", help="lock held-seconds/contention "
                         "tables + observed lock-order inversions from "
                         "a metrics snapshot (PADDLE_TPU_LOCKCHECK)")
    lkp.add_argument("path", nargs="?", help="metrics.json from "
                     "PADDLE_TPU_METRICS_DIR (omit with --live)")
    lkp.add_argument("--live", action="store_true",
                     help="read this process's registry instead of a "
                     "file")
    lkp.add_argument("--json", action="store_true",
                     help="JSON instead of the aligned tables")
    lkp.set_defaults(fn=cmd_locks)

    pp = sub.add_parser("ps", help="parameter-server resilience summary "
                        "(RPC outcomes, breakers, reconnects, drops) "
                        "from a metrics snapshot")
    pp.add_argument("path", nargs="?", help="metrics.json from "
                    "PADDLE_TPU_METRICS_DIR (omit with --live)")
    pp.add_argument("--live", action="store_true",
                    help="read this process's registry instead of a file")
    pp.add_argument("--json", action="store_true",
                    help="JSON instead of the aligned tables")
    pp.add_argument("--events", default=None, metavar="JSONL",
                    help="also tail ps_failover events from this event "
                    "log")
    pp.add_argument("-n", type=int, default=20,
                    help="with --events: last N events (default 20)")
    pp.set_defaults(fn=cmd_ps)

    dp = sub.add_parser("decode", help="continuous-batching decode "
                        "summary (queue, slots, KV blocks, TTFT, "
                        "per-step latency) from a metrics snapshot")
    dp.add_argument("path", nargs="?", help="metrics.json from "
                    "PADDLE_TPU_METRICS_DIR (omit with --live)")
    dp.add_argument("--live", action="store_true",
                    help="read this process's registry instead of a file")
    dp.add_argument("--json", action="store_true",
                    help="JSON instead of the summary lines")
    dp.add_argument("--events", default=None, metavar="JSONL",
                    help="also tail decode events from this event log")
    dp.add_argument("-n", type=int, default=20,
                    help="with --events: last N events (default 20)")
    dp.set_defaults(fn=cmd_decode)

    fp = sub.add_parser("fleet", help="serving-fleet summary (world "
                        "size, per-replica health/ejections/retries, "
                        "breaker states, autoscale actions) from a "
                        "metrics snapshot")
    fp.add_argument("path", nargs="?", help="metrics.json from "
                    "PADDLE_TPU_METRICS_DIR (omit with --live)")
    fp.add_argument("--live", action="store_true",
                    help="read this process's registry instead of a file")
    fp.add_argument("--json", action="store_true",
                    help="JSON instead of the summary lines")
    fp.add_argument("--events", default=None, metavar="JSONL",
                    help="also tail fleet events from this event log")
    fp.add_argument("-n", type=int, default=20,
                    help="with --events: last N events (default 20)")
    fp.set_defaults(fn=cmd_fleet)

    tnp = sub.add_parser("tenants", help="multi-tenant serving summary "
                         "(per-tenant outcomes/tokens/latency, sheds "
                         "by tier+kind, per-model registry versions "
                         "and hot-swaps) from a metrics snapshot")
    tnp.add_argument("path", nargs="?", help="metrics.json from "
                     "PADDLE_TPU_METRICS_DIR (omit with --live)")
    tnp.add_argument("--live", action="store_true",
                     help="read this process's registry instead of a "
                     "file")
    tnp.add_argument("--json", action="store_true",
                     help="JSON instead of the summary tables")
    tnp.add_argument("--events", default=None, metavar="JSONL",
                     help="also tail shed/model_swap/registry events "
                     "from this event log")
    tnp.add_argument("-n", type=int, default=20,
                     help="with --events: last N events (default 20)")
    tnp.set_defaults(fn=cmd_tenants)

    top = sub.add_parser("top", help="live fleet dashboard from a "
                         "PADDLE_TPU_TS_DIR time-series dir: request/"
                         "error rates, latency quantiles, token "
                         "throughput merged across recording pids")
    top.add_argument("ts_dir", help="PADDLE_TPU_TS_DIR with ts-*.jsonl "
                     "recorder segments")
    top.add_argument("--window", type=float, default=60.0,
                     help="trailing window seconds for rates/quantiles "
                     "(default 60)")
    top.add_argument("--watch", type=float, default=0.0, metavar="S",
                     help="refresh every S seconds (0 = render once)")
    top.add_argument("--frames", type=int, default=0,
                     help="with --watch: stop after N frames (0 = "
                     "until interrupted)")
    top.add_argument("--json", action="store_true",
                     help="one JSON object per frame instead of the "
                     "dashboard")
    top.set_defaults(fn=cmd_top)

    slp = sub.add_parser("slo", help="SLO objective table (target, "
                         "current, burn rates, alert state) from a "
                         "time-series dir + JSON spec")
    slp.add_argument("ts_dir", help="PADDLE_TPU_TS_DIR with ts-*.jsonl "
                     "recorder segments")
    slp.add_argument("--spec", required=True,
                     help="SLO spec JSON file (PROFILE.md §Time series "
                     "& SLOs)")
    slp.add_argument("--window-scale", type=float, default=1.0,
                     help="shrink every burn window uniformly "
                     "(PADDLE_TPU_SLO_WINDOW_SCALE equivalent; bench "
                     "dirs need ~0.001)")
    slp.add_argument("--json", action="store_true",
                     help="rows as JSON instead of the aligned table")
    slp.set_defaults(fn=cmd_slo)

    mp = sub.add_parser("mem", help="per-owner HBM attribution table "
                        "(watermark, budget state, top buffers)")
    mp.add_argument("path", nargs="?", help="metrics.json from "
                    "PADDLE_TPU_METRICS_DIR (omit with --live)")
    mp.add_argument("--live", action="store_true",
                    help="force a fresh sweep in this process (adds "
                    "the ranked top-buffer list)")
    mp.add_argument("--json", action="store_true",
                    help="report as JSON instead of the aligned table")
    mp.set_defaults(fn=cmd_mem)

    prp = sub.add_parser("profile", help="render a /v1/profile capture "
                         "dir (trace summary + attribution tables); "
                         "--url triggers a capture first")
    prp.add_argument("dir", nargs="?", help="capture artifact dir "
                     "(holds trace.json + perf.json)")
    prp.add_argument("--url", help="base URL of a live server "
                     "(replica, metrics port, or fleet router) to POST "
                     "/v1/profile at before rendering")
    prp.add_argument("--seconds", type=float, default=2.0,
                     help="capture window for --url (default 2s)")
    prp.add_argument("--json", action="store_true",
                     help="summary as JSON instead of tables")
    prp.set_defaults(fn=cmd_profile)

    # unknown/missing subcommands exit nonzero via argparse itself
    # (required=True subparsers error out with status 2)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
