"""Downpour-flow CTR throughput benchmark (VERDICT r1 item 7).

Measures, on one host (CPU — the CTR path is host-side by design):
1. end-to-end Downpour worker flow samples/s: native datafeed batch →
   distributed_embedding prefetch (pull_sparse RPC) → compiled step →
   sparse grad push (reference: DownpourWorker loop downpour_worker.cc:611)
2. raw PS sparse-table op throughput: pull_sparse rows/s and
   push_sparse_grad rows/s over the TCP protocol
3. raw dense push→optimize throughput on the server (adam desc applied
   per arrival, the async-mode hot path)

Prints one JSON line per metric. Run: python tools/ctr_bench.py
"""

from __future__ import annotations

import json
import os
import socket
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def bench_raw_sparse(client, n_iters=50, rows_per_call=512, V=100_000,
                     D=16):
    from paddle_tpu.ps.sparse_table import (init_sparse_table,
                                            push_row_grads, pull_rows)

    rng = np.random.RandomState(0)
    init_sparse_table(client, "bench_table",
                      rng.rand(V, D).astype("float32"))
    ids = rng.randint(0, V, (n_iters, rows_per_call))
    grads = rng.rand(n_iters, rows_per_call, D).astype("float32")

    t0 = time.perf_counter()
    for i in range(n_iters):
        pull_rows(client, "bench_table", ids[i])
    dt_pull = time.perf_counter() - t0
    t0 = time.perf_counter()
    for i in range(n_iters):
        push_row_grads(client, "bench_table", ids[i], grads[i], lr=0.01)
    dt_push = time.perf_counter() - t0
    n_rows = n_iters * rows_per_call
    print(json.dumps({
        "metric": "ps_sparse_pull_rows_per_sec",
        "value": round(n_rows / dt_pull, 1), "unit": "rows/s",
        "detail": {"rows_per_call": rows_per_call, "dim": D,
                   "servers": len(client.endpoints)}}), flush=True)
    print(json.dumps({
        "metric": "ps_sparse_push_rows_per_sec",
        "value": round(n_rows / dt_push, 1), "unit": "rows/s",
        "detail": {"rows_per_call": rows_per_call, "dim": D,
                   "servers": len(client.endpoints)}}), flush=True)


def bench_box_cache(client, n_iters=50, rows_per_call=512, V=100_000,
                    D=16, hot_frac=0.1, capacity=1 << 14):
    """BoxPS-analogue pull throughput (reference: fleet/box_wrapper.h):
    zipf-ish CTR id stream (10% hot ids get 90% of lookups) through the
    hot-row LRU — reports rows/s and the cache hit rate. NOTE on
    reading the number: against the LOOPBACK pservers of this bench the
    RPC is nearly free, so the cache roughly breaks even on pull
    throughput; its value is hit_rate x (RPC rows + round trips)
    avoided, which dominates when the PS is across a real network —
    exactly BoxPS's raison d'etre."""
    from paddle_tpu.ps.box_cache import BoxSparseCache
    from paddle_tpu.ps.sparse_table import init_sparse_table

    rng = np.random.RandomState(7)
    init_sparse_table(client, "box_bench_table",
                      rng.rand(V, D).astype("float32"))
    box = BoxSparseCache(client, capacity_rows=capacity)
    hot_n = int(V * hot_frac * 0.01)  # hot set sized well under capacity
    hot = rng.randint(0, V, max(hot_n, 1))
    batches = np.where(rng.rand(n_iters, rows_per_call) < 0.9,
                       hot[rng.randint(0, hot.size,
                                       (n_iters, rows_per_call))],
                       rng.randint(0, V, (n_iters, rows_per_call)))
    grads = rng.rand(rows_per_call, D).astype("float32")

    t0 = time.perf_counter()
    for i in range(n_iters):
        box.pull_sparse("box_bench_table", batches[i], D)
    dt_pull = time.perf_counter() - t0
    t0 = time.perf_counter()
    for i in range(n_iters):
        box.push_sparse_grad("box_bench_table", batches[i], grads, lr=0.01)
    box.end_pass()  # include flush drain in the push timing
    dt_push = time.perf_counter() - t0
    n_rows = n_iters * rows_per_call
    print(json.dumps({
        "metric": "box_cache_pull_rows_per_sec",
        "value": round(n_rows / dt_pull, 1), "unit": "rows/s",
        "detail": {"hit_rate": box.stats()["hit_rate"],
                   "resident_rows": box.stats()["resident_rows"],
                   "push_rows_per_sec_incl_flush":
                       round(n_rows / dt_push, 1),
                   "rows_per_call": rows_per_call, "dim": D,
                   "servers": len(client.endpoints)}}), flush=True)


def _init_dense_adam_var(client, name, dim):
    adam_descs = [{
        "type": "adam",
        "inputs": {"Param": [name], "Grad": [f"{name}@GRAD"],
                   "LearningRate": [f"{name}_lr"],
                   "Moment1": [f"{name}_m1"], "Moment2": [f"{name}_m2"],
                   "Beta1Pow": [f"{name}_b1"], "Beta2Pow": [f"{name}_b2"]},
        "outputs": {"ParamOut": [name], "Moment1Out": [f"{name}_m1"],
                    "Moment2Out": [f"{name}_m2"],
                    "Beta1PowOut": [f"{name}_b1"],
                    "Beta2PowOut": [f"{name}_b2"]},
        "attrs": {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
    }]
    client.init_var(name, np.zeros(dim, np.float32), adam_descs,
                    grad_name=f"{name}@GRAD")
    client.init_aux(f"{name}_lr", np.array([0.001], np.float32), owner=name)
    for suffix, v in (("_m1", np.zeros(dim)), ("_m2", np.zeros(dim)),
                      ("_b1", np.array([0.9])), ("_b2", np.array([0.999]))):
        client.init_aux(name + suffix, v.astype(np.float32), owner=name)


def bench_raw_dense(client, n_iters=50, n_vars=16, dim=6_250):
    """Dense push→adam-desc-apply per arrival (async-mode server path),
    shaped like a real model: n_vars dense params per step (a CTR MLP
    ships each layer's weights), 100k elems total. A/Bs the merged
    send path (push_grads: ONE RPC per server per step, VERDICT r4
    item 8 / communicator.h:276) against one-RPC-per-var at the SAME
    shape; the metric is the merged (production transpiler) path."""
    rng = np.random.RandomState(1)
    names = [f"dw{i}" for i in range(n_vars)]
    for n in names:
        _init_dense_adam_var(client, n, dim)
    grads = {n: rng.rand(dim).astype("float32") for n in names}

    client.push_grads(grads)  # warm kernel caches + placement
    t0 = time.perf_counter()
    for _ in range(n_iters):
        client.push_grads(grads)
    dt_merged = time.perf_counter() - t0

    for n, g in grads.items():
        client.push_grad(n, g)  # warm per-var path
    t0 = time.perf_counter()
    for _ in range(n_iters):
        for n, g in grads.items():
            client.push_grad(n, g)
    dt_pervar = time.perf_counter() - t0

    from paddle_tpu.ps import native_opt

    kernel = ("fused native (psopt.cc)"
              if native_opt.get_lib() is not None
              else "numpy fallback (native psopt build failed)")
    n_updates = n_iters * n_vars
    print(json.dumps({
        "metric": "ps_dense_adam_updates_per_sec",
        "value": round(n_updates / dt_merged, 1), "unit": "updates/s",
        "detail": {
            "n_vars": n_vars, "param_elems_each": dim,
            "elems_per_sec": round(n_updates * dim / dt_merged, 1),
            "per_var_rpc_updates_per_sec": round(n_updates / dt_pervar, 1),
            "merged_speedup_vs_per_var":
                round(dt_pervar / dt_merged, 2),
            "apply_kernel": kernel,
            "note": "merged path = ps_send_many/push_grads (one RPC per "
                    "server per step, the transpiler default); per-var "
                    "path kept for the A/B"}}),
        flush=True)


def bench_downpour_flow(client, tmpdir, V=100_000, D=16, batch=512,
                        n_files=4, lines_per_file=4096):
    import paddle_tpu as pt
    from paddle_tpu.io_native import NativeDataset
    from paddle_tpu.ps.sparse_table import init_sparse_table

    rng = np.random.RandomState(2)
    init_sparse_table(client, "flow_table",
                      (rng.rand(V, D).astype("float32") * 0.1))
    files = []
    for i in range(n_files):
        ids = rng.randint(0, V, (lines_per_file, 1))
        clicks = (ids % 3 == 0).astype(np.float32)
        path = os.path.join(tmpdir, f"ctr-{i}.txt")
        np.savetxt(path, np.hstack([ids.astype(np.float32), clicks]),
                   fmt="%.1f")
        files.append(path)

    main, startup = pt.Program(), pt.Program()
    with pt.framework.unique_name.guard(), pt.program_guard(main, startup):
        w = pt.layers.data(name="wf", shape=[1], dtype="float32")
        label = pt.layers.data(name="label", shape=[1], dtype="float32")
        ids64 = pt.layers.cast(w, "int64")
        emb = pt.layers.distributed_embedding(ids64, (V, D), "flow_table",
                                              sparse_lr=0.1)
        emb = pt.layers.reshape(emb, shape=[-1, D])
        pred = pt.layers.fc(input=emb, size=1, act="sigmoid")
        loss = pt.layers.mean(pt.layers.log_loss(pred, label))
        pt.optimizer.Adam(0.01).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        ds = NativeDataset(slots=[("wf", (1,)), ("label", (1,))],
                           batch_size=batch)
        ds.set_filelist(files)
        # warm epoch compiles the step
        n_samples = 0
        for feed in iter(ds):
            exe.run(main, feed=feed, fetch_list=[loss])
        t0 = time.perf_counter()
        for _ in range(2):
            ds2 = NativeDataset(slots=[("wf", (1,)), ("label", (1,))],
                                batch_size=batch)
            ds2.set_filelist(files)
            for feed in iter(ds2):
                exe.run(main, feed=feed, fetch_list=[loss])
                n_samples += feed["wf"].shape[0]
        dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "downpour_ctr_samples_per_sec",
        "value": round(n_samples / dt, 1), "unit": "samples/s",
        "detail": {"batch_size": batch, "vocab": V, "emb_dim": D,
                   "servers": len(client.endpoints),
                   "pipeline": "native datafeed -> pull_sparse -> "
                               "step -> push_sparse"}}), flush=True)


def main():
    from paddle_tpu.ops.distributed import bind_client
    from paddle_tpu.ps import ParameterServer, PSClient

    ports = _free_ports(2)
    eps = [f"127.0.0.1:{p}" for p in ports]
    servers = [ParameterServer(ep, num_trainers=1, mode="async")
               for ep in eps]
    for s in servers:
        s.start_background()
    client = PSClient(eps)
    bind_client(client)
    try:
        bench_raw_sparse(client)
        bench_box_cache(client)
        bench_raw_dense(client)
        with tempfile.TemporaryDirectory() as td:
            bench_downpour_flow(client, td)
    finally:
        for s in servers:
            s.stop()
    return 0


if __name__ == "__main__":
    # CPU-pinned (PS/TCP benchmark — no chip involvement): no TPU lock.
    sys.exit(main())
