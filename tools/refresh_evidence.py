"""Regenerate the builder-owned evidence artifacts in one command.

VERDICT r3 #2: evidence that drifts from claims is how overclaiming
starts — INFER_BENCH.json and BENCH_CTR.json had gone stale against
PARITY's round-3 claims, and PARITY's op count lagged the live registry.
This tool re-runs the benchmark tools, rewrites those artifacts, and
syncs PARITY.md's registered-op-type count with the live registry.

Covered: INFER_BENCH.json, BENCH_CTR.json, PARITY.md op count.
NOT covered (driver-generated at round end, do not hand-edit):
BENCH_rXX.json (`python bench.py`), MULTICHIP_rXX.json
(`__graft_entry__.dryrun_multichip`), COPYCHECK.json, BASELINE.json.

Usage: python tools/refresh_evidence.py            (all covered artifacts)
       python tools/refresh_evidence.py ctr parity (a subset)
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _run_tool_to_json_lines(tool: str, out_path: str):
    """Run a bench tool, keep only its JSON lines, write the artifact."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", tool)],
        capture_output=True, text=True, cwd=_REPO, timeout=3600)
    lines = []
    for ln in proc.stdout.splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            json.loads(ln)
        except json.JSONDecodeError:
            continue
        lines.append(ln)
    if proc.returncode != 0 or not lines:
        raise RuntimeError(
            f"{tool} failed (rc={proc.returncode}):\n{proc.stderr[-2000:]}")
    with open(os.path.join(_REPO, out_path), "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {out_path}: {len(lines)} metrics")


def refresh_infer():
    _run_tool_to_json_lines("infer_bench.py", "INFER_BENCH.json")


def refresh_ctr():
    _run_tool_to_json_lines("ctr_bench.py", "BENCH_CTR.json")


def refresh_parity_op_count():
    import paddle_tpu  # noqa: F401  (populates the registry)
    from paddle_tpu.core import registry

    live = len(registry._REGISTRY)
    path = os.path.join(_REPO, "PARITY.md")
    with open(path) as f:
        text = f.read()
    new, n = re.subn(r"\*\*\d+ registered op types\*\*",
                     f"**{live} registered op types**", text)
    if n != 1:
        raise RuntimeError(
            f"PARITY.md op-count line not found exactly once (n={n})")
    if new != text:
        with open(path, "w") as f:
            f.write(new)
        print(f"PARITY.md op count -> {live}")
    else:
        print(f"PARITY.md op count already {live}")


def bench_fallback_recorded(data) -> bool:
    """Distinguish "chip wedged, CPU fallback recorded" from "harness
    crashed" for a BENCH driver file with rc != 0 (VERDICT weak #7 /
    ROADMAP item 5). True when the recorded metric lines carry the
    structured top-level `env` block bench.py now attaches and at
    least one of them records an actual TPU→CPU fallback
    (tpu_reachable false + a fallback_reason): the harness ran to
    completion and said so, which is citable as CPU evidence. A file
    whose lines carry no env blocks (pre-env bench, or a crash before
    any line was written) stays an error under rc != 0."""
    recs = []
    parsed = data.get("parsed")
    if isinstance(parsed, list):
        recs.extend(r for r in parsed if isinstance(r, dict))
    elif isinstance(parsed, dict):
        recs.append(parsed)
    for line in (data.get("tail") or "").splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            recs.append(rec)
    envs = [r.get("env") for r in recs if isinstance(r.get("env"), dict)]
    return any(e.get("tpu_reachable") is False and e.get("fallback_reason")
               for e in envs)


def lint_evidence_claims():
    """Claims may only cite driver evidence that exists AND recorded ok
    (VERDICT r4 item 9: round 4 claimed a flagship number against a
    BENCH file that was rc=1). Every ``BENCH_rNN``/``MULTICHIP_rNN``
    name appearing in PARITY.md or PROFILE.md must have its committed
    JSON present with rc==0 (bench) / ok==true (multichip). Returns a
    list of violations; run by the test suite
    (tests/test_evidence_lint.py) so a stale citation fails CI."""
    pat = re.compile(r"\b(BENCH_r\d+|MULTICHIP_r\d+)\b")
    errors = []
    for doc in ("PARITY.md", "PROFILE.md"):
        doc_path = os.path.join(_REPO, doc)
        if not os.path.exists(doc_path):
            continue
        with open(doc_path) as f:
            cited = sorted(set(pat.findall(f.read())))
        for name in cited:
            path = os.path.join(_REPO, name + ".json")
            if not os.path.exists(path):
                errors.append(f"{doc} cites {name}, but {name}.json "
                              "does not exist")
                continue
            try:
                with open(path) as f:
                    data = json.load(f)
            except ValueError:
                errors.append(f"{doc} cites {name}, but {name}.json is "
                              "not valid JSON")
                continue
            if name.startswith("BENCH_") and data.get("rc") != 0 \
                    and not bench_fallback_recorded(data):
                errors.append(
                    f"{doc} cites {name}, but its recorded "
                    f"rc={data.get('rc')} with no structured env "
                    "fallback on its metric lines (harness crash, not "
                    "a recorded CPU fallback — see "
                    "bench_fallback_recorded)")
            if name.startswith("MULTICHIP_") and not data.get("ok"):
                errors.append(f"{doc} cites {name}, but its recorded "
                              f"ok={data.get('ok')} (driver run failed)")
    return errors


def main():
    known = {"infer", "ctr", "parity", "lint"}
    targets = set(sys.argv[1:]) or set(known)
    bad = targets - known
    if bad:
        print(f"unknown target(s) {sorted(bad)}; choose from "
              f"{sorted(known)}", file=sys.stderr)
        return 2
    if "parity" in targets:
        refresh_parity_op_count()
    if "ctr" in targets:
        refresh_ctr()
    if "infer" in targets:
        refresh_infer()
    if "lint" in targets:
        errors = lint_evidence_claims()
        for e in errors:
            print(f"EVIDENCE LINT: {e}", file=sys.stderr)
        if errors:
            return 1
        print("evidence lint: all driver citations valid")
    return 0


if __name__ == "__main__":
    sys.exit(main())
