"""Open-loop Poisson load generator for the serving subsystem.

Starts a `serving.Server` on a LeNet-sized MLP, fires requests with
exponential inter-arrival times at a fixed offered rate (open loop:
arrivals do not wait for completions, so overload shows up as rejects
and latency, not as a silently throttled client), and reports
INFER_BENCH-style JSON lines: p50/p99 end-to-end latency, achieved
throughput, and the reject rate.

Run:  python tools/serve_bench.py [--rate 200] [--duration 10]
      [--max-batch 16] [--max-wait-ms 5] [--max-queue 128] [--batch 1]
      [--smoke]

--smoke is the tier-1-safe mode the test suite invokes (CPU backend,
~1.5 s of traffic, small model) — it validates the full HTTP path and
the report schema, not absolute numbers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time


def _build_args():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rate", type=float, default=200.0,
                    help="offered load, requests/second")
    ap.add_argument("--duration", type=float, default=10.0,
                    help="seconds of traffic")
    ap.add_argument("--batch", type=int, default=1,
                    help="rows per request")
    ap.add_argument("--max-batch", type=int, default=16)
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--max-queue", type=int, default=128)
    ap.add_argument("--timeout-s", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU run for CI (overrides rate/duration)")
    return ap.parse_args()


def _save_model(tmpdir: str):
    """LeNet-sized MLP (784→128→10) saved as an inference model."""
    import numpy as np

    import paddle_tpu as pt

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = pt.layers.data(name="x", shape=[784], dtype="float32")
        h = pt.layers.fc(input=x, size=128, act="relu")
        pred = pt.layers.fc(input=h, size=10, act="softmax")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    pt.io.save_inference_model(tmpdir, ["x"], [pred], exe,
                               main_program=main)
    return np.random.RandomState(0).rand(64, 784).astype("float32")


def _percentile(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1))))
    return xs[i]


def run_bench(args) -> int:
    import random
    import urllib.error
    import urllib.request

    import jax

    from paddle_tpu.serving import ServingConfig, Server

    tmpdir = tempfile.mkdtemp(prefix="serve_bench_")
    probe = _save_model(tmpdir)
    cfg = ServingConfig(
        tmpdir, max_batch=args.max_batch, max_queue=args.max_queue,
        max_wait_ms=args.max_wait_ms, timeout_s=args.timeout_s)
    server = Server(cfg)
    port = server.start(0)
    url = f"http://127.0.0.1:{port}/v1/predict"

    rng = random.Random(args.seed)
    n_requests = max(1, int(args.rate * args.duration))
    arrivals, t = [], 0.0
    for _ in range(n_requests):
        t += rng.expovariate(args.rate)
        arrivals.append(t)

    lock = threading.Lock()
    oks, rejects, timeouts, errors = [], 0, 0, 0
    body = json.dumps(
        {"feeds": {"x": probe[:args.batch].tolist()}}).encode()

    def fire():
        nonlocal rejects, timeouts, errors
        t0 = time.perf_counter()
        try:
            req = urllib.request.Request(
                url, data=body, headers={"Content-Type":
                                         "application/json"})
            with urllib.request.urlopen(req, timeout=args.timeout_s + 5):
                pass
            dt = (time.perf_counter() - t0) * 1000
            with lock:
                oks.append(dt)
        except urllib.error.HTTPError as e:
            with lock:
                if e.code == 503:
                    rejects += 1
                elif e.code == 504:
                    timeouts += 1
                else:
                    errors += 1
        except Exception:
            with lock:
                errors += 1

    # bound in-flight senders: unbounded per-request threads would
    # distort the latencies being measured (thread-stack/scheduler
    # pressure) and can hit RLIMIT under overload. At the cap the
    # generator degrades toward closed-loop — visible as completed <
    # requests in the report rather than a silent distortion.
    cap = threading.Semaphore(max(64, 4 * args.max_queue))

    def fire_capped():
        try:
            fire()
        finally:
            cap.release()

    threads = []
    start = time.perf_counter()
    for at in arrivals:
        delay = at - (time.perf_counter() - start)
        if delay > 0:
            time.sleep(delay)
        cap.acquire()
        th = threading.Thread(target=fire_capped, daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=args.timeout_s + 10)
    wall = time.perf_counter() - start
    server.stop()

    done = len(oks) + rejects + timeouts + errors
    detail = {
        "rate_offered_rps": args.rate, "duration_s": args.duration,
        "requests": n_requests, "completed": done, "ok": len(oks),
        "rejected": rejects, "timeout": timeouts, "error": errors,
        "rows_per_request": args.batch, "max_batch": args.max_batch,
        "max_wait_ms": args.max_wait_ms, "max_queue": args.max_queue,
        "platform": jax.devices()[0].platform, "smoke": bool(args.smoke),
    }
    for metric, value, unit in (
            ("serving_p50_latency_ms", _percentile(oks, 50), "ms"),
            ("serving_p99_latency_ms", _percentile(oks, 99), "ms"),
            ("serving_throughput_rps",
             round(len(oks) * args.batch / wall, 3) if wall > 0 else 0,
             "req_rows/s"),
            ("serving_reject_rate",
             round(rejects / max(1, done), 4), "fraction")):
        print(json.dumps({
            "metric": metric,
            "value": round(value, 3) if isinstance(value, float) else value,
            "unit": unit, "detail": detail}), flush=True)
    return 0 if (len(oks) > 0 and errors == 0) else 1


def main() -> int:
    args = _build_args()
    if args.smoke:
        # tier-1 safety: tiny, CPU-only, deterministic-ish
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        args.rate, args.duration = 80.0, 1.5
        args.max_batch, args.max_queue = 8, 64
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from paddle_tpu.core.tpu_lock import tpu_singleflight

    with tpu_singleflight():  # one real chip: serialize vs bench/tools
        return run_bench(args)


if __name__ == "__main__":
    sys.exit(main())
