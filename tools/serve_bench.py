"""Open-loop Poisson load generator for the serving subsystem.

Three modes:

**Predict mode** (default): starts a `serving.Server` on a LeNet-sized
MLP, fires requests with exponential inter-arrival times at a fixed
offered rate (open loop: arrivals do not wait for completions, so
overload shows up as rejects and latency, not as a silently throttled
client), and reports INFER_BENCH-style JSON lines: p50/p99 end-to-end
latency, achieved throughput, and the reject rate.

**Token mode** (`--tokens`, ISSUE 12): boots the continuous-batching
decode engine on a tiny GPT, streams open-loop Poisson prompt arrivals
through chunked POST /v1/generate, and reports time-to-first-token,
per-token gap p50/p99, and tokens/s/chip — then re-runs the SAME
arrival schedule against the static-batch drain-between-batches
baseline (`DecodeConfig(static_batching=True)`, identical machinery,
scheduler policy only) for the continuous-vs-static A/B, and finally
replays the full phase grid on a warmstart-booted engine asserting
ZERO fresh compile events and bit-identical tokens vs the cold engine.
Acceptance (ISSUE 12): continuous sustains >=2x tokens/s at equal (or
better) p99 end-to-end latency, and the warm replay is compile-free
and bit-identical.

**Fleet mode** (`--fleet`, ISSUE 14): boots a ReplicaSupervisor fleet
(N replica subprocesses warmstart-booted from an artifact baked
in-process, heartbeating into a shared rendezvous store) behind a
Router, then runs the three chaos gates from the ISSUE 14 acceptance
criteria:

  1. **failover** — open-loop Poisson load through the router;
     mid-load, SIGKILL one replica. Gate: ZERO failed client requests
     (the router health-ejects the corpse and retries the in-flight
     idempotent predicts on a survivor; ejection + retry recorded in
     fleet events), and the supervisor respawns the slot.
  2. **scale-out** — traffic steps to 2x with the Autoscaler armed.
     Gate: a scale-out lands (warmstart-booted: the new replica's
     /v1/status shows warmstart_adopted > 0), and the p99 of the final
     third of the step phase recovers to <= --p99-recover-factor x the
     phase's peak window p99.
  3. **scale-in** — traffic drops; a graceful scale_in drains the
     newest replica WHILE a request burst is in flight. Gate: zero
     dropped requests (drain semantics: leave rendezvous, finish
     in-flight, 503+Retry-After stragglers fail over).
  4. **distributed tracing** (ISSUE 15) — the fleet runs with
     PADDLE_TPU_TRACE_DIR shared and sampling OFF for gates 1-3; gate
     4 then (a) A/Bs a predict phase with PADDLE_TPU_TRACE_SAMPLE=0 vs
     1.0 (gate: traced p50 <= 1.05x untraced, with a small absolute
     floor for CPU-smoke noise), and (b) with sampling at 1.0, routes
     one generate through a decode replica (--decode-tiny) and one
     predict through the main fleet, then reassembles both traces from
     the shared trace dir (the obsdump `trace --trace-id` machinery).
     Gate: the generate trace is a SINGLE tree spanning router →
     replica → decode with queue-wait, prefill-phase, and TTFT spans
     attributed, crossing >= 2 processes; the predict trace carries
     batcher queue-wait + batch spans under the router root.
  5. **telemetry + SLO burn-rate** (ISSUE 16) — recycles the fleet
     with PADDLE_TPU_TS_DIR set so router + every replica pid records
     metric time series, A/Bs recorder-on vs -off predict p50 (same
     <= 1.05x / 2.5ms gate as tracing), then arms a sleep shim
     (PADDLE_TPU_SLOW_SHIM_FILE) on the replicas and declares a tight
     latency SLO over paddle_tpu_fleet_request_seconds: the slow
     replica breaches the fast burn pair → `slo_alert` fires
     (fast_burn), the shim lifts → the alert clears to ok, and
     `obsdump slo` / `obsdump top` against the shared TS dir reflect
     both states with >= 3 recording pids fleet-merged.

Run:  python tools/serve_bench.py [--rate 200] [--duration 10]
      [--max-batch 16] [--max-wait-ms 5] [--max-queue 128] [--batch 1]
      [--tokens] [--slots 4,8] [--prefill-buckets 8,16,32]
      [--warmstart ART] [--fleet] [--replicas 2] [--smoke]

--smoke is the tier-1-safe mode the test suite invokes (CPU backend,
short traffic, small model) — it validates the full HTTP path, the A/B
gates, and the report schema, not absolute numbers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time


def _build_args():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rate", type=float, default=200.0,
                    help="offered load, requests/second")
    ap.add_argument("--duration", type=float, default=10.0,
                    help="seconds of traffic")
    ap.add_argument("--batch", type=int, default=1,
                    help="rows per request")
    ap.add_argument("--max-batch", type=int, default=16)
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--max-queue", type=int, default=128)
    ap.add_argument("--timeout-s", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--tokens", action="store_true",
                    help="token-streaming mode: continuous-batching "
                    "decode A/B + warmstart grid replay")
    ap.add_argument("--slots", default="4,8",
                    help="decode slot configs (token mode)")
    ap.add_argument("--prefill-buckets", default="8,16,32",
                    help="prompt-length buckets (token mode)")
    ap.add_argument("--warmstart", default=None,
                    help="pre-baked decode warmstart artifact to boot "
                    "the warm-replay engine from (token mode; default: "
                    "bake in-process from the cold engine)")
    ap.add_argument("--prefix-share", action="store_true",
                    help="with --tokens: shared-system-prompt A/B — "
                    "KV reuse (chunked prefill + prefix cache + "
                    "speculation) vs the plain engine on the same "
                    "prompts (SERVING.md §KV reuse)")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet chaos mode: replica kill under load, "
                    "2x traffic step with autoscaling, graceful "
                    "scale-in (ISSUE 14 gates)")
    ap.add_argument("--tenants", action="store_true",
                    help="multi-tenant chaos mode: bronze-tier noisy-"
                    "neighbor flood vs a gold-tier trickle (sheds "
                    "must land on bronze only, gold p99 holds), then "
                    "a registry hot-swap under load with zero failed "
                    "requests and zero fresh compiles (SERVING.md "
                    "§Multi-tenancy gates)")
    ap.add_argument("--tenant-p99-factor", type=float, default=10.0,
                    help="noisy-neighbor gate: gold p99 under the "
                    "bronze flood must be <= this x its unloaded "
                    "baseline (plus a 100ms absolute allowance for "
                    "CI noise)")
    ap.add_argument("--flood-threads", type=int, default=8,
                    help="closed-loop bronze flood senders "
                    "(tenants mode)")
    ap.add_argument("--replicas", type=int, default=2,
                    help="initial fleet size (fleet mode)")
    ap.add_argument("--fleet-max", type=int, default=3,
                    help="autoscaler max replicas (fleet mode)")
    ap.add_argument("--p99-recover-factor", type=float, default=1.0,
                    help="scale-out gate: tail-third p99 must be <= "
                    "this x the step phase's peak window p99")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU run for CI (overrides rate/duration)")
    return ap.parse_args()


def _save_model(tmpdir: str):
    """LeNet-sized MLP (784→128→10) saved as an inference model."""
    import numpy as np

    import paddle_tpu as pt

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = pt.layers.data(name="x", shape=[784], dtype="float32")
        h = pt.layers.fc(input=x, size=128, act="relu")
        pred = pt.layers.fc(input=h, size=10, act="softmax")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    pt.io.save_inference_model(tmpdir, ["x"], [pred], exe,
                               main_program=main)
    return np.random.RandomState(0).rand(64, 784).astype("float32")


def _percentile(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1))))
    return xs[i]


def run_bench(args) -> int:
    import random
    import urllib.error
    import urllib.request

    import jax

    from paddle_tpu.serving import ServingConfig, Server

    tmpdir = tempfile.mkdtemp(prefix="serve_bench_")
    probe = _save_model(tmpdir)
    cfg = ServingConfig(
        tmpdir, max_batch=args.max_batch, max_queue=args.max_queue,
        max_wait_ms=args.max_wait_ms, timeout_s=args.timeout_s)
    server = Server(cfg)
    port = server.start(0)
    url = f"http://127.0.0.1:{port}/v1/predict"

    rng = random.Random(args.seed)
    n_requests = max(1, int(args.rate * args.duration))
    arrivals, t = [], 0.0
    for _ in range(n_requests):
        t += rng.expovariate(args.rate)
        arrivals.append(t)

    lock = threading.Lock()
    oks, rejects, timeouts, errors = [], 0, 0, 0
    body = json.dumps(
        {"feeds": {"x": probe[:args.batch].tolist()}}).encode()

    def fire():
        nonlocal rejects, timeouts, errors
        t0 = time.perf_counter()
        try:
            req = urllib.request.Request(
                url, data=body, headers={"Content-Type":
                                         "application/json"})
            with urllib.request.urlopen(req, timeout=args.timeout_s + 5):
                pass
            dt = (time.perf_counter() - t0) * 1000
            with lock:
                oks.append(dt)
        except urllib.error.HTTPError as e:
            with lock:
                if e.code == 503:
                    rejects += 1
                elif e.code == 504:
                    timeouts += 1
                else:
                    errors += 1
        except Exception:
            with lock:
                errors += 1

    # bound in-flight senders: unbounded per-request threads would
    # distort the latencies being measured (thread-stack/scheduler
    # pressure) and can hit RLIMIT under overload. At the cap the
    # generator degrades toward closed-loop — visible as completed <
    # requests in the report rather than a silent distortion.
    cap = threading.Semaphore(max(64, 4 * args.max_queue))

    def fire_capped():
        try:
            fire()
        finally:
            cap.release()

    threads = []
    start = time.perf_counter()
    for at in arrivals:
        delay = at - (time.perf_counter() - start)
        if delay > 0:
            time.sleep(delay)
        cap.acquire()
        th = threading.Thread(target=fire_capped, daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=args.timeout_s + 10)
    wall = time.perf_counter() - start
    server.stop()

    done = len(oks) + rejects + timeouts + errors
    detail = {
        "rate_offered_rps": args.rate, "duration_s": args.duration,
        "requests": n_requests, "completed": done, "ok": len(oks),
        "rejected": rejects, "timeout": timeouts, "error": errors,
        "rows_per_request": args.batch, "max_batch": args.max_batch,
        "max_wait_ms": args.max_wait_ms, "max_queue": args.max_queue,
        "platform": jax.devices()[0].platform, "smoke": bool(args.smoke),
    }
    for metric, value, unit in (
            ("serving_p50_latency_ms", _percentile(oks, 50), "ms"),
            ("serving_p99_latency_ms", _percentile(oks, 99), "ms"),
            ("serving_throughput_rps",
             round(len(oks) * args.batch / wall, 3) if wall > 0 else 0,
             "req_rows/s"),
            ("serving_reject_rate",
             round(rejects / max(1, done), 4), "fraction")):
        print(json.dumps({
            "metric": metric,
            "value": round(value, 3) if isinstance(value, float) else value,
            "unit": unit, "detail": detail}), flush=True)
    return 0 if (len(oks) > 0 and errors == 0) else 1


# ---------------------------------------------------------------------------
# Token-streaming mode (ISSUE 12)
# ---------------------------------------------------------------------------

# max_new_tokens cycles through these per arrival: the length variance
# is what the static drain-between-batches baseline pays for (its batch
# holds every slot until the LONGEST member finishes, ~28% slot
# utilization at this mix), while continuous batching backfills the
# freed slots from the queue
_GEN_LENGTHS = (2, 2, 4, 64)


def _build_decode_engine(static: bool, slots, buckets, seed: int = 0):
    import jax

    from paddle_tpu.models import gpt
    from paddle_tpu.serving.decode import DecodeConfig, DecodeEngine

    cfg = gpt.GPTConfig.tiny()
    params, _ = gpt.init(jax.random.key(seed), cfg)
    max_len = max(buckets) + max(_GEN_LENGTHS) + 8
    blocks_per_seq = -(-max_len // 8)
    dc = DecodeConfig(
        block_size=8,
        num_blocks=1 + max(slots) * blocks_per_seq + 4,
        decode_slots=slots, prefill_buckets=buckets, max_len=max_len,
        max_queue=4096,  # A/B fairness: both phases must accept all
        precision="bf16", static_batching=static)
    return DecodeEngine(params, cfg, dc), cfg


def _token_phase(label: str, static: bool, args, slots, buckets,
                 arrivals, prompts):
    """One load phase over HTTP: boot engine+server, fire the arrival
    schedule, stream every reply, return the aggregate stats."""
    import threading
    import urllib.error
    import urllib.request

    from paddle_tpu.serving import ServingConfig, Server

    eng, _ = _build_decode_engine(static, slots, buckets)
    eng.warmup()
    server = Server(ServingConfig(warmup=False), decode=eng)
    port = server.start(0)
    url = f"http://127.0.0.1:{port}/v1/generate"

    lock = threading.Lock()
    stats = {"ttft": [], "gaps": [], "e2e": [], "tokens": 0, "ok": 0,
             "rejected": 0, "error": 0}

    def fire(idx):
        """One non-streamed generation. The load phases deliberately
        use stream=false: N concurrent in-process chunked readers
        throttle the scheduler thread through the GIL and flatten the
        A/B into a client artifact (engine-direct control: 3.5x at the
        same schedule the streamed client measured at 1.2x). TTFT
        comes back in-band from the server (submit-to-first-token at
        the engine), e2e is client wall; the streamed path itself is
        exercised by the sequential probes below."""
        import json as _json

        ids, max_new = prompts[idx % len(prompts)], \
            _GEN_LENGTHS[idx % len(_GEN_LENGTHS)]
        body = _json.dumps({"ids": ids, "max_new_tokens": max_new,
                            "stream": False}).encode()
        t0 = time.perf_counter()
        try:
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req,
                                        timeout=args.timeout_s) as r:
                rec = _json.loads(r.read())
            e2e = time.perf_counter() - t0
            with lock:
                stats["ok"] += 1
                stats["tokens"] += len(rec.get("tokens") or [])
                stats["e2e"].append(e2e)
                if rec.get("ttft_ms") is not None:
                    stats["ttft"].append(rec["ttft_ms"] / 1000.0)
        except urllib.error.HTTPError as e:
            with lock:
                stats["rejected" if e.code == 503 else "error"] += 1
        except Exception:
            with lock:
                stats["error"] += 1

    def stream_probe():
        """Sequential chunked-stream request: validates the streaming
        frontend and measures unloaded inter-token gaps."""
        import json as _json

        body = _json.dumps({"ids": prompts[0],
                            "max_new_tokens": 16}).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        last = None
        n = 0
        with urllib.request.urlopen(req, timeout=args.timeout_s) as r:
            while True:
                ln = r.readline()
                if not ln:
                    break
                rec = _json.loads(ln)
                now = time.perf_counter()
                if "token" in rec:
                    n += 1
                    if last is not None:
                        stats["gaps"].append(now - last)
                    last = now
                elif rec.get("done") and rec.get("error"):
                    stats["error"] += 1
        if n == 0:
            stats["error"] += 1

    cap = threading.Semaphore(256)

    def fire_capped(i):
        try:
            fire(i)
        finally:
            cap.release()

    threads = []
    start = time.perf_counter()
    for i, at in enumerate(arrivals):
        delay = at - (time.perf_counter() - start)
        if delay > 0:
            time.sleep(delay)
        cap.acquire()
        th = threading.Thread(target=fire_capped, args=(i,), daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=args.timeout_s + 60)
    wall = time.perf_counter() - start
    for _ in range(3):  # outside the timed window
        try:
            stream_probe()
        except Exception:
            # a flaky probe must not throw away the whole measured A/B
            stats["error"] += 1
    status = server.status()
    server.stop()
    return {
        "label": label, "wall_s": round(wall, 3),
        "tokens_per_sec": round(stats["tokens"] / wall, 2) if wall else 0,
        "tokens": stats["tokens"], "ok": stats["ok"],
        "rejected": stats["rejected"], "error": stats["error"],
        "ttft_p50_ms": _ms(_percentile(stats["ttft"], 50)),
        "ttft_p99_ms": _ms(_percentile(stats["ttft"], 99)),
        "token_gap_p50_ms": _ms(_percentile(stats["gaps"], 50)),
        "token_gap_p99_ms": _ms(_percentile(stats["gaps"], 99)),
        "e2e_p50_ms": _ms(_percentile(stats["e2e"], 50)),
        "e2e_p99_ms": _ms(_percentile(stats["e2e"], 99)),
        "decode_status": {k: status.get("decode", {}).get(k)
                          for k in ("requests", "kv", "phase_grid")},
    }


def _ms(v):
    return round(v * 1000, 3) if v is not None else None


def _compile_counts():
    from paddle_tpu import observability

    snap = observability.snapshot()
    comp = snap.get("paddle_tpu_compile_seconds") or {"series": []}
    out = {}
    for s in comp["series"]:
        k = s["labels"].get("kind", "?")
        out[k] = out.get(k, 0) + s["count"]
    return out


def _grid_replay(eng, slots, buckets):
    """Deterministic canonical generation touching every prefill
    bucket (sequential) plus a full-slot burst: the token sequences are
    composition-independent (row-isolated decode math), so cold and
    warm engines must agree bit-for-bit."""
    outs = {}
    for b in buckets:
        plen = max(1, b // 2)
        outs[f"bucket_{b}"] = eng.submit(
            [1 + (i % 64) for i in range(plen)],
            max_new_tokens=4).result(timeout_s=300)
    hs = [eng.submit([3 + i, 5 + i], max_new_tokens=4)
          for i in range(max(slots))]
    outs["burst"] = [h.result(timeout_s=300) for h in hs]
    return outs


def run_token_bench(args) -> int:
    import jax

    platform = jax.devices()[0].platform
    slots = tuple(sorted({int(s) for s in args.slots.split(",")}))
    buckets = tuple(sorted({int(b) for b in
                            args.prefill_buckets.split(",")}))

    import random

    rng = random.Random(args.seed)
    n_requests = max(8, int(args.rate * args.duration))
    # identical arrival schedule and prompt pool for both phases
    arrivals, t = [], 0.0
    for _ in range(n_requests):
        t += rng.expovariate(args.rate)
        arrivals.append(t)
    prompts = [[1 + rng.randrange(60)
                for _ in range(3 + (i % (min(buckets) - 2)))]
               for i in range(16)]

    # best-of-2 per phase, interleaved: a noisy-neighbor CPU must not
    # decide the speedup gate (same discipline as bench_pipeline)
    cont = max((_token_phase("continuous", False, args, slots, buckets,
                             arrivals, prompts) for _ in range(2)),
               key=lambda r: r["tokens_per_sec"])
    stat = max((_token_phase("static", True, args, slots, buckets,
                             arrivals, prompts) for _ in range(2)),
               key=lambda r: r["tokens_per_sec"])
    speedup = cont["tokens_per_sec"] / stat["tokens_per_sec"] \
        if stat["tokens_per_sec"] else 0.0
    p99_ok = (cont["e2e_p99_ms"] is not None
              and stat["e2e_p99_ms"] is not None
              and cont["e2e_p99_ms"] <= stat["e2e_p99_ms"] * 1.05)

    # -- warmstart grid replay: zero fresh compiles, bit-identical ----
    cold_eng, _ = _build_decode_engine(False, slots, buckets)
    cold_eng.warmup()
    cold_tokens = _grid_replay(cold_eng, slots, buckets)
    if args.warmstart:
        art = args.warmstart
    else:
        art = os.path.join(tempfile.mkdtemp(prefix="serve_bench_"),
                           "decode.warmstart")
        cold_eng.export_warmstart(art)
    cold_eng.stop()
    before = _compile_counts()
    warm_eng, _ = _build_decode_engine(False, slots, buckets)
    adopted = warm_eng.load_warmstart(art)
    ready = warm_eng.warmup()
    warm_tokens = _grid_replay(warm_eng, slots, buckets)
    warm_eng.stop()
    after = _compile_counts()
    fresh = sum(after.get(k, 0) - before.get(k, 0)
                for k in ("prefill", "decode"))
    bit_identical = warm_tokens == cold_tokens

    from paddle_tpu.observability import memwatch as _memwatch
    from paddle_tpu.observability import perfwatch as _perfwatch

    detail_base = {
        "platform": platform, "smoke": bool(args.smoke),
        "rate_offered_rps": args.rate, "duration_s": args.duration,
        "requests": n_requests, "slots": list(slots),
        "prefill_buckets": list(buckets), "gen_lengths":
        list(_GEN_LENGTHS), "precision": "bf16",
        # live-attribution view of the same run: chip-normalized decode
        # MFU from retained cost_analysis FLOPs, plus the HBM
        # high-watermark the KV pools + params drove
        "mfu": round(_perfwatch.mfu("decode"), 6),
        "tokens_per_sec_per_chip_live":
            round(_perfwatch.tokens_per_sec_per_chip("decode"), 2),
        "hbm_peak_bytes": int(_memwatch.watermark_bytes()),
    }
    for metric, value, unit, detail in (
            ("decode_tokens_per_sec_continuous",
             cont["tokens_per_sec"], "tokens/s/chip",
             dict(detail_base, **cont)),
            ("decode_tokens_per_sec_static",
             stat["tokens_per_sec"], "tokens/s/chip",
             dict(detail_base, **stat)),
            ("decode_continuous_speedup", round(speedup, 3), "x",
             dict(detail_base, equal_p99_ok=p99_ok,
                  e2e_p99_ms_continuous=cont["e2e_p99_ms"],
                  e2e_p99_ms_static=stat["e2e_p99_ms"],
                  acceptance=">=2x tokens/s at equal-or-better p99")),
            ("decode_warm_replay_fresh_compiles", fresh, "count",
             dict(detail_base, adopted=adopted, phases_ready=ready,
                  bit_identical=bit_identical, artifact=art))):
        print(json.dumps({"metric": metric, "value": value,
                          "unit": unit, "detail": detail}), flush=True)
    ok = (cont["error"] == 0 and stat["error"] == 0
          and cont["tokens"] > 0 and speedup >= 2.0 and p99_ok
          and fresh == 0 and bit_identical)
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# Prefix-share mode (ISSUE 18): KV reuse A/B
# ---------------------------------------------------------------------------


def _prefix_phase(label, engine_kw, draft, prompts, max_new, repeats):
    """Engine-direct phase: submit every prompt `repeats` times in
    waves (wave 1 is the cold population pass; later waves hit the
    prefix cache when it is on) and collect per-request TTFT + the
    emitted streams."""
    import jax

    from paddle_tpu.models import gpt
    from paddle_tpu.serving.decode import DecodeConfig, DecodeEngine

    cfg = gpt.GPTConfig.tiny()
    params, _ = gpt.init(jax.random.key(0), cfg)
    dargs = (params, cfg) if draft else None
    eng = DecodeEngine(params, cfg, DecodeConfig(**engine_kw),
                       draft=dargs)
    eng.warmup()
    streams, ttft_warm, ttft_all = [], [], []
    t0 = time.perf_counter()
    tokens = 0
    try:
        for wave in range(repeats):
            hs = [eng.submit(p, max_new_tokens=max_new)
                  for p in prompts]
            for h in hs:
                toks = h.result(timeout_s=300)
                streams.append(toks)
                tokens += len(toks)
                t = h.info["ttft_s"]
                ttft_all.append(t)
                if wave > 0:
                    ttft_warm.append(t)
        wall = time.perf_counter() - t0
        status = eng.status()
    finally:
        eng.stop()
    return {
        "label": label,
        "wall_s": round(wall, 3),
        "tokens": tokens,
        "tokens_per_sec": round(tokens / wall, 2) if wall else 0,
        "ttft_p50_ms": _ms(_percentile(ttft_all, 50)),
        "ttft_p99_ms": _ms(_percentile(ttft_all, 99)),
        "ttft_warm_p50_ms": _ms(_percentile(ttft_warm, 50)),
        "ttft_warm_p99_ms": _ms(_percentile(ttft_warm, 99)),
        "streams": streams,
        "kv": {k: v for k, v in status["kv"].items()
               if "prefix" in k or "cached" in k or "reuse" in k
               or "cow" in k or "evict" in k},
        "kv_reuse": status.get("kv_reuse"),
    }


def run_prefix_bench(args) -> int:
    """Shared-system-prompt A/B (SERVING.md §KV reuse): the same
    prompt set — one long shared prefix + short unique suffixes,
    submitted in repeated waves — through (a) the plain continuous
    engine and (b) the KV-reuse engine (chunked prefill + prefix cache
    + self-draft speculation). Gates: bit-identical streams, warm-wave
    TTFT p50 improvement on prefix hits, accept rate ~1.0 for the
    self-draft, and prefix-cache hits > 0."""
    import random

    import jax

    platform = jax.devices()[0].platform
    rng = random.Random(args.seed)
    if args.smoke:
        shared_len, n_suffix, max_new, repeats = 24, 3, 6, 3
    else:
        shared_len, n_suffix, max_new, repeats = 48, 4, 16, 4
    block_size = 8
    max_len = shared_len + 16 + max_new + 8
    shared = [1 + rng.randrange(60) for _ in range(shared_len)]
    prompts = [shared + [1 + rng.randrange(60)
                         for _ in range(3 + i)]
               for i in range(n_suffix)]
    plen_max = max(len(p) for p in prompts)
    bucket = 1
    while bucket < plen_max:
        bucket *= 2
    blocks_per_seq = -(-max_len // block_size)
    base_kw = dict(block_size=block_size,
                   num_blocks=1 + 4 * blocks_per_seq + 4,
                   decode_slots=(4,), max_len=max_len,
                   max_queue=4096, precision="f32")

    plain = _prefix_phase(
        "plain", dict(base_kw, prefill_buckets=(bucket,)), False,
        prompts, max_new, repeats)
    reuse = _prefix_phase(
        "kv_reuse", dict(base_kw, prefill_chunk=block_size,
                         prefix_cache=True, spec_k=2), True,
        prompts, max_new, repeats)

    bit_identical = plain.pop("streams") == reuse.pop("streams")
    hits = int(reuse["kv"].get("prefix_hits_total") or 0)
    accept = (reuse["kv_reuse"] or {}).get("spec_accept_rate")
    ttft_gain = None
    if plain["ttft_warm_p50_ms"] and reuse["ttft_warm_p50_ms"]:
        ttft_gain = round(plain["ttft_warm_p50_ms"] /
                          reuse["ttft_warm_p50_ms"], 3)

    detail = {
        "platform": platform, "smoke": bool(args.smoke),
        "shared_prefix_tokens": shared_len, "suffixes": n_suffix,
        "waves": repeats, "max_new": max_new,
        "block_size": block_size,
        "bit_identical": bit_identical,
        "prefix_hits": hits,
        "spec_accept_rate": accept,
        "plain": plain, "kv_reuse": reuse,
        "acceptance": "bit-identical streams; warm-wave TTFT p50 "
                      "improves on prefix hits; accept rate ~1 for "
                      "the self-draft",
    }
    for metric, value, unit in (
            ("decode_prefix_share_ttft_speedup", ttft_gain, "x"),
            ("decode_prefix_share_hits", hits, "blocks"),
            ("decode_spec_accept_rate", accept, "fraction")):
        print(json.dumps({"metric": metric, "value": value,
                          "unit": unit, "detail": detail}), flush=True)
        detail = {"see": "decode_prefix_share_ttft_speedup"}
    ok = (bit_identical and hits > 0
          and accept is not None and accept >= 0.99)
    if not args.smoke:
        # the latency claim is a real-hardware gate; the CPU smoke run
        # validates correctness + the report schema, not timings
        ok = ok and ttft_gain is not None and ttft_gain > 1.0
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# Multi-tenant chaos mode (SERVING.md §Multi-tenancy)
# ---------------------------------------------------------------------------


def run_tenants_bench(args) -> int:
    """The two multi-tenant acceptance gates:

      1. **noisy neighbor** — a bronze-tier closed-loop flood
         saturates a deliberately small queue while a gold-tier
         trickle keeps measuring. Degradation must be tier-scoped:
         every shed lands on bronze (checked per-response AND against
         the paddle_tpu_serving_sheds_total{tier} counter), gold sees
         ZERO failures, and gold p99 stays within
         --tenant-p99-factor x its unloaded baseline (+100ms CI
         allowance).
      2. **hot-swap under load** — with both tenants still firing,
         the serving program's warmstart artifact is published into a
         ModelRegistry and the watcher hot-swaps it in: zero failed
         requests across the swap window and zero fresh XLA compiles
         on the adopting slot (warmstart adoption, PR 6 contract).
    """
    import random
    import urllib.error
    import urllib.request

    import jax

    from paddle_tpu.serving import Server, ServingConfig
    from paddle_tpu.serving.registry import ModelRegistry

    tmpdir = tempfile.mkdtemp(prefix="serve_bench_mt_")
    probe = _save_model(tmpdir)
    qos = {"tiers": ["gold", "bronze"], "default_tier": "bronze",
           "tenants": {"gold-client": {"tier": "gold", "weight": 4}}}
    qsize = max(8, args.max_queue // 8)
    cfg = ServingConfig(
        tmpdir, max_batch=args.max_batch,
        # small queue: the flood must actually hit the shed path
        max_queue=qsize,
        max_wait_ms=args.max_wait_ms, timeout_s=args.timeout_s,
        qos=qos, model_id="bench")
    server = Server(cfg)
    port = server.start(0)
    url = f"http://127.0.0.1:{port}/v1/predict"
    rows = probe[:args.batch].tolist()

    def fire(tenant):
        """One predict; returns (outcome, latency_ms, shed_tier)."""
        t0 = time.perf_counter()
        body = json.dumps({"feeds": {"x": rows},
                           "tenant": tenant}).encode()
        try:
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=args.timeout_s + 5):
                pass
            return "ok", (time.perf_counter() - t0) * 1000, None
        except urllib.error.HTTPError as e:
            try:
                info = json.loads(e.read())
            except ValueError:
                info = {}
            if e.code == 503 and isinstance(info, dict) \
                    and info.get("shed"):
                return "shed", None, str(info["shed"])
            if e.code == 503:
                return "rejected", None, None
            return ("timeout" if e.code == 504 else "error"), None, None
        except Exception:
            return "error", None, None

    def shed_counts():
        from paddle_tpu import observability

        snap = observability.snapshot()
        out = {}
        for s in (snap.get("paddle_tpu_serving_sheds_total")
                  or {"series": []})["series"]:
            tier = s["labels"].get("tier", "?")
            out[tier] = out.get(tier, 0) + int(s["value"])
        return out

    try:
        # ---- unloaded gold baseline ---------------------------------
        base_lat = []
        for _ in range(20):
            oc, ms, _tier = fire("gold-client")
            if oc == "ok":
                base_lat.append(ms)
        p99_base = _percentile(base_lat, 99)

        # ---- gate 1: bronze flood vs gold trickle -------------------
        sheds_t0 = shed_counts()
        stop = threading.Event()
        flood_stats = {"ok": 0, "shed": 0, "rejected": 0, "error": 0,
                       "timeout": 0}
        flood_shed_tiers = set()
        flood_lock = threading.Lock()

        flood_lat = []

        def flood():
            while not stop.is_set():
                oc, ms, tier = fire("bronze-flood")
                with flood_lock:
                    flood_stats[oc] += 1
                    if oc == "ok":
                        flood_lat.append(ms)
                    if tier is not None:
                        flood_shed_tiers.add(tier)

        # closed-loop senders bound the in-flight count at the thread
        # count, so the flood must outnumber queue + one active batch
        # or the queue never fills and nothing sheds
        n_flood = max(args.flood_threads, qsize + args.max_batch + 4)
        flooders = [threading.Thread(target=flood, daemon=True)
                    for _ in range(n_flood)]
        for th in flooders:
            th.start()
        gold_lat, gold_fails, gold_shed_tiers = [], [], set()
        gold_lock = threading.Lock()
        gold_rate = max(5.0, args.rate / 4.0)
        t_end = time.perf_counter() + args.duration

        # several open-loop gold probes: one slow reply must not
        # serialize the sampler down to a single latency point (the
        # p99 of one contended sample is pure machine noise)
        def gold_trickle(seed):
            tr = random.Random(seed)
            while time.perf_counter() < t_end:
                oc, ms, tier = fire("gold-client")
                with gold_lock:
                    if oc == "ok":
                        gold_lat.append(ms)
                    else:
                        gold_fails.append(oc)
                        if tier is not None:
                            gold_shed_tiers.add(tier)
                time.sleep(tr.expovariate(gold_rate))

        golds = [threading.Thread(target=gold_trickle,
                                  args=(args.seed + i,), daemon=True)
                 for i in range(3)]
        for th in golds:
            th.start()
        for th in golds:
            th.join(timeout=args.duration + args.timeout_s + 10)
        stop.set()
        for th in flooders:
            th.join(timeout=args.timeout_s + 10)
        gold_fail = len(gold_fails)
        sheds_t1 = shed_counts()
        shed_delta = {t: sheds_t1.get(t, 0) - sheds_t0.get(t, 0)
                      for t in set(sheds_t0) | set(sheds_t1)}
        p99_flood = _percentile(gold_lat, 99)
        p99_bronze = _percentile(flood_lat, 99)
        bronze_sheds = shed_delta.get("bronze", 0)
        p99_bound = None
        if p99_base is not None:
            p99_bound = args.tenant_p99_factor * p99_base + 100.0
        # primary gate: gold p99 within factor x unloaded baseline.
        # Relative escape for badly contended CI hosts (everything is
        # slow, including the unloaded baseline's scale): the tier-
        # isolation claim still holds when gold's p99 is far below the
        # flooding tier's — bronze absorbs the degradation.
        abs_ok = (p99_flood is not None and p99_bound is not None
                  and p99_flood <= p99_bound)
        rel_ok = (p99_flood is not None and p99_bronze is not None
                  and p99_flood <= 0.5 * p99_bronze)
        neighbor_ok = (
            gold_fail == 0 and not gold_shed_tiers
            and bronze_sheds > 0
            and flood_shed_tiers <= {"bronze"}
            and shed_delta.get("gold", 0) == 0
            and (abs_ok or rel_ok))

        # ---- gate 2: registry hot-swap under load -------------------
        ws = os.path.join(tmpdir, "bench.warmstart")
        server._engine.export_warmstart(ws)
        registry = ModelRegistry(os.path.join(tmpdir, "registry"))
        entry = registry.publish("bench", ws, model_dir=tmpdir)
        compiles_t0 = sum(_compile_counts().values())
        stop = threading.Event()
        swap_stats = {"ok": 0, "shed": 0, "rejected": 0, "error": 0,
                      "timeout": 0}
        swap_lock = threading.Lock()

        def light_load(tenant, rate):
            lr = random.Random(hash(tenant) & 0xFFFF)
            while not stop.is_set():
                oc, _ms, _tier = fire(tenant)
                with swap_lock:
                    swap_stats[oc] += 1
                time.sleep(lr.expovariate(rate))

        loaders = [
            threading.Thread(target=light_load,
                             args=("gold-client", gold_rate),
                             daemon=True),
            threading.Thread(target=light_load,
                             args=("bronze-steady", gold_rate),
                             daemon=True)]
        for th in loaders:
            th.start()
        server.attach_registry(registry, poll_s=0.1)
        adopted, deadline = None, time.perf_counter() + 60.0
        while time.perf_counter() < deadline:
            row = next((r for r in server.models()
                        if r["id"] == "bench"), None)
            if row is not None and row.get("version") \
                    == entry["version"]:
                adopted = row
                break
            time.sleep(0.05)
        # keep load flowing briefly past the swap so post-swap
        # requests land in the window too
        time.sleep(0.3)
        stop.set()
        for th in loaders:
            th.join(timeout=args.timeout_s + 10)
        compiles_t1 = sum(_compile_counts().values())
        swap_failed = (swap_stats["error"] + swap_stats["rejected"]
                       + swap_stats["shed"] + swap_stats["timeout"])
        swap_ok = (
            adopted is not None
            and adopted.get("warmstart_adopted", 0) > 0
            and swap_failed == 0 and swap_stats["ok"] > 0
            and compiles_t1 == compiles_t0)
    finally:
        server.stop()

    detail = {
        "platform": jax.devices()[0].platform, "smoke": bool(args.smoke),
        "qos": qos, "flood_threads": n_flood,
        "duration_s": args.duration,
        "gold": {"ok": len(gold_lat), "failed": gold_fail,
                 "p99_base_ms": p99_base, "p99_flood_ms": p99_flood,
                 "p99_bound_ms": round(p99_bound, 3)
                 if p99_bound is not None else None,
                 "p99_bronze_ms": p99_bronze,
                 "abs_ok": abs_ok, "rel_ok": rel_ok},
        "flood": dict(flood_stats),
        "shed_delta": shed_delta,
        "swap": {"requests": dict(swap_stats),
                 "failed": swap_failed,
                 "adopted_version": adopted.get("version")
                 if adopted else None,
                 "warmstart_adopted": adopted.get("warmstart_adopted")
                 if adopted else None,
                 "fresh_compiles": compiles_t1 - compiles_t0},
    }
    for metric, value, unit, extra in (
            ("tenant_gold_p99_ms", p99_flood, "ms",
             dict(gate_ok=neighbor_ok,
                  acceptance="bronze flood sheds bronze ONLY, zero "
                             "gold failures, gold p99 <= "
                             "factor x baseline + 100ms (or well "
                             "under the flooding tier's p99)")),
            ("tenant_bronze_sheds", bronze_sheds, "count",
             dict(gate_ok=neighbor_ok)),
            ("hot_swap_failed_requests", swap_failed, "count",
             dict(gate_ok=swap_ok,
                  acceptance="registry hot-swap under load: zero "
                             "failed requests, zero fresh compiles, "
                             "warmstart adopted"))):
        print(json.dumps({"metric": metric,
                          "value": round(value, 3)
                          if isinstance(value, float) else value,
                          "unit": unit,
                          "detail": {**detail, **extra}}), flush=True)
    return 0 if (neighbor_ok and swap_ok) else 1


# ---------------------------------------------------------------------------
# Fleet chaos mode (ISSUE 14)
# ---------------------------------------------------------------------------


def _fleet_phase(url: str, rate: float, duration: float, body: bytes,
                 timeout_s: float, on_tick=None):
    """Open-loop Poisson load against the router; returns per-request
    records [(arrival_s, latency_ms, outcome)] with outcome in
    ok|rejected|timeout|error. `on_tick(elapsed_s)` runs on the arrival
    thread (the chaos hook: kill a replica at a chosen moment)."""
    import random
    import urllib.error
    import urllib.request

    rng = random.Random(1234)
    n_requests = max(4, int(rate * duration))
    arrivals, t = [], 0.0
    for _ in range(n_requests):
        t += rng.expovariate(rate)
        arrivals.append(t)

    lock = threading.Lock()
    records = []

    def fire(at):
        t0 = time.perf_counter()
        try:
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout_s + 5):
                pass
            out = "ok"
        except urllib.error.HTTPError as e:
            out = {503: "rejected", 504: "timeout"}.get(e.code, "error")
        except Exception:
            out = "error"
        with lock:
            records.append((at, (time.perf_counter() - t0) * 1000, out))

    cap = threading.Semaphore(256)

    def fire_capped(at):
        try:
            fire(at)
        finally:
            cap.release()

    threads = []
    start = time.perf_counter()
    for at in arrivals:
        delay = at - (time.perf_counter() - start)
        if delay > 0:
            time.sleep(delay)
        if on_tick is not None:
            on_tick(time.perf_counter() - start)
        cap.acquire()
        th = threading.Thread(target=fire_capped, args=(at,),
                              daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=timeout_s + 30)
    # a thread still wedged past its join timeout produced no record;
    # the zero-failed-requests gate must count it as a failure, not
    # silently shrink the denominator
    with lock:
        lost = len(threads) - len(records)
        for _ in range(lost):
            records.append((float("nan"), float("nan"), "error"))
        return list(records)


def _outcomes(records):
    out = {"ok": 0, "rejected": 0, "timeout": 0, "error": 0}
    for _, _, oc in records:
        out[oc] += 1
    return out


def _phase_p99s(records, tail_frac: float = 1 / 3, windows: int = 6):
    """(peak windowed p99, tail-third p99) of the ok latencies, by
    arrival time — the 'p99 recovers' gate compares the tail against
    the worst window the traffic step caused."""
    oks = sorted((at, ms) for (at, ms, oc) in records if oc == "ok")
    if not oks:
        return None, None
    span = max(at for at, _ in oks) or 1e-9
    per_win = [[] for _ in range(windows)]
    for at, ms in oks:
        per_win[min(windows - 1, int(windows * at / span))].append(ms)
    win_p99 = [_percentile(w, 99) for w in per_win if w]
    tail = [ms for at, ms in oks if at >= span * (1 - tail_frac)]
    return (max(win_p99) if win_p99 else None,
            _percentile(tail, 99))


def _fleet_events(kind_action):
    from paddle_tpu.observability import events as oe

    return [e for e in oe.recent(4096, kind="fleet")
            if e.get("action") == kind_action]


def run_fleet_bench(args) -> int:
    """The three ISSUE 14 acceptance gates — see module docstring."""
    import urllib.request

    import jax

    from paddle_tpu.distributed.launch_serve import (ReplicaSpec,
                                                     ReplicaSupervisor)
    from paddle_tpu.serving import Engine, ServingConfig
    from paddle_tpu.serving.autoscale import Autoscaler
    from paddle_tpu.serving.router import Router, RouterServer

    platform = jax.devices()[0].platform
    tmpdir = tempfile.mkdtemp(prefix="serve_fleet_")
    model_dir = os.path.join(tmpdir, "model")
    os.makedirs(model_dir, exist_ok=True)
    probe = _save_model(model_dir)

    # gate 4 (ISSUE 15): one shared trace dir for the whole fleet —
    # replica subprocesses inherit it via the environment. Sampling
    # stays OFF through gates 1-3 (the router is the trace head; with
    # no traceparent inbound and rate 0, replicas never sample either).
    trace_dir = os.path.join(tmpdir, "trace")
    os.environ["PADDLE_TPU_TRACE_DIR"] = trace_dir
    os.environ.pop("PADDLE_TPU_TRACE_SAMPLE", None)

    # bake the warmstart artifact every replica (incl. scale-outs)
    # boots from — scale-out must be seconds, not an XLA warmup
    art = os.path.join(tmpdir, "fleet.warmstart")
    bake = Engine(ServingConfig(model_dir, max_batch=args.max_batch,
                                use_tpu=False))
    bake.warmup()
    bake.export_warmstart(art)

    rdzv = os.path.join(tmpdir, "rdzv")
    spec = ReplicaSpec(model_dir, warmstart=art, cpu=True,
                       max_batch=args.max_batch,
                       max_queue=args.max_queue,
                       max_wait_ms=args.max_wait_ms,
                       timeout_s=args.timeout_s)
    sup = ReplicaSupervisor(spec, rdzv, replicas=args.replicas,
                            backoff_s=0.3,
                            log_dir=os.path.join(tmpdir, "logs"))
    router = Router(rdzv_dir=rdzv, poll_interval_s=0.1,
                    request_timeout_s=args.timeout_s)
    front = RouterServer(router)
    sup.start()
    port = front.start(0)
    url = f"http://127.0.0.1:{port}/v1/predict"
    body = json.dumps(
        {"feeds": {"x": probe[:args.batch].tolist()}}).encode()

    def wait_healthy(n, timeout=180.0):
        t0 = time.time()
        while len(router.healthy_endpoints()) < n:
            if time.time() - t0 > timeout:
                raise RuntimeError(
                    f"fleet never reached {n} healthy replicas "
                    f"(status: {router.status()})")
            time.sleep(0.1)
        return time.time() - t0

    rc = 0
    scaler = None
    try:
        boot_s = wait_healthy(args.replicas)

        # ---- gate 1: SIGKILL one replica under open-loop load -------
        kill_state = {"done": False, "endpoint": None}

        def chaos(elapsed):
            if not kill_state["done"] and elapsed >= args.duration * 0.4:
                kill_state["done"] = True
                live = [s for s in sup.slot_info() if s["alive"]]
                kill_state["endpoint"] = sup.kill_slot(live[0]["slot"])

        rec1 = _fleet_phase(url, args.rate, args.duration, body,
                            args.timeout_s, on_tick=chaos)
        oc1 = _outcomes(rec1)
        st1 = router.status()
        ejections = len(_fleet_events("eject"))
        retried = sum(st1["retries"].values())
        failover_ok = (oc1["error"] == 0 and oc1["timeout"] == 0
                       and oc1["rejected"] == 0 and oc1["ok"] > 0
                       and kill_state["done"] and ejections >= 1)
        respawn_s = wait_healthy(args.replicas)  # supervisor heals it

        # ---- gate 2: 2x traffic step with the autoscaler armed ------
        scaler = Autoscaler(
            router, sup, min_replicas=args.replicas,
            max_replicas=args.fleet_max,
            high_load=1.0, low_load=0.2,
            interval_s=0.1, breach_polls=2, clear_polls=50,
            out_cooldown_s=2.0, in_cooldown_s=3600.0)
        known = set(sup.endpoints())
        scaler.start()
        rec2 = _fleet_phase(url, args.rate * 2, args.duration * 2,
                            body, args.timeout_s)
        scaler.stop()
        oc2 = _outcomes(rec2)
        scale_outs = scaler.status()["actions"]["out"]
        new_eps = sorted(set(sup.endpoints()) - known)
        adopted = None
        for ep in new_eps:
            try:
                with urllib.request.urlopen(
                        f"http://{ep}/v1/status", timeout=5) as r:
                    adopted = json.loads(r.read()).get(
                        "warmstart_adopted")
            except Exception:
                continue
        peak_p99, tail_p99 = _phase_p99s(rec2)
        p99_recovered = (peak_p99 is not None and tail_p99 is not None
                         and tail_p99 <=
                         peak_p99 * args.p99_recover_factor)
        scaleout_ok = (scale_outs >= 1 and bool(new_eps)
                       and (adopted or 0) > 0 and p99_recovered
                       and oc2["error"] == 0)

        # ---- gate 3: graceful scale-in under an in-flight burst -----
        burst_n = 24
        results = {"ok": 0, "fail": 0}
        lock = threading.Lock()

        def burst_fire():
            import urllib.error

            try:
                req = urllib.request.Request(
                    url, data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req,
                                            timeout=args.timeout_s + 5):
                    pass
                with lock:
                    results["ok"] += 1
            except Exception:
                with lock:
                    results["fail"] += 1

        ths = [threading.Thread(target=burst_fire, daemon=True)
               for _ in range(burst_n)]
        for th in ths:
            th.start()
        drained = sup.scale_in()
        for th in ths:
            th.join(timeout=args.timeout_s + 30)
        scalein_ok = (results["fail"] == 0 and results["ok"] == burst_n
                      and drained is not None)

        # ---- gate 4: distributed tracing (ISSUE 15) -----------------
        import signal as _signal
        import subprocess

        from paddle_tpu.observability import tracing as _tracing

        def _one(url_, body_, extra_headers=None):
            req = urllib.request.Request(
                url_, data=body_,
                headers={"Content-Type": "application/json",
                         **(extra_headers or {})})
            with urllib.request.urlopen(req,
                                        timeout=args.timeout_s + 5) as r:
                return dict(r.headers), json.loads(r.read())

        ab_dur = min(args.duration, 2.0)
        os.environ["PADDLE_TPU_TRACE_SAMPLE"] = "0"
        rec_off = _fleet_phase(url, args.rate, ab_dur, body,
                               args.timeout_s)
        os.environ["PADDLE_TPU_TRACE_SAMPLE"] = "1.0"
        # the traced predict whose tree gate 4 reassembles — fired
        # BEFORE the sampled load phase, whose flood of sampled spans
        # flushes every replica's sink past this request's records
        pred_hdrs, _ = _one(url, body)
        pred_tid = pred_hdrs.get("X-Request-Id")
        rec_on = _fleet_phase(url, args.rate, ab_dur, body,
                              args.timeout_s)
        p50_off = _percentile([ms for (_, ms, oc) in rec_off
                               if oc == "ok"], 50)
        p50_on = _percentile([ms for (_, ms, oc) in rec_on
                              if oc == "ok"], 50)
        overhead = (p50_on / p50_off) if p50_off and p50_on else None
        # the <5% acceptance bar, with a small absolute floor: at CPU
        # smoke p50s of tens of ms, 5% is inside run-to-run noise
        overhead_ok = overhead is not None and \
            (overhead <= 1.05 or (p50_on - p50_off) <= 2.5)

        # ...and one traced generate through a decode replica (a
        # SEPARATE subprocess + router front, so the tree must cross
        # process boundaries: router pid != replica pid)
        gen_tid, gen_err = None, None
        dec_front = None
        proc = subprocess.Popen(
            [sys.executable, "-u", "-m", "paddle_tpu.serving.replica",
             "--decode-tiny", "0", "--cpu", "--port", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True)
        try:
            ready_line = {}

            def _read_ready():
                try:
                    ready_line["v"] = json.loads(
                        proc.stdout.readline() or "{}")
                except ValueError:
                    ready_line["v"] = {}

            reader = threading.Thread(target=_read_ready, daemon=True)
            reader.start()
            reader.join(timeout=240.0)
            ep = (ready_line.get("v") or {}).get("endpoint")
            if not ep:
                raise RuntimeError("decode replica never became ready")
            from paddle_tpu.serving.router import Router as _Router
            from paddle_tpu.serving.router import \
                RouterServer as _RouterServer

            dec_router = _Router([ep], poll_interval_s=0.1,
                                 request_timeout_s=args.timeout_s)
            dec_front = _RouterServer(dec_router)
            dport = dec_front.start(0)
            deadline = time.time() + 60
            while not dec_router.healthy_endpoints():
                if time.time() > deadline:
                    raise RuntimeError("decode replica never healthy")
                time.sleep(0.1)
            gen_body = json.dumps({"ids": [3, 1, 4, 1, 5],
                                   "max_new_tokens": 4,
                                   "stream": False}).encode()
            gen_hdrs, _ = _one(f"http://127.0.0.1:{dport}/v1/generate",
                               gen_body)
            gen_tid = gen_hdrs.get("X-Request-Id")
        except Exception as e:
            gen_err = f"{type(e).__name__}: {e}"
        finally:
            if dec_front is not None:
                dec_front.stop()
            if proc.poll() is None:
                proc.send_signal(_signal.SIGTERM)  # drain + sink flush
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    proc.kill()
            if proc.stdout is not None:
                proc.stdout.close()
        time.sleep(0.5)                 # replica handler span settle
        _tracing.flush_trace_sink()     # router-side (this process)
        trace_recs = _tracing.read_trace_dir(trace_dir)

        def _trace_view(tid):
            if not tid:
                return set(), 0, 0
            mine = [r for r in trace_recs if r.get("trace_id") == tid]
            tree = _tracing.build_trace_tree(trace_recs, tid)
            return ({r["name"] for r in mine},
                    len(tree), len({r.get("pid") for r in mine}))

        gen_names, gen_roots, gen_procs = _trace_view(gen_tid)
        pred_names, pred_roots, pred_procs = _trace_view(pred_tid)
        gen_ok = (gen_roots == 1 and gen_procs >= 2 and
                  {"router.http_generate", "router.generate",
                   "http.generate", "decode.queue_wait",
                   "decode.prefill", "decode.ttft"} <= gen_names)
        pred_ok = (pred_roots == 1 and pred_procs >= 2 and
                   {"router.predict", "router.attempt", "http.predict",
                    "serve.queue_wait", "serve.batch"} <= pred_names)
        trace_ok = gen_ok and pred_ok and overhead_ok

        # ---- gate 5: telemetry pipeline + SLO burn-rate (ISSUE 16) --
        # recorder on across router + every replica pid, a tight
        # latency SLO, one slow replica (sleep shim) breaching the fast
        # burn window -> slo_alert fires, lifts -> clears; recorder p50
        # overhead <= 1.05x like the trace gate.
        from paddle_tpu.observability import events as _oevents
        from paddle_tpu.observability import slo as _slo_mod
        from paddle_tpu.observability import timeseries as _ts_mod

        ts_dir = os.path.join(tmpdir, "ts")
        shim_file = os.path.join(tmpdir, "slow_shim")
        slo_name = "predict-latency"
        # freeze the fleet: this gate measures recorder overhead and
        # drives a deliberate latency brownout — autoscale reactions
        # would fight both
        if scaler is not None:
            scaler.stop()
        # sampling off again: this A/B isolates the RECORDER's cost
        os.environ["PADDLE_TPU_TRACE_SAMPLE"] = "0"
        ts_rec_off = _fleet_phase(url, args.rate, ab_dur, body,
                                  args.timeout_s)

        def _live_slots():
            # a retired slot may still show alive while its graceful
            # drain finishes — it is not coming back, don't count it
            return [s for s in sup.slot_info()
                    if s["alive"] and not s["retired"]]

        def _respawn_fleet(n_live):
            """Recycle every live slot so respawned replicas inherit
            the env flipped since boot (TS recording, shim arming)."""
            for s in _live_slots():
                sup.kill_slot(s["slot"])
                deadline = time.time() + 180
                while len(_live_slots()) < n_live:
                    if time.time() > deadline:
                        raise RuntimeError("slot never respawned")
                    time.sleep(0.2)
            time.sleep(1.0)   # let the rendezvous drop the dead member
            wait_healthy(n_live)

        n_live = len(_live_slots())
        os.environ["PADDLE_TPU_TS_DIR"] = ts_dir
        os.environ["PADDLE_TPU_TS_INTERVAL_S"] = "0.5"
        # arm the sleep shim for respawns too (inert until the file
        # exists); every replica recycled below records AND can be
        # slowed later by just creating shim_file
        os.environ["PADDLE_TPU_SLOW_SHIM_FILE"] = shim_file
        _respawn_fleet(n_live)
        _ts_mod.maybe_start_recorder()  # the router side of the fleet
        # settle phase (discarded): the just-respawned fleet pays cold
        # sockets / first-batch costs that are respawn artifacts, not
        # recorder overhead — don't bill them to the A/B
        _fleet_phase(url, args.rate, ab_dur, body, args.timeout_s)
        ts_rec_on = _fleet_phase(url, args.rate, ab_dur, body,
                                 args.timeout_s)
        ts_p50_off = _percentile([ms for (_, ms, oc) in ts_rec_off
                                  if oc == "ok"], 50)
        ts_p50_on = _percentile([ms for (_, ms, oc) in ts_rec_on
                                 if oc == "ok"], 50)
        ts_overhead = (ts_p50_on / ts_p50_off) \
            if ts_p50_off and ts_p50_on else None
        ts_overhead_ok = ts_overhead is not None and \
            (ts_overhead <= 1.05 or (ts_p50_on - ts_p50_off) <= 2.5)

        # a tight latency objective with bench-scale burn windows: one
        # slow replica pushes well past 1% of requests over 0.5s (burn
        # >> 14.4 on a 99% target), healthy traffic stays far under
        slo_spec_path = os.path.join(tmpdir, "slos.json")
        with open(slo_spec_path, "w") as f:  # atomic-exempt: bench-local scratch file, single writer
            json.dump({"slos": [{
                "name": slo_name, "type": "latency", "target": 0.99,
                "metric": "paddle_tpu_fleet_request_seconds",
                "threshold_s": 0.5,
                "windows": [
                    {"name": "fast", "short_s": 2.0, "long_s": 6.0,
                     "burn": 14.4},
                    {"name": "slow", "short_s": 6.0, "long_s": 18.0,
                     "burn": 6.0}]}]}, f)
        slo_engine = _slo_mod.SLOEngine(
            _slo_mod.load_spec(slo_spec_path), ts_dir)
        slo_engine.evaluate()

        def _obsdump(*cmd):
            out = subprocess.run(
                [sys.executable,
                 os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "obsdump.py")] + list(cmd),
                capture_output=True, text=True, timeout=120)
            return out.returncode, out.stdout

        # breach: the shim file makes every armed replica sleep 0.75s
        # per predict — drive load until the fast pair confirms
        with open(shim_file, "w") as f:  # atomic-exempt: chaos trigger file, presence is the signal
            f.write("0.75")
        breach_t0 = time.time()
        fired = False
        while time.time() - breach_t0 < 60.0:
            _fleet_phase(url, args.rate, 1.0, body, args.timeout_s + 5)
            slo_engine.evaluate()
            if slo_engine.state(slo_name) == "fast_burn":
                fired = True
                break
        breach_s = round(time.time() - breach_t0, 3)
        dump_rc_b, dump_out_b = _obsdump("slo", ts_dir, "--spec",
                                         slo_spec_path)
        obsdump_breach_ok = dump_rc_b == 0 and "fast_burn" in dump_out_b

        # recovery: lift the shim, keep traffic flowing until the
        # short windows drain and the alert clears
        os.unlink(shim_file)
        clear_t0 = time.time()
        cleared = False
        while fired and time.time() - clear_t0 < 60.0:
            _fleet_phase(url, args.rate, 1.0, body, args.timeout_s)
            slo_engine.evaluate()
            if slo_engine.state(slo_name) == "ok":
                cleared = True
                break
        clear_s = round(time.time() - clear_t0, 3)
        dump_rc_c, dump_out_c = _obsdump("slo", ts_dir, "--spec",
                                         slo_spec_path, "--json")
        try:
            clear_rows = json.loads(dump_out_c)
        except ValueError:
            clear_rows = []
        obsdump_clear_ok = dump_rc_c == 0 and any(
            r.get("name") == slo_name and r.get("state") == "ok"
            for r in clear_rows)

        slo_events = [e for e in _oevents.recent(4096, kind="slo_alert")
                      if e.get("slo") == slo_name]
        slo_states = [e.get("state") for e in slo_events]
        alert_ok = fired and cleared and "fast_burn" in slo_states \
            and "ok" in slo_states

        # fleet-merged dashboard: router + >= 2 replica pids recording
        top_rc, top_out = _obsdump("top", ts_dir, "--window", "30",
                                   "--json")
        try:
            top_view = json.loads(top_out)
        except ValueError:
            top_view = {}
        ts_pids = (top_view.get("pids") or [])
        top_ok = (top_rc == 0 and len(ts_pids) >= 3
                  and top_view.get("fleet", {}).get("req_per_s", 0) > 0
                  and top_view.get("fleet", {}).get("p99_ms") is not None)

        slo_ok = (alert_ok and obsdump_breach_ok and obsdump_clear_ok
                  and top_ok and ts_overhead_ok)

        detail_base = {
            "platform": platform, "smoke": bool(args.smoke),
            "rate_rps": args.rate, "duration_s": args.duration,
            "replicas": args.replicas, "fleet_max": args.fleet_max,
            "boot_s": round(boot_s, 3),
        }
        for metric, value, unit, detail in (
                ("fleet_failover_failed_requests",
                 oc1["error"] + oc1["timeout"] + oc1["rejected"],
                 "count",
                 dict(detail_base, **oc1, killed=kill_state["endpoint"],
                      ejections=ejections, retries=retried,
                      respawn_s=round(respawn_s, 3),
                      gate_ok=failover_ok,
                      acceptance="SIGKILL one replica under load -> "
                                 "zero failed client requests")),
                ("fleet_scaleout_p99_recovered",
                 int(p99_recovered), "bool",
                 dict(detail_base, **oc2, scale_outs=scale_outs,
                      new_replicas=new_eps,
                      warmstart_adopted=adopted,
                      peak_window_p99_ms=peak_p99,
                      tail_p99_ms=tail_p99,
                      p99_recover_factor=args.p99_recover_factor,
                      gate_ok=scaleout_ok,
                      acceptance="2x step -> warmstart scale-out, "
                                 "tail p99 <= factor x peak")),
                ("fleet_scalein_dropped_requests", results["fail"],
                 "count",
                 dict(detail_base, burst=burst_n, ok=results["ok"],
                      drained_endpoint=drained, gate_ok=scalein_ok,
                      acceptance="graceful drain -> zero dropped "
                                 "in-flight requests")),
                ("fleet_trace_reconstructed",
                 int(gen_ok and pred_ok), "bool",
                 dict(detail_base, trace_dir=trace_dir,
                      generate_trace_id=gen_tid,
                      generate_spans=sorted(gen_names),
                      generate_roots=gen_roots,
                      generate_processes=gen_procs,
                      generate_error=gen_err,
                      predict_trace_id=pred_tid,
                      predict_spans=sorted(pred_names),
                      predict_roots=pred_roots,
                      predict_processes=pred_procs,
                      gate_ok=gen_ok and pred_ok,
                      acceptance="one sampled generate reassembles to "
                                 "a single tree spanning router -> "
                                 "replica -> decode with queue-wait, "
                                 "phase, and TTFT spans")),
                ("fleet_trace_overhead_p50", overhead
                 if overhead is not None else -1.0, "ratio",
                 dict(detail_base, p50_off_ms=p50_off, p50_on_ms=p50_on,
                      abs_delta_ms=(p50_on - p50_off)
                      if p50_on and p50_off else None,
                      gate_ok=overhead_ok,
                      acceptance="PADDLE_TPU_TRACE_SAMPLE=1.0 predict "
                                 "p50 <= 1.05x tracing-off (or within "
                                 "2.5ms absolute)")),
                ("fleet_slo_alert_fired", int(alert_ok), "bool",
                 dict(detail_base, slo=slo_name,
                      states_seen=slo_states,
                      breach_detect_s=breach_s, clear_s=clear_s,
                      obsdump_breach_ok=obsdump_breach_ok,
                      obsdump_clear_ok=obsdump_clear_ok,
                      gate_ok=alert_ok,
                      acceptance="slow-replica shim breaches the fast "
                                 "burn pair -> slo_alert fast_burn, "
                                 "shim lifted -> clears to ok, obsdump "
                                 "slo reflects both states")),
                ("fleet_ts_recording_pids", len(ts_pids), "count",
                 dict(detail_base, ts_dir=ts_dir, pids=ts_pids,
                      fleet_req_per_s=top_view.get(
                          "fleet", {}).get("req_per_s"),
                      fleet_p99_ms=top_view.get(
                          "fleet", {}).get("p99_ms"),
                      gate_ok=top_ok,
                      acceptance="obsdump top merges the TS dir across "
                                 "router + >= 2 replica pids into one "
                                 "fleet dashboard")),
                ("fleet_ts_overhead_p50", ts_overhead
                 if ts_overhead is not None else -1.0, "ratio",
                 dict(detail_base, p50_off_ms=ts_p50_off,
                      p50_on_ms=ts_p50_on,
                      abs_delta_ms=(ts_p50_on - ts_p50_off)
                      if ts_p50_on and ts_p50_off else None,
                      gate_ok=ts_overhead_ok,
                      acceptance="PADDLE_TPU_TS_DIR recording predict "
                                 "p50 <= 1.05x recorder-off (or within "
                                 "2.5ms absolute)"))):
            print(json.dumps({"metric": metric, "value": value,
                              "unit": unit, "detail": detail}),
                  flush=True)
        rc = 0 if (failover_ok and scaleout_ok and scalein_ok
                   and trace_ok and slo_ok) else 1
    finally:
        if scaler is not None:
            scaler.stop()
        front.stop()
        sup.stop()
    return rc


def main() -> int:
    args = _build_args()
    if args.fleet:
        # the fleet is N CPU replica subprocesses (one real chip cannot
        # host N engines); the in-process warmstart bake must match the
        # replicas' backend or every boot degrades to cold
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.smoke:
        # tier-1 safety: tiny, CPU-only, deterministic-ish
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        args.rate, args.duration = 80.0, 1.5
        args.max_batch, args.max_queue = 8, 64
        if args.fleet:
            args.rate, args.duration = 60.0, 2.5
            args.max_wait_ms = 1.0
            args.timeout_s = 20.0
        if args.tokens:
            # saturating burst: the A/B measures service capacity, so
            # arrivals must not be the bottleneck in either phase
            args.rate, args.duration = 600.0, 0.08
            args.slots, args.prefill_buckets = "4", "8,16"
            args.timeout_s = 120.0
        if args.tenants:
            args.rate, args.duration = 40.0, 1.2
            args.max_batch, args.max_queue = 8, 64
            args.flood_threads = 4
            args.timeout_s = 30.0
            # ~20 GIL-bound flood threads on a shared CPU box add
            # scheduler noise the real TPU shape doesn't have; keep
            # the p99 claim but widen the smoke allowance
            args.tenant_p99_factor = max(args.tenant_p99_factor, 15.0)
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from paddle_tpu.core.tpu_lock import tpu_singleflight

    with tpu_singleflight():  # one real chip: serialize vs bench/tools
        if args.fleet:
            return run_fleet_bench(args)
        if args.tenants:
            return run_tenants_bench(args)
        if args.tokens and args.prefix_share:
            return run_prefix_bench(args)
        return run_token_bench(args) if args.tokens else run_bench(args)


if __name__ == "__main__":
    sys.exit(main())
