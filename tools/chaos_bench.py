"""Scripted kill/resume cycles against the resilience stack.

Drives the fault-tolerant training loop (parallel.train.train_loop +
resilience.CheckpointManager) through whole-process crash + resume
cycles and reports SERVING-bench-style JSON lines: checkpoint save and
restore seconds, recovered-step overhead (steps re-executed because
they post-dated the last committed checkpoint), and whether every
resumed trajectory reproduced the uninterrupted baseline.

Each cycle: run the worker with PADDLE_TPU_FAULT_SPEC="step=K:crash"
(the injector os._exit()s the process at that exact step boundary —
a hard kill, not an exception), then relaunch the same command; the
worker restores via CheckpointManager.restore_latest() and finishes
the run. Losses are keyed by global step, so equivalence with the
baseline is a direct per-step comparison.

Run:  python tools/chaos_bench.py [--steps 24] [--save-every 4]
      [--kill-steps 7,15] [--smoke]

--smoke is the tier-1-safe mode the test suite invokes (CPU backend,
one short cycle) — it validates the whole kill/resume machinery and
the report schema, not absolute numbers.

Elastic mode (--elastic, RESILIENCE.md §Elasticity): instead of
kill-the-whole-process cycles, this drives a MEMBERSHIP chaos scenario
through the rendezvous store: a chief trainer plus world-1 member
processes rendezvous at world W; mid-training the orchestrator
SIGKILLs one member (its heartbeat goes stale → the chief re-forms on
W-1 survivors at the next checkpoint boundary, resharding the mesh-W
checkpoint onto mesh-(W-1) — NO process restarts), then spawns a
replacement (scale back out to W). The chief's loss trajectory must
match an uninterrupted fixed-world baseline within --tol, and the
report carries rendezvous seconds, resharding seconds, the generation
history, and the data-shard ledger check (no example lost or
double-seen across either membership change).

Run:  python tools/chaos_bench.py --elastic [--smoke]
      [--world 4] [--kill-at 2] [--join-at 8] [--tol 1e-3]

PS mode (--ps, RESILIENCE.md §Parameter-server fault tolerance): a CTR
workload (PS-sharded embedding + transpiled dense params, async mode)
trains against S pserver processes snapshotting through their own
CheckpointManager. Mid-run the orchestrator SIGKILLs one server and
respawns it on the same endpoint after --outage seconds; the respawn
restores its committed sparse+dense snapshot, and the single trainer
process rides the outage on the resilient client (reconnect + capped
backoff + idempotent retry + circuit breaker) with ZERO trainer
restarts. The report carries the loss-trajectory delta vs an
uninterrupted baseline (--tol), plus the degraded-seconds / rpc-retry /
reconnect metrics that prove the outage cost bounded step time (no
180 s socket stall).

Run:  python tools/chaos_bench.py --ps [--smoke]
      [--ps-servers 2] [--kill-at 4] [--outage 0.5] [--tol 0.05]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_args():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=24,
                    help="total training steps per run")
    ap.add_argument("--save-every", type=int, default=4)
    ap.add_argument("--kill-steps", type=str, default="7,15",
                    help="comma-separated steps to crash at, one cycle "
                    "per step")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--keep-last", type=int, default=2)
    ap.add_argument("--timeout-s", type=float, default=300.0)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU run for CI (overrides steps/kills)")
    # elastic membership chaos (see module docstring)
    ap.add_argument("--elastic", action="store_true",
                    help="membership chaos: kill/join members through "
                    "the rendezvous store instead of killing the "
                    "training process")
    ap.add_argument("--world", type=int, default=4,
                    help="elastic: starting world size")
    ap.add_argument("--kill-at", type=int, default=4,
                    help="elastic: SIGKILL one member once the chief "
                    "reports this step")
    ap.add_argument("--join-at", type=int, default=12,
                    help="elastic: spawn a replacement member once the "
                    "chief reports this step (after the scale-in)")
    ap.add_argument("--tol", type=float, default=1e-3,
                    help="elastic: relative per-step loss tolerance "
                    "vs the fixed-world baseline (cross-world float "
                    "reduction order differs)")
    ap.add_argument("--step-delay", type=float, default=0.15,
                    help="elastic: host-side seconds per step, so "
                    "membership changes land mid-run deterministically")
    # PS failover chaos (see module docstring)
    ap.add_argument("--ps", action="store_true",
                    help="parameter-server failover chaos: SIGKILL one "
                    "pserver mid-CTR-run, respawn it from its committed "
                    "snapshot, trainers ride through")
    ap.add_argument("--ps-servers", type=int, default=2,
                    help="ps: number of pserver processes")
    ap.add_argument("--outage", type=float, default=0.5,
                    help="ps: seconds between the SIGKILL and the "
                    "respawn")
    ap.add_argument("--rpc-deadline", type=float, default=60.0,
                    help="ps: trainer-side per-call retry budget "
                    "(PADDLE_TPU_PS_RPC_DEADLINE_S)")
    # internal PS roles
    ap.add_argument("--ps-server", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--ps-trainer", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--endpoint", type=str, default="",
                    help=argparse.SUPPRESS)
    ap.add_argument("--snapshot-dir", type=str, default="",
                    help=argparse.SUPPRESS)
    ap.add_argument("--server-index", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--ps-endpoints", type=str, default="",
                    help=argparse.SUPPRESS)
    # internal: run one training process instead of orchestrating
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-dir", type=str, default="",
                    help=argparse.SUPPRESS)
    # internal elastic roles
    ap.add_argument("--elastic-worker", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--member", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--member-id", type=str, default="",
                    help=argparse.SUPPRESS)
    ap.add_argument("--wait-file", type=str, default="",
                    help=argparse.SUPPRESS)
    ap.add_argument("--rdzv-dir", type=str, default="",
                    help=argparse.SUPPRESS)
    ap.add_argument("--progress-file", type=str, default="",
                    help=argparse.SUPPRESS)
    ap.add_argument("--static-world", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--min-world", type=int, default=2,
                    help=argparse.SUPPRESS)
    return ap.parse_args()


# ---------------------------------------------------------------------------
# Worker mode: one training process (baseline, crashing, or resuming —
# the fault spec and the checkpoint dir contents decide which).
# ---------------------------------------------------------------------------


def run_worker(args) -> int:
    import jax
    import optax

    from paddle_tpu.models import lenet
    from paddle_tpu.observability import events
    from paddle_tpu.parallel import make_mesh, mesh_guard
    from paddle_tpu.parallel.train import (TrainStrategy, make_train_step,
                                           train_loop)
    from paddle_tpu.resilience import CheckpointManager
    from paddle_tpu.resilience.preemption import PREEMPT_EXIT_CODE

    params, axes = lenet.init(jax.random.key(0))
    mesh = make_mesh()
    data_key = jax.random.key(42)

    def batch_fn(step):
        if step >= args.steps:
            return None
        k = jax.random.fold_in(data_key, step)
        img = jax.random.normal(k, (args.batch, 1, 28, 28), "float32")
        label = jax.random.randint(jax.random.fold_in(k, 1),
                                   (args.batch, 1), 0, 10, "int32")
        return {"img": img, "label": label}

    with mesh_guard(mesh):
        init_state, step_fn = make_train_step(
            lenet.loss_fn, optax.adam(1e-3), mesh, axes,
            strategy=TrainStrategy(shard_optimizer_states=False))
        state = init_state(params)
        mgr = CheckpointManager(args.ckpt_dir,
                                keep_last_n=args.keep_last)
        resumed_from = None
        restored = mgr.restore_latest(state)
        if restored is not None:
            state = restored
            resumed_from = int(state.step)
        state, losses, stop = train_loop(
            step_fn, state, batch_fn, rng=jax.random.key(7),
            manager=mgr, save_every=args.save_every)

    save_s = [e["seconds"] for e in events.recent(n=None, kind="checkpoint")
              if e.get("site") == "manager_save" and "seconds" in e]
    restore_s = [e["seconds"] for e in events.recent(n=None, kind="restore")
                 if e.get("ok")]
    print(json.dumps({
        "worker": "chaos", "stop": stop, "final_step": int(state.step),
        "resumed_from": resumed_from,
        "losses": {str(k): float(v) for k, v in losses.items()},
        "save_seconds": save_s, "restore_seconds": restore_s,
    }), flush=True)
    return PREEMPT_EXIT_CODE if stop == "preempted" else 0


# ---------------------------------------------------------------------------
# Elastic roles
# ---------------------------------------------------------------------------

# heartbeat cadence shared by every elastic role: a member is declared
# dead after missing ~4 beats, fast enough that a kill lands within a
# couple of (step-delayed) training steps
_HB_S, _DEAD_S = 0.15, 0.6


def _example(i):
    """Global example `i` of the synthetic regression stream —
    derived from the INDEX alone, so every process (baseline, chief,
    any world size) sees the identical example for the same index."""
    import numpy as np

    rs = np.random.RandomState((1_000_003 * (int(i) + 1)) & 0x7FFFFFFF)
    return (rs.randn(8).astype(np.float32),
            rs.randn(4).astype(np.float32))


def _elastic_model():
    import jax
    import jax.numpy as jnp
    import optax

    from paddle_tpu.models.common import ParamStore, dense
    from paddle_tpu.parallel.train import make_train_step

    def make_params():
        # fresh arrays per call: init_state donates its params
        s = ParamStore(jax.random.key(0))
        s.dense("fc", 8, 4)
        return s.params

    store = ParamStore(jax.random.key(0))
    store.dense("fc", 8, 4)
    axes = store.axes

    def loss_fn(params, batch, rng):
        out = dense(params, "fc", batch["x"]).astype(jnp.float32)
        return jnp.mean((out - batch["y"]) ** 2)

    def build(mesh):
        return make_train_step(loss_fn, optax.adam(1e-2), mesh, axes)

    return build, make_params


def run_member(args) -> int:
    """A rendezvous member that holds a slot and heartbeats until
    killed — it models a slice host's liveness, nothing else (the
    single-host chief owns the actual devices). Deliberately does NOT
    import jax: members must be cheap to spawn and kill."""
    import time

    from paddle_tpu.distributed.rendezvous import FileRendezvous

    if args.wait_file:
        # pre-spawned joiner: interpreter+imports are already paid, so
        # the orchestrator can release the join with file latency, not
        # process-startup latency (keeps the smoke scenario's scale-out
        # inside its step budget)
        while not os.path.exists(args.wait_file):
            time.sleep(0.05)
    rdzv = FileRendezvous(args.rdzv_dir, args.member_id,
                          heartbeat_s=_HB_S, dead_after_s=_DEAD_S)
    rdzv.register()
    print(json.dumps({"member": args.member_id, "pid": os.getpid()}),
          flush=True)
    while True:  # until SIGKILLed by the orchestrator
        time.sleep(_HB_S)
        rdzv.register()
        # liveness stubs ack sealed generations so the chief's join
        # barrier completes (a real training member acks by
        # participating in rendezvous() itself)
        rdzv.ack_current()


def run_elastic_worker(args) -> int:
    """The chief trainer: elastic_train_loop over the rendezvous store,
    global batch split per step across live members by
    reader.ElasticShardPlan. Also the fixed-world baseline
    (--static-world N skips the store entirely)."""
    import time

    import jax
    import numpy as np

    from paddle_tpu.observability import events
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.mesh import MeshConfig, mesh_guard
    from paddle_tpu.parallel.train import train_loop
    from paddle_tpu.reader import ElasticShardPlan
    from paddle_tpu.resilience.atomic import json_dump

    build, make_params = _elastic_model()
    gb = args.batch
    plan = ElasticShardPlan(n_examples=args.steps * gb, global_batch=gb,
                            seed=5)
    consumed = []  # (step, world) ledger for the no-loss/no-dup check

    rdzv = None
    if not args.static_world:
        from paddle_tpu.distributed.rendezvous import FileRendezvous

        rdzv = FileRendezvous(args.rdzv_dir, "chief",
                              min_workers=args.min_world,
                              heartbeat_s=_HB_S, dead_after_s=_DEAD_S,
                              settle_s=0.3, timeout_s=60.0)

    def batch_fn(step):
        if step >= args.steps:
            return None
        if args.step_delay:
            time.sleep(args.step_delay)
        if rdzv is not None:
            info = rdzv.current()
            world = info.world_size if info is not None else 1
        else:
            world = args.static_world
        consumed.append((int(step), int(world)))
        if args.progress_file:
            json_dump({"step": int(step), "world": int(world)},
                      args.progress_file)
        # assemble the global batch the way the fleet would feed it:
        # each live member's plan slice, concatenated in rank order
        idx = np.concatenate([plan.worker_indices(step, r, world)
                              for r in range(world)])
        xs, ys = zip(*(_example(i) for i in idx))
        return {"x": np.stack(xs), "y": np.stack(ys)}

    if args.static_world:
        mesh = make_mesh(MeshConfig(dp=-1),
                         devices=jax.devices()[:args.static_world])
        with mesh_guard(mesh):
            init_state, step_fn = build(mesh)
            state, losses, stop = train_loop(
                step_fn, init_state(make_params()), batch_fn,
                rng=jax.random.key(7))
        history = []
    else:
        from paddle_tpu.distributed.elastic import elastic_train_loop
        from paddle_tpu.resilience import CheckpointManager

        mgr = CheckpointManager(args.ckpt_dir, keep_last_n=args.keep_last)
        state, losses, stop, history = elastic_train_loop(
            build, make_params, batch_fn, rdzv=rdzv, manager=mgr,
            save_every=args.save_every, rng=jax.random.key(7))

    # ledger check: with the worlds ACTUALLY used per step, the plan
    # must have assigned every consumed example exactly once
    ledger = []
    for step, world in consumed:
        if step in losses:  # executed steps only
            for r in range(world):
                ledger.extend(int(i) for i in
                              plan.worker_indices(step, r, world))
    expected = []
    for step in sorted(losses):
        expected.extend(int(i) for i in plan.batch_indices(step))
    plan_ok = sorted(ledger) == sorted(expected) and \
        len(set(ledger)) == len(ledger)

    rdzv_s = [e["seconds"] for e in events.recent(n=None, kind="rendezvous")
              if e.get("action") == "sealed" and "seconds" in e]
    reshard_s = [e["seconds"] for e in
                 events.recent(n=None, kind="restore_resharded")]
    lost = sorted({w for e in events.recent(n=None, kind="rendezvous")
                   for w in e.get("lost", [])})
    print(json.dumps({
        "worker": "elastic", "stop": stop, "pid": os.getpid(),
        "losses": {str(k): float(v) for k, v in losses.items()},
        "generations": [{"generation": h.generation,
                         "world": h.world_size} for h in history],
        "plan_ok": plan_ok,
        "rendezvous_seconds": rdzv_s, "resharding_seconds": reshard_s,
        "lost_members": lost,
    }), flush=True)
    return 0 if stop == "completed" else 1


def _read_progress(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def run_elastic_bench(args) -> int:
    """Orchestrate the elastic scenario: world W chief+members, kill one
    member mid-training (scale-in to W-1 at the next checkpoint
    boundary, no process restarts), spawn a replacement (scale-out back
    to W), and compare the chief's full loss trajectory against an
    uninterrupted fixed-world-W baseline."""
    import subprocess
    import time

    work = tempfile.mkdtemp(prefix="chaos_elastic_")
    rdzv_dir = os.path.join(work, "rdzv")
    progress = os.path.join(work, "progress.json")
    os.makedirs(rdzv_dir, exist_ok=True)
    failures = []
    members = {}

    def env_for(n_devices):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.pop("PADDLE_TPU_FAULT_SPEC", None)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count="
                            f"{n_devices}").strip()
        return env

    def spawn_member(mid, wait_file=""):
        cmd = [sys.executable, os.path.abspath(__file__), "--member",
               "--member-id", mid, "--rdzv-dir", rdzv_dir]
        if wait_file:
            cmd += ["--wait-file", wait_file]
        p = subprocess.Popen(
            cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            cwd=_REPO, env=dict(os.environ))
        members[mid] = p
        return p

    def wait_for(pred, timeout, what):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if pred():
                return True
            time.sleep(0.05)
        failures.append(f"timeout waiting for {what}")
        return False

    chief_cmd = [sys.executable, os.path.abspath(__file__),
                 "--elastic-worker", "--steps", str(args.steps),
                 "--save-every", str(args.save_every),
                 "--batch", str(args.batch),
                 "--keep-last", str(args.keep_last),
                 "--step-delay", str(args.step_delay),
                 "--min-world", str(args.min_world)]
    try:
        # -- baseline: uninterrupted fixed world W ------------------------
        base = subprocess.run(
            chief_cmd + ["--static-world", str(args.world)],
            capture_output=True, text=True, timeout=args.timeout_s,
            cwd=_REPO, env=env_for(args.world))
        base_rep = _elastic_report(base)
        if base.returncode != 0 or base_rep is None:
            print(base.stdout + base.stderr, file=sys.stderr)
            raise SystemExit("chaos_bench --elastic: baseline failed")
        base_losses = base_rep["losses"]

        # -- elastic run --------------------------------------------------
        members_dir = os.path.join(rdzv_dir, "members")

        def members_registered():
            return (os.path.isdir(members_dir)
                    and len(os.listdir(members_dir)) >= args.world - 1)

        join_gate = os.path.join(work, "join_gate")
        for i in range(1, args.world):
            spawn_member(f"m{i}")
        # the replacement is pre-spawned behind a file gate so the
        # scale-out lands with file latency, not interpreter startup
        spawn_member("m-replacement", wait_file=join_gate)
        if not wait_for(members_registered, 30, "members to register"):
            raise SystemExit("chaos_bench --elastic: members never joined")
        chief = subprocess.Popen(
            chief_cmd + ["--rdzv-dir", rdzv_dir, "--ckpt-dir",
                         os.path.join(work, "ckpt"),
                         "--progress-file", progress],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=_REPO, env=env_for(args.world))

        def chief_wait(pred, what):
            # a dead chief can never satisfy pred — fail fast with its
            # stderr instead of burning the whole timeout
            ok = wait_for(lambda: chief.poll() is not None or pred(),
                          args.timeout_s, what)
            if chief.poll() is not None and not pred():
                return False
            return ok

        victim = f"m{(args.world - 1) // 2 + 1}"
        alive = chief_wait(lambda: _read_progress(progress).get("step", -1)
                           >= args.kill_at, "kill step")
        if alive:
            members[victim].kill()
            chief_wait(lambda: _read_progress(progress).get("world")
                       == args.world - 1, "scale-in")
            chief_wait(lambda: _read_progress(progress).get("step", -1)
                       >= args.join_at, "join step")
            with open(join_gate, "w"):  # atomic-exempt: empty gate file, existence is the signal
                pass
            chief_wait(lambda: _read_progress(progress).get("world")
                       == args.world, "scale-out")

        try:
            out, err = chief.communicate(timeout=args.timeout_s)
        except subprocess.TimeoutExpired:
            chief.kill()
            out, err = chief.communicate()
            failures.append("chief timed out")
        rep = _elastic_report_text(out)
        if chief.returncode != 0 or rep is None:
            failures.append(f"chief rc={chief.returncode}: {err[-500:]}")
            rep = rep or {}
    finally:
        for p in members.values():
            if p.poll() is None:
                p.kill()
        shutil.rmtree(work, ignore_errors=True)

    worlds = [g["world"] for g in rep.get("generations", [])]
    if rep:
        if rep.get("stop") != "completed":
            failures.append(f"chief stop={rep.get('stop')}")
        if not rep.get("plan_ok"):
            failures.append("data-shard ledger check failed: an example "
                            "was lost or double-seen across a resize")
        # the scenario itself: W -> W-1 (scale-in) -> W (scale-out)
        if args.world - 1 not in worlds:
            failures.append(f"never re-formed at world {args.world - 1}: "
                            f"{worlds}")
        elif args.world not in worlds[worlds.index(args.world - 1) + 1:]:
            failures.append(f"never scaled back out to {args.world}: "
                            f"{worlds}")
        if not rep.get("resharding_seconds"):
            failures.append("no restore_resharded event recorded")
        for step, loss in rep.get("losses", {}).items():
            ref = base_losses.get(step)
            if ref is None or abs(loss - ref) > \
                    args.tol * max(1.0, abs(ref)):
                failures.append(f"step {step}: elastic loss {loss} vs "
                                f"baseline {ref} beyond tol {args.tol}")
                break

    detail = {
        "steps": args.steps, "save_every": args.save_every,
        "world": args.world, "kill_at": args.kill_at,
        "join_at": args.join_at, "worlds": worlds,
        "generations": rep.get("generations", []),
        "lost_members": rep.get("lost_members", []),
        "plan_ok": rep.get("plan_ok"), "tol": args.tol,
        "failures": failures, "smoke": bool(args.smoke),
    }
    for metric, value, unit in (
            ("elastic_rendezvous_seconds_p50",
             _percentile(rep.get("rendezvous_seconds", []), 50), "s"),
            ("elastic_resharding_seconds_p50",
             _percentile(rep.get("resharding_seconds", []), 50), "s"),
            ("elastic_resize_count",
             max(0, len(worlds) - 1) if worlds else None, "resizes"),
            ("elastic_recovered_steps_mean", 0.0 if rep else None,
             "steps"),  # the chief never restarts in this scenario
            ("elastic_equivalence_ok", 0.0 if failures else 1.0, "bool")):
        print(json.dumps({
            "metric": metric,
            "value": round(value, 6) if isinstance(value, float) else value,
            "unit": unit, "detail": detail}), flush=True)
    if failures:
        print("\n".join(failures), file=sys.stderr)
    return 1 if failures else 0


def _elastic_report(proc):
    return _elastic_report_text(proc.stdout)


def _elastic_report_text(text):
    for line in reversed((text or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rep = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rep.get("worker") == "elastic":
                return rep
    return None


# ---------------------------------------------------------------------------
# PS failover roles (see module docstring: --ps)
# ---------------------------------------------------------------------------


def run_ps_server(args) -> int:
    """One pserver process (async mode, single trainer) with durable
    snapshots through its own CheckpointManager; serves until killed or
    shut down by the trainer. A respawn on the same endpoint +
    snapshot dir restores the committed tables at construction."""
    from paddle_tpu.ps.server import ParameterServer

    srv = ParameterServer(args.endpoint, num_trainers=1, mode="async",
                          snapshot_dir=args.snapshot_dir or None,
                          server_index=args.server_index)
    print(json.dumps({"ps_server": args.endpoint, "pid": os.getpid(),
                      "restored_vars": len(srv.vars),
                      "generation": srv._generation}), flush=True)
    srv.serve_forever()
    return 0


def run_ps_trainer(args) -> int:
    """The CTR trainer: PS-sharded embedding (distributed_lookup_table)
    + transpiled dense params, async mode, deterministic per-step
    batches. Snapshots every server each --save-every steps (the
    durable-state cadence), writes per-step progress for the
    orchestrator, and reports losses + the resilience metrics that
    prove a mid-run server kill cost bounded step time."""
    import time

    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.observability import metrics as _m
    from paddle_tpu.ops.distributed import bind_client
    from paddle_tpu.ps import (DistributeTranspiler,
                               DistributeTranspilerConfig, PSClient)
    from paddle_tpu.ps.sparse_table import init_sparse_table
    from paddle_tpu.resilience.atomic import json_dump

    eps = args.ps_endpoints.split(",")
    V, D = 40, 8
    rng = np.random.RandomState(0)
    table = rng.rand(V, D).astype("float32") * 0.1

    main, startup = pt.Program(), pt.Program()
    main.random_seed = startup.random_seed = 7
    with pt.framework.unique_name.guard(), pt.program_guard(main, startup):
        wf = pt.layers.data(name="wf", shape=[1], dtype="float32")
        label = pt.layers.data(name="label", shape=[1], dtype="float32")
        ids64 = pt.layers.cast(wf, "int64")
        emb = pt.layers.distributed_embedding(ids64, (V, D), "ctr_table",
                                              sparse_lr=0.3)
        emb = pt.layers.reshape(emb, shape=[-1, D])
        pred = pt.layers.fc(input=emb, size=1, act="sigmoid")
        loss = pt.layers.mean(pt.layers.log_loss(pred, label))
        pt.optimizer.SGD(0.05).minimize(loss)

    cfg = DistributeTranspilerConfig()
    cfg.sync_mode = False
    t = DistributeTranspiler(cfg)
    t.transpile(0, program=main, pservers=args.ps_endpoints, trainers=1,
                sync_mode=False)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    client = PSClient(eps, rpc_deadline_s=args.rpc_deadline)
    bind_client(client)
    t.publish_params(pt.global_scope(), client)
    init_sparse_table(client, "ctr_table", table)
    client.snapshot_servers()   # snapshot 0: the post-init state
    prog = t.get_trainer_program()

    def batch(step):
        rs = np.random.RandomState((step + 1) * 7919)
        ids = rs.randint(0, V, (16, 1))
        return {"wf": ids.astype(np.float32),
                "label": (ids % 3 == 0).astype(np.float32)}

    losses = {}
    step_secs = []
    snap_latest = -1
    for step in range(args.steps):
        if args.step_delay:
            time.sleep(args.step_delay)
        fd = batch(step)
        t0 = time.perf_counter()
        val = exe.run(prog, feed=fd, fetch_list=[loss])[0]
        step_secs.append(time.perf_counter() - t0)
        losses[step] = float(np.asarray(val).reshape(()))
        if args.save_every and (step + 1) % args.save_every == 0:
            client.snapshot_servers()
            snap_latest = step
        if args.progress_file:
            json_dump({"step": step, "snapshotted": snap_latest},
                      args.progress_file)

    snap = _m.snapshot()

    def total(name, outcome=None):
        out = 0.0
        for s in (snap.get(name) or {}).get("series", []):
            if outcome is None or \
                    s.get("labels", {}).get("outcome") == outcome:
                out += s.get("value", 0)
        return out

    print(json.dumps({
        "worker": "ps", "pid": os.getpid(),
        "losses": {str(k): v for k, v in losses.items()},
        "steps_done": len(losses),
        "max_step_s": round(max(step_secs), 4),
        "degraded_s": round(total("paddle_tpu_ps_degraded_seconds_total"),
                            4),
        "retries": int(total("paddle_tpu_ps_rpc_total", "retry")),
        "unavailable": int(total("paddle_tpu_ps_rpc_total",
                                 "unavailable")),
        "reconnects": int(total("paddle_tpu_ps_reconnects_total")),
    }), flush=True)
    client.shutdown_servers()
    return 0


def _ps_report(text):
    for line in reversed((text or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rep = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rep.get("worker") == "ps":
                return rep
    return None


def run_ps_bench(args) -> int:
    """Orchestrate the PS failover scenario: S servers + 1 CTR trainer;
    SIGKILL server 0 right after a committed snapshot, respawn it on
    the same endpoint after --outage seconds (it restores the
    snapshot), and require (a) the trainer rides through with ZERO
    restarts, (b) the full loss trajectory within --tol of an
    uninterrupted baseline, (c) the outage cost bounded step time,
    evidenced by the degraded-seconds / retry / reconnect metrics."""
    import socket as _socket
    import time

    work = tempfile.mkdtemp(prefix="chaos_ps_")
    failures = []
    procs = []

    def env_for():
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PADDLE_TPU_FAULT_SPEC", None)
        return env

    def free_eps(n):
        socks, eps = [], []
        for _ in range(n):
            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            eps.append(f"127.0.0.1:{s.getsockname()[1]}")
        for s in socks:
            s.close()
        return eps

    def spawn_server(i, ep, snap_dir, log):
        cmd = [sys.executable, os.path.abspath(__file__), "--ps-server",
               "--endpoint", ep, "--snapshot-dir", snap_dir,
               "--server-index", str(i)]
        p = subprocess.Popen(cmd, stdout=open(log, "a"),  # atomic-exempt: live log stream
                             stderr=subprocess.STDOUT, cwd=_REPO,
                             env=env_for())
        procs.append(p)
        return p

    def wait_ep(ep, timeout=20.0):
        host, port = ep.rsplit(":", 1)
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                _socket.create_connection((host, int(port)), 0.2).close()
                return True
            except OSError:
                time.sleep(0.05)
        return False

    def trainer_cmd(eps, progress=""):
        cmd = [sys.executable, os.path.abspath(__file__), "--ps-trainer",
               "--ps-endpoints", ",".join(eps),
               "--steps", str(args.steps),
               "--save-every", str(args.save_every),
               "--step-delay", str(args.step_delay),
               "--rpc-deadline", str(args.rpc_deadline)]
        if progress:
            cmd += ["--progress-file", progress]
        return cmd

    outage_s = None
    rep = {}
    try:
        # -- baseline: no faults ------------------------------------------
        base_eps = free_eps(args.ps_servers)
        for i, ep in enumerate(base_eps):
            spawn_server(i, ep, os.path.join(work, f"base_snap_{i}"),
                         os.path.join(work, f"base_server_{i}.log"))
        for ep in base_eps:
            if not wait_ep(ep):
                raise SystemExit(f"chaos --ps: baseline server {ep} "
                                 f"never bound")
        base = subprocess.run(trainer_cmd(base_eps), capture_output=True,
                              text=True, timeout=args.timeout_s,
                              cwd=_REPO, env=env_for())
        base_rep = _ps_report(base.stdout)
        if base.returncode != 0 or base_rep is None:
            print(base.stdout + base.stderr, file=sys.stderr)
            raise SystemExit("chaos --ps: baseline run failed")

        # -- chaos run ----------------------------------------------------
        eps = free_eps(args.ps_servers)
        servers = {}
        for i, ep in enumerate(eps):
            servers[i] = spawn_server(
                i, ep, os.path.join(work, f"snap_{i}"),
                os.path.join(work, f"server_{i}.log"))
        for ep in eps:
            if not wait_ep(ep):
                raise SystemExit(f"chaos --ps: server {ep} never bound")
        progress = os.path.join(work, "progress.json")
        trainer = subprocess.Popen(
            trainer_cmd(eps, progress), stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, cwd=_REPO, env=env_for())

        def wait_progress(pred, what, timeout):
            deadline = time.time() + timeout
            while time.time() < deadline:
                if trainer.poll() is not None:
                    return False  # dead trainer can't satisfy pred
                if pred(_read_progress(progress)):
                    return True
                time.sleep(0.05)
            failures.append(f"timeout waiting for {what}")
            return False

        # kill server 0 right AFTER a committed snapshot at/after
        # --kill-at: the restored state then trails the live state by at
        # most the couple of steps the kill latency admits (--tol
        # absorbs those lost updates)
        if wait_progress(lambda p: p.get("snapshotted", -1) >= args.kill_at,
                         "kill snapshot", args.timeout_s):
            victim = servers[0]
            victim.kill()
            victim.wait(timeout=10)
            t_kill = time.time()
            time.sleep(args.outage)
            servers[0] = spawn_server(
                0, eps[0], os.path.join(work, "snap_0"),
                os.path.join(work, "server_0.log"))
            if not wait_ep(eps[0]):
                failures.append("respawned server 0 never bound")
            outage_s = time.time() - t_kill
        try:
            out, err = trainer.communicate(timeout=args.timeout_s)
        except subprocess.TimeoutExpired:
            trainer.kill()
            out, err = trainer.communicate()
            failures.append("trainer timed out (outage not survived)")
        rep = _ps_report(out) or {}
        if trainer.returncode != 0 or not rep:
            failures.append(f"trainer rc={trainer.returncode}: "
                            f"{(err or '')[-500:]}")
        # -- acceptance ---------------------------------------------------
        if rep:
            if rep.get("steps_done") != args.steps:
                failures.append(f"trainer finished {rep.get('steps_done')}"
                                f"/{args.steps} steps")
            for step, loss in rep.get("losses", {}).items():
                ref = base_rep["losses"].get(step)
                if ref is None or abs(loss - ref) > \
                        args.tol * max(1.0, abs(ref)):
                    failures.append(
                        f"step {step}: chaos loss {loss} vs baseline "
                        f"{ref} beyond tol {args.tol}")
                    break
            if rep.get("reconnects", 0) < 1:
                failures.append("trainer never reconnected — did the "
                                "kill land?")
            if rep.get("retries", 0) < 1:
                failures.append("no rpc retries recorded during the "
                                "outage")
            if rep.get("degraded_s", 0.0) <= 0.0:
                failures.append("degraded-seconds metric stayed zero")
            # the no-180s-stall bound: the worst step costs at most the
            # outage plus breaker/backoff slack, never a socket timeout
            bound = (outage_s or args.outage) + 30.0
            if rep.get("max_step_s", 0.0) > bound:
                failures.append(f"max step {rep['max_step_s']}s exceeds "
                                f"outage+slack bound {bound:.1f}s")
        # respawned server restored its snapshot?
        try:
            with open(os.path.join(work, "server_0.log")) as f:
                boots = [json.loads(l) for l in f
                         if l.strip().startswith("{")]
            if len(boots) >= 2 and boots[-1].get("restored_vars", 0) < 1:
                failures.append("respawned server 0 restored no vars "
                                "(snapshot not found?)")
        except (OSError, ValueError) as e:
            failures.append(f"cannot verify respawn restore: {e}")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        shutil.rmtree(work, ignore_errors=True)

    detail = {
        "steps": args.steps, "save_every": args.save_every,
        "servers": args.ps_servers, "kill_at": args.kill_at,
        "outage_requested_s": args.outage, "tol": args.tol,
        "trainer_restarts": 0,   # by construction: one trainer process
        "retries": rep.get("retries"), "reconnects": rep.get("reconnects"),
        "unavailable": rep.get("unavailable"),
        "failures": failures, "smoke": bool(args.smoke),
    }
    for metric, value, unit in (
            ("ps_outage_seconds",
             round(outage_s, 3) if outage_s else None, "s"),
            ("ps_degraded_seconds", rep.get("degraded_s"), "s"),
            ("ps_rpc_retries", rep.get("retries"), "count"),
            ("ps_reconnects", rep.get("reconnects"), "count"),
            ("ps_max_step_seconds", rep.get("max_step_s"), "s"),
            ("ps_equivalence_ok", 0.0 if failures else 1.0, "bool")):
        print(json.dumps({
            "metric": metric,
            "value": round(value, 6) if isinstance(value, float) else value,
            "unit": unit, "detail": detail}), flush=True)
    if failures:
        print("\n".join(failures), file=sys.stderr)
    return 1 if failures else 0


# ---------------------------------------------------------------------------
# Orchestrator mode
# ---------------------------------------------------------------------------


def _spawn(args, ckpt_dir, fault_spec=None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("PADDLE_TPU_FAULT_SPEC", None)
    if fault_spec:
        env["PADDLE_TPU_FAULT_SPEC"] = fault_spec
    cmd = [sys.executable, os.path.abspath(__file__), "--worker",
           "--ckpt-dir", ckpt_dir, "--steps", str(args.steps),
           "--save-every", str(args.save_every),
           "--batch", str(args.batch), "--keep-last", str(args.keep_last)]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=args.timeout_s, cwd=_REPO, env=env)


def _worker_report(proc):
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rep = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rep.get("worker") == "chaos":
                return rep
    return None


def _percentile(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1))))
    return xs[i]


def run_bench(args) -> int:
    from paddle_tpu.resilience.faults import CRASH_EXIT_CODE

    kill_steps = [int(s) for s in args.kill_steps.split(",") if s.strip()]
    work = tempfile.mkdtemp(prefix="chaos_bench_")
    failures = []
    save_s, restore_s, recovered = [], [], []

    base = _spawn(args, os.path.join(work, "baseline"))
    base_rep = _worker_report(base)
    if base.returncode != 0 or base_rep is None:
        print(base.stdout + base.stderr, file=sys.stderr)
        shutil.rmtree(work, ignore_errors=True)
        raise SystemExit("chaos_bench: baseline run failed")
    base_losses = base_rep["losses"]
    save_s += base_rep["save_seconds"]

    for kill in kill_steps:
        ckpt = os.path.join(work, f"kill_{kill}")
        crashed = _spawn(args, ckpt, fault_spec=f"step={kill}:crash")
        if crashed.returncode != CRASH_EXIT_CODE:
            failures.append(
                f"kill@{kill}: expected crash rc={CRASH_EXIT_CODE}, got "
                f"{crashed.returncode}: {crashed.stderr[-500:]}")
            continue
        resumed = _spawn(args, ckpt)
        rep = _worker_report(resumed)
        if resumed.returncode != 0 or rep is None:
            failures.append(f"kill@{kill}: resume failed rc="
                            f"{resumed.returncode}: {resumed.stderr[-500:]}")
            continue
        if rep["resumed_from"] is None:
            failures.append(f"kill@{kill}: resume found no checkpoint")
            continue
        recovered.append(kill - rep["resumed_from"])
        save_s += rep["save_seconds"]
        restore_s += rep["restore_seconds"]
        for step, loss in rep["losses"].items():
            ref = base_losses.get(step)
            if ref is None or abs(loss - ref) > 1e-5 * max(1.0, abs(ref)):
                failures.append(
                    f"kill@{kill}: step {step} loss {loss} != baseline "
                    f"{ref}")
                break
    shutil.rmtree(work, ignore_errors=True)

    detail = {
        "steps": args.steps, "save_every": args.save_every,
        "kill_steps": kill_steps, "cycles": len(kill_steps),
        "failures": failures, "smoke": bool(args.smoke),
    }
    for metric, value, unit in (
            ("chaos_save_seconds_p50", _percentile(save_s, 50), "s"),
            ("chaos_restore_seconds_p50", _percentile(restore_s, 50), "s"),
            ("chaos_recovered_steps_mean",
             round(sum(recovered) / len(recovered), 3) if recovered
             else None, "steps"),
            ("chaos_equivalence_ok", 0.0 if failures else 1.0, "bool")):
        print(json.dumps({
            "metric": metric,
            "value": round(value, 6) if isinstance(value, float) else value,
            "unit": unit, "detail": detail}), flush=True)
    if failures:
        print("\n".join(failures), file=sys.stderr)
    return 1 if failures else 0


def main() -> int:
    args = _build_args()
    sys.path.insert(0, _REPO)
    if args.ps_server:
        if not args.endpoint:
            raise SystemExit("--ps-server needs --endpoint")
        return run_ps_server(args)
    if args.ps_trainer:
        if not args.ps_endpoints:
            raise SystemExit("--ps-trainer needs --ps-endpoints")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        return run_ps_trainer(args)
    if args.ps:
        # host-side CPU scenario end to end (pservers are host processes,
        # the trainer is forced to CPU): no TPU singleflight needed
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        if args.smoke:
            args.steps, args.save_every = 12, 2
            args.kill_at, args.outage = 4, 0.5
            args.ps_servers = min(args.ps_servers, 2)
        if args.tol == 1e-3:
            # the elastic default is bit-tight; a PS kill legitimately
            # loses the couple of steps between the last snapshot and
            # the SIGKILL landing — 5% relative absorbs them
            args.tol = 0.05
        return run_ps_bench(args)
    if args.member:
        if not (args.member_id and args.rdzv_dir):
            raise SystemExit("--member needs --member-id and --rdzv-dir")
        return run_member(args)
    if args.elastic_worker:
        return run_elastic_worker(args)
    if args.worker:
        if not args.ckpt_dir:
            raise SystemExit("--worker needs --ckpt-dir")
        return run_worker(args)
    if args.elastic:
        if args.world < 3:
            raise SystemExit(
                "--elastic needs --world >= 3: the scenario kills one "
                "member and must keep world-1 at or above quorum")
        if args.min_world > args.world - 1:
            raise SystemExit(
                f"--min-world {args.min_world} would deadlock the "
                f"scale-in to {args.world - 1}")
        if args.smoke:
            # tier-1 safety: tiny CPU scenario, one kill + one join
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            args.steps, args.save_every = 18, 2
            args.kill_at, args.join_at = 2, 8
            args.world = min(args.world, 4)
        else:
            args.steps = max(args.steps, args.join_at + 8)
        if args.batch % args.world or args.batch % (args.world - 1):
            # global batch divisible by both worlds keeps the batch
            # dp-sharded through the scale-in, not silently replicated
            args.batch = args.world * (args.world - 1) \
                * max(1, args.batch // (args.world * (args.world - 1)))
        from paddle_tpu.core.tpu_lock import tpu_singleflight

        with tpu_singleflight():
            return run_elastic_bench(args)
    if args.smoke:
        # tier-1 safety: tiny, CPU-only, a single kill/resume cycle
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        args.steps, args.save_every = 8, 2
        args.kill_steps = "5"
    from paddle_tpu.core.tpu_lock import tpu_singleflight

    with tpu_singleflight():  # one real chip: serialize vs bench/tools
        return run_bench(args)


if __name__ == "__main__":
    sys.exit(main())
