"""Scripted kill/resume cycles against the resilience stack.

Drives the fault-tolerant training loop (parallel.train.train_loop +
resilience.CheckpointManager) through whole-process crash + resume
cycles and reports SERVING-bench-style JSON lines: checkpoint save and
restore seconds, recovered-step overhead (steps re-executed because
they post-dated the last committed checkpoint), and whether every
resumed trajectory reproduced the uninterrupted baseline.

Each cycle: run the worker with PADDLE_TPU_FAULT_SPEC="step=K:crash"
(the injector os._exit()s the process at that exact step boundary —
a hard kill, not an exception), then relaunch the same command; the
worker restores via CheckpointManager.restore_latest() and finishes
the run. Losses are keyed by global step, so equivalence with the
baseline is a direct per-step comparison.

Run:  python tools/chaos_bench.py [--steps 24] [--save-every 4]
      [--kill-steps 7,15] [--smoke]

--smoke is the tier-1-safe mode the test suite invokes (CPU backend,
one short cycle) — it validates the whole kill/resume machinery and
the report schema, not absolute numbers.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_args():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=24,
                    help="total training steps per run")
    ap.add_argument("--save-every", type=int, default=4)
    ap.add_argument("--kill-steps", type=str, default="7,15",
                    help="comma-separated steps to crash at, one cycle "
                    "per step")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--keep-last", type=int, default=2)
    ap.add_argument("--timeout-s", type=float, default=300.0)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU run for CI (overrides steps/kills)")
    # internal: run one training process instead of orchestrating
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-dir", type=str, default="",
                    help=argparse.SUPPRESS)
    return ap.parse_args()


# ---------------------------------------------------------------------------
# Worker mode: one training process (baseline, crashing, or resuming —
# the fault spec and the checkpoint dir contents decide which).
# ---------------------------------------------------------------------------


def run_worker(args) -> int:
    import jax
    import optax

    from paddle_tpu.models import lenet
    from paddle_tpu.observability import events
    from paddle_tpu.parallel import make_mesh, mesh_guard
    from paddle_tpu.parallel.train import (TrainStrategy, make_train_step,
                                           train_loop)
    from paddle_tpu.resilience import CheckpointManager
    from paddle_tpu.resilience.preemption import PREEMPT_EXIT_CODE

    params, axes = lenet.init(jax.random.key(0))
    mesh = make_mesh()
    data_key = jax.random.key(42)

    def batch_fn(step):
        if step >= args.steps:
            return None
        k = jax.random.fold_in(data_key, step)
        img = jax.random.normal(k, (args.batch, 1, 28, 28), "float32")
        label = jax.random.randint(jax.random.fold_in(k, 1),
                                   (args.batch, 1), 0, 10, "int32")
        return {"img": img, "label": label}

    with mesh_guard(mesh):
        init_state, step_fn = make_train_step(
            lenet.loss_fn, optax.adam(1e-3), mesh, axes,
            strategy=TrainStrategy(shard_optimizer_states=False))
        state = init_state(params)
        mgr = CheckpointManager(args.ckpt_dir,
                                keep_last_n=args.keep_last)
        resumed_from = None
        restored = mgr.restore_latest(state)
        if restored is not None:
            state = restored
            resumed_from = int(state.step)
        state, losses, stop = train_loop(
            step_fn, state, batch_fn, rng=jax.random.key(7),
            manager=mgr, save_every=args.save_every)

    save_s = [e["seconds"] for e in events.recent(n=None, kind="checkpoint")
              if e.get("site") == "manager_save" and "seconds" in e]
    restore_s = [e["seconds"] for e in events.recent(n=None, kind="restore")
                 if e.get("ok")]
    print(json.dumps({
        "worker": "chaos", "stop": stop, "final_step": int(state.step),
        "resumed_from": resumed_from,
        "losses": {str(k): float(v) for k, v in losses.items()},
        "save_seconds": save_s, "restore_seconds": restore_s,
    }), flush=True)
    return PREEMPT_EXIT_CODE if stop == "preempted" else 0


# ---------------------------------------------------------------------------
# Orchestrator mode
# ---------------------------------------------------------------------------


def _spawn(args, ckpt_dir, fault_spec=None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("PADDLE_TPU_FAULT_SPEC", None)
    if fault_spec:
        env["PADDLE_TPU_FAULT_SPEC"] = fault_spec
    cmd = [sys.executable, os.path.abspath(__file__), "--worker",
           "--ckpt-dir", ckpt_dir, "--steps", str(args.steps),
           "--save-every", str(args.save_every),
           "--batch", str(args.batch), "--keep-last", str(args.keep_last)]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=args.timeout_s, cwd=_REPO, env=env)


def _worker_report(proc):
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rep = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rep.get("worker") == "chaos":
                return rep
    return None


def _percentile(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1))))
    return xs[i]


def run_bench(args) -> int:
    from paddle_tpu.resilience.faults import CRASH_EXIT_CODE

    kill_steps = [int(s) for s in args.kill_steps.split(",") if s.strip()]
    work = tempfile.mkdtemp(prefix="chaos_bench_")
    failures = []
    save_s, restore_s, recovered = [], [], []

    base = _spawn(args, os.path.join(work, "baseline"))
    base_rep = _worker_report(base)
    if base.returncode != 0 or base_rep is None:
        print(base.stdout + base.stderr, file=sys.stderr)
        shutil.rmtree(work, ignore_errors=True)
        raise SystemExit("chaos_bench: baseline run failed")
    base_losses = base_rep["losses"]
    save_s += base_rep["save_seconds"]

    for kill in kill_steps:
        ckpt = os.path.join(work, f"kill_{kill}")
        crashed = _spawn(args, ckpt, fault_spec=f"step={kill}:crash")
        if crashed.returncode != CRASH_EXIT_CODE:
            failures.append(
                f"kill@{kill}: expected crash rc={CRASH_EXIT_CODE}, got "
                f"{crashed.returncode}: {crashed.stderr[-500:]}")
            continue
        resumed = _spawn(args, ckpt)
        rep = _worker_report(resumed)
        if resumed.returncode != 0 or rep is None:
            failures.append(f"kill@{kill}: resume failed rc="
                            f"{resumed.returncode}: {resumed.stderr[-500:]}")
            continue
        if rep["resumed_from"] is None:
            failures.append(f"kill@{kill}: resume found no checkpoint")
            continue
        recovered.append(kill - rep["resumed_from"])
        save_s += rep["save_seconds"]
        restore_s += rep["restore_seconds"]
        for step, loss in rep["losses"].items():
            ref = base_losses.get(step)
            if ref is None or abs(loss - ref) > 1e-5 * max(1.0, abs(ref)):
                failures.append(
                    f"kill@{kill}: step {step} loss {loss} != baseline "
                    f"{ref}")
                break
    shutil.rmtree(work, ignore_errors=True)

    detail = {
        "steps": args.steps, "save_every": args.save_every,
        "kill_steps": kill_steps, "cycles": len(kill_steps),
        "failures": failures, "smoke": bool(args.smoke),
    }
    for metric, value, unit in (
            ("chaos_save_seconds_p50", _percentile(save_s, 50), "s"),
            ("chaos_restore_seconds_p50", _percentile(restore_s, 50), "s"),
            ("chaos_recovered_steps_mean",
             round(sum(recovered) / len(recovered), 3) if recovered
             else None, "steps"),
            ("chaos_equivalence_ok", 0.0 if failures else 1.0, "bool")):
        print(json.dumps({
            "metric": metric,
            "value": round(value, 6) if isinstance(value, float) else value,
            "unit": unit, "detail": detail}), flush=True)
    if failures:
        print("\n".join(failures), file=sys.stderr)
    return 1 if failures else 0


def main() -> int:
    args = _build_args()
    sys.path.insert(0, _REPO)
    if args.worker:
        if not args.ckpt_dir:
            raise SystemExit("--worker needs --ckpt-dir")
        return run_worker(args)
    if args.smoke:
        # tier-1 safety: tiny, CPU-only, a single kill/resume cycle
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        args.steps, args.save_every = 8, 2
        args.kill_steps = "5"
    from paddle_tpu.core.tpu_lock import tpu_singleflight

    with tpu_singleflight():  # one real chip: serialize vs bench/tools
        return run_bench(args)


if __name__ == "__main__":
    sys.exit(main())
