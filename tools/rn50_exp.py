"""ResNet-50 perf experiment driver (real chip): A/B layouts & variants.

Usage: python tools/rn50_exp.py [nchw|nhwc] [bs] [steps]
Prints step time + samples/s + MFU for the chosen variant.
"""

import sys
import time

import jax

jax.config.update("jax_default_prng_impl", "unsafe_rbg")

import optax  # noqa: E402

from paddle_tpu.models import resnet  # noqa: E402
from paddle_tpu.parallel import MeshConfig, make_mesh, mesh_guard  # noqa: E402
from paddle_tpu.parallel.train import TrainStrategy, make_train_step  # noqa: E402


def run(data_format="NHWC", bs=256, n_steps=20, hw=224):
    cfg = resnet.ResNetConfig.resnet50()
    mesh = make_mesh(MeshConfig(dp=-1), devices=jax.devices()[:1])
    with mesh_guard(mesh):
        params, axes = resnet.init(jax.random.key(0), cfg)

        def loss_fn(p, b, r):
            return resnet.loss_fn(p, cfg, b, r, data_format=data_format)

        init_state, step = make_train_step(
            loss_fn, optax.sgd(0.1, momentum=0.9), mesh, axes,
            strategy=TrainStrategy(shard_optimizer_states=False),
            has_aux=True)
        state = init_state(params)
        batch = resnet.make_batch(jax.random.key(1), cfg, bs, hw=hw,
                                  data_format=data_format)
        state, loss = step(state, batch, jax.random.key(2))
        float(loss)
        t0 = time.perf_counter()
        for i in range(n_steps):
            state, loss = step(state, batch, jax.random.key(3 + i))
        fl = float(loss)
        dt = time.perf_counter() - t0
    sps = bs * n_steps / dt
    mfu = sps * cfg.flops_per_image(hw) / 197e12
    print(f"{data_format} bs={bs}: step={1000 * dt / n_steps:.2f} ms  "
          f"{sps:.0f} img/s  MFU={mfu:.4f}  loss={fl:.3f}", flush=True)
    return sps


if __name__ == "__main__":
    from paddle_tpu.core.tpu_lock import tpu_singleflight

    fmt = (sys.argv[1] if len(sys.argv) > 1 else "nhwc").upper()
    bs = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    steps = int(sys.argv[3]) if len(sys.argv) > 3 else 20
    with tpu_singleflight():  # one real chip: serialize vs bench/tools
        run(fmt, bs, steps)
