#!/usr/bin/env python
"""analyze — run the static-analysis pass suite over a program, offline.

The CLI face of paddle_tpu/analysis (ANALYSIS.md): validate a saved
model before deploying it, lint a hand-built/transpiled program before
committing it, or render the block as DOT to see what the passes see.

Usage:
  analyze.py --model-dir DIR                 # saved __model__ dir
  analyze.py --model lenet                   # in-repo model builder
  analyze.py --program prog.json             # raw ProgramDesc JSON
  ... [--feeds a,b] [--fetches x,y]          # run binding (defaults:
                                             #   the model's saved ones)
  ... [--policy mixed_bf16]                  # precision policy to audit
  ... [--passes def_use,shape_dtype]         # subset (default: all)
  ... [--json]                               # findings as JSON lines
  ... [--dot out.dot]                        # render block 0 via
                                             #   debugger.block_to_dot
  ... [--max-findings N]                     # truncate the table

Exit code: 0 = no error-severity findings, 1 = errors found, 2 = usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _load_target(args):
    """(ProgramDesc, feed_names, fetch_names) from whichever source the
    flags name."""
    if args.model_dir:
        path = os.path.join(args.model_dir, "__model__")
        with open(path) as f:
            payload = json.load(f)
        from paddle_tpu.core.ir import ProgramDesc

        return (ProgramDesc.from_dict(payload["program"]),
                list(payload.get("feed_names", [])),
                list(payload.get("fetch_names", [])))
    if args.program:
        with open(args.program) as f:
            payload = json.load(f)
        from paddle_tpu.core.ir import ProgramDesc

        if isinstance(payload, dict) and "program" in payload:
            return (ProgramDesc.from_dict(payload["program"]),
                    list(payload.get("feed_names", [])),
                    list(payload.get("fetch_names", [])))
        return ProgramDesc.from_dict(payload), [], []
    if args.model:
        import paddle_tpu as pt

        builders = {"lenet": _build_lenet}
        if args.model not in builders:
            raise SystemExit(
                f"analyze: unknown --model {args.model!r}; choose from "
                f"{sorted(builders)} or use --model-dir/--program")
        return builders[args.model](pt)
    raise SystemExit("analyze: need one of --model-dir, --model, "
                     "--program")


def _build_lenet(pt):
    from paddle_tpu.models import lenet

    main, _startup, feeds, loss, acc = lenet.build_program(pt)
    return main.desc, list(feeds), [loss.name, acc.name]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="analyze", description=__doc__)
    src = ap.add_argument_group("target")
    src.add_argument("--model-dir", help="saved-model dir holding "
                     "__model__")
    src.add_argument("--model", help="in-repo model builder (lenet)")
    src.add_argument("--program", help="raw ProgramDesc JSON file")
    ap.add_argument("--feeds", default=None,
                    help="comma-separated feed var names (default: the "
                    "model's saved feed_names)")
    ap.add_argument("--fetches", default=None,
                    help="comma-separated fetch var names (default: the "
                    "model's saved fetch_names)")
    ap.add_argument("--policy", default=None,
                    help="precision policy to audit under (f32|bf16|"
                    "mixed_bf16|mixed_f16; default f32)")
    ap.add_argument("--passes", default=None,
                    help="comma-separated pass subset (default: all)")
    ap.add_argument("--json", action="store_true",
                    help="findings as JSON lines instead of the table")
    ap.add_argument("--dot", default=None,
                    help="also render block 0 as DOT to this path")
    ap.add_argument("--max-findings", type=int, default=200)
    args = ap.parse_args(argv)

    desc, saved_feeds, saved_fetches = _load_target(args)
    feeds = (args.feeds.split(",") if args.feeds else saved_feeds)
    fetches = (args.fetches.split(",") if args.fetches
               else saved_fetches)

    from paddle_tpu import analysis

    passes = args.passes.split(",") if args.passes else None
    findings = analysis.run_passes(
        desc, feed_names=[f for f in feeds if f],
        fetch_names=[f for f in fetches if f],
        policy=args.policy, passes=passes, where="cli")

    if args.dot:
        # debugger.block_to_dot works on anything with .desc.vars/.ops;
        # wrap the raw BlockDesc in that shape
        from paddle_tpu import debugger

        class _B:
            def __init__(self, bdesc):
                self.desc = bdesc

        from paddle_tpu.resilience import atomic as _atomic

        _atomic.write_text(args.dot,
                           debugger.block_to_dot(_B(desc.block(0))))
        print(f"wrote {args.dot} (render: dot -Tpng {args.dot})",
              file=sys.stderr)

    shown = findings[:max(0, args.max_findings)]
    if args.json:
        for f in shown:
            print(json.dumps(f.to_dict()))
    else:
        n_ops = sum(len(b.ops) for b in desc.blocks)
        print(f"analyzed {n_ops} op(s) over {len(desc.blocks)} "
              f"block(s); feeds={feeds} fetches={fetches} "
              f"policy={args.policy or 'f32'}")
        if not findings:
            print("clean: no findings")
        for f in shown:
            print(f"  {f}")
        if len(findings) > len(shown):
            print(f"  ... {len(findings) - len(shown)} more "
                  f"(--max-findings)")
    errors = sum(1 for f in findings if f.severity == analysis.ERROR)
    if errors:
        print(f"{errors} error-severity finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
