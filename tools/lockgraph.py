#!/usr/bin/env python
"""lockgraph — static lock-order analysis over the paddle_tpu codebase.

The runtime sanitizer (`paddle_tpu/analysis/lockcheck.py`,
PADDLE_TPU_LOCKCHECK) catches the deadlock that actually forms; this
tool PROVES the absence of the class before anything runs. It walks
every `.py` file (reusing tools/lint.py's file walker and Finding
shape), infers a canonical identity for each lock, builds the
interprocedural held→acquired edge graph, and reports every cycle —
a potential lock-order inversion — as an error naming both
acquisition sites.

Lock identities
  `self._lock` assigned `threading.Lock()/RLock()/Condition()` (or the
  lockcheck factories) in class C of module m  →  `m.C._lock`
  module-level `_lock = threading.Lock()`      →  `m._lock`
  function-local locks                         →  `m.func._lock`
  `Condition(self._mu)` aliases to the wrapped lock's id (one
  identity, matching the runtime wrapper); a lockcheck factory's
  explicit `name="..."` literal wins over derivation, which is how the
  static ids and the runtime metric sites stay one naming scheme.

Edges
  direct lexical nesting (`with a: ... with b:`), `.acquire()` spans,
  and call-mediated acquisition: while holding `a`, calling a function
  whose transitive closure acquires `b` adds a→b. Calls resolve
  through self-methods (with base classes), same-module functions,
  `self.attr` objects of known class, and paddle_tpu-internal imports.

Escapes (each must carry a why)
  `# lock-order-exempt: <why>` on an acquisition line drops every edge
  through that site; `# lock-id: <id>` forces an unresolvable
  expression (`vs.lock` on a duck-typed local) onto a known identity,
  and `# lock-id: external` excludes one on purpose.

The ledger (tools/lock_order.json, shared with the runtime prong)
  {"order": [id, ...], "exempt_edges": [{"first","second","why"}]}
  `order` is the blessed global acquisition order: an edge the ledger
  orders the other way is an error even before it closes a cycle
  (another call path following the ledger would complete it).
  `--write-ledger` regenerates `order` from a topological sort of the
  current (cycle-free) graph.

Usage:
  lockgraph.py [paths...] [--json] [--graph] [--ledger PATH]
               [--write-ledger]
Exit code: 0 clean, 1 findings, 2 usage/cycle-on-write.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from lint import LintFinding, iter_py_files  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DEFAULT_TARGET = os.path.join(_REPO, "paddle_tpu")
DEFAULT_LEDGER = os.path.join(_REPO, "tools", "lock_order.json")

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
_EXEMPT_RE = re.compile(r"lock-order-exempt:\s*(\S.*)")
_LOCK_ID_RE = re.compile(r"lock-id:\s*([\w.<>\-]+)")


def _call_name(node: ast.Call) -> str:
    try:
        return ast.unparse(node.func)
    except Exception:
        return ""


def _module_id(rel: str) -> str:
    p = rel.replace(os.sep, "/")
    if p.endswith(".py"):
        p = p[:-3]
    if p.startswith("paddle_tpu/"):
        p = p[len("paddle_tpu/"):]
    if p.startswith(".."):  # fixture files outside the repo
        p = os.path.basename(p)
    return p.replace("/", ".")


def _is_lock_factory(name: str) -> Optional[str]:
    """'threading.Lock' / 'lockcheck.Condition' / bare 'RLock' →
    the primitive kind, else None."""
    parts = name.split(".")
    kind = parts[-1]
    if kind not in _LOCK_FACTORIES:
        return None
    recv = ".".join(parts[:-1])
    if recv in ("threading", "") or "lockcheck" in recv or recv == "_lc":
        return kind
    return None


class _FileInfo:
    """Everything phase A collects from one parsed source file."""

    def __init__(self, path: str, rel: str, src: str, tree: ast.AST):
        self.path = path
        self.rel = rel
        self.src = src
        self.lines = src.splitlines()
        self.tree = tree
        self.mod = _module_id(rel)
        self.mod_aliases: Dict[str, str] = {}      # alias -> module id
        self.sym_imports: Dict[str, Tuple[str, str]] = {}  # name -> (mod, n)
        self.classes: Dict[str, List[str]] = {}    # cname -> base exprs

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def marker(self, lineno: int, regex) -> Optional[str]:
        for ln in (lineno, lineno - 1):
            m = regex.search(self.line(ln))
            if m:
                return m.group(1)
        return None


class _Analysis:
    """The whole-corpus index and graph builder."""

    def __init__(self):
        self.files: List[_FileInfo] = []
        # (mod, cname or None, attr) -> lock id (pre-aliasing)
        self.lock_defs: Dict[Tuple[str, Optional[str], str], str] = {}
        self.lock_sites: Dict[str, Tuple[str, int]] = {}  # id -> def site
        self.aliases: Dict[str, str] = {}          # cond id -> lock id
        # function table: (mod, qualname) -> (ast node, class ctx, file)
        self.funcs: Dict[Tuple[str, str], Tuple[ast.AST, Optional[str],
                                                _FileInfo]] = {}
        # (mod, cname, attr) -> (mod2, cname2) for self.X = ClassName()
        self.attr_types: Dict[Tuple[str, str, str], Tuple[str, str]] = {}
        self.exempt_sites: Dict[Tuple[str, int], str] = {}  # site -> why
        # per-function scan results
        self.direct_acq: Dict[Tuple[str, str],
                              Dict[str, Tuple[str, int]]] = {}
        self.acq_events: Dict[Tuple[str, str], List[tuple]] = {}
        self.call_events: Dict[Tuple[str, str], List[tuple]] = {}

    # -- phase A: per-file definitions ---------------------------------

    def add_file(self, path: str, rel: str, src: str):
        tree = ast.parse(src, filename=path)
        fi = _FileInfo(path, rel, src, tree)
        self.files.append(fi)
        self._collect_imports(fi)
        self._collect_defs(fi)

    def _collect_imports(self, fi: _FileInfo):
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith("paddle_tpu."):
                        fi.mod_aliases[a.asname or a.name.split(".")[-1]] \
                            = a.name[len("paddle_tpu."):]
            elif isinstance(node, ast.ImportFrom):
                base = fi.mod.split(".")[:-1]
                if node.level:
                    # from ..x import y in module a.b: level 1 stays in
                    # a/, each extra level climbs one package
                    if node.level - 1 <= len(base):
                        base = base[:len(base) - (node.level - 1)]
                    else:
                        continue
                elif not (node.module or "").startswith("paddle_tpu"):
                    continue  # absolute non-internal import
                mod = node.module or ""
                if mod.startswith("paddle_tpu"):
                    mod = mod[len("paddle_tpu"):].lstrip(".")
                    base = []
                target = ".".join([p for p in base + mod.split(".") if p])
                for a in node.names:
                    local = a.asname or a.name
                    # `from ..observability import metrics` imports a
                    # MODULE; `from .errors import PSTimeoutError` a
                    # symbol — disambiguated in phase B once every
                    # module id is known (store both candidates)
                    fi.mod_aliases.setdefault(
                        local, f"{target}.{a.name}" if target else a.name)
                    fi.sym_imports.setdefault(local, (target, a.name))

    def _collect_defs(self, fi: _FileInfo):
        mod = fi.mod
        for node in fi.tree.body:
            if isinstance(node, ast.ClassDef):
                fi.classes[node.name] = [
                    ast.unparse(b) if not isinstance(b, ast.Name) else b.id
                    for b in node.bases]
                self._collect_class_defs(fi, node)
            elif isinstance(node, ast.Assign):
                self._maybe_lock_def(fi, node, cname=None)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs[(mod, node.name)] = (node, None, fi)
        # nested functions (thread bodies, closures): scanned for their
        # own direct edges, not resolvable as callees
        parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(fi.tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        for node in ast.walk(fi.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            qual, cls_ctx, p = [node.name], None, parents.get(node)
            while p is not None and not isinstance(p, ast.Module):
                if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = [p.name, "<locals>"] + qual
                elif isinstance(p, ast.ClassDef):
                    if cls_ctx is None:
                        cls_ctx = p.name
                    qual = [p.name] + qual
                p = parents.get(p)
            key = (mod, ".".join(qual))
            if key not in self.funcs:
                self.funcs[key] = (node, cls_ctx, fi)

    def _collect_class_defs(self, fi: _FileInfo, cls: ast.ClassDef):
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                self._maybe_lock_def(fi, node, cname=cls.name)
                self._maybe_attr_type(fi, node, cls.name)
            elif isinstance(node, ast.AnnAssign):
                self._maybe_ann_attr_type(fi, node, cls.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs.setdefault(
                    (fi.mod, f"{cls.name}.{node.name}"),
                    (node, cls.name, fi))

    def _maybe_lock_def(self, fi: _FileInfo, node: ast.Assign,
                        cname: Optional[str]):
        if not isinstance(node.value, ast.Call):
            return
        kind = _is_lock_factory(_call_name(node.value))
        if kind is None:
            return
        for t in node.targets:
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self" and cname:
                key = (fi.mod, cname, t.attr)
                lid = f"{fi.mod}.{cname}.{t.attr}"
            elif isinstance(t, ast.Name):
                key = (fi.mod, cname, t.id)
                lid = (f"{fi.mod}.{cname}.{t.id}" if cname
                       else f"{fi.mod}.{t.id}")
            else:
                continue
            # an explicit lockcheck name= literal IS the id
            for kw in node.value.keywords:
                if kw.arg == "name" \
                        and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    lid = kw.value.value
            self.lock_defs[key] = lid
            self.lock_sites.setdefault(lid, (fi.rel, node.lineno))
            if kind == "Condition" and node.value.args:
                arg = node.value.args[0]
                if isinstance(arg, ast.Attribute) \
                        and isinstance(arg.value, ast.Name) \
                        and arg.value.id == "self" and cname:
                    self.aliases[lid] = (fi.mod, cname, arg.attr)

    def _maybe_attr_type(self, fi: _FileInfo, node: ast.Assign,
                         cname: str):
        if not isinstance(node.value, ast.Call):
            return
        name = _call_name(node.value)
        target_cls = self._resolve_class_name(fi, name)
        if target_cls is None:
            return
        for t in node.targets:
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                self.attr_types[(fi.mod, cname, t.attr)] = target_cls

    def _maybe_ann_attr_type(self, fi: _FileInfo, node: ast.AnnAssign,
                             cname: str):
        """`self._decode: Optional[DecodeEngine] = decode` — the
        annotation types an attribute the VALUE cannot (a constructor
        parameter, a late None). Every Name / string constant inside
        the annotation is tried against the class index; first
        resolvable wins."""
        t = node.target
        if not (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id == "self"):
            return
        key = (fi.mod, cname, t.attr)
        for n in ast.walk(node.annotation):
            cand = None
            if isinstance(n, ast.Name):
                cand = n.id
            elif isinstance(n, ast.Constant) and isinstance(n.value, str):
                cand = n.value.split(".")[-1]  # forward-ref string
            if not cand or cand in ("Optional", "None", "List", "Dict",
                                    "Tuple", "Sequence", "Callable"):
                continue
            resolved = self._resolve_class_name(fi, cand)
            if resolved is not None:
                self.attr_types.setdefault(key, resolved)
                return

    def _resolve_class_name(self, fi: _FileInfo, name: str
                            ) -> Optional[Tuple[str, str]]:
        parts = name.split(".")
        if len(parts) == 1:
            if parts[0] in fi.classes:
                return (fi.mod, parts[0])
            if parts[0] in fi.sym_imports:
                m2, n2 = fi.sym_imports[parts[0]]
                return (m2, n2)  # verified against the index in phase C
        elif len(parts) == 2 and parts[0] in fi.mod_aliases:
            return (fi.mod_aliases[parts[0]], parts[1])
        return None

    # -- phase B: finalize identities ----------------------------------

    def finalize(self):
        module_ids = {f.mod for f in self.files}
        for fi in self.files:
            # an alias that names a real module is a module alias; one
            # that doesn't falls back to its symbol-import reading
            fi.mod_aliases = {a: m for a, m in fi.mod_aliases.items()
                              if m in module_ids}
        self._class_index = {}
        for fi in self.files:
            for cname, bases in fi.classes.items():
                self._class_index[(fi.mod, cname)] = (bases, fi)

    def _find_method(self, mod: str, cname: str, meth: str,
                     depth: int = 0) -> Optional[Tuple[str, str]]:
        if depth > 5:
            return None
        key = (mod, f"{cname}.{meth}")
        if key in self.funcs:
            return key
        entry = self._class_index.get((mod, cname))
        if entry is None:
            return None
        bases, fi = entry
        for b in bases:
            base_cls = self._resolve_class_name(fi, b)
            if base_cls and base_cls in self._class_index:
                found = self._find_method(base_cls[0], base_cls[1],
                                          meth, depth + 1)
                if found:
                    return found
        return None

    # -- phase C: scan function bodies ---------------------------------

    def scan_all(self):
        for key, (node, cls_ctx, fi) in self.funcs.items():
            self._scan_func(key, node, cls_ctx, fi)

    def _scan_func(self, key, node, cls_ctx, fi: _FileInfo):
        acqs: List[tuple] = []   # (lock_id, lineno, held tuple)
        calls: List[tuple] = []  # (callee key, lineno, held tuple)
        local_locks: Dict[str, str] = {}
        soft_held: List[Tuple[str, int]] = []

        def resolve_lock(expr) -> Optional[str]:
            forced = fi.marker(expr.lineno, _LOCK_ID_RE)
            if forced:
                return None if forced in ("external", "none") else forced
            lid = None
            if isinstance(expr, ast.Attribute) \
                    and isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self" and cls_ctx:
                lid = self._lookup_attr_lock(fi.mod, cls_ctx, expr.attr)
            elif isinstance(expr, ast.Name):
                lid = local_locks.get(expr.id) \
                    or self.lock_defs.get((fi.mod, None, expr.id))
            if lid is None:
                return None
            return self._canon_id(lid)

        def record_acq(lid: str, lineno: int, held):
            acqs.append((lid, lineno, tuple(held)))

        def visit(n, held):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                return  # separate scan / separate scope
            if isinstance(n, (ast.With, ast.AsyncWith)):
                new_held = list(held)
                for item in n.items:
                    visit(item.context_expr, tuple(new_held))
                    lid = resolve_lock(item.context_expr)
                    if lid:
                        record_acq(lid, item.context_expr.lineno,
                                   tuple(new_held) + tuple(soft_held))
                        new_held.append((lid, item.context_expr.lineno))
                for st in n.body:
                    visit(st, tuple(new_held))
                return
            if isinstance(n, ast.Assign) \
                    and isinstance(n.value, ast.Call) \
                    and _is_lock_factory(_call_name(n.value)):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        local_locks[t.id] = \
                            f"{fi.mod}.{key[1]}.{t.id}"
                        self.lock_sites.setdefault(
                            local_locks[t.id], (fi.rel, n.lineno))
            if isinstance(n, ast.Call):
                name = _call_name(n)
                recv, _, attr = name.rpartition(".")
                if attr in ("acquire", "release") and recv:
                    lid = resolve_lock(n.func.value) \
                        if isinstance(n.func, ast.Attribute) else None
                    if lid:
                        if attr == "acquire":
                            record_acq(lid, n.lineno,
                                       tuple(held) + tuple(soft_held))
                            soft_held.append((lid, n.lineno))
                        else:
                            for i in range(len(soft_held) - 1, -1, -1):
                                if soft_held[i][0] == lid:
                                    del soft_held[i]
                                    break
                else:
                    callee = self._resolve_call(fi, cls_ctx, name)
                    if callee:
                        calls.append((callee, n.lineno,
                                      tuple(held) + tuple(soft_held)))
            # soft-held (.acquire() spans) merges at EVENT points only;
            # the recursion parameter carries just the lexical with-stack
            for child in ast.iter_child_nodes(n):
                visit(child, held)

        for st in node.body:
            visit(st, ())

        for lid, lineno, held in acqs:
            why = fi.marker(lineno, _EXEMPT_RE)
            if why:
                self.exempt_sites[(fi.rel, lineno)] = why
        self.acq_events[key] = acqs
        self.call_events[key] = calls
        direct = {}
        for lid, lineno, _held in acqs:
            direct.setdefault(lid, (fi.rel, lineno))
        self.direct_acq[key] = direct

    def _lookup_attr_lock(self, mod, cname, attr,
                          depth: int = 0) -> Optional[str]:
        if depth > 5:
            return None
        lid = self.lock_defs.get((mod, cname, attr))
        if lid:
            return lid
        entry = self._class_index.get((mod, cname))
        if entry is None:
            return None
        bases, fi = entry
        for b in bases:
            base_cls = self._resolve_class_name(fi, b)
            if base_cls:
                lid = self._lookup_attr_lock(base_cls[0], base_cls[1],
                                             attr, depth + 1)
                if lid:
                    return lid
        return None

    def _canon_id(self, lid: str) -> str:
        seen = set()
        while lid in self.aliases and lid not in seen:
            seen.add(lid)
            target_key = self.aliases[lid]
            resolved = self.lock_defs.get(target_key)
            if not resolved or resolved == lid:
                break
            lid = resolved
        return lid

    def _resolve_call(self, fi: _FileInfo, cls_ctx, name: str
                      ) -> Optional[Tuple[str, str]]:
        parts = name.split(".")
        if parts[0] == "self" and cls_ctx:
            if len(parts) == 2:
                return self._find_method(fi.mod, cls_ctx, parts[1])
            if len(parts) == 3:
                t = self.attr_types.get((fi.mod, cls_ctx, parts[1]))
                if t and t in self._class_index:
                    return self._find_method(t[0], t[1], parts[2])
            return None
        if len(parts) == 1:
            n = parts[0]
            if (fi.mod, n) in self.funcs:
                return (fi.mod, n)
            if n in fi.classes:
                return self._find_method(fi.mod, n, "__init__")
            if n in fi.sym_imports:
                m2, n2 = fi.sym_imports[n]
                if (m2, n2) in self.funcs:
                    return (m2, n2)
                if (m2, n2) in self._class_index:
                    return self._find_method(m2, n2, "__init__")
            return None
        if len(parts) == 2:
            m2 = fi.mod_aliases.get(parts[0])
            if m2:
                if (m2, parts[1]) in self.funcs:
                    return (m2, parts[1])
                if (m2, parts[1]) in self._class_index:
                    return self._find_method(m2, parts[1], "__init__")
            # ClassName.method(...) in the same module
            if parts[0] in fi.classes:
                return self._find_method(fi.mod, parts[0], parts[1])
        return None

    # -- phase D: transitive closure + edges ---------------------------

    def build_edges(self) -> Dict[Tuple[str, str], List[dict]]:
        trans = {k: dict(v) for k, v in self.direct_acq.items()}
        changed = True
        while changed:
            changed = False
            for k, calls in self.call_events.items():
                mine = trans[k]
                for callee, _ln, _held in calls:
                    for lid, site in trans.get(callee, {}).items():
                        if lid not in mine:
                            mine[lid] = site
                            changed = True
        edges: Dict[Tuple[str, str], List[dict]] = {}

        def add(a, a_site, b, b_site, via):
            if a == b:
                return
            if a_site in self.exempt_sites or b_site in self.exempt_sites:
                return
            edges.setdefault((a, b), []).append(
                {"from": a_site, "to": b_site, "via": via})

        for key, acqs in self.acq_events.items():
            fi = self.funcs[key][2]
            for lid, lineno, held in acqs:
                for h, h_ln in held:
                    add(h, (fi.rel, h_ln), lid, (fi.rel, lineno),
                        "nested with")
            for callee, lineno, held in self.call_events[key]:
                if (fi.rel, lineno) in self.exempt_sites:
                    continue
                for b, b_site in trans.get(callee, {}).items():
                    for h, h_ln in held:
                        add(h, (fi.rel, h_ln), b, b_site,
                            f"call {callee[0]}.{callee[1]} "
                            f"({fi.rel}:{lineno})")
        return edges


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------


def load_ledger(path: Optional[str]) -> dict:
    if not path:
        return {"order": [], "exempt_edges": []}
    try:
        with open(path) as f:
            data = json.load(f)
    except FileNotFoundError:
        return {"order": [], "exempt_edges": []}
    return {"order": list(data.get("order", [])),
            "exempt_edges": list(data.get("exempt_edges", []))}


def _site_str(site: Tuple[str, int]) -> str:
    return f"{site[0]}:{site[1]}"


# ---------------------------------------------------------------------------
# cycles
# ---------------------------------------------------------------------------


def _find_cycles(edges: Dict[Tuple[str, str], List[dict]]
                 ) -> List[List[str]]:
    """Every elementary cycle's node list (Tarjan SCCs, then one DFS
    cycle per non-trivial SCC — enough to make the report actionable
    without enumerating the exponential set)."""
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Dict[str, bool] = {}
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v):
        # iterative Tarjan (deep graphs must not hit the recursion cap)
        work = [(v, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack[node] = True
            advanced = False
            for i in range(pi, len(adj[node])):
                w = adj[node][i]
                if w not in index:
                    work[-1] = (node, i + 1)
                    work.append((w, 0))
                    advanced = True
                    break
                if on_stack.get(w):
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    for v in adj:
        if v not in index:
            strongconnect(v)

    cycles = []
    for comp in sccs:
        if len(comp) == 1:
            if comp[0] in adj and comp[0] in adj.get(comp[0], []):
                cycles.append([comp[0]])
            continue
        comp_set = set(comp)
        start = sorted(comp)[0]
        # DFS WITH BACKTRACKING for one elementary cycle through
        # `start` (a greedy walk can dead-end on a branch whose
        # successors are all already on the path — e.g. A->B->C with
        # C->B only — and an SCC guarantees a cycle exists, so
        # backtrack instead of crashing)
        path, on_path = [start], {start}
        iters = [iter(sorted(w for w in adj[start] if w in comp_set))]
        found = None
        while iters and found is None:
            try:
                w = next(iters[-1])
            except StopIteration:
                iters.pop()
                on_path.discard(path.pop())
                continue
            if w == start:
                found = list(path)
            elif w not in on_path:
                path.append(w)
                on_path.add(w)
                iters.append(iter(sorted(x for x in adj[w]
                                         if x in comp_set)))
        cycles.append(found if found else sorted(comp))
    return cycles


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def build(paths: Optional[Sequence[str]] = None) -> _Analysis:
    paths = list(paths) if paths else [_DEFAULT_TARGET]
    an = _Analysis()
    skipped = []
    for path in iter_py_files(paths):
        rel = os.path.relpath(path, _REPO)
        try:
            with open(path) as fh:
                an.add_file(path, rel, fh.read())
        except (OSError, SyntaxError) as e:
            skipped.append((rel, e))
    an.finalize()
    an.scan_all()
    an.skipped = skipped
    return an


def analyze(paths: Optional[Sequence[str]] = None,
            ledger_path: Optional[str] = DEFAULT_LEDGER
            ) -> List[LintFinding]:
    """Run the analysis; findings are lock-order cycles (errors), edges
    contradicting the ledger's blessed order, and files that failed to
    parse. Empty list == provably consistent ordering (up to the
    documented resolution limits)."""
    an = build(paths)
    ledger = load_ledger(ledger_path)
    edges = an.build_edges()
    exempt_pairs = {(e.get("first"), e.get("second"))
                    for e in ledger["exempt_edges"]}
    edges = {pair: occ for pair, occ in edges.items()
             if pair not in exempt_pairs}

    findings: List[LintFinding] = []
    for rel, e in an.skipped:
        findings.append(LintFinding(
            rel, getattr(e, "lineno", 0) or 0, "lock-parse",
            f"could not analyze: {type(e).__name__}: {e}"))

    for cyc in _find_cycles(edges):
        hops = []
        anchor = None
        for i, a in enumerate(cyc):
            b = cyc[(i + 1) % len(cyc)]
            occ = edges.get((a, b), [{}])[0]
            f_site = occ.get("from", ("?", 0))
            t_site = occ.get("to", ("?", 0))
            if anchor is None:
                anchor = t_site
            hops.append(f"{a} (held at {_site_str(f_site)}) -> {b} "
                        f"(acquired at {_site_str(t_site)}, "
                        f"via {occ.get('via', '?')})")
        findings.append(LintFinding(
            anchor[0], anchor[1], "lock-cycle",
            "potential deadlock: lock-order cycle "
            + " ; ".join(hops)
            + " — fix the acquisition order, or exempt one edge in "
              "tools/lock_order.json / '# lock-order-exempt: <why>'"))

    order_idx = {lid: i for i, lid in enumerate(ledger["order"])}
    for (a, b), occ in sorted(edges.items()):
        ia, ib = order_idx.get(a), order_idx.get(b)
        if ia is None or ib is None or ia < ib:
            continue
        site = occ[0]["to"]
        findings.append(LintFinding(
            site[0], site[1], "lock-ledger",
            f"acquisition order {a} -> {b} contradicts the blessed "
            f"ledger order (lock_order.json says {b} before {a}; "
            f"first seen held at {_site_str(occ[0]['from'])}, acquired "
            f"at {_site_str(site)} via {occ[0]['via']})"))
    findings.sort(key=lambda x: (x.path, x.lineno, x.pass_name))
    return findings


def write_ledger(paths: Optional[Sequence[str]] = None,
                 ledger_path: str = DEFAULT_LEDGER) -> dict:
    """Regenerate `order` from a topological sort of the current graph
    (preserving exempt_edges). Raises on a cyclic graph — fix or
    exempt the cycles first, the ledger blesses only a real order."""
    an = build(paths)
    ledger = load_ledger(ledger_path)
    edges = an.build_edges()
    exempt_pairs = {(e.get("first"), e.get("second"))
                    for e in ledger["exempt_edges"]}
    edges = {p: o for p, o in edges.items() if p not in exempt_pairs}
    if _find_cycles(edges):
        raise RuntimeError("graph has cycles; run `lockgraph.py` and "
                           "fix/exempt them before --write-ledger")
    nodes = sorted({n for pair in edges for n in pair})
    indeg = {n: 0 for n in nodes}
    for _a, b in edges:
        indeg[b] += 1
    order: List[str] = []
    ready = sorted(n for n, d in indeg.items() if d == 0)
    while ready:
        n = ready.pop(0)
        order.append(n)
        for (a, b) in edges:
            if a == n:
                indeg[b] -= 1
                if indeg[b] == 0 and b not in order and b not in ready:
                    ready.append(b)
        ready.sort()
    ledger["order"] = order
    ledger["_comment"] = (
        "Blessed global lock-acquisition order, generated by "
        "`tools/lockgraph.py --write-ledger` from the observed "
        "held->acquired graph. Locks must be taken in list order; the "
        "runtime sanitizer (PADDLE_TPU_LOCKCHECK) counts any observed "
        "contradiction as an inversion. exempt_edges suppress "
        "individually-justified edges from both prongs.")
    tmp = ledger_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"_comment": ledger["_comment"],
                   "order": ledger["order"],
                   "exempt_edges": ledger["exempt_edges"]}, f, indent=1)
        f.write("\n")
    os.replace(tmp, ledger_path)
    return ledger


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="lockgraph", description=__doc__)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: paddle_tpu/)")
    ap.add_argument("--ledger", default=DEFAULT_LEDGER,
                    help="lock_order.json path")
    ap.add_argument("--json", action="store_true",
                    help="findings as JSON lines")
    ap.add_argument("--graph", action="store_true",
                    help="dump every held->acquired edge and exit")
    ap.add_argument("--write-ledger", action="store_true",
                    help="regenerate the ledger's blessed order from "
                         "the (cycle-free) graph")
    args = ap.parse_args(argv)

    if args.graph:
        an = build(args.paths or None)
        for (a, b), occ in sorted(an.build_edges().items()):
            o = occ[0]
            print(f"{a} -> {b}   [{_site_str(o['from'])} -> "
                  f"{_site_str(o['to'])}; {o['via']}; "
                  f"x{len(occ)} site(s)]")
        return 0
    if args.write_ledger:
        try:
            ledger = write_ledger(args.paths or None, args.ledger)
        except RuntimeError as e:
            print(f"lockgraph: {e}", file=sys.stderr)
            return 2
        print(f"wrote {args.ledger} ({len(ledger['order'])} locks)")
        return 0

    findings = analyze(args.paths or None, args.ledger)
    for f in findings:
        print(json.dumps(f.to_dict()) if args.json else str(f))
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
