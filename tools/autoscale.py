#!/usr/bin/env python
"""autoscale — run a serving fleet with elastic autoscaling.

Operator entry for the fleet tier (SERVING.md §Fleet): boots a
ReplicaSupervisor (N warmstart-booted replica processes heartbeating
into a shared rendezvous store), a Router + RouterServer HTTP front on
--port, and the Autoscaler control loop that moves the replica count
within [--min, --max] on queue-depth/p99 with hysteresis.

    python tools/autoscale.py --model-dir M [--warmstart ART] \
        [--replicas 2] [--min 1] [--max 4] [--port 8600] \
        [--high-load 4] [--low-load 0.5] [--p99-high-ms 500] \
        [--rdzv-dir DIR] [--cpu] [--duration 0]

Prints one JSON status line per --status-every seconds (replica set,
per-replica health/load, router outcome counts, autoscaler actions).
--duration 0 runs until Ctrl-C; the shutdown path drains every replica
gracefully. `tools/obsdump.py fleet` renders the same story offline
from a metrics snapshot.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def _build_args(argv=None):
    ap = argparse.ArgumentParser(prog="autoscale", description=__doc__)
    ap.add_argument("--model-dir", required=True)
    ap.add_argument("--warmstart", default="",
                    help="PR 6 warmstart artifact replicas boot from "
                    "(scale-out serves in seconds)")
    ap.add_argument("--buckets", default="")
    ap.add_argument("--replicas", type=int, default=2,
                    help="initial replica count")
    ap.add_argument("--min", type=int, default=1, dest="min_replicas")
    ap.add_argument("--max", type=int, default=4, dest="max_replicas")
    ap.add_argument("--port", type=int, default=8600,
                    help="router HTTP port (0 = ephemeral)")
    ap.add_argument("--rdzv-dir", default="",
                    help="shared membership store (default: temp dir)")
    ap.add_argument("--high-load", type=float, default=4.0)
    ap.add_argument("--low-load", type=float, default=0.5)
    ap.add_argument("--p99-high-ms", type=float, default=None)
    ap.add_argument("--interval-s", type=float, default=0.5)
    ap.add_argument("--out-cooldown-s", type=float, default=5.0)
    ap.add_argument("--in-cooldown-s", type=float, default=10.0)
    ap.add_argument("--max-respawns", type=int, default=3)
    ap.add_argument("--log-dir", default="")
    ap.add_argument("--status-every", type=float, default=2.0)
    ap.add_argument("--duration", type=float, default=0.0,
                    help="seconds to run (0 = until Ctrl-C)")
    ap.add_argument("--cpu", action="store_true",
                    help="CPU replicas (fleet simulation)")
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = _build_args(argv)
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from paddle_tpu.distributed.launch_serve import (ReplicaSpec,
                                                     ReplicaSupervisor)
    from paddle_tpu.serving.autoscale import Autoscaler
    from paddle_tpu.serving.router import Router, RouterServer

    rdzv_dir = args.rdzv_dir or tempfile.mkdtemp(prefix="fleet_rdzv_")
    spec = ReplicaSpec(args.model_dir,
                       warmstart=args.warmstart or None,
                       buckets=args.buckets or None, cpu=args.cpu)
    sup = ReplicaSupervisor(spec, rdzv_dir, replicas=args.replicas,
                            max_respawns=args.max_respawns,
                            log_dir=args.log_dir or None)
    router = Router(rdzv_dir=rdzv_dir)
    front = RouterServer(router)
    scaler = Autoscaler(router, sup,
                        min_replicas=args.min_replicas,
                        max_replicas=args.max_replicas,
                        high_load=args.high_load,
                        low_load=args.low_load,
                        p99_high_ms=args.p99_high_ms,
                        interval_s=args.interval_s,
                        out_cooldown_s=args.out_cooldown_s,
                        in_cooldown_s=args.in_cooldown_s)
    sup.start()
    port = front.start(args.port)
    scaler.start()
    print(json.dumps({"fleet": "up", "router_port": port,
                      "rdzv_dir": rdzv_dir,
                      "replicas": sup.endpoints()}), flush=True)
    t_end = time.monotonic() + args.duration if args.duration else None
    try:
        while t_end is None or time.monotonic() < t_end:
            time.sleep(args.status_every)
            st = router.status()
            print(json.dumps({
                "ts": round(time.time(), 3),
                "replicas": st["world_size"],
                "healthy": st["healthy"],
                "requests": st["requests"],
                "retries": st["retries"],
                "recent_p99_ms": st["recent_p99_ms"],
                "autoscaler": scaler.status()["actions"],
            }), flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        scaler.stop()
        front.stop()
        sup.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
