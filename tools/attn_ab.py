"""A/B attention paths on the real chip: XLA bf16-scores vs legacy flash
vs splash (several block configs), fwd+bwd, at long sequence lengths.

Usage: python tools/attn_ab.py [T ...]   (default 1024 2048 4096 8192)

Timing protocol (see memory: tunneled backend adds ~100 ms per jitted
invocation): each measurement scan-chains ITERS attention fwd+bwd passes
inside ONE jit and divides; the carry feeds dq back into q so XLA cannot
dead-code or constant-fold any iteration. Numbers are per fwd+bwd pass.
"""

from __future__ import annotations

import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

ITERS = 16
N_HEADS, HEAD_DIM = 12, 64


def xla_attn(q, k, v, scale, causal):
    from paddle_tpu.ops.pallas.attention import _xla_mha, _merge_causal
    mask = _merge_causal(None, q.shape[1]) if causal else None
    return _xla_mha(q, k, v, mask, scale)


def legacy_flash(q, k, v, scale, causal):
    from jax.experimental.pallas.ops.tpu.flash_attention import flash_attention
    out = flash_attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                          v.transpose(0, 2, 1, 3), causal=causal,
                          sm_scale=float(scale))
    return out.transpose(0, 2, 1, 3)


def splash_kernel(T, n_heads, causal, bq, bkv, bqb, bkvb, fused):
    # fresh per call — caching the kernel pytree across traces leaks
    # tracer-wrapped mask-info arrays (UnexpectedTracerError in bwd)
    from jax.experimental.pallas.ops.tpu import splash_attention as sa
    kw = dict(block_q=bq, block_kv=bkv, block_kv_compute=bkv,
              block_q_dkv=bqb, block_kv_dkv=bkvb, block_kv_dkv_compute=bkvb)
    if fused:
        sizes = sa.BlockSizes(use_fused_bwd_kernel=True, **kw)
    else:
        sizes = sa.BlockSizes(block_q_dq=bqb, block_kv_dq=bkvb, **kw)
    one = sa.CausalMask((T, T)) if causal else sa.FullMask((T, T))
    return sa.make_splash_mha(sa.MultiHeadMask([one] * n_heads),
                              head_shards=1, q_seq_shards=1,
                              block_sizes=sizes)


def splash_attn(q, k, v, scale, causal, cfg):
    kernel = splash_kernel(q.shape[1], q.shape[2], causal, *cfg)
    qt = (q * jnp.asarray(scale, q.dtype)).transpose(0, 2, 1, 3)
    out = jax.vmap(kernel)(qt, k.transpose(0, 2, 1, 3),
                           v.transpose(0, 2, 1, 3))
    return out.transpose(0, 2, 1, 3)


def measure(name, fn, B, T, causal):
    scale = 1.0 / math.sqrt(HEAD_DIM)
    shape = (B, T, N_HEADS, HEAD_DIM)
    key = jax.random.key(0)
    ks = jax.random.split(key, 4)
    q, k, v, ct = (jax.random.normal(kk, shape, jnp.bfloat16) for kk in ks)

    def one(q, k, v, ct):
        out, vjp = jax.vjp(lambda a, b, c: fn(a, b, c, scale, causal), q, k, v)
        dq, dk, dv = vjp(ct)
        return dq, out

    @jax.jit
    def chain(q, k, v, ct):
        def body(carry, _):
            q, _ = carry
            dq, out = one(q, k, v, ct)
            # feed dq back so iterations serialize; renormalize to avoid
            # bf16 overflow across 16 chained vjps
            qn = dq / jnp.maximum(jnp.abs(dq).max(), 1e-3).astype(dq.dtype)
            return (qn, out.mean()), None
        (qf, m), _ = jax.lax.scan(body, (q, 0.0), None, length=ITERS)
        return m

    try:
        m = chain(q, k, v, ct)
        float(m)  # sync (block_until_ready lies on the tunnel)
        t0 = time.perf_counter()
        m = chain(q, k, v, ct)
        float(m)
        dt = (time.perf_counter() - t0) / ITERS
        print(f"  {name:34s} {1000*dt:8.2f} ms/pass", flush=True)
        return dt
    except Exception as e:
        print(f"  {name:34s} FAIL: {str(e)[:110]}", flush=True)
        return None


def main():
    Ts = [int(a) for a in sys.argv[1:]] or [1024, 2048, 4096, 8192]
    cfgs = {
        "splash-def128": (128, 128, 128, 128, False),
        "splash-512/1024": (512, 1024, 512, 512, False),
        "splash-512/512-fused": (512, 512, 512, 512, True),
        "splash-1024/2048": (1024, 2048, 512, 1024, False),
    }
    for T in Ts:
        B = max(1, 2 ** 25 // (T * T // 128))  # keep score bytes bounded
        B = min(B, 8)
        for causal in (False, True):
            print(f"T={T} B={B} causal={causal}", flush=True)
            measure("xla_bf16", xla_attn, B, T, causal)
            if not causal:
                measure("legacy_flash", legacy_flash, B, T, causal)
            for cname, cfg in cfgs.items():
                if cfg[0] > T or cfg[1] > T:
                    continue
                measure(cname, lambda q, k, v, s, c, _cfg=cfg:
                        splash_attn(q, k, v, s, c, _cfg), B, T, causal)


if __name__ == "__main__":
    from paddle_tpu.core.tpu_lock import tpu_singleflight

    with tpu_singleflight():  # one real chip: serialize vs bench/tools
        main()
