"""ResNet-50 per-tensor HBM bytes table (VERDICT r5 item 5).

The round-3 roofline (PROFILE.md, tools/rn50_roofline.py) showed the
training step pinned to the HBM ceiling: 78 GB modeled traffic -> 95 ms
byte floor vs 103 ms measured. The verdict's follow-up: bytes, not
FLOPs, are the budget — so itemize them per tensor and quantify every
attackable slice (bf16 optimizer state / master params, BN-pass fusion,
residual traffic, batch scaling), or concede the measured 0.304 MFU is
this part's ceiling for bs=256.

Pure analysis (no chip needed): the byte model is exactly
tools/rn50_roofline.py's stated pass-count model (validated there
against measured per-stage GB/s at 93-126% of nominal peak), broken to
per-tensor granularity and per-category attack surfaces.

Pass model per conv+BN+relu unit (bf16 activations/weights):
  fwd : conv(read in, read W, write out) + BN stats(read out)
        + BN apply(read out, write out)
  bwd : BN/relu bwd(read g, read act, write g)
        + dgrad(read g, read W, write gx) + wgrad(read g, read act)
  residual (per block): +3 out-sized passes
Categories:
  conv-io   3*a_in + 3*a_out   irreducible conv traffic (in/out/grads)
  bn        6*a_out            stats read + apply r/w + bwd r/r/w
  residual  3*a_out            skip add fwd/bwd
  weights   2*wb               fwd + dgrad weight reads
  optimizer f32 master+momentum read/write + f32 grad + bf16 cast
"""

import importlib.util
import json
import os

BS = 256
BF = 2           # bf16 activation/weight bytes
F32 = 4


def _load_device_peaks():
    """File-path import of the shared per-device-kind peak table
    (stdlib-only by contract) — this tool must run without the
    paddle_tpu package (and its jax import) on sys.path."""
    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                     "paddle_tpu", "observability", "device_peaks.py")
    spec = importlib.util.spec_from_file_location("_rn50_device_peaks", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_V5E = _load_device_peaks().lookup("TPU v5 lite")
PEAK_BW = _V5E.hbm_bytes_per_s
PEAK_TF = _V5E.flops
STEP_FLOPS = 6.281e12       # exact conv sum, tools/rn50_roofline.py (bs=256)
MEASURED_MS = 103.0          # BENCH_r03 step (one-pass BN, NHWC)


def conv_unit(name, h, cin, cout, kh, kw, st):
    oh = h // st
    a_in = BS * h * h * cin * BF
    a_out = BS * oh * oh * cout * BF
    wb = kh * kw * cin * cout * BF
    return {
        "name": name, "shape": f"{h}²×{cin}→{oh}²×{cout} {kh}x{kw}/{st}",
        "params": kh * kw * cin * cout,
        "out_mb": a_out / 1e6,
        "conv_io": 3 * a_in + 3 * a_out,
        "bn": 6 * a_out,
        "weights": 2 * wb,
    }


def build_units():
    units = []
    units.append(conv_unit("stem", 224, 3, 64, 7, 7, 2))
    # maxpool: fwd read 112² write 56², bwd ~2 passes (select-and-scatter)
    mp = BS * 112 * 112 * 64 * BF
    units.append({"name": "maxpool", "shape": "112²×64→56²×64",
                  "params": 0, "out_mb": mp / 4 / 1e6,
                  "conv_io": mp + mp // 4 + 2 * (mp // 4), "bn": 0,
                  "weights": 0})
    h, c = 56, 64
    residual = 0.0
    for gi, blocks in ((0, 3), (1, 4), (2, 6), (3, 3)):
        mid = 64 * (2 ** gi)
        cout = mid * 4
        for bi in range(blocks):
            st = 2 if (bi == 0 and gi > 0) else 1
            pre = f"g{gi}b{bi}"
            units.append(conv_unit(f"{pre}.c1", h, c, mid, 1, 1, 1))
            units.append(conv_unit(f"{pre}.c2", h, mid, mid, 3, 3, st))
            oh = h // st
            units.append(conv_unit(f"{pre}.c3", oh, mid, cout, 1, 1, 1))
            if bi == 0:
                units.append(conv_unit(f"{pre}.proj", h, c, cout, 1, 1,
                                       st))
            residual += 3 * BS * oh * oh * cout * BF
            h, c = oh, cout
    # head: GAP + fc(2048->1000) + softmax/loss — noise-level bytes
    units.append({"name": "head", "shape": "7²×2048→1000",
                  "params": 2048 * 1000, "out_mb": 0.5,
                  "conv_io": 3 * BS * 2048 * BF + 3 * BS * 1000 * F32,
                  "bn": 0, "weights": 2 * 2048 * 1000 * BF})
    return units, residual


def main():
    units, residual = build_units()
    n_params = sum(u["params"] for u in units) \
        + 53 * 2 * 256  # BN scale/shift approx (gamma/beta per conv)
    conv_io = sum(u["conv_io"] for u in units)
    bn = sum(u["bn"] for u in units)
    weights = sum(u["weights"] for u in units)
    # optimizer: read f32 master + f32 momentum, write both, read f32
    # wgrad, write bf16 compute copy
    opt = n_params * (4 * F32 + F32 + BF)
    total = conv_io + bn + residual + weights + opt

    def ms(bytes_):
        return bytes_ / PEAK_BW * 1e3

    def mfu(bytes_):
        return STEP_FLOPS / (bytes_ / PEAK_BW) / PEAK_TF

    print("## ResNet-50 per-tensor HBM bytes (bs=256 NHWC bf16, "
          "pass model = rn50_roofline.py)\n")
    print("| unit | shape | out MB | conv-io GB | BN GB | weights MB |")
    print("|---|---|---|---|---|---|")
    groups = {}
    for u in units:
        key = u["name"].split("b")[0].split(".")[0]
        g = groups.setdefault(key, {"conv_io": 0, "bn": 0, "weights": 0,
                                    "n": 0})
        g["conv_io"] += u["conv_io"]
        g["bn"] += u["bn"]
        g["weights"] += u["weights"]
        g["n"] += 1
    for u in units[:3] + [u for u in units if u["name"].endswith("b0.c2")]:
        print(f"| {u['name']} | {u['shape']} | {u['out_mb']:.1f} | "
              f"{u['conv_io'] / 1e9:.2f} | {u['bn'] / 1e9:.2f} | "
              f"{u['weights'] / 1e6:.1f} |")
    print(f"| … ({len(units)} units total; per-group sums below) |")
    print("\n| group | units | conv-io GB | BN GB | weights MB |")
    print("|---|---|---|---|---|")
    for k, g in groups.items():
        print(f"| {k} | {g['n']} | {g['conv_io'] / 1e9:.1f} | "
              f"{g['bn'] / 1e9:.1f} | {g['weights'] / 1e6:.1f} |")

    print("\n| category | GB/step | % | note |")
    print("|---|---|---|---|")
    for name, b, note in (
        ("conv io (in/out/grads)", conv_io,
         "irreducible conv activation traffic"),
        ("BN passes", bn, "stats read + apply r/w + bwd r/r/w"),
        ("residual adds", residual, "skip fwd/bwd"),
        ("weight reads", weights, "fwd + dgrad"),
        ("optimizer/master (f32)", opt,
         "master+momentum r/w, f32 grad, bf16 cast"),
    ):
        print(f"| {name} | {b / 1e9:.1f} | {100 * b / total:.1f}% | "
              f"{note} |")
    print(f"| **total** | **{total / 1e9:.1f}** | 100% | floor "
          f"{ms(total):.0f} ms @819 GB/s |")

    print("\n### Attackable slices (what each buys)\n")
    print("| change | GB saved | floor ms | ceiling MFU | verdict |")
    print("|---|---|---|---|---|")
    rows = []
    rows.append(("baseline model", 0.0, total))
    rows.append(("bf16 optimizer state + master params "
                 "(optax accumulator_dtype)", opt * 0.55, total - opt * 0.55))
    rows.append(("fuse BN apply into consumer conv (saves 2 of 6 BN "
                 "passes; needs custom epilogue kernels)",
                 bn / 3, total - bn / 3))
    rows.append(("ideal fused conv+BN+relu fwd&bwd (4 of 6 passes; "
                 "beyond XLA today)", 2 * bn / 3, total - 2 * bn / 3))
    rows.append(("all of the above", opt * 0.55 + 2 * bn / 3,
                 total - opt * 0.55 - 2 * bn / 3))
    for name, saved, left in rows:
        print(f"| {name} | {saved / 1e9:.1f} | {ms(left):.0f} | "
              f"{mfu(left):.3f} | "
              f"{'measured 0.304 = %d%% of this ceiling' % round(100 * 0.304 / mfu(left)) if saved == 0 else ''} |")
    print(f"""
### Reading

- The no-change byte floor gives ceiling MFU {mfu(total):.3f} at bs=256 —
  **below the 0.40 bar**. The measured 0.304 (BENCH_r03, 103 ms) already
  runs at {100 * 0.304 / mfu(total):.0f}% of that ceiling; scheduling cannot close it.
- bf16 optimizer state + master params saves {opt * 0.55 / 1e9:.1f} GB
  (<1%): irrelevant for MFU at this model's activation/parameter ratio
  (25.6M params vs {total / 1e9:.0f} GB of activation traffic). It remains useful
  for HBM *capacity* (larger per-chip batch), not bandwidth.
- The only lever that reaches ≥0.40 is removing BN passes with fused
  conv+BN+relu kernels ({bn / 1e9:.0f} GB = {100 * bn / total:.0f}% of traffic): the 'ideal
  fusion' row lands at {mfu(total - 2 * bn / 3):.3f}. XLA does not fuse
  across the BN-stats reduction barrier today, and a Pallas conv+BN
  epilogue kernel set (im2col matmul with fused stats/apply, fwd+bwd) is
  the named line-item this table scopes — not a scheduling or layout fix.
- Larger batch (bs=512 + remat) does not change bytes/image: activation
  traffic dominates and scales linearly with batch; weight/optimizer
  amortization is already <2% of the budget.

Conclusion: **0.304 ≈ 90% of the architectural byte-floor ceiling
({mfu(total):.3f}) for this part/batch**; ≥0.40 requires kernel-level
conv+BN fusion, quantified above. (VERDICT r5 item 5 option (b).)""")
    print(json.dumps({"metric": "rn50_bytes_total_gb",
                      "value": round(total / 1e9, 1), "unit": "GB/step",
                      "detail": {"floor_ms": round(ms(total), 1),
                                 "ceiling_mfu": round(mfu(total), 4),
                                 "measured_mfu": 0.304}}))


if __name__ == "__main__":
    main()
