#!/usr/bin/env python
"""warmstart — pre-bake a serving model's bucket executables offline.

A serving boot normally XLA-compiles every traffic bucket during
warmup; with a warmstart artifact the engine deserializes them instead,
so time-to-first-healthy is I/O-bound (SERVING.md §Warmstart). This
tool is the offline half: load the model, warm the full bucket set
once, and serialize the executables into one artifact the engine (or
`ServingConfig(warmstart=...)`) adopts at boot.

Usage:
  warmstart.py bake --model-dir DIR --out ART [--buckets 1,2,4,8]
                    [--max-batch N] [--cpu]
  warmstart.py bake-decode --out ART [--preset tiny] [--seed 0]
                    [--slots 4,8] [--prefill-buckets 8,16,32]
                    [--prefill-chunk C] [--spec-k K]
                    [--block-size 16] [--num-blocks N]
                    [--precision bf16] [--cpu]
  warmstart.py inspect ART

`bake-decode` (ISSUE 12) pre-bakes the decode engine's whole PHASE
GRID — every prefill-length bucket plus every decode slot-count
executable — so a decode serving boot replays the grid from I/O with
zero fresh compiles (`DecodeConfig(warmstart=...)`). The model is
rebuilt deterministically from --preset/--seed (jax PRNG is
reproducible across processes for a fixed jax version), and the
artifact is bound to the params digest + grid geometry, so a drifted
model or config is rejected at adoption, never silently served.

`--prefill-chunk` re-keys the grid for the chunked-prefill path
(SERVING.md §KV reuse): the per-prompt-length prefill buckets collapse
into one fixed-size chunk program, so the artifact carries
chunk+decode phases instead of bucket+decode phases. `--spec-k` adds
the speculative-decoding phases (draft prefill/decode + verify); the
draft is the same preset model (self-draft), deterministic from the
same --seed, so the digest binding still holds.

`bake` prints one JSON line: buckets warmed, entries serialized,
warmup seconds, artifact size. `inspect` reads only the artifact
(stdlib, no jax import) and prints its metadata + per-signature blob
sizes — what an operator checks before shipping the artifact to the
serving fleet. NOTE: artifacts are pickles; `inspect` unpickles, so
(like the engine) only run it on artifacts from the trusted channel
that carries the model files themselves.

The artifact is environment-bound (jax version, backend, device kind)
and model-bound (digest of __model__): the engine rejects a mismatched
artifact and falls back to compiling, so baking on the wrong machine
costs nothing but the cold boot it failed to avoid.
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def cmd_bake(args) -> int:
    import contextlib

    sys.path.insert(0, _REPO)
    import jax

    if args.cpu:
        # use_tpu=False alone still compiles on the DEFAULT backend
        # (the Predictor's jax.jit), and artifacts are backend-stamped:
        # without this pin a TPU host would bake tpu-stamped blobs that
        # every CPU serving boot rejects. Must happen before any jax
        # use; the env var alone is overridden by the baked
        # sitecustomize.
        jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.serving.engine import Engine, ServingConfig

    buckets = None
    if args.buckets:
        try:
            buckets = sorted({int(b) for b in args.buckets.split(",")})
        except ValueError:
            print(f"bake: bad --buckets {args.buckets!r} (want e.g. "
                  f"1,2,4,8)", file=sys.stderr)
            return 2
    cfg = ServingConfig(args.model_dir, buckets=buckets,
                        max_batch=args.max_batch,
                        use_tpu=not args.cpu, aot=True)
    if args.cpu:
        guard = contextlib.nullcontext()
    else:
        # baking drives the chip: serialize against bench/other tools
        from paddle_tpu.core.tpu_lock import tpu_singleflight

        guard = tpu_singleflight(timeout=600.0)
    with guard:
        t0 = time.perf_counter()
        engine = Engine(cfg)
        ready = engine.warmup()
        warm_s = time.perf_counter() - t0
        n = engine.export_warmstart(args.out)
    print(json.dumps({
        "artifact": args.out,
        "model_dir": args.model_dir,
        "buckets": [int(b) for b in engine.policy.buckets],
        "buckets_ready": ready,
        "entries": n,
        "warmup_seconds": round(warm_s, 3),
        "artifact_bytes": os.path.getsize(args.out),
    }), flush=True)
    return 0 if n else 1


def cmd_bake_decode(args) -> int:
    import contextlib

    sys.path.insert(0, _REPO)
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.models import gpt
    from paddle_tpu.serving.decode import DecodeConfig, DecodeEngine

    if args.preset != "tiny":
        print(f"bake-decode: unknown --preset {args.preset!r} (only "
              "'tiny' is shipped; build bigger grids through the "
              "DecodeEngine API)", file=sys.stderr)
        return 2
    try:
        slots = sorted({int(s) for s in args.slots.split(",")})
        buckets = sorted({int(b) for b in
                          args.prefill_buckets.split(",")})
    except ValueError:
        print(f"bake-decode: bad --slots/--prefill-buckets (want e.g. "
              f"4,8)", file=sys.stderr)
        return 2
    cfg = gpt.GPTConfig.tiny()
    params, _ = gpt.init(jax.random.key(args.seed), cfg)
    max_len = args.max_len or cfg.max_len
    blocks_per_seq = -(-max_len // args.block_size)
    num_blocks = args.num_blocks or \
        (1 + max(slots) * blocks_per_seq)
    grid_kw = {}
    if args.prefill_chunk:
        # chunked path: the bucket dimension collapses into one chunk
        # program, so --prefill-buckets is ignored for the grid key
        grid_kw["prefill_chunk"] = args.prefill_chunk
    else:
        grid_kw["prefill_buckets"] = buckets
    dc = DecodeConfig(block_size=args.block_size, num_blocks=num_blocks,
                      decode_slots=slots, max_len=max_len,
                      precision=args.precision, spec_k=args.spec_k,
                      **grid_kw)
    # self-draft: same params serve as the draft model, so the baked
    # draft/verify phases stay deterministic from --preset/--seed
    draft = (params, cfg) if args.spec_k else None
    if args.cpu:
        guard = contextlib.nullcontext()
    else:
        from paddle_tpu.core.tpu_lock import tpu_singleflight

        guard = tpu_singleflight(timeout=600.0)
    with guard:
        t0 = time.perf_counter()
        engine = DecodeEngine(params, cfg, dc, draft=draft)
        ready = engine.warmup()
        warm_s = time.perf_counter() - t0
        n = engine.export_warmstart(args.out)
    grid_out = {"decode_slots": slots, "spec_k": args.spec_k}
    if args.prefill_chunk:
        grid_out["prefill_chunk"] = args.prefill_chunk
    else:
        grid_out["prefill_buckets"] = buckets
    print(json.dumps({
        "artifact": args.out,
        "preset": args.preset, "seed": args.seed,
        "phase_grid": grid_out,
        "phases_ready": ready,
        "entries": n,
        "precision": args.precision,
        "warmup_seconds": round(warm_s, 3),
        "artifact_bytes": os.path.getsize(args.out),
    }), flush=True)
    return 0 if n else 1


def cmd_inspect(args) -> int:
    try:
        with open(args.artifact, "rb") as f:
            art = pickle.loads(f.read())
    # pickle.loads on a truncated/foreign stream raises well beyond
    # UnpicklingError (EOFError, ImportError, AttributeError, ...);
    # the operator check must print its diagnostic + rc=2, not a
    # traceback, for any of them
    except Exception as e:
        print(f"inspect: cannot read {args.artifact}: {e}",
              file=sys.stderr)
        return 2
    if not isinstance(art, dict) or "entries" not in art:
        print(f"inspect: {args.artifact} is not a warmstart artifact",
              file=sys.stderr)
        return 2
    # a dict with "entries" can still be structurally malformed
    # (tampered/truncated-then-repickled, or a future format): the
    # same diagnostic-not-traceback contract applies to shape errors
    # as to unpickling errors
    try:
        entries = art["entries"]
        if art.get("format") == "paddle_tpu-decode-warmstart-v1":
            # decode artifacts key entries by phase ("prefill", T) /
            # ("decode", S), not by feed signature
            signatures = [
                {"phase": f"{kind}@{n}",
                 "blob_bytes": len(e["blob"]),
                 "fingerprint": (e.get("fingerprint") or "")[:16]}
                for (kind, n), e in sorted(entries.items())]
        else:
            signatures = [
                {"feeds": [f"{n}:{list(s)}:{d}" for n, s, d in sig],
                 "blob_bytes": len(e["blob"]),
                 "fingerprint": (e.get("fingerprint") or "")[:16]}
                for sig, e in sorted(entries.items())]
        report = {
            "format": art.get("format"),
            "jax_version": art.get("jax_version"),
            "backend": art.get("backend"),
            "device_kind": art.get("device_kind"),
            "model_digest": art.get("model_digest"),
            "buckets": art.get("buckets"),
            "phase_grid": art.get("grid"),
            "created_at": art.get("created_at"),
            "entries": len(entries),
            "signatures": signatures,
        }
    except Exception as e:
        print(f"inspect: {args.artifact} has malformed entries: {e!r}",
              file=sys.stderr)
        return 2
    print(json.dumps(report, indent=2))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="warmstart", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    bp = sub.add_parser("bake", help="warm every bucket and serialize "
                        "the executables into one artifact")
    bp.add_argument("--model-dir", required=True,
                    help="saved inference model directory")
    bp.add_argument("--out", required=True, help="artifact path")
    bp.add_argument("--buckets", default=None,
                    help="comma-separated batch buckets (default: pow2 "
                    "up to --max-batch)")
    bp.add_argument("--max-batch", type=int, default=64)
    bp.add_argument("--cpu", action="store_true",
                    help="bake for the CPU backend (artifacts are "
                    "backend-bound)")
    bp.set_defaults(fn=cmd_bake)

    dp = sub.add_parser("bake-decode", help="pre-bake a decode "
                        "engine's full phase grid (prefill buckets + "
                        "decode slot configs) into one artifact")
    dp.add_argument("--out", required=True, help="artifact path")
    dp.add_argument("--preset", default="tiny",
                    help="model preset (deterministic from --seed)")
    dp.add_argument("--seed", type=int, default=0)
    dp.add_argument("--slots", default="4,8",
                    help="comma-separated decode slot counts")
    dp.add_argument("--prefill-buckets", default="8,16,32",
                    help="comma-separated prompt-length buckets")
    dp.add_argument("--prefill-chunk", type=int, default=0,
                    help="chunked-prefill slice size; collapses the "
                    "prefill buckets into one chunk phase (0 = off)")
    dp.add_argument("--spec-k", type=int, default=0,
                    help="speculative-decoding draft length; bakes the "
                    "draft + verify phases with a self-draft (0 = off)")
    dp.add_argument("--block-size", type=int, default=16)
    dp.add_argument("--num-blocks", type=int, default=None,
                    help="KV pool blocks (default: worst-case for the "
                    "slot count)")
    dp.add_argument("--max-len", type=int, default=None)
    dp.add_argument("--precision", default="bf16",
                    choices=("f32", "bf16"))
    dp.add_argument("--cpu", action="store_true",
                    help="bake for the CPU backend (artifacts are "
                    "backend-bound)")
    dp.set_defaults(fn=cmd_bake_decode)

    ip = sub.add_parser("inspect", help="print an artifact's metadata "
                        "(no jax import)")
    ip.add_argument("artifact")
    ip.set_defaults(fn=cmd_inspect)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
