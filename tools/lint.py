#!/usr/bin/env python
"""lint — multi-pass static lints over the paddle_tpu codebase.

Grown out of the single-purpose durable-write check in
tests/test_evidence_lint.py (PR 4): one framework, several passes, all
run in tier-1 CI over every `.py` file under paddle_tpu/. A finding
fails the suite unless the line (or the line above it) carries an
explicit escape hatch:

    # lint-exempt:<pass>[: reason]

(the atomic pass also honors the legacy `# atomic-exempt: <why>`
annotation it migrated from).

Passes:
  atomic    — bare `open(..., "w")` / np.save / json.dump / pickle.dump
              inside paddle_tpu/ bypass the crash-safe tmp+fsync+
              os.replace helpers (resilience/atomic.py) and can leave
              truncated artifacts behind a kill.
  thread    — `threading.Thread(...)` without a `daemon=` decision and
              with no visible `.join()` of the created thread: such a
              thread silently blocks interpreter exit (non-daemon) or
              dies un-reaped — either way the lifetime is accidental.
  swallow   — `except:` / `except Exception:` / `except BaseException:`
              whose body is only `pass`: the one shape of handler that
              hides real bugs (typed narrow catches are fine).
  lockblock — blocking calls (sleep, subprocess, socket accept/recv/
              connect, serve_forever, Event.wait, thread join) made
              while holding a lock: every other thread touching that
              lock stalls for the duration. Heuristic: the with-item
              must look like a lock (name contains "lock"/"_cv"/"_mu");
              nested function bodies are skipped (they run later, off
              the lock) and waiting ON the held condition variable is
              fine (wait releases it).
  condwait  — a bare `Condition.wait()` not lexically inside a `while`
              loop: condition waits are subject to spurious wakeups and
              stolen wakeups, so the predicate must be re-checked in a
              loop (`while not pred: cv.wait()`) or the wait written as
              `cv.wait_for(pred)`, which loops internally and is never
              flagged. Only receivers assigned `threading.Condition`/
              `lockcheck.Condition` in the same file are considered —
              `Event.wait` needs no predicate loop.
  stopjoin  — a class that spawns a `threading.Thread` bound to a self
              attribute in a start-like method (`__init__`/`start*`/
              `open*`) where no stop-like method (`stop*`/`close*`/
              `shutdown*`/`terminate*`/`__exit__`) joins THAT attr
              (directly or through a local alias; str.join/os.path.join
              never count): shutdown returns while the worker still
              runs, the PR 3/11 review class this pass automates.
  traceheader — distributed-tracing propagation in paddle_tpu/serving/
              (PROFILE.md §Distributed tracing): (a) every `do_POST`
              HTTP handler method must enter the trace context via
              `tracing.begin_request` (in its own body or a self-method
              it calls, one level deep) — a handler that forwards work
              downstream without it silently breaks every trace at
              that hop; (b) every `urllib.request.Request(...)` built
              in serving code must inject the context (a `headers=`
              expression mentioning `trace_headers`/`traceparent`).
              Poll-loop probes and other deliberately request-unscoped
              calls escape with '# lint-exempt:traceheader: <why>'.

Usage:
  lint.py [paths...] [--json] [--pass NAME] [--list]
Exit code: 0 clean, 1 findings, 2 usage.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DEFAULT_TARGET = os.path.join(_REPO, "paddle_tpu")

_EXEMPT_RE = re.compile(r"lint-exempt:\s*([A-Za-z0-9_-]+)")


@dataclass(frozen=True)
class LintFinding:
    path: str  # repo-relative
    lineno: int
    pass_name: str
    message: str
    line: str = ""

    def __str__(self):
        return (f"{self.path}:{self.lineno}: [{self.pass_name}] "
                f"{self.message}: {self.line.strip()}")

    def to_dict(self):
        return {"path": self.path, "lineno": self.lineno,
                "pass": self.pass_name, "message": self.message,
                "line": self.line.strip()}


class _File:
    """One parsed source file handed to every pass."""

    def __init__(self, path: str, rel: str, src: str):
        self.path = path
        self.rel = rel
        self.src = src
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def exempt(self, lineno: int, pass_name: str,
               extra_markers: Sequence[str] = ()) -> bool:
        """Is `lineno` exempted from `pass_name`? The annotation may sit
        on the line itself or the line above (long statements put it
        above)."""
        for ln in (lineno, lineno - 1):
            text = self.line(ln)
            for m in _EXEMPT_RE.finditer(text):
                if m.group(1) == pass_name:
                    return True
            for marker in extra_markers:
                if marker in text:
                    return True
        return False


# ---------------------------------------------------------------------------
# pass registry
# ---------------------------------------------------------------------------

_PASSES: Dict[str, Callable[[_File], List[LintFinding]]] = {}


def lint_pass(name: str):
    def deco(fn):
        _PASSES[name] = fn
        fn.pass_name = name
        return fn

    return deco


def pass_names() -> List[str]:
    return list(_PASSES)


# ---------------------------------------------------------------------------
# atomic: durable writes must route through resilience/atomic.py
# (migrated verbatim from tests/test_evidence_lint.py; that test now
# wraps this pass)
# ---------------------------------------------------------------------------

# `(?<![\w.])` keeps atomic_open/gzip.open/os.fdopen out of the `open`
# match; modes are matched literally, so an `open(path, mode)` stream
# helper with a variable mode is out of scope (it writes on the
# caller's behalf, the caller owns durability). The open() pattern
# allows anything (including nested calls' parens) between `open(` and
# the quoted mode, which must be followed by `,` or `)` — so
# `open(os.path.join(d, f), "w")` is caught, at the cost of a rare
# false positive when a line happens to contain both `open(` and a
# stray `"w")` (annotate those).
WRITE_PATTERNS = (
    (re.compile(r"(?<![\w.])np\.(save|savez|savez_compressed)\s*\("),
     "np.save/np.savez"),
    (re.compile(r"(?<![\w.])json\.dump\s*\("), "json.dump"),
    # pickle.dump (not .dumps) streams into an already-open handle —
    # the compile-cache/warmstart writers must pickle.dumps into
    # atomic.write_bytes instead
    (re.compile(r"(?<![\w.])pickle\.dump\s*\("), "pickle.dump"),
    (re.compile(
        r"(?<![\w.])open\s*\(.*[\"'](w|wb|w\+|wb\+|x|xb)[\"']\s*[,)]"),
     'open(..., "w")'),
)

# The helper module itself is the one place allowed to open durable
# files for write.
_ATOMIC_ALLOWED = ("resilience/atomic.py",)


@lint_pass("atomic")
def _atomic_pass(f: _File) -> List[LintFinding]:
    if f.rel.replace(os.sep, "/").endswith(_ATOMIC_ALLOWED):
        return []
    out = []
    for lineno, line in enumerate(f.lines, 1):
        if f.exempt(lineno, "atomic", extra_markers=("atomic-exempt",)):
            continue
        for pat, what in WRITE_PATTERNS:
            if pat.search(line):
                out.append(LintFinding(
                    f.rel, lineno, "atomic",
                    f"bare {what} write — use paddle_tpu.resilience."
                    f"atomic or add '# lint-exempt:atomic: <why>'",
                    line))
    return out


# ---------------------------------------------------------------------------
# thread: Thread() must pick daemon= or be join()ed
# ---------------------------------------------------------------------------


def _call_name(node: ast.Call) -> str:
    try:
        return ast.unparse(node.func)
    except Exception:
        return ""


@lint_pass("thread")
def _thread_pass(f: _File) -> List[LintFinding]:
    out = []
    # names (last attribute component) that get .join()ed anywhere in
    # the file — `self._thread.join(...)` joins the thread bound to
    # `self._thread = threading.Thread(...)`
    joined = set(re.findall(r"(\w+)\s*\.\s*join\s*\(", f.src))
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if not (name == "threading.Thread" or name.endswith(".Thread")
                or name == "Thread"):
            continue
        if any(k.arg == "daemon" for k in node.keywords):
            continue
        if f.exempt(node.lineno, "thread"):
            continue
        # assigned target later join()ed? walk up is hard without
        # parents; approximate by the assignment on the same statement
        line = f.line(node.lineno)
        target = re.match(r"\s*([\w.]+)\s*=", line)
        tname = target.group(1).split(".")[-1] if target else None
        if tname and tname in joined:
            continue
        out.append(LintFinding(
            f.rel, node.lineno, "thread",
            "Thread() without an explicit daemon= decision and no "
            "visible .join() — thread lifetime is accidental "
            "(add daemon=True/False, join it, or "
            "'# lint-exempt:thread: <why>')",
            line))
    return out


# ---------------------------------------------------------------------------
# swallow: broad except with a pass-only body
# ---------------------------------------------------------------------------


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name) and t.id in ("Exception", "BaseException"):
        return True
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name)
                   and e.id in ("Exception", "BaseException")
                   for e in t.elts)
    return False


@lint_pass("swallow")
def _swallow_pass(f: _File) -> List[LintFinding]:
    out = []
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node):
            continue
        if not (len(node.body) == 1 and isinstance(node.body[0], ast.Pass)):
            continue
        lineno = node.lineno
        if f.exempt(lineno, "swallow") \
                or f.exempt(node.body[0].lineno, "swallow"):
            continue
        out.append(LintFinding(
            f.rel, lineno, "swallow",
            "broad except swallows every error with `pass` — catch the "
            "specific exception, handle it, or add "
            "'# lint-exempt:swallow: <why>'",
            f.line(lineno)))
    return out


# ---------------------------------------------------------------------------
# lockblock: blocking call while holding a lock
# ---------------------------------------------------------------------------

_LOCKISH_RE = re.compile(r"lock|_cv\b|_mu\b|mutex", re.IGNORECASE)

# call names that block for unbounded/long time
_BLOCKING_EXACT = {
    "time.sleep", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "urllib.request.urlopen", "urlopen",
}
_BLOCKING_ATTRS = {"serve_forever", "accept", "recv", "recv_into",
                   "connect", "wait"}


def _lock_exprs(node: ast.With) -> List[str]:
    out = []
    for item in node.items:
        try:
            s = ast.unparse(item.context_expr)
        except Exception:
            continue
        # `lock.acquire()`-style context exprs don't occur with `with`;
        # strip a trailing call like `self._lock` vs `get_lock()`
        if _LOCKISH_RE.search(s):
            out.append(s.split("(")[0])
    return out


def _iter_body_calls(node: ast.With):
    """Calls lexically under the with-body that execute WHILE the lock
    is held: nested function/class bodies are skipped — they run later,
    typically on another thread."""
    stack = list(node.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


@lint_pass("lockblock")
def _lockblock_pass(f: _File) -> List[LintFinding]:
    out = []
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.With):
            continue
        locks = _lock_exprs(node)
        if not locks:
            continue
        for call in _iter_body_calls(node):
            name = _call_name(call)
            blocking = name in _BLOCKING_EXACT
            recv = None
            if not blocking and "." in name:
                recv, attr = name.rsplit(".", 1)
                if attr in _BLOCKING_ATTRS:
                    # waiting ON the held lock/condvar is the one
                    # legitimate shape: Condition.wait releases it
                    blocking = recv not in locks
                elif attr == "join" and "thread" in recv.lower():
                    blocking = True
            if not blocking:
                continue
            if f.exempt(call.lineno, "lockblock"):
                continue
            out.append(LintFinding(
                f.rel, call.lineno, "lockblock",
                f"blocking call `{name}(...)` while holding "
                f"`{locks[0]}` — every thread contending on that lock "
                f"stalls for the duration (move it outside the lock or "
                f"add '# lint-exempt:lockblock: <why>')",
                f.line(call.lineno)))
    return out


# ---------------------------------------------------------------------------
# condwait: Condition.wait() must sit in a while-predicate loop
# ---------------------------------------------------------------------------


def _condition_names(f: _File) -> set:
    """Attribute/variable names bound to a Condition factory anywhere
    in the file (threading.Condition or the lockcheck factory)."""
    names = set()
    for node in ast.walk(f.tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        if _call_name(node.value).split(".")[-1] != "Condition":
            continue
        for t in node.targets:
            if isinstance(t, ast.Attribute):
                names.add(t.attr)
            elif isinstance(t, ast.Name):
                names.add(t.id)
    return names


@lint_pass("condwait")
def _condwait_pass(f: _File) -> List[LintFinding]:
    cond_names = _condition_names(f)
    if not cond_names:
        return []
    out = []

    def visit(node, in_while):
        for child in ast.iter_child_nodes(node):
            child_in_while = in_while
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                child_in_while = False  # a nested body runs elsewhere
            elif isinstance(child, ast.While):
                child_in_while = True
            if isinstance(child, ast.Call):
                name = _call_name(child)
                recv, _, attr = name.rpartition(".")
                if attr == "wait" \
                        and recv.split(".")[-1] in cond_names \
                        and not in_while \
                        and not f.exempt(child.lineno, "condwait"):
                    out.append(LintFinding(
                        f.rel, child.lineno, "condwait",
                        f"`{name}()` outside a while loop — condition "
                        f"waits wake spuriously and lose races; re-check "
                        f"the predicate in a loop, use "
                        f"`{recv}.wait_for(pred)`, or add "
                        f"'# lint-exempt:condwait: <why>'",
                        f.line(child.lineno)))
            visit(child, child_in_while)

    visit(f.tree, False)
    return out


# ---------------------------------------------------------------------------
# stopjoin: a stop/close path must join the threads start spawned
# ---------------------------------------------------------------------------

_STARTISH = ("start", "open")
_STOPPISH = ("stop", "close", "shutdown", "terminate")


def _is_startish(name: str) -> bool:
    return name == "__init__" or name.startswith(_STARTISH)


def _is_stoppish(name: str) -> bool:
    return name == "__exit__" or name.startswith(_STOPPISH)


def _thread_join_receivers(method) -> set:
    """Local/attr names whose `.join()` plausibly joins a thread in
    this method. `", ".join(parts)` and `os.path.join(...)` must NOT
    count — they would silently exempt spawned threads."""
    names = set()
    for node in ast.walk(method):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            continue
        recv = node.func.value
        if isinstance(recv, ast.Constant):
            continue  # ", ".join(...) — a string join
        parts = _call_name(node).rsplit(".", 2)
        if len(parts) >= 2 and parts[-2] in ("path", "os"):
            continue  # os.path.join(...)
        if isinstance(recv, ast.Name):
            names.add(recv.id)
        elif isinstance(recv, ast.Attribute):
            names.add(recv.attr)
    return names


def _alias_joined_attrs(method) -> set:
    """Thread attrs joined through a local alias in this method —
    `t = self._thread` (or `t, self._thread = self._thread, None`)
    followed by `t.join(...)`. Resolved PER ATTRIBUTE so a class that
    spawns two threads but joins only one is still flagged for the
    other."""
    aliases = {}  # local name -> self attr it was read from
    for node in ast.walk(method):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt, val = node.targets[0], node.value
        pairs = []
        if isinstance(tgt, ast.Tuple) and isinstance(val, ast.Tuple) \
                and len(tgt.elts) == len(val.elts):
            pairs = list(zip(tgt.elts, val.elts))
        else:
            pairs = [(tgt, val)]
        for t, v in pairs:
            if isinstance(t, ast.Name) and isinstance(v, ast.Attribute) \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id == "self":
                aliases[t.id] = v.attr
    joined = _thread_join_receivers(method)
    return {attr for name, attr in aliases.items() if name in joined}


@lint_pass("stopjoin")
def _stopjoin_pass(f: _File) -> List[LintFinding]:
    out = []
    for cls in (n for n in ast.walk(f.tree)
                if isinstance(n, ast.ClassDef)):
        methods = [m for m in cls.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        stoppers = [m for m in methods if _is_stoppish(m.name)]
        if not stoppers:
            continue  # no shutdown surface to hold accountable
        spawns = []  # (attr, assign lineno) in start-like methods
        for m in methods:
            if not _is_startish(m.name):
                continue
            for node in ast.walk(m):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                cname = _call_name(node.value)
                if not (cname == "Thread" or cname.endswith(".Thread")):
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        spawns.append((t.attr, node.lineno))
        if not spawns:
            continue
        cls_src = ast.get_source_segment(f.src, cls) or ""
        joined = set(re.findall(r"(\w+)\s*\.\s*join\s*\(", cls_src))
        for m in stoppers:
            joined |= _alias_joined_attrs(m)
        for attr, lineno in spawns:
            # joined directly (self._t.join) anywhere in the class, or
            # through a stop-method local alias (t = self._t; t.join())
            if attr in joined:
                continue
            if f.exempt(lineno, "stopjoin"):
                continue
            out.append(LintFinding(
                f.rel, lineno, "stopjoin",
                f"class {cls.name} spawns thread `self.{attr}` in a "
                f"start-like method but no stop/close path joins it — "
                f"shutdown returns while the worker still runs (join it "
                f"in {', '.join(m.name + '()' for m in stoppers)}, or "
                f"add '# lint-exempt:stopjoin: <why>')",
                f.line(lineno)))
    return out


# ---------------------------------------------------------------------------
# traceheader: serving HTTP hops must propagate the trace context
# ---------------------------------------------------------------------------

# the canonical entry/injection helper names (observability/tracing.py);
# mentioning `traceparent` directly (manual header plumbing) also counts
_TRACE_ENTRY = "begin_request"
_TRACE_INJECT = ("trace_headers", "traceparent")


def _self_called_names(method) -> set:
    """Names of `self.X(...)` calls made inside `method` (one level of
    indirection for the entry-helper search)."""
    out = set()
    for node in ast.walk(method):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            out.add(node.func.attr)
    return out


@lint_pass("traceheader")
def _traceheader_pass(f: _File) -> List[LintFinding]:
    rel = f.rel.replace(os.sep, "/")
    if "paddle_tpu/serving/" not in rel:
        return []
    out = []
    # (a) do_POST handlers must extract-or-start the trace context
    for cls in (n for n in ast.walk(f.tree)
                if isinstance(n, ast.ClassDef)):
        methods = {m.name: m for m in cls.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        handler = methods.get("do_POST")
        if handler is None:
            continue
        sources = [ast.get_source_segment(f.src, handler) or ""]
        for name in _self_called_names(handler):
            m = methods.get(name)
            if m is not None:
                sources.append(ast.get_source_segment(f.src, m) or "")
        if any(_TRACE_ENTRY in s for s in sources):
            continue
        if f.exempt(handler.lineno, "traceheader"):
            continue
        out.append(LintFinding(
            f.rel, handler.lineno, "traceheader",
            f"HTTP handler {cls.name}.do_POST never calls "
            f"tracing.{_TRACE_ENTRY} — requests through this hop lose "
            f"their trace context (extract-or-start it, or add "
            f"'# lint-exempt:traceheader: <why>')",
            f.line(handler.lineno)))
    # (b) downstream urllib requests must inject the context
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name.split(".")[-1] != "Request" \
                or "urllib" not in name and name != "Request":
            continue
        hdr_src = ""
        for kw in node.keywords:
            if kw.arg == "headers":
                try:
                    hdr_src = ast.unparse(kw.value)
                except Exception:
                    hdr_src = ""
        if any(tok in hdr_src for tok in _TRACE_INJECT):
            continue
        if f.exempt(node.lineno, "traceheader"):
            continue
        out.append(LintFinding(
            f.rel, node.lineno, "traceheader",
            "urllib Request built without trace propagation — pass "
            "headers={..., **tracing.trace_headers()} (or justify with "
            "'# lint-exempt:traceheader: <why>')",
            f.line(node.lineno)))
    return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def iter_py_files(paths: Sequence[str]):
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__",)]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def lint_paths(paths: Optional[Sequence[str]] = None,
               passes: Optional[Sequence[str]] = None
               ) -> List[LintFinding]:
    """Run the (selected) passes over every .py file under `paths`
    (default: the paddle_tpu package). Unparseable files produce a
    finding instead of crashing the linter."""
    paths = list(paths) if paths else [_DEFAULT_TARGET]
    selected = list(passes) if passes else pass_names()
    for name in selected:
        if name not in _PASSES:
            raise KeyError(f"unknown lint pass {name!r}; choose from "
                           f"{pass_names()}")
    findings: List[LintFinding] = []
    for path in iter_py_files(paths):
        rel = os.path.relpath(path, _REPO)
        try:
            with open(path) as fh:
                src = fh.read()
            f = _File(path, rel, src)
        except (OSError, SyntaxError) as e:
            findings.append(LintFinding(
                rel, getattr(e, "lineno", 0) or 0, "parse",
                f"could not lint: {type(e).__name__}: {e}"))
            continue
        for name in selected:
            findings.extend(_PASSES[name](f))
    findings.sort(key=lambda x: (x.path, x.lineno, x.pass_name))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="lint", description=__doc__)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: paddle_tpu/)")
    ap.add_argument("--pass", dest="passes", action="append",
                    help="run only this pass (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="findings as JSON lines")
    ap.add_argument("--list", action="store_true",
                    help="list registered passes and exit")
    args = ap.parse_args(argv)
    if args.list:
        for n in pass_names():
            print(n)
        return 0
    try:
        findings = lint_paths(args.paths or None, args.passes)
    except KeyError as e:
        print(f"lint: {e.args[0]}", file=sys.stderr)
        return 2
    for f in findings:
        print(json.dumps(f.to_dict()) if args.json else str(f))
    if findings:
        print(f"{len(findings)} finding(s) across "
              f"{len({f.path for f in findings})} file(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
