"""Inference predictor — deployment API.

Reference: paddle/fluid/inference/ — `PaddlePredictor`/`AnalysisPredictor`
(api/paddle_api.h:204, api/analysis_predictor.h:47): load a saved inference
model, run an analysis/optimization pipeline, expose Run()/ZeroCopyRun with
a config object (AnalysisConfig).

TPU-native: the "analysis pipeline" is XLA — the loaded program lowers to
one jit-compiled (optionally AOT-compiled) computation per input signature.
Zero-copy semantics come from device-resident params + donated inputs.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import io
from .core import lowering
from .core.executor import Executor, Scope, scope_guard
from .core.ir import normalize_dtype
from .core.places import CPUPlace, Place, TPUPlace, default_place


class AnalysisConfig:
    """reference: inference/api/analysis_config.cc — knobs subset that is
    meaningful on TPU; the rest are accepted and recorded for parity."""

    def __init__(self, model_dir: Optional[str] = None):
        self.model_dir = model_dir
        self._use_tpu = True
        self._device_id = 0
        self._memory_optim = True       # XLA buffer assignment
        self._ir_optim = True           # XLA fusion
        self._enable_profile = False
        self._aot = False               # ahead-of-time compile at load
        self._native_engine = False     # C++ interpreter (capi) backend

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_tpu = True  # accelerator = TPU in this framework
        self._device_id = device_id

    def disable_gpu(self):
        self._use_tpu = False

    def switch_ir_optim(self, x=True):
        self._ir_optim = x

    def enable_memory_optim(self):
        self._memory_optim = True

    def enable_profile(self):
        self._enable_profile = True

    def enable_aot(self):
        self._aot = True

    def enable_native_engine(self):
        """Serve through the C++ interpreter (native/src/predictor.cc) —
        the reference's analogous switch is picking the Native vs Analysis
        predictor (api/api_impl.h); here it swaps the XLA engine for the
        dependency-free CPU one."""
        self._native_engine = True


class PaddleTensor:
    """reference: api/paddle_api.h PaddleTensor — named ndarray."""

    def __init__(self, data, name: str = ""):
        self.name = name
        self.data = np.asarray(data)

    @property
    def shape(self):
        return self.data.shape


class Predictor:
    """reference: AnalysisPredictor. Loads the model once; each distinct
    input signature compiles once and is cached (the reference caches one
    engine per optimized graph)."""

    def __init__(self, config: AnalysisConfig):
        self.config = config
        if config._native_engine:
            from .capi import NativePredictor

            self._native = NativePredictor(config.model_dir)
            self._feed_names = self._native.input_names
            self._fetch_names = self._native.output_names
            # declared feed dtypes: the native engine gets the same
            # feed-dtype normalization the XLA path performs
            import json

            with open(os.path.join(config.model_dir, "__model__")) as f:
                payload = json.load(f)
            feed_set = set(self._feed_names)
            # first match across blocks wins (same rule as the XLA path) —
            # a sub-block local sharing a feed name must not shadow it
            self._feed_dtypes = {}
            for b in payload["program"]["blocks"]:
                for v in b["vars"]:
                    if v["name"] in feed_set and \
                            v["name"] not in self._feed_dtypes:
                        self._feed_dtypes[v["name"]] = v.get("dtype",
                                                             "float32")
            return
        self._native = None
        place = TPUPlace(config._device_id) if config._use_tpu else CPUPlace()
        self._exe = Executor(place)
        self._scope = Scope()
        with scope_guard(self._scope):
            (self._program, self._feed_names,
             self._fetch_vars) = io.load_inference_model(
                config.model_dir, self._exe)
        self._fetch_names = [v if isinstance(v, str) else v.name
                             for v in self._fetch_vars]
        self._program._is_test = True
        self._cache: Dict = {}

    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    def _compiled(self, sig):
        step = self._cache.get(sig)
        if step is None:
            desc = self._program.desc
            feed_names = tuple(n for n, _, _ in sig)

            def fwd(feeds, state):
                env = dict(state)
                env.update(feeds)
                lowering.lower_block(desc, 0, env, rng_key=None, is_test=True)
                return [env[n] for n in self._fetch_names]

            state = {}
            for b in desc.blocks:
                for name, v in b.vars.items():
                    if v.persistable:
                        val = self._scope.find_var(name)
                        if val is not None:
                            state[name] = jnp.asarray(val)
            jitted = jax.jit(fwd)
            if self.config._aot:
                shapes = {n: jax.ShapeDtypeStruct(s, np.dtype(d))
                          for n, s, d in sig}
                jitted = jitted.lower(shapes, state).compile()
            step = (jitted, state)
            self._cache[sig] = step
        return step

    def run(self, inputs: Sequence[PaddleTensor]) -> List[PaddleTensor]:
        if self._native is not None:
            feed = {}
            for i, t in enumerate(inputs):
                name = t.name or self._feed_names[i]
                dt = self._feed_dtypes.get(name)
                # unknown feed names keep their dtype — the engine then
                # raises its clear unknown-var error, like the XLA path
                feed[name] = np.asarray(t.data).astype(dt) if dt \
                    else np.asarray(t.data)
            outs = self._native.run(feed)
            return [PaddleTensor(o, name=n)
                    for n, o in zip(self._fetch_names, outs)]
        feeds = {}
        for i, t in enumerate(inputs):
            name = t.name or self._feed_names[i]
            want = None
            for b in self._program.desc.blocks:
                if name in b.vars:
                    want = np.dtype(normalize_dtype(b.vars[name].dtype))
                    break
            arr = np.asarray(t.data)
            if want is not None and arr.dtype != want:
                arr = arr.astype(want)
            feeds[name] = arr
        sig = tuple(sorted((n, tuple(v.shape), str(v.dtype))
                           for n, v in feeds.items()))
        jitted, state = self._compiled(sig)
        outs = jitted({n: jnp.asarray(v) for n, v in feeds.items()}, state)
        return [PaddleTensor(np.asarray(o), name=n)
                for o, n in zip(outs, self._fetch_names)]

    # numpy-dict convenience API
    def predict(self, **feeds) -> Dict[str, np.ndarray]:
        tensors = [PaddleTensor(v, name=k) for k, v in feeds.items()]
        outs = self.run(tensors)
        return {t.name: t.data for t in outs}


def create_paddle_predictor(config: AnalysisConfig) -> Predictor:
    """reference: api/paddle_api.h:346 CreatePaddlePredictor."""
    return Predictor(config)
