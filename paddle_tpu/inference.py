"""Inference predictor — deployment API.

Reference: paddle/fluid/inference/ — `PaddlePredictor`/`AnalysisPredictor`
(api/paddle_api.h:204, api/analysis_predictor.h:47): load a saved inference
model, run an analysis/optimization pipeline, expose Run()/ZeroCopyRun with
a config object (AnalysisConfig).

TPU-native: the "analysis pipeline" is XLA — the loaded program lowers to
one jit-compiled (optionally AOT-compiled) computation per input signature.
Zero-copy semantics come from device-resident params + donated inputs.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import io
from .core import lowering
from .core import precision as _precision
from .core.executor import Executor, Scope, _JitDispatch, scope_guard
from .core.ir import normalize_dtype
from .core.places import CPUPlace, Place, TPUPlace, default_place


class AnalysisConfig:
    """reference: inference/api/analysis_config.cc — knobs subset that is
    meaningful on TPU; the rest are accepted and recorded for parity."""

    def __init__(self, model_dir: Optional[str] = None):
        self.model_dir = model_dir
        self._use_tpu = True
        self._device_id = 0
        self._memory_optim = True       # XLA buffer assignment
        self._ir_optim = True           # XLA fusion
        self._enable_profile = False
        self._aot = False               # ahead-of-time compile at load
        self._native_engine = False     # C++ interpreter (capi) backend
        self._bucketing = None          # serving.bucketing.BucketPolicy
        self._precision = None          # core.precision policy name

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_tpu = True  # accelerator = TPU in this framework
        self._device_id = device_id

    def disable_gpu(self):
        self._use_tpu = False

    def switch_ir_optim(self, x=True):
        self._ir_optim = x

    def enable_memory_optim(self):
        self._memory_optim = True

    def enable_profile(self):
        self._enable_profile = True

    def enable_aot(self):
        self._aot = True

    def enable_bucketing(self, max_batch: int = 64, buckets=None):
        """Round every Run() batch up to the nearest configured bucket
        (powers of two up to `max_batch` by default, or an explicit
        `buckets` sequence), padding feeds and slicing outputs back to
        the true batch — so bs=1..64 traffic hits at most log2(64)+1
        compiled signatures instead of up to 64. Batches larger than
        the biggest bucket fall back to exact-shape compilation. See
        SERVING.md §Bucket policy."""
        from .serving.bucketing import BucketPolicy

        self._bucketing = BucketPolicy(max_batch=max_batch,
                                       buckets=buckets)

    def set_precision(self, name: Optional[str]):
        """Serve under a named precision policy (core/precision.py:
        "f32" | "bf16" | "mixed_bf16"): floating feeds normalize to the
        policy's compute dtype and the loaded program lowers under its
        autocast. Resolution order: this config > the loaded program's
        precision attr > PADDLE_TPU_PRECISION > f32. Ignored by the
        native (C++) engine, which is f32-only."""
        if name is not None:
            _precision.get_policy(name)  # fail fast on typos
        self._precision = name

    def enable_native_engine(self):
        """Serve through the C++ interpreter (native/src/predictor.cc) —
        the reference's analogous switch is picking the Native vs Analysis
        predictor (api/api_impl.h); here it swaps the XLA engine for the
        dependency-free CPU one."""
        self._native_engine = True


class PaddleTensor:
    """reference: api/paddle_api.h PaddleTensor — named ndarray."""

    def __init__(self, data, name: str = ""):
        self.name = name
        self.data = np.asarray(data)

    @property
    def shape(self):
        return self.data.shape


class Predictor:
    """reference: AnalysisPredictor. Loads the model once; each distinct
    input signature compiles once and is cached (the reference caches one
    engine per optimized graph)."""

    def __init__(self, config: AnalysisConfig):
        self.config = config
        if config._native_engine:
            from .capi import NativePredictor

            self._native = NativePredictor(config.model_dir)
            self._feed_names = self._native.input_names
            self._fetch_names = self._native.output_names
            # declared feed dtypes: the native engine gets the same
            # feed-dtype normalization the XLA path performs
            import json

            with open(os.path.join(config.model_dir, "__model__")) as f:
                payload = json.load(f)
            feed_set = set(self._feed_names)
            # first match across blocks wins (same rule as the XLA path) —
            # a sub-block local sharing a feed name must not shadow it
            self._feed_dtypes = {}
            for b in payload["program"]["blocks"]:
                for v in b["vars"]:
                    if v["name"] in feed_set and \
                            v["name"] not in self._feed_dtypes:
                        self._feed_dtypes[v["name"]] = v.get("dtype",
                                                             "float32")
            return
        self._native = None
        place = TPUPlace(config._device_id) if config._use_tpu else CPUPlace()
        self._exe = Executor(place)
        self._scope = Scope()
        with scope_guard(self._scope):
            (self._program, self._feed_names,
             self._fetch_vars) = io.load_inference_model(
                config.model_dir, self._exe)
        self._fetch_names = [v if isinstance(v, str) else v.name
                             for v in self._fetch_vars]
        self._program._is_test = True
        # one policy per Predictor, resolved at load: config >
        # program attr (a model saved under a policy keeps it) > env
        self._policy = _precision.resolve(self._program,
                                          explicit=config._precision)
        self._cache: Dict = {}
        # which fetches carry the batch dim (declared leading dim is
        # dynamic): bucketing must never slice an output whose fixed
        # leading dim merely coincides with the bucket size. None =
        # shape undeclared → fall back to the runtime-shape heuristic.
        self._fetch_batched: Dict[str, Optional[bool]] = {}
        for name in self._fetch_names:
            self._fetch_batched[name] = self._var_batched(name)
        # feeds get the symmetric treatment: a feed whose declared
        # leading dim is fixed (lookup tables, masks) must be neither
        # counted toward the batch size nor padded
        self._feed_batched: Dict[str, Optional[bool]] = {
            name: self._var_batched(name) for name in self._feed_names}

    def _var_batched(self, name: str) -> Optional[bool]:
        """Does `name`'s declared leading dim carry the batch (-1/0 =
        dynamic)? None when the shape is undeclared."""
        var = self._find_var(name)
        shape = var.shape if var is not None else None
        if shape is None:
            return None
        return bool(shape) and shape[0] in (-1, 0)

    def _find_var(self, name: str):
        """First match across blocks (a sub-block local must not shadow
        the outer var — same rule the native path applies to feeds)."""
        for b in self._program.desc.blocks:
            if name in b.vars:
                return b.vars[name]
        return None

    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    def _compiled(self, sig, warm: Optional[bool] = None):
        step = self._cache.get(sig)
        if step is None:
            desc = self._program.desc
            feed_names = tuple(n for n, _, _ in sig)
            policy = self._policy

            def fwd(feeds, state):
                env = dict(state)
                env.update(feeds)
                with _precision.autocast(policy):
                    lowering.lower_block(desc, 0, env, rng_key=None,
                                         is_test=True)
                return [env[n] for n in self._fetch_names]

            state = {}
            for b in desc.blocks:
                for name, v in b.vars.items():
                    if v.persistable:
                        val = self._scope.find_var(name)
                        if val is not None:
                            arr = jnp.asarray(val)
                            if policy.cast_state:
                                # pure low-precision serving: params are
                                # cast ONCE here, not per request
                                arr = _precision.cast_floating(
                                    arr, policy.compute_dtype)
                            state[name] = arr
            # _JitDispatch: compiles land in paddle_tpu_compile_seconds
            # {kind="infer"} and the `compile` event log, so a serving
            # deployment can assert its bucket set stays closed
            jitted = _JitDispatch(jax.jit(fwd), "infer", meta={
                "signature": ",".join(f"{n}:{list(s)}" for n, s, _ in sig)},
                policy=policy.name)
            # warm=False (adopt_warm) builds the slot for an executable
            # that already exists — warming would compile the very thing
            # the warmstart artifact exists to skip
            if self.config._aot if warm is None else warm:
                shapes = {n: jax.ShapeDtypeStruct(s, np.dtype(d))
                          for n, s, d in sig}
                jitted.warm(shapes, state)
            step = (jitted, state)
            self._cache[sig] = step
        return step

    def _feed_sig(self, batch_size: int):
        """Signature tuple for the declared feed shapes at `batch_size`
        (leading dynamic dim replaced; any other dynamic dim is an
        error — such a model must be warmed by running a real batch)."""
        entries = []
        for name in self._feed_names:
            var = self._find_var(name)
            if var is None or var.shape is None:
                raise ValueError(f"feed '{name}' has no declared shape; "
                                 "cannot warm ahead of traffic")
            shape = [int(d) for d in var.shape]
            if shape and shape[0] in (-1, 0):
                shape[0] = int(batch_size)
            if any(d < 1 for d in shape):
                raise ValueError(
                    f"feed '{name}' has non-batch dynamic dims "
                    f"{tuple(var.shape)}; warm it with a real batch")
            dtype = self._policy.feed_dtype(
                np.dtype(normalize_dtype(var.dtype)))
            entries.append((name, tuple(shape), str(dtype)))
        return tuple(sorted(entries))

    def warm(self, batch_size: int) -> bool:
        """AOT-compile the signature for `batch_size` without executing
        — a bucketed serving deployment warms every configured bucket at
        startup so no live request pays a compile. No-op on the native
        engine (no XLA). Returns whether an AOT executable is ready."""
        if self._native is not None:
            return False
        sig = self._feed_sig(batch_size)
        jitted, state = self._compiled(sig)
        shapes = {n: jax.ShapeDtypeStruct(s, np.dtype(d))
                  for n, s, d in sig}
        return jitted.warm(shapes, state)

    # -- warmstart (serialized-executable) export/import ---------------

    def serialize_warm(self) -> Dict[Tuple, Dict]:
        """Serialized executable per cached signature whose AOT compile
        is ready — the payload of a serving warmstart artifact
        (SERVING.md §Warmstart). Each entry carries the signature's
        lowering FINGERPRINT (compile_cache.fingerprint over the
        StableHLO this process's paddle_tpu emits, plus the environment
        meta), re-checked at adoption: an artifact baked before a
        lowering change must fall back to compiling, never serve the
        old computation. Signatures a backend refuses to serialize are
        skipped, not fatal: the artifact then simply covers fewer
        buckets and boot compiles the rest."""
        from .core import compile_cache

        out: Dict[Tuple, Dict] = {}
        for sig, (jitted, state) in self._cache.items():
            exe = getattr(jitted, "_aot", None)
            if exe is None:
                continue
            try:
                shapes = {n: jax.ShapeDtypeStruct(s, np.dtype(d))
                          for n, s, d in sig}
                # cache_fingerprint, not bare fingerprint: the policy is
                # key material, so an artifact baked under one policy is
                # rejected by a process serving another
                fp = jitted.cache_fingerprint(
                    jitted.lower(shapes, state))
                out[sig] = {"blob":
                            compile_cache.serialize_executable(exe),
                            "fingerprint": fp}
            except Exception:
                continue
        return out

    def adopt_warm(self, entries: Dict[Tuple, Dict]) -> int:
        """Install pre-serialized executables keyed by feed signature
        (the inverse of serialize_warm, called by the serving engine at
        boot): each adopted entry becomes a ready compiled-signature
        cache slot without any XLA compile. Adoption DOES re-lower each
        signature (tracing, milliseconds) to recompute its fingerprint
        against the artifact's: a stale artifact — baked by a paddle_tpu
        whose lowering has since changed, or under different compile
        flags — is rejected per entry and that bucket warms/compiles
        normally. Any malformed, undeserializable, or mismatched entry
        is likewise skipped, never raised: a bad artifact costs a cold
        bucket, not a serving boot. Returns how many signatures
        adopted."""
        from .core import compile_cache

        if self._native is not None:
            return 0
        adopted = 0
        for sig, entry in entries.items():
            try:
                jitted, state = self._compiled(sig, warm=False)
                shapes = {n: jax.ShapeDtypeStruct(s, np.dtype(d))
                          for n, s, d in sig}
                fp = jitted.cache_fingerprint(
                    jitted.lower(shapes, state))
                if fp is None or fp != entry["fingerprint"]:
                    continue  # lowering/flags drifted since the bake
                exe = compile_cache.deserialize_executable(
                    entry["blob"])
                jitted.adopt(exe, shapes, state)
                adopted += 1
            except Exception:
                continue
        return adopted

    def run(self, inputs: Sequence[PaddleTensor]) -> List[PaddleTensor]:
        return self.run_handle(inputs).result()

    def run_handle(self, inputs: Sequence[PaddleTensor]):
        """Dispatch without fetching: returns a lazy
        core.async_exec.FetchHandle whose `.result()` is the
        List[PaddleTensor] `run` would return — pad-slice bucketing
        postprocessing included. The device computes while the caller
        (e.g. the serving Engine) does other host work; resolution
        records the dispatch-to-ready latency. On the native engine
        (no XLA, synchronous by construction) the handle is
        pre-computed."""
        from .core.async_exec import FetchHandle

        if self._native is not None:
            feed = {}
            for i, t in enumerate(inputs):
                name = t.name or self._feed_names[i]
                dt = self._feed_dtypes.get(name)
                # unknown feed names keep their dtype — the engine then
                # raises its clear unknown-var error, like the XLA path
                feed[name] = np.asarray(t.data).astype(dt) if dt \
                    else np.asarray(t.data)
            outs = self._native.run(feed)
            return FetchHandle(
                outs, site="infer",
                transform=lambda arrs: [PaddleTensor(o, name=n)
                                        for n, o in zip(self._fetch_names,
                                                        arrs)])
        feeds = {}
        for i, t in enumerate(inputs):
            name = t.name or self._feed_names[i]
            var = self._find_var(name)
            want = self._policy.feed_dtype(
                np.dtype(normalize_dtype(var.dtype))) \
                if var is not None else None
            arr = np.asarray(t.data)
            if want is not None and arr.dtype != want:
                arr = arr.astype(want)
            feeds[name] = arr
        # opt-in shape bucketing: pad the batch up to its bucket so the
        # jit cache stays bounded by the bucket set, then slice outputs
        # back to the true batch (rows whose leading dim is the bucket)
        policy = self.config._bucketing
        true_n = bucket = None
        if policy is not None:
            from .serving.bucketing import common_batch

            batched = {k: v for k, v in feeds.items()
                       if self._feed_batched.get(k) is not False}
            n = common_batch(batched) if batched else None
            if n:
                b = policy.bucket_for(n)
                if b is not None and b != n:
                    feeds = {k: (policy.pad_batch(v, b) if k in batched
                                 else v)
                             for k, v in feeds.items()}
                    true_n, bucket = n, b
        sig = tuple(sorted((n, tuple(v.shape), str(v.dtype))
                           for n, v in feeds.items()))
        jitted, state = self._compiled(sig)
        outs = jitted({n: jnp.asarray(v) for n, v in feeds.items()}, state)

        def postprocess(arrs):
            results = []
            for a, name in zip(arrs, self._fetch_names):
                if true_n is not None and a.ndim \
                        and a.shape[0] == bucket \
                        and self._fetch_batched.get(name) is not False:
                    a = a[:true_n]
                results.append(PaddleTensor(a, name=name))
            return results

        return FetchHandle(outs, site="infer", transform=postprocess)

    # numpy-dict convenience API
    def predict(self, **feeds) -> Dict[str, np.ndarray]:
        return self.predict_handle(**feeds).result()

    def predict_handle(self, **feeds):
        """Lazy predict: dispatch now, numpy dict on `.result()`."""
        tensors = [PaddleTensor(v, name=k) for k, v in feeds.items()]
        return self.run_handle(tensors).map(
            lambda ts: {t.name: t.data for t in ts})


def create_paddle_predictor(config: AnalysisConfig) -> Predictor:
    """reference: api/paddle_api.h:346 CreatePaddlePredictor."""
    return Predictor(config)
