"""Text-matching / CTR ops rounding out the pyramid family and misc
leftovers.

Reference behaviors: operators/pad_constant_like_op.cc,
squared_l2_distance_op.h, bilinear_tensor_product_op.h, conv_shift_op.cc
(circular correlation), cvm_op.h:26-40 (log show/click transform),
hash_op.h:60-63 (per-seed hash of the id window mod mod_by — XXH64 in the
reference; a splitmix-style integer hash here, same contract:
deterministic per (input, seed)), match_matrix_tensor_op.cc
(x_i^T W_t y_j similarity cube), var_conv_2d_op.cc (conv over per-row
variable-sized grids → masked dense conv here), tree_conv_op.cc (TBCNN —
continuous window over parent/children with position-interpolated
left/right weights).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("pad_constant_like", nondiff_inputs=("X",))
def pad_constant_like(ins, attrs, ctx):
    """Out = Y padded up to X's shape with pad_value (grad flows to Y)."""
    x = ins["X"][0]
    y = ins["Y"][0]
    pad_value = float(attrs.get("pad_value", 0.0))
    pads = [(0, int(xs - ys)) for xs, ys in zip(x.shape, y.shape)]
    return {"Out": jnp.pad(y, pads, constant_values=pad_value)}


@register_op("squared_l2_distance",
             intermediate_outputs=("sub_result",))
def squared_l2_distance(ins, attrs, ctx):
    x = ins["X"][0]
    y = ins["Y"][0]
    sub = x - y                     # y broadcasts when it has one row
    # the reference flattens all non-batch dims before summing
    flat = sub.reshape(sub.shape[0], -1)
    return {"Out": jnp.sum(flat * flat, axis=-1, keepdims=True),
            "sub_result": flat}


@register_op("bilinear_tensor_product")
def bilinear_tensor_product(ins, attrs, ctx):
    """out[n,o] = x_n W_o y_n^T (+ bias)."""
    x = ins["X"][0]
    y = ins["Y"][0]
    w = ins["Weight"][0]            # [O, D1, D2]
    out = jnp.einsum("nd,ode,ne->no", x, w, y)
    if ins.get("Bias") and ins["Bias"][0] is not None:
        out = out + ins["Bias"][0].reshape(1, -1)
    return {"Out": out}


@register_op("conv_shift")
def conv_shift(ins, attrs, ctx):
    """Circular correlation (reference: conv_shift_op.cc): out[b, i] =
    Σ_j x[b, (i + j - M/2) mod N] · y[b, j], M odd, M <= N."""
    x = ins["X"][0]                 # [B, N]
    y = ins["Y"][0]                 # [B, M]
    b, n = x.shape
    m = y.shape[1]
    half = m // 2
    out = jnp.zeros_like(x)
    for j in range(m):
        out = out + jnp.roll(x, half - j, axis=1) * y[:, j:j + 1]
    return {"Out": out}


def _cvm_grad(ins, attrs, ctx):
    """reference: cvm_op.h CvmGradComputeKernel — dX[:, 0:2] is OVERWRITTEN
    with the CVM input's per-sample [show, click] values (not the autodiff
    of the log transform), so in the Downpour CTR flow the embedding's
    counter slots train through the injected CVM values; the tail gradient
    passes straight through (dY[:, 2:] with use_cvm, full dY without)."""
    from ..core.registry import GRAD_PREFIX_IG, GRAD_PREFIX_IN, GRAD_PREFIX_OG

    x = ins[GRAD_PREFIX_IN + "X"][0]
    cvm_in = ins[GRAD_PREFIX_IN + "CVM"][0]
    dy = ins[GRAD_PREFIX_OG + "Y"][0]
    use_cvm = bool(attrs.get("use_cvm", True))
    head = jnp.broadcast_to(cvm_in[:, :2],
                            (x.shape[0], 2)).astype(x.dtype)
    tail = dy[:, 2:] if use_cvm else dy
    return {GRAD_PREFIX_IG + "X": [jnp.concatenate([head, tail], axis=1)]}


@register_op("cvm", grad=_cvm_grad, nondiff_inputs=("CVM",))
def cvm(ins, attrs, ctx):
    """reference: cvm_op.h:26-40 — X rows are [show, click, emb...]; with
    use_cvm the two counters become [log(show+1), log(click+1)-log(show+1)];
    otherwise they are stripped."""
    x = ins["X"][0]
    use_cvm = bool(attrs.get("use_cvm", True))
    if use_cvm:
        show = jnp.log(x[:, 0:1] + 1.0)
        click = jnp.log(x[:, 1:2] + 1.0) - show
        return {"Y": jnp.concatenate([show, click, x[:, 2:]], axis=1)}
    return {"Y": x[:, 2:]}


def _int_hash(vals, seed):
    """splitmix64-style avalanche over the id window (uint32 lanes on TPU —
    jax has no uint64 math without x64); deterministic per (window, seed)."""
    h = jnp.uint32(0x9E3779B9) * jnp.uint32(seed + 1)
    for i in range(vals.shape[-1]):
        v = vals[..., i].astype(jnp.uint32)
        h = h ^ (v + jnp.uint32(0x85EBCA6B) + (h << 6) + (h >> 2))
        h = h * jnp.uint32(0xC2B2AE35)
        h = h ^ (h >> 16)
    # 31-bit result: stays non-negative through the int cast even when
    # int64 canonicalizes to int32 (x64 disabled)
    return h & jnp.uint32(0x7FFFFFFF)


@register_op("hash", grad=None, nondiff_inputs=("X",))
def hash_op(ins, attrs, ctx):
    """reference: hash_op.h:60-63 — out[idx, k] = hash_k(id window) %
    mod_by for k < num_hash. X [N, W] int → Out [N, num_hash] int64."""
    x = ins["X"][0]
    mod_by = int(attrs.get("mod_by", 100000))
    num_hash = int(attrs.get("num_hash", 1))
    if mod_by >= 2 ** 31:
        # the 31-bit hash is already < mod_by — modulus is a no-op, and
        # materializing mod_by overflows when int64 canonicalizes to int32
        outs = [_int_hash(x, k).astype(jnp.int64) for k in range(num_hash)]
    else:
        outs = [(_int_hash(x, k).astype(jnp.int64) % mod_by)
                for k in range(num_hash)]
    return {"Out": jnp.stack(outs, axis=-1)}


@register_op("match_matrix_tensor",
             intermediate_outputs=("Tmp",))
def match_matrix_tensor(ins, attrs, ctx):
    """reference: match_matrix_tensor_op.cc — similarity cube
    out[n, t, i, j] = x_i^T W_t y_j over [N,Tx,D] x [N,Ty,D] with
    W [D, dim_t, D]."""
    x = ins["X"][0]
    y = ins["Y"][0]
    w = ins["W"][0]                 # [D, dim_t, D]
    tmp = jnp.einsum("nid,dte->nite", x, w)      # [N, Tx, dim_t, D]
    out = jnp.einsum("nite,nje->ntij", tmp, y)   # [N, dim_t, Tx, Ty]
    return {"Out": out, "Tmp": tmp}


@register_op("var_conv_2d", nondiff_inputs=("ROW", "COLUMN"))
def var_conv_2d(ins, attrs, ctx):
    """reference: var_conv_2d_op.cc — per-row variable-sized 2-D conv;
    statically: mask the padded [N, C, H, W] input past each row/col
    length, run a dense conv2d."""
    x = ins["X"][0]
    w = ins["W"][0]                 # [out_ch, in_ch * kh * kw] or 4-D
    kh = int(attrs.get("kernel_h", 3))
    kw = int(attrs.get("kernel_w", 3))
    sh = int(attrs.get("stride_h", 1))
    sw = int(attrs.get("stride_w", 1))
    n, c, h, w_dim = x.shape
    if w.ndim == 2:
        w = w.reshape(w.shape[0], c, kh, kw)
    if ins.get("ROW") and ins["ROW"][0] is not None:
        rl = ins["ROW"][0].reshape(-1).astype(jnp.int32)
        x = x * (jnp.arange(h)[None, None, :, None] < rl[:, None, None,
                                                        None])
    if ins.get("COLUMN") and ins["COLUMN"][0] is not None:
        cl = ins["COLUMN"][0].reshape(-1).astype(jnp.int32)
        x = x * (jnp.arange(w_dim)[None, None, None, :] < cl[:, None, None,
                                                             None])
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCHW", "OIHW", "NCHW"))
    pad_h, pad_w = (kh - 1) // 2, (kw - 1) // 2
    out = jax.lax.conv_general_dilated(
        x, w, (sh, sw), [(pad_h, pad_h), (pad_w, pad_w)],
        dimension_numbers=dn)
    # mask outputs past each row's valid extent too — SAME-padded windows
    # just outside it still see valid cells (the reference computes only
    # over the valid grid)
    oh, ow = out.shape[2], out.shape[3]
    if ins.get("ROW") and ins["ROW"][0] is not None:
        orl = (rl + sh - 1) // sh
        out = out * (jnp.arange(oh)[None, None, :, None] <
                     orl[:, None, None, None])
    if ins.get("COLUMN") and ins["COLUMN"][0] is not None:
        ocl = (cl + sw - 1) // sw
        out = out * (jnp.arange(ow)[None, None, None, :] <
                     ocl[:, None, None, None])
    return {"Out": out}


@register_op("tree_conv", nondiff_inputs=("EdgeSet",))
def tree_conv(ins, attrs, ctx):
    """reference: tree_conv_op.cc + math/tree2col (TBCNN): a node's
    receptive field is its subtree down to attr max_depth (default 1);
    the filter has three weight planes (top/left/right). Depth-d
    descendants are reached through boolean adjacency powers; the top
    coefficient decays with depth, eta_t(d) = (max_depth - d)/max_depth,
    and each node's left/right coefficient is fixed by its position among
    its OWN siblings in edge order (it travels with the node into every
    ancestor's window). NodesVector [N, M, F], EdgeSet [N, E, 2] (parent, child;
    0,0 rows = padding, node ids 1-based like the reference), Filter
    [F, 3, C] → Out [N, M, C]."""
    nodes = ins["NodesVector"][0]
    edges = ins["EdgeSet"][0].astype(jnp.int32)
    filt = ins["Filter"][0]         # [F, 3, C]
    n, m, f = nodes.shape
    max_depth = int(attrs.get("max_depth", 1))

    e = edges.shape[1]

    def one(feat, edge):
        parent = edge[:, 0] - 1     # -1 = padding
        child = edge[:, 1] - 1
        valid = (edge[:, 0] > 0) & (edge[:, 1] > 0)
        adj = jnp.zeros((m, m), feat.dtype).at[
            jnp.maximum(parent, 0), jnp.maximum(child, 0)].max(
            valid.astype(feat.dtype))
        # per-NODE left/right coefficient from the node's position among
        # its siblings in EDGE order (tree2col semantics — it travels with
        # the node, whatever ancestor's window it appears in)
        same = (parent[None, :] == parent[:, None]) & valid[None, :] & \
            valid[:, None]
        before = jnp.tril(jnp.ones((e, e), bool), k=-1)
        rank = jnp.sum(same & before, axis=1).astype(feat.dtype)
        count = jnp.maximum(jnp.sum(same, axis=1), 1).astype(feat.dtype)
        edge_eta_r = jnp.where(count > 1,
                               rank / jnp.maximum(count - 1.0, 1.0), 0.5)
        eta_r = jnp.zeros((m,), feat.dtype).at[
            jnp.maximum(child, 0)].max(
            jnp.where(valid, edge_eta_r, 0.0))
        eta_l = 1.0 - eta_r
        wt, wl, wr = filt[:, 0], filt[:, 1], filt[:, 2]   # [F, C]
        out = feat @ wt                                    # self: eta_t=1
        reach = adj                                        # depth-1 reach
        for d in range(1, max_depth + 1):
            eta_t = (max_depth - d) / max_depth
            out = out + eta_t * (reach @ (feat @ wt))
            out = out + (1.0 - eta_t) * (
                (reach * eta_l[None, :]) @ (feat @ wl) +
                (reach * eta_r[None, :]) @ (feat @ wr))
            if d < max_depth:
                reach = jnp.minimum(reach @ adj, 1.0)
        return out

    out = jax.vmap(one)(nodes, edges)
    return {"Out": jnp.tanh(out)}


@register_op("filter_by_instag", nondiff_inputs=("Ins_tag", "Filter_tag"))
def filter_by_instag(ins, attrs, ctx):
    """reference: filter_by_instag_op.h — keep instances whose tag list
    intersects Filter_tag. Static shapes: kept rows compact to the top
    (zero-padded below), LossWeight marks kept rows 1.0/0.0, IndexMap
    row i holds [i, original_row] for kept rows (-1 padding). Ins_tag is
    the padded [N, T] tag matrix (LoD→padded, SURVEY §5); pad with any
    value not in Filter_tag (e.g. -1)."""
    x = ins["Ins"][0]                    # [N, D]
    tags = ins["Ins_tag"][0]             # [N, T] padded
    filt = ins["Filter_tag"][0].reshape(-1)
    if tags.ndim == 1:
        tags = tags[:, None]
    n = x.shape[0]
    hit = (tags[:, :, None] == filt[None, None, :]).any((1, 2))   # [N]
    order = jnp.argsort(jnp.where(hit, 0, 1), stable=True)
    kept_rows = jnp.where(hit[order][:, None], x[order], 0.0)
    n_kept = jnp.sum(hit.astype(jnp.int32))
    valid = jnp.arange(n) < n_kept
    index_map = jnp.where(
        valid[:, None],
        jnp.stack([jnp.arange(n), order], axis=1), -1).astype(jnp.int64)
    loss_weight = valid.astype(x.dtype)[:, None]
    return {"Out": kept_rows, "LossWeight": loss_weight,
            "IndexMap": index_map}
