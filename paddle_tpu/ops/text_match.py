"""Text-matching / CTR ops rounding out the pyramid family and misc
leftovers.

Reference behaviors: operators/pad_constant_like_op.cc,
squared_l2_distance_op.h, bilinear_tensor_product_op.h, conv_shift_op.cc
(circular correlation), cvm_op.h:26-40 (log show/click transform),
hash_op.h:60-63 (per-seed hash of the id window mod mod_by — XXH64 in the
reference; a splitmix-style integer hash here, same contract:
deterministic per (input, seed)), match_matrix_tensor_op.cc
(x_i^T W_t y_j similarity cube), var_conv_2d_op.cc (conv over per-row
variable-sized grids → masked dense conv here), tree_conv_op.cc (TBCNN —
continuous window over parent/children with position-interpolated
left/right weights).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("pad_constant_like", nondiff_inputs=("X",))
def pad_constant_like(ins, attrs, ctx):
    """Out = Y padded up to X's shape with pad_value (grad flows to Y)."""
    x = ins["X"][0]
    y = ins["Y"][0]
    pad_value = float(attrs.get("pad_value", 0.0))
    pads = [(0, int(xs - ys)) for xs, ys in zip(x.shape, y.shape)]
    return {"Out": jnp.pad(y, pads, constant_values=pad_value)}


@register_op("squared_l2_distance",
             intermediate_outputs=("sub_result",))
def squared_l2_distance(ins, attrs, ctx):
    x = ins["X"][0]
    y = ins["Y"][0]
    sub = x - y                     # y broadcasts when it has one row
    return {"Out": jnp.sum(sub * sub, axis=-1, keepdims=True),
            "sub_result": sub}


@register_op("bilinear_tensor_product")
def bilinear_tensor_product(ins, attrs, ctx):
    """out[n,o] = x_n W_o y_n^T (+ bias)."""
    x = ins["X"][0]
    y = ins["Y"][0]
    w = ins["Weight"][0]            # [O, D1, D2]
    out = jnp.einsum("nd,ode,ne->no", x, w, y)
    if ins.get("Bias") and ins["Bias"][0] is not None:
        out = out + ins["Bias"][0].reshape(1, -1)
    return {"Out": out}


@register_op("conv_shift")
def conv_shift(ins, attrs, ctx):
    """Circular correlation (reference: conv_shift_op.cc): out[b, i] =
    Σ_j x[b, (i + j - M/2) mod N] · y[b, j], M odd, M <= N."""
    x = ins["X"][0]                 # [B, N]
    y = ins["Y"][0]                 # [B, M]
    b, n = x.shape
    m = y.shape[1]
    half = m // 2
    out = jnp.zeros_like(x)
    for j in range(m):
        out = out + jnp.roll(x, half - j, axis=1) * y[:, j:j + 1]
    return {"Out": out}


@register_op("cvm", nondiff_inputs=("CVM",))
def cvm(ins, attrs, ctx):
    """reference: cvm_op.h:26-40 — X rows are [show, click, emb...]; with
    use_cvm the two counters become [log(show+1), log(click+1)-log(show+1)];
    otherwise they are stripped."""
    x = ins["X"][0]
    use_cvm = bool(attrs.get("use_cvm", True))
    if use_cvm:
        show = jnp.log(x[:, 0:1] + 1.0)
        click = jnp.log(x[:, 1:2] + 1.0) - show
        return {"Y": jnp.concatenate([show, click, x[:, 2:]], axis=1)}
    return {"Y": x[:, 2:]}


def _int_hash(vals, seed):
    """splitmix64-style avalanche over the id window (uint32 lanes on TPU —
    jax has no uint64 math without x64); deterministic per (window, seed)."""
    h = jnp.uint32(0x9E3779B9) * jnp.uint32(seed + 1)
    for i in range(vals.shape[-1]):
        v = vals[..., i].astype(jnp.uint32)
        h = h ^ (v + jnp.uint32(0x85EBCA6B) + (h << 6) + (h >> 2))
        h = h * jnp.uint32(0xC2B2AE35)
        h = h ^ (h >> 16)
    return h


@register_op("hash", grad=None, nondiff_inputs=("X",))
def hash_op(ins, attrs, ctx):
    """reference: hash_op.h:60-63 — out[idx, k] = hash_k(id window) %
    mod_by for k < num_hash. X [N, W] int → Out [N, num_hash] int64."""
    x = ins["X"][0]
    mod_by = int(attrs.get("mod_by", 100000))
    num_hash = int(attrs.get("num_hash", 1))
    outs = [(_int_hash(x, k) % jnp.uint32(mod_by)).astype(jnp.int64)
            for k in range(num_hash)]
    return {"Out": jnp.stack(outs, axis=-1)}


@register_op("match_matrix_tensor",
             intermediate_outputs=("Tmp",))
def match_matrix_tensor(ins, attrs, ctx):
    """reference: match_matrix_tensor_op.cc — similarity cube
    out[n, t, i, j] = x_i^T W_t y_j over [N,Tx,D] x [N,Ty,D] with
    W [D, dim_t, D]."""
    x = ins["X"][0]
    y = ins["Y"][0]
    w = ins["W"][0]                 # [D, dim_t, D]
    tmp = jnp.einsum("nid,dte->nite", x, w)      # [N, Tx, dim_t, D]
    out = jnp.einsum("nite,nje->ntij", tmp, y)   # [N, dim_t, Tx, Ty]
    return {"Out": out, "Tmp": tmp}


@register_op("var_conv_2d", nondiff_inputs=("ROW", "COLUMN"))
def var_conv_2d(ins, attrs, ctx):
    """reference: var_conv_2d_op.cc — per-row variable-sized 2-D conv;
    statically: mask the padded [N, C, H, W] input past each row/col
    length, run a dense conv2d."""
    x = ins["X"][0]
    w = ins["W"][0]                 # [out_ch, in_ch * kh * kw] or 4-D
    kh = int(attrs.get("kernel_h", 3))
    kw = int(attrs.get("kernel_w", 3))
    sh = int(attrs.get("stride_h", 1))
    sw = int(attrs.get("stride_w", 1))
    n, c, h, w_dim = x.shape
    if w.ndim == 2:
        w = w.reshape(w.shape[0], c, kh, kw)
    if ins.get("ROW") and ins["ROW"][0] is not None:
        rl = ins["ROW"][0].reshape(-1).astype(jnp.int32)
        x = x * (jnp.arange(h)[None, None, :, None] < rl[:, None, None,
                                                        None])
    if ins.get("COLUMN") and ins["COLUMN"][0] is not None:
        cl = ins["COLUMN"][0].reshape(-1).astype(jnp.int32)
        x = x * (jnp.arange(w_dim)[None, None, None, :] < cl[:, None, None,
                                                             None])
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCHW", "OIHW", "NCHW"))
    pad_h, pad_w = (kh - 1) // 2, (kw - 1) // 2
    out = jax.lax.conv_general_dilated(
        x, w, (sh, sw), [(pad_h, pad_h), (pad_w, pad_w)],
        dimension_numbers=dn)
    # mask outputs past each row's valid extent too — SAME-padded windows
    # just outside it still see valid cells (the reference computes only
    # over the valid grid)
    oh, ow = out.shape[2], out.shape[3]
    if ins.get("ROW") and ins["ROW"][0] is not None:
        orl = (rl + sh - 1) // sh
        out = out * (jnp.arange(oh)[None, None, :, None] <
                     orl[:, None, None, None])
    if ins.get("COLUMN") and ins["COLUMN"][0] is not None:
        ocl = (cl + sw - 1) // sw
        out = out * (jnp.arange(ow)[None, None, None, :] <
                     ocl[:, None, None, None])
    return {"Out": out}


@register_op("tree_conv", nondiff_inputs=("EdgeSet",))
def tree_conv(ins, attrs, ctx):
    """reference: tree_conv_op.cc + math/tree2col (TBCNN): each node's
    receptive field is itself + its children; the filter has three weight
    planes (top/left/right) mixed by continuous position coefficients —
    eta_t = 1 for the node, children interpolate left↔right by sibling
    position. NodesVector [N, M, F], EdgeSet [N, E, 2] (parent, child;
    0,0 rows = padding, node ids 1-based like the reference), Filter
    [F, 3, C] → Out [N, M, C]."""
    nodes = ins["NodesVector"][0]
    edges = ins["EdgeSet"][0].astype(jnp.int32)
    filt = ins["Filter"][0]         # [F, 3, C]
    n, m, f = nodes.shape
    e = edges.shape[1]

    def one(feat, edge):
        parent = edge[:, 0] - 1     # -1 = padding
        child = edge[:, 1] - 1
        valid = (edge[:, 0] > 0) & (edge[:, 1] > 0)
        # sibling position: rank of each edge among edges sharing a parent
        same = (parent[None, :] == parent[:, None]) & valid[None, :] & \
            valid[:, None]
        before = jnp.tril(jnp.ones((e, e), bool), k=-1)
        rank = jnp.sum(same & before, axis=1)
        count = jnp.maximum(jnp.sum(same, axis=1), 1)
        # eta_r grows with sibling position, eta_l = 1 - eta_r (TBCNN)
        eta_r = jnp.where(count > 1, rank / jnp.maximum(count - 1, 1),
                          0.5).astype(feat.dtype)
        eta_l = 1.0 - eta_r
        wt, wl, wr = filt[:, 0], filt[:, 1], filt[:, 2]   # [F, C]
        out = feat @ wt                                    # self (top)
        child_feat = feat[jnp.maximum(child, 0)]           # [E, F]
        contrib = child_feat @ wl * eta_l[:, None] + \
            child_feat @ wr * eta_r[:, None]
        contrib = jnp.where(valid[:, None], contrib, 0.0)
        out = out.at[jnp.maximum(parent, 0)].add(contrib)
        return out

    out = jax.vmap(one)(nodes, edges)
    return {"Out": jnp.tanh(out)}
