"""Fused matmul+BatchNorm building blocks for 1x1 convolutions.

The ResNet-50 byte-floor analysis (PROFILE.md round 5,
tools/rn50_bytes_table.py) shows BN passes are 44% of the training
step's HBM traffic and the ONLY lever that reaches the >=0.40 MFU bar —
XLA cannot fuse across the BN-stats reduction barrier. These kernels
implement the forward half of that line-item for the 1x1 convs (2/3 of
ResNet-50's conv units; a 1x1 conv over NHWC is exactly a [B*H*W, Cin]
@ [Cin, Cout] matmul):

- matmul_stats:   y = x @ w, with per-channel sum/sumsq accumulated in
                  the kernel epilogue — the separate BN-stats read pass
                  over y never happens.
- bn_act_matmul:  y = act(norm(x)) @ w — the PRODUCER's BN-apply is
                  fused into the CONSUMER matmul's prologue, so the
                  normalized activation never reaches HBM (saves the
                  apply read+write passes).

Together these remove ~3 of the 6 modeled BN passes per conv unit
(bytes table: floor 95 -> ~81 ms, ceiling MFU 0.337 -> ~0.395 at
bs=256). Backward is the XLA reference implementation via custom_vjp
(rematerialized from the raw inputs — same bytes as the unfused
backward; fusing the backward is the remaining half of the line-item).

Reference analogue: none — the reference computes conv, BN-stats and
BN-apply as separate C++/cuDNN ops (batch_norm_op.cc, conv_op.cc); this
fusion is TPU-native ground. Off-TPU the kernels run under the pallas
interpreter, so CPU tests execute the real kernel bodies. Like every
pallas op here, the kernels require a single device or a shard_map
manual region (pallas_call has no GSPMD partitioning rule).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _block(n, cap):
    """Largest divisor of n that is <= cap (TPU-friendly caps are
    multiples of 128; inputs here are conv channel counts, powers of 2)."""
    b = min(n, cap)
    while n % b:
        b -= 1
    return b


# ---------------------------------------------------------------------------
# matmul with stats epilogue
# ---------------------------------------------------------------------------


def _mm_stats_kernel(x_ref, w_ref, y_ref, ps_ref, pss_ref):
    # accumulation dtype rides on the stats refs (f32 normally; f64 under
    # the x64 parity rig, where interpret mode executes on CPU)
    y = jnp.dot(x_ref[...], w_ref[...],
                preferred_element_type=ps_ref.dtype)
    y_ref[...] = y.astype(y_ref.dtype)
    # per-(row-block, col-block) partial channel sums; finished by a tiny
    # [gm, N] reduction outside the kernel
    ps_ref[...] = jnp.sum(y, axis=0, keepdims=True)
    pss_ref[...] = jnp.sum(y * y, axis=0, keepdims=True)


def _acc_dt(x):
    return jnp.promote_types(x.dtype, jnp.float32)


def _mm_stats_pallas(x, w, interpret):
    M, K = x.shape
    K2, N = w.shape
    acc = _acc_dt(x)
    bm = _block(M, 512)
    bn = _block(N, 512)
    gm, gn = M // bm, N // bn
    y, ps, pss = pl.pallas_call(
        _mm_stats_kernel,
        grid=(gm, gn),
        in_specs=[pl.BlockSpec((bm, K), lambda i, j: (i, 0)),
                  pl.BlockSpec((K, bn), lambda i, j: (0, j))],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
                   pl.BlockSpec((1, bn), lambda i, j: (i, j)),
                   pl.BlockSpec((1, bn), lambda i, j: (i, j))],
        out_shape=[jax.ShapeDtypeStruct((M, N), x.dtype),
                   jax.ShapeDtypeStruct((gm, N), acc),
                   jax.ShapeDtypeStruct((gm, N), acc)],
        interpret=interpret,
    )(x, w)
    s = jnp.sum(ps, axis=0)
    ss = jnp.sum(pss, axis=0)
    mean = s / M
    var = jnp.maximum(ss / M - mean * mean, 0.0)
    return y, mean, var


def _mm_stats_ref(x, w):
    """XLA reference: semantically what the kernel computes (promoted
    accumulation, one-pass E[y^2]-E[y]^2 stats)."""
    y32 = jnp.dot(x, w, preferred_element_type=_acc_dt(x))
    y = y32.astype(x.dtype)
    mean = jnp.mean(y32, axis=0)
    var = jnp.maximum(jnp.mean(y32 * y32, axis=0) - mean * mean, 0.0)
    return y, mean, var


@jax.custom_vjp
def matmul_stats(x, w):
    """y = x @ w plus per-output-channel (mean, biased var), with the
    stats accumulated in the matmul's epilogue — the BN-stats pass over
    y never touches HBM. x: [M, K]; w: [K, N] -> (y [M,N], mean [N],
    var [N], both f32)."""
    return _mm_stats_pallas(x, w, interpret=_interpret())


def _mm_stats_fwd(x, w):
    return matmul_stats(x, w), (x, w)


def _mm_stats_bwd(res, cts):
    x, w = res
    _, pull = jax.vjp(_mm_stats_ref, x, w)
    return pull(cts)


matmul_stats.defvjp(_mm_stats_fwd, _mm_stats_bwd)


# ---------------------------------------------------------------------------
# BN-apply (+activation) fused into the consumer matmul's prologue
# ---------------------------------------------------------------------------


def _bn_mm_kernel(x_ref, s_ref, b_ref, w_ref, y_ref, *, relu):
    xn = (x_ref[...].astype(s_ref.dtype) * s_ref[...]
          + b_ref[...])
    if relu:
        xn = jnp.maximum(xn, 0.0)
    y_ref[...] = jnp.dot(xn.astype(x_ref.dtype), w_ref[...],
                         preferred_element_type=s_ref.dtype
                         ).astype(y_ref.dtype)


def _bn_mm_pallas(x, scale, shift, w, relu, interpret):
    M, K = x.shape
    K2, N = w.shape
    bm = _block(M, 512)
    bn = _block(N, 512)
    return pl.pallas_call(
        functools.partial(_bn_mm_kernel, relu=relu),
        grid=(M // bm, N // bn),
        in_specs=[pl.BlockSpec((bm, K), lambda i, j: (i, 0)),
                  pl.BlockSpec((1, K), lambda i, j: (0, 0)),
                  pl.BlockSpec((1, K), lambda i, j: (0, 0)),
                  pl.BlockSpec((K, bn), lambda i, j: (0, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        interpret=interpret,
    )(x, scale.reshape(1, K), shift.reshape(1, K), w)


def _bn_mm_ref(x, scale, shift, w, relu):
    xn = x.astype(scale.dtype) * scale + shift
    if relu:
        xn = jnp.maximum(xn, 0.0)
    return jnp.dot(xn.astype(x.dtype), w,
                   preferred_element_type=scale.dtype).astype(x.dtype)


def bn_act_matmul(x, scale, shift, w, relu=True):
    """y = act(x * scale + shift) @ w, the normalization applied in the
    matmul prologue — the normalized tensor never reaches HBM. Callers
    fold BN into (scale, shift): scale = gamma * rsqrt(var + eps),
    shift = beta - mean * scale (both [K], f32). x: [M, K]; w: [K, N]."""
    return _bn_act_matmul(bool(relu), x, scale, shift, w)


# custom_vjp takes positional args only; the static relu flag leads
@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _bn_act_matmul(relu, x, scale, shift, w):
    return _bn_mm_pallas(x, scale, shift, w, relu,
                         interpret=_interpret())


def _bn_mm_fwd(relu, x, scale, shift, w):
    return _bn_act_matmul(relu, x, scale, shift, w), (x, scale, shift, w)


def _bn_mm_bwd(relu, res, ct):
    x, scale, shift, w = res
    _, pull = jax.vjp(
        lambda x, s, b, w: _bn_mm_ref(x, s, b, w, relu), x, scale,
        shift, w)
    return pull(ct)


_bn_act_matmul.defvjp(_bn_mm_fwd, _bn_mm_bwd)


# ---------------------------------------------------------------------------
# combined: BN-apply prologue + stats epilogue in one kernel
# ---------------------------------------------------------------------------


def _bn_mm_stats_kernel(x_ref, s_ref, b_ref, w_ref, y_ref, ps_ref,
                        pss_ref, *, relu):
    xn = x_ref[...].astype(s_ref.dtype) * s_ref[...] + b_ref[...]
    if relu:
        xn = jnp.maximum(xn, 0.0)
    y = jnp.dot(xn.astype(x_ref.dtype), w_ref[...],
                preferred_element_type=ps_ref.dtype)
    y_ref[...] = y.astype(y_ref.dtype)
    ps_ref[...] = jnp.sum(y, axis=0, keepdims=True)
    pss_ref[...] = jnp.sum(y * y, axis=0, keepdims=True)


def _bn_mm_stats_ref(x, scale, shift, w, relu):
    # stats from the PRE-downcast accumulator product, mirroring both
    # _mm_stats_ref and the kernel (which reduces the f32 `y` before
    # y_ref downcasts it): at bf16 the bwd must differentiate the same
    # stats the fwd computed, not stats of the already-rounded y
    # (ADVICE r5)
    xn = x.astype(scale.dtype) * scale + shift
    if relu:
        xn = jnp.maximum(xn, 0.0)
    y32 = jnp.dot(xn.astype(x.dtype), w,
                  preferred_element_type=_acc_dt(x))
    y = y32.astype(x.dtype)
    mean = jnp.mean(y32, axis=0)
    var = jnp.maximum(jnp.mean(y32 * y32, axis=0) - mean * mean, 0.0)
    return y, mean, var


def bn_act_matmul_stats(x, scale, shift, w, relu=True):
    """The full producer/consumer fusion: y = act(x*scale+shift) @ w with
    (mean, var) of y accumulated in the same kernel — the previous BN's
    apply AND this conv's stats pass both disappear from HBM traffic.
    This is ResNet's conv3 shape: bn2-apply+relu in the prologue, bn3
    stats in the epilogue."""
    return _bn_act_matmul_stats(bool(relu), x, scale, shift, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _bn_act_matmul_stats(relu, x, scale, shift, w):
    M, K = x.shape
    K2, N = w.shape
    bm = _block(M, 512)
    bn = _block(N, 512)
    gm = M // bm
    acc = _acc_dt(x)
    y, ps, pss = pl.pallas_call(
        functools.partial(_bn_mm_stats_kernel, relu=relu),
        grid=(gm, N // bn),
        in_specs=[pl.BlockSpec((bm, K), lambda i, j: (i, 0)),
                  pl.BlockSpec((1, K), lambda i, j: (0, 0)),
                  pl.BlockSpec((1, K), lambda i, j: (0, 0)),
                  pl.BlockSpec((K, bn), lambda i, j: (0, j))],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
                   pl.BlockSpec((1, bn), lambda i, j: (i, j)),
                   pl.BlockSpec((1, bn), lambda i, j: (i, j))],
        out_shape=[jax.ShapeDtypeStruct((M, N), x.dtype),
                   jax.ShapeDtypeStruct((gm, N), acc),
                   jax.ShapeDtypeStruct((gm, N), acc)],
        interpret=_interpret(),
    )(x, scale.reshape(1, K), shift.reshape(1, K), w)
    s = jnp.sum(ps, axis=0)
    ss = jnp.sum(pss, axis=0)
    mean = s / M
    var = jnp.maximum(ss / M - mean * mean, 0.0)
    return y, mean, var


def _bn_mm_stats_fwd(relu, x, scale, shift, w):
    return (_bn_act_matmul_stats(relu, x, scale, shift, w),
            (x, scale, shift, w))


def _bn_mm_stats_bwd(relu, res, cts):
    x, scale, shift, w = res
    _, pull = jax.vjp(
        lambda x, s, b, w: _bn_mm_stats_ref(x, s, b, w, relu), x, scale,
        shift, w)
    return pull(cts)


_bn_act_matmul_stats.defvjp(_bn_mm_stats_fwd, _bn_mm_stats_bwd)


def _interpret() -> bool:
    """Run under the pallas interpreter off-TPU (same kernel body, CPU
    execution) — how the tests drive these kernels."""
    from paddle_tpu.parallel.mesh import current_mesh

    m = current_mesh()
    if m is not None:
        return m.devices.flat[0].platform != "tpu"
    return jax.default_backend() != "tpu"


def fold_bn(mean, var, gamma, beta, eps=1e-5):
    """(mean, var, gamma, beta) -> (scale, shift) for bn_act_matmul."""
    scale = gamma * jax.lax.rsqrt(var + eps)
    return scale, beta - mean * scale
