"""Fused multi-head attention.

Reference: no TPU counterpart — the reference computes attention from
unfused matmul/softmax ops (e.g. the BERT graph in
inference/tests/api/analyzer_bert_tester.cc). TPU-native: a Pallas
flash-attention kernel (online softmax, O(T) memory) on TPU backends, an
XLA einsum+softmax fallback elsewhere. The f32 fallback is semantically
identical to the flash kernel, so tests run on CPU; for bf16 inputs the
fallback stores the T x T logits in bf16 (f32-accumulated, f32 softmax —
halves score-buffer HBM traffic; see PROFILE.md), which rounds logits to
bf16 precision relative to the kernel's f32 score pipeline.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp


def _xla_mha(q, k, v, mask, scale):
    """[B,T,N,H] attention via plain XLA ops (fallback + reference).

    bf16 inputs keep the T x T score tensor in bf16 (the einsum still
    accumulates in f32 on the MXU; softmax upcasts to f32 after the
    max-subtraction-safe store) — at BERT shapes the f32 score buffers
    were ~15% of step HBM traffic (measured 172->153 ms fwd+bwd, bs=256
    seq=128 v5e). Wider dtypes keep the fully-f32 path."""
    if q.dtype == jnp.bfloat16:
        # f32 accumulation made explicit; the immediate bf16 cast fuses
        # into the matmul epilogue so only bf16 buffers reach HBM
        logits = jnp.einsum(
            "btnh,bsnh->bnts", q, k,
            preferred_element_type=jnp.float32).astype(jnp.bfloat16) * \
            jnp.asarray(scale, jnp.bfloat16)
        if mask is not None:
            logits = logits + mask.astype(logits.dtype)
        probs = jax.nn.softmax(logits.astype(jnp.float32),
                               axis=-1).astype(v.dtype)
        return jnp.einsum("bnts,bsnh->btnh", probs, v,
                          preferred_element_type=jnp.float32).astype(v.dtype)
    logits = jnp.einsum("btnh,bsnh->bnts", q, k).astype(jnp.float32) * scale
    if mask is not None:
        logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bnts,bsnh->btnh", probs, v)


def _use_pallas(q) -> bool:
    try:
        dev = q.devices() if hasattr(q, "devices") else None
    except Exception:
        dev = None
    platform = None
    if dev:
        platform = next(iter(dev)).platform
    else:
        # Tracers carry no devices; the active mesh (if any) says where the
        # computation will actually run — it may be a CPU mesh even when
        # the default backend is TPU (dryrun_multichip's in-process mode).
        from paddle_tpu.parallel.mesh import current_mesh
        m = current_mesh()
        if m is not None:
            platform = m.devices.flat[0].platform
        else:
            platform = jax.default_backend()
    if platform != "tpu" or q.ndim != 4:
        return False
    return _gate_allows(q.shape[1])


def _gate_allows(T: int) -> bool:
    """Mode dispatch of the flash gate, separated from the platform check
    so the decision logic is unit-testable off-TPU."""
    from ...core.flags import get_flag

    mode = str(get_flag("FLAGS_flash_attention")).lower()
    if mode in ("on", "1", "true"):
        return True
    if mode in ("off", "0", "false"):
        return False
    # Measured on v5e (BERT-base training steps, bf16-scores XLA path as
    # the baseline): flash is 2.5x slower at T=128, 2.1x at 512, 2.3x at
    # 1024, 2.7x at 2048, 2.8x at 4096 (bs=2), 2.7x at 8192 (bs=1), 2.8x
    # at 16384 (bs=1) — and XLA + rematerialization FITS at every one of
    # those shapes, so the round-2 hypothesis that score buffers crowd
    # HBM at T>=4096 is refuted on this chip/kernel version. Auto
    # therefore never selects the jax-shipped flash kernel; it remains an
    # explicit opt-in (FLAGS_flash_attention=on) and the long-context
    # scaling path is exact ring attention over the 'sp' mesh axis
    # (ops/pallas/ring_attention.py). Full table: PROFILE.md round 3;
    # re-measured on-chip each round by bench.py's bert_long config.
    del T
    return False


def mha(q: jax.Array, k: jax.Array, v: jax.Array,
        mask: Optional[jax.Array] = None, scale: Optional[float] = None,
        causal: bool = False) -> jax.Array:
    """Multi-head attention over [B, T, N, H] tensors.

    mask: additive [B, 1, 1, T] or [B, N, T, T] (float, -inf style), or None.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if _use_pallas(q):
        try:
            return _pallas_mha(q, k, v, mask, scale, causal)
        except Exception:  # fall back if kernel unsupported on this shape
            pass
    out = _xla_mha(q, k, v, mask if not causal else _merge_causal(mask, q.shape[1]), scale)
    return out.astype(q.dtype)


def _merge_causal(mask, T):
    cm = jnp.where(jnp.tril(jnp.ones((T, T), jnp.bool_)), 0.0, -1e9)[None, None]
    return cm if mask is None else mask + cm


# ---------------------------------------------------------------------------
# Pallas flash-attention kernel (TPU)
# ---------------------------------------------------------------------------


def _pallas_mha(q, k, v, mask, scale, causal):
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention)

    # pallas kernel wants [B, N, T, H]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    ab = None
    if mask is not None:
        ab = jnp.broadcast_to(
            mask.astype(jnp.float32),
            (q.shape[0], q.shape[2], q.shape[1], k.shape[1]))
    out = flash_attention(qt, kt, vt, ab=ab, causal=causal,
                          sm_scale=float(scale))
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
