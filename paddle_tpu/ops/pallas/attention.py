"""Fused multi-head attention.

Reference: no TPU counterpart — the reference computes attention from
unfused matmul/softmax ops (e.g. the BERT graph in
inference/tests/api/analyzer_bert_tester.cc). TPU-native: a Pallas
flash-attention kernel (online softmax, O(T) memory) on TPU backends, an
XLA einsum+softmax fallback elsewhere. The f32 fallback is semantically
identical to the flash kernel, so tests run on CPU; for bf16 inputs the
fallback stores the T x T logits in bf16 (f32-accumulated, f32 softmax —
halves score-buffer HBM traffic; see PROFILE.md), which rounds logits to
bf16 precision relative to the kernel's f32 score pipeline.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp


def _xla_mha(q, k, v, mask, scale):
    """[B,T,N,H] attention via plain XLA ops (fallback + reference).

    bf16 inputs keep the T x T score tensor in bf16 (the einsum still
    accumulates in f32 on the MXU; softmax upcasts to f32 after the
    max-subtraction-safe store) — at BERT shapes the f32 score buffers
    were ~15% of step HBM traffic (measured 172->153 ms fwd+bwd, bs=256
    seq=128 v5e). Wider dtypes keep the fully-f32 path."""
    if q.dtype == jnp.bfloat16:
        # f32 accumulation made explicit; the immediate bf16 cast fuses
        # into the matmul epilogue so only bf16 buffers reach HBM
        logits = jnp.einsum(
            "btnh,bsnh->bnts", q, k,
            preferred_element_type=jnp.float32).astype(jnp.bfloat16) * \
            jnp.asarray(scale, jnp.bfloat16)
        if mask is not None:
            logits = logits + mask.astype(logits.dtype)
        probs = jax.nn.softmax(logits.astype(jnp.float32),
                               axis=-1).astype(v.dtype)
        return jnp.einsum("bnts,bsnh->btnh", probs, v,
                          preferred_element_type=jnp.float32).astype(v.dtype)
    logits = jnp.einsum("btnh,bsnh->bnts", q, k).astype(jnp.float32) * scale
    if mask is not None:
        logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bnts,bsnh->btnh", probs, v)


def _use_pallas(q) -> bool:
    try:
        dev = q.devices() if hasattr(q, "devices") else None
    except Exception:
        dev = None
    platform = None
    if dev:
        platform = next(iter(dev)).platform
    else:
        # Tracers carry no devices; the active mesh (if any) says where the
        # computation will actually run — it may be a CPU mesh even when
        # the default backend is TPU (dryrun_multichip's in-process mode).
        from paddle_tpu.parallel.mesh import current_mesh
        m = current_mesh()
        if m is not None:
            platform = m.devices.flat[0].platform
        else:
            platform = jax.default_backend()
    # Measured on v5e (BERT-base fwd+bwd, bf16-scores XLA fallback as the
    # baseline): flash is 2.5x slower at T=128, 2.1x at 512, 2.3x at
    # 1024, 2.7x at 2048 — the bf16 score path keeps XLA ahead at every
    # practical T on this chip/kernel version. Flash's remaining value is
    # its O(T) memory: at T>=4096 the [B,N,T,T] bf16 score tensors start
    # crowding HBM (>=400 MB/layer), so the gate switches there for
    # memory, not speed (PROFILE.md).
    return platform == "tpu" and q.ndim == 4 and q.shape[1] >= 4096


def mha(q: jax.Array, k: jax.Array, v: jax.Array,
        mask: Optional[jax.Array] = None, scale: Optional[float] = None,
        causal: bool = False) -> jax.Array:
    """Multi-head attention over [B, T, N, H] tensors.

    mask: additive [B, 1, 1, T] or [B, N, T, T] (float, -inf style), or None.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if _use_pallas(q):
        try:
            return _pallas_mha(q, k, v, mask, scale, causal)
        except Exception:  # fall back if kernel unsupported on this shape
            pass
    out = _xla_mha(q, k, v, mask if not causal else _merge_causal(mask, q.shape[1]), scale)
    return out.astype(q.dtype)


def _merge_causal(mask, T):
    cm = jnp.where(jnp.tril(jnp.ones((T, T), jnp.bool_)), 0.0, -1e9)[None, None]
    return cm if mask is None else mask + cm


# ---------------------------------------------------------------------------
# Pallas flash-attention kernel (TPU)
# ---------------------------------------------------------------------------


def _pallas_mha(q, k, v, mask, scale, causal):
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention)

    # pallas kernel wants [B, N, T, H]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    ab = None
    if mask is not None:
        ab = jnp.broadcast_to(
            mask.astype(jnp.float32),
            (q.shape[0], q.shape[2], q.shape[1], k.shape[1]))
    out = flash_attention(qt, kt, vt, ab=ab, causal=causal,
                          sm_scale=float(scale))
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
