"""Fused multi-head attention.

Reference: no TPU counterpart — the reference computes attention from
unfused matmul/softmax ops (e.g. the BERT graph in
inference/tests/api/analyzer_bert_tester.cc). TPU-native: a Pallas
flash-attention kernel (online softmax, O(T) memory) on TPU backends, an
XLA einsum+softmax fallback elsewhere. The f32 fallback is semantically
identical to the flash kernel, so tests run on CPU; for bf16 inputs the
fallback stores the T x T logits in bf16 (f32-accumulated, f32 softmax —
halves score-buffer HBM traffic; see PROFILE.md), which rounds logits to
bf16 precision relative to the kernel's f32 score pipeline.
"""

from __future__ import annotations

import collections
import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

# Trace-time gate observability: which attention path was selected.
# Keys: "splash" (single-device / manual region), "splash_shardmap"
# (dp/tp shard_map wrapper), "ring_splash" (sp ring with splash blocks),
# "ring_xla" (sp ring, XLA blocks), "pallas_flash" (legacy kernel),
# "xla". Incremented once per mha() trace; reset with GATE_COUNTS.clear()
# in tests/dryruns to assert a path actually engaged (VERDICT r5 item 4).
GATE_COUNTS: collections.Counter = collections.Counter()


def _xla_mha(q, k, v, mask, scale):
    """[B,T,N,H] attention via plain XLA ops (fallback + reference).

    bf16 inputs keep the T x T score tensor in bf16 (the einsum still
    accumulates in f32 on the MXU; softmax upcasts to f32 after the
    max-subtraction-safe store) — at BERT shapes the f32 score buffers
    were ~15% of step HBM traffic (measured 172->153 ms fwd+bwd, bs=256
    seq=128 v5e). Wider dtypes keep the fully-f32 path."""
    if q.dtype == jnp.bfloat16:
        # f32 accumulation made explicit; the immediate bf16 cast fuses
        # into the matmul epilogue so only bf16 buffers reach HBM
        logits = jnp.einsum(
            "btnh,bsnh->bnts", q, k,
            preferred_element_type=jnp.float32).astype(jnp.bfloat16) * \
            jnp.asarray(scale, jnp.bfloat16)
        if mask is not None:
            logits = logits + mask.astype(logits.dtype)
        probs = jax.nn.softmax(logits.astype(jnp.float32),
                               axis=-1).astype(v.dtype)
        return jnp.einsum("bnts,bsnh->btnh", probs, v,
                          preferred_element_type=jnp.float32).astype(v.dtype)
    logits = jnp.einsum("btnh,bsnh->bnts", q, k).astype(jnp.float32) * scale
    if mask is not None:
        logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bnts,bsnh->btnh", probs, v)


def _platform(q) -> str:
    """Where this computation will actually run. Tracers carry no devices;
    the active mesh (if any) decides — it may be a CPU mesh even when the
    default backend is TPU (dryrun_multichip's in-process mode)."""
    try:
        dev = q.devices() if hasattr(q, "devices") else None
    except Exception:
        dev = None
    if dev:
        return next(iter(dev)).platform
    from paddle_tpu.parallel.mesh import current_mesh
    m = current_mesh()
    if m is not None:
        return m.devices.flat[0].platform
    return jax.default_backend()


try:  # private but the only trace-time manual-region signal (jax 0.9)
    from jax._src.core import get_axis_env as _get_axis_env
except ImportError:  # jax moved the symbol: detection unavailable
    _get_axis_env = None
    import warnings

    warnings.warn(
        "jax._src.core.get_axis_env unavailable: pallas attention kernels "
        "are disabled under >1-device meshes (cannot detect shard_map "
        "manual regions); update _mesh_partitionable for this jax version")


def _mesh_partitionable(q) -> bool:
    """A pallas_call has no GSPMD partitioning rule: under a >1-device
    mesh outside a shard_map manual region, XLA would all-gather the
    operands (defeating dp/sp/tp sharding) or fail at lowering — which
    the trace-time try/except in mha() cannot catch. Inside a manual
    region shapes are already per-device local, so the kernel is safe."""
    from paddle_tpu.parallel.mesh import current_mesh
    m = current_mesh()
    if m is None or m.devices.size == 1:
        return True
    if _get_axis_env is None:
        return False  # conservative: warned once at import above
    return bool(_get_axis_env().axis_sizes)  # inside shard_map


def _use_pallas(q) -> bool:
    if _platform(q) != "tpu" or q.ndim != 4 or not _mesh_partitionable(q):
        return False
    return _gate_allows(q.shape[1])


def _gate_allows(T: int) -> bool:
    """Mode dispatch of the flash gate, separated from the platform check
    so the decision logic is unit-testable off-TPU."""
    from ...core.flags import get_flag

    mode = str(get_flag("FLAGS_flash_attention")).lower()
    if mode in ("on", "1", "true"):
        return True
    if mode in ("off", "0", "false"):
        return False
    # Measured on v5e (BERT-base training steps, bf16-scores XLA path as
    # the baseline): flash is 2.5x slower at T=128, 2.1x at 512, 2.3x at
    # 1024, 2.7x at 2048, 2.8x at 4096 (bs=2), 2.7x at 8192 (bs=1), 2.8x
    # at 16384 (bs=1) — and XLA + rematerialization FITS at every one of
    # those shapes, so the round-2 hypothesis that score buffers crowd
    # HBM at T>=4096 is refuted on this chip/kernel version. Auto
    # therefore never selects the jax-shipped LEGACY flash kernel; it
    # remains an explicit opt-in (FLAGS_flash_attention=on). The long-T
    # single-chip path is splash_attention (_use_splash, round 4 — tuned
    # blocks beat XLA bf16-scores 2.2x at T=4096), and long-context
    # *scaling* is exact ring attention over the 'sp' mesh axis
    # (ops/pallas/ring_attention.py). Full tables: PROFILE.md rounds 3-4;
    # re-measured on-chip each round by bench.py's bert_long config.
    del T
    return False


def _multichip_splash_route(q, k, mask, causal):
    """Pick the multi-chip splash composition (VERDICT r5 item 4): under
    a >1-device mesh OUTSIDE a manual region, a bare pallas_call cannot
    be GSPMD-partitioned — but attention itself shards cleanly, so mha
    builds the manual region around the kernel:

    - seq axis unsharded  -> "shardmap": manualize (batch, heads); the
      tuned kernel runs on per-device local blocks, zero collectives.
    - seq axis sharded    -> "ring": full-mask ring attention over sp
      with splash(lse) blocks (ring_attention.ring_splash); causal ring
      keeps the exact XLA blocks ("ring_xla") because a splash mask is
      static per trace and cannot track the rotating KV block's
      diagonal.

    Returns None (no reroute), "shardmap", "ring", or "ring_xla".
    """
    from paddle_tpu.parallel.mesh import current_mesh
    from paddle_tpu.parallel.sharding import current_rules

    m = current_mesh()
    if m is None or m.devices.size == 1 or q.ndim != 4 or mask is not None:
        return None
    if _get_axis_env is not None and bool(_get_axis_env().axis_sizes):
        return None  # already inside a manual region: _use_splash applies
    from ...core.flags import get_flag

    mode = str(get_flag("FLAGS_flash_attention")).lower()
    platform = m.devices.flat[0].platform
    force = mode == "splash"
    if platform != "tpu" and not force:
        return None  # interpret-mode execution is explicit opt-in
    if not (force or (mode == "auto" and q.shape[1] >= _SPLASH_MIN_T)):
        return None
    rules = current_rules()

    def _size(ax):
        return m.shape.get(ax, 1) if ax else 1

    b_ax, s_ax, h_ax = (rules.mesh_axis("batch"), rules.mesh_axis("seq"),
                        rules.mesh_axis("heads"))
    B, T, N, H = q.shape
    Tk = k.shape[1]
    sp = _size(s_ax)
    if sp > 1:
        if T % sp or Tk != T:
            return None
        if causal or (T // sp) % 128 or H % 64 or B % _size(b_ax) \
                or N % _size(h_ax):
            return "ring_xla"
        return "ring"
    if _size(b_ax) * _size(h_ax) == 1:
        return None  # replicated: the plain paths handle it
    if B % _size(b_ax) or N % _size(h_ax) or T % 128 or Tk % 128 or H % 64:
        return None
    return "shardmap"


def _shardmap_splash_mha(q, k, v, scale, causal):
    """Splash composed with dp/tp: attention is independent across batch
    and heads, so manualizing those axes feeds the tuned kernel
    per-device local blocks with NO collectives."""
    from paddle_tpu.parallel.mesh import current_mesh
    from paddle_tpu.parallel.sharding import current_rules

    m = current_mesh()
    rules = current_rules()
    b_ax, h_ax = rules.mesh_axis("batch"), rules.mesh_axis("heads")
    axes = {a for a in (b_ax, h_ax) if a and m.shape.get(a, 1) > 1}
    spec = jax.sharding.PartitionSpec(
        b_ax if b_ax in axes else None, None,
        h_ax if h_ax in axes else None, None)
    interpret = m.devices.flat[0].platform != "tpu"
    from .ring_attention import _shard_map_mesh

    sm_mesh = _shard_map_mesh(m)

    @functools.partial(jax.shard_map, mesh=sm_mesh, in_specs=(spec,) * 3,
                       out_specs=spec, axis_names=axes, check_vma=False)
    def run(ql, kl, vl):
        return _splash_mha(ql, kl, vl, scale, causal, interpret=interpret)

    return run(q, k, v)


def mha(q: jax.Array, k: jax.Array, v: jax.Array,
        mask: Optional[jax.Array] = None, scale: Optional[float] = None,
        causal: bool = False) -> jax.Array:
    """Multi-head attention over [B, T, N, H] tensors.

    mask: additive [B, 1, 1, T] or [B, N, T, T] (float, -inf style), or None.
    """
    import warnings

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if _use_splash(q, k, mask, causal):
        try:
            out = _splash_mha(q, k, v, scale, causal,
                              interpret=_platform(q) != "tpu")
            GATE_COUNTS["splash"] += 1
            return out
        except Exception as e:  # unsupported shape: fall back, but say so
            warnings.warn(f"splash_attention failed at trace time "
                          f"({type(e).__name__}: {str(e)[:200]}); falling "
                          f"back to the XLA path — which may not fit at "
                          f"this shape")
    route = _multichip_splash_route(q, k, mask, causal)
    if route is not None:
        try:
            if route == "shardmap":
                out = _shardmap_splash_mha(q, k, v, scale, causal)
            else:
                from paddle_tpu.parallel.mesh import current_mesh
                from paddle_tpu.parallel.sharding import current_rules
                from . import ring_attention as ra

                m = current_mesh()
                rules = current_rules()
                if route == "ring":
                    out = ra.ring_splash(
                        q, k, v, m, s_axis=rules.mesh_axis("seq"),
                        b_axis=rules.mesh_axis("batch"),
                        h_axis=rules.mesh_axis("heads"), scale=scale)
                else:  # "ring_xla": exact ring, XLA blocks
                    out = ra.ring_attention(
                        q, k, v, m, axis=rules.mesh_axis("seq"),
                        causal=causal, scale=scale)
            GATE_COUNTS[{"shardmap": "splash_shardmap",
                         "ring": "ring_splash",
                         "ring_xla": "ring_xla"}[route]] += 1
            return out
        except Exception as e:
            warnings.warn(f"multi-chip splash route '{route}' failed at "
                          f"trace time ({type(e).__name__}: "
                          f"{str(e)[:200]}); falling back to GSPMD XLA")
    if _use_pallas(q):
        try:
            out = _pallas_mha(q, k, v, mask, scale, causal)
            GATE_COUNTS["pallas_flash"] += 1
            return out
        except Exception:  # fall back if kernel unsupported on this shape  # lint-exempt:swallow: gated fallback: unsupported shape routes to XLA
            pass
    out = _xla_mha(q, k, v, mask if not causal else _merge_causal(mask, q.shape[1]), scale)
    GATE_COUNTS["xla"] += 1
    return out.astype(q.dtype)


def _merge_causal(mask, T):
    cm = jnp.where(jnp.tril(jnp.ones((T, T), jnp.bool_)), 0.0, -1e9)[None, None]
    return cm if mask is None else mask + cm


# ---------------------------------------------------------------------------
# SplashAttention (the production TPU attention kernel shipped with jax)
# ---------------------------------------------------------------------------

# Measured on v5e (tools/attn_ab.py, fwd+bwd, bf16, 12 heads, head_dim 64):
# splash with the block sizes below beats the XLA bf16-scores path for
# T >= _SPLASH_MIN_T on full (bidirectional) masks and at every causal
# shape — unlike the legacy flash_attention kernel, which never won.
_SPLASH_MIN_T = 1024


def _use_splash(q, k, mask, causal) -> bool:
    """Splash handles the padding-free (mask=None) and causal cases; an
    arbitrary additive mask falls back to the XLA/legacy paths."""
    if q.ndim != 4 or mask is not None:
        return False  # additive masks (padding) take the XLA path
    T, Tk, hd = q.shape[1], k.shape[1], q.shape[-1]
    if T % 128 or Tk % 128 or hd % 64:
        return False
    if not _mesh_partitionable(q):
        return False
    from ...core.flags import get_flag

    mode = str(get_flag("FLAGS_flash_attention")).lower()
    if mode == "splash":
        # explicit opt-in ALSO runs off-TPU, via the pallas interpreter —
        # this is how CPU-mesh tests execute the real kernel
        return True
    if _platform(q) != "tpu":
        return False
    if mode not in ("auto",):
        return False  # explicit on(legacy flash)/off respected
    return T >= _SPLASH_MIN_T


def _splash_kernel(Tq: int, Tk: int, n_heads: int, causal: bool,
                   interpret: bool = False, save_residuals: bool = False):
    # NOT cached: the kernel pytree holds mask-info arrays; under a vjp
    # trace those are tracers of that trace, and caching them across
    # traces raises UnexpectedTracerError in the backward pass. Creation
    # is cheap (lazy Full/Causal masks process block-wise in numpy).
    from jax.experimental.pallas.ops.tpu import splash_attention as sa

    # Block sizes tuned on v5e (tools/attn_ab.py, fwd+bwd, bf16, bs=8):
    # at T=4096 full-mask this config runs 17.0 ms vs 37.4 ms XLA
    # bf16-scores and 114 ms with the jax default all-128 blocks; at
    # T=8192 it is 56 ms where the XLA path cannot even compile (13 GB
    # of score buffers). Big fwd KV blocks amortize the online-softmax
    # rescale; bwd q-blocks stay at 512 to fit dq/dkv accumulators in
    # VMEM.
    bq = min(1024, Tq)
    bkv = min(2048, Tk)
    bqb = min(512, Tq)
    # bwd dkv/dq kv-block: 2048 wins at T>=4096 (17.0 vs 19.0 ms), 1024
    # wins at T<=2048 (6.8 vs 9.2 ms at T=2048)
    bkvb = min(2048 if Tk >= 4096 else 1024, Tk)
    sizes = sa.BlockSizes(
        block_q=bq, block_kv=bkv, block_kv_compute=bkv,
        block_q_dkv=bqb, block_kv_dkv=bkvb, block_kv_dkv_compute=bkvb,
        block_q_dq=bqb, block_kv_dq=bkvb)
    one = (sa.CausalMask((Tq, Tk)) if causal else sa.FullMask((Tq, Tk)))
    mask = sa.MultiHeadMask([one] * n_heads)
    # interpret=True runs the very same kernel via the pallas CPU
    # interpreter — how the multi-chip compositions are executed (not
    # just compile-checked) on the virtual CPU mesh; save_residuals
    # returns the per-row logsumexp the ring merge needs.
    return sa.make_splash_mha(mask, head_shards=1, q_seq_shards=1,
                              block_sizes=sizes, interpret=interpret,
                              save_residuals=save_residuals)


def _splash_mha(q, k, v, scale, causal, interpret=False):
    B, T, N, H = q.shape
    kernel = _splash_kernel(T, k.shape[1], N, bool(causal),
                            interpret=interpret)
    # kernel wants [N, T, H] per example; scale is folded into q (splash
    # applies no sm_scale itself)
    qt = (q * jnp.asarray(scale, q.dtype)).transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = jax.vmap(kernel)(qt, kt, vt)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _splash_block_with_lse(q, k, v, interpret=False):
    """One full-mask splash block returning (out, logsumexp) — the ring
    merge's building block. q,k,v: [B,T,N,H] (q pre-scaled); out
    [B,T,N,H], lse [B,N,T] (f32)."""
    B, T, N, H = q.shape
    kernel = _splash_kernel(T, k.shape[1], N, causal=False,
                            interpret=interpret, save_residuals=True)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out, (lse,) = jax.vmap(kernel)(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse


# ---------------------------------------------------------------------------
# Pallas flash-attention kernel (TPU)
# ---------------------------------------------------------------------------


def _pallas_mha(q, k, v, mask, scale, causal):
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention)

    # pallas kernel wants [B, N, T, H]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    ab = None
    if mask is not None:
        ab = jnp.broadcast_to(
            mask.astype(jnp.float32),
            (q.shape[0], q.shape[2], q.shape[1], k.shape[1]))
    out = flash_attention(qt, kt, vt, ab=ab, causal=causal,
                          sm_scale=float(scale))
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
