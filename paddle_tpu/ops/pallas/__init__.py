"""Pallas TPU kernels (fused attention, top-k compression, ...).

The reference's hand-written CUDA kernel zoo (operators/math/*.cu,
operators/fused/) maps here: most fusion is XLA's job, Pallas covers the
few patterns XLA can't fuse optimally (flash attention online-softmax,
DGC top-k). Every kernel has an XLA fallback so CPU tests and non-TPU
backends run the same code path semantically.
"""

from . import attention  # noqa: F401
