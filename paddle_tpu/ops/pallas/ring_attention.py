"""Ring attention: sequence-parallel exact attention over the 'sp' mesh axis.

No reference counterpart (SURVEY §2.5: the reference has no sequence/context
parallelism — its long-sequence story is LoD). TPU-native: each device holds
a sequence chunk of Q/K/V; K/V blocks rotate around the ring via
lax.ppermute while a flash-style online softmax accumulates partial results,
overlapping compute with ICI transfers. Memory per device is O(T/sp), so
context length scales linearly with the ring size.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _shard_map_mesh(mesh):
    """Mesh to hand jax.shard_map: under an active context mesh (set by
    jit/mesh_guard) the ABSTRACT mesh must be passed — a concrete mesh no
    longer matches (jax 0.9 behavior). Shared by every shard_map site in
    the attention stack so an API shift is a one-line fix."""
    abstract = jax.sharding.get_abstract_mesh()
    return abstract if (abstract is not None and not abstract.empty) \
        else mesh



def _block_attn(q, k, v, scale, q_off, k_off, causal, Tq, Tk):
    """Partial (unnormalized) attention of local q against one k/v block.
    q: [B,Tq,N,H]; k,v: [B,Tk,N,H]. Returns (acc, m, l) contributions."""
    logits = jnp.einsum("btnh,bsnh->bnts", q, k).astype(jnp.float32) * scale
    if causal:
        qpos = q_off + jnp.arange(Tq)
        kpos = k_off + jnp.arange(Tk)
        mask = qpos[:, None] >= kpos[None, :]
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1)                          # [B,N,Tq]
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)                               # [B,N,Tq]
    acc = jnp.einsum("bnts,bsnh->btnh", p.astype(v.dtype), v)
    return acc, m, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mesh: Mesh, axis: str = "sp",
                   causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Exact attention with q/k/v sharded [B, T, N, H] on T over `axis`.

    Must run inside jit under `mesh`. Equivalent to full attention; the
    sequence never materializes on one device.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    S = mesh.shape[axis]
    if S == 1:
        from .attention import mha

        return mha(q, k, v, scale=scale, causal=causal)

    perm = [(i, (i + 1) % S) for i in range(S)]

    sm_mesh = _shard_map_mesh(mesh)

    @functools.partial(
        jax.shard_map, mesh=sm_mesh,
        in_specs=(P(None, axis, None, None),) * 3,
        out_specs=P(None, axis, None, None),
        axis_names={axis},
        check_vma=False)
    def run(q, k, v):
        s = jax.lax.axis_index(axis)
        B, Tl, N, H = q.shape
        q_off = s * Tl

        def step(carry, i):
            kv, acc, m, l = carry
            kb, vb = kv
            # block index currently held: it started at (s - i) ... ring hops
            src = (s - i) % S
            k_off = src * Tl
            a, bm, bl = _block_attn(q, kb, vb, scale, q_off, k_off,
                                    causal, Tl, Tl)
            m_new = jnp.maximum(m, bm)
            c_old = jnp.exp(m - m_new)
            c_blk = jnp.exp(bm - m_new)
            acc = (acc * c_old.transpose(0, 2, 1)[..., None]
                   + a.astype(jnp.float32) * c_blk.transpose(0, 2, 1)[..., None])
            l = l * c_old + bl * c_blk
            kv = jax.tree.map(lambda x: jax.lax.ppermute(x, axis, perm),
                              (kb, vb))
            return (kv, acc, m_new, l), None

        acc0 = jnp.zeros((B, Tl, N, H), jnp.float32)
        m0 = jnp.full((B, N, Tl), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, N, Tl), jnp.float32)
        (kv, acc, m, l), _ = jax.lax.scan(
            step, ((k, v), acc0, m0, l0), jnp.arange(S))
        out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
        return out.astype(q.dtype)

    return run(q, k, v)


def ring_splash(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                s_axis: str = "sp", b_axis: Optional[str] = "dp",
                h_axis: Optional[str] = "tp",
                scale: Optional[float] = None) -> jax.Array:
    """Full-mask ring attention whose per-block attention is the tuned
    splash kernel (VERDICT r5 item 4: T>=1024 splash speedups must
    compose with dp/sp/tp).

    The manual region covers (batch, seq, heads) so the pallas kernel
    sees fully local blocks; the ring rotates K/V over `s_axis` via
    ppermute while normalized block outputs are merged through their
    logsumexp residuals (save_residuals=True), which is numerically the
    same online-softmax combine as ring_attention's unnormalized form:
    out = sum_b out_b * exp(lse_b - m) / sum_b exp(lse_b - m).

    Full (bidirectional) masks only — a splash mask is static per trace
    and cannot track the rotating block's causal diagonal; causal ring
    stays on ring_attention's exact XLA blocks. Off-TPU the kernel runs
    under the pallas interpreter, so CPU-mesh tests execute (not just
    compile) this path.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    S = mesh.shape[s_axis]
    if S == 1:
        from .attention import mha

        return mha(q, k, v, scale=scale, causal=False)
    perm = [(i, (i + 1) % S) for i in range(S)]
    interpret = mesh.devices.flat[0].platform != "tpu"
    axes = {s_axis} | {a for a in (b_axis, h_axis)
                       if a and mesh.shape.get(a, 1) > 1}
    spec = P(b_axis if b_axis in axes else None, s_axis,
             h_axis if h_axis in axes else None, None)
    sm_mesh = _shard_map_mesh(mesh)

    @functools.partial(
        jax.shard_map, mesh=sm_mesh, in_specs=(spec,) * 3, out_specs=spec,
        axis_names=axes, check_vma=False)
    def run(q, k, v):
        return _ring_splash_local(float(scale), s_axis, S, tuple(perm),
                                  interpret, q, k, v)

    return run(q, k, v)


# --- per-shard ring-splash with a custom VJP -------------------------------
# splash's save_residuals variant has no AD rule ("Higher-order AD not
# supported"), so the ring takes the standard memory-efficient route:
# FORWARD runs the tuned splash kernel per block and merges by logsumexp;
# BACKWARD is the flash-attention backward done blockwise in XLA einsums
# against the saved GLOBAL logsumexp — p_b = exp(q k_b^T * scale - lse)
# is exactly the global softmax restricted to block b, so each block's
# dq/dk/dv contribution is independent; dk/dv accumulators ride around
# the ring WITH their block and are home after S hops. O(Tl^2) score
# blocks, never the full T^2.


def _t(x):  # [B,N,Tl] -> [B,Tl,N,1] broadcast helper
    return x.transpose(0, 2, 1)[..., None]


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _ring_splash_local(scale, s_axis, S, perm, interpret, q, k, v):
    out, _ = _ring_splash_fwd_impl(scale, s_axis, S, perm, interpret,
                                   q, k, v)
    return out


def _ring_splash_fwd_impl(scale, s_axis, S, perm, interpret, q, k, v):
    from .attention import _splash_block_with_lse

    B, Tl, N, H = q.shape
    qs = q * jnp.asarray(scale, q.dtype)  # splash applies no sm_scale

    def step(carry, _):
        kv, acc, m, w = carry
        kb, vb = kv
        out_b, lse_b = _splash_block_with_lse(qs, kb, vb,
                                              interpret=interpret)
        # merge normalized block outputs by logsumexp weight
        m_new = jnp.maximum(m, lse_b)                 # [B,N,Tl]
        c_old = jnp.exp(m - m_new)
        c_blk = jnp.exp(lse_b - m_new)
        acc = acc * _t(c_old) + out_b.astype(jnp.float32) * _t(c_blk)
        w = w * c_old + c_blk
        kv = jax.tree.map(lambda x: jax.lax.ppermute(x, s_axis, perm),
                          (kb, vb))
        return (kv, acc, m_new, w), None

    acc0 = jnp.zeros((B, Tl, N, H), jnp.float32)
    m0 = jnp.full((B, N, Tl), NEG_INF, jnp.float32)
    w0 = jnp.zeros((B, N, Tl), jnp.float32)
    (kv, acc, m, w), _ = jax.lax.scan(
        step, ((k, v), acc0, m0, w0), None, length=S)
    out = (acc / jnp.maximum(w, 1e-30).transpose(0, 2, 1)[..., None]
           ).astype(q.dtype)
    lse = m + jnp.log(jnp.maximum(w, 1e-30))          # global logsumexp
    return out, (q, k, v, out, lse)


def _ring_splash_fwd(scale, s_axis, S, perm, interpret, q, k, v):
    out, res = _ring_splash_fwd_impl(scale, s_axis, S, perm, interpret,
                                     q, k, v)
    return out, res


def _ring_splash_bwd(scale, s_axis, S, perm, interpret, res, dout):
    q, k, v, out, lse = res
    qf = q.astype(jnp.float32)
    doutf = dout.astype(jnp.float32)
    # delta_i = sum_h dout_ih * out_ih  (rowwise correction term)
    delta = jnp.einsum("btnh,btnh->bnt", doutf, out.astype(jnp.float32))

    def step(carry, _):
        (kb, vb, dkb, dvb), dq = carry
        kbf, vbf = kb.astype(jnp.float32), vb.astype(jnp.float32)
        logits = jnp.einsum("btnh,bsnh->bnts", qf, kbf,
                            preferred_element_type=jnp.float32) * scale
        p = jnp.exp(logits - lse[..., None])          # global softmax slice
        dvb = dvb + jnp.einsum("bnts,btnh->bsnh", p, doutf)
        dp = jnp.einsum("btnh,bsnh->bnts", doutf, vbf)
        ds = p * (dp - delta[..., None])
        dq = dq + jnp.einsum("bnts,bsnh->btnh", ds, kbf) * scale
        dkb = dkb + jnp.einsum("bnts,btnh->bsnh", ds, qf) * scale
        rotated = jax.tree.map(
            lambda x: jax.lax.ppermute(x, s_axis, perm),
            (kb, vb, dkb, dvb))
        return (rotated, dq), None

    B, Tl, N, H = q.shape
    zeros = jnp.zeros((B, Tl, N, H), jnp.float32)
    ((kb, vb, dk, dv), dq), _ = jax.lax.scan(
        step, ((k, v, zeros, zeros), zeros), None, length=S)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_splash_local.defvjp(_ring_splash_fwd, _ring_splash_bwd)
