"""Ring attention: sequence-parallel exact attention over the 'sp' mesh axis.

No reference counterpart (SURVEY §2.5: the reference has no sequence/context
parallelism — its long-sequence story is LoD). TPU-native: each device holds
a sequence chunk of Q/K/V; K/V blocks rotate around the ring via
lax.ppermute while a flash-style online softmax accumulates partial results,
overlapping compute with ICI transfers. Memory per device is O(T/sp), so
context length scales linearly with the ring size.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, scale, q_off, k_off, causal, Tq, Tk):
    """Partial (unnormalized) attention of local q against one k/v block.
    q: [B,Tq,N,H]; k,v: [B,Tk,N,H]. Returns (acc, m, l) contributions."""
    logits = jnp.einsum("btnh,bsnh->bnts", q, k).astype(jnp.float32) * scale
    if causal:
        qpos = q_off + jnp.arange(Tq)
        kpos = k_off + jnp.arange(Tk)
        mask = qpos[:, None] >= kpos[None, :]
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1)                          # [B,N,Tq]
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)                               # [B,N,Tq]
    acc = jnp.einsum("bnts,bsnh->btnh", p.astype(v.dtype), v)
    return acc, m, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mesh: Mesh, axis: str = "sp",
                   causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Exact attention with q/k/v sharded [B, T, N, H] on T over `axis`.

    Must run inside jit under `mesh`. Equivalent to full attention; the
    sequence never materializes on one device.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    S = mesh.shape[axis]
    if S == 1:
        from .attention import mha

        return mha(q, k, v, scale=scale, causal=causal)

    perm = [(i, (i + 1) % S) for i in range(S)]

    # under an active context mesh (set by jit/mesh_guard) the abstract mesh
    # must be passed to shard_map — a concrete mesh no longer matches
    abstract = jax.sharding.get_abstract_mesh()
    sm_mesh = abstract if (abstract is not None and not abstract.empty) else mesh

    @functools.partial(
        jax.shard_map, mesh=sm_mesh,
        in_specs=(P(None, axis, None, None),) * 3,
        out_specs=P(None, axis, None, None),
        axis_names={axis},
        check_vma=False)
    def run(q, k, v):
        s = jax.lax.axis_index(axis)
        B, Tl, N, H = q.shape
        q_off = s * Tl

        def step(carry, i):
            kv, acc, m, l = carry
            kb, vb = kv
            # block index currently held: it started at (s - i) ... ring hops
            src = (s - i) % S
            k_off = src * Tl
            a, bm, bl = _block_attn(q, kb, vb, scale, q_off, k_off,
                                    causal, Tl, Tl)
            m_new = jnp.maximum(m, bm)
            c_old = jnp.exp(m - m_new)
            c_blk = jnp.exp(bm - m_new)
            acc = (acc * c_old.transpose(0, 2, 1)[..., None]
                   + a.astype(jnp.float32) * c_blk.transpose(0, 2, 1)[..., None])
            l = l * c_old + bl * c_blk
            kv = jax.tree.map(lambda x: jax.lax.ppermute(x, axis, perm),
                              (kb, vb))
            return (kv, acc, m_new, l), None

        acc0 = jnp.zeros((B, Tl, N, H), jnp.float32)
        m0 = jnp.full((B, N, Tl), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, N, Tl), jnp.float32)
        (kv, acc, m, l), _ = jax.lax.scan(
            step, ((k, v), acc0, m0, l0), jnp.arange(S))
        out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
        return out.astype(q.dtype)

    return run(q, k, v)
