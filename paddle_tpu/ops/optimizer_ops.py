"""Optimizer ops — run inside the compiled step like the reference's
graph-embedded optimizer ops (reference: paddle/fluid/operators/optimizers/:
sgd_op.cc, momentum_op.cc, adam_op.cc, lamb_op.cc, lars_momentum_op.cc, ...).

All are grad=None (no second-order through optimizer updates) and write
Param/moments in place via the functional name-rebinding in lowering.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _lr(ins):
    lr = ins["LearningRate"][0]
    return lr.reshape(()) if getattr(lr, "ndim", 0) else lr


def _dense_grad(ins):
    """Densify a SelectedRows grad for kernels without a sparse branch
    (the reference falls back the same way where no SelectedRows kernel
    is registered)."""
    from ..core.selected_rows import to_dense

    return to_dense(ins["Grad"][0])


@register_op("sgd", grad=None)
def sgd(ins, attrs, ctx):
    """reference: optimizers/sgd_op.cc — dense branch plus the
    SelectedRows branch (sgd_op.h sparse path): only the touched rows
    are updated; duplicate ids accumulate, matching the reference's
    row-wise apply."""
    from ..core.selected_rows import is_selected_rows

    p, g = ins["Param"][0], ins["Grad"][0]
    lr = _lr(ins).astype(p.dtype)
    if is_selected_rows(g):
        return {"ParamOut": p.at[g.ids].add(-lr * g.rows.astype(p.dtype))}
    return {"ParamOut": p - lr * g.astype(p.dtype)}


@register_op("momentum", grad=None)
def momentum(ins, attrs, ctx):
    """reference: optimizers/momentum_op.cc. Its SparseMomentumFunctor
    (momentum_op.h) iterates the WHOLE parameter with g=0 for rows absent
    from the SelectedRows grad — velocity decays and params move
    everywhere, numerically identical to the dense path — so sparse
    grads densify here (scatter-add) and take exactly that path."""
    p, g, v = ins["Param"][0], _dense_grad(ins), ins["Velocity"][0]
    mu = attrs.get("mu", 0.9)
    lr = _lr(ins).astype(p.dtype)
    v_new = mu * v + g
    if attrs.get("use_nesterov", False):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    return {"ParamOut": p_new, "VelocityOut": v_new}


@register_op("lars_momentum", grad=None)
def lars_momentum(ins, attrs, ctx):
    """reference: optimizers/lars_momentum_op.cc — layer-wise adaptive rate
    scaling for large-batch training."""
    p, g, v = ins["Param"][0], _dense_grad(ins), ins["Velocity"][0]
    mu = attrs.get("mu", 0.9)
    coeff = attrs.get("lars_coeff", 0.001)
    wd = attrs.get("lars_weight_decay", 0.0005)
    eps = attrs.get("epsilon", 0.0)
    lr = _lr(ins).astype(p.dtype)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * coeff * p_norm / (g_norm + wd * p_norm + eps),
        lr)
    v_new = mu * v + local_lr * (g + wd * p)
    return {"ParamOut": p - v_new, "VelocityOut": v_new}


@register_op("adam", grad=None)
def adam(ins, attrs, ctx):
    """reference: optimizers/adam_op.cc (Beta1Pow/Beta2Pow threaded as 1-elem
    tensors exactly like the reference). SelectedRows grads follow the
    reference's lazy_mode attr (adam_op.h SparseAdamFunctor): the
    DEFAULT lazy_mode=False is numerically dense-equivalent (every row's
    moments decay, g=0 where untouched), so it densifies; lazy_mode=True
    merges duplicates and updates ONLY the touched rows — untouched
    rows' moments do not decay."""
    from ..core.selected_rows import is_selected_rows

    p, g = ins["Param"][0], ins["Grad"][0]
    if is_selected_rows(g) and not bool(attrs.get("lazy_mode", False)):
        g = g.to_dense()
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins).astype(jnp.float32)
    lr_t = lr * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    if is_selected_rows(g):
        ids, rows, keep = g.merged()
        sids = g.masked_ids(ids, keep)
        rows = rows.astype(jnp.float32)
        m1i = b1 * m1[ids] + (1 - b1) * rows
        m2i = b2 * m2[ids] + (1 - b2) * jnp.square(rows)
        step = lr_t * m1i / (jnp.sqrt(m2i) + eps)
        return {"ParamOut": p.at[sids].add(-step.astype(p.dtype),
                                           mode="drop"),
                "Moment1Out": m1.at[sids].set(m1i, mode="drop"),
                "Moment2Out": m2.at[sids].set(m2i, mode="drop"),
                "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    p_new = p - lr_t * m1n / (jnp.sqrt(m2n) + eps)
    return {"ParamOut": p_new.astype(p.dtype), "Moment1Out": m1n, "Moment2Out": m2n,
            "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}


@register_op("adamw", grad=None)
def adamw(ins, attrs, ctx):
    outs = adam(ins, attrs, ctx)
    wd = attrs.get("coeff", attrs.get("weight_decay", 0.01))
    p = ins["Param"][0]
    lr = _lr(ins).astype(p.dtype)
    outs["ParamOut"] = outs["ParamOut"] - lr * wd * p
    return outs


@register_op("adamax", grad=None)
def adamax(ins, attrs, ctx):
    p, g = ins["Param"][0], _dense_grad(ins)
    m, u = ins["Moment"][0], ins["InfNorm"][0]
    b1p = ins["Beta1Pow"][0]
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins).astype(p.dtype)
    m_new = b1 * m + (1 - b1) * g
    u_new = jnp.maximum(b2 * u, jnp.abs(g))
    p_new = p - (lr / (1 - b1p.reshape(()))) * m_new / (u_new + eps)
    return {"ParamOut": p_new, "MomentOut": m_new, "InfNormOut": u_new}


@register_op("adagrad", grad=None)
def adagrad(ins, attrs, ctx):
    """reference: optimizers/adagrad_op.cc incl. its SelectedRows branch
    (duplicates merged, touched rows only)."""
    from ..core.selected_rows import is_selected_rows

    p, g, mom = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    eps = attrs.get("epsilon", 1e-6)
    lr = _lr(ins).astype(p.dtype)
    if is_selected_rows(g):
        ids, rows, keep = g.merged()
        sids = g.masked_ids(ids, keep)
        rows = rows.astype(p.dtype)
        mom_i = mom[ids] + jnp.square(rows)
        step = lr * rows / (jnp.sqrt(mom_i) + eps)
        return {"ParamOut": p.at[sids].add(-step, mode="drop"),
                "MomentOut": mom.at[sids].set(mom_i, mode="drop")}
    mom_new = mom + jnp.square(g)
    return {"ParamOut": p - lr * g / (jnp.sqrt(mom_new) + eps), "MomentOut": mom_new}


@register_op("decayed_adagrad", grad=None)
def decayed_adagrad(ins, attrs, ctx):
    p, g, mom = ins["Param"][0], _dense_grad(ins), ins["Moment"][0]
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    lr = _lr(ins).astype(p.dtype)
    mom_new = decay * mom + (1 - decay) * jnp.square(g)
    return {"ParamOut": p - lr * g / (jnp.sqrt(mom_new) + eps), "MomentOut": mom_new}


@register_op("adadelta", grad=None)
def adadelta(ins, attrs, ctx):
    p, g = ins["Param"][0], _dense_grad(ins)
    avg_sq, avg_upd = ins["AvgSquaredGrad"][0], ins["AvgSquaredUpdate"][0]
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    avg_sq_new = rho * avg_sq + (1 - rho) * jnp.square(g)
    upd = jnp.sqrt(avg_upd + eps) / jnp.sqrt(avg_sq_new + eps) * g
    avg_upd_new = rho * avg_upd + (1 - rho) * jnp.square(upd)
    return {"ParamOut": p - upd, "AvgSquaredGradOut": avg_sq_new,
            "AvgSquaredUpdateOut": avg_upd_new}


@register_op("rmsprop", grad=None)
def rmsprop(ins, attrs, ctx):
    p, g = ins["Param"][0], _dense_grad(ins)
    ms, mom = ins["MeanSquare"][0], ins["Moment"][0]
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mu = attrs.get("momentum", 0.0)
    lr = _lr(ins).astype(p.dtype)
    ms_new = rho * ms + (1 - rho) * jnp.square(g)
    if attrs.get("centered", False):
        mg = ins["MeanGrad"][0]
        mg_new = rho * mg + (1 - rho) * g
        mom_new = mu * mom + lr * g / jnp.sqrt(ms_new - jnp.square(mg_new) + eps)
        return {"ParamOut": p - mom_new, "MeanSquareOut": ms_new,
                "MomentOut": mom_new, "MeanGradOut": mg_new}
    mom_new = mu * mom + lr * g / jnp.sqrt(ms_new + eps)
    return {"ParamOut": p - mom_new, "MeanSquareOut": ms_new, "MomentOut": mom_new}


@register_op("ftrl", grad=None)
def ftrl(ins, attrs, ctx):
    p, g = ins["Param"][0], _dense_grad(ins)
    sq, lin = ins["SquaredAccumulator"][0], ins["LinearAccumulator"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr_power = attrs.get("lr_power", -0.5)
    lr = _lr(ins).astype(p.dtype)
    new_sq = sq + jnp.square(g)
    sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    quad = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    p_new = pre / quad
    return {"ParamOut": p_new, "SquaredAccumOut": new_sq, "LinearAccumOut": new_lin}


@register_op("lamb", grad=None)
def lamb(ins, attrs, ctx):
    """reference: optimizers/lamb_op.cc — layer-adaptive large-batch Adam."""
    p, g = ins["Param"][0], _dense_grad(ins)
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    lr = _lr(ins).astype(jnp.float32)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    m1h = m1n / (1 - b1p.reshape(()))
    m2h = m2n / (1 - b2p.reshape(()))
    r = m1h / (jnp.sqrt(m2h) + eps) + wd * p
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    p_new = p - lr * trust * r
    return {"ParamOut": p_new.astype(p.dtype), "Moment1Out": m1n, "Moment2Out": m2n,
            "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}


@register_op("dpsgd", grad=None, is_random=True)
def dpsgd(ins, attrs, ctx):
    """reference: optimizers/dpsgd_op.cc — differentially-private SGD
    (clip + gaussian noise)."""
    p, g = ins["Param"][0], _dense_grad(ins)
    clip = attrs.get("clip", 10.0)
    batch_size = attrs.get("batch_size", 16.0)
    sigma = attrs.get("sigma", 1.0)
    lr = _lr(ins).astype(p.dtype)
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    g = g / jnp.maximum(1.0, g_norm / clip)
    noise = sigma * clip * jax.random.normal(ctx.rng(), g.shape, dtype=jnp.float32)
    return {"ParamOut": p - lr * (g + noise.astype(p.dtype)) / batch_size}


# -- DGC: deep gradient compression (reference: optimizers/dgc_momentum_op +
# details/sparse_all_reduce_op_handle.cc:44; paper arxiv 1712.01887) --------


def _dgc_infer(op, input_descs):
    """Static: every output mirrors Param's shape/dtype (eval_shape would
    trace the sparse allreduce outside shard_map and hit the unbound axis)."""
    import jax
    import numpy as np

    from ..core.ir import normalize_dtype

    p = input_descs[op.inputs["Param"][0]]
    sds = jax.ShapeDtypeStruct(tuple(p.shape or ()),
                               np.dtype(normalize_dtype(p.dtype)))
    out = {}
    for slot in ("ParamOut", "UOut", "VOut", "GradOut"):
        for n in op.outputs.get(slot, []):
            if n:
                out[n] = sds
    return out


@register_op("dgc_momentum", grad=None, infer_shape=_dgc_infer)
def dgc_momentum(ins, attrs, ctx):
    """Top-k sparsified momentum step. On TPU the sparse allgather of the
    reference (sparseAllGReduce) is replaced by dense psum of the sparsified
    (mostly-zero) gradient — GSPMD handles the collective; the compression
    semantic (only top-k% of grads applied, rest accumulated locally) is
    preserved via the U/V accumulators."""
    p, g = ins["Param"][0], _dense_grad(ins)
    u, v = ins["U"][0], ins["V"][0]
    mu = attrs.get("mu", 0.9)
    ratio = attrs.get("sparsity_ratio", 0.001)
    lr = _lr(ins).astype(p.dtype)
    u_new = mu * u + g
    v_new = v + u_new
    flat = jnp.abs(v_new).reshape(-1)
    k = max(1, int(flat.size * ratio))
    thresh = jax.lax.top_k(flat, k)[0][-1]
    mask = jnp.abs(v_new) >= thresh
    sparse_grad = jnp.where(mask, v_new, 0.0)
    u_out = jnp.where(mask, 0.0, u_new)
    v_out = jnp.where(mask, 0.0, v_new)
    axis = attrs.get("axis_name")
    if axis:
        # under shard_map (SPMDRunner): sparse-allgather the compressed
        # grads BEFORE the update so all ranks apply the reduced gradient
        from .collective import sparse_allreduce

        sparse_grad = sparse_allreduce(
            sparse_grad.reshape(-1), k, axis).reshape(sparse_grad.shape)
    return {"ParamOut": p - lr * sparse_grad, "UOut": u_out, "VOut": v_out,
            "GradOut": sparse_grad}


@register_op("proximal_gd", grad=None)
def proximal_gd(ins, attrs, ctx):
    """reference: optimizers/proximal_gd_op.cc — prox_param = p - lr*g,
    then soft-threshold by l1 and shrink by l2."""
    p, g = ins["Param"][0], _dense_grad(ins)
    lr = _lr(ins).astype(p.dtype)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    prox = p - lr * g.astype(p.dtype)
    if l1 > 0:
        new_p = (jnp.sign(prox) *
                 jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) /
                 (1.0 + lr * l2))
    else:
        new_p = prox / (1.0 + lr * l2)
    return {"ParamOut": new_p}


@register_op("proximal_adagrad", grad=None)
def proximal_adagrad(ins, attrs, ctx):
    """reference: optimizers/proximal_adagrad_op.cc."""
    p, g, m = ins["Param"][0], _dense_grad(ins), ins["Moment"][0]
    lr = _lr(ins).astype(p.dtype)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    g = g.astype(p.dtype)
    m_new = m + g * g
    eff_lr = lr / jnp.sqrt(m_new)
    prox = p - eff_lr * g
    if l1 > 0:
        new_p = (jnp.sign(prox) *
                 jnp.maximum(jnp.abs(prox) - eff_lr * l1, 0.0) /
                 (1.0 + eff_lr * l2))
    else:
        new_p = prox / (1.0 + eff_lr * l2)
    return {"ParamOut": new_p, "MomentOut": m_new}


@register_op("average_accumulates", grad=None,
             nondiff_inputs=("param", "in_sum_1", "in_sum_2", "in_sum_3",
                             "in_num_accumulates", "in_old_num_accumulates",
                             "in_num_updates"))
def average_accumulates(ins, attrs, ctx):
    """reference: average_accumulates_op.h — ModelAverage's accumulator
    update: sum_1 += param each step; every 16384 updates sum_1 rolls
    into sum_2; when num_accumulates exceeds max(avg_window *
    num_updates, min_window) (capped by max_window), sum_2 <- sum_1 +
    sum_2 rolls into sum_3 and the window restarts."""
    k_max = 16384
    p = ins["param"][0]
    s1 = ins["in_sum_1"][0]
    s2 = ins["in_sum_2"][0]
    s3 = ins["in_sum_3"][0]
    na = ins["in_num_accumulates"][0].reshape(()).astype(jnp.int32)
    ona = ins["in_old_num_accumulates"][0].reshape(()).astype(jnp.int32)
    nu = ins["in_num_updates"][0].reshape(()).astype(jnp.int32)
    avg_win = float(attrs.get("average_window", 0.0))
    max_win = int(attrs.get("max_average_window", 2 ** 31 - 1))
    min_win = int(attrs.get("min_average_window", 10000))

    nu = nu + 1
    na = na + 1
    s1 = s1 + p
    roll16k = (nu % k_max) == 0
    s2 = jnp.where(roll16k, s2 + s1, s2)
    s1 = jnp.where(roll16k, jnp.zeros_like(s1), s1)
    window = jnp.minimum(
        jnp.maximum((avg_win * nu.astype(jnp.float32)).astype(jnp.int32),
                    min_win), max_win)
    restart = na >= window
    s3 = jnp.where(restart, s1 + s2, s3)
    s1 = jnp.where(restart, jnp.zeros_like(s1), s1)
    s2 = jnp.where(restart, jnp.zeros_like(s2), s2)
    ona = jnp.where(restart, na, ona)
    na = jnp.where(restart, jnp.zeros_like(na), na)
    return {"out_sum_1": s1, "out_sum_2": s2, "out_sum_3": s3,
            "out_num_accumulates": na.reshape(1),
            "out_old_num_accumulates": ona.reshape(1),
            "out_num_updates": nu.reshape(1)}
