"""Linear-chain CRF ops — sequence labeling (SRL, NER).

Reference behavior: operators/linear_chain_crf_op.{cc,h} (forward algorithm,
Transition layout [D+2, D]: row 0 = start weights, row 1 = end weights, rows
2.. = tag->tag transitions; output LogLikelihood is the *negative*
log-likelihood per sequence), operators/crf_decoding_op.h (Viterbi; with a
Label input the output becomes a 0/1 per-position correctness mask), and
operators/chunk_eval_op.h (IOB/IOE/IOBES/plain chunk precision/recall/F1).

TPU-native design: the reference iterates per-sequence over LoD slices with
normalized probabilities; here sequences are a padded [N, T, D] batch with a
[N] Length vector, the forward/Viterbi recursions are `lax.scan` over time in
log space (no L1 renormalisation needed), and the whole batch runs as one
XLA computation. Gradients come from jax.vjp of the scan (the reference
hand-writes the backward recursion). chunk_eval vectorizes the reference's
per-position chunk state machine so the metric runs in-graph on TPU (no
host callback — the axon PJRT backend has none).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _crf_batch(emission, transition, lengths):
    """Log-partition and log-alpha for a padded batch. emission [N,T,D],
    transition [D+2,D], lengths [N] -> (logZ [N], alpha [N,T,D])."""
    n, t, d = emission.shape
    w_start, w_end, w_trans = transition[0], transition[1], transition[2:]
    lengths = lengths.astype(jnp.int32)

    alpha0 = w_start[None, :] + emission[:, 0, :]  # [N, D]

    def step(carry, xs):
        alpha_prev = carry
        x_k, k = xs
        # logsumexp_j(alpha[j] + trans[j, i]) + x[i]
        scores = alpha_prev[:, :, None] + w_trans[None, :, :]
        alpha_new = jax.nn.logsumexp(scores, axis=1) + x_k
        keep = (k < lengths)[:, None]
        alpha = jnp.where(keep, alpha_new, alpha_prev)
        return alpha, alpha

    xs = (jnp.moveaxis(emission[:, 1:, :], 1, 0), jnp.arange(1, t))
    alpha_last, alpha_rest = jax.lax.scan(step, alpha0, xs)
    alpha = jnp.concatenate([alpha0[:, None, :],
                             jnp.moveaxis(alpha_rest, 0, 1)], axis=1)
    logz = jax.nn.logsumexp(alpha_last + w_end[None, :], axis=-1)
    return logz, alpha


def _crf_score(emission, transition, label, lengths):
    """Score of the gold path, masked past each length. -> [N]."""
    n, t, d = emission.shape
    w_start, w_end, w_trans = transition[0], transition[1], transition[2:]
    lengths = lengths.astype(jnp.int32)
    lbl = label.astype(jnp.int32)
    pos = jnp.arange(t)[None, :]
    valid = pos < lengths[:, None]  # [N, T]

    emit = jnp.take_along_axis(emission, lbl[:, :, None], axis=2)[:, :, 0]
    emit_score = jnp.sum(jnp.where(valid, emit, 0.0), axis=1)

    trans = w_trans[lbl[:, :-1], lbl[:, 1:]]  # [N, T-1]
    trans_score = jnp.sum(jnp.where(valid[:, 1:], trans, 0.0), axis=1)

    last = jnp.maximum(lengths - 1, 0)
    last_lbl = jnp.take_along_axis(lbl, last[:, None], axis=1)[:, 0]
    return w_start[lbl[:, 0]] + emit_score + trans_score + w_end[last_lbl]


@register_op("linear_chain_crf", nondiff_inputs=("Label", "Length"),
             intermediate_outputs=("Alpha", "EmissionExps", "TransitionExps"))
def linear_chain_crf(ins, attrs, ctx):
    """NLL of gold tag paths under a linear-chain CRF.

    Inputs: Emission [N,T,D] (or [T,D] for one sequence), Transition [D+2,D],
    Label [N,T] int, Length [N] (optional; defaults to full T).
    Output LogLikelihood [N,1] = logZ - score (a cost, as in the reference).
    """
    emission = ins["Emission"][0]
    transition = ins["Transition"][0]
    label = ins["Label"][0]
    squeeze = emission.ndim == 2
    if squeeze:
        emission, label = emission[None], jnp.asarray(label).reshape(1, -1)
    if label.ndim == 3:  # [N,T,1] feed convention
        label = label[:, :, 0]
    n, t, d = emission.shape
    if ins.get("Length") and ins["Length"][0] is not None:
        lengths = ins["Length"][0].reshape(-1)
    else:
        lengths = jnp.full((n,), t, jnp.int32)
    logz, alpha = _crf_batch(emission, transition, lengths)
    score = _crf_score(emission, transition, label, lengths)
    nll = (logz - score)[:, None]
    return {"LogLikelihood": nll[0] if squeeze else nll,
            "Alpha": alpha,
            "EmissionExps": jnp.exp(emission),
            "TransitionExps": jnp.exp(transition)}


@register_op("crf_decoding", grad=None,
             nondiff_inputs=("Emission", "Transition", "Label", "Length"))
def crf_decoding(ins, attrs, ctx):
    """Viterbi decode. Output ViterbiPath [N,T] int64 (0 past length). When
    Label is given the output is 1 where decoded==label else 0, matching
    crf_decoding_op.h:69."""
    emission = ins["Emission"][0]
    transition = ins["Transition"][0]
    squeeze = emission.ndim == 2
    if squeeze:
        emission = emission[None]
    n, t, d = emission.shape
    if ins.get("Length") and ins["Length"][0] is not None:
        lengths = ins["Length"][0].reshape(-1).astype(jnp.int32)
    else:
        lengths = jnp.full((n,), t, jnp.int32)
    w_start, w_end, w_trans = transition[0], transition[1], transition[2:]

    alpha0 = w_start[None, :] + emission[:, 0, :]

    def fwd(carry, xs):
        alpha_prev = carry
        x_k, k = xs
        scores = alpha_prev[:, :, None] + w_trans[None, :, :]  # [N, D, D]
        best_prev = jnp.argmax(scores, axis=1)                 # [N, D]
        alpha_new = jnp.max(scores, axis=1) + x_k
        keep = (k < lengths)[:, None]
        alpha = jnp.where(keep, alpha_new, alpha_prev)
        return alpha, (best_prev, keep)

    xs = (jnp.moveaxis(emission[:, 1:, :], 1, 0), jnp.arange(1, t))
    alpha_last, (back, keeps) = jax.lax.scan(fwd, alpha0, xs)
    last_tag = jnp.argmax(alpha_last + w_end[None, :], axis=-1)  # [N]

    def bwd(carry, xs):
        tag = carry
        bp, keep = xs  # bp [N, D], keep [N, 1]
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        new_tag = jnp.where(keep[:, 0], prev, tag)
        # emit the tag at position k; positions >= length emit last valid tag
        return new_tag, jnp.where(keep[:, 0], tag, -1)

    first_tag, rev_path = jax.lax.scan(bwd, last_tag, (back, keeps),
                                       reverse=True)
    path = jnp.concatenate([first_tag[:, None],
                            jnp.moveaxis(rev_path, 0, 1)], axis=1)  # [N, T]
    # the reverse scan emits -1 only at invalid (k >= length) positions,
    # which this mask zeroes anyway
    pos = jnp.arange(t)[None, :]
    valid = pos < lengths[:, None]
    path = jnp.where(valid, path, 0)
    if ins.get("Label") and ins["Label"][0] is not None:
        lbl = ins["Label"][0]
        if lbl.ndim == 3:
            lbl = lbl[:, :, 0]
        if squeeze:
            lbl = jnp.asarray(lbl).reshape(1, -1)
        hit = (path == lbl.astype(path.dtype)) & valid
        path = hit.astype(jnp.int64)
    else:
        path = path.astype(jnp.int64)
    return {"ViterbiPath": path[0] if squeeze else path}


_SCHEMES = {
    # scheme -> (num_tag_types, begin, inside, end, single)
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _chunk_flags(labels, lengths, num_chunk_types, scheme):
    """Vectorized chunk state machine: per-position (begin, end-position,
    type) flags equivalent to the reference's ChunkBegin/ChunkEnd scan.
    Key fact making this exact: whenever ChunkBegin fires mid-run,
    ChunkEnd fires for the previous chunk, and every non-Other run starts
    with a begin — so chunks are runs of non-Other positions split at
    begin flags. Returns (begin [N,T] bool, ends [N,T] int32 = index of the
    chunk end for the chunk starting here, typ [N,T] int32)."""
    ntag, t_beg, t_in, t_end, t_sng = _SCHEMES[scheme]
    other = num_chunk_types
    lab = labels.astype(jnp.int32)
    n, t = lab.shape
    tag = lab % ntag
    typ = lab // ntag
    pos = jnp.arange(t, dtype=jnp.int32)
    valid = pos[None, :] < lengths.astype(jnp.int32)[:, None]
    typ = jnp.where(valid, typ, other)

    ptag = jnp.concatenate([jnp.full((n, 1), -1, tag.dtype),
                            tag[:, :-1]], axis=1)
    ptyp = jnp.concatenate([jnp.full((n, 1), other, typ.dtype),
                            typ[:, :-1]], axis=1)
    is_other = typ == other
    p_other = ptyp == other
    same_type = typ == ptyp
    tag_cond = ((tag == t_beg) | (tag == t_sng) |
                (((tag == t_in) | (tag == t_end)) &
                 ((ptag == t_end) | (ptag == t_sng))))
    begin = jnp.where(p_other, ~is_other,
                      jnp.where(is_other, False,
                                jnp.where(~same_type, True, tag_cond)))
    next_begin = jnp.concatenate(
        [begin[:, 1:], jnp.zeros((n, 1), bool)], axis=1)
    next_other = jnp.concatenate(
        [is_other[:, 1:], jnp.ones((n, 1), bool)], axis=1)
    end = (~is_other) & (next_other | next_begin)
    # for each position, the index of the next end at-or-after it
    end_idx = jnp.where(end, pos[None, :], t + 1)
    ends = jax.lax.cummin(end_idx, axis=1, reverse=True)
    return begin, ends, typ


@register_op("chunk_eval", grad=None,
             nondiff_inputs=("Inference", "Label", "SeqLength"))
def chunk_eval(ins, attrs, ctx):
    """Chunk precision/recall/F1 (reference: chunk_eval_op.h). The
    reference walks each LoD sequence with a state machine on the host;
    here the state machine is vectorized over the padded batch (shifted
    compares + a reverse cummin for chunk extents) so the metric runs
    in-graph on TPU."""
    inference = ins["Inference"][0]
    label = ins["Label"][0]
    if inference.ndim == 1:
        inference, label = inference[None], label[None]
    if inference.ndim == 3:
        inference, label = inference[:, :, 0], label[:, :, 0]
    n, t = inference.shape
    if ins.get("SeqLength") and ins["SeqLength"][0] is not None:
        seqlen = ins["SeqLength"][0].reshape(-1)
    else:
        seqlen = jnp.full((n,), t, jnp.int32)
    num_chunk_types = int(attrs["num_chunk_types"])
    scheme = attrs.get("chunk_scheme", "IOB")
    excluded = tuple(attrs.get("excluded_chunk_types", []) or [])

    bi, ei, ti = _chunk_flags(inference, seqlen, num_chunk_types, scheme)
    bl, el, tl = _chunk_flags(label, seqlen, num_chunk_types, scheme)

    def keep(typ):
        m = jnp.ones(typ.shape, bool)
        for e in excluded:
            m &= typ != int(e)
        return m

    int_dt = jnp.asarray(0, jnp.int64).dtype  # canonical int
    ni = jnp.sum(bi & keep(ti)).astype(int_dt)
    nl = jnp.sum(bl & keep(tl)).astype(int_dt)
    correct = bi & bl & (ti == tl) & (ei == el) & keep(ti)
    nc = jnp.sum(correct).astype(int_dt)

    p = jnp.where(ni > 0, nc / jnp.maximum(ni, 1), 0.0).astype(jnp.float32)
    r = jnp.where(nl > 0, nc / jnp.maximum(nl, 1), 0.0).astype(jnp.float32)
    f1 = jnp.where(nc > 0, 2 * p * r / jnp.maximum(p + r, 1e-12),
                   0.0).astype(jnp.float32)
    return {"Precision": p.reshape(1), "Recall": r.reshape(1),
            "F1-Score": f1.reshape(1), "NumInferChunks": ni.reshape(1),
            "NumLabelChunks": nl.reshape(1),
            "NumCorrectChunks": nc.reshape(1)}
