"""Control-flow ops with nested sub-blocks.

Reference: operators/controlflow/conditional_block_op.cc, while_op.cc,
recurrent_op.cc — sub-blocks stored as BLOCK attrs, interpreted by nested
executors with step-scopes.

TPU-native: sub-blocks lower into `lax.cond` / `lax.while_loop` / `lax.scan`
inside the same XLA computation. `scan` replaces recurrent_op/StaticRNN and
is reverse-differentiable via the generic vjp grad (lax.scan supports vjp);
`while` is forward-only (XLA's while has no reverse-mode — the reference's
while_grad re-runs the block per step, which scan covers).

Grad note: outer vars captured by a sub-block only receive gradients if
passed through the op's "Input" slot (declared in `input_names`) — the layers
API does this for parameters.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _block_idx(attrs, key):
    v = attrs[key]
    if isinstance(v, dict):
        return v["__block__"]
    return int(v)


@register_op("cond", nondiff_inputs=("Cond",))
def cond_op(ins, attrs, ctx):
    """Two-branch conditional (replaces the reference's pair of
    conditional_block ops + select_input used by layers.cond)."""
    pred = ins["Cond"][0].reshape(())
    input_names = list(attrs.get("input_names", []))
    operands = list(ins.get("Input", []))
    out_names = list(attrs["out_names"])
    tb = _block_idx(attrs, "true_block")
    fb = _block_idx(attrs, "false_block")

    def make_branch(bidx):
        def branch(ops):
            env = dict(ctx.env or {})
            env.update(zip(input_names, ops))
            ctx.lower_block(bidx, env)
            return [env[n] for n in out_names]

        return branch

    outs = jax.lax.cond(pred, make_branch(tb), make_branch(fb), operands)
    return {"Out": list(outs)}


@register_op("while", grad=None, nondiff_inputs=("Condition", "X"))
def while_op(ins, attrs, ctx):
    """reference: controlflow/while_op.cc. Loop-carried vars are every var
    the sub-block writes (attr carry_names), incl. the condition var."""
    bidx = _block_idx(attrs, "sub_block")
    carry_names = list(attrs["carry_names"])
    cond_name = attrs["cond_name"]
    env0 = dict(ctx.env or {})
    init = [env0[n] for n in carry_names]
    cond0 = ins["Condition"][0].reshape(())

    def cond_fun(state):
        pred, _ = state
        return pred

    def body_fun(state):
        _, carry = state
        env = dict(env0)
        env.update(zip(carry_names, carry))
        ctx.lower_block(bidx, env)
        new_carry = [env[n] for n in carry_names]
        new_pred = env[cond_name].reshape(())
        return new_pred, new_carry

    _, final = jax.lax.while_loop(cond_fun, body_fun, (cond0, init))
    return {"Out": list(final)}


@register_op("while_v2", grad=None, nondiff_inputs=("X", "Extra"))
def while_v2_op(ins, attrs, ctx):
    """Functional while: separate cond and body sub-blocks over an explicit
    carry (layers.while_loop). Forward-only like the reference's while."""
    cb = _block_idx(attrs, "cond_block")
    bb = _block_idx(attrs, "body_block")
    carry_names = list(attrs["carry_names"])
    extra_names = list(attrs.get("extra_names", []))
    pred_name = attrs["pred_name"]
    body_out_names = list(attrs["body_out_names"])
    extras = list(ins.get("Extra", []))
    env0 = dict(ctx.env or {})
    env0.update(zip(extra_names, extras))

    def run_block(bidx, carry, out_names):
        env = dict(env0)
        env.update(zip(carry_names, carry))
        ctx.lower_block(bidx, env)
        return [env[n] for n in out_names]

    final = jax.lax.while_loop(
        lambda c: run_block(cb, c, [pred_name])[0].reshape(()),
        lambda c: run_block(bb, c, body_out_names),
        list(ins["X"]))
    return {"Out": list(final)}


@register_op("scan")
def scan_op(ins, attrs, ctx):
    """Sequence recurrence via lax.scan — the TPU-native recurrent_op
    (reference: recurrent_op.cc, StaticRNN layers/control_flow.py). Inputs:
      SeqIn    : tensors [T, ...] sliced per step (in-block names seq_names)
      InitState: initial states (in-block prev-state names state_names;
                 the block writes state_out_names each step)
      Extra    : extra captured tensors needing grads (extra_names)
    Outputs: per-step outs stacked [T, ...] (out_names) + FinalState.
    Differentiable (generic vjp through lax.scan)."""
    bidx = _block_idx(attrs, "sub_block")
    seq_names = list(attrs.get("seq_names", []))
    state_names = list(attrs.get("state_names", []))
    state_out_names = list(attrs.get("state_out_names", []))
    extra_names = list(attrs.get("extra_names", []))
    out_names = list(attrs.get("out_names", []))
    reverse = bool(attrs.get("is_reverse", False))

    seqs = list(ins.get("SeqIn", []))
    init = list(ins.get("InitState", []))
    extras = list(ins.get("Extra", []))
    env0 = dict(ctx.env or {})

    def body(carry, xs):
        env = dict(env0)
        env.update(zip(extra_names, extras))
        env.update(zip(state_names, carry))
        env.update(zip(seq_names, xs))
        ctx.lower_block(bidx, env)
        new_carry = [env[n] for n in state_out_names]
        step_outs = [env[n] for n in out_names]
        return new_carry, step_outs

    final, ys = jax.lax.scan(body, init, seqs, reverse=reverse)
    return {"Out": list(ys), "FinalState": list(final)}


@register_op("select_input", nondiff_inputs=("Mask",))
def select_input(ins, attrs, ctx):
    mask = ins["Mask"][0].reshape(()).astype(jnp.int32)
    xs = ins["X"]
    return {"Out": jax.lax.switch(mask, [lambda i=i: xs[i] for i in range(len(xs))])}


@register_op("assign_skip", grad=None)
def assign_skip(ins, attrs, ctx):
    return {"Out": ins["X"][0]}
