"""NN ops: conv/pool/norm/dropout/softmax/losses.

Reference: paddle/fluid/operators/{conv_op.cc,conv_cudnn_op.cu.cc,
pool_op.cc,batch_norm_op.cc,layer_norm_op.cc,dropout_op.cc,softmax_op.cc,
cross_entropy_op.cc,softmax_with_cross_entropy_op.cc,...}. The cuDNN
dispatch (`use_cudnn` attr) has no TPU meaning: XLA lowers conv/matmul onto
the MXU directly, so the attr is accepted and ignored.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op


# ---------------------------------------------------------------------------
# Convolutions (NCHW like the reference; lax conv handles layout for TPU)
# ---------------------------------------------------------------------------


def _conv_padding(attrs, spatial_rank, strides, x_spatial, k_spatial, dilations):
    algo = attrs.get("padding_algorithm", "EXPLICIT")
    if algo == "SAME":
        return "SAME"
    if algo == "VALID":
        return "VALID"
    pads = [int(p) for p in attrs.get("paddings", [0] * spatial_rank)]
    if len(pads) == spatial_rank:
        return [(p, p) for p in pads]
    # [before0, after0, before1, after1, ...]
    return [(pads[2 * i], pads[2 * i + 1]) for i in range(spatial_rank)]


def _conv_nd(x, w, attrs, nd, feature_group_count=None, f32_accum=True):
    strides = tuple(int(s) for s in attrs.get("strides", [1] * nd))
    dilations = tuple(int(d) for d in attrs.get("dilations", [1] * nd))
    groups = int(attrs.get("groups", 1)) if feature_group_count is None else feature_group_count
    padding = _conv_padding(attrs, nd, strides, x.shape[2:], w.shape[2:], dilations)
    dn_str = ("NCHW", "OIHW", "NCHW") if nd == 2 else ("NCDHW", "OIDHW", "NCDHW")
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, dn_str)
    # f32_accum (inference only): explicit f32 accumulation for bf16
    # convs. The TRAINING path must not request it — jax 0.4.x's conv
    # transpose rule feeds the f32-typed cotangent back into a conv
    # against the bf16 filter and rejects the dtype mix, so the
    # differentiable path accumulates at the input width (the TPU MXU
    # accumulates bf16 partials in f32 internally regardless).
    accum = jnp.float32 if f32_accum and x.dtype == jnp.bfloat16 else None
    return jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=accum,
    ).astype(x.dtype)


@register_op("conv2d", nondiff_inputs=())
def conv2d(ins, attrs, ctx):
    x, w = ins["Input"][0], ins["Filter"][0]
    out = _conv_nd(x, w, attrs, 2, f32_accum=ctx.is_test)
    if ins.get("Bias") and ins["Bias"][0] is not None:
        out = out + ins["Bias"][0].reshape(1, -1, 1, 1)
    return {"Output": out}


@register_op("depthwise_conv2d")
def depthwise_conv2d(ins, attrs, ctx):
    x, w = ins["Input"][0], ins["Filter"][0]
    # reference: groups == in_channels; lax expects OIHW with I = C/groups = 1
    out = _conv_nd(x, w, attrs, 2, feature_group_count=x.shape[1],
                   f32_accum=ctx.is_test)
    return {"Output": out}


@register_op("conv3d")
def conv3d(ins, attrs, ctx):
    x, w = ins["Input"][0], ins["Filter"][0]
    return {"Output": _conv_nd(x, w, attrs, 3, f32_accum=ctx.is_test)}


@register_op("conv2d_transpose")
def conv2d_transpose(ins, attrs, ctx):
    x, w = ins["Input"][0], ins["Filter"][0]  # w: [C_in, C_out/groups, H, W]
    strides = tuple(int(s) for s in attrs.get("strides", [1, 1]))
    dilations = tuple(int(d) for d in attrs.get("dilations", [1, 1]))
    pads = attrs.get("paddings", [0, 0])
    if len(pads) == 2:
        pad_pairs = [(int(p), int(p)) for p in pads]
    else:
        pad_pairs = [(int(pads[0]), int(pads[1])),
                     (int(pads[2]), int(pads[3]))]
    # jax's conv_transpose applies `padding` to the underlying dilated
    # conv; the transpose of a conv padded by p needs (k-1)*d - p so the
    # output is (in-1)*s - 2p + (k-1)*d + 1 (conv_transpose_op.cc shape)
    padding = [((w.shape[2 + i] - 1) * dilations[i] - lo,
                (w.shape[2 + i] - 1) * dilations[i] - hi)
               for i, (lo, hi) in enumerate(pad_pairs)]
    # kernel layout is [C_in, C_out, H, W]; with transpose_kernel=True
    # conv_transpose swaps the I/O labels, so axis 0 must be labeled O for
    # the effective input-feature axis to be C_in (C_in != C_out broke
    # under "IOHW")
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, ("NCHW", "OIHW", "NCHW"))
    out = jax.lax.conv_transpose(
        x, w, strides=strides, padding=padding,
        rhs_dilation=dilations, dimension_numbers=dn, transpose_kernel=True)
    return {"Output": out}


# ---------------------------------------------------------------------------
# Pooling (reference: operators/pool_op.cc; math/pooling.{cc,cu})
# ---------------------------------------------------------------------------


def _pool2d(x, attrs):
    ptype = attrs.get("pooling_type", "max")
    ksize = [int(k) for k in attrs.get("ksize", [2, 2])]
    strides = [int(s) for s in attrs.get("strides", ksize)]
    pads = [int(p) for p in attrs.get("paddings", [0, 0])]
    if attrs.get("global_pooling", False) or attrs.get("adaptive", False) and all(
            k == 1 for k in ksize):
        if ptype == "max":
            return jnp.max(x, axis=(2, 3), keepdims=True)
        return jnp.mean(x, axis=(2, 3), keepdims=True)
    if attrs.get("adaptive", False):
        n, c, h, w = x.shape
        oh, ow = ksize
        assert h % oh == 0 and w % ow == 0, "adaptive pool needs divisible dims"
        xr = x.reshape(n, c, oh, h // oh, ow, w // ow)
        return jnp.max(xr, axis=(3, 5)) if ptype == "max" else jnp.mean(xr, axis=(3, 5))

    window = (1, 1, ksize[0], ksize[1])
    strides_ = (1, 1, strides[0], strides[1])
    if len(pads) == 2:
        padding = [(0, 0), (0, 0), (pads[0], pads[0]), (pads[1], pads[1])]
    else:
        padding = [(0, 0), (0, 0), (pads[0], pads[1]), (pads[2], pads[3])]
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return jax.lax.reduce_window(x, init, jax.lax.max, window, strides_, padding)
    s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides_, padding)
    if attrs.get("exclusive", True) and any(p != (0, 0) for p in padding):
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides_, padding)
        return s / counts
    return s / (ksize[0] * ksize[1])


@register_op("pool2d")
def pool2d(ins, attrs, ctx):
    return {"Out": _pool2d(ins["X"][0], attrs)}


@register_op("pool3d")
def pool3d(ins, attrs, ctx):
    x = ins["X"][0]
    ptype = attrs.get("pooling_type", "max")
    if attrs.get("global_pooling", False):
        fn = jnp.max if ptype == "max" else jnp.mean
        return {"Out": fn(x, axis=(2, 3, 4), keepdims=True)}
    ksize = [int(k) for k in attrs.get("ksize", [2, 2, 2])]
    strides = [int(s) for s in attrs.get("strides", ksize)]
    pads = [int(p) for p in attrs.get("paddings", [0, 0, 0])]
    window = (1, 1) + tuple(ksize)
    strides_ = (1, 1) + tuple(strides)
    padding = [(0, 0), (0, 0)] + [(p, p) for p in pads]
    if ptype == "max":
        return {"Out": jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, strides_, padding)}
    s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides_, padding)
    return {"Out": s / float(np.prod(ksize))}


def _bilinear_sample_chw(x, ys, xs):
    """Bilinear sample x [C,H,W] at float coords (ys, xs) of any shape;
    out-of-range corners contribute 0 (the reference deformable kernels'
    zero-padding semantics). Returns [C, *ys.shape]."""
    h, w = x.shape[1:]
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy = (ys - y0).astype(x.dtype)
    wx = (xs - x0).astype(x.dtype)

    def gather(yy, xx):
        inb = (yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        return x[:, yc, xc] * inb.astype(x.dtype)

    return (gather(y0, x0) * (1 - wy) * (1 - wx)
            + gather(y0, x0 + 1) * (1 - wy) * wx
            + gather(y0 + 1, x0) * wy * (1 - wx)
            + gather(y0 + 1, x0 + 1) * wy * wx)


def _deformable_conv(ins, attrs, modulated):
    """reference: deformable_conv_op.h (v2, modulated) /
    deformable_conv_v1_op.h — y(p) = sum_k w_k * x(p + p_k + dp_k) * dm_k.
    TPU-native: bilinear gather of the K sampled taps into an im2col
    column tensor, then one grouped einsum on the MXU (replaces the
    reference's ModulatedDeformableIm2col + GEMM per image)."""
    x = ins["Input"][0]                       # [N, C, H, W]
    off = ins["Offset"][0]                    # [N, dg*K*2, OH, OW]
    w = ins["Filter"][0]                      # [Cout, C/groups, kh, kw]
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    pads = [int(p) for p in attrs.get("paddings", [0, 0])]
    dils = [int(d) for d in attrs.get("dilations", [1, 1])]
    groups = int(attrs.get("groups", 1))
    dg = int(attrs.get("deformable_groups", 1))
    n, c, h_in, w_in = x.shape
    cout, cg, kh, kw = w.shape
    K = kh * kw
    oh, ow = off.shape[2], off.shape[3]
    cpg = c // dg

    # base sampling grid: h = oh*stride - pad + ki*dilation (+ offset)
    ki = (jnp.arange(K) // kw).astype(x.dtype)
    kj = (jnp.arange(K) % kw).astype(x.dtype)
    base_y = (jnp.arange(oh, dtype=x.dtype) * strides[0] - pads[0])
    base_x = (jnp.arange(ow, dtype=x.dtype) * strides[1] - pads[1])
    grid_y = base_y[None, :, None] + ki[:, None, None] * dils[0]  # [K,OH,1]
    grid_x = base_x[None, None, :] + kj[:, None, None] * dils[1]  # [K,1,OW]

    off = off.reshape(n, dg, K, 2, oh, ow)
    if modulated:
        mask = ins["Mask"][0].reshape(n, dg, K, oh, ow)
    else:
        mask = None

    def per_image(xi, offi, maski):
        def per_group(xg, og, mg):
            ys = grid_y + og[:, 0]            # [K, OH, OW]
            xs = grid_x + og[:, 1]
            v = _bilinear_sample_chw(xg, ys, xs)   # [cpg, K, OH, OW]
            return v if mg is None else v * mg[None].astype(v.dtype)
        xg = xi.reshape(dg, cpg, h_in, w_in)
        if maski is None:
            cols = jax.vmap(lambda a, b: per_group(a, b, None))(xg, offi)
        else:
            cols = jax.vmap(per_group)(xg, offi, maski)
        return cols.reshape(c, K, oh, ow)

    if mask is None:
        cols = jax.vmap(lambda a, b: per_image(a, b, None))(x, off)
    else:
        cols = jax.vmap(per_image)(x, off, mask)

    cols_g = cols.reshape(n, groups, cg, K, oh, ow)
    w_g = w.reshape(groups, cout // groups, cg, K).astype(cols.dtype)
    out = jnp.einsum("ngckhw,gock->ngohw", cols_g, w_g)
    return {"Output": out.reshape(n, cout, oh, ow)}


@register_op("deformable_conv")
def deformable_conv(ins, attrs, ctx):
    return _deformable_conv(ins, attrs, modulated=True)


@register_op("deformable_conv_v1")
def deformable_conv_v1(ins, attrs, ctx):
    return _deformable_conv(ins, attrs, modulated=False)


def _max_pool_with_index(x, attrs, nd):
    """Shared kernel for max_pool{2,3}d_with_index (reference:
    pool_with_index_op.cc, math/pooling.cc MaxPool*WithIdxFunctor).
    Mask = row-major flat index of the argmax within each channel's input
    volume; argmax keeps the FIRST maximum in scan order, like the
    reference's strict `<` comparison."""
    spatial = x.shape[2:]
    ksize = [int(k) for k in attrs.get("ksize", [2] * nd)]
    if attrs.get("global_pooling", False):
        ksize = list(spatial)
    strides = [int(s) for s in attrs.get("strides", ksize)]
    pads = [int(p) for p in attrs.get("paddings", [0] * nd)]
    if attrs.get("global_pooling", False):
        pads = [0] * nd
    if attrs.get("adaptive", False):
        # divisible adaptive bins (same convention as _pool2d)
        out_sz = ksize
        assert all(s % o == 0 for s, o in zip(spatial, out_sz)), \
            "adaptive pool needs divisible dims"
        ksize = [s // o for s, o in zip(spatial, out_sz)]
        strides = ksize
        pads = [0] * nd

    neg = jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.iinfo(x.dtype).min
    xp = jnp.pad(x, [(0, 0), (0, 0)] + [(p, p) for p in pads],
                 constant_values=neg)
    # patches: [N, C*prod(k), *out_spatial], feature dim ordered (C, k...)
    patches = jax.lax.conv_general_dilated_patches(
        xp, tuple(ksize), tuple(strides), "VALID")
    n, c = x.shape[:2]
    out_sp = patches.shape[2:]
    K = int(np.prod(ksize))
    patches = patches.reshape((n, c, K) + out_sp)
    k_local = jnp.argmax(patches, axis=2)                    # [N, C, *out]
    out = jnp.take_along_axis(patches, k_local[:, :, None], axis=2)[:, :, 0]

    # local k -> global row-major input index (padding never wins: its
    # value is dtype-min and every window overlaps >=1 real cell)
    idx = jnp.zeros(k_local.shape, jnp.int32)
    rem = k_local
    for d in range(nd):
        tail = int(np.prod(ksize[d + 1:]))
        kd = rem // tail
        rem = rem % tail
        coord = jnp.arange(out_sp[d]) * strides[d] - pads[d]
        shape = [1] * (2 + nd)
        shape[2 + d] = out_sp[d]
        g = coord.reshape(shape) + kd
        idx = idx * spatial[d] + g.astype(jnp.int32)
    return out, idx


@register_op("max_pool2d_with_index", intermediate_outputs=())
def max_pool2d_with_index(ins, attrs, ctx):
    out, mask = _max_pool_with_index(ins["X"][0], attrs, 2)
    return {"Out": out, "Mask": mask}


@register_op("max_pool3d_with_index")
def max_pool3d_with_index(ins, attrs, ctx):
    out, mask = _max_pool_with_index(ins["X"][0], attrs, 3)
    return {"Out": out, "Mask": mask}


@register_op("unpool", nondiff_inputs=("Indices",))
def unpool(ins, attrs, ctx):
    """reference: unpool_op.cc ('max' unpooling) — scatter X into a zero
    output at the row-major positions recorded by max_pool2d_with_index;
    out_size = (in-1)*stride - 2*pad + ksize."""
    x, idx = ins["X"][0], ins["Indices"][0]
    n, c, h, w = x.shape
    ksize = [int(k) for k in attrs.get("ksize", [2, 2])]
    strides = [int(s) for s in attrs.get("strides", ksize)]
    pads = [int(p) for p in attrs.get("paddings", [0, 0])]
    oh = (h - 1) * strides[0] - 2 * pads[0] + ksize[0]
    ow = (w - 1) * strides[1] - 2 * pads[1] + ksize[1]
    flat = x.reshape(n * c, h * w)
    idxf = idx.reshape(n * c, h * w).astype(jnp.int32)
    rows = jnp.arange(n * c)[:, None]
    out = jnp.zeros((n * c, oh * ow), x.dtype).at[rows, idxf].set(flat)
    return {"Out": out.reshape(n, c, oh, ow)}


@register_op("spp")
def spp(ins, attrs, ctx):
    """reference: spp_op.h — spatial pyramid pooling: levels p=0..H-1 pool
    into 2^p x 2^p bins (kernel=ceil(dim/bins), pad=(k*bins-dim+1)/2),
    flattened and concatenated along channels."""
    x = ins["X"][0]
    n, c, h, w = x.shape
    height = int(attrs.get("pyramid_height", 1))
    ptype = attrs.get("pooling_type", "max")
    outs = []
    for p in range(height):
        bins = 2 ** p
        kh = -(-h // bins)
        kw = -(-w // bins)
        ph_ = (kh * bins - h + 1) // 2
        pw_ = (kw * bins - w + 1) // 2
        lvl = _pool2d(x, {"pooling_type": ptype, "ksize": [kh, kw],
                          "strides": [kh, kw], "paddings": [ph_, pw_],
                          "exclusive": True})
        outs.append(lvl.reshape(n, c * bins * bins))
    return {"Out": jnp.concatenate(outs, axis=1)}


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------


@register_op("batch_norm", nondiff_inputs=("Mean", "Variance"),
             intermediate_outputs=("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"))
def batch_norm(ins, attrs, ctx):
    """reference: operators/batch_norm_op.cc.

    NOTE (TPU semantics): under data-parallel GSPMD sharding the batch
    reductions below become *global* (cross-replica) reductions — i.e. this
    is automatically sync-BN (reference needs BuildStrategy.sync_batch_norm +
    sync_batch_norm_op.cu).
    """
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = bool(attrs.get("is_test", False)) or ctx.is_test
    use_global = bool(attrs.get("use_global_stats", False)) or is_test
    layout = attrs.get("data_layout", "NCHW")
    axes = tuple(i for i in range(x.ndim) if i != (1 if layout == "NCHW" else x.ndim - 1))
    ch_shape = [1] * x.ndim
    ch_shape[1 if layout == "NCHW" else -1] = x.shape[1 if layout == "NCHW" else -1]

    if use_global:
        m, v = mean, var
        y = (x - m.reshape(ch_shape)) * (scale.reshape(ch_shape) *
             jax.lax.rsqrt(v.reshape(ch_shape) + eps)) + bias.reshape(ch_shape)
        return {"Y": y, "MeanOut": mean, "VarianceOut": var,
                "SavedMean": mean, "SavedVariance": var}

    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=axes)
    v = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(m)
    y = (xf - m.reshape(ch_shape)) * jax.lax.rsqrt(v.reshape(ch_shape) + eps)
    y = y.astype(x.dtype) * scale.reshape(ch_shape) + bias.reshape(ch_shape)
    new_mean = mean * momentum + m * (1.0 - momentum)
    new_var = var * momentum + v * (1.0 - momentum)
    return {"Y": y, "MeanOut": new_mean, "VarianceOut": new_var,
            "SavedMean": m, "SavedVariance": jax.lax.rsqrt(v + eps)}


@register_op("sync_batch_norm", nondiff_inputs=("Mean", "Variance"),
             intermediate_outputs=("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"))
def sync_batch_norm(ins, attrs, ctx):
    # identical to batch_norm: GSPMD makes batch reductions global
    return batch_norm(ins, attrs, ctx)


@register_op("layer_norm", intermediate_outputs=("Mean", "Variance"))
def layer_norm(ins, attrs, ctx):
    """reference: operators/layer_norm_op.cc (begin_norm_axis flattening)."""
    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    bna = int(attrs.get("begin_norm_axis", 1))
    axes = tuple(range(bna, x.ndim))
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=axes, keepdims=True)
    v = jnp.mean(jnp.square(xf - m), axis=axes, keepdims=True)
    y = (xf - m) * jax.lax.rsqrt(v + eps)
    y = y.astype(x.dtype)
    norm_shape = x.shape[bna:]
    if ins.get("Scale") and ins["Scale"][0] is not None:
        y = y * ins["Scale"][0].reshape(norm_shape)
    if ins.get("Bias") and ins["Bias"][0] is not None:
        y = y + ins["Bias"][0].reshape(norm_shape)
    return {"Y": y, "Mean": m.reshape(x.shape[:bna]), "Variance": v.reshape(x.shape[:bna])}


@register_op("group_norm", intermediate_outputs=("Mean", "Variance"))
def group_norm(ins, attrs, ctx):
    x = ins["X"][0]  # NCHW
    groups = int(attrs["groups"])
    eps = attrs.get("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, groups, c // groups) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    m = jnp.mean(xg, axis=axes, keepdims=True)
    v = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - m) * jax.lax.rsqrt(v + eps)).reshape(x.shape)
    ch = [1, c] + [1] * (x.ndim - 2)
    if ins.get("Scale") and ins["Scale"][0] is not None:
        y = y * ins["Scale"][0].reshape(ch)
    if ins.get("Bias") and ins["Bias"][0] is not None:
        y = y + ins["Bias"][0].reshape(ch)
    return {"Y": y, "Mean": m.reshape(n, groups), "Variance": v.reshape(n, groups)}


@register_op("instance_norm", intermediate_outputs=("SavedMean", "SavedVariance"))
def instance_norm(ins, attrs, ctx):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    m = jnp.mean(x, axis=axes, keepdims=True)
    v = jnp.var(x, axis=axes, keepdims=True)
    y = (x - m) * jax.lax.rsqrt(v + eps)
    ch = [1, x.shape[1]] + [1] * (x.ndim - 2)
    if ins.get("Scale") and ins["Scale"][0] is not None:
        y = y * ins["Scale"][0].reshape(ch)
    if ins.get("Bias") and ins["Bias"][0] is not None:
        y = y + ins["Bias"][0].reshape(ch)
    return {"Y": y, "SavedMean": jnp.squeeze(m), "SavedVariance": jnp.squeeze(v)}


@register_op("l2_normalize")
def l2_normalize(ins, attrs, ctx):
    x = ins["X"][0]
    axis = int(attrs.get("axis", -1))
    eps = attrs.get("epsilon", 1e-10)
    return {"Out": x * jax.lax.rsqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)}


# ---------------------------------------------------------------------------
# Dropout / softmax
# ---------------------------------------------------------------------------


@register_op("dropout", is_random=True, intermediate_outputs=("Mask",))
def dropout(ins, attrs, ctx):
    """reference: operators/dropout_op.cc (upscale_in_train vs
    downgrade_in_infer implementations)."""
    x = ins["X"][0]
    p = attrs.get("dropout_prob", 0.5)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    is_test = bool(attrs.get("is_test", False)) or ctx.is_test
    if is_test or p == 0.0:
        out = x if impl == "upscale_in_train" else x * (1.0 - p)
        return {"Out": out, "Mask": jnp.ones_like(x, dtype=jnp.uint8)}
    keep = jax.random.bernoulli(ctx.rng(), 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    else:
        out = jnp.where(keep, x, 0.0).astype(x.dtype)
    return {"Out": out, "Mask": keep.astype(jnp.uint8)}


@register_op("softmax")
def softmax(ins, attrs, ctx):
    x = ins["X"][0]
    axis = int(attrs.get("axis", -1))
    return {"Out": jax.nn.softmax(x, axis=axis)}


@register_op("log_softmax")
def log_softmax(ins, attrs, ctx):
    x = ins["X"][0]
    return {"Out": jax.nn.log_softmax(x, axis=int(attrs.get("axis", -1)))}


# ---------------------------------------------------------------------------
# Losses
# ---------------------------------------------------------------------------


@register_op("cross_entropy", nondiff_inputs=("Label",))
def cross_entropy(ins, attrs, ctx):
    """reference: operators/cross_entropy_op.cc — X is a probability
    distribution; hard or soft labels."""
    x, label = ins["X"][0], ins["Label"][0]
    ignore_index = int(attrs.get("ignore_index", -100))
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, 1e-20)), axis=-1, keepdims=True)
        return {"Y": loss}
    idx = label.astype(jnp.int32)
    if idx.ndim == x.ndim and idx.shape[-1] == 1:
        idx = idx[..., 0]
    picked = jnp.take_along_axis(x, idx[..., None], axis=-1)
    loss = -jnp.log(jnp.maximum(picked, 1e-20))
    if ignore_index != -100:
        loss = jnp.where(idx[..., None] == ignore_index, 0.0, loss)
    return {"Y": loss}


@register_op("softmax_with_cross_entropy", nondiff_inputs=("Label",),
             intermediate_outputs=("Softmax",))
def softmax_with_cross_entropy(ins, attrs, ctx):
    """reference: operators/softmax_with_cross_entropy_op.cc — numerically
    stable fused version (the BERT/Transformer loss)."""
    logits, label = ins["Logits"][0], ins["Label"][0]
    axis = int(attrs.get("axis", -1))
    lse = jax.scipy.special.logsumexp(logits, axis=axis, keepdims=True)
    log_probs = logits - lse
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * log_probs, axis=axis, keepdims=True)
    else:
        idx = label.astype(jnp.int32)
        if idx.ndim == logits.ndim and idx.shape[axis] == 1:
            idx = jnp.squeeze(idx, axis)
        picked = jnp.take_along_axis(log_probs, jnp.expand_dims(idx, axis), axis=axis)
        loss = -picked
        ignore_index = int(attrs.get("ignore_index", -100))
        if ignore_index >= 0:
            loss = jnp.where(jnp.expand_dims(idx, axis) == ignore_index, 0.0, loss)
    return {"Loss": loss, "Softmax": jnp.exp(log_probs)}


@register_op("sigmoid_cross_entropy_with_logits", nondiff_inputs=("Label",))
def sigmoid_cross_entropy_with_logits(ins, attrs, ctx):
    x, label = ins["X"][0], ins["Label"][0]
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore_index = int(attrs.get("ignore_index", -100))
    if ignore_index != -100:
        loss = jnp.where(label == ignore_index, 0.0, loss)
    if attrs.get("normalize", False):
        n = jnp.maximum(jnp.sum(label != ignore_index), 1.0)
        loss = loss / n
    return {"Out": loss}


@register_op("square_error_cost", nondiff_inputs=())
def square_error_cost(ins, attrs, ctx):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": jnp.square(x - y)}


@register_op("smooth_l1_loss", nondiff_inputs=("InsideWeight", "OutsideWeight"),
             intermediate_outputs=("Diff",))
def smooth_l1_loss(ins, attrs, ctx):
    x, y = ins["X"][0], ins["Y"][0]
    sigma = attrs.get("sigma", 1.0)
    sigma2 = sigma * sigma
    diff = x - y
    if ins.get("InsideWeight") and ins["InsideWeight"][0] is not None:
        diff = diff * ins["InsideWeight"][0]
    abs_diff = jnp.abs(diff)
    loss = jnp.where(abs_diff < 1.0 / sigma2,
                     0.5 * sigma2 * jnp.square(diff),
                     abs_diff - 0.5 / sigma2)
    if ins.get("OutsideWeight") and ins["OutsideWeight"][0] is not None:
        loss = loss * ins["OutsideWeight"][0]
    return {"Out": jnp.sum(loss, axis=tuple(range(1, loss.ndim)), keepdims=False)[..., None],
            "Diff": diff}


@register_op("huber_loss", intermediate_outputs=("Residual",))
def huber_loss(ins, attrs, ctx):
    x, y = ins["X"][0], ins["Y"][0]
    delta = attrs.get("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * jnp.square(r), delta * (ar - 0.5 * delta))
    return {"Out": loss, "Residual": r}


@register_op("kldiv_loss", nondiff_inputs=("Target",))
def kldiv_loss(ins, attrs, ctx):
    x, t = ins["X"][0], ins["Target"][0]
    loss = t * (jnp.log(jnp.maximum(t, 1e-20)) - x)
    red = attrs.get("reduction", "mean")
    if red == "mean":
        return {"Loss": jnp.mean(loss)}
    if red == "sum":
        return {"Loss": jnp.sum(loss)}
    if red == "batchmean":
        return {"Loss": jnp.sum(loss) / x.shape[0]}
    return {"Loss": loss}


@register_op("bce_loss", nondiff_inputs=("Label",))
def bce_loss(ins, attrs, ctx):
    x, label = ins["X"][0], ins["Label"][0]
    return {"Out": -(label * jnp.log(jnp.maximum(x, 1e-12))
                     + (1 - label) * jnp.log(jnp.maximum(1 - x, 1e-12)))}


@register_op("margin_rank_loss", nondiff_inputs=("Label",),
             intermediate_outputs=("Activated",))
def margin_rank_loss(ins, attrs, ctx):
    x1, x2, label = ins["X1"][0], ins["X2"][0], ins["Label"][0]
    margin = attrs.get("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": out, "Activated": (out > 0).astype(x1.dtype)}


@register_op("hinge_loss", nondiff_inputs=("Labels",))
def hinge_loss(ins, attrs, ctx):
    logits, labels = ins["Logits"][0], ins["Labels"][0]
    return {"Loss": jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits)}


# ---------------------------------------------------------------------------
# Interpolation / resampling
# ---------------------------------------------------------------------------


def _linear_resize_weights(s, o, align_corners, align_mode, dtype):
    """[o, s] interpolation-weight matrix for one axis (two taps per row).
    Source positions follow interpolate_op.h: align_corners →
    i*(s-1)/(o-1); align_mode 0 → (i+0.5)*s/o - 0.5; align_mode 1 →
    i*s/o."""
    if o == 1 or s == 1:
        pos = jnp.zeros((o,), dtype)
    elif align_corners:
        pos = jnp.arange(o, dtype=dtype) * (s - 1) / (o - 1)
    elif int(align_mode) == 0:
        pos = (jnp.arange(o, dtype=dtype) + 0.5) * s / o - 0.5
    else:
        pos = jnp.arange(o, dtype=dtype) * s / o
    pos = jnp.clip(pos, 0.0, s - 1)
    lo = jnp.floor(pos).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, s - 1)
    frac = (pos - lo).astype(dtype)
    rows = jnp.arange(o)
    return jnp.zeros((o, s), dtype).at[rows, lo].add(1.0 - frac) \
        .at[rows, hi].add(frac)


def _interp(ins, attrs, method):
    """reference: interpolate_op.h — separable linear resize honoring
    align_corners/align_mode (each axis is one [O,S] weight matmul; XLA
    fuses the chain onto the MXU). nearest keeps jax.image.resize."""
    x = ins["X"][0]  # NC + spatial
    n, c = x.shape[:2]
    spatial = x.shape[2:]
    nd = len(spatial)
    keys = ("out_d", "out_h", "out_w")[-nd:]
    given = [k for k in keys if attrs.get(k, -1) > 0]
    if given:
        assert len(given) == nd, (
            f"interp on {nd}-D spatial input needs all of {keys}, "
            f"got only {given}")
        out_sp = tuple(int(attrs[k]) for k in keys)
    else:
        scale = attrs.get("scale", 1.0)
        out_sp = tuple(int(s * scale) for s in spatial)
    if method == "nearest":
        out = jax.image.resize(x, (n, c) + out_sp, method="nearest")
        return {"Out": out.astype(x.dtype)}
    ac = bool(attrs.get("align_corners", True))
    am = int(attrs.get("align_mode", 1))
    wdt = jnp.float32 if x.dtype == jnp.bfloat16 else x.dtype
    out = x.astype(wdt)
    for d in range(nd):
        wm = _linear_resize_weights(spatial[d], out_sp[d], ac, am, wdt)
        out = jnp.moveaxis(
            jnp.tensordot(wm, jnp.moveaxis(out, 2 + d, 0), axes=([1], [0])),
            0, 2 + d)
    return {"Out": out.astype(x.dtype)}


@register_op("bilinear_interp")
def bilinear_interp(ins, attrs, ctx):
    return _interp(ins, attrs, "bilinear")


@register_op("nearest_interp")
def nearest_interp(ins, attrs, ctx):
    return _interp(ins, attrs, "nearest")


@register_op("trilinear_interp")
def trilinear_interp(ins, attrs, ctx):
    """reference: interpolate_op.cc trilinear branch — 5-D NCDHW linear
    resize (resize_trilinear layer, nn.py:9716)."""
    return _interp(ins, attrs, "trilinear")


@register_op("grid_sampler")
def grid_sampler(ins, attrs, ctx):
    """reference: operators/grid_sampler_op.cc (cudnn spatial sampler) —
    bilinear sampling from normalized [-1,1] grid coords."""
    x, grid = ins["X"][0], ins["Grid"][0]  # x: NCHW, grid: NHW2
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1
    wx1, wy1 = gx - x0, gy - y0
    wx0, wy0 = 1.0 - wx1, 1.0 - wy1

    def sample(yy, xx):
        yy = jnp.clip(yy, 0, h - 1)
        xx = jnp.clip(xx, 0, w - 1)
        batch_idx = jnp.arange(n)[:, None, None]
        return x[batch_idx, :, yy, xx]  # N,H',W',C

    v00 = sample(y0, x0) * (wy0 * wx0)[..., None]
    v01 = sample(y0, x1) * (wy0 * wx1)[..., None]
    v10 = sample(y1, x0) * (wy1 * wx0)[..., None]
    v11 = sample(y1, x1) * (wy1 * wx1)[..., None]
    out = (v00 + v01 + v10 + v11).transpose(0, 3, 1, 2)
    return {"Output": out.astype(x.dtype)}


# ---------------------------------------------------------------------------
# Misc NN
# ---------------------------------------------------------------------------


@register_op("pixel_shuffle")
def pixel_shuffle(ins, attrs, ctx):
    x = ins["X"][0]
    r = int(attrs.get("upscale_factor", 1))
    n, c, h, w = x.shape
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = out.transpose(0, 1, 4, 2, 5, 3).reshape(n, c // (r * r), h * r, w * r)
    return {"Out": out}


@register_op("temporal_shift")
def temporal_shift(ins, attrs, ctx):
    x = ins["X"][0]
    seg = int(attrs["seg_num"])
    ratio = attrs.get("shift_ratio", 0.25)
    nt, c, h, w = x.shape
    n = nt // seg
    xr = x.reshape(n, seg, c, h, w)
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    fwd = jnp.concatenate([xr[:, 1:, :c1], jnp.zeros_like(xr[:, :1, :c1])], axis=1)
    back = jnp.concatenate([jnp.zeros_like(xr[:, :1, c1:c2]), xr[:, :-1, c1:c2]], axis=1)
    rest = xr[:, :, c2:]
    return {"Out": jnp.concatenate([fwd, back, rest], axis=2).reshape(nt, c, h, w)}


@register_op("label_smooth", nondiff_inputs=("PriorDist",))
def label_smooth(ins, attrs, ctx):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 0.0)
    k = x.shape[-1]
    if ins.get("PriorDist") and ins["PriorDist"][0] is not None:
        return {"Out": (1 - eps) * x + eps * ins["PriorDist"][0]}
    return {"Out": (1 - eps) * x + eps / k}


@register_op("embedding_with_scaled_gradient", nondiff_inputs=("Ids",))
def embedding_with_scaled_gradient(ins, attrs, ctx):
    from .tensor import lookup_table_v2

    return lookup_table_v2(ins, attrs, ctx)


@register_op("fc")
def fc_op(ins, attrs, ctx):
    """reference: fc_op.cc (the fused inference fc): Out =
    act(flatten(X) @ W + b) with in_num_col_dims."""
    x = ins["Input"][0]
    w = ins["W"][0]
    b = (ins.get("Bias") or [None])[0]
    ncol = int(attrs.get("in_num_col_dims", 1))
    lead = x.shape[:ncol]
    x2 = x.reshape((int(np.prod(lead)), -1))
    out = x2 @ w.astype(x2.dtype)
    if b is not None:
        out = out + b.reshape(1, -1).astype(out.dtype)
    act = attrs.get("activation_type", "")
    if act == "relu":
        out = jax.nn.relu(out)
    elif act:
        raise ValueError(f"fc: unsupported activation {act}")
    return {"Out": out.reshape(tuple(lead) + (w.shape[1],))}


@register_op("depthwise_conv2d_transpose")
def depthwise_conv2d_transpose(ins, attrs, ctx):
    """reference: conv_transpose_op.cc depthwise registration — grouped
    transpose conv with groups == channels. ONE batched HLO: vmap over
    the channel axis (a Python per-channel loop would emit C separate
    convs)."""
    x, w = ins["Input"][0], ins["Filter"][0]   # w: [C, 1, kh, kw]
    strides = tuple(int(s) for s in attrs.get("strides", [1, 1]))
    dils = tuple(int(d) for d in attrs.get("dilations", [1, 1]))
    pads = [int(p) for p in attrs.get("paddings", [0, 0])]
    if len(pads) == 2:
        pad_pairs = [(pads[0], pads[0]), (pads[1], pads[1])]
    else:  # [top, bottom, left, right]
        pad_pairs = [(pads[0], pads[1]), (pads[2], pads[3])]
    padding = [((w.shape[2 + i] - 1) * dils[i] - lo,
                (w.shape[2 + i] - 1) * dils[i] - hi)
               for i, (lo, hi) in enumerate(pad_pairs)]

    def one_channel(xc, wc):
        # xc [N,1,H,W], wc [1,1,kh,kw]
        dn = jax.lax.conv_dimension_numbers(xc.shape, wc.shape,
                                            ("NCHW", "OIHW", "NCHW"))
        return jax.lax.conv_transpose(
            xc, wc, strides=strides, padding=padding, rhs_dilation=dils,
            dimension_numbers=dn, transpose_kernel=True)[:, 0]

    # [C, N, 1, H, W] per-channel inputs; vmap emits one batched conv
    xc = jnp.moveaxis(x, 1, 0)[:, :, None]
    out = jax.vmap(one_channel)(xc, w[:, None])   # [C, N, H', W']
    return {"Output": jnp.moveaxis(out, 0, 1)}
