"""Tensor creation / manipulation ops.

Reference: paddle/fluid/operators/{fill_constant,uniform_random,
gaussian_random,cast,concat,split,stack,reshape,transpose,squeeze,unsqueeze,
expand,slice,gather,scatter,assign,shape,one_hot,lookup_table,...}_op.cc
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.ir import normalize_dtype
from ..core.registry import register_op


def _dt(attrs, key="dtype", default="float32"):
    return np.dtype(normalize_dtype(attrs.get(key, default)))


def _x(ins, slot="X"):
    return ins[slot][0]


# ---------------------------------------------------------------------------
# Creation
# ---------------------------------------------------------------------------


@register_op("fill_constant", grad=None)
def fill_constant(ins, attrs, ctx):
    shape = [int(s) for s in attrs.get("shape", [1])]
    val = attrs.get("value", 0.0)
    return {"Out": jnp.full(shape, val, dtype=_dt(attrs))}


@register_op("fill_constant_batch_size_like", grad=None, nondiff_inputs=("Input",))
def fill_constant_batch_size_like(ins, attrs, ctx):
    shape = batch_size_like_shape(ins, attrs)
    return {"Out": jnp.full(shape, attrs.get("value", 0.0), dtype=_dt(attrs))}


def batch_size_like_shape(ins, attrs):
    """Shared BatchSizeLikeOp shape rule: shape[output_dim_idx] =
    Input.shape[input_dim_idx]."""
    ref = ins["Input"][0]
    shape = [int(s) for s in attrs["shape"]]
    in_idx = int(attrs.get("input_dim_idx", 0))
    out_idx = int(attrs.get("output_dim_idx", 0))
    shape[out_idx] = ref.shape[in_idx]
    return shape


@register_op("fill_zeros_like", grad=None, nondiff_inputs=("X",))
def fill_zeros_like(ins, attrs, ctx):
    x = _x(ins)
    return {"Out": jnp.zeros_like(x)}


@register_op("uniform_random", grad=None, is_random=True)
def uniform_random(ins, attrs, ctx):
    shape = [int(s) for s in attrs["shape"]]
    lo, hi = attrs.get("min", -1.0), attrs.get("max", 1.0)
    out = jax.random.uniform(ctx.rng(), shape, dtype=jnp.float32, minval=lo, maxval=hi)
    return {"Out": out.astype(_dt(attrs))}


@register_op("gaussian_random", grad=None, is_random=True)
def gaussian_random(ins, attrs, ctx):
    shape = [int(s) for s in attrs["shape"]]
    mean, std = attrs.get("mean", 0.0), attrs.get("std", 1.0)
    out = mean + std * jax.random.normal(ctx.rng(), shape, dtype=jnp.float32)
    return {"Out": out.astype(_dt(attrs))}


@register_op("truncated_gaussian_random", grad=None, is_random=True)
def truncated_gaussian_random(ins, attrs, ctx):
    shape = [int(s) for s in attrs["shape"]]
    mean, std = attrs.get("mean", 0.0), attrs.get("std", 1.0)
    out = mean + std * jax.random.truncated_normal(ctx.rng(), -2.0, 2.0, shape, jnp.float32)
    return {"Out": out.astype(_dt(attrs))}


@register_op("randint", grad=None, is_random=True)
def randint(ins, attrs, ctx):
    shape = [int(s) for s in attrs["shape"]]
    out = jax.random.randint(ctx.rng(), shape, attrs.get("low", 0), attrs.get("high", 100))
    return {"Out": out.astype(_dt(attrs, default="int64"))}


@register_op("range", grad=None, nondiff_inputs=("Start", "End", "Step"))
def range_op(ins, attrs, ctx):
    start, end, step = ins["Start"][0], ins["End"][0], ins["Step"][0]
    # static shapes: bounds must be trace-time constants
    s, e, st = float(start), float(end), float(step)
    return {"Out": jnp.arange(s, e, st, dtype=start.dtype)}


@register_op("assign")
def assign(ins, attrs, ctx):
    return {"Out": _x(ins)}


@register_op("assign_value", grad=None)
def assign_value(ins, attrs, ctx):
    shape = [int(s) for s in attrs["shape"]]
    vals = attrs.get("fp32_values") or attrs.get("int32_values") or attrs.get("values")
    return {"Out": jnp.asarray(vals, dtype=_dt(attrs)).reshape(shape)}


@register_op("shape", grad=None, nondiff_inputs=("Input",))
def shape_op(ins, attrs, ctx):
    x = ins["Input"][0]
    return {"Out": jnp.asarray(x.shape, dtype=jnp.int32)}


# ---------------------------------------------------------------------------
# Casting / copy
# ---------------------------------------------------------------------------


@register_op("cast")
def cast(ins, attrs, ctx):
    return {"Out": _x(ins).astype(_dt(attrs, "out_dtype"))}


@register_op("increment", grad=None)
def increment(ins, attrs, ctx):
    x = _x(ins)
    return {"Out": x + jnp.asarray(attrs.get("step", 1.0), x.dtype)}


# ---------------------------------------------------------------------------
# Shape manipulation
# ---------------------------------------------------------------------------


@register_op("reshape2", intermediate_outputs=("XShape",))
def reshape2(ins, attrs, ctx):
    x = _x(ins)
    if ins.get("Shape") and ins["Shape"][0] is not None:
        shape = [int(s) for s in np.asarray(ins["Shape"][0])]
    else:
        shape = [int(s) for s in attrs["shape"]]
    # paddle semantics: 0 means copy the input dim at that position
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return {"Out": jnp.reshape(x, shape), "XShape": None}


@register_op("reshape")
def reshape(ins, attrs, ctx):
    return {"Out": reshape2(ins, attrs, ctx)["Out"]}


@register_op("transpose2", intermediate_outputs=("XShape",))
def transpose2(ins, attrs, ctx):
    x = _x(ins)
    return {"Out": jnp.transpose(x, attrs["axis"]), "XShape": None}


@register_op("transpose")
def transpose(ins, attrs, ctx):
    return {"Out": jnp.transpose(_x(ins), attrs["axis"])}


@register_op("squeeze2", intermediate_outputs=("XShape",))
def squeeze2(ins, attrs, ctx):
    x = _x(ins)
    axes = attrs.get("axes", [])
    if not axes:
        return {"Out": jnp.squeeze(x), "XShape": None}
    return {"Out": jnp.squeeze(x, axis=tuple(int(a) for a in axes)), "XShape": None}


@register_op("unsqueeze2", intermediate_outputs=("XShape",))
def unsqueeze2(ins, attrs, ctx):
    x = _x(ins)
    for a in sorted(int(a) for a in attrs["axes"]):
        x = jnp.expand_dims(x, a)
    return {"Out": x, "XShape": None}


@register_op("squeeze")
def squeeze(ins, attrs, ctx):
    return {"Out": squeeze2(ins, attrs, ctx)["Out"]}


@register_op("unsqueeze")
def unsqueeze(ins, attrs, ctx):
    return {"Out": unsqueeze2(ins, attrs, ctx)["Out"]}


@register_op("flatten2", intermediate_outputs=("XShape",))
def flatten2(ins, attrs, ctx):
    x = _x(ins)
    axis = int(attrs.get("axis", 1))
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    return {"Out": jnp.reshape(x, (lead, -1)), "XShape": None}


@register_op("flatten")
def flatten(ins, attrs, ctx):
    return {"Out": flatten2(ins, attrs, ctx)["Out"]}


@register_op("concat")
def concat(ins, attrs, ctx):
    xs = [x for x in ins["X"] if x is not None]
    return {"Out": jnp.concatenate(xs, axis=int(attrs.get("axis", 0)))}


@register_op("split")
def split(ins, attrs, ctx):
    x = _x(ins)
    axis = int(attrs.get("axis", 0))
    sections = attrs.get("sections") or []
    num = int(attrs.get("num", 0))
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register_op("stack")
def stack(ins, attrs, ctx):
    xs = [x for x in ins["X"] if x is not None]
    return {"Y": jnp.stack(xs, axis=int(attrs.get("axis", 0)))}


@register_op("unstack")
def unstack(ins, attrs, ctx):
    x = _x(ins)
    axis = int(attrs.get("axis", 0))
    n = x.shape[axis]
    return {"Y": [jnp.squeeze(s, axis) for s in jnp.split(x, n, axis=axis)]}


@register_op("expand")
def expand(ins, attrs, ctx):
    x = _x(ins)
    times = [int(t) for t in attrs["expand_times"]]
    return {"Out": jnp.tile(x, times)}


@register_op("expand_as")
def expand_as(ins, attrs, ctx):
    x, target = ins["X"][0], ins["target_tensor"][0]
    times = [t // s for t, s in zip(target.shape, x.shape)]
    return {"Out": jnp.tile(x, times)}


@register_op("tile")
def tile(ins, attrs, ctx):
    return {"Out": jnp.tile(_x(ins), [int(t) for t in attrs["repeat_times"]])}


@register_op("slice")
def slice_op(ins, attrs, ctx):
    x = ins["Input"][0]
    axes = [int(a) for a in attrs["axes"]]
    starts = [int(s) for s in attrs["starts"]]
    ends = [int(e) for e in attrs["ends"]]
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    out = x[tuple(idx)]
    if attrs.get("decrease_axis"):
        out = jnp.squeeze(out, axis=tuple(int(a) for a in attrs["decrease_axis"]))
    return {"Out": out}


@register_op("strided_slice")
def strided_slice(ins, attrs, ctx):
    x = ins["Input"][0]
    axes = [int(a) for a in attrs["axes"]]
    starts, ends = [int(s) for s in attrs["starts"]], [int(e) for e in attrs["ends"]]
    strides = [int(s) for s in attrs.get("strides", [1] * len(axes))]
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice(s, e, st)
    return {"Out": x[tuple(idx)]}


@register_op("reverse")
def reverse(ins, attrs, ctx):
    x = _x(ins)
    return {"Out": jnp.flip(x, axis=tuple(int(a) for a in attrs["axis"]))}


@register_op("pad")
def pad(ins, attrs, ctx):
    x = _x(ins)
    p = attrs["paddings"]
    pairs = [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, pairs, constant_values=attrs.get("pad_value", 0.0))}


@register_op("pad2d")
def pad2d(ins, attrs, ctx):
    x = _x(ins)  # NCHW
    t, b, l, r = [int(v) for v in attrs["paddings"]]
    mode = attrs.get("mode", "constant")
    pairs = [(0, 0), (0, 0), (t, b), (l, r)]
    if mode == "constant":
        return {"Out": jnp.pad(x, pairs, constant_values=attrs.get("pad_value", 0.0))}
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return {"Out": jnp.pad(x, pairs, mode=jmode)}


# ---------------------------------------------------------------------------
# Indexing
# ---------------------------------------------------------------------------


@register_op("gather", nondiff_inputs=("Index",))
def gather(ins, attrs, ctx):
    x, idx = ins["X"][0], ins["Index"][0]
    return {"Out": jnp.take(x, idx.astype(jnp.int32), axis=0)}


@register_op("gather_nd", nondiff_inputs=("Index",))
def gather_nd(ins, attrs, ctx):
    x, idx = ins["X"][0], ins["Index"][0]
    nd = idx.shape[-1]
    flat_idx = tuple(idx[..., i] for i in range(nd))
    return {"Out": x[flat_idx]}


@register_op("scatter", nondiff_inputs=("Ids",))
def scatter(ins, attrs, ctx):
    x, ids, updates = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    ids = ids.astype(jnp.int32).reshape(-1)
    if attrs.get("overwrite", True):
        return {"Out": x.at[ids].set(updates)}
    return {"Out": x.at[ids].add(updates)}


@register_op("scatter_nd_add", nondiff_inputs=("Index",))
def scatter_nd_add(ins, attrs, ctx):
    x, idx, upd = ins["X"][0], ins["Index"][0], ins["Updates"][0]
    nd = idx.shape[-1]
    return {"Out": x.at[tuple(idx[..., i] for i in range(nd))].add(upd)}


@register_op("index_select", nondiff_inputs=("Index",))
def index_select(ins, attrs, ctx):
    x, idx = ins["X"][0], ins["Index"][0]
    return {"Out": jnp.take(x, idx.astype(jnp.int32), axis=int(attrs.get("dim", 0)))}


@register_op("one_hot", grad=None, nondiff_inputs=("X",))
def one_hot(ins, attrs, ctx):
    x = _x(ins)
    depth = int(attrs["depth"])
    flat = x.reshape(x.shape[:-1]) if x.shape and x.shape[-1] == 1 else x
    return {"Out": jax.nn.one_hot(flat, depth, dtype=jnp.float32)}


def _lookup_table_grad(ins, attrs, ctx):
    """Custom grad for lookup_table(+_v2): with `is_sparse` the W grad is
    a true SelectedRows (reference: lookup_table_op.cc W@GRAD declared
    SELECTED_ROWS when is_sparse, selected_rows_functor.cc) — rows = the
    incoming output grads, ids = the looked-up indices, no dense [V,D]
    materialization. Dense mode keeps the scatter-add."""
    from ..core.registry import GRAD_PREFIX_IG, GRAD_PREFIX_IN, GRAD_PREFIX_OG
    from ..core.selected_rows import SelectedRows

    w = ins[GRAD_PREFIX_IN + "W"][0]
    ids = ins[GRAD_PREFIX_IN + "Ids"][0]
    og = ins[GRAD_PREFIX_OG + "Out"][0]
    padding_idx = int(attrs.get("padding_idx", -1))
    idx = ids.astype(jnp.int32)
    if ctx.op.type.startswith("lookup_table_grad") or \
            ctx.op.type == "lookup_table":
        # v1 squeezes a trailing [.,1] dim (mirror of the forward)
        if idx.ndim > 1 and idx.shape[-1] == 1:
            idx = idx[..., 0]
    flat_ids = idx.reshape(-1)
    rows = og.reshape(flat_ids.shape[0], -1).astype(w.dtype)
    if padding_idx != -1:
        rows = jnp.where((flat_ids == padding_idx)[:, None],
                         jnp.zeros((), rows.dtype), rows)
    if bool(attrs.get("is_sparse", False)):
        gw = SelectedRows(rows, flat_ids, w.shape[0])
    else:
        gw = jnp.zeros_like(w).at[flat_ids].add(rows)
    return {GRAD_PREFIX_IG + "W": [gw]}


@register_op("lookup_table", grad=_lookup_table_grad,
             nondiff_inputs=("Ids",))
def lookup_table(ins, attrs, ctx):
    """reference: operators/lookup_table_op.cc — Ids [...,1] int64, W [V,D]."""
    w, ids = ins["W"][0], ins["Ids"][0]
    padding_idx = int(attrs.get("padding_idx", -1))
    idx = ids.astype(jnp.int32)
    squeeze_last = idx.ndim > 1 and idx.shape[-1] == 1
    if squeeze_last:
        idx = idx[..., 0]
    out = jnp.take(w, idx, axis=0)
    if padding_idx != -1:
        mask = (idx == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros((), out.dtype), out)
    return {"Out": out}


@register_op("lookup_table_v2", grad=_lookup_table_grad,
             nondiff_inputs=("Ids",))
def lookup_table_v2(ins, attrs, ctx):
    w, ids = ins["W"][0], ins["Ids"][0]
    padding_idx = int(attrs.get("padding_idx", -1))
    idx = ids.astype(jnp.int32)
    out = jnp.take(w, idx, axis=0)
    if padding_idx != -1:
        mask = (idx == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros((), out.dtype), out)
    return {"Out": out}


@register_op("where", nondiff_inputs=("Condition",))
def where(ins, attrs, ctx):
    c, x, y = ins["Condition"][0], ins["X"][0], ins["Y"][0]
    return {"Out": jnp.where(c, x, y)}


@register_op("where_index", grad=None, nondiff_inputs=("Condition",))
def where_index(ins, attrs, ctx):
    # dynamic-shape op: only usable at trace boundaries / eager mode
    c = ins["Condition"][0]
    return {"Out": jnp.stack(jnp.nonzero(c), axis=1).astype(jnp.int64)}


# ---------------------------------------------------------------------------
# Sorting / search
# ---------------------------------------------------------------------------


@register_op("top_k", nondiff_inputs=(), intermediate_outputs=("Indices",))
def top_k(ins, attrs, ctx):
    x = _x(ins)
    k = int(attrs["k"]) if "k" in attrs else int(ins["K"][0])
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": vals, "Indices": idx.astype(jnp.int64)}


@register_op("top_k_v2", intermediate_outputs=("Indices",))
def top_k_v2(ins, attrs, ctx):
    x = _x(ins)
    k = int(attrs["k"])
    axis = int(attrs.get("axis", -1))
    if axis not in (-1, x.ndim - 1):
        x = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(x, k)
    if axis not in (-1, x.ndim - 1):
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    return {"Out": vals, "Indices": idx.astype(jnp.int64)}


@register_op("arg_max", grad=None, nondiff_inputs=("X",))
def arg_max(ins, attrs, ctx):
    x = _x(ins)
    return {"Out": jnp.argmax(x, axis=int(attrs.get("axis", -1))).astype(jnp.int64)}


@register_op("arg_min", grad=None, nondiff_inputs=("X",))
def arg_min(ins, attrs, ctx):
    x = _x(ins)
    return {"Out": jnp.argmin(x, axis=int(attrs.get("axis", -1))).astype(jnp.int64)}


@register_op("argsort", grad=None, nondiff_inputs=("X",))
def argsort(ins, attrs, ctx):
    x = _x(ins)
    axis = int(attrs.get("axis", -1))
    if attrs.get("descending", False):
        idx = jnp.argsort(-x, axis=axis)
    else:
        idx = jnp.argsort(x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": out, "Indices": idx.astype(jnp.int64)}


def _unique_static(x):
    """jit-safe unique in FIRST-OCCURRENCE order (the reference
    unique_op.h appends values on first sight). Static shapes: all
    outputs are length N; slots past the true unique count carry value 0
    and count 0 (count > 0 marks valid slots — every real unique value
    occurs at least once)."""
    n = x.shape[0]
    vals, inv, counts = jnp.unique(
        x, size=n, return_inverse=True, return_counts=True, fill_value=0)
    inv = inv.reshape(-1)
    # first original position of each sorted-unique slot; padded slots n
    first_occ = jnp.full((n,), n, jnp.int32).at[inv].min(
        jnp.arange(n, dtype=jnp.int32))
    order = jnp.argsort(first_occ)         # occurrence order, pads last
    out = vals[order]
    counts_o = counts[order]
    remap = jnp.argsort(order)
    index = remap[inv]
    return out, index, counts_o


@register_op("unique", grad=None, nondiff_inputs=("X",))
def unique(ins, attrs, ctx):
    """reference: unique_op.h — 1-D unique + per-element index into the
    unique list. Static-shape convention: see _unique_static."""
    x = _x(ins).reshape(-1)
    out, index, _ = _unique_static(x)
    return {"Out": out, "Index": index.astype(jnp.int64)}


@register_op("unique_with_counts", grad=None, nondiff_inputs=("X",))
def unique_with_counts(ins, attrs, ctx):
    """reference: unique_with_counts_op.cc — unique + Index + per-unique
    Count. Same static-shape convention as `unique` (Count==0 marks
    padding slots)."""
    x = _x(ins).reshape(-1)
    out, index, counts = _unique_static(x)
    return {"Out": out, "Index": index.astype(jnp.int64),
            "Count": counts.astype(jnp.int64)}


# ---------------------------------------------------------------------------
# Clipping / norms
# ---------------------------------------------------------------------------


@register_op("clip")
def clip(ins, attrs, ctx):
    """SelectedRows stay sparse: clip the row values elementwise
    (reference clip_op's SelectedRows kernel clips the merged value)."""
    from ..core.selected_rows import SelectedRows, is_selected_rows

    x = _x(ins)
    if is_selected_rows(x):
        ids, rows, is_first = x.merged()
        clipped = jnp.clip(rows, attrs.get("min"), attrs.get("max"))
        # merged() zeroes non-first duplicate slots but keeps their real
        # ids; with min>0 (or max<0) those zeros would clip to a nonzero
        # value and later scatter-add into untouched slots — re-zero them
        clipped = jnp.where(is_first[:, None], clipped,
                            0.0).astype(rows.dtype)
        return {"Out": SelectedRows(clipped, ids, x.height)}
    return {"Out": jnp.clip(x, attrs.get("min"), attrs.get("max"))}


@register_op("clip_by_norm")
def clip_by_norm(ins, attrs, ctx):
    """SelectedRows stay sparse: merge duplicate rows first (reference
    clip_by_norm_op.h merges via merge_add), then scale by the norm of
    the merged rows."""
    from ..core.selected_rows import SelectedRows, is_selected_rows

    x = _x(ins)
    max_norm = attrs["max_norm"]
    if is_selected_rows(x):
        ids, rows, _ = x.merged()
        norm = jnp.sqrt(jnp.sum(jnp.square(rows)))
        scale = jnp.where(norm > max_norm,
                          max_norm / jnp.maximum(norm, 1e-12), 1.0)
        return {"Out": SelectedRows(rows * scale.astype(rows.dtype),
                                    ids, x.height)}
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": x * scale.astype(x.dtype)}


@register_op("squared_l2_norm")
def squared_l2_norm(ins, attrs, ctx):
    """SelectedRows: norm of the MERGED rows (duplicates summed first,
    like the reference's merge_add before GlobalNorm accumulation)."""
    from ..core.selected_rows import is_selected_rows

    x = _x(ins)
    if is_selected_rows(x):
        _, rows, _ = x.merged()
        return {"Out": jnp.sum(jnp.square(rows)).reshape(1)}
    return {"Out": jnp.sum(jnp.square(x)).reshape(1)}


@register_op("norm", intermediate_outputs=("Norm",))
def norm(ins, attrs, ctx):
    x = _x(ins)
    axis = int(attrs.get("axis", -1))
    eps = attrs.get("epsilon", 1e-10)
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": x / n, "Norm": n}


@register_op("p_norm")
def p_norm(ins, attrs, ctx):
    x = _x(ins)
    p = attrs.get("porder", 2.0)
    axis = int(attrs.get("axis", -1))
    keep = attrs.get("keepdim", False)
    out = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keep) ** (1.0 / p)
    return {"Out": out}


# ---------------------------------------------------------------------------
# Misc
# ---------------------------------------------------------------------------


@register_op("dlpack/identity", grad=None)
def identity(ins, attrs, ctx):
    return {"Out": _x(ins)}


@register_op("print", grad=None)
def print_op(ins, attrs, ctx):
    x = _x(ins)
    jax.debug.print("{} {}", attrs.get("message", ""), x)
    return {"Out": x}


@register_op("is_empty", grad=None, nondiff_inputs=("X",))
def is_empty(ins, attrs, ctx):
    x = _x(ins)
    return {"Out": jnp.asarray(x.size == 0)}


@register_op("cumsum")
def cumsum(ins, attrs, ctx):
    x = _x(ins)
    axis = int(attrs.get("axis", -1))
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = out - x
    if attrs.get("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
        if attrs.get("exclusive", False):
            out = out - x
    return {"Out": out}


@register_op("linspace", grad=None, nondiff_inputs=("Start", "Stop", "Num"))
def linspace(ins, attrs, ctx):
    s, e, n = ins["Start"][0], ins["Stop"][0], ins["Num"][0]
    return {"Out": jnp.linspace(float(s), float(e), int(n), dtype=_dt(attrs))}


@register_op("eye", grad=None)
def eye(ins, attrs, ctx):
    n = int(attrs["num_rows"])
    m = int(attrs.get("num_columns", n))
    return {"Out": jnp.eye(n, m, dtype=_dt(attrs))}


@register_op("diag")
def diag(ins, attrs, ctx):
    """reference: operators/diag_op.cc — vector -> diagonal matrix."""
    return {"Out": jnp.diag(ins["Diagonal"][0])}


@register_op("size", grad=None, nondiff_inputs=("Input",))
def size_op(ins, attrs, ctx):
    """reference: size_op.cc — total element count of the runtime tensor."""
    return {"Out": jnp.asarray([ins["Input"][0].size], jnp.int64)}


@register_op("diag_part", nondiff_inputs=())
def diag_part(ins, attrs, ctx):
    """Diagonal of a square matrix (used by MultivariateNormalDiag)."""
    return {"Out": jnp.diagonal(_x(ins))}


@register_op("load", grad=None)
def load_op(ins, attrs, ctx):
    """reference: load_op.cc — load a persisted var from file at run
    time (the save_vars per-var .npy format). Host-side via
    pure_callback; the declared output var shape/dtype fixes the
    callback signature."""
    path = attrs["file_path"]
    out_names = ctx.op.outputs.get("Out", [])
    shape = dtype = None
    if ctx.program is not None and out_names:
        for b in ctx.program.blocks:
            if out_names[0] in b.vars:
                vd = b.vars[out_names[0]]
                shape = tuple(int(s) for s in vd.shape)
                dtype = np.dtype(normalize_dtype(vd.dtype))
                break
    if shape is None:
        raise RuntimeError(
            "load: output var shape unknown — declare the var with a "
            "concrete shape before layers.load")

    def host():
        arr = np.load(path if path.endswith(".npy") else path + ".npy")
        return np.asarray(arr, dtype).reshape(shape)

    return {"Out": jax.pure_callback(
        host, jax.ShapeDtypeStruct(shape, dtype))}


@register_op("fill", grad=None)
def fill_op(ins, attrs, ctx):
    """reference: fill_op.cc — explicit per-element values + shape."""
    shape = [int(s) for s in attrs["shape"]]
    vals = attrs.get("value", attrs.get("values"))
    return {"Out": jnp.asarray(vals, dtype=_dt(attrs)).reshape(shape)}


@register_op("fill_any_like", grad=None, nondiff_inputs=("X",))
def fill_any_like(ins, attrs, ctx):
    x = _x(ins)
    dt = _dt(attrs) if attrs.get("dtype") else x.dtype
    return {"Out": jnp.full(x.shape, attrs.get("value", 0.0), dt)}


@register_op("fill_zeros_like2", grad=None, nondiff_inputs=("X",))
def fill_zeros_like2(ins, attrs, ctx):
    x = _x(ins)
    dt = _dt(attrs) if attrs.get("dtype") else x.dtype
    return {"Out": jnp.zeros(x.shape, dt)}


@register_op("one_hot_v2", grad=None, nondiff_inputs=("X",))
def one_hot_v2(ins, attrs, ctx):
    """reference: one_hot_v2_op.cc — appends depth to the input shape
    AS-IS (unlike one_hot, which squeezes a trailing [.,1] dim)."""
    x = _x(ins)
    depth = int(attrs["depth"])
    return {"Out": jax.nn.one_hot(x, depth, dtype=jnp.float32)}


@register_op("shard_index", grad=None, nondiff_inputs=("X",))
def shard_index(ins, attrs, ctx):
    """reference: shard_index_op.cc — out = in//shard_size == shard_id ?
    in % shard_size : ignore_value (sharded classification heads).
    shard_size uses FLOOR division (shard_index_op.h:37 index_num/nshards),
    so when index_num % nshards != 0 the trailing ids map to shard
    `nshards` which no shard_id in [0, nshards) owns — the reference's
    quirk, kept. One deviation: the reference host kernel ENFORCEs
    0 <= id < index_num per element (shard_index_op.h:44); a
    data-dependent check cannot raise under jit, so out-of-range ids
    here land outside every shard and yield ignore_value silently."""
    x = _x(ins)
    index_num = int(attrs["index_num"])
    nshards = int(attrs["nshards"])
    shard_id = int(attrs["shard_id"])
    ignore = int(attrs.get("ignore_value", -1))
    shard_size = index_num // nshards
    assert shard_size > 0, (
        f"shard_index: index_num ({index_num}) // nshards ({nshards}) "
        f"== 0; nshards must not exceed index_num")
    in_shard = (x // shard_size) == shard_id
    return {"Out": jnp.where(in_shard, x % shard_size, ignore)}


def _resolve_save_path(path):
    import os

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    return path


@register_op("save", grad=None, nondiff_inputs=("X",))
def save_op(ins, attrs, ctx):
    """reference: save_op.cc — persist a var at run time (the save_vars
    per-var .npy format io.py reads back)."""
    from jax.experimental import io_callback

    x = _x(ins)
    path = attrs["file_path"]

    def host(v):
        from ..resilience import atomic as _atomic

        _atomic.np_save(_resolve_save_path(path), np.asarray(v))

    io_callback(host, None, x, ordered=True)
    return {}


@register_op("save_combine", grad=None, nondiff_inputs=("X",))
def save_combine(ins, attrs, ctx):
    """reference: save_combine_op.cc — many vars into one file (.npz,
    matching io.py's save_vars(filename=...) format)."""
    from jax.experimental import io_callback

    pairs = [(n, x) for n, x in zip(ctx.op.inputs.get("X", []),
                                    ins["X"]) if n and x is not None]
    names = [n for n, _ in pairs]
    xs = [x for _, x in pairs]
    path = attrs["file_path"]

    def host(*arrays):
        from ..resilience import atomic as _atomic

        _atomic.np_savez(_resolve_save_path(path),
                         **{n: np.asarray(a) for n, a in zip(names, arrays)})

    io_callback(host, None, *xs, ordered=True)
    return {}


@register_op("load_combine", grad=None)
def load_combine(ins, attrs, ctx):
    """reference: load_combine_op.cc — restore many declared vars from a
    save_combine .npz."""
    path = attrs["file_path"]
    out_names = ctx.op.outputs.get("Out", [])
    shapes = []
    from ..core.ir import normalize_dtype as _nd

    for n in out_names:
        vd = None
        if ctx.program is not None:
            for b in ctx.program.blocks:
                if n in b.vars:
                    vd = b.vars[n]
                    break
        if vd is None:
            raise RuntimeError(f"load_combine: unknown out var {n}")
        shapes.append(jax.ShapeDtypeStruct(
            tuple(int(s) for s in vd.shape), np.dtype(_nd(vd.dtype))))

    def host():
        f = path if path.endswith(".npz") else path + ".npz"
        data = np.load(f)
        return tuple(np.asarray(data[n], s.dtype).reshape(s.shape)
                     for n, s in zip(out_names, shapes))

    outs = jax.pure_callback(host, tuple(shapes))
    return {"Out": list(outs)}
