"""Collective ops.

Reference: paddle/fluid/operators/collective/ — c_allreduce_{sum,max,min,prod},
c_broadcast, c_allgather, c_reducescatter, plus the comm-bootstrap ops
(c_comm_init, c_gen_nccl_id) and stream-sync ops.

TPU-native: these lower to `jax.lax` collectives over a named mesh axis
(SURVEY §5: ring_id → mesh axis). They are only meaningful when the program
is lowered inside shard_map (paddle_tpu.parallel); under plain jit GSPMD
inserts collectives automatically and explicit ones are unnecessary. The
bootstrap/stream ops are no-ops: `jax.distributed.initialize` replaces
gen_nccl_id (no NCCL rings to build), and XLA owns stream ordering.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _same_shape_infer(op, input_descs):
    """Collectives keep (or statically transform) shapes; eval_shape-based
    inference would trace psum outside shard_map and fail on the unbound
    axis, so shape inference is static here."""
    import jax
    import numpy as np

    from ..core.ir import normalize_dtype

    out = {}
    in_names = op.inputs.get("X", [])
    out_names = op.outputs.get("Out", [])
    for i, n in enumerate(out_names):
        if not n:
            continue
        src = input_descs[in_names[min(i, len(in_names) - 1)]]
        shape = list(src.shape or ())
        nranks = int(op.attrs.get("nranks", 0))
        if op.type == "c_allgather" and nranks and shape:
            shape[0] = shape[0] * nranks if shape[0] != -1 else -1
        elif op.type == "c_reducescatter" and nranks and shape:
            shape[0] = shape[0] // nranks if shape[0] != -1 else -1
        out[n] = jax.ShapeDtypeStruct(
            tuple(shape), np.dtype(normalize_dtype(src.dtype)))
    return out


def _axis(attrs):
    # ring_id selected a NCCLCommContext in the reference; here it names a
    # mesh axis (default the data axis).
    return attrs.get("axis_name", "data")


def _allreduce(op):
    def kernel(ins, attrs, ctx):
        x = ins["X"][0]
        return {"Out": op(x, _axis(attrs))}

    return kernel


register_op("c_allreduce_sum", infer_shape=_same_shape_infer)(_allreduce(lambda x, a: jax.lax.psum(x, a)))
register_op("c_allreduce_max", grad=None, infer_shape=_same_shape_infer)(_allreduce(lambda x, a: jax.lax.pmax(x, a)))
register_op("c_allreduce_min", grad=None, infer_shape=_same_shape_infer)(_allreduce(lambda x, a: jax.lax.pmin(x, a)))
register_op("c_allreduce_prod", grad=None, infer_shape=_same_shape_infer)(
    _allreduce(lambda x, a: jnp.exp(jax.lax.psum(jnp.log(x), a))))


@register_op("c_broadcast", infer_shape=_same_shape_infer)
def c_broadcast(ins, attrs, ctx):
    x = ins["X"][0]
    root = int(attrs.get("root", 0))
    axis = _axis(attrs)
    idx = jax.lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return {"Out": jax.lax.psum(masked, axis)}


@register_op("c_allgather", infer_shape=_same_shape_infer)
def c_allgather(ins, attrs, ctx):
    x = ins["X"][0]
    return {"Out": jax.lax.all_gather(x, _axis(attrs), tiled=True)}


@register_op("c_reducescatter", infer_shape=_same_shape_infer)
def c_reducescatter(ins, attrs, ctx):
    x = ins["X"][0]
    return {"Out": jax.lax.psum_scatter(x, _axis(attrs), tiled=True)}


@register_op("c_ppermute", infer_shape=_same_shape_infer)
def c_ppermute(ins, attrs, ctx):
    """Ring permute — the building block of ring attention / pipeline comm
    (no reference counterpart; exposed because ICI rings make it cheap)."""
    x = ins["X"][0]
    axis = _axis(attrs)
    shift = int(attrs.get("shift", 1))
    n = jax.lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return {"Out": jax.lax.ppermute(x, axis, perm)}


def _noop(ins, attrs, ctx):
    xs = ins.get("X", [])
    return {"Out": list(xs)} if xs else {}


# Bootstrap / stream ops: no-ops on TPU (see module docstring).
register_op("c_comm_init", grad=None)(_noop)
register_op("c_comm_init_all", grad=None)(_noop)
register_op("c_gen_nccl_id", grad=None)(_noop)
register_op("c_sync_calc_stream", grad=None)(_noop)
register_op("c_sync_comm_stream", grad=None)(_noop)
register_op("c_wait_compute", grad=None)(_noop)
register_op("c_wait_comm", grad=None)(_noop)


@register_op("c_embedding", nondiff_inputs=("Ids",))
def c_embedding(ins, attrs, ctx):
    """Sharded embedding lookup (vocab-parallel): each shard holds rows
    [start, start+per_part); out-of-range ids contribute zeros, combined by
    psum (reference: collective/c_embedding_op.cc pattern)."""
    w, ids = ins["W"][0], ins["Ids"][0]
    start = int(attrs.get("start_index", 0))
    idx = ids.astype(jnp.int32) - start
    valid = (idx >= 0) & (idx < w.shape[0])
    safe = jnp.clip(idx, 0, w.shape[0] - 1)
    out = jnp.take(w, safe, axis=0)
    return {"Out": jnp.where(valid[..., None], out, 0.0)}


def sparse_allreduce(flat, k: int, axis: str):
    """Top-k (value,index) allgather + local decode — the reference's
    sparseAllGReduce (details/sparse_all_reduce_op_handle.cc). Values and
    bitcast int32 indices pack into ONE [2,k] buffer so a single collective
    runs per tensor; 2k elements on the wire instead of the dense size."""
    k = min(int(k), flat.size)  # tiny tensors (biases) carry fewer entries
    _, idx = jax.lax.top_k(jnp.abs(flat), k)
    # pack in f32: a narrower dtype would corrupt the bitcast index bits
    vals = flat[idx].astype(jnp.float32)
    idx_bits = jax.lax.bitcast_convert_type(idx.astype(jnp.int32),
                                            jnp.float32)
    packed = jnp.stack([vals, idx_bits])                  # [2, k]
    gathered = jax.lax.all_gather(packed, axis)           # [nranks, 2, k]
    all_vals = gathered[:, 0].reshape(-1).astype(flat.dtype)
    all_idx = jax.lax.bitcast_convert_type(
        gathered[:, 1], jnp.int32).reshape(-1)
    return jnp.zeros_like(flat).at[all_idx].add(all_vals)


@register_op("c_dgc_allreduce", grad=None, infer_shape=_same_shape_infer)
def c_dgc_allreduce(ins, attrs, ctx):
    """Standalone DGC sparse-allreduce collective over a sparsified tensor
    (see sparse_allreduce); grad=None like the other nonlinear collectives."""
    x = ins["X"][0]
    flat = x.reshape(-1)
    k = int(attrs.get("k", max(1, flat.size // 1000)))
    return {"Out": sparse_allreduce(flat, k, _axis(attrs)).reshape(x.shape)}
