"""Activation ops (reference: paddle/fluid/operators/activation_op.cc —
~24 activations registered from one macro table; same idea here)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _unary(fn):
    def kernel(ins, attrs, ctx):
        return {"Out": fn(ins["X"][0], attrs)}

    return kernel


_SIMPLE = {
    "relu": lambda x, a: jax.nn.relu(x),
    "sigmoid": lambda x, a: jax.nn.sigmoid(x),
    "logsigmoid": lambda x, a: jax.nn.log_sigmoid(x),
    "tanh": lambda x, a: jnp.tanh(x),
    "tanh_shrink": lambda x, a: x - jnp.tanh(x),
    "exp": lambda x, a: jnp.exp(x),
    "log": lambda x, a: jnp.log(x),
    "log1p": lambda x, a: jnp.log1p(x),
    "log2": lambda x, a: jnp.log2(x),
    "log10": lambda x, a: jnp.log10(x),
    "abs": lambda x, a: jnp.abs(x),
    "square": lambda x, a: jnp.square(x),
    "sqrt": lambda x, a: jnp.sqrt(x),
    "rsqrt": lambda x, a: jax.lax.rsqrt(x),
    "reciprocal": lambda x, a: 1.0 / x,
    "softsign": lambda x, a: jax.nn.soft_sign(x),
    "sin": lambda x, a: jnp.sin(x),
    "cos": lambda x, a: jnp.cos(x),
    "tan": lambda x, a: jnp.tan(x),
    "asin": lambda x, a: jnp.arcsin(x),
    "acos": lambda x, a: jnp.arccos(x),
    "atan": lambda x, a: jnp.arctan(x),
    "sinh": lambda x, a: jnp.sinh(x),
    "cosh": lambda x, a: jnp.cosh(x),
    "erf": lambda x, a: jax.scipy.special.erf(x),
    "floor": lambda x, a: jnp.floor(x),
    "ceil": lambda x, a: jnp.ceil(x),
    "round": lambda x, a: jnp.round(x),
    "sign": lambda x, a: jnp.sign(x),
    "silu": lambda x, a: jax.nn.silu(x),
    "mish": lambda x, a: x * jnp.tanh(jax.nn.softplus(x)),
}

for _name, _fn in _SIMPLE.items():
    grad = None if _name in ("floor", "ceil", "round", "sign") else "generic"
    register_op(_name, grad=grad)(_unary(_fn))


@register_op("gelu")
def gelu(ins, attrs, ctx):
    x = ins["X"][0]
    return {"Out": jax.nn.gelu(x, approximate=bool(attrs.get("approximate", False)))}


@register_op("leaky_relu")
def leaky_relu(ins, attrs, ctx):
    x = ins["X"][0]
    alpha = attrs.get("alpha", 0.02)
    return {"Out": jnp.where(x >= 0, x, alpha * x)}


@register_op("elu")
def elu(ins, attrs, ctx):
    x = ins["X"][0]
    return {"Out": jax.nn.elu(x, alpha=attrs.get("alpha", 1.0))}


@register_op("selu")
def selu(ins, attrs, ctx):
    return {"Out": jax.nn.selu(ins["X"][0])}


@register_op("relu6")
def relu6(ins, attrs, ctx):
    x = ins["X"][0]
    return {"Out": jnp.clip(x, 0.0, attrs.get("threshold", 6.0))}


@register_op("brelu")
def brelu(ins, attrs, ctx):
    x = ins["X"][0]
    return {"Out": jnp.clip(x, attrs.get("t_min", 0.0), attrs.get("t_max", 24.0))}


@register_op("softplus")
def softplus(ins, attrs, ctx):
    return {"Out": jax.nn.softplus(ins["X"][0])}


@register_op("softshrink")
def softshrink(ins, attrs, ctx):
    x = ins["X"][0]
    lam = attrs.get("lambda", 0.5)
    return {"Out": jnp.where(x > lam, x - lam, jnp.where(x < -lam, x + lam, 0.0))}


@register_op("hard_shrink")
def hard_shrink(ins, attrs, ctx):
    x = ins["X"][0]
    t = attrs.get("threshold", 0.5)
    return {"Out": jnp.where(jnp.abs(x) > t, x, 0.0)}


@register_op("thresholded_relu")
def thresholded_relu(ins, attrs, ctx):
    x = ins["X"][0]
    t = attrs.get("threshold", 1.0)
    return {"Out": jnp.where(x > t, x, 0.0)}


@register_op("hard_sigmoid")
def hard_sigmoid(ins, attrs, ctx):
    x = ins["X"][0]
    slope = attrs.get("slope", 0.2)
    offset = attrs.get("offset", 0.5)
    return {"Out": jnp.clip(slope * x + offset, 0.0, 1.0)}


@register_op("hard_swish")
def hard_swish(ins, attrs, ctx):
    x = ins["X"][0]
    t = attrs.get("threshold", 6.0)
    s = attrs.get("scale", 6.0)
    o = attrs.get("offset", 3.0)
    return {"Out": x * jnp.clip(x + o, 0.0, t) / s}


@register_op("swish")
def swish(ins, attrs, ctx):
    x = ins["X"][0]
    beta = attrs.get("beta", 1.0)
    return {"Out": x * jax.nn.sigmoid(beta * x)}


@register_op("stanh")
def stanh(ins, attrs, ctx):
    x = ins["X"][0]
    a = attrs.get("scale_a", 0.67)
    b = attrs.get("scale_b", 1.7159)
    return {"Out": b * jnp.tanh(a * x)}


@register_op("prelu")
def prelu(ins, attrs, ctx):
    x, alpha = ins["X"][0], ins["Alpha"][0]
    mode = attrs.get("mode", "all")
    if mode == "channel" and alpha.ndim == 1:
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    return {"Out": jnp.where(x >= 0, x, alpha * x)}


@register_op("pow")
def pow_op(ins, attrs, ctx):
    x = ins["X"][0]
    f = attrs.get("factor", 1.0)
    if ins.get("FactorTensor") and ins["FactorTensor"][0] is not None:
        f = ins["FactorTensor"][0]
    return {"Out": jnp.power(x, f)}


@register_op("maxout")
def maxout(ins, attrs, ctx):
    x = ins["X"][0]  # NCHW
    groups = int(attrs["groups"])
    n, c, h, w = x.shape
    return {"Out": jnp.max(x.reshape(n, c // groups, groups, h, w), axis=2)}


@register_op("soft_relu")
def soft_relu(ins, attrs, ctx):
    """reference: activation_op.cc SoftRelu — ln(1+exp(clip(x, ±t)))."""
    t = attrs.get("threshold", 40.0)
    return {"Out": jnp.log1p(jnp.exp(jnp.clip(ins["X"][0], -t, t)))}
