"""Operator library: every kernel is a JAX lowering (reference:
paddle/fluid/operators/ — ~510 C++/CUDA ops, SURVEY.md §2.3).

Importing this package registers all ops. Grad ops are generated generically
via jax.vjp (core/registry.py) unless an op overrides.
"""

from . import tensor
from . import math
from . import activation
from . import reduce
from . import compare
from . import nn
from . import optimizer_ops
from . import control_flow
from . import metrics_ops
from . import sequence
from . import rnn
from . import distributed
from . import detection
from . import collective
from . import crf
from . import classify
from . import beam
from . import misc
from . import quant
from . import text_match
