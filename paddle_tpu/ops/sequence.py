"""Sequence ops — mask/segment based.

Reference: operators/sequence_ops/ (26 LoD-based ops, SURVEY.md §2.3). The
reference's variable-length story is LoD offset tables (lod_tensor.h:215);
XLA wants static shapes, so the TPU-native encoding is *padded batches +
lengths/masks* (SURVEY §5 "Long-context"): a [N, T, ...] tensor plus a
[N] lengths vector replaces a LoDTensor. Each op takes Length input instead
of reading LoD metadata.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _mask(lengths, maxlen, dtype=jnp.float32):
    return (jnp.arange(maxlen)[None, :] < lengths.reshape(-1, 1)).astype(dtype)


def _lengths(ins, n, t, slot="Length"):
    """Row lengths from the optional Length input, defaulting to full T."""
    if ins.get(slot) and ins[slot][0] is not None:
        return ins[slot][0].reshape(-1).astype(jnp.int32)
    return jnp.full((n,), t, jnp.int32)


def _compact_left(x, keep, fill=0):
    """Stable-compact kept positions to the left along axis 1; freed tail
    positions hold `fill`. Returns (compacted, new_lengths)."""
    order = jnp.argsort(jnp.where(keep, 0, 1), axis=1, stable=True)
    compacted = jnp.take_along_axis(x, order, axis=1)
    new_len = jnp.sum(keep, axis=1)
    t = x.shape[1]
    out = jnp.where(jnp.arange(t)[None, :] < new_len[:, None], compacted,
                    fill)
    return out, new_len


@register_op("sequence_mask", grad=None, nondiff_inputs=("X",))
def sequence_mask(ins, attrs, ctx):
    """reference: sequence_ops/sequence_mask_op.cc."""
    x = ins["X"][0]
    maxlen = int(attrs.get("maxlen", -1))
    if maxlen < 0:
        maxlen = int(jnp.max(x))  # requires static value; prefer explicit maxlen
    from ..core.ir import normalize_dtype
    import numpy as np

    dt = np.dtype(normalize_dtype(attrs.get("out_dtype", "int64")))
    return {"Y": _mask(x, maxlen, dt)}


@register_op("sequence_pool", nondiff_inputs=("Length",))
def sequence_pool(ins, attrs, ctx):
    """Masked pooling over the time axis of a padded [N,T,D] batch
    (reference: sequence_ops/sequence_pool_op.cc over LoD)."""
    x = ins["X"][0]
    ptype = attrs.get("pooltype", "SUM").upper()
    if ins.get("Length") and ins["Length"][0] is not None:
        m = _mask(ins["Length"][0], x.shape[1], x.dtype)[..., None]
    else:
        m = jnp.ones(x.shape[:2], x.dtype)[..., None]
    if ptype == "SUM":
        out = jnp.sum(x * m, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)
    elif ptype == "SQRT":
        out = jnp.sum(x * m, axis=1) / jnp.sqrt(jnp.maximum(jnp.sum(m, axis=1), 1.0))
    elif ptype == "MAX":
        neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
        out = jnp.max(jnp.where(m > 0, x, neg), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(jnp.sum(m[:, :, 0], axis=1).astype(jnp.int32) - 1, 0)
        out = x[jnp.arange(x.shape[0]), idx]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"unsupported pooltype {ptype}")
    return {"Out": out, "MaxIndex": None}


@register_op("sequence_softmax", nondiff_inputs=("Length",))
def sequence_softmax(ins, attrs, ctx):
    x = ins["X"][0]  # [N, T]
    if ins.get("Length") and ins["Length"][0] is not None:
        m = _mask(ins["Length"][0], x.shape[-1], x.dtype)
        x = jnp.where(m > 0, x, jnp.asarray(-1e9, x.dtype))
    return {"Out": jax.nn.softmax(x, axis=-1)}


@register_op("sequence_reverse", nondiff_inputs=("Length",))
def sequence_reverse(ins, attrs, ctx):
    x = ins["X"][0]  # [N, T, ...]
    if ins.get("Length") and ins["Length"][0] is not None:
        lengths = ins["Length"][0]
        t = x.shape[1]
        idx = jnp.arange(t)[None, :]
        rev = lengths.reshape(-1, 1) - 1 - idx
        gather_idx = jnp.where(idx < lengths.reshape(-1, 1), rev, idx)
        return {"Y": jnp.take_along_axis(
            x, gather_idx.reshape(gather_idx.shape + (1,) * (x.ndim - 2)).astype(jnp.int32),
            axis=1)}
    return {"Y": jnp.flip(x, axis=1)}


@register_op("sequence_expand", nondiff_inputs=("Y",))
def sequence_expand(ins, attrs, ctx):
    # padded-batch equivalent: broadcast x rows along a repeat count — with
    # static shapes this is tile along axis 1
    x = ins["X"][0]
    y = ins["Y"][0]
    return {"Out": jnp.repeat(x, y.shape[1] // max(x.shape[1], 1), axis=1)
            if x.ndim > 1 else x}


@register_op("sequence_concat")
def sequence_concat(ins, attrs, ctx):
    xs = [x for x in ins["X"] if x is not None]
    return {"Out": jnp.concatenate(xs, axis=1)}


@register_op("sequence_slice")
def sequence_slice(ins, attrs, ctx):
    """Offset may be a traced tensor (lax.dynamic_slice); length must be
    static (attr) — XLA output shapes are static."""
    x = ins["X"][0]
    length = int(attrs["length"])
    off = ins["Offset"][0] if ins.get("Offset") else None
    if off is None:
        o = int(attrs.get("offset", 0))
        return {"Out": jax.lax.slice_in_dim(x, o, o + length, axis=1)}
    o = off.reshape(-1)[0].astype(jnp.int32)
    return {"Out": jax.lax.dynamic_slice_in_dim(x, o, length, axis=1)}


@register_op("im2sequence")
def im2sequence(ins, attrs, ctx):
    """reference: im2sequence_op.cc — sliding-window patches to sequence
    (OCR models). [N,C,H,W] -> [N, H'*W', C*kh*kw]."""
    x = ins["X"][0]
    kh, kw = [int(k) for k in attrs["kernels"]]
    sh, sw = [int(s) for s in attrs.get("strides", [1, 1])]
    pads = [int(p) for p in attrs.get("paddings", [0, 0, 0, 0])]
    x = jnp.pad(x, [(0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3])])
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: [N, C*kh*kw, oh, ow] -> [N, oh*ow, C*kh*kw]
    return {"Out": patches.reshape(n, c * kh * kw, oh * ow).transpose(0, 2, 1)}


@register_op("sequence_pad", nondiff_inputs=("PadValue", "Length"))
def sequence_pad(ins, attrs, ctx):
    """reference: sequence_ops/sequence_pad_op.cc — LoD → padded batch.
    Here the batch is already [N, T, ...]: re-pad to padded_length with
    PadValue beyond each row's Length (truncating or extending T)."""
    x = ins["X"][0]
    if ins.get("PadValue") and ins["PadValue"][0] is not None:
        pv = ins["PadValue"][0]
        # scalar, or shaped like one time step (sequence_pad_op.cc)
        pad_value = pv.reshape(()) if pv.size == 1 else \
            pv.reshape(x.shape[2:])
    else:
        pad_value = jnp.asarray(0.0, x.dtype)
    n, t = x.shape[0], x.shape[1]
    plen = int(attrs.get("padded_length", -1))
    if plen < 0:
        plen = t
    if plen > t:
        pad_width = [(0, 0), (0, plen - t)] + [(0, 0)] * (x.ndim - 2)
        x = jnp.pad(x, pad_width, constant_values=0)
    elif plen < t:
        x = x[:, :plen]
    lengths = jnp.minimum(_lengths(ins, n, min(t, plen)), plen)
    m = _mask(lengths, plen, jnp.bool_)
    m = m.reshape(m.shape + (1,) * (x.ndim - 2))
    out = jnp.where(m, x, pad_value.astype(x.dtype))
    return {"Out": out, "Length": lengths.astype(jnp.int64)}


@register_op("sequence_unpad", nondiff_inputs=("Length",))
def sequence_unpad(ins, attrs, ctx):
    """reference: sequence_ops/sequence_unpad_op.cc — strips padding back
    to LoD; statically: zero positions past Length (consumers read Length)."""
    x = ins["X"][0]
    lengths = ins["Length"][0].reshape(-1)
    m = _mask(lengths, x.shape[1], jnp.bool_)
    m = m.reshape(m.shape + (1,) * (x.ndim - 2))
    return {"Out": jnp.where(m, x, jnp.asarray(0, x.dtype)),
            "Length": lengths.astype(jnp.int64)}


@register_op("sequence_conv", nondiff_inputs=("Length",))
def sequence_conv(ins, attrs, ctx):
    """reference: sequence_ops/sequence_conv_op.cc — 1-D convolution over
    time with a [context_length * D, out] filter; frames outside
    [0, length) contribute zeros (the reference's context padding)."""
    x = ins["X"][0]                        # [N, T, D]
    filt = ins["Filter"][0]                # [ctx_len * D, out]
    ctx_len = int(attrs.get("contextLength", 3))
    ctx_start = int(attrs.get("contextStart", -(ctx_len - 1) // 2))
    n, t, d = x.shape
    if ins.get("Length") and ins["Length"][0] is not None:
        x = x * _mask(_lengths(ins, n, t), t, x.dtype)[..., None]
    cols = []
    for k in range(ctx_len):
        off = ctx_start + k
        shifted = jnp.roll(x, -off, axis=1)
        pos = jnp.arange(t) + off
        ok = ((pos >= 0) & (pos < t))[None, :, None]
        cols.append(jnp.where(ok, shifted, 0.0))
    im2col = jnp.concatenate(cols, axis=-1)        # [N, T, ctx_len*D]
    return {"Out": jnp.einsum("ntc,co->nto", im2col, filt)}


@register_op("sequence_enumerate", grad=None, nondiff_inputs=("X", "Length"))
def sequence_enumerate(ins, attrs, ctx):
    """reference: sequence_ops/sequence_enumerate_op.cc — sliding win_size
    windows of ids; positions past the row end hold pad_value."""
    x = ins["X"][0]                        # [N, T] int
    win = int(attrs["win_size"])
    pad = int(attrs.get("pad_value", 0))
    n, t = x.shape[0], x.shape[1]
    lengths = _lengths(ins, n, t)
    pos = jnp.arange(t)[None, :, None] + jnp.arange(win)[None, None, :]
    idx = jnp.minimum(pos, t - 1)
    gathered = jnp.take_along_axis(
        jnp.broadcast_to(x[:, :, None], (n, t, win)),
        jnp.broadcast_to(idx, (n, t, win)), axis=1)
    ok = pos < lengths[:, None, None]
    return {"Out": jnp.where(ok, gathered, pad).astype(x.dtype)}


@register_op("sequence_erase", grad=None, nondiff_inputs=("X", "Length"))
def sequence_erase(ins, attrs, ctx):
    """reference: sequence_ops/sequence_erase_op.cc — drop listed tokens
    and compact left; freed tail positions hold 0 and Out_length shrinks
    (a stable sort on the erase flag replaces the reference's compaction)."""
    x = ins["X"][0]                        # [N, T] int
    tokens = [int(v) for v in attrs.get("tokens", [])]
    n, t = x.shape
    lengths = _lengths(ins, n, t)
    valid = _mask(lengths, t, jnp.bool_)
    erase = jnp.zeros_like(valid)
    for tok in tokens:
        erase |= x == tok
    out, new_len = _compact_left(x, valid & ~erase)
    return {"Out": out.astype(x.dtype), "Length": new_len.astype(jnp.int64)}


@register_op("sequence_expand_as", nondiff_inputs=("Y",))
def sequence_expand_as(ins, attrs, ctx):
    """reference: sequence_ops/sequence_expand_as_op.cc — broadcast each
    row of X along Y's time axis ([N, D] → [N, T, D])."""
    x = ins["X"][0]
    y = ins["Y"][0]
    t = y.shape[1]
    if x.ndim == 2:
        return {"Out": jnp.broadcast_to(x[:, None, :],
                                        (x.shape[0], t, x.shape[1]))}
    return {"Out": jnp.broadcast_to(x, (x.shape[0], t) + x.shape[2:])}


@register_op("sequence_reshape")
def sequence_reshape(ins, attrs, ctx):
    """reference: sequence_ops/sequence_reshape_op.cc — trade time steps
    for feature width: [N, T, D] → [N, T*D/new_dim, new_dim]."""
    x = ins["X"][0]
    new_dim = int(attrs["new_dim"])
    n, t, d = x.shape
    return {"Out": x.reshape(n, t * d // new_dim, new_dim)}


@register_op("sequence_scatter", nondiff_inputs=("Ids", "Length"))
def sequence_scatter(ins, attrs, ctx):
    """reference: sequence_ops/sequence_scatter_op.cc — per row i, add
    Updates[i, j] into X[i, Ids[i, j]] for j < Length[i]."""
    x = ins["X"][0]                        # [N, D]
    ids = ins["Ids"][0].astype(jnp.int32)  # [N, T]
    upd = ins["Updates"][0]                # [N, T]
    if ins.get("Length") and ins["Length"][0] is not None:
        m = _mask(ins["Length"][0].reshape(-1), ids.shape[1], upd.dtype)
        upd = upd * m
    def one(row, i_row, u_row):
        return row.at[i_row].add(u_row)
    return {"Out": jax.vmap(one)(x, ids, upd)}


@register_op("sequence_topk_avg_pooling",
             nondiff_inputs=("ROW", "COLUMN"))
def sequence_topk_avg_pooling(ins, attrs, ctx):
    """reference: sequence_ops/sequence_topk_avg_pooling_op.cc — for each
    (row position, channel), average the top-k values across the column
    axis, for every k in `topks`. Static layout: X [N, C, H, W] (+optional
    ROW/COLUMN lengths) → Out [N, H, C * len(topks)]."""
    x = ins["X"][0]
    topks = [int(k) for k in attrs["topks"]]
    n, c, h, w = x.shape
    if ins.get("COLUMN") and ins["COLUMN"][0] is not None:
        col_len = ins["COLUMN"][0].reshape(-1)
        cm = _mask(col_len, w, x.dtype)            # [N, W]
        x = jnp.where(cm[:, None, None, :] > 0, x, -jnp.inf)
    kmax = min(max(topks), w)
    top = jax.lax.top_k(x, kmax)[0]                # [N, C, H, kmax]
    top = jnp.where(jnp.isfinite(top), top, 0.0)
    outs = []
    for k in topks:
        k_eff = min(k, kmax)
        outs.append(jnp.sum(top[..., :k_eff], axis=-1) / float(k))
    out = jnp.stack(outs, axis=-1)                 # [N, C, H, K]
    out = out.transpose(0, 2, 1, 3).reshape(n, h, c * len(topks))
    if ins.get("ROW") and ins["ROW"][0] is not None:
        rm = _mask(ins["ROW"][0].reshape(-1), h, out.dtype)
        out = out * rm[:, :, None]
    return {"Out": out, "pos": None}
