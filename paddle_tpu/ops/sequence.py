"""Sequence ops — mask/segment based.

Reference: operators/sequence_ops/ (26 LoD-based ops, SURVEY.md §2.3). The
reference's variable-length story is LoD offset tables (lod_tensor.h:215);
XLA wants static shapes, so the TPU-native encoding is *padded batches +
lengths/masks* (SURVEY §5 "Long-context"): a [N, T, ...] tensor plus a
[N] lengths vector replaces a LoDTensor. Each op takes Length input instead
of reading LoD metadata.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _mask(lengths, maxlen, dtype=jnp.float32):
    return (jnp.arange(maxlen)[None, :] < lengths.reshape(-1, 1)).astype(dtype)


@register_op("sequence_mask", grad=None, nondiff_inputs=("X",))
def sequence_mask(ins, attrs, ctx):
    """reference: sequence_ops/sequence_mask_op.cc."""
    x = ins["X"][0]
    maxlen = int(attrs.get("maxlen", -1))
    if maxlen < 0:
        maxlen = int(jnp.max(x))  # requires static value; prefer explicit maxlen
    from ..core.ir import normalize_dtype
    import numpy as np

    dt = np.dtype(normalize_dtype(attrs.get("out_dtype", "int64")))
    return {"Y": _mask(x, maxlen, dt)}


@register_op("sequence_pool", nondiff_inputs=("Length",))
def sequence_pool(ins, attrs, ctx):
    """Masked pooling over the time axis of a padded [N,T,D] batch
    (reference: sequence_ops/sequence_pool_op.cc over LoD)."""
    x = ins["X"][0]
    ptype = attrs.get("pooltype", "SUM").upper()
    if ins.get("Length") and ins["Length"][0] is not None:
        m = _mask(ins["Length"][0], x.shape[1], x.dtype)[..., None]
    else:
        m = jnp.ones(x.shape[:2], x.dtype)[..., None]
    if ptype == "SUM":
        out = jnp.sum(x * m, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)
    elif ptype == "SQRT":
        out = jnp.sum(x * m, axis=1) / jnp.sqrt(jnp.maximum(jnp.sum(m, axis=1), 1.0))
    elif ptype == "MAX":
        neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
        out = jnp.max(jnp.where(m > 0, x, neg), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(jnp.sum(m[:, :, 0], axis=1).astype(jnp.int32) - 1, 0)
        out = x[jnp.arange(x.shape[0]), idx]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"unsupported pooltype {ptype}")
    return {"Out": out, "MaxIndex": None}


@register_op("sequence_softmax", nondiff_inputs=("Length",))
def sequence_softmax(ins, attrs, ctx):
    x = ins["X"][0]  # [N, T]
    if ins.get("Length") and ins["Length"][0] is not None:
        m = _mask(ins["Length"][0], x.shape[-1], x.dtype)
        x = jnp.where(m > 0, x, jnp.asarray(-1e9, x.dtype))
    return {"Out": jax.nn.softmax(x, axis=-1)}


@register_op("sequence_reverse", nondiff_inputs=("Length",))
def sequence_reverse(ins, attrs, ctx):
    x = ins["X"][0]  # [N, T, ...]
    if ins.get("Length") and ins["Length"][0] is not None:
        lengths = ins["Length"][0]
        t = x.shape[1]
        idx = jnp.arange(t)[None, :]
        rev = lengths.reshape(-1, 1) - 1 - idx
        gather_idx = jnp.where(idx < lengths.reshape(-1, 1), rev, idx)
        return {"Y": jnp.take_along_axis(
            x, gather_idx.reshape(gather_idx.shape + (1,) * (x.ndim - 2)).astype(jnp.int32),
            axis=1)}
    return {"Y": jnp.flip(x, axis=1)}


@register_op("sequence_expand", nondiff_inputs=("Y",))
def sequence_expand(ins, attrs, ctx):
    # padded-batch equivalent: broadcast x rows along a repeat count — with
    # static shapes this is tile along axis 1
    x = ins["X"][0]
    y = ins["Y"][0]
    return {"Out": jnp.repeat(x, y.shape[1] // max(x.shape[1], 1), axis=1)
            if x.ndim > 1 else x}


@register_op("sequence_concat")
def sequence_concat(ins, attrs, ctx):
    xs = [x for x in ins["X"] if x is not None]
    return {"Out": jnp.concatenate(xs, axis=1)}


@register_op("sequence_slice")
def sequence_slice(ins, attrs, ctx):
    """Offset may be a traced tensor (lax.dynamic_slice); length must be
    static (attr) — XLA output shapes are static."""
    x = ins["X"][0]
    length = int(attrs["length"])
    off = ins["Offset"][0] if ins.get("Offset") else None
    if off is None:
        o = int(attrs.get("offset", 0))
        return {"Out": jax.lax.slice_in_dim(x, o, o + length, axis=1)}
    o = off.reshape(-1)[0].astype(jnp.int32)
    return {"Out": jax.lax.dynamic_slice_in_dim(x, o, length, axis=1)}


@register_op("im2sequence")
def im2sequence(ins, attrs, ctx):
    """reference: im2sequence_op.cc — sliding-window patches to sequence
    (OCR models). [N,C,H,W] -> [N, H'*W', C*kh*kw]."""
    x = ins["X"][0]
    kh, kw = [int(k) for k in attrs["kernels"]]
    sh, sw = [int(s) for s in attrs.get("strides", [1, 1])]
    pads = [int(p) for p in attrs.get("paddings", [0, 0, 0, 0])]
    x = jnp.pad(x, [(0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3])])
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: [N, C*kh*kw, oh, ow] -> [N, oh*ow, C*kh*kw]
    return {"Out": patches.reshape(n, c * kh * kw, oh * ow).transpose(0, 2, 1)}
