"""Large-vocabulary classification ops: NCE, hierarchical sigmoid,
sampled softmax, cosine similarity.

Reference behavior: operators/nce_op.h (per-sample cost -log(o/(o+b)) for
true classes and -log(b/(o+b)) for negatives, where o = sigmoid(logit) and
b = P(class) * num_neg_samples), operators/hierarchical_sigmoid_op.h +
math/matrix_bit_code.h (SimpleCode over label+num_classes: node index
(c>>(d+1))-1, bit (c>>d)&1; cost = sum_d softplus(pre_d) - bit_d*pre_d with
pre clipped to [-40,40]), operators/sample_logits_op.cc +
layers/nn.py:7916 sampled_softmax_with_cross_entropy, operators/cos_sim_op.h.

TPU-native: everything is batched gathers + one [N, S, D] x [N, D] einsum
(MXU-friendly); negative sampling uses the executor-threaded RNG
(ctx.rng()); no SelectedRows — weight gradients are dense scatter-adds,
which XLA fuses.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _log_uniform_prob(classes, range_max):
    """P(c) of the log-uniform sampler (reference: math/sampler.cc
    LogUniformSampler): log((c+2)/(c+1)) / log(range_max+1)."""
    c = classes.astype(jnp.float32)
    return jnp.log((c + 2.0) / (c + 1.0)) / np.log(range_max + 1.0)


def _sample_classes(rng, shape, num_classes, sampler, custom_probs=None):
    if sampler == "custom":
        if custom_probs is None:
            raise ValueError("sampler='custom' requires CustomDistProbs")
        logits = jnp.log(jnp.maximum(custom_probs, 1e-30))
        return jax.random.categorical(rng, logits, shape=shape).astype(
            jnp.int64)
    if sampler == "log_uniform":
        # inverse-CDF of the log-uniform distribution
        u = jax.random.uniform(rng, shape)
        s = jnp.exp(u * np.log(num_classes + 1.0)) - 1.0
        return jnp.clip(s.astype(jnp.int64), 0, num_classes - 1)
    return jax.random.randint(rng, shape, 0, num_classes, dtype=jnp.int64)


@register_op("nce", is_random=True,
             nondiff_inputs=("Label", "SampleWeight", "CustomDistProbs",
                             "CustomDistAlias", "CustomDistAliasProbs"),
             intermediate_outputs=("SampleLogits", "SampleLabels"))
def nce(ins, attrs, ctx):
    """Noise-contrastive estimation loss (reference: nce_op.h:241-266)."""
    x = ins["Input"][0]                      # [N, D]
    label = ins["Label"][0]                  # [N, num_true]
    w = ins["Weight"][0]                     # [C, D]
    bias = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None \
        else None
    if label.ndim == 1:
        label = label[:, None]
    n, num_true = label.shape
    num_neg = int(attrs.get("num_neg_samples", 10))
    num_classes = int(attrs["num_total_classes"])
    sampler = {0: "uniform", 1: "log_uniform", 2: "custom"}.get(
        attrs.get("sampler", 0), "uniform") if isinstance(
            attrs.get("sampler", 0), int) else attrs.get("sampler", "uniform")
    custom_probs = None
    if ins.get("CustomDistProbs") and ins["CustomDistProbs"][0] is not None:
        custom_probs = ins["CustomDistProbs"][0].reshape(-1)

    neg = _sample_classes(ctx.rng(), (n, num_neg), num_classes, sampler,
                          custom_probs)
    samples = jnp.concatenate([label.astype(jnp.int64), neg], axis=1)  # [N,S]

    w_rows = w[samples]                                   # [N, S, D]
    logits = jnp.einsum("nsd,nd->ns", w_rows, x)
    if bias is not None:
        logits = logits + bias[samples]
    o = jax.nn.sigmoid(logits)

    if sampler == "custom":
        p = custom_probs[samples].astype(logits.dtype)
    elif sampler == "log_uniform":
        p = _log_uniform_prob(samples, num_classes).astype(logits.dtype)
    else:
        p = jnp.full(samples.shape, 1.0 / num_classes, logits.dtype)
    b = p * num_neg

    eps = 1e-12
    cost_true = -jnp.log(o[:, :num_true] / (o[:, :num_true] +
                                            b[:, :num_true] + eps) + eps)
    cost_neg = -jnp.log(b[:, num_true:] / (o[:, num_true:] +
                                           b[:, num_true:] + eps) + eps)
    if ins.get("SampleWeight") and ins["SampleWeight"][0] is not None:
        sw = ins["SampleWeight"][0].reshape(-1, 1)
        cost_true = cost_true * sw
        cost_neg = cost_neg * sw
    cost = cost_true.sum(1, keepdims=True) + cost_neg.sum(1, keepdims=True)
    return {"Cost": cost, "SampleLogits": logits,
            "SampleLabels": samples}


def _simple_code(label, num_classes):
    """Default complete-binary-tree path for class `label` (reference:
    matrix_bit_code.h SimpleCode). Returns (indices [N,L], bits [N,L],
    mask [N,L]) with L = static max code length."""
    c = label.astype(jnp.int64) + num_classes
    max_len = int(2 * num_classes - 1).bit_length() - 1
    d = jnp.arange(max_len)
    # length(c) = bit_length(c) - 1 = #bits d>=1 with c >> d > 0... computed
    # positionally: position d is valid iff c >> (d+1) > 0
    valid = (c[:, None] >> (d[None, :] + 1)) > 0
    idx = jnp.maximum((c[:, None] >> (d[None, :] + 1)) - 1, 0)
    bits = (c[:, None] >> d[None, :]) & 1
    return idx, bits, valid


@register_op("hierarchical_sigmoid", nondiff_inputs=("Label", "PathTable",
                                                     "PathCode"),
             intermediate_outputs=("PreOut",))
def hierarchical_sigmoid(ins, attrs, ctx):
    """Hierarchical sigmoid cost (reference: hierarchical_sigmoid_op.h:
    pre = clip(W_path·x + b_path, ±40); cost = Σ softplus(pre) − bit·pre)."""
    x = ins["X"][0]                        # [N, D]
    w = ins["W"][0]                        # [num_nodes, D]
    label = ins["Label"][0].reshape(-1)    # [N]
    bias = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None \
        else None
    num_classes = int(attrs.get("num_classes", 2))
    if ins.get("PathTable") and ins["PathTable"][0] is not None:
        idx = ins["PathTable"][0].astype(jnp.int64)       # [N, L]
        bits = ins["PathCode"][0]
        valid = idx >= 0
        idx = jnp.maximum(idx, 0)
    else:
        idx, bits, valid = _simple_code(label, num_classes)
    w_rows = w[idx]                                       # [N, L, D]
    pre = jnp.einsum("nld,nd->nl", w_rows, x)
    if bias is not None:
        pre = pre + bias.reshape(-1)[idx]
    pre = jnp.clip(pre, -40.0, 40.0)
    mask = valid.astype(pre.dtype)
    cost = jnp.sum((jax.nn.softplus(pre) -
                    bits.astype(pre.dtype) * pre) * mask, axis=1,
                   keepdims=True)
    return {"Out": cost, "PreOut": pre}


@register_op("sampled_softmax_with_cross_entropy", is_random=True,
             nondiff_inputs=("Label", "CustomizedSamples",
                             "CustomizedProbabilities"),
             intermediate_outputs=("Samples", "SampledLogits"))
def sampled_softmax_with_cross_entropy(ins, attrs, ctx):
    """Softmax CE over {true class} ∪ {S negatives} with expected-count
    logit correction (reference: sample_logits_op.cc + layers/nn.py:7916).
    Negatives are log-uniform draws, or caller-provided via
    CustomizedSamples/CustomizedProbabilities [N, 1+S] when
    use_customized_samples."""
    logits = ins["Logits"][0]              # [N, C]
    label = ins["Label"][0]
    if label.ndim == 1:
        label = label[:, None]
    n, c = logits.shape
    s = int(attrs.get("num_samples", 5))
    nt = label.shape[1]
    use_custom = bool(attrs.get("use_customized_samples", False))
    remove_hits = bool(attrs.get("remove_accidental_hits", True))

    if use_custom:
        samples = ins["CustomizedSamples"][0].astype(jnp.int64)
        probs = ins["CustomizedProbabilities"][0]
        sub = jnp.take_along_axis(logits, samples, axis=1)
        sub = sub - jnp.log(probs.astype(sub.dtype) + 1e-12)
    else:
        neg = _sample_classes(ctx.rng(), (n, s), c, "log_uniform")
        samples = jnp.concatenate([label.astype(jnp.int64), neg], axis=1)
        sub = jnp.take_along_axis(logits, samples, axis=1)    # [N, nt+S]
        sub = sub - jnp.log(_log_uniform_prob(samples, c).astype(sub.dtype)
                            * s + 1e-12)
    if remove_hits:
        # a negative equal to ANY true class gets -inf
        hit = (samples[:, None, nt:] ==
               label.astype(jnp.int64)[:, :, None]).any(axis=1)
        sub = sub.at[:, nt:].add(jnp.where(hit, -1e20, 0.0).astype(sub.dtype))
    logp = jax.nn.log_softmax(sub, axis=-1)
    # soft uniform target over the nt true columns (num_true > 1 support)
    loss = -jnp.mean(logp[:, :nt], axis=1, keepdims=True)
    return {"Loss": loss, "Samples": samples, "SampledLogits": sub}


@register_op("sample_logits", is_random=True,
             nondiff_inputs=("Labels", "CustomizedSamples",
                             "CustomizedProbabilities"),
             intermediate_outputs=("Samples", "Probabilities",
                                   "SampledLabels", "LogitsDim",
                                   "LabelsDim"))
def sample_logits(ins, attrs, ctx):
    """reference: sample_logits_op.h — the building block under sampled
    softmax: Samples = [labels | S log-uniform negatives];
    SampledLogits[i,j] = logits[i, samples[i,j]] - log(q(samples[i,j]));
    accidental hits (negative == any true label of the row) get -1e20;
    SampledLabels[i,j] = j (position of the true columns)."""
    logits = ins["Logits"][0]              # [N, C]
    label = ins["Labels"][0]
    if label.ndim == 1:
        label = label[:, None]
    n, c = logits.shape
    s = int(attrs.get("num_samples", 5))
    nt = label.shape[1]
    use_custom = bool(attrs.get("use_customized_samples", False))
    remove_hits = bool(attrs.get("remove_accidental_hits", True))
    uniq = bool(attrs.get("uniq", True))   # accepted; sampling is i.i.d.

    if use_custom:
        samples = ins["CustomizedSamples"][0].astype(jnp.int64)
        probs = ins["CustomizedProbabilities"][0].astype(logits.dtype)
    else:
        neg = _sample_classes(ctx.rng(), (n, s), c, "log_uniform")
        samples = jnp.concatenate([label.astype(jnp.int64), neg], axis=1)
        probs = (_log_uniform_prob(samples, c) * s).astype(logits.dtype)
    sub = jnp.take_along_axis(logits, samples, axis=1)    # [N, nt+S]
    if remove_hits:
        hit = (samples[:, None, nt:] ==
               label.astype(jnp.int64)[:, :, None]).any(axis=1)
        sub = sub.at[:, nt:].add(jnp.where(hit, -1e20, 0.0).astype(sub.dtype))
    sub = sub - jnp.log(probs + 1e-12).astype(sub.dtype)
    sampled_labels = jnp.tile(jnp.arange(nt, dtype=jnp.int64)[None], (n, 1))
    return {"Samples": samples, "Probabilities": probs,
            "SampledLogits": sub, "SampledLabels": sampled_labels,
            "LogitsDim": jnp.array(logits.shape, jnp.int64),
            "LabelsDim": jnp.array(label.shape, jnp.int64)}


@register_op("cos_sim", intermediate_outputs=("XNorm", "YNorm"))
def cos_sim(ins, attrs, ctx):
    """Row-wise cosine similarity; Y broadcasts when it has one row
    (reference: cos_sim_op.h)."""
    x = ins["X"][0]
    y = ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    dot = jnp.sum(x * y, axis=-1, keepdims=True)
    eps = jnp.asarray(1e-12, x.dtype)
    return {"Out": dot / jnp.maximum(xn * yn, eps), "XNorm": xn, "YNorm": yn}


@register_op("cross_entropy2", nondiff_inputs=("Label",),
             intermediate_outputs=("XShape", "MatchX"))
def cross_entropy2(ins, attrs, ctx):
    """reference: cross_entropy2_op.cc — hard-label CE that also emits
    MatchX (the matched probability, reused by its backward)."""
    x = ins["X"][0]
    label = ins["Label"][0]
    if label.ndim == x.ndim:
        label = label[..., 0]
    ix = int(attrs.get("ignore_index", -100))
    lab = jnp.maximum(label, 0).astype(jnp.int32)
    match = jnp.take_along_axis(x, lab[..., None], axis=-1)[..., 0]
    valid = label != ix
    y = jnp.where(valid, -jnp.log(jnp.maximum(match, 1e-20)), 0.0)
    return {"Y": y[..., None], "MatchX": match[..., None], "XShape": None}
