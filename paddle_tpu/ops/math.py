"""Math ops: elementwise (with the reference's axis-broadcast semantics),
matmul family, sum/scale.

Reference: paddle/fluid/operators/elementwise/ (16 ops), matmul_op.cc,
mul_op.cc, sum_op.cc, scale_op.cc; BLAS dispatch operators/math/blas.h —
on TPU jnp.matmul lowers straight to MXU dots.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op


def _bcast(x, y, axis: int):
    """Reference broadcast (elementwise_op_function.h): align y's dims to x
    starting at `axis` (axis=-1 → trailing alignment)."""
    if x.shape == y.shape:
        return x, y
    if axis == -1 or y.ndim == 0:
        return x, y
    # pad y's shape with trailing 1s so it aligns at `axis`
    new_shape = [1] * x.ndim
    for i, s in enumerate(y.shape):
        new_shape[axis + i] = s
    return x, y.reshape(new_shape)


def _ew(fn):
    def kernel(ins, attrs, ctx):
        from ..core.selected_rows import (SelectedRows, is_selected_rows,
                                          to_dense)

        x, y = ins["X"][0], ins["Y"][0]
        # SelectedRows x (scalar) keeps the rows sparse — the
        # GlobalNorm-clip `g * scale` composition; any other SelectedRows
        # operand densifies (e.g. a sparse grad meeting a dense
        # regularization term — the reference's elementwise ops have no
        # SelectedRows kernels either)
        if is_selected_rows(x) and not is_selected_rows(y) \
                and getattr(y, "size", 0) == 1:
            return {"Out": SelectedRows(fn(x.rows, y.reshape(())),
                                        x.ids, x.height)}
        x, y = to_dense(x), to_dense(y)
        x, y = _bcast(x, y, int(attrs.get("axis", -1)))
        return {"Out": fn(x, y)}

    return kernel


register_op("elementwise_add")(_ew(jnp.add))
register_op("elementwise_sub")(_ew(jnp.subtract))
register_op("elementwise_mul")(_ew(jnp.multiply))
register_op("elementwise_div")(_ew(jnp.divide))
register_op("elementwise_max")(_ew(jnp.maximum))
register_op("elementwise_min")(_ew(jnp.minimum))
register_op("elementwise_pow")(_ew(jnp.power))
register_op("elementwise_mod", grad=None)(_ew(jnp.mod))
register_op("elementwise_floordiv", grad=None)(_ew(jnp.floor_divide))


@register_op("sum")
def sum_op(ins, attrs, ctx):
    """Multi-input add (reference: operators/sum_op.cc) — the grad
    accumulator emitted by backward.py. SelectedRows inputs concatenate
    their row sets (reference sum_op's SelectedRows branch via
    selected_rows_functor); mixing sparse and dense densifies."""
    from ..core.selected_rows import SelectedRows, is_selected_rows

    xs = [x for x in ins["X"] if x is not None]
    if any(is_selected_rows(x) for x in xs):
        if all(is_selected_rows(x) for x in xs):
            return {"Out": SelectedRows(
                jnp.concatenate([x.rows for x in xs]),
                jnp.concatenate([x.ids for x in xs]),
                xs[0].height)}
        xs = [x.to_dense() if is_selected_rows(x) else x for x in xs]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": out}


@register_op("scale")
def scale(ins, attrs, ctx):
    x = ins["X"][0]
    s = attrs.get("scale", 1.0)
    if ins.get("ScaleTensor") and ins["ScaleTensor"][0] is not None:
        s = ins["ScaleTensor"][0]
    b = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        out = x * jnp.asarray(s, x.dtype) + jnp.asarray(b, x.dtype)
    else:
        out = (x + jnp.asarray(b, x.dtype)) * jnp.asarray(s, x.dtype)
    return {"Out": out}


@register_op("mul")
def mul(ins, attrs, ctx):
    """reference: operators/mul_op.cc — flatten X to 2D at x_num_col_dims,
    Y at y_num_col_dims, then GEMM (the `fc` workhorse → MXU)."""
    x, y = ins["X"][0], ins["Y"][0]
    xnc = int(attrs.get("x_num_col_dims", 1))
    ync = int(attrs.get("y_num_col_dims", 1))
    xm = x.reshape((int(np.prod(x.shape[:xnc])), -1))
    ym = y.reshape((int(np.prod(y.shape[:ync])), -1))
    out = xm @ ym
    out_shape = x.shape[:xnc] + y.shape[ync:]
    return {"Out": out.reshape(out_shape)}


@register_op("matmul")
def matmul(ins, attrs, ctx):
    """reference: operators/matmul_op.cc (transpose_X/Y, alpha; batched via
    cublas strided-batch — here one MXU dot_general)."""
    x, y = ins["X"][0], ins["Y"][0]
    tx, ty = attrs.get("transpose_X", False), attrs.get("transpose_Y", False)
    alpha = attrs.get("alpha", 1.0)
    if x.ndim == 1:
        x = x[None, :] if not tx else x[:, None]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty and y.ndim > 1:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, out.dtype)
    return {"Out": out}


@register_op("matmul_v2")
def matmul_v2(ins, attrs, ctx):
    x, y = ins["X"][0], ins["Y"][0]
    if attrs.get("trans_x", False):
        x = jnp.swapaxes(x, -1, -2)
    if attrs.get("trans_y", False):
        y = jnp.swapaxes(y, -1, -2)
    return {"Out": jnp.matmul(x, y)}


@register_op("bmm")
def bmm(ins, attrs, ctx):
    return {"Out": jnp.matmul(ins["X"][0], ins["Y"][0])}


@register_op("dot")
def dot(ins, attrs, ctx):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": jnp.sum(x * y, axis=-1, keepdims=True)}


@register_op("addmm")
def addmm(ins, attrs, ctx):
    inp, x, y = ins["Input"][0], ins["X"][0], ins["Y"][0]
    return {"Out": attrs.get("Beta", 1.0) * inp + attrs.get("Alpha", 1.0) * (x @ y)}


@register_op("kron")
def kron(ins, attrs, ctx):
    return {"Out": jnp.kron(ins["X"][0], ins["Y"][0])}


@register_op("trace")
def trace_op(ins, attrs, ctx):
    x = ins["Input"][0]
    return {"Out": jnp.trace(x, offset=int(attrs.get("offset", 0)),
                             axis1=int(attrs.get("axis1", 0)),
                             axis2=int(attrs.get("axis2", 1)))}


@register_op("cholesky")
def cholesky(ins, attrs, ctx):
    x = ins["X"][0]
    if attrs.get("upper", False):
        return {"Out": jnp.swapaxes(jnp.linalg.cholesky(x), -1, -2)}
    return {"Out": jnp.linalg.cholesky(x)}


@register_op("inverse")
def inverse(ins, attrs, ctx):
    return {"Out": jnp.linalg.inv(ins["Input"][0])}


@register_op("max", grad="generic")
def max_op(ins, attrs, ctx):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": jnp.maximum(x, y)}


@register_op("maximum")
def maximum(ins, attrs, ctx):
    return {"Out": jnp.maximum(ins["X"][0], ins["Y"][0])}


@register_op("minimum")
def minimum(ins, attrs, ctx):
    return {"Out": jnp.minimum(ins["X"][0], ins["Y"][0])}


@register_op("l1_norm")
def l1_norm(ins, attrs, ctx):
    """reference: l1_norm_op.cc — sum(|x|) to shape [1]."""
    return {"Out": jnp.sum(jnp.abs(ins["X"][0])).reshape(1)}
