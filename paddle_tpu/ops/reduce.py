"""Reduction ops (reference: paddle/fluid/operators/reduce_ops/ — shared
reduce_op.h template over sum/mean/max/min/prod/all/any; same table here)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op


def _axes(attrs, ndim):
    if attrs.get("reduce_all", False):
        return None
    dim = attrs.get("dim", [0])
    if isinstance(dim, int):
        dim = [dim]
    return tuple(d % ndim for d in dim)


def _shape1(out):
    """Framework convention: full reductions yield shape [1], never 0-d
    (reference reduce_op.h; the backward loss seed is built as [1])."""
    return out.reshape(1) if out.ndim == 0 else out


def _reduce(fn, differentiable=True):
    def kernel(ins, attrs, ctx):
        x = ins["X"][0]
        axes = _axes(attrs, x.ndim)
        keep = attrs.get("keep_dim", False)
        return {"Out": _shape1(fn(x, axis=axes, keepdims=keep))}

    return kernel


register_op("reduce_sum")(_reduce(jnp.sum))
register_op("reduce_mean")(_reduce(jnp.mean))
register_op("reduce_max")(_reduce(jnp.max))
register_op("reduce_min")(_reduce(jnp.min))
register_op("reduce_prod")(_reduce(jnp.prod))
register_op("reduce_all", grad=None)(_reduce(jnp.all))
register_op("reduce_any", grad=None)(_reduce(jnp.any))


@register_op("logsumexp")
def logsumexp(ins, attrs, ctx):
    import jax

    x = ins["X"][0]
    axes = _axes(attrs, x.ndim)
    keep = attrs.get("keep_dim", False)
    return {"Out": _shape1(
        jax.scipy.special.logsumexp(x, axis=axes, keepdims=keep))}


@register_op("mean")
def mean(ins, attrs, ctx):
    """reference: operators/mean_op.cc — full mean to scalar [1]."""
    x = ins["X"][0]
    return {"Out": jnp.mean(x).reshape(1)}


@register_op("frobenius_norm")
def frobenius_norm(ins, attrs, ctx):
    x = ins["X"][0]
    axes = _axes(attrs, x.ndim)
    keep = attrs.get("keep_dim", False)
    return {"Out": _shape1(
        jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=keep)))}
