"""Fake-quantization ops for quantization-aware training.

Reference: operators/fake_quantize_op.cc / fake_dequantize_op.cc and the
op list in contrib/slim/quantization/quantization_pass.py:32-37
(fake_quantize_abs_max, fake_quantize_moving_average_abs_max,
fake_channel_wise_quantize_abs_max, fake_dequantize_max_abs).

TPU-native: each op quantize→dequantizes in one kernel with a
straight-through estimator — out = x + stop_gradient(q(x) − x) — so the
generic vjp yields the identity gradient inside the clip range and the
whole QAT graph stays one differentiable XLA computation (the reference
splits quant and dequant into separate int8 tensors; on TPU the simulated
int grid in fp32/bf16 is the idiomatic QAT form).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _ste(x, quantized):
    """Straight-through estimator: forward = quantized, grad = identity."""
    return x + jax.lax.stop_gradient(quantized - x)


def _quant_dequant(x, scale, bits):
    bnt = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * bnt), -bnt, bnt) * s / bnt
    return q


@register_op("fake_quantize_dequantize_abs_max",
             intermediate_outputs=("OutScale",))
def fake_quantize_dequantize_abs_max(ins, attrs, ctx):
    """Per-tensor abs-max quant-dequant (weights)."""
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    return {"Out": _ste(x, _quant_dequant(x, scale, bits)),
            "OutScale": scale.reshape(1)}


@register_op("fake_channel_wise_quantize_dequantize_abs_max",
             intermediate_outputs=("OutScale",))
def fake_channel_wise_quantize_dequantize_abs_max(ins, attrs, ctx):
    """Per-output-channel abs-max quant-dequant (conv/fc weights; channel
    axis 0 for conv [O,I,H,W], last axis for fc [In, Out] via attr)."""
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    axis = int(attrs.get("quant_axis", 0))
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    return {"Out": _ste(x, _quant_dequant(x, scale, bits)),
            "OutScale": scale.reshape(-1)}


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             nondiff_inputs=("InScale", "InState", "InAccum"),
             intermediate_outputs=("OutScale", "OutState", "OutAccum"))
def fake_quantize_dequantize_moving_average_abs_max(ins, attrs, ctx):
    """Activation quant-dequant with a moving-average abs-max scale
    (reference: fake_quantize_op.cc moving_average variant): in training,
    accum = accum*rate + absmax, state = state*rate + 1, scale =
    accum/state; at inference the stored scale is used as-is."""
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    rate = float(attrs.get("moving_rate", 0.9))
    is_test = bool(attrs.get("is_test", False)) or ctx.is_test
    in_scale = ins["InScale"][0].reshape(())
    state = ins["InState"][0].reshape(()) if ins.get("InState") and \
        ins["InState"][0] is not None else jnp.asarray(1.0, x.dtype)
    accum = ins["InAccum"][0].reshape(()) if ins.get("InAccum") and \
        ins["InAccum"][0] is not None else in_scale
    cur = jnp.max(jnp.abs(x))
    if is_test:
        scale, new_state, new_accum = in_scale, state, accum
    else:
        new_state = rate * state + 1.0
        new_accum = rate * accum + cur
        scale = new_accum / new_state
    return {"Out": _ste(x, _quant_dequant(x, scale, bits)),
            "OutScale": scale.reshape(1),
            "OutState": new_state.reshape(1),
            "OutAccum": new_accum.reshape(1)}


# ---------------------------------------------------------------------------
# Quantize-only / dequantize-only export ops (reference: the INT8 export
# path in quantization_pass.py — quantized values live in float tensors)
# ---------------------------------------------------------------------------


def _quant_only(x, scale, bits):
    bnt = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-9)
    return jnp.clip(jnp.round(x / s * bnt), -bnt, bnt)


@register_op("fake_quantize_abs_max", grad=None,
             intermediate_outputs=("OutScale",))
def fake_quantize_abs_max(ins, attrs, ctx):
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    return {"Out": _quant_only(x, scale, bits), "OutScale": scale.reshape(1)}


@register_op("fake_channel_wise_quantize_abs_max", grad=None,
             intermediate_outputs=("OutScale",))
def fake_channel_wise_quantize_abs_max(ins, attrs, ctx):
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    axis = int(attrs.get("quant_axis", 0))
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    return {"Out": _quant_only(x, scale, bits),
            "OutScale": scale.reshape(-1)}


@register_op("fake_quantize_range_abs_max", grad=None,
             nondiff_inputs=("InScale", "Iter", "InScales"),
             intermediate_outputs=("OutScale", "OutScales"))
def fake_quantize_range_abs_max(ins, attrs, ctx):
    """reference: fake_quantize_op.cc FindRangeAbsMaxFunctor:119-142 —
    training keeps a sliding window (size `window_size`) of recent
    abs-maxes indexed by Iter % window_size; scale = max over the valid
    window, so the scale CAN decrease once an old maximum slides out.
    Thread the window buffer in as `InScales` [window_size] (the
    reference reuses the OutScales var in place; the functional form
    takes it as an input and returns the updated buffer in OutScales).
    Without InScales there is no window state, so the op degrades to the
    monotone scale = max(in_scale, cur) — an upper bound of the windowed
    scale, documented as a deviation in PARITY.md. Inference: in_scale.

    Note the reference's full-rescan branch uses size = min(it,
    window_size), excluding the just-written slot at index `it` while
    filling; we always include the freshly written slot (valid =
    min(it+1, window_size)), which matches because the `max < cur`
    short-circuit covers the slot the reference's count misses.

    Deliberate deviation: we recompute the true window max every step.
    The reference's lazy branch (rescan only when the evicted slot WAS
    the max) makes a stale InScale sticky — resume from a checkpoint
    with InScale larger than every window entry and the reference keeps
    returning that InScale forever even though no window entry supports
    it. Given self-consistent (InScale, InScales) state the two agree;
    on inconsistent state we return the scale the window actually
    justifies."""
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    is_test = bool(attrs.get("is_test", False)) or ctx.is_test
    in_scale = ins["InScale"][0].reshape(())
    window = (ins.get("InScales") or [None])[0]
    cur = jnp.max(jnp.abs(x))
    if is_test:
        scale = in_scale
        out_scales = scale.reshape(1) if window is None else window
    elif window is None:
        scale = jnp.maximum(in_scale, cur)
        out_scales = scale.reshape(1)
    else:
        wsize = window.shape[0]
        assert wsize == int(attrs.get("window_size", wsize)), (
            f"fake_quantize_range_abs_max: InScales buffer length {wsize} "
            f"!= window_size attr {attrs.get('window_size')}")
        it = ins["Iter"][0].reshape(()).astype(jnp.int32)
        idx = jnp.mod(it, wsize)
        window = window.at[idx].set(cur.astype(window.dtype))
        valid = jnp.minimum(it + 1, wsize)
        masked = jnp.where(jnp.arange(wsize) < valid, window,
                           jnp.zeros((), window.dtype))
        scale = jnp.max(masked).astype(x.dtype)
        out_scales = window
    return {"Out": _quant_only(x, scale, bits),
            "OutScale": scale.reshape(1), "OutScales": out_scales}


@register_op("fake_quantize_moving_average_abs_max", grad=None,
             nondiff_inputs=("InScale", "InState", "InAccum"),
             intermediate_outputs=("OutScale", "OutState", "OutAccum"))
def fake_quantize_moving_average_abs_max(ins, attrs, ctx):
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    rate = float(attrs.get("moving_rate", 0.9))
    is_test = bool(attrs.get("is_test", False)) or ctx.is_test
    in_scale = ins["InScale"][0].reshape(())
    state = ins["InState"][0].reshape(()) if ins.get("InState") and \
        ins["InState"][0] is not None else jnp.asarray(1.0, x.dtype)
    accum = ins["InAccum"][0].reshape(()) if ins.get("InAccum") and \
        ins["InAccum"][0] is not None else in_scale
    cur = jnp.max(jnp.abs(x))
    if is_test:
        scale, new_state, new_accum = in_scale, state, accum
    else:
        new_state = rate * state + 1.0
        new_accum = rate * accum + cur
        scale = new_accum / new_state
    return {"Out": _quant_only(x, scale, bits),
            "OutScale": scale.reshape(1), "OutState": new_state.reshape(1),
            "OutAccum": new_accum.reshape(1)}


@register_op("fake_dequantize_max_abs", grad=None,
             nondiff_inputs=("Scale",))
def fake_dequantize_max_abs(ins, attrs, ctx):
    x = ins["X"][0]
    scale = ins["Scale"][0].reshape(())
    max_range = float(attrs.get("max_range", 127.0))
    return {"Out": x * scale / max_range}


@register_op("fake_channel_wise_dequantize_max_abs", grad=None,
             nondiff_inputs=("Scales",))
def fake_channel_wise_dequantize_max_abs(ins, attrs, ctx):
    """reference: fake_dequantize_op.cc channel-wise — Scales is a list
    of 1 or 2 scale tensors (weight-channel scale, then optional
    activation scale); quant_bits gives the ranges."""
    x = ins["X"][0]
    scales = [s for s in ins["Scales"] if s is not None]
    bits = [int(b) for b in attrs.get("quant_bits", [8])]
    axis = int(attrs.get("quant_axis", 0))
    shape = [1] * x.ndim
    shape[axis] = -1
    out = x * scales[0].reshape(shape) / float(2 ** (bits[0] - 1) - 1)
    if len(scales) > 1:
        out = out * scales[1].reshape(()) / float(2 ** (bits[1] - 1) - 1)
    return {"Out": out}


@register_op("moving_average_abs_max_scale", grad=None,
             nondiff_inputs=("InState", "InAccum"),
             intermediate_outputs=("OutScale", "OutState", "OutAccum"))
def moving_average_abs_max_scale(ins, attrs, ctx):
    """Scale observer only: Out = X passthrough, scale state updates like
    the moving-average quantizer (used to record activation ranges)."""
    x = ins["X"][0]
    rate = float(attrs.get("moving_rate", 0.9))
    is_test = bool(attrs.get("is_test", False)) or ctx.is_test
    state = ins["InState"][0].reshape(()) if ins.get("InState") and \
        ins["InState"][0] is not None else jnp.asarray(1.0, x.dtype)
    accum = ins["InAccum"][0].reshape(()) if ins.get("InAccum") and \
        ins["InAccum"][0] is not None else jnp.asarray(0.0, x.dtype)
    cur = jnp.max(jnp.abs(x))
    if is_test:
        scale, new_state, new_accum = accum / jnp.maximum(state, 1e-9), \
            state, accum
    else:
        new_state = rate * state + 1.0
        new_accum = rate * accum + cur
        scale = new_accum / new_state
    return {"Out": x, "OutScale": scale.reshape(1),
            "OutState": new_state.reshape(1),
            "OutAccum": new_accum.reshape(1)}


# ---------------------------------------------------------------------------
# INT8 runtime ops — true integer compute for calibrated inference models
# (reference: inference/api/mkldnn_quantizer.cc feeds calibration scales
# into INT8 kernels via cpu_quantize_pass.cc; here
# slim.quantization.calibrate_and_quantize rewrites the saved program to
# these ops and both the XLA and native engines execute them).
# ---------------------------------------------------------------------------


def _quantize_activation(x, x_scale):
    """Symmetric per-tensor int8 quantization of the activation."""
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) / x_scale), -127, 127)
    return xq.astype(jnp.int8)


@register_op("quantized_mul", grad=None, nondiff_inputs=("Y", "Scale"))
def quantized_mul(ins, attrs, ctx):
    """mul with int8 weight + int8-quantized activation: int32 MXU
    accumulation, dequantized by x_scale * w_scale (per output column)."""
    import numpy as np

    x, wq = ins["X"][0], ins["Y"][0]          # wq int8 [K, N]
    w_scale = ins["Scale"][0]                  # [1, N] (per out channel)
    x_scale = float(attrs["x_scale"])
    xnc = int(attrs.get("x_num_col_dims", 1))
    xm = x.reshape((int(np.prod(x.shape[:xnc])), -1))
    xq = _quantize_activation(xm, x_scale)
    acc = jax.lax.dot_general(
        xq, wq, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (x_scale * w_scale.reshape(1, -1))
    out_shape = x.shape[:xnc] + wq.shape[1:]
    return {"Out": out.reshape(out_shape).astype(x.dtype)}


@register_op("quantized_matmul", grad=None, nondiff_inputs=("Y", "Scale"))
def quantized_matmul(ins, attrs, ctx):
    """2-D matmul variant (transposes unsupported — the rewriter only
    targets plain X @ W)."""
    x, wq = ins["X"][0], ins["Y"][0]
    w_scale = ins["Scale"][0]
    x_scale = float(attrs["x_scale"])
    xq = _quantize_activation(x, x_scale)
    acc = jax.lax.dot_general(
        xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (x_scale * w_scale.reshape(1, -1))
    return {"Out": out.astype(x.dtype)}


@register_op("quantized_conv2d", grad=None, nondiff_inputs=("Filter", "Scale"))
def quantized_conv2d(ins, attrs, ctx):
    """conv2d (NCHW, reference layout) with int8 filter [O,I,H,W] and
    int8-quantized activation; int32 accumulation, per-output-channel
    dequant scale."""
    x, wq = ins["Input"][0], ins["Filter"][0]
    w_scale = ins["Scale"][0]                  # [O,1,1,1]
    x_scale = float(attrs["x_scale"])
    strides = tuple(int(s) for s in attrs.get("strides", [1, 1]))
    pads = [int(p) for p in attrs.get("paddings", [0, 0])]
    if len(pads) == 2:
        pads = [pads[0], pads[0], pads[1], pads[1]]
    dil = tuple(int(d) for d in attrs.get("dilations", [1, 1]))
    xq = _quantize_activation(x, x_scale)
    acc = jax.lax.conv_general_dilated(
        xq, wq, strides,
        ((pads[0], pads[1]), (pads[2], pads[3])),
        rhs_dilation=dil,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=int(attrs.get("groups", 1) or 1),
        preferred_element_type=jnp.int32)
    scale = (x_scale * w_scale.reshape(-1)).reshape(1, -1, 1, 1)
    out = acc.astype(jnp.float32) * scale
    if ins.get("Bias") and ins["Bias"][0] is not None:
        out = out + ins["Bias"][0].reshape(1, -1, 1, 1)
    return {"Output": out.astype(x.dtype)}
