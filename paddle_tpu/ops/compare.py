"""Comparison / logical ops (reference: operators/controlflow/compare_op.cc,
logical_op.cc; isfinite operators/isfinite_op.cc)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op


def _cmp(fn):
    def kernel(ins, attrs, ctx):
        return {"Out": fn(ins["X"][0], ins["Y"][0])}

    return kernel


register_op("equal", grad=None)(_cmp(jnp.equal))
register_op("not_equal", grad=None)(_cmp(jnp.not_equal))
register_op("less_than", grad=None)(_cmp(jnp.less))
register_op("less_equal", grad=None)(_cmp(jnp.less_equal))
register_op("greater_than", grad=None)(_cmp(jnp.greater))
register_op("greater_equal", grad=None)(_cmp(jnp.greater_equal))
register_op("logical_and", grad=None)(_cmp(jnp.logical_and))
register_op("logical_or", grad=None)(_cmp(jnp.logical_or))
register_op("logical_xor", grad=None)(_cmp(jnp.logical_xor))


@register_op("logical_not", grad=None)
def logical_not(ins, attrs, ctx):
    return {"Out": jnp.logical_not(ins["X"][0])}


@register_op("isinf", grad=None)
def isinf(ins, attrs, ctx):
    return {"Out": jnp.any(jnp.isinf(ins["X"][0])).reshape(1)}


@register_op("isnan", grad=None)
def isnan(ins, attrs, ctx):
    return {"Out": jnp.any(jnp.isnan(ins["X"][0])).reshape(1)}


@register_op("isfinite", grad=None)
def isfinite(ins, attrs, ctx):
    return {"Out": jnp.all(jnp.isfinite(ins["X"][0])).reshape(1)}


@register_op("isinf_v2", grad=None)
def isinf_v2(ins, attrs, ctx):
    return {"Out": jnp.isinf(ins["X"][0])}


@register_op("isnan_v2", grad=None)
def isnan_v2(ins, attrs, ctx):
    return {"Out": jnp.isnan(ins["X"][0])}


@register_op("allclose", grad=None)
def allclose(ins, attrs, ctx):
    x, y = ins["Input"][0], ins["Other"][0]
    return {"Out": jnp.allclose(x, y, rtol=float(attrs.get("rtol", 1e-5)),
                                atol=float(attrs.get("atol", 1e-8)),
                                equal_nan=bool(attrs.get("equal_nan", False)))}
