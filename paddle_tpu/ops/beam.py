"""Beam-search ops — step selection, trellis decode, tree backtrack.

Reference behavior: operators/beam_search_op.{cc,h} (one step: per source
sentence, expand every live prefix with its candidates, keep the global
top-beam_size; finished prefixes — pre_id == end_id — contribute exactly
one candidate, themselves, with unchanged score; is_accumulated=False means
incoming scores are raw probabilities to be log-accumulated onto
pre_scores) and operators/beam_search_decode_op.{cc,h} (walk the recorded
steps backwards through parent pointers to emit full sentences + scores).

TPU-native design: the reference tracks beams in 2-level LoD with dynamic
shrinking; XLA needs static shapes, so beams are a fixed [B, K] lane and
ended beams are frozen in place via -inf masking (same selection results).
Selection is one flat top_k over [B, K*W] — a single XLA sort per step.
The decode is a reverse lax.scan over parent pointers (the reference's
sentence walk), emitting end_id-padded [B, K, T] sentences.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op

_NEG_INF = -1e9


@register_op("beam_search", grad=None,
             nondiff_inputs=("pre_ids", "pre_scores", "ids", "scores"))
def beam_search(ins, attrs, ctx):
    """One beam-search step.

    Inputs: pre_ids [B,K] int, pre_scores [B,K], scores [B,K,W] candidate
    scores, optional ids [B,K,W] candidate ids (defaults to the class axis
    0..W-1). Outputs selected_ids/selected_scores [B,K] and parent_idx
    [B,K] (which incoming beam each selected beam extends).
    """
    pre_ids = ins["pre_ids"][0]
    pre_scores = ins["pre_scores"][0]
    scores = ins["scores"][0]
    if pre_ids.ndim == 1:
        pre_ids = pre_ids[None]
        pre_scores = pre_scores[None]
    if scores.ndim == 2:  # [K, W] single-sentence convention
        scores = scores[None]
    b, k, w = scores.shape
    beam_size = int(attrs.get("beam_size", k))
    end_id = int(attrs["end_id"])
    is_accumulated = bool(attrs.get("is_accumulated", True))

    if ins.get("ids") and ins["ids"][0] is not None:
        cand_ids = ins["ids"][0].reshape(b, k, w).astype(jnp.int32)
    else:
        cand_ids = jnp.broadcast_to(jnp.arange(w, dtype=jnp.int32),
                                    (b, k, w))

    if not is_accumulated:
        scores = pre_scores[:, :, None] + \
            jnp.log(jnp.maximum(scores, 1e-20))

    finished = pre_ids.astype(jnp.int32) == end_id            # [B, K]
    # a finished beam offers exactly one candidate: itself at slot 0
    keep_self = jnp.concatenate(
        [jnp.ones((b, k, 1), bool), jnp.zeros((b, k, w - 1), bool)], axis=2)
    scores = jnp.where(finished[:, :, None],
                       jnp.where(keep_self, pre_scores[:, :, None],
                                 _NEG_INF),
                       scores)
    cand_ids = jnp.where(finished[:, :, None], end_id, cand_ids)

    flat_scores = scores.reshape(b, k * w)
    top_scores, top_idx = jax.lax.top_k(flat_scores, beam_size)   # [B, Kout]
    parent = (top_idx // w).astype(jnp.int64)
    sel_ids = jnp.take_along_axis(cand_ids.reshape(b, k * w), top_idx,
                                  axis=1).astype(jnp.int64)
    return {"selected_ids": sel_ids, "selected_scores": top_scores,
            "parent_idx": parent}


def _backtrack(step_ids, parents):
    """Reverse scan through parent pointers. step_ids/parents [T,B,K] →
    sequences [T,B,K] where lane j at every t holds the token of the final
    beam j's path."""
    t = step_ids.shape[0]

    def step(carry, xs):
        beam = carry                      # [B, K] lane -> beam index at t+1
        ids_t, par_t = xs
        tok = jnp.take_along_axis(ids_t, beam, axis=1)
        prev_beam = jnp.take_along_axis(par_t, beam, axis=1)
        return prev_beam, tok

    k = step_ids.shape[2]
    lane0 = jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32),
                             step_ids.shape[1:])
    _, toks = jax.lax.scan(step, lane0,
                           (step_ids, parents.astype(jnp.int32)),
                           reverse=True)
    return toks


@register_op("gather_tree", grad=None, nondiff_inputs=("Ids", "Parents"))
def gather_tree(ins, attrs, ctx):
    """Backtrack full beams from per-step ids/parents (the beam_search_decode
    walk exposed as its own op; matches the later-paddle gather_tree
    contract: inputs and output are [T, B, K])."""
    ids = ins["Ids"][0].astype(jnp.int32)
    parents = ins["Parents"][0]
    return {"Out": _backtrack(ids, parents).astype(jnp.int64)}


@register_op("beam_search_decode", grad=None,
             nondiff_inputs=("Ids", "ParentIdx", "Scores"))
def beam_search_decode(ins, attrs, ctx):
    """Assemble final sentences from recorded steps (reference:
    beam_search_decode_op.h walks each prefix back through the LoD trellis).

    Inputs: Ids [T,B,K], ParentIdx [T,B,K], Scores [T,B,K] (accumulated).
    Outputs SentenceIds [B,K,T] (tokens after each beam's first end_id are
    end_id) and SentenceScores [B,K] (the accumulated score at each beam's
    final step), both ordered best-first per sentence.
    """
    ids = ins["Ids"][0].astype(jnp.int32)
    parents = ins["ParentIdx"][0]
    scores = ins["Scores"][0]
    end_id = int(attrs["end_id"])
    t, b, k = ids.shape

    toks = _backtrack(ids, parents)                  # [T, B, K]
    toks = jnp.moveaxis(toks, 0, 2)                  # [B, K, T]
    # freeze everything after the first end_id to end_id
    ended = jnp.cumsum((toks == end_id).astype(jnp.int32), axis=2) > 0
    shifted = jnp.concatenate(
        [jnp.zeros_like(ended[:, :, :1]), ended[:, :, :-1]], axis=2)
    toks = jnp.where(shifted, end_id, toks)
    final_scores = scores[-1]                        # [B, K]
    order = jnp.argsort(-final_scores, axis=1)
    toks = jnp.take_along_axis(toks, order[:, :, None], axis=1)
    final_scores = jnp.take_along_axis(final_scores, order, axis=1)
    return {"SentenceIds": toks.astype(jnp.int64),
            "SentenceScores": final_scores}
