"""LSTM / GRU kernels via lax.scan.

Reference: operators/lstm_op.cc + math/lstm_compute (gate order c̃,i,f,o),
gru_op.cc + math/gru_compute (z,r,c̃). One scan over time replaces the
reference's per-step BLAS loop; XLA keeps the [B,·]×[·,H] gate matmuls on
the MXU and the scan carries (h, c) in registers/VMEM.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _lstm_scan(x_proj, w_hh, h0, c0):
    """x_proj: [N, T, 4H] (input projection + bias already added),
    w_hh: [H, 4H]. Gate slice order is c̃,i,f,o — the reference's memory
    layout (math/detail/lstm_cpu_kernel.h: candidate +0, input +H,
    forget +2H, output +3H), so converged reference weights transfer.
    Returns (hidden [N,T,H], cell [N,T,H], last_h, last_c)."""
    H = w_hh.shape[0]

    def step(carry, xt):
        h, c = carry
        gates = xt + h @ w_hh
        g, i, f, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), (h, c)

    xs = jnp.swapaxes(x_proj, 0, 1)  # [T, N, 4H]
    (h_last, c_last), (hs, cs) = jax.lax.scan(step, (h0, c0), xs)
    return (jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1), h_last, c_last)


@register_op("lstm_v2", nondiff_inputs=())
def lstm_v2(ins, attrs, ctx):
    x = ins["Input"][0]                      # [N, T, D]
    w = ins["Weight"][0]                     # [D+H, 4H]
    b = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    H = int(attrs["hidden_size"])
    N = x.shape[0]
    if bool(attrs.get("is_reverse", False)):
        x = jnp.flip(x, axis=1)
    w_ih, w_hh = w[:-H], w[-H:]
    x_proj = jnp.einsum("ntd,dh->nth", x, w_ih)
    if b is not None:
        x_proj = x_proj + b
    h0 = ins["H0"][0] if ins.get("H0") and ins["H0"][0] is not None else \
        jnp.zeros((N, H), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") and ins["C0"][0] is not None else \
        jnp.zeros((N, H), x.dtype)
    hidden, _, h_last, c_last = _lstm_scan(x_proj, w_hh, h0, c0)
    if bool(attrs.get("is_reverse", False)):
        hidden = jnp.flip(hidden, axis=1)
    return {"Hidden": hidden, "LastH": h_last, "LastC": c_last}


@register_op("dynamic_lstm_v2", nondiff_inputs=())
def dynamic_lstm_v2(ins, attrs, ctx):
    """Pre-projected input [N, T, 4H] (reference dynamic_lstm contract)."""
    x = ins["Input"][0]
    w = ins["Weight"][0]                     # [H, 4H]
    b = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    H = int(attrs["hidden_size"])
    N = x.shape[0]
    if bool(attrs.get("is_reverse", False)):
        x = jnp.flip(x, axis=1)
    if b is not None:
        x = x + b
    h0 = ins["H0"][0] if ins.get("H0") and ins["H0"][0] is not None else \
        jnp.zeros((N, H), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") and ins["C0"][0] is not None else \
        jnp.zeros((N, H), x.dtype)
    hidden, cell, h_last, c_last = _lstm_scan(x, w, h0, c0)
    if bool(attrs.get("is_reverse", False)):
        hidden = jnp.flip(hidden, axis=1)
        cell = jnp.flip(cell, axis=1)
    # Cell is the per-step cell-state SEQUENCE (reference lstm_op contract)
    return {"Hidden": hidden, "Cell": cell}


def _gru_scan(x_proj, w_hh, h0):
    """x_proj [N,T,3H], w_hh [H, 3H] (z|r|c layout)."""
    H = w_hh.shape[0]
    w_zr, w_c = w_hh[:, :2 * H], w_hh[:, 2 * H:]

    def step(h, xt):
        zr = jax.nn.sigmoid(xt[..., :2 * H] + h @ w_zr)
        z, r = jnp.split(zr, 2, axis=-1)
        c = jnp.tanh(xt[..., 2 * H:] + (r * h) @ w_c)
        h = (1 - z) * h + z * c
        return h, h

    xs = jnp.swapaxes(x_proj, 0, 1)
    h_last, hs = jax.lax.scan(step, h0, xs)
    return jnp.swapaxes(hs, 0, 1), h_last


@register_op("gru_v2", nondiff_inputs=())
def gru_v2(ins, attrs, ctx):
    x = ins["Input"][0]
    w = ins["Weight"][0]                     # [D+H, 3H]
    b = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    H = int(attrs["hidden_size"])
    N = x.shape[0]
    if bool(attrs.get("is_reverse", False)):
        x = jnp.flip(x, axis=1)
    w_ih, w_hh = w[:-H], w[-H:]
    x_proj = jnp.einsum("ntd,dh->nth", x, w_ih)
    if b is not None:
        x_proj = x_proj + b
    h0 = ins["H0"][0] if ins.get("H0") and ins["H0"][0] is not None else \
        jnp.zeros((N, H), x.dtype)
    hidden, h_last = _gru_scan(x_proj, w_hh, h0)
    if bool(attrs.get("is_reverse", False)):
        hidden = jnp.flip(hidden, axis=1)
    return {"Hidden": hidden, "LastH": h_last}


_ACTS = {
    "identity": lambda x: x,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
}

# gru_unit integer activation codes (gru_unit_op.h enum)
_ACT_CODES = {0: "identity", 1: "sigmoid", 2: "tanh", 3: "relu"}


@register_op("lstm_unit", nondiff_inputs=())
def lstm_unit(ins, attrs, ctx):
    """reference: lstm_unit_op.h:63-71 — single LSTM step on pre-projected
    gates X [B, 4D] in (i, f, o, j) order:
    C = C_prev*sigm(f+forget_bias) + sigm(i)*tanh(j); H = sigm(o)*tanh(C).
    """
    x = ins["X"][0]
    c_prev = ins["C_prev"][0]
    fb = float(attrs.get("forget_bias", 0.0))
    i, f, o, j = jnp.split(x, 4, axis=-1)
    c = c_prev * jax.nn.sigmoid(f + fb) + jax.nn.sigmoid(i) * jnp.tanh(j)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return {"C": c, "H": h}


@register_op("gru_unit", nondiff_inputs=())
def gru_unit(ins, attrs, ctx):
    """reference: gru_unit_op.h — single GRU step. Input [B,3D] is the
    pre-projected x; Weight [D,3D] = [W_update|W_reset | W_candidate];
    Gate output holds the activated (u, r, c) triple."""
    x = ins["Input"][0]
    h_p = ins["HiddenPrev"][0]
    w = ins["Weight"][0]
    b = (ins.get("Bias") or [None])[0]
    D = h_p.shape[1]
    act = _ACTS[_ACT_CODES[int(attrs.get("activation", 2))]]
    gate_act = _ACTS[_ACT_CODES[int(attrs.get("gate_activation", 1))]]
    g = x if b is None else x + b.reshape(1, -1)
    g_ur = g[:, :2 * D] + h_p @ w[:, :2 * D]
    u = gate_act(g_ur[:, :D])
    r = gate_act(g_ur[:, D:])
    r_h_p = r * h_p
    c = act(g[:, 2 * D:] + r_h_p @ w[:, 2 * D:])
    if bool(attrs.get("origin_mode", False)):
        h = c + u * (h_p - c)          # (1-u)*c + u*h_p
    else:
        h = u * (c - h_p) + h_p        # u*c + (1-u)*h_p
    return {"Gate": jnp.concatenate([u, r, c], axis=1),
            "ResetHiddenPrev": r_h_p, "Hidden": h}


@register_op("lstmp_v2", nondiff_inputs=())
def lstmp_v2(ins, attrs, ctx):
    """reference: lstmp_op.h — LSTM with recurrent projection (LSTMP,
    Sak et al.): gates = x_t + r_{t-1} @ Weight[P,4D]; standard cell;
    r_t = proj_act(h_t @ ProjWeight[D,P]) with optional cell/proj clip.
    Padded-batch: Input [N,T,4D] pre-projected (the dynamic_lstm input
    contract); gate slice order c̃,i,f,o as in _lstm_scan. use_peepholes
    is not supported (documented refusal: peephole weights are a
    cuDNN-era micro-optimisation with no TPU benefit)."""
    x = ins["Input"][0]                        # [N, T, 4D]
    w = ins["Weight"][0]                       # [P, 4D]
    pw = ins["ProjWeight"][0]                  # [D, P]
    b = (ins.get("Bias") or [None])[0]
    assert not bool(attrs.get("use_peepholes", False)), \
        "lstmp_v2: use_peepholes not supported"
    D = pw.shape[0]
    P = pw.shape[1]
    N = x.shape[0]
    cell_clip = float(attrs.get("cell_clip", 0.0))
    proj_clip = float(attrs.get("proj_clip", 0.0))
    gate_act = _ACTS[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACTS[attrs.get("cell_activation", "tanh")]
    cand_act = _ACTS[attrs.get("candidate_activation", "tanh")]
    proj_act = _ACTS[attrs.get("proj_activation", "tanh")]
    if bool(attrs.get("is_reverse", False)):
        x = jnp.flip(x, axis=1)
    if b is not None:
        x = x + b.reshape(1, 1, -1)
    h0 = (ins.get("H0") or [None])[0]
    c0 = (ins.get("C0") or [None])[0]
    # The reference kernel (lstmp_op.h:211) feeds H0 straight into the
    # gate matmul against Weight[P,4D], i.e. H0 is the initial *projection*
    # of shape [N,P] (despite the op doc calling it the [N,D] hidden — the
    # reference's own doc/kernel shapes disagree; we follow the kernel).
    if h0 is None:
        r0 = jnp.zeros((N, P), x.dtype)
    else:
        assert h0.shape[-1] == P, (
            f"lstmp_v2: H0 must be the initial projection of shape [N,{P}] "
            f"(the reference kernel uses H0 directly as r0), got {h0.shape}")
        r0 = h0.astype(x.dtype)
    c0 = jnp.zeros((N, D), x.dtype) if c0 is None else c0

    def step(carry, xt):
        r, c = carry
        gates = xt + r @ w
        g, i, f, o = jnp.split(gates, 4, axis=-1)
        i, f, o = gate_act(i), gate_act(f), gate_act(o)
        c = f * c + i * cand_act(g)
        if cell_clip > 0:
            c = jnp.clip(c, -cell_clip, cell_clip)
        h = o * cell_act(c)
        r = proj_act(h @ pw)
        if proj_clip > 0:
            r = jnp.clip(r, -proj_clip, proj_clip)
        return (r, c), (r, c)

    xs = jnp.swapaxes(x, 0, 1)
    _, (rs, cs) = jax.lax.scan(step, (r0, c0), xs)
    proj = jnp.swapaxes(rs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    if bool(attrs.get("is_reverse", False)):
        proj = jnp.flip(proj, axis=1)
        cell = jnp.flip(cell, axis=1)
    return {"Projection": proj, "Cell": cell}


@register_op("attention_lstm", nondiff_inputs=(),
             intermediate_outputs=("AttentionedX", "AttentionFCOut",
                                   "LSTMX", "LSTMOUT"))
def attention_lstm(ins, attrs, ctx):
    """reference: attention_lstm_op.cc — fused attention LSTM: at each
    output step, scores = relu(x@Wa[:M] + dot(c_prev, Wa[M:]) (+scalar
    stage)), softmaxed over the sequence, pool x with them into lstm_x,
    then one LSTM step whose weight layout is rows [0:D]=hidden,
    [D:D+M]=x and gate order (f, i, o, c̃). Padded-batch: X [N,T,M] with
    optional SeqLen [N]; one lax.scan emits hidden/cell per step."""
    x = ins["X"][0]                            # [N, T, M]
    c0 = ins["C0"][0]
    h0 = (ins.get("H0") or [None])[0]
    wa = ins["AttentionWeight"][0].reshape(-1)  # [M+D]
    ba = (ins.get("AttentionBias") or [None])[0]
    sc = (ins.get("AttentionScalar") or [None])[0]
    scb = (ins.get("AttentionScalarBias") or [None])[0]
    lw = ins["LSTMWeight"][0]                  # [D+M, 4D]
    lb = ins["LSTMBias"][0].reshape(-1)        # [4D]
    seq_len = (ins.get("SeqLen") or [None])[0]
    n, t, m = x.shape
    d = c0.shape[1]
    gate_act = _ACTS[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACTS[attrs.get("cell_activation", "tanh")]
    cand_act = _ACTS[attrs.get("candidate_activation", "tanh")]

    atted_x = jnp.einsum("ntm,m->nt", x, wa[:m])
    if ba is not None:
        atted_x = atted_x + ba.reshape(())
    valid = jnp.ones((n, t), bool) if seq_len is None else \
        jnp.arange(t)[None, :] < seq_len.reshape(-1, 1)
    h_prev = jnp.zeros((n, d), x.dtype) if h0 is None else h0

    def step(carry, _):
        h, c = carry
        score = jax.nn.relu(atted_x + (c @ wa[m:])[:, None])   # [N, T]
        if sc is not None:
            score = score * sc.reshape(())
            if scb is not None:
                score = score + scb.reshape(())
            score = jax.nn.relu(score)
        # finite mask value: an all-padded row (SeqLen 0) softmaxes to a
        # uniform distribution instead of NaN
        score = jnp.where(valid, score, -1e30)
        att = jax.nn.softmax(score, axis=1)
        lstm_x = jnp.einsum("nt,ntm->nm", att, x)
        gates = lstm_x @ lw[d:] + h @ lw[:d] + lb
        f, i, o = (gate_act(gates[:, :d]), gate_act(gates[:, d:2 * d]),
                   gate_act(gates[:, 2 * d:3 * d]))
        cand = cand_act(gates[:, 3 * d:])
        c = f * c + i * cand
        h = cell_act(c) * o
        return (h, c), (h, c, att, lstm_x)

    (_, _), (hs, cs, atts, lxs) = jax.lax.scan(
        step, (h_prev, c0), None, length=t)
    hidden = jnp.swapaxes(hs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    return {"Hidden": hidden, "Cell": cell,
            "AttentionedX": atted_x[..., None],
            "AttentionFCOut": jnp.swapaxes(atts, 0, 1)[..., None],
            "LSTMX": jnp.swapaxes(lxs, 0, 1),
            "LSTMOUT": jnp.concatenate([hidden, cell], axis=-1)}


@register_op("dynamic_gru_v2", nondiff_inputs=())
def dynamic_gru_v2(ins, attrs, ctx):
    x = ins["Input"][0]                      # [N, T, 3H]
    w = ins["Weight"][0]                     # [H, 3H]
    b = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    H = int(attrs["hidden_size"])
    N = x.shape[0]
    if bool(attrs.get("is_reverse", False)):
        x = jnp.flip(x, axis=1)
    if b is not None:
        x = x + b
    h0 = ins["H0"][0] if ins.get("H0") and ins["H0"][0] is not None else \
        jnp.zeros((N, H), x.dtype)
    hidden, h_last = _gru_scan(x, w, h0)
    if bool(attrs.get("is_reverse", False)):
        hidden = jnp.flip(hidden, axis=1)
    return {"Hidden": hidden, "LastH": h_last}
