"""LSTM / GRU kernels via lax.scan.

Reference: operators/lstm_op.cc + math/lstm_compute (gate order c̃,i,f,o),
gru_op.cc + math/gru_compute (z,r,c̃). One scan over time replaces the
reference's per-step BLAS loop; XLA keeps the [B,·]×[·,H] gate matmuls on
the MXU and the scan carries (h, c) in registers/VMEM.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _lstm_scan(x_proj, w_hh, h0, c0):
    """x_proj: [N, T, 4H] (input projection + bias already added),
    w_hh: [H, 4H]. Gate slice order is c̃,i,f,o — the reference's memory
    layout (math/detail/lstm_cpu_kernel.h: candidate +0, input +H,
    forget +2H, output +3H), so converged reference weights transfer.
    Returns (hidden [N,T,H], cell [N,T,H], last_h, last_c)."""
    H = w_hh.shape[0]

    def step(carry, xt):
        h, c = carry
        gates = xt + h @ w_hh
        g, i, f, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), (h, c)

    xs = jnp.swapaxes(x_proj, 0, 1)  # [T, N, 4H]
    (h_last, c_last), (hs, cs) = jax.lax.scan(step, (h0, c0), xs)
    return (jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1), h_last, c_last)


@register_op("lstm_v2", nondiff_inputs=())
def lstm_v2(ins, attrs, ctx):
    x = ins["Input"][0]                      # [N, T, D]
    w = ins["Weight"][0]                     # [D+H, 4H]
    b = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    H = int(attrs["hidden_size"])
    N = x.shape[0]
    if bool(attrs.get("is_reverse", False)):
        x = jnp.flip(x, axis=1)
    w_ih, w_hh = w[:-H], w[-H:]
    x_proj = jnp.einsum("ntd,dh->nth", x, w_ih)
    if b is not None:
        x_proj = x_proj + b
    h0 = ins["H0"][0] if ins.get("H0") and ins["H0"][0] is not None else \
        jnp.zeros((N, H), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") and ins["C0"][0] is not None else \
        jnp.zeros((N, H), x.dtype)
    hidden, _, h_last, c_last = _lstm_scan(x_proj, w_hh, h0, c0)
    if bool(attrs.get("is_reverse", False)):
        hidden = jnp.flip(hidden, axis=1)
    return {"Hidden": hidden, "LastH": h_last, "LastC": c_last}


@register_op("dynamic_lstm_v2", nondiff_inputs=())
def dynamic_lstm_v2(ins, attrs, ctx):
    """Pre-projected input [N, T, 4H] (reference dynamic_lstm contract)."""
    x = ins["Input"][0]
    w = ins["Weight"][0]                     # [H, 4H]
    b = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    H = int(attrs["hidden_size"])
    N = x.shape[0]
    if bool(attrs.get("is_reverse", False)):
        x = jnp.flip(x, axis=1)
    if b is not None:
        x = x + b
    h0 = ins["H0"][0] if ins.get("H0") and ins["H0"][0] is not None else \
        jnp.zeros((N, H), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") and ins["C0"][0] is not None else \
        jnp.zeros((N, H), x.dtype)
    hidden, cell, h_last, c_last = _lstm_scan(x, w, h0, c0)
    if bool(attrs.get("is_reverse", False)):
        hidden = jnp.flip(hidden, axis=1)
        cell = jnp.flip(cell, axis=1)
    # Cell is the per-step cell-state SEQUENCE (reference lstm_op contract)
    return {"Hidden": hidden, "Cell": cell}


def _gru_scan(x_proj, w_hh, h0):
    """x_proj [N,T,3H], w_hh [H, 3H] (z|r|c layout)."""
    H = w_hh.shape[0]
    w_zr, w_c = w_hh[:, :2 * H], w_hh[:, 2 * H:]

    def step(h, xt):
        zr = jax.nn.sigmoid(xt[..., :2 * H] + h @ w_zr)
        z, r = jnp.split(zr, 2, axis=-1)
        c = jnp.tanh(xt[..., 2 * H:] + (r * h) @ w_c)
        h = (1 - z) * h + z * c
        return h, h

    xs = jnp.swapaxes(x_proj, 0, 1)
    h_last, hs = jax.lax.scan(step, h0, xs)
    return jnp.swapaxes(hs, 0, 1), h_last


@register_op("gru_v2", nondiff_inputs=())
def gru_v2(ins, attrs, ctx):
    x = ins["Input"][0]
    w = ins["Weight"][0]                     # [D+H, 3H]
    b = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    H = int(attrs["hidden_size"])
    N = x.shape[0]
    if bool(attrs.get("is_reverse", False)):
        x = jnp.flip(x, axis=1)
    w_ih, w_hh = w[:-H], w[-H:]
    x_proj = jnp.einsum("ntd,dh->nth", x, w_ih)
    if b is not None:
        x_proj = x_proj + b
    h0 = ins["H0"][0] if ins.get("H0") and ins["H0"][0] is not None else \
        jnp.zeros((N, H), x.dtype)
    hidden, h_last = _gru_scan(x_proj, w_hh, h0)
    if bool(attrs.get("is_reverse", False)):
        hidden = jnp.flip(hidden, axis=1)
    return {"Hidden": hidden, "LastH": h_last}


@register_op("dynamic_gru_v2", nondiff_inputs=())
def dynamic_gru_v2(ins, attrs, ctx):
    x = ins["Input"][0]                      # [N, T, 3H]
    w = ins["Weight"][0]                     # [H, 3H]
    b = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    H = int(attrs["hidden_size"])
    N = x.shape[0]
    if bool(attrs.get("is_reverse", False)):
        x = jnp.flip(x, axis=1)
    if b is not None:
        x = x + b
    h0 = ins["H0"][0] if ins.get("H0") and ins["H0"][0] is not None else \
        jnp.zeros((N, H), x.dtype)
    hidden, h_last = _gru_scan(x, w, h0)
    if bool(attrs.get("is_reverse", False)):
        hidden = jnp.flip(hidden, axis=1)
    return {"Hidden": hidden, "LastH": h_last}
