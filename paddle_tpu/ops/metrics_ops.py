"""Metric ops (reference: paddle/fluid/operators/metrics/: accuracy_op.cc,
auc_op.cc, precision_recall_op.cc)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("accuracy", grad=None)
def accuracy(ins, attrs, ctx):
    """reference: metrics/accuracy_op.cc — Out: topk values, Indices: topk
    indices, Label: [N,1] int64."""
    indices, label = ins["Indices"][0], ins["Label"][0]
    if label.ndim == indices.ndim:
        lbl = label
    else:
        lbl = label[:, None]
    correct = jnp.any(indices == lbl.astype(indices.dtype), axis=-1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    total = jnp.asarray(indices.shape[0], jnp.float32)
    return {
        "Accuracy": (num_correct / total).reshape(1),
        "Correct": num_correct.astype(jnp.int32).reshape(1),
        "Total": total.astype(jnp.int32).reshape(1),
    }


@register_op("auc", grad=None)
def auc(ins, attrs, ctx):
    """reference: metrics/auc_op.cc — streaming AUC with bucketed positive/
    negative histograms carried as state tensors."""
    predict, label = ins["Predict"][0], ins["Label"][0]
    stat_pos, stat_neg = ins["StatPos"][0], ins["StatNeg"][0]
    num_thresholds = int(attrs.get("num_thresholds", 4095))
    pos_score = predict[:, 1] if predict.ndim == 2 and predict.shape[1] == 2 else predict.reshape(-1)
    lbl = label.reshape(-1).astype(jnp.float32)
    bucket = jnp.clip((pos_score * num_thresholds).astype(jnp.int32), 0, num_thresholds)
    pos_new = stat_pos.at[bucket].add(lbl)
    neg_new = stat_neg.at[bucket].add(1.0 - lbl)
    # trapezoid integration over buckets (descending threshold)
    tp = jnp.cumsum(pos_new[::-1])
    fp = jnp.cumsum(neg_new[::-1])
    tot_pos = tp[-1]
    tot_neg = fp[-1]
    tp0 = jnp.concatenate([jnp.zeros(1, tp.dtype), tp[:-1]])
    fp0 = jnp.concatenate([jnp.zeros(1, fp.dtype), fp[:-1]])
    area = jnp.sum((fp - fp0) * (tp + tp0) / 2.0)
    auc_val = jnp.where(tot_pos * tot_neg > 0, area / (tot_pos * tot_neg + 1e-12), 0.0)
    return {"AUC": auc_val.reshape(1), "StatPosOut": pos_new, "StatNegOut": neg_new}


@register_op("precision_recall", grad=None)
def precision_recall(ins, attrs, ctx):
    pred, label = ins["MaxProbs"][0], ins["Labels"][0]
    idx = ins["Indices"][0].reshape(-1)
    lbl = label.reshape(-1).astype(idx.dtype)
    cls = int(attrs.get("class_number", 2))
    tp = jnp.zeros(cls).at[idx].add((idx == lbl).astype(jnp.float32))
    fp = jnp.zeros(cls).at[idx].add((idx != lbl).astype(jnp.float32))
    fn = jnp.zeros(cls).at[lbl].add((idx != lbl).astype(jnp.float32))
    precision = tp / jnp.maximum(tp + fp, 1.0)
    recall = tp / jnp.maximum(tp + fn, 1.0)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-6)
    macro = jnp.stack([jnp.mean(precision), jnp.mean(recall), jnp.mean(f1)])
    return {"BatchMetrics": macro, "AccumMetrics": macro,
            "AccumStatesInfo": jnp.stack([tp, fp, fn], axis=1)}


@register_op("positive_negative_pair", grad=None)
def positive_negative_pair(ins, attrs, ctx):
    """reference: positive_negative_pair_op.h — per-query pair ranking
    statistic. For every same-query pair with different labels (weight
    (w_i+w_j)/2): equal scores add to NeutralPair AND NegativePair (the
    reference's branch structure), correctly-ordered pairs to
    PositivePair, else NegativePair; optional Accumulate* inputs chain
    batches."""
    score = ins["Score"][0]
    label = ins["Label"][0].reshape(-1)
    query = ins["QueryID"][0].reshape(-1)
    w_in = (ins.get("Weight") or [None])[0]
    col = int(attrs.get("column", -1))
    if score.ndim == 1:
        score = score[:, None]
    s = score[:, col]
    n = s.shape[0]
    w = jnp.ones((n,), s.dtype) if w_in is None else \
        w_in.reshape(-1).astype(s.dtype)

    upper = jnp.triu(jnp.ones((n, n), bool), k=1)
    same_q = query[:, None] == query[None, :]
    diff_l = label[:, None] != label[None, :]
    mask = (upper & same_q & diff_l).astype(s.dtype)
    pw = (w[:, None] + w[None, :]) * 0.5
    ds = s[:, None] - s[None, :]
    dl = (label[:, None] - label[None, :]).astype(s.dtype)
    eq = (ds == 0).astype(s.dtype)
    pos_m = (ds * dl > 0).astype(s.dtype)
    pos = jnp.sum(mask * pw * pos_m)
    neg = jnp.sum(mask * pw * (1.0 - pos_m))
    neu = jnp.sum(mask * pw * eq)
    for slot, acc in (("AccumulatePositivePair", "pos"),
                      ("AccumulateNegativePair", "neg"),
                      ("AccumulateNeutralPair", "neu")):
        v = (ins.get(slot) or [None])[0]
        if v is not None:
            if acc == "pos":
                pos = pos + v.reshape(())
            elif acc == "neg":
                neg = neg + v.reshape(())
            else:
                neu = neu + v.reshape(())
    return {"PositivePair": pos.reshape(1), "NegativePair": neg.reshape(1),
            "NeutralPair": neu.reshape(1)}
