"""Metric ops (reference: paddle/fluid/operators/metrics/: accuracy_op.cc,
auc_op.cc, precision_recall_op.cc)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("accuracy", grad=None)
def accuracy(ins, attrs, ctx):
    """reference: metrics/accuracy_op.cc — Out: topk values, Indices: topk
    indices, Label: [N,1] int64."""
    indices, label = ins["Indices"][0], ins["Label"][0]
    if label.ndim == indices.ndim:
        lbl = label
    else:
        lbl = label[:, None]
    correct = jnp.any(indices == lbl.astype(indices.dtype), axis=-1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    total = jnp.asarray(indices.shape[0], jnp.float32)
    return {
        "Accuracy": (num_correct / total).reshape(1),
        "Correct": num_correct.astype(jnp.int32).reshape(1),
        "Total": total.astype(jnp.int32).reshape(1),
    }


@register_op("auc", grad=None)
def auc(ins, attrs, ctx):
    """reference: metrics/auc_op.cc — streaming AUC with bucketed positive/
    negative histograms carried as state tensors."""
    predict, label = ins["Predict"][0], ins["Label"][0]
    stat_pos, stat_neg = ins["StatPos"][0], ins["StatNeg"][0]
    num_thresholds = int(attrs.get("num_thresholds", 4095))
    pos_score = predict[:, 1] if predict.ndim == 2 and predict.shape[1] == 2 else predict.reshape(-1)
    lbl = label.reshape(-1).astype(jnp.float32)
    bucket = jnp.clip((pos_score * num_thresholds).astype(jnp.int32), 0, num_thresholds)
    pos_new = stat_pos.at[bucket].add(lbl)
    neg_new = stat_neg.at[bucket].add(1.0 - lbl)
    # trapezoid integration over buckets (descending threshold)
    tp = jnp.cumsum(pos_new[::-1])
    fp = jnp.cumsum(neg_new[::-1])
    tot_pos = tp[-1]
    tot_neg = fp[-1]
    tp0 = jnp.concatenate([jnp.zeros(1, tp.dtype), tp[:-1]])
    fp0 = jnp.concatenate([jnp.zeros(1, fp.dtype), fp[:-1]])
    area = jnp.sum((fp - fp0) * (tp + tp0) / 2.0)
    auc_val = jnp.where(tot_pos * tot_neg > 0, area / (tot_pos * tot_neg + 1e-12), 0.0)
    return {"AUC": auc_val.reshape(1), "StatPosOut": pos_new, "StatNegOut": neg_new}


@register_op("precision_recall", grad=None)
def precision_recall(ins, attrs, ctx):
    pred, label = ins["MaxProbs"][0], ins["Labels"][0]
    idx = ins["Indices"][0].reshape(-1)
    lbl = label.reshape(-1).astype(idx.dtype)
    cls = int(attrs.get("class_number", 2))
    tp = jnp.zeros(cls).at[idx].add((idx == lbl).astype(jnp.float32))
    fp = jnp.zeros(cls).at[idx].add((idx != lbl).astype(jnp.float32))
    fn = jnp.zeros(cls).at[lbl].add((idx != lbl).astype(jnp.float32))
    precision = tp / jnp.maximum(tp + fp, 1.0)
    recall = tp / jnp.maximum(tp + fn, 1.0)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-6)
    macro = jnp.stack([jnp.mean(precision), jnp.mean(recall), jnp.mean(f1)])
    return {"BatchMetrics": macro, "AccumMetrics": macro,
            "AccumStatesInfo": jnp.stack([tp, fp, fn], axis=1)}
