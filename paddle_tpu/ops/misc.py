"""Miscellaneous NN / loss / metric ops rounding out the reference zoo.

Reference behaviors (all paddle/fluid/operators/): affine_channel_op.cc,
affine_grid_op.cc, lrn_op.cc, data_norm_op.cc, spectral_norm_op.cc,
row_conv_op.cc, shuffle_channel_op.cc, space_to_depth_op.cc, unfold_op.cc,
crop_op.cc + crop_tensor_op.cc, random_crop_op.cc, sampling_id_op.cc,
add_position_encoding_op.cc, rank_loss_op.cc, log_loss_op.cc,
bpr_loss_op.cc (-mean_j log σ(x_y - x_j)), npair_loss (layers/nn.py),
center_loss_op.cc, teacher_student_sigmoid_loss_op.h:43-63 (piecewise on
the label code), modified_huber_loss_op.h:40-49, edit_distance_op.cc
(Levenshtein DP), ctc_align_op.cc (merge repeats, drop blanks), and
warpctc_op.cc (CTC loss — computed with optax.ctc_loss, the same
log-space forward algorithm the external warp-ctc library implements).

TPU-native: everything static-shape; DP recursions are lax.scan; the CTC
"compaction" ops use the stable-sort trick instead of LoD shrinking.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .sequence import _compact_left, _lengths


@register_op("affine_channel")
def affine_channel(ins, attrs, ctx):
    x = ins["X"][0]
    scale = ins["Scale"][0].reshape(-1)
    bias = ins["Bias"][0].reshape(-1)
    layout = attrs.get("data_layout", "NCHW")
    if layout == "NCHW":
        shp = (1, -1) + (1,) * (x.ndim - 2)
    else:
        shp = (1,) * (x.ndim - 1) + (-1,)
    return {"Out": x * scale.reshape(shp) + bias.reshape(shp)}


@register_op("affine_grid", nondiff_inputs=("OutputShape",))
def affine_grid(ins, attrs, ctx):
    """theta [N,2,3] → normalized sampling grid [N,H,W,2]."""
    theta = ins["Theta"][0]
    if ins.get("OutputShape") and ins["OutputShape"][0] is not None:
        shape = [int(v) for v in np.asarray(ins["OutputShape"][0])]
    else:
        shape = [int(v) for v in attrs["output_shape"]]
    n, _, h, w = shape
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gx, gy = jnp.meshgrid(xs, ys)                     # [H, W]
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)         # [H, W, 3]
    out = jnp.einsum("hwk,nck->nhwc", base, theta)    # [N, H, W, 2]
    return {"Output": out}


@register_op("lrn", intermediate_outputs=("MidOut",))
def lrn(ins, attrs, ctx):
    """reference: lrn_op.cc — mid = k + alpha * Σ_window x², out = x·mid^-β."""
    x = ins["X"][0]                                   # [N, C, H, W]
    n_size = int(attrs.get("n", 5))
    k = float(attrs.get("k", 2.0))
    alpha = float(attrs.get("alpha", 1e-4))
    beta = float(attrs.get("beta", 0.75))
    half = n_size // 2
    sq = x * x
    pad = [(0, 0), (half, half)] + [(0, 0)] * (x.ndim - 2)
    sq = jnp.pad(sq, pad)
    acc = sum(sq[:, i:i + x.shape[1]] for i in range(n_size))
    mid = k + alpha * acc
    return {"Out": x * mid ** (-beta), "MidOut": mid}


@register_op("data_norm", nondiff_inputs=("BatchSize", "BatchSum",
                                          "BatchSquareSum"),
             intermediate_outputs=("Means", "Scales"))
def data_norm(ins, attrs, ctx):
    """reference: data_norm_op.cc — normalize by running accumulators
    (CTR models): mean = Σx/n, scale = sqrt(n/Σx²)·... per feature."""
    x = ins["X"][0]
    bsize = ins["BatchSize"][0].reshape(-1)
    bsum = ins["BatchSum"][0].reshape(-1)
    bsqs = ins["BatchSquareSum"][0].reshape(-1)
    means = bsum / bsize
    scales = jnp.sqrt(bsize / bsqs)
    return {"Y": (x - means[None, :]) * scales[None, :],
            "Means": means, "Scales": scales}


@register_op("spectral_norm", nondiff_inputs=("U", "V"))
def spectral_norm(ins, attrs, ctx):
    """reference: spectral_norm_op.cc — normalize Weight by its largest
    singular value, estimated by power_iters rounds from U/V."""
    w = ins["Weight"][0]
    u = ins["U"][0].reshape(-1)
    v = ins["V"][0].reshape(-1)
    dim = int(attrs.get("dim", 0))
    power_iters = int(attrs.get("power_iters", 1))
    eps = float(attrs.get("eps", 1e-12))
    perm = (dim,) + tuple(i for i in range(w.ndim) if i != dim)
    wm = jnp.transpose(w, perm).reshape(w.shape[dim], -1)   # [H, W']

    def it(carry, _):
        u_, v_ = carry
        v_ = wm.T @ u_
        v_ = v_ / (jnp.linalg.norm(v_) + eps)
        u_ = wm @ v_
        u_ = u_ / (jnp.linalg.norm(u_) + eps)
        return (u_, v_), None

    (u, v), _ = jax.lax.scan(it, (u, v), None, length=max(power_iters, 1))
    sigma = u @ wm @ v
    return {"Out": w / sigma}


@register_op("row_conv", nondiff_inputs=())
def row_conv(ins, attrs, ctx):
    """reference: row_conv_op.cc — lookahead conv (Deep Speech): out[t] =
    Σ_{k<K} w[k] ⊙ x[t+k], per feature dim."""
    x = ins["X"][0]                        # [N, T, D]
    filt = ins["Filter"][0]                # [K, D]
    k, d = filt.shape
    t = x.shape[1]
    out = jnp.zeros_like(x)
    for i in range(k):
        shifted = jnp.roll(x, -i, axis=1)
        ok = (jnp.arange(t) + i < t)[None, :, None]
        out = out + jnp.where(ok, shifted, 0.0) * filt[i][None, None, :]
    return {"Out": out}


@register_op("shuffle_channel")
def shuffle_channel(ins, attrs, ctx):
    x = ins["X"][0]
    g = int(attrs.get("group", 1))
    n, c, h, w = x.shape
    return {"Out": x.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4)
            .reshape(n, c, h, w)}


@register_op("space_to_depth")
def space_to_depth(ins, attrs, ctx):
    x = ins["X"][0]
    bs = int(attrs["blocksize"])
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // bs, bs, w // bs, bs)
    return {"Out": x.transpose(0, 3, 5, 1, 2, 4)
            .reshape(n, c * bs * bs, h // bs, w // bs)}


@register_op("unfold")
def unfold(ins, attrs, ctx):
    """reference: unfold_op.cc (im2col): [N,C,H,W] → [N, C·kh·kw, L]."""
    x = ins["X"][0]
    kh, kw = [int(v) for v in attrs["kernel_sizes"]]
    sh, sw = [int(v) for v in attrs.get("strides", [1, 1])]
    pads = [int(v) for v in attrs.get("paddings", [0, 0, 0, 0])]
    dh, dw = [int(v) for v in attrs.get("dilations", [1, 1])]
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw),
        [(pads[0], pads[2]), (pads[1], pads[3])],
        rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    n, ckk, oh, ow = patches.shape
    return {"Y": patches.reshape(n, ckk, oh * ow)}


def _crop(x, offsets, shape):
    slices = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return x[slices]


@register_op("crop", nondiff_inputs=("Y", "Offsets"))
def crop(ins, attrs, ctx):
    """reference: crop_op.cc — crop X to Y's shape (or attr shape)."""
    x = ins["X"][0]
    if ins.get("Y") and ins["Y"][0] is not None:
        shape = ins["Y"][0].shape
    else:
        shape = [int(v) for v in attrs["shape"]]
    if ins.get("Offsets") and ins["Offsets"][0] is not None:
        off = ins["Offsets"][0].reshape(-1).astype(jnp.int32)
        return {"Out": jax.lax.dynamic_slice(
            x, tuple(off[i] for i in range(x.ndim)), shape)}
    offsets = [int(v) for v in attrs.get("offsets", [0] * x.ndim)]
    return {"Out": _crop(x, offsets, shape)}


@register_op("crop_tensor", nondiff_inputs=("Shape", "Offsets"))
def crop_tensor(ins, attrs, ctx):
    x = ins["X"][0]
    if ins.get("Shape") and ins["Shape"][0] is not None:
        shape = [int(v) for v in np.asarray(ins["Shape"][0])]
    else:
        shape = [int(v) for v in attrs["shape"]]
    shape = [x.shape[i] if s == -1 else s for i, s in enumerate(shape)]
    if ins.get("Offsets") and ins["Offsets"][0] is not None:
        off = ins["Offsets"][0].reshape(-1).astype(jnp.int32)
        return {"Out": jax.lax.dynamic_slice(x, tuple(off[i] for i in
                                                      range(x.ndim)),
                                             shape)}
    offsets = [int(v) for v in attrs.get("offsets", [0] * x.ndim)]
    return {"Out": _crop(x, offsets, shape)}


@register_op("random_crop", is_random=True, grad=None)
def random_crop(ins, attrs, ctx):
    """reference: random_crop_op.cc — crop `shape` at a uniform offset
    (trailing dims)."""
    x = ins["X"][0]
    shape = [int(v) for v in attrs["shape"]]
    lead = x.ndim - len(shape)
    key = ctx.rng()
    starts = []
    for i, s in enumerate(shape):
        key, sub = jax.random.split(key)
        hi = x.shape[lead + i] - s
        starts.append(jax.random.randint(sub, (), 0, hi + 1))
    off = tuple([0] * lead) + tuple(starts)
    return {"Out": jax.lax.dynamic_slice(x, off,
                                         tuple(x.shape[:lead]) +
                                         tuple(shape))}


@register_op("sampling_id", is_random=True, grad=None)
def sampling_id(ins, attrs, ctx):
    """reference: sampling_id_op.cc — sample a class index per row of a
    probability matrix."""
    x = ins["X"][0]
    logits = jnp.log(jnp.maximum(x, 1e-20))
    return {"Out": jax.random.categorical(ctx.rng(), logits,
                                          axis=-1).astype(jnp.int64)}


@register_op("add_position_encoding")
def add_position_encoding(ins, attrs, ctx):
    """reference: add_position_encoding_op.cc — out = α·x + β·PE(pos)."""
    x = ins["X"][0]                        # [N, T, D]
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    n, t, d = x.shape
    pos = jnp.arange(t, dtype=x.dtype)[:, None]
    half = d // 2
    div = jnp.exp(jnp.arange(half, dtype=x.dtype) *
                  (-np.log(10000.0) / max(half - 1, 1)))
    pe = jnp.concatenate([jnp.sin(pos * div), jnp.cos(pos * div)], axis=1)
    if pe.shape[1] < d:
        pe = jnp.pad(pe, [(0, 0), (0, d - pe.shape[1])])
    return {"Out": alpha * x + beta * pe[None, :, :]}


@register_op("rank_loss")
def rank_loss(ins, attrs, ctx):
    """reference: rank_loss_op.cc — o = left-right; C = log(1+e^o) - o·label."""
    label = ins["Label"][0]
    left = ins["Left"][0]
    right = ins["Right"][0]
    o = left - right
    return {"Out": jax.nn.softplus(o) - o * label}


@register_op("log_loss")
def log_loss(ins, attrs, ctx):
    p = ins["Predicted"][0]
    y = ins["Labels"][0]
    eps = float(attrs.get("epsilon", 1e-4))
    return {"Loss": -y * jnp.log(p + eps) - (1 - y) * jnp.log(1 - p + eps)}


@register_op("bpr_loss", nondiff_inputs=("Label",))
def bpr_loss(ins, attrs, ctx):
    """reference: bpr_loss_op.cc:127 — Y[i] = -mean_j log σ(x[i,y_i]-x[i,j])."""
    x = ins["X"][0]                        # [N, C]
    label = ins["Label"][0].reshape(-1)
    n, c = x.shape
    xy = jnp.take_along_axis(x, label[:, None].astype(jnp.int32), axis=1)
    diff = xy - x                          # [N, C]
    logsig = jax.nn.log_sigmoid(diff)
    notself = jnp.arange(c)[None, :] != label[:, None]
    return {"Y": (-jnp.sum(jnp.where(notself, logsig, 0.0), axis=1,
                           keepdims=True) / max(c - 1, 1))}


@register_op("npair_loss", nondiff_inputs=("Labels",))
def npair_loss(ins, attrs, ctx):
    """reference: layers/nn.py npair_loss — softmax CE over the
    anchor·positiveᵀ similarity matrix with same-label soft targets, plus
    l2 regularization of the embeddings."""
    anchor = ins["Anchor"][0]              # [N, D]
    positive = ins["Positive"][0]
    labels = ins["Labels"][0].reshape(-1)
    l2_reg = float(attrs.get("l2_reg", 0.002))
    sim = anchor @ positive.T              # [N, N]
    same = (labels[:, None] == labels[None, :]).astype(sim.dtype)
    targets = same / jnp.sum(same, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.mean(jnp.sum(targets * logp, axis=1))
    l2 = jnp.mean(jnp.sum(anchor * anchor + positive * positive, axis=1)) \
        * l2_reg * 0.25
    return {"Out": ce + l2}


@register_op("center_loss", nondiff_inputs=("Label", "Centers",
                                            "CenterUpdateRate"),
             intermediate_outputs=("SampleCenterDiff", "CentersOut"))
def center_loss(ins, attrs, ctx):
    """reference: center_loss_op.cc — 0.5‖x − c_y‖²; centers drift toward
    their class means when update_center."""
    x = ins["X"][0]
    label = ins["Label"][0].reshape(-1).astype(jnp.int32)
    centers = ins["Centers"][0]
    alpha = ins["CenterUpdateRate"][0].reshape(()) if \
        ins.get("CenterUpdateRate") and ins["CenterUpdateRate"][0] is not \
        None else jnp.asarray(0.5, x.dtype)
    diff = x - centers[label]
    loss = 0.5 * jnp.sum(diff * diff, axis=1, keepdims=True)
    if attrs.get("update_center", True):
        counts = jnp.zeros((centers.shape[0],), x.dtype).at[label].add(1.0)
        upd = jnp.zeros_like(centers).at[label].add(diff)
        centers_out = centers + alpha * upd / (counts[:, None] + 1.0)
    else:
        centers_out = centers
    return {"Loss": loss, "SampleCenterDiff": diff,
            "CentersOut": centers_out}


@register_op("teacher_student_sigmoid_loss", nondiff_inputs=("Label",))
def teacher_student_sigmoid_loss(ins, attrs, ctx):
    """reference: teacher_student_sigmoid_loss_op.h:43-63 — piecewise on
    the encoded label: <-1 → bce(x,0); <0 → bce(x,1); <1 → bce(x,0) +
    bce(x, z'); else → bce(x,1) + bce(x, z'-1)."""
    x = ins["X"][0].reshape(-1)
    label = ins["Label"][0].reshape(-1).astype(x.dtype)

    def bce_with(z):
        return jnp.maximum(x, 0.0) - x * z + jnp.log1p(jnp.exp(-jnp.abs(x)))

    y = jnp.where(
        label < -1.0, bce_with(0.0),
        jnp.where(label < 0.0, bce_with(1.0),
                  jnp.where(label < 1.0, bce_with(0.0) + bce_with(label),
                            bce_with(1.0) + bce_with(label - 1.0))))
    return {"Y": y[:, None]}


@register_op("modified_huber_loss", nondiff_inputs=("Y",),
             intermediate_outputs=("IntermediateVal",))
def modified_huber_loss(ins, attrs, ctx):
    """reference: modified_huber_loss_op.h:40-49 — on z = x·y (y∈{0,1}
    mapped to ±1): -4z if z<-1; (1-z)² if z<1; else 0."""
    x = ins["X"][0]
    y = ins["Y"][0]
    z = x * (2.0 * y - 1.0)
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, (1.0 - z) ** 2, 0.0))
    return {"Out": loss, "IntermediateVal": z}


@register_op("edit_distance", grad=None,
             nondiff_inputs=("Hyps", "Refs", "HypsLength", "RefsLength"))
def edit_distance(ins, attrs, ctx):
    """reference: edit_distance_op.cc — Levenshtein distance per pair;
    normalized by ref length when `normalized`. The DP rolls over one
    row at a time under lax.scan (static [T2+1] state)."""
    hyps = ins["Hyps"][0]
    refs = ins["Refs"][0]
    if hyps.ndim == 1:
        hyps, refs = hyps[None], refs[None]
    n, t1 = hyps.shape
    t2 = refs.shape[1]
    hlen = _lengths(ins, n, t1, slot="HypsLength")
    rlen = _lengths(ins, n, t2, slot="RefsLength")
    normalized = bool(attrs.get("normalized", True))
    ignored = [int(v) for v in attrs.get("ignored_tokens", []) or []]
    if ignored:
        vh = jnp.arange(t1)[None, :] < hlen[:, None]
        vr = jnp.arange(t2)[None, :] < rlen[:, None]
        eh = jnp.zeros_like(vh)
        er = jnp.zeros_like(vr)
        for tok in ignored:
            eh |= hyps == tok
            er |= refs == tok
        hyps, hlen = _compact_left(hyps, vh & ~eh)
        refs, rlen = _compact_left(refs, vr & ~er)
        hlen = hlen.astype(jnp.int32)
        rlen = rlen.astype(jnp.int32)

    def one(h, r, hl, rl):
        row0 = jnp.arange(t2 + 1, dtype=jnp.float32)

        def step(row, i):
            # row = dp[i], compute dp[i+1]
            def inner(carry, j):
                left = carry              # dp[i+1][j]
                sub = row[j] + jnp.where(h[i] == r[j], 0.0, 1.0)
                up = row[j + 1] + 1.0
                val = jnp.minimum(jnp.minimum(left + 1.0, up), sub)
                return val, val

            first = row[0] + 1.0
            _, rest = jax.lax.scan(inner, first, jnp.arange(t2))
            new_row = jnp.concatenate([first[None], rest])
            # past hyp length: row stays (distance frozen at hl)
            return jnp.where(i < hl, new_row, row), None

        final, _ = jax.lax.scan(step, row0, jnp.arange(t1))
        d = final[rl]
        return jnp.where(normalized, d / jnp.maximum(rl, 1), d)

    dist = jax.vmap(one)(hyps, refs, hlen, rlen)
    return {"Out": dist[:, None],
            "SequenceNum": jnp.asarray([n], jnp.int64)}


@register_op("ctc_align", grad=None, nondiff_inputs=("Input", "InputLength"))
def ctc_align(ins, attrs, ctx):
    """reference: ctc_align_op.cc — merge repeated tokens then drop
    blanks; compact left with the stable-sort trick, pad with -1... the
    reference pads removed tail with 0 and reports OutputLength."""
    x = ins["Input"][0]                    # [N, T] int
    blank = int(attrs.get("blank", 0))
    merge = bool(attrs.get("merge_repeated", True))
    n, t = x.shape
    ilen = _lengths(ins, n, t, slot="InputLength")
    valid = jnp.arange(t)[None, :] < ilen[:, None]
    prev = jnp.concatenate([jnp.full((n, 1), -1, x.dtype), x[:, :-1]],
                           axis=1)
    keep = valid & (x != blank)
    if merge:
        keep &= x != prev
    out, new_len = _compact_left(x, keep)
    return {"Output": out, "OutputLength": new_len[:, None].astype(jnp.int64)}


@register_op("warpctc", nondiff_inputs=("Label", "LogitsLength",
                                        "LabelLength"),
             intermediate_outputs=("WarpCTCGrad",))
def warpctc(ins, attrs, ctx):
    """reference: warpctc_op.cc — CTC loss. The external warp-ctc library
    is replaced by the same log-space forward algorithm via optax.ctc_loss
    (blank handling and padding semantics match)."""
    import optax

    logits = ins["Logits"][0]              # [N, T, C] (norm_by_times off)
    label = ins["Label"][0]                # [N, L]
    blank = int(attrs.get("blank", 0))
    n, t, c = logits.shape
    llen = _lengths(ins, n, t, slot="LogitsLength")
    yl = _lengths(ins, n, label.shape[1], slot="LabelLength")
    logit_pad = (jnp.arange(t)[None, :] >= llen[:, None]).astype(
        logits.dtype)
    label_pad = (jnp.arange(label.shape[1])[None, :] >=
                 yl[:, None]).astype(logits.dtype)

    def raw_loss(lg):
        per_sample = optax.ctc_loss(lg, logit_pad, label.astype(jnp.int32),
                                    label_pad, blank_id=blank)
        return jnp.sum(per_sample), per_sample

    # the reference caches warp-ctc's gradient of the (unnormalized)
    # per-sample loss w.r.t. the logits in WarpCTCGrad; value_and_grad
    # shares the forward, and XLA DCE drops the grad when unfetched
    (_, loss), ctc_grad = jax.value_and_grad(raw_loss, has_aux=True)(logits)
    if attrs.get("norm_by_times", False):
        loss = loss / jnp.maximum(llen.astype(loss.dtype), 1.0)
    return {"Loss": loss[:, None], "WarpCTCGrad": ctc_grad}


@register_op("multiplex", nondiff_inputs=("Ids",))
def multiplex(ins, attrs, ctx):
    """reference: multiplex_op.cc — out[i] = X[ids[i]][i] (row-wise select
    among the candidate tensors)."""
    xs = jnp.stack([x for x in ins["X"] if x is not None])   # [K, N, D]
    ids = ins["Ids"][0].reshape(-1).astype(jnp.int32)        # [N]
    rows = jnp.arange(xs.shape[1])
    return {"Out": xs[ids, rows]}


@register_op("conv3d_transpose")
def conv3d_transpose(ins, attrs, ctx):
    """reference: conv_transpose_op.cc (3-D branch)."""
    x, w = ins["Input"][0], ins["Filter"][0]   # w: [C_in, C_out, D, H, W]
    strides = tuple(int(s) for s in attrs.get("strides", [1, 1, 1]))
    dilations = tuple(int(d) for d in attrs.get("dilations", [1, 1, 1]))
    pads = attrs.get("paddings", [0, 0, 0])
    # see conv2d_transpose: jax pads the underlying conv, so map p ->
    # (k-1)*d - p for reference transpose-conv output shapes
    padding = [((w.shape[2 + i] - 1) * dilations[i] - int(p),
                (w.shape[2 + i] - 1) * dilations[i] - int(p))
               for i, p in enumerate(pads)]
    # axis 0 labeled O: see conv2d_transpose — transpose_kernel swaps I/O
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCDHW", "OIDHW", "NCDHW"))
    out = jax.lax.conv_transpose(
        x, w, strides=strides, padding=padding,
        rhs_dilation=dilations, dimension_numbers=dn, transpose_kernel=True)
    return {"Output": out}


@register_op("minus")
def minus(ins, attrs, ctx):
    """reference: minus_op.cc — Out = X - Y."""
    return {"Out": ins["X"][0] - ins["Y"][0]}


@register_op("fsp", nondiff_inputs=())
def fsp(ins, attrs, ctx):
    """reference: fsp_op.cc — flow-of-solution-procedure matrix:
    [N,Cx,H,W] x [N,Cy,H,W] → [N,Cx,Cy] / (H·W) (distillation)."""
    x = ins["X"][0]
    y = ins["Y"][0]
    h, w = x.shape[2], x.shape[3]
    out = jnp.einsum("nchw,ndhw->ncd", x, y) / float(h * w)
    return {"Out": out}


@register_op("mean_iou", grad=None, nondiff_inputs=("Predictions", "Labels"))
def mean_iou(ins, attrs, ctx):
    """reference: mean_iou_op.cc — mean IoU over classes from dense
    prediction/label maps."""
    pred = ins["Predictions"][0].reshape(-1).astype(jnp.int32)
    label = ins["Labels"][0].reshape(-1).astype(jnp.int32)
    c = int(attrs["num_classes"])
    onehot_p = pred[:, None] == jnp.arange(c)[None, :]
    onehot_l = label[:, None] == jnp.arange(c)[None, :]
    # reference mean_iou_op.h increments out_wrong at BOTH the pred and the
    # label class of every mismatch, so OutWrong[c] = FP[c] + FN[c]
    fp = jnp.sum(onehot_p & ~onehot_l, axis=0).astype(jnp.int32)
    fn = jnp.sum(~onehot_p & onehot_l, axis=0).astype(jnp.int32)
    wrong = fp + fn
    correct = jnp.sum(onehot_p & onehot_l, axis=0).astype(jnp.int32)
    # streaming accumulation (reference mean_iou_op.cc sums the optional
    # InWrongs/InCorrects lists into the outputs)
    for w_in in ins.get("InWrongs", []) or []:
        if w_in is not None:
            wrong = wrong + w_in.astype(jnp.int32)
    for c_in in ins.get("InCorrects", []) or []:
        if c_in is not None:
            correct = correct + c_in.astype(jnp.int32)
    # per-class union = accumulated wrong (fp+fn) + correct (tp), matching
    # the reference denominator out_wrong + out_correct
    union = (wrong + correct).astype(jnp.float32)
    present = union > 0
    iou = jnp.where(present, correct.astype(jnp.float32) /
                    jnp.maximum(union, 1.0), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(present), 1)
    for m_in in ins.get("InMeanIou", []) or []:
        if m_in is not None:
            miou = miou + m_in.reshape(())
    return {"OutMeanIou": miou.reshape(1), "OutWrong": wrong,
            "OutCorrect": correct}


@register_op("similarity_focus", grad=None, nondiff_inputs=("X",))
def similarity_focus(ins, attrs, ctx):
    """reference: similarity_focus_op.cc — for each (batch, index) slice
    T = X[:, idx] ([B, C] per sample after picking `axis`), greedily pick
    maxima so each row/column is used at most once, and set the focus
    mask 1 at every channel of the chosen (row, col) positions."""
    x = ins["X"][0]                 # [N, A, B, C] with axis=1
    axis = int(attrs.get("axis", 1))
    indexes = [int(i) for i in attrs["indexes"]]
    if axis != 1:
        x = jnp.moveaxis(x, axis, 1)
    n, a, b, c = x.shape
    steps = min(b, c)

    def focus_one(t):  # t [B, C] -> mask [B, C]
        def step(carry, _):
            scores, mask = carry
            flat = jnp.argmax(scores)
            i, j = flat // c, flat % c
            ok = scores[i, j] > -jnp.inf
            # only the selected cell is marked; its row/col are merely
            # excluded from later picks (similarity_focus_op.cc)
            mask = jnp.where(ok, mask.at[i, j].set(1.0), mask)
            scores = jnp.where(
                ok, scores.at[i, :].set(-jnp.inf).at[:, j].set(-jnp.inf),
                scores)
            return (scores, mask), None

        (scores, mask), _ = jax.lax.scan(
            step, (t, jnp.zeros_like(t)), None, length=steps)
        return mask

    out = jnp.zeros_like(x)
    for idx in indexes:
        m = jax.vmap(focus_one)(x[:, idx])        # [N, B, C]
        out = jnp.maximum(out, m[:, None, :, :])
    if axis != 1:
        out = jnp.moveaxis(out, 1, axis)
    return {"Out": out}


@register_op("uniform_random_batch_size_like", is_random=True, grad=None,
             nondiff_inputs=("Input",))
def uniform_random_batch_size_like(ins, attrs, ctx):
    from .tensor import _dt, batch_size_like_shape

    shape = batch_size_like_shape(ins, attrs)
    lo = float(attrs.get("min", -1.0))
    hi = float(attrs.get("max", 1.0))
    return {"Out": jax.random.uniform(ctx.rng(), tuple(shape),
                                      minval=lo,
                                      maxval=hi).astype(_dt(attrs))}


@register_op("gaussian_random_batch_size_like", is_random=True, grad=None,
             nondiff_inputs=("Input",))
def gaussian_random_batch_size_like(ins, attrs, ctx):
    from .tensor import _dt, batch_size_like_shape

    shape = batch_size_like_shape(ins, attrs)
    mean = float(attrs.get("mean", 0.0))
    std = float(attrs.get("std", 1.0))
    return {"Out": (jax.random.normal(ctx.rng(), tuple(shape)) * std +
                    mean).astype(_dt(attrs))}


# ---------------------------------------------------------------------------
# py_func — user-extensible host callback op
# ---------------------------------------------------------------------------

# callables registered by layers.py_func (reference: py_func_op.cc keeps a
# global vector of py::objects indexed by the callable-id attrs)
PY_FUNC_REGISTRY: list = []


def register_py_func(fn) -> int:
    PY_FUNC_REGISTRY.append(fn)
    return len(PY_FUNC_REGISTRY) - 1


def _py_func_grad(ins, attrs, ctx):
    """reference: py_func_op.cc backward — calls the registered backward
    callable with (forward inputs, forward outputs, output grads), minus
    any names in skip_vars_in_backward_input; it returns grads for the
    forward inputs in order (None → zeros)."""
    from ..core.registry import (GRAD_PREFIX_IG, GRAD_PREFIX_IN,
                                 GRAD_PREFIX_OG, GRAD_PREFIX_OUT)

    xs = ins.get(GRAD_PREFIX_IN + "X", [])
    outs = ins.get(GRAD_PREFIX_OUT + "Out", [])
    ogs = ins.get(GRAD_PREFIX_OG + "Out", [])
    bid = int(attrs.get("backward_callable_id", -1))
    if bid < 0:
        return {GRAD_PREFIX_IG + "X": [
            None if x is None else jnp.zeros(jnp.shape(x),
                                             jnp.result_type(x))
            for x in xs]}
    fn = PY_FUNC_REGISTRY[bid]
    skip = set(attrs.get("backward_skip_vars", []) or [])
    x_names = ctx.op.inputs.get(GRAD_PREFIX_IN + "X", [])
    out_names = ctx.op.inputs.get(GRAD_PREFIX_OUT + "Out", [])
    arg_vals, shapes = [], []
    for name, v in list(zip(x_names, xs)) + list(zip(out_names, outs)):
        if name not in skip and v is not None:
            arg_vals.append(v)
    for i, o in enumerate(outs):
        g = ogs[i] if i < len(ogs) and ogs[i] is not None else \
            jnp.zeros(jnp.shape(o), jnp.result_type(o))
        arg_vals.append(g)
    result_shapes = [jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))
                     for x in xs]

    def host(*arrays):
        res = fn(*arrays)
        if res is None:
            res = ()
        if not isinstance(res, (tuple, list)):
            res = (res,)
        padded = []
        for i in range(len(xs)):
            r = res[i] if i < len(res) else None
            if r is None:
                # xs[i] is a trace-time tracer here — shapes must come
                # from the precomputed result_shapes
                r = np.zeros(result_shapes[i].shape, result_shapes[i].dtype)
            padded.append(np.asarray(r).astype(result_shapes[i].dtype)
                          .reshape(result_shapes[i].shape))
        return tuple(padded)

    gx = jax.pure_callback(host, tuple(result_shapes), *arg_vals)
    return {GRAD_PREFIX_IG + "X": list(gx)}


@register_op("py_func", grad=_py_func_grad)
def py_func(ins, attrs, ctx):
    """reference: py_func_op.cc — run a user-registered Python callable on
    host as an op. TPU-native lowering: jax.pure_callback (jit/grad-safe
    host escape); output shapes/dtypes come from the out vars the caller
    declared (recorded by layers.py_func in out_shapes/out_dtypes)."""
    fid = int(attrs["forward_callable_id"])
    fn = PY_FUNC_REGISTRY[fid]
    xs = [x for x in ins.get("X", []) if x is not None]
    shapes = attrs.get("out_shapes", []) or []
    dtypes = attrs.get("out_dtypes", []) or []
    if not shapes:
        # output-less debug hook: io_callback keeps the side effect alive
        from jax.experimental import io_callback

        io_callback(lambda *a: fn(*a), None, *xs, ordered=True)
        return {}
    def resolve(s):
        s = [int(v) for v in s]
        for i, v in enumerate(s):
            if v < 0:
                assert i == 0 and xs, (
                    "py_func: only a -1 batch dim is resolvable; declare "
                    "concrete trailing dims on the out var")
                s[i] = xs[0].shape[0]
        return tuple(s)

    result_shapes = tuple(
        jax.ShapeDtypeStruct(resolve(s), np.dtype(d))
        for s, d in zip(shapes, dtypes))

    def host(*arrays):
        res = fn(*arrays)
        if not isinstance(res, (tuple, list)):
            res = (res,)
        return tuple(np.asarray(r).astype(rs.dtype).reshape(rs.shape)
                     for r, rs in zip(res, result_shapes))

    outs = jax.pure_callback(host, result_shapes, *xs)
    return {"Out": list(outs)}


# ---------------------------------------------------------------------------
# SelectedRows / distributed utility ops (reference behaviors:
# merge_selected_rows_op.cc, get_tensor_from_selected_rows_op.cc,
# split_selected_rows_op.cc, coalesce_tensor_op.cc, fake_init_op.cc,
# controlflow/ops delete_var, distributed_ops/ref_by_trainer_id_op.cc).
# TPU-native: every variant is static-shape — "merge" keeps the slot
# count and zeroes duplicate slots (core/selected_rows.merged), "split"
# masks out-of-section ids to the drop sentinel instead of shrinking.
# ---------------------------------------------------------------------------


@register_op("merge_selected_rows", grad=None)
def merge_selected_rows(ins, attrs, ctx):
    """Sum duplicate row ids (merge_selected_rows_op.cc — MergeAdd).
    Static-shape: duplicates fold into their first-occurrence slot;
    non-first slots carry zero rows (dropped by masked scatters)."""
    from ..core.selected_rows import SelectedRows, is_selected_rows

    x = ins["X"][0]
    if not is_selected_rows(x):
        return {"Out": x}  # dense input: nothing to merge
    sid, rows, _ = x.merged()
    return {"Out": SelectedRows(rows, sid, x.height)}


@register_op("get_tensor_from_selected_rows", grad=None)
def get_tensor_from_selected_rows(ins, attrs, ctx):
    """SelectedRows value tensor as a plain dense tensor
    (get_tensor_from_selected_rows_op.cc)."""
    from ..core.selected_rows import is_selected_rows

    x = ins["X"][0]
    return {"Out": x.rows if is_selected_rows(x) else x}


@register_op("split_selected_rows", grad=None)
def split_selected_rows(ins, attrs, ctx):
    """Split a SelectedRows by height sections (split_selected_rows_op.cc
    — the PS shard split). Static-shape: each section keeps the full
    slot count; ids outside the section are masked to the section's
    height (the scatter drop sentinel) with zeroed rows, which is
    scatter-equivalent to the reference's shrunken outputs."""
    from ..core.selected_rows import SelectedRows, is_selected_rows

    x = ins["X"][0]
    assert is_selected_rows(x), "split_selected_rows wants SelectedRows"
    sections = [int(s) for s in attrs["height_sections"]]
    outs = []
    off = 0
    for h in sections:
        inside = (x.ids >= off) & (x.ids < off + h)
        local = jnp.where(inside, x.ids - off, h)
        rows = jnp.where(inside[:, None], x.rows, 0)
        outs.append(SelectedRows(rows, local, h))
        off += h
    return {"Out": outs}


@register_op("coalesce_tensor", grad=None)
def coalesce_tensor(ins, attrs, ctx):
    """Pack many tensors into one contiguous fused buffer + per-tensor
    views (coalesce_tensor_op.cc — the fused-allreduce/optimizer
    enabler). Functionally: FusedOutput is the flat concat; Output
    returns each tensor reshaped from its slice, so downstream ops see
    the same values whether they consume the views or the fused flat."""
    xs = ins["Input"]
    dtype = xs[0].dtype
    if any(x.dtype != dtype for x in xs):
        # the reference rejects inputs not matching its dtype attr
        # (coalesce_tensor_op.cc); a silent cast would round fp32 grads
        # through the first input's dtype
        raise TypeError(
            f"coalesce_tensor: mixed input dtypes "
            f"{[str(x.dtype) for x in xs]} — all inputs must match")
    total = sum(int(np.prod(x.shape)) if x.shape else 1 for x in xs)
    set_constant = bool(attrs.get("set_constant", False))
    if set_constant:
        flat = jnp.full((total,), float(attrs.get("constant", 0.0)),
                        dtype)
    else:
        flat = jnp.concatenate([x.reshape(-1) for x in xs])
    outs, off = [], 0
    for x in xs:
        n = int(np.prod(x.shape)) if x.shape else 1
        outs.append(flat[off:off + n].reshape(x.shape))
        off += n
    return {"Output": outs, "FusedOutput": flat}


@register_op("fake_init", grad=None)
def fake_init(ins, attrs, ctx):
    """Placeholder init for vars whose real storage lives remotely (the
    trainer side of a distributed lookup table — fake_init_op.cc):
    materializes zeros of the declared shape so the program traces."""
    shape = [int(s) for s in attrs.get("shape", [1])]
    from ..core.ir import normalize_dtype

    dtype = np.dtype(normalize_dtype(attrs.get("dtype", 5)))
    return {"Out": jnp.zeros(shape, dtype)}


@register_op("delete_var", grad=None, nondiff_inputs=("X",))
def delete_var(ins, attrs, ctx):
    """Scope GC marker (controlflow delete ops): functional lowering has
    no mutable scope mid-trace — dead values are freed by XLA liveness —
    so this is a no-op accepted for program compatibility."""
    return {}


@register_op("ref_by_trainer_id", grad=None,
             nondiff_inputs=("X", "TrainerId"))
def ref_by_trainer_id(ins, attrs, ctx):
    """Select this trainer's slice from a list input by TrainerId
    (distributed_ops/ref_by_trainer_id_op.cc — DC-ASGD plumbing). The
    reference enforces trainer_id < len(X); an out-of-range id here is
    a misconfigured cluster and must fail fast, not clamp to the last
    slice (jnp.take's jit default) and silently train on wrong data."""
    tid = jnp.asarray(ins["TrainerId"][0]).reshape(()).astype(jnp.int32)
    n = len(ins["X"])
    if not isinstance(tid, jax.core.Tracer):
        concrete = int(tid)
        if not 0 <= concrete < n:
            raise ValueError(
                f"ref_by_trainer_id: TrainerId {concrete} out of range "
                f"for {n} inputs")
    xs = jnp.stack([jnp.asarray(x) for x in ins["X"]])
    # traced ids can't raise at runtime under jit: poison out-of-range
    # selections with NaN so they surface instead of silently training
    sel = jnp.take(xs, jnp.clip(tid, 0, n - 1), axis=0)
    if jnp.issubdtype(xs.dtype, jnp.floating):
        sel = jnp.where((tid >= 0) & (tid < n), sel, jnp.nan)
    return {"Out": sel}
