"""Detection ops (reference: paddle/fluid/operators/detection/ — 28 ops).

Round-1 coverage: the geometry ops (box_coder, prior_box, iou_similarity,
yolo_box); NMS-family ops need sorted dynamic shapes and follow in a later
round as masked fixed-size variants.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op


@register_op("iou_similarity", grad=None)
def iou_similarity(ins, attrs, ctx):
    x, y = ins["X"][0], ins["Y"][0]  # [N,4],[M,4] xyxy
    area_x = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    area_y = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    return {"Out": inter / (area_x[:, None] + area_y[None, :] - inter + 1e-10)}


@register_op("box_coder", grad=None)
def box_coder(ins, attrs, ctx):
    """reference: detection/box_coder_op.cc."""
    prior, tb = ins["PriorBox"][0], ins["TargetBox"][0]
    code_type = attrs.get("code_type", "encode_center_size")
    norm = attrs.get("box_normalized", True)
    pv = ins.get("PriorBoxVar")
    pv = pv[0] if pv and pv[0] is not None else None
    one = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + one
    ph = prior[:, 3] - prior[:, 1] + one
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        tw = tb[:, None, 2] - tb[:, None, 0] + one
        th = tb[:, None, 3] - tb[:, None, 1] + one
        tcx = tb[:, None, 0] + tw * 0.5
        tcy = tb[:, None, 1] + th * 0.5
        ox = (tcx - pcx) / pw
        oy = (tcy - pcy) / ph
        ow = jnp.log(jnp.abs(tw / pw))
        oh = jnp.log(jnp.abs(th / ph))
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
        if pv is not None:
            out = out / pv[None, :, :]
        return {"OutputBox": out}
    # decode_center_size
    if tb.ndim == 2:
        tb = tb[:, None, :]
    var = pv[None, :, :] if pv is not None else 1.0
    t = tb * var if pv is not None else tb
    ocx = t[..., 0] * pw + pcx
    ocy = t[..., 1] * ph + pcy
    ow = jnp.exp(t[..., 2]) * pw
    oh = jnp.exp(t[..., 3]) * ph
    out = jnp.stack([ocx - ow / 2, ocy - oh / 2,
                     ocx + ow / 2 - one, ocy + oh / 2 - one], axis=-1)
    return {"OutputBox": out}


@register_op("prior_box", grad=None)
def prior_box(ins, attrs, ctx):
    """reference: detection/prior_box_op.cc (SSD anchors)."""
    inp, image = ins["Input"][0], ins["Image"][0]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [float(a) for a in attrs.get("aspect_ratios", [1.0])]
    flip = attrs.get("flip", False)
    clip = attrs.get("clip", False)
    variances = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    offset = attrs.get("offset", 0.5)
    ih, iw = image.shape[2], image.shape[3]
    fh, fw = inp.shape[2], inp.shape[3]
    sw = attrs.get("step_w", 0.0) or iw / fw
    sh = attrs.get("step_h", 0.0) or ih / fh

    full_ars = []
    for a in ars:
        full_ars.append(a)
        if flip and a != 1.0:
            full_ars.append(1.0 / a)

    boxes = []
    for ms_i, ms in enumerate(min_sizes):
        for a in full_ars:
            boxes.append((ms * np.sqrt(a), ms / np.sqrt(a)))
            if a == 1.0 and ms_i < len(max_sizes):
                s = np.sqrt(ms * max_sizes[ms_i])
                boxes.append((s, s))
    num_priors = len(boxes)
    cx = (jnp.arange(fw) + offset) * sw
    cy = (jnp.arange(fh) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)
    wh = jnp.asarray(boxes)  # [P, 2]
    out = jnp.stack([
        (cxg[..., None] - wh[None, None, :, 0] / 2) / iw,
        (cyg[..., None] - wh[None, None, :, 1] / 2) / ih,
        (cxg[..., None] + wh[None, None, :, 0] / 2) / iw,
        (cyg[..., None] + wh[None, None, :, 1] / 2) / ih,
    ], axis=-1)  # [fh, fw, P, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), out.shape)
    return {"Boxes": out, "Variances": var}


@register_op("yolo_box", grad=None)
def yolo_box(ins, attrs, ctx):
    """reference: detection/yolo_box_op.cc."""
    x, img_size = ins["X"][0], ins["ImgSize"][0]
    anchors = [int(a) for a in attrs["anchors"]]
    class_num = int(attrs["class_num"])
    conf_thresh = attrs.get("conf_thresh", 0.01)
    downsample = int(attrs.get("downsample_ratio", 32))
    n, c, h, w = x.shape
    an_num = len(anchors) // 2
    x = x.reshape(n, an_num, 5 + class_num, h, w)
    grid_x = jnp.arange(w).reshape(1, 1, 1, w)
    grid_y = jnp.arange(h).reshape(1, 1, h, 1)
    import jax

    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    aw = jnp.asarray(anchors[0::2]).reshape(1, an_num, 1, 1)
    ah = jnp.asarray(anchors[1::2]).reshape(1, an_num, 1, 1)
    input_size = downsample * h
    bw = jnp.exp(x[:, :, 2]) * aw / input_size
    bh = jnp.exp(x[:, :, 3]) * ah / input_size
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    img_h = img_size[:, 0].reshape(n, 1, 1, 1).astype(x.dtype)
    img_w = img_size[:, 1].reshape(n, 1, 1, 1).astype(x.dtype)
    boxes = jnp.stack([
        (bx - bw / 2) * img_w, (by - bh / 2) * img_h,
        (bx + bw / 2) * img_w, (by + bh / 2) * img_h,
    ], axis=-1)
    keep = (conf > conf_thresh)[..., None]
    boxes = jnp.where(keep, boxes, 0.0).reshape(n, -1, 4)
    scores = jnp.where(conf[..., None] > conf_thresh,
                       probs.transpose(0, 1, 3, 4, 2), 0.0).reshape(n, -1, class_num)
    return {"Boxes": boxes, "Scores": scores}


@register_op("roi_align")
def roi_align(ins, attrs, ctx):
    """reference: detection/roi_align_op.cc — bilinear-sampled ROI pooling."""
    import jax

    x, rois = ins["X"][0], ins["ROIs"][0]  # x: [N,C,H,W], rois: [R,4]
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = attrs.get("spatial_scale", 1.0)
    ratio = int(attrs.get("sampling_ratio", -1))
    if ratio <= 0:
        ratio = 2
    n, c, h, w = x.shape

    def one_roi(roi):
        x1, y1, x2, y2 = roi * scale
        rh = jnp.maximum(y2 - y1, 1.0)
        rw = jnp.maximum(x2 - x1, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        ys = y1 + (jnp.arange(ph * ratio) + 0.5) * bin_h / ratio
        xs = x1 + (jnp.arange(pw * ratio) + 0.5) * bin_w / ratio
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = ys - jnp.floor(ys)
        wx = xs - jnp.floor(xs)
        # feat: [C, ph*ratio, pw*ratio] bilinear
        f = (x[0, :, y0][:, :, x0] * ((1 - wy)[None, :, None] * (1 - wx)[None, None, :])
             + x[0, :, y1i][:, :, x0] * (wy[None, :, None] * (1 - wx)[None, None, :])
             + x[0, :, y0][:, :, x1i] * ((1 - wy)[None, :, None] * wx[None, None, :])
             + x[0, :, y1i][:, :, x1i] * (wy[None, :, None] * wx[None, None, :]))
        return jnp.mean(f.reshape(c, ph, ratio, pw, ratio), axis=(2, 4))

    out = jax.vmap(one_roi)(rois)
    return {"Out": out}


@register_op("box_clip", grad=None)
def box_clip(ins, attrs, ctx):
    boxes, im_info = ins["Input"][0], ins["ImInfo"][0]
    h = im_info[0, 0] - 1
    w = im_info[0, 1] - 1
    return {"Output": jnp.stack([
        jnp.clip(boxes[..., 0], 0, w), jnp.clip(boxes[..., 1], 0, h),
        jnp.clip(boxes[..., 2], 0, w), jnp.clip(boxes[..., 3], 0, h)], axis=-1)}
