"""Detection ops (reference: paddle/fluid/operators/detection/ — 28 ops).

Coverage: geometry (box_coder, prior_box, density_prior_box,
anchor_generator, iou_similarity, box_clip, polygon_box_transform,
box_decoder_and_assign), matching/assignment (bipartite_match,
target_assign, mine_hard_examples, rpn_target_assign), losses
(sigmoid_focal_loss, yolov3_loss), ROI pooling (roi_align, roi_pool,
psroi_pool), and the NMS family (multiclass_nms, generate_proposals,
retinanet_detection_output, collect/distribute_fpn_proposals, yolo_box).

TPU-native design note: the reference's NMS/proposal ops emit LoD tensors
with per-image dynamic counts; XLA needs static shapes, so these ops emit
fixed-capacity outputs padded with -1 labels / zero rows plus an explicit
count (NmsRoisNum/RoisNum), and NMS itself is a fixed-length argmax-and-
suppress scan (_nms_static) — identical selection order to NMSFast.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import _DYN_SENTINEL, register_op


@register_op("iou_similarity", grad=None)
def iou_similarity(ins, attrs, ctx):
    x, y = ins["X"][0], ins["Y"][0]  # [N,4],[M,4] xyxy
    area_x = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    area_y = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    return {"Out": inter / (area_x[:, None] + area_y[None, :] - inter + 1e-10)}


@register_op("box_coder", grad=None)
def box_coder(ins, attrs, ctx):
    """reference: detection/box_coder_op.cc."""
    prior, tb = ins["PriorBox"][0], ins["TargetBox"][0]
    code_type = attrs.get("code_type", "encode_center_size")
    norm = attrs.get("box_normalized", True)
    pv = ins.get("PriorBoxVar")
    pv = pv[0] if pv and pv[0] is not None else None
    one = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + one
    ph = prior[:, 3] - prior[:, 1] + one
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        tw = tb[:, None, 2] - tb[:, None, 0] + one
        th = tb[:, None, 3] - tb[:, None, 1] + one
        tcx = tb[:, None, 0] + tw * 0.5
        tcy = tb[:, None, 1] + th * 0.5
        ox = (tcx - pcx) / pw
        oy = (tcy - pcy) / ph
        ow = jnp.log(jnp.abs(tw / pw))
        oh = jnp.log(jnp.abs(th / ph))
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
        if pv is not None:
            out = out / pv[None, :, :]
        return {"OutputBox": out}
    # decode_center_size
    if tb.ndim == 2:
        tb = tb[:, None, :]
    var = pv[None, :, :] if pv is not None else 1.0
    t = tb * var if pv is not None else tb
    ocx = t[..., 0] * pw + pcx
    ocy = t[..., 1] * ph + pcy
    ow = jnp.exp(t[..., 2]) * pw
    oh = jnp.exp(t[..., 3]) * ph
    out = jnp.stack([ocx - ow / 2, ocy - oh / 2,
                     ocx + ow / 2 - one, ocy + oh / 2 - one], axis=-1)
    return {"OutputBox": out}


@register_op("prior_box", grad=None)
def prior_box(ins, attrs, ctx):
    """reference: detection/prior_box_op.cc (SSD anchors)."""
    inp, image = ins["Input"][0], ins["Image"][0]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [float(a) for a in attrs.get("aspect_ratios", [1.0])]
    flip = attrs.get("flip", False)
    clip = attrs.get("clip", False)
    variances = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    offset = attrs.get("offset", 0.5)
    ih, iw = image.shape[2], image.shape[3]
    fh, fw = inp.shape[2], inp.shape[3]
    sw = attrs.get("step_w", 0.0) or iw / fw
    sh = attrs.get("step_h", 0.0) or ih / fh

    full_ars = []
    for a in ars:
        full_ars.append(a)
        if flip and a != 1.0:
            full_ars.append(1.0 / a)

    boxes = []
    for ms_i, ms in enumerate(min_sizes):
        for a in full_ars:
            boxes.append((ms * np.sqrt(a), ms / np.sqrt(a)))
            if a == 1.0 and ms_i < len(max_sizes):
                s = np.sqrt(ms * max_sizes[ms_i])
                boxes.append((s, s))
    num_priors = len(boxes)
    cx = (jnp.arange(fw) + offset) * sw
    cy = (jnp.arange(fh) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)
    wh = jnp.asarray(boxes)  # [P, 2]
    out = jnp.stack([
        (cxg[..., None] - wh[None, None, :, 0] / 2) / iw,
        (cyg[..., None] - wh[None, None, :, 1] / 2) / ih,
        (cxg[..., None] + wh[None, None, :, 0] / 2) / iw,
        (cyg[..., None] + wh[None, None, :, 1] / 2) / ih,
    ], axis=-1)  # [fh, fw, P, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), out.shape)
    return {"Boxes": out, "Variances": var}


@register_op("yolo_box", grad=None)
def yolo_box(ins, attrs, ctx):
    """reference: detection/yolo_box_op.cc."""
    x, img_size = ins["X"][0], ins["ImgSize"][0]
    anchors = [int(a) for a in attrs["anchors"]]
    class_num = int(attrs["class_num"])
    conf_thresh = attrs.get("conf_thresh", 0.01)
    downsample = int(attrs.get("downsample_ratio", 32))
    n, c, h, w = x.shape
    an_num = len(anchors) // 2
    x = x.reshape(n, an_num, 5 + class_num, h, w)
    grid_x = jnp.arange(w).reshape(1, 1, 1, w)
    grid_y = jnp.arange(h).reshape(1, 1, h, 1)
    import jax

    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    aw = jnp.asarray(anchors[0::2]).reshape(1, an_num, 1, 1)
    ah = jnp.asarray(anchors[1::2]).reshape(1, an_num, 1, 1)
    input_size = downsample * h
    bw = jnp.exp(x[:, :, 2]) * aw / input_size
    bh = jnp.exp(x[:, :, 3]) * ah / input_size
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    img_h = img_size[:, 0].reshape(n, 1, 1, 1).astype(x.dtype)
    img_w = img_size[:, 1].reshape(n, 1, 1, 1).astype(x.dtype)
    boxes = jnp.stack([
        (bx - bw / 2) * img_w, (by - bh / 2) * img_h,
        (bx + bw / 2) * img_w, (by + bh / 2) * img_h,
    ], axis=-1)
    keep = (conf > conf_thresh)[..., None]
    boxes = jnp.where(keep, boxes, 0.0).reshape(n, -1, 4)
    scores = jnp.where(conf[..., None] > conf_thresh,
                       probs.transpose(0, 1, 3, 4, 2), 0.0).reshape(n, -1, class_num)
    return {"Boxes": boxes, "Scores": scores}


def _require_single_image(op_name, x, ctx):
    """All roi ops in this repo share the pools-image-0 convention (ROIs
    carry no batch-index column); reject N>1 loudly instead of silently
    pooling the wrong image. Under shape inference only, -1 batch dims
    appear as the registry's _DYN_SENTINEL stand-in and are let through
    — at execution time the concrete batch is enforced unconditionally."""
    if ctx.in_shape_inference and x.shape[0] == _DYN_SENTINEL:
        return
    assert x.shape[0] == 1, (
        f"{op_name}: ROIs carry no batch index (the repo-wide roi-op "
        f"convention pools image 0), so N must be 1; got N={x.shape[0]}")


@register_op("roi_align")
def roi_align(ins, attrs, ctx):
    """reference: detection/roi_align_op.cc — bilinear-sampled ROI pooling."""
    import jax

    x, rois = ins["X"][0], ins["ROIs"][0]  # x: [N,C,H,W], rois: [R,4]
    _require_single_image("roi_align", x, ctx)
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = attrs.get("spatial_scale", 1.0)
    ratio = int(attrs.get("sampling_ratio", -1))
    if ratio <= 0:
        ratio = 2
    n, c, h, w = x.shape

    def one_roi(roi):
        x1, y1, x2, y2 = roi * scale
        rh = jnp.maximum(y2 - y1, 1.0)
        rw = jnp.maximum(x2 - x1, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        ys = y1 + (jnp.arange(ph * ratio) + 0.5) * bin_h / ratio
        xs = x1 + (jnp.arange(pw * ratio) + 0.5) * bin_w / ratio
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = ys - jnp.floor(ys)
        wx = xs - jnp.floor(xs)
        # feat: [C, ph*ratio, pw*ratio] bilinear. Index in two steps —
        # x[0, :, y0] would put the advanced-index axis FIRST (scalar and
        # array indices separated by a slice), silently mis-broadcasting
        # for C > 1.
        xc = x[0]                                     # [C, H, W]
        f00 = xc[:, y0][:, :, x0]
        f10 = xc[:, y1i][:, :, x0]
        f01 = xc[:, y0][:, :, x1i]
        f11 = xc[:, y1i][:, :, x1i]
        f = (f00 * ((1 - wy)[None, :, None] * (1 - wx)[None, None, :])
             + f10 * (wy[None, :, None] * (1 - wx)[None, None, :])
             + f01 * ((1 - wy)[None, :, None] * wx[None, None, :])
             + f11 * (wy[None, :, None] * wx[None, None, :]))
        return jnp.mean(f.reshape(c, ph, ratio, pw, ratio), axis=(2, 4))

    out = jax.vmap(one_roi)(rois)
    return {"Out": out}


def _tent_integral(lo, hi, centers):
    """∫_{lo}^{hi} max(0, 1-|y-c|) dy for each pixel center c — the exact
    integral of the bilinear-interpolation basis over a window (PrRoI
    pooling's closed form; reference prroi_pool_op.h
    PrRoIPoolingMatCalculation accumulates the same cell-wise integrals)."""
    def G(u):  # antiderivative of the tent evaluated at offset u
        return jnp.where(
            u <= -1.0, 0.0,
            jnp.where(u < 0.0, (u + 1.0) ** 2 / 2.0,
                      jnp.where(u < 1.0, 1.0 - (1.0 - u) ** 2 / 2.0, 1.0)))
    return G(hi[:, None] - centers[None, :]) - G(lo[:, None] - centers[None, :])


@register_op("prroi_pool", nondiff_inputs=("ROIs",))
def prroi_pool(ins, attrs, ctx):
    """reference: prroi_pool_op.cc — precise (integral) position-sensitive
    RoI pooling: out[r,c,i,j] = ∫∫_bin x[(c*ph+i)*pw+j] / bin_area, the
    integral taken over the bilinearly-interpolated feature surface.
    Computed exactly as two separable tent-integral weight matrices
    contracted on the MXU (no sampling-point approximation)."""
    x, rois = ins["X"][0], ins["ROIs"][0]      # x: [N,C,H,W], rois: [R,4]
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    oc = int(attrs.get("output_channels", x.shape[1] // (ph * pw)))
    n, c, h, w = x.shape
    _require_single_image("prroi_pool", x, ctx)
    assert c == oc * ph * pw, (
        f"prroi_pool input channels {c} != output_channels*ph*pw "
        f"{oc * ph * pw}")
    xr = x[0].reshape(oc, ph, pw, h, w)
    hs = jnp.arange(h, dtype=x.dtype)
    ws = jnp.arange(w, dtype=x.dtype)

    def one(roi):
        x1, y1, x2, y2 = roi * scale
        rh = jnp.maximum(y2 - y1, 0.0)
        rw = jnp.maximum(x2 - x1, 0.0)
        bin_h, bin_w = rh / ph, rw / pw
        ylo = y1 + jnp.arange(ph, dtype=x.dtype) * bin_h
        xlo = x1 + jnp.arange(pw, dtype=x.dtype) * bin_w
        wh = _tent_integral(ylo, ylo + bin_h, hs)     # [ph, H]
        ww = _tent_integral(xlo, xlo + bin_w, ws)     # [pw, W]
        win = bin_h * bin_w
        out = jnp.einsum("cijhw,ih,jw->cij", xr, wh, ww)
        return jnp.where(win > 0.0, out / jnp.maximum(win, 1e-12), 0.0)

    return {"Out": jax.vmap(one)(rois)}


@register_op("deformable_psroi_pooling", nondiff_inputs=("ROIs",))
def deformable_psroi_pooling(ins, attrs, ctx):
    """reference: deformable_psroi_pooling_op.h
    DeformablePSROIPoolForwardCPUKernel — position-sensitive RoI pooling
    whose bin starts are shifted by learned per-part offsets (Trans),
    averaged over a sample_per_part^2 grid of bilinear taps; samples
    falling outside [-0.5, size-0.5] are excluded from the mean."""
    x, rois = ins["Input"][0], ins["ROIs"][0]
    trans = (ins.get("Trans") or [None])[0]
    no_trans = bool(attrs.get("no_trans", trans is None))
    scale = float(attrs.get("spatial_scale", 1.0))
    out_dim = int(attrs["output_dim"])
    gh_, gw_ = [int(v) for v in attrs.get("group_size", [1, 1])]
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    part = attrs.get("part_size", [ph, pw]) or [ph, pw]
    part_h, part_w = int(part[0]), int(part[1])
    spp = int(attrs.get("sample_per_part", 4))
    tstd = float(attrs.get("trans_std", 0.1))
    n, c, H, W = x.shape
    _require_single_image("deformable_psroi_pooling", x, ctx)
    n_classes = 1 if (no_trans or trans is None) else trans.shape[1] // 2
    ceach = out_dim // n_classes
    x0 = x[0]
    fdt = x.dtype

    iy = jnp.arange(ph)
    jx = jnp.arange(pw)
    part_hi = jnp.floor(iy.astype(fdt) / ph * part_h).astype(jnp.int32)
    part_wi = jnp.floor(jx.astype(fdt) / pw * part_w).astype(jnp.int32)
    ghi = jnp.clip(jnp.floor(iy.astype(fdt) * gh_ / ph).astype(jnp.int32),
                   0, gh_ - 1)
    gwi = jnp.clip(jnp.floor(jx.astype(fdt) * gw_ / pw).astype(jnp.int32),
                   0, gw_ - 1)
    ctop = jnp.arange(out_dim)
    class_id = ctop // ceach
    # channel map per output cell: (ctop*gh + gh_i)*gw + gw_i
    cidx = ((ctop[:, None, None] * gh_ + ghi[None, :, None]) * gw_
            + gwi[None, None, :])                        # [od, ph, pw]

    def one(roi, tr):
        rsw = jnp.round(roi[0]) * scale - 0.5
        rsh = jnp.round(roi[1]) * scale - 0.5
        rew = (jnp.round(roi[2]) + 1.0) * scale - 0.5
        reh = (jnp.round(roi[3]) + 1.0) * scale - 0.5
        rw = jnp.maximum(rew - rsw, 0.1)
        rh = jnp.maximum(reh - rsh, 0.1)
        bh, bw = rh / ph, rw / pw
        if no_trans or tr is None:
            tx = ty = jnp.zeros((out_dim, ph, pw), fdt)
        else:
            tx = tr[class_id * 2][:, part_hi][:, :, part_wi] * tstd
            ty = tr[class_id * 2 + 1][:, part_hi][:, :, part_wi] * tstd
        hstart = iy.astype(fdt)[None, :, None] * bh + rsh + ty * rh
        wstart = jx.astype(fdt)[None, None, :] * bw + rsw + tx * rw
        # sample grid [od, ph, pw, spp, spp]
        sh = hstart[..., None, None] + \
            jnp.arange(spp, dtype=fdt)[None, None, None, :, None] * (bh / spp)
        sw = wstart[..., None, None] + \
            jnp.arange(spp, dtype=fdt)[None, None, None, None, :] * (bw / spp)
        sh, sw = jnp.broadcast_to(sh, sh.shape[:3] + (spp, spp)), \
            jnp.broadcast_to(sw, sw.shape[:3] + (spp, spp))
        valid = ((sw >= -0.5) & (sw <= W - 0.5)
                 & (sh >= -0.5) & (sh <= H - 0.5))
        shc = jnp.clip(sh, 0.0, H - 1.0)
        swc = jnp.clip(sw, 0.0, W - 1.0)

        from .nn import _bilinear_sample_chw
        maps = x0[cidx.reshape(-1)]                      # [M, H, W]
        vals = jax.vmap(
            lambda m, yy, xx: _bilinear_sample_chw(m[None], yy, xx)[0])(
                maps, shc.reshape(-1, spp, spp), swc.reshape(-1, spp, spp))
        vals = vals.reshape(out_dim, ph, pw, spp, spp)
        cnt = valid.sum((-1, -2))
        s = (vals * valid.astype(fdt)).sum((-1, -2))
        out = jnp.where(cnt > 0, s / jnp.maximum(cnt, 1).astype(fdt), 0.0)
        return out, cnt.astype(fdt)

    if trans is None:
        out, count = jax.vmap(lambda r: one(r, None))(rois)
    else:
        out, count = jax.vmap(one)(rois, trans)
    return {"Output": out, "TopCount": count}


@register_op("box_clip", grad=None)
def box_clip(ins, attrs, ctx):
    boxes, im_info = ins["Input"][0], ins["ImInfo"][0]
    h = im_info[0, 0] - 1
    w = im_info[0, 1] - 1
    return {"Output": jnp.stack([
        jnp.clip(boxes[..., 0], 0, w), jnp.clip(boxes[..., 1], 0, h),
        jnp.clip(boxes[..., 2], 0, w), jnp.clip(boxes[..., 3], 0, h)], axis=-1)}


# ---------------------------------------------------------------------------
# Shared geometry helpers
# ---------------------------------------------------------------------------


def _box_area(b, normalized=True):
    one = 0.0 if normalized else 1.0
    return (b[..., 2] - b[..., 0] + one) * (b[..., 3] - b[..., 1] + one)


def _pairwise_iou(a, b, normalized=True):
    """IoU matrix [.., M, N] of boxes a [.., M, 4] and b [.., N, 4]."""
    lt = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    rb = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    one = 0.0 if normalized else 1.0
    wh = jnp.maximum(rb - lt + one, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = (_box_area(a, normalized)[..., :, None] +
             _box_area(b, normalized)[..., None, :] - inter)
    return inter / jnp.maximum(union, 1e-10)


def _nms_static(boxes, scores, iou_threshold, max_out, normalized=True,
                score_threshold=None):
    """Static-shape greedy NMS: returns (indices [max_out] int32, padded
    with -1, and selected scores). The reference's NMSFast prunes a
    dynamically sized list; here a fixed-length scan picks argmax and
    suppresses per step — identical selection order, XLA-compilable."""
    if score_threshold is not None:
        scores = jnp.where(scores > score_threshold, scores, -jnp.inf)

    def step(masked_scores, _):
        i = jnp.argmax(masked_scores)
        valid = masked_scores[i] > -jnp.inf
        iou = _pairwise_iou(boxes[i][None], boxes, normalized)[0]
        suppress = (iou > iou_threshold) | \
            (jnp.arange(boxes.shape[0]) == i)
        new_scores = jnp.where(suppress, -jnp.inf, masked_scores)
        return new_scores, (jnp.where(valid, i, -1).astype(jnp.int32),
                            jnp.where(valid, masked_scores[i], -jnp.inf))

    _, (idx, sel_scores) = jax.lax.scan(step, scores, None, length=max_out)
    return idx, sel_scores


# ---------------------------------------------------------------------------
# Losses / assignment / anchors
# ---------------------------------------------------------------------------


@register_op("sigmoid_focal_loss", nondiff_inputs=("Label", "FgNum"))
def sigmoid_focal_loss(ins, attrs, ctx):
    """reference: detection/sigmoid_focal_loss_op.cc — per-element focal
    loss; Label holds the 1-based foreground class (0 = background), class
    j of X corresponds to label j+1; normalized by FgNum."""
    x = ins["X"][0]                       # [N, C]
    label = ins["Label"][0].reshape(-1)   # [N]
    fg = ins["FgNum"][0].reshape(()).astype(x.dtype)
    gamma = float(attrs.get("gamma", 2.0))
    alpha = float(attrs.get("alpha", 0.25))
    n, c = x.shape
    t = (label[:, None] == jnp.arange(1, c + 1)[None, :]).astype(x.dtype)
    p = jax.nn.sigmoid(x)
    # stable log σ / log(1-σ)
    logp = jax.nn.log_sigmoid(x)
    log1mp = jax.nn.log_sigmoid(-x)
    loss = -(t * alpha * (1 - p) ** gamma * logp +
             (1 - t) * (1 - alpha) * p ** gamma * log1mp)
    return {"Out": loss / jnp.maximum(fg, 1.0)}


@register_op("anchor_generator", grad=None)
def anchor_generator(ins, attrs, ctx):
    """reference: detection/anchor_generator_op.h:55-85 (exact rounding
    behavior of base_w/base_h preserved)."""
    x = ins["Input"][0]                  # [N, C, H, W]
    sizes = [float(s) for s in attrs["anchor_sizes"]]
    ratios = [float(r) for r in attrs["aspect_ratios"]]
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    stride = [float(s) for s in attrs["stride"]]
    offset = float(attrs.get("offset", 0.5))
    h, w = x.shape[2], x.shape[3]
    sw, sh = stride[0], stride[1]

    anchors = []
    for ar in ratios:
        for size in sizes:
            area = sw * sh
            base_w = np.round(np.sqrt(area / ar))
            base_h = np.round(base_w * ar)
            anchor_w = (size / sw) * base_w
            anchor_h = (size / sh) * base_h
            anchors.append((anchor_w, anchor_h))
    aw = jnp.asarray([a[0] for a in anchors])
    ah = jnp.asarray([a[1] for a in anchors])
    x_ctr = jnp.arange(w) * sw + offset * (sw - 1)
    y_ctr = jnp.arange(h) * sh + offset * (sh - 1)
    xc = x_ctr[None, :, None]
    yc = y_ctr[:, None, None]
    out = jnp.stack(
        jnp.broadcast_arrays(xc - 0.5 * (aw - 1), yc - 0.5 * (ah - 1),
                             xc + 0.5 * (aw - 1), yc + 0.5 * (ah - 1)),
        axis=-1)                          # [H, W, A, 4]
    var = jnp.broadcast_to(jnp.asarray(variances), out.shape)
    return {"Anchors": out, "Variances": var}


@register_op("density_prior_box", grad=None)
def density_prior_box(ins, attrs, ctx):
    """reference: detection/density_prior_box_op.cc — dense anchor grid
    per (fixed_size, density) with uniform sub-cell shifts."""
    x = ins["Input"][0]
    image = ins["Image"][0]
    fixed_sizes = [float(s) for s in attrs.get("fixed_sizes", [])]
    fixed_ratios = [float(r) for r in attrs.get("fixed_ratios", [1.0])]
    densities = [int(d) for d in attrs.get("densities", [1])]
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    offset = float(attrs.get("offset", 0.5))
    clip = bool(attrs.get("clip", False))
    h, w = x.shape[2], x.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    step_w = attrs.get("step_w", 0.0) or img_w / w
    step_h = attrs.get("step_h", 0.0) or img_h / h

    boxes = []
    for k, (size, density) in enumerate(zip(fixed_sizes, densities)):
        for ratio in fixed_ratios:
            bw = size * np.sqrt(ratio)
            bh = size / np.sqrt(ratio)
            shift = size / density
            for di in range(density):
                for dj in range(density):
                    cx_off = -size / 2.0 + shift / 2.0 + dj * shift
                    cy_off = -size / 2.0 + shift / 2.0 + di * shift
                    boxes.append((cx_off, cy_off, bw, bh))
    cx = (jnp.arange(w) + offset) * step_w
    cy = (jnp.arange(h) + offset) * step_h
    cxg = cx[None, :, None]
    cyg = cy[:, None, None]
    offs = jnp.asarray(boxes)             # [A, 4]
    ax = cxg + offs[:, 0]
    ay = cyg + offs[:, 1]
    bw = offs[:, 2]
    bh = offs[:, 3]
    out = jnp.stack(jnp.broadcast_arrays(
        (ax - bw / 2.0) / img_w, (ay - bh / 2.0) / img_h,
        (ax + bw / 2.0) / img_w, (ay + bh / 2.0) / img_h), axis=-1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), out.shape)
    return {"Boxes": out, "Variances": var}


@register_op("bipartite_match", grad=None)
def bipartite_match(ins, attrs, ctx):
    """reference: detection/bipartite_match_op.cc — greedy global-max
    matching (columns→rows), then optional per_prediction argmax fill for
    unmatched columns above dist_threshold. DistMat [N, R, C] batched
    (replaces the LoD convention)."""
    dist = ins["DistMat"][0]
    if dist.ndim == 2:
        dist = dist[None]
    b, r, c = dist.shape
    match_type = attrs.get("match_type", "bipartite")
    thresh = float(attrs.get("dist_threshold", 0.5))

    def one(d):
        def step(carry, _):
            dm, midx, mdist = carry
            flat = jnp.argmax(dm)
            i, j = flat // c, flat % c
            ok = dm[i, j] > 0
            midx = jnp.where(ok, midx.at[j].set(i.astype(jnp.int32)), midx)
            mdist = jnp.where(ok, mdist.at[j].set(dm[i, j]), mdist)
            dm = jnp.where(ok, dm.at[i, :].set(-1.0).at[:, j].set(-1.0), dm)
            return (dm, midx, mdist), None

        init = (d, jnp.full((c,), -1, jnp.int32), jnp.zeros((c,), d.dtype))
        (dm, midx, mdist), _ = jax.lax.scan(
            step, init, None, length=min(r, c))
        if match_type == "per_prediction":
            best_row = jnp.argmax(d, axis=0).astype(jnp.int32)
            best = jnp.max(d, axis=0)
            fill = (midx < 0) & (best > thresh)
            midx = jnp.where(fill, best_row, midx)
            mdist = jnp.where(fill, best, mdist)
        return midx, mdist

    midx, mdist = jax.vmap(one)(dist)
    return {"ColToRowMatchIndices": midx, "ColToRowMatchDist": mdist}


@register_op("target_assign", grad=None)
def target_assign(ins, attrs, ctx):
    """reference: detection/target_assign_op.cc — out[i,j] =
    X[i, match[i,j]] where matched, else mismatch_value; weight 1 on
    matched (and negative-flagged) columns. X is [N, M, K] batched;
    NegFlag [N, P] replaces the reference's LoD NegIndices."""
    x = ins["X"][0]
    match = ins["MatchIndices"][0]
    mismatch = attrs.get("mismatch_value", 0)
    if x.ndim == 2:
        x = x[None]
    idx = jnp.maximum(match, 0)
    out = jnp.take_along_axis(x, idx[:, :, None].astype(jnp.int32), axis=1)
    matched = (match >= 0)[:, :, None]
    out = jnp.where(matched, out, jnp.asarray(mismatch, x.dtype))
    wt = matched.astype(x.dtype)
    if ins.get("NegFlag") and ins["NegFlag"][0] is not None:
        wt = jnp.maximum(wt, ins["NegFlag"][0][:, :, None].astype(x.dtype))
    return {"Out": out, "OutWeight": wt}


@register_op("mine_hard_examples", grad=None)
def mine_hard_examples(ins, attrs, ctx):
    """reference: detection/mine_hard_examples_op.cc — online hard negative
    mining: among unmatched priors, flag the neg_pos_ratio*num_pos highest-
    loss ones as negatives. Outputs NegFlag [N, P] (static stand-in for the
    LoD NegIndices) + UpdatedMatchIndices."""
    cls_loss = ins["ClsLoss"][0]
    match = ins["MatchIndices"][0]
    loss = cls_loss.reshape(match.shape)
    if ins.get("LocLoss") and ins["LocLoss"][0] is not None and \
            attrs.get("mining_type", "max_negative") == "hard_example":
        loss = loss + ins["LocLoss"][0].reshape(match.shape)
    neg_pos_ratio = float(attrs.get("neg_pos_ratio", 3.0))
    neg_overlap = float(attrs.get("neg_dist_threshold", 0.5))
    del neg_overlap  # overlap filtering happens upstream via MatchDist
    n, p = match.shape
    is_neg_cand = match < 0
    num_pos = jnp.sum(match >= 0, axis=1)
    num_neg = jnp.minimum((num_pos * neg_pos_ratio).astype(jnp.int32),
                          jnp.sum(is_neg_cand, axis=1))
    cand_loss = jnp.where(is_neg_cand, loss, -jnp.inf)
    order = jnp.argsort(-cand_loss, axis=1)
    rank = jnp.argsort(order, axis=1)      # rank of each column by loss
    neg_flag = (rank < num_neg[:, None]) & is_neg_cand
    return {"NegFlag": neg_flag.astype(jnp.int32),
            "UpdatedMatchIndices": match}


# ---------------------------------------------------------------------------
# Pooling / geometry transforms
# ---------------------------------------------------------------------------


@register_op("roi_pool")
def roi_pool(ins, attrs, ctx):
    """reference: roi_pool_op.cc — max pooling over quantized ROI bins."""
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    n, c, h, w = x.shape
    _require_single_image("roi_pool", x, ctx)

    def one_roi(roi):
        x1 = jnp.round(roi[0] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[2] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        # bin edges per pooled cell (quantized, reference semantics)
        py = jnp.arange(ph)
        px = jnp.arange(pw)
        hs = y1 + jnp.floor(py * rh / ph).astype(jnp.int32)
        he = y1 + jnp.ceil((py + 1) * rh / ph).astype(jnp.int32)
        ws = x1 + jnp.floor(px * rw / pw).astype(jnp.int32)
        we = x1 + jnp.ceil((px + 1) * rw / pw).astype(jnp.int32)
        yy = jnp.arange(h)[None, :]
        xx = jnp.arange(w)[None, :]
        ymask = (yy >= hs[:, None]) & (yy < he[:, None]) & \
            (yy >= 0) & (yy < h)                       # [ph, H]
        xmask = (xx >= ws[:, None]) & (xx < we[:, None]) & \
            (xx >= 0) & (xx < w)                       # [pw, W]
        m = ymask[:, None, :, None] & xmask[None, :, None, :]  # [ph,pw,H,W]
        neg = jnp.asarray(-jnp.inf, x.dtype)
        vals = jnp.where(m[None], x[0][:, None, None, :, :], neg)
        out = jnp.max(vals, axis=(-2, -1))             # [C, ph, pw]
        empty = ~jnp.any(m, axis=(-2, -1))
        return jnp.where(empty[None], 0.0, out)

    return {"Out": jax.vmap(one_roi)(rois)}


@register_op("psroi_pool")
def psroi_pool(ins, attrs, ctx):
    """reference: detection/psroi_pool_op.cc — position-sensitive average
    ROI pooling: output channel d at bin (i,j) averages input channel
    d*ph*pw + i*pw + j over that bin."""
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    out_c = int(attrs["output_channels"])
    scale = float(attrs.get("spatial_scale", 1.0))
    n, c, h, w = x.shape
    _require_single_image("psroi_pool", x, ctx)

    def one_roi(roi):
        x1 = jnp.round(roi[0]) * scale
        y1 = jnp.round(roi[1]) * scale
        x2 = jnp.round(roi[2] + 1.0) * scale
        y2 = jnp.round(roi[3] + 1.0) * scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bh, bw_ = rh / ph, rw / pw
        py = jnp.arange(ph)
        px = jnp.arange(pw)
        hs = jnp.floor(y1 + py * bh).astype(jnp.int32)
        he = jnp.ceil(y1 + (py + 1) * bh).astype(jnp.int32)
        ws = jnp.floor(x1 + px * bw_).astype(jnp.int32)
        we = jnp.ceil(x1 + (px + 1) * bw_).astype(jnp.int32)
        yy = jnp.arange(h)[None, :]
        xx = jnp.arange(w)[None, :]
        ymask = (yy >= jnp.clip(hs, 0, h)[:, None]) & \
            (yy < jnp.clip(he, 0, h)[:, None])
        xmask = (xx >= jnp.clip(ws, 0, w)[:, None]) & \
            (xx < jnp.clip(we, 0, w)[:, None])
        m = (ymask[:, None, :, None] & xmask[None, :, None, :]).astype(
            x.dtype)                                     # [ph,pw,H,W]
        # channel layout: input channel for (d, i, j) = d*ph*pw + i*pw + j
        xc = x[0].reshape(out_c, ph * pw, h, w)
        grid = xc.reshape(out_c, ph, pw, h, w)
        s = jnp.sum(grid * m[None], axis=(-2, -1))
        cnt = jnp.sum(m, axis=(-2, -1))
        return s / jnp.maximum(cnt, 1.0)[None]

    return {"Out": jax.vmap(one_roi)(rois)}


@register_op("polygon_box_transform", grad=None)
def polygon_box_transform(ins, attrs, ctx):
    """reference: detection/polygon_box_transform_op.cc — for OCR EAST:
    output(id_plane, h, w) = 4*w_coord ± input offset: even planes are x
    offsets (x = 4*w - in), odd are y (y = 4*h - in)."""
    x = ins["Input"][0]                  # [N, geo_channels, H, W]
    n, c, h, w = x.shape
    wg = jnp.arange(w, dtype=x.dtype)[None, :]
    hg = jnp.arange(h, dtype=x.dtype)[:, None]
    even = jnp.arange(c) % 2 == 0
    base = jnp.where(even[:, None, None], 4 * wg[None], 4 * hg[None])
    return {"Output": base[None] - x}


@register_op("box_decoder_and_assign", grad=None)
def box_decoder_and_assign(ins, attrs, ctx):
    """reference: detection/box_decoder_and_assign_op.cc — decode per-class
    deltas against prior boxes, then pick each ROI's best-scoring class
    box."""
    prior = ins["PriorBox"][0]            # [R, 4]
    pv = ins["PriorBoxVar"][0]            # [R, 4] or attr-less
    deltas = ins["TargetBox"][0]          # [R, 4*C]
    scores = ins["BoxScore"][0]           # [R, C]
    box_clip = float(attrs.get("box_clip", 4.135))
    r, c4 = deltas.shape
    ncls = c4 // 4
    d = deltas.reshape(r, ncls, 4) * pv[:, None, :]
    pw = prior[:, 2] - prior[:, 0] + 1.0
    ph = prior[:, 3] - prior[:, 1] + 1.0
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    ocx = d[..., 0] * pw[:, None] + pcx[:, None]
    ocy = d[..., 1] * ph[:, None] + pcy[:, None]
    ow = jnp.exp(jnp.minimum(d[..., 2], box_clip)) * pw[:, None]
    oh = jnp.exp(jnp.minimum(d[..., 3], box_clip)) * ph[:, None]
    decoded = jnp.stack([ocx - ow / 2, ocy - oh / 2,
                         ocx + ow / 2 - 1.0, ocy + oh / 2 - 1.0], axis=-1)
    best = jnp.argmax(scores, axis=1)
    assigned = jnp.take_along_axis(
        decoded, best[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    return {"DecodeBox": decoded.reshape(r, c4),
            "OutputAssignBox": assigned}


# ---------------------------------------------------------------------------
# NMS family / proposals
# ---------------------------------------------------------------------------


def _multiclass_nms_alias(ins, attrs, ctx):
    return multiclass_nms(ins, attrs, ctx)


@register_op("multiclass_nms", grad=None)
def multiclass_nms(ins, attrs, ctx):
    """reference: detection/multiclass_nms_op.cc. Static-shape output:
    the reference emits a LoD tensor of per-image variable detection
    counts; here Out is [N, keep_top_k, 6] ([label, score, x1,y1,x2,y2],
    padded entries label=-1) plus NmsRoisNum [N]."""
    bboxes = ins["BBoxes"][0]             # [N, M, 4]
    scores = ins["Scores"][0]             # [N, C, M]
    bg = int(attrs.get("background_label", 0))
    score_thr = float(attrs.get("score_threshold", 0.0))
    nms_top_k = int(attrs.get("nms_top_k", -1))
    nms_thr = float(attrs.get("nms_threshold", 0.3))
    keep_top_k = int(attrs.get("keep_top_k", 100))
    normalized = bool(attrs.get("normalized", True))
    n, c, m = scores.shape
    per_class = min(m, nms_top_k) if nms_top_k > 0 else m
    # clamp to the flat candidate pool (reference keeps at most that many)
    n_fg_cls = c - (1 if 0 <= bg < c else 0)
    pool = n_fg_cls * per_class
    # keep_top_k=-1 means "keep everything" (the pool is the static bound)
    keep_top_k = pool if keep_top_k <= 0 else min(keep_top_k, pool)

    def one_image(boxes, sc):
        def one_class(cls_scores):
            s = cls_scores
            if nms_top_k > 0 and nms_top_k < m:
                top_s, top_i = jax.lax.top_k(s, nms_top_k)
                cb = boxes[top_i]
                idx, ss = _nms_static(cb, top_s, nms_thr, per_class,
                                      normalized, score_thr)
                idx = jnp.where(idx >= 0, top_i[jnp.maximum(idx, 0)], -1)
            else:
                idx, ss = _nms_static(boxes, s, nms_thr, per_class,
                                      normalized, score_thr)
            return idx, ss

        cls_ids = jnp.asarray([cc for cc in range(c) if cc != bg],
                              jnp.int32)
        idxs, sss = jax.vmap(one_class)(sc[cls_ids])  # [C', K], [C', K]
        labels = jnp.broadcast_to(cls_ids[:, None], idxs.shape)
        flat_s = sss.reshape(-1)
        flat_i = idxs.reshape(-1)
        flat_l = labels.reshape(-1)
        top_s, order = jax.lax.top_k(flat_s, keep_top_k)
        sel_i = flat_i[order]
        sel_l = flat_l[order]
        valid = (top_s > -jnp.inf) & (sel_i >= 0)
        sel_boxes = boxes[jnp.maximum(sel_i, 0)]
        out = jnp.concatenate([
            jnp.where(valid, sel_l, -1).astype(boxes.dtype)[:, None],
            jnp.where(valid, top_s, 0.0)[:, None],
            jnp.where(valid[:, None], sel_boxes, 0.0)], axis=1)
        return out, jnp.sum(valid.astype(jnp.int32)), \
            jnp.where(valid, sel_i, -1)

    out, num, sel = jax.vmap(one_image)(bboxes, scores)
    # Index: selected box row in the batch-flattened [N*M, 4] boxes
    # (reference multiclass_nms2's Index over the LoD-flattened input);
    # -1 marks padding rows
    gidx = jnp.where(sel >= 0,
                     sel + jnp.arange(n)[:, None] * m, -1)[..., None]
    return {"Out": out, "NmsRoisNum": num,
            "Index": gidx.astype(jnp.int32)}


@register_op("generate_proposals", grad=None)
def generate_proposals(ins, attrs, ctx):
    """reference: detection/generate_proposals_op.cc — RPN: decode anchor
    deltas, clip to image, filter small boxes, NMS. Static shapes: outputs
    RpnRois [N, post_nms_topN, 4], RpnRoiProbs [N, post_nms_topN, 1],
    RpnRoisNum [N] (invalid rows zeroed)."""
    scores = ins["Scores"][0]             # [N, A, H, W]
    deltas = ins["BboxDeltas"][0]         # [N, 4A, H, W]
    im_info = ins["ImInfo"][0]            # [N, 3] (h, w, scale)
    anchors = ins["Anchors"][0].reshape(-1, 4)     # [H*W*A, 4]
    variances = ins["Variances"][0].reshape(-1, 4)
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thr = float(attrs.get("nms_thresh", 0.7))
    min_size = float(attrs.get("min_size", 0.1))
    n, a, h, w = scores.shape
    total = a * h * w
    pre_n = min(pre_n, total)

    # [N, A, H, W] -> [N, H*W*A] matching anchors' [H, W, A] layout
    sc = scores.transpose(0, 2, 3, 1).reshape(n, -1)
    dl = deltas.reshape(n, a, 4, h, w).transpose(0, 3, 4, 1, 2).reshape(
        n, -1, 4)

    def one(s, d, info):
        top_s, top_i = jax.lax.top_k(s, pre_n)
        anc = anchors[top_i]
        var = variances[top_i]
        dd = d[top_i] * var
        pw = anc[:, 2] - anc[:, 0] + 1.0
        ph = anc[:, 3] - anc[:, 1] + 1.0
        pcx = anc[:, 0] + pw * 0.5
        pcy = anc[:, 1] + ph * 0.5
        ocx = dd[:, 0] * pw + pcx
        ocy = dd[:, 1] * ph + pcy
        ow = jnp.exp(jnp.minimum(dd[:, 2], 10.0)) * pw
        oh = jnp.exp(jnp.minimum(dd[:, 3], 10.0)) * ph
        boxes = jnp.stack([ocx - ow / 2, ocy - oh / 2,
                           ocx + ow / 2 - 1.0, ocy + oh / 2 - 1.0], -1)
        ih, iw = info[0], info[1]
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, iw - 1),
                           jnp.clip(boxes[:, 1], 0, ih - 1),
                           jnp.clip(boxes[:, 2], 0, iw - 1),
                           jnp.clip(boxes[:, 3], 0, ih - 1)], -1)
        ms = min_size * info[2]
        keep = ((boxes[:, 2] - boxes[:, 0] + 1.0) >= ms) & \
               ((boxes[:, 3] - boxes[:, 1] + 1.0) >= ms)
        s_kept = jnp.where(keep, top_s, -jnp.inf)
        idx, ss = _nms_static(boxes, s_kept, nms_thr, post_n,
                              normalized=False)
        valid = idx >= 0
        rois = jnp.where(valid[:, None], boxes[jnp.maximum(idx, 0)], 0.0)
        probs = jnp.where(valid, ss, 0.0)[:, None]
        return rois, probs, jnp.sum(valid.astype(jnp.int32))

    rois, probs, num = jax.vmap(one)(sc, dl, im_info)
    return {"RpnRois": rois, "RpnRoiProbs": probs, "RpnRoisNum": num}


@register_op("collect_fpn_proposals", grad=None)
def collect_fpn_proposals(ins, attrs, ctx):
    """reference: detection/collect_fpn_proposals_op.cc — concat per-level
    RoIs, keep global top post_nms_topN by score.

    Static-shape convention: per-level inputs may be zero-padded (the
    generate_proposals output style); the optional MultiLevelRoisNum input
    ([N] valid-count per image per level) masks padded rows to -inf score
    so they are never selected, and RoisNum reports the true number of
    valid collected proposals."""
    rois_in = [r for r in ins["MultiLevelRois"] if r is not None]
    scores_in = [s for s in ins["MultiLevelScores"] if s is not None]
    counts_in = [c for c in (ins.get("MultiLevelRoisNum") or [])
                 if c is not None]
    # accept [R,4] (single image) or [N,R,4] (batched); top-k per image
    if rois_in[0].ndim == 2:
        rois_in = [r[None] for r in rois_in]
        scores_in = [s.reshape(1, -1) for s in scores_in]
        squeeze = True
    else:
        squeeze = False
    rois = jnp.concatenate([r.reshape(r.shape[0], -1, 4)
                            for r in rois_in], axis=1)      # [N, R, 4]
    scores = jnp.concatenate([s.reshape(s.shape[0], -1)
                              for s in scores_in], axis=1)  # [N, R]
    if counts_in:
        assert len(counts_in) == len(scores_in), (
            f"MultiLevelRoisNum must supply one count per level: got "
            f"{len(counts_in)} counts for {len(scores_in)} score levels")
        level_masks = []
        for c, s in zip(counts_in, scores_in):
            r = s.reshape(s.shape[0], -1).shape[1]
            c = jnp.asarray(c).reshape(-1).astype(jnp.int32)
            level_masks.append(jnp.arange(r)[None, :] < c[:, None])
        valid = jnp.concatenate(level_masks, axis=1)        # [N, R]
        scores = jnp.where(valid, scores, -jnp.inf)
    post_n = min(int(attrs.get("post_nms_topN", 100)), scores.shape[1])

    def one(ro, sc):
        top_s, top_i = jax.lax.top_k(sc, post_n)
        ok = top_s > -jnp.inf
        return jnp.where(ok[:, None], ro[top_i], 0.0), \
            jnp.sum(ok.astype(jnp.int32))

    out, num = jax.vmap(one)(rois, scores)
    return {"FpnRois": out[0] if squeeze else out, "RoisNum": num}


@register_op("distribute_fpn_proposals", grad=None)
def distribute_fpn_proposals(ins, attrs, ctx):
    """reference: detection/distribute_fpn_proposals_op.cc — route each RoI
    to FPN level floor(log2(sqrt(area)/refer_scale)) + refer_level,
    clipped to [min_level, max_level]. Static shapes: each level output is
    [R, 4] with a LevelMask instead of variable-size splits; RestoreIndex
    maps sorted-by-level order back."""
    rois = ins["FpnRois"][0].reshape(-1, 4)
    min_l = int(attrs.get("min_level", 2))
    max_l = int(attrs.get("max_level", 5))
    refer_l = int(attrs.get("refer_level", 4))
    refer_s = float(attrs.get("refer_scale", 224.0))
    r = rois.shape[0]
    scale = jnp.sqrt(_box_area(rois, normalized=False))
    lvl = jnp.floor(jnp.log2(scale / refer_s + 1e-6)) + refer_l
    lvl = jnp.clip(lvl, min_l, max_l).astype(jnp.int32)
    outs = {"MultiFpnRois": [], "MultiLevelMask": []}
    for L in range(min_l, max_l + 1):
        m = (lvl == L)
        outs["MultiFpnRois"].append(jnp.where(m[:, None], rois, 0.0))
        outs["MultiLevelMask"].append(m.astype(jnp.int32))
    order = jnp.argsort(lvl, stable=True)
    restore = jnp.argsort(order, stable=True).astype(jnp.int32)
    outs["RestoreIndex"] = restore[:, None]
    return outs


@register_op("rpn_target_assign", is_random=True, grad=None)
def rpn_target_assign(ins, attrs, ctx):
    """reference: detection/rpn_target_assign_op.cc — label anchors fg/bg
    by IoU against gt boxes and subsample a fixed batch. Static shapes:
    LocationIndex/ScoreIndex are fixed-capacity with -1 padding;
    TargetLabel aligns with ScoreIndex (1 fg / 0 bg)."""
    anchors = ins["Anchor"][0].reshape(-1, 4)      # [A, 4]
    gt = ins["GtBoxes"][0].reshape(-1, 4)          # [G, 4]
    batch = int(attrs.get("rpn_batch_size_per_im", 256))
    fg_frac = float(attrs.get("rpn_fg_fraction", 0.5))
    pos_thr = float(attrs.get("rpn_positive_overlap", 0.7))
    neg_thr = float(attrs.get("rpn_negative_overlap", 0.3))
    use_random = bool(attrs.get("use_random", True))
    a = anchors.shape[0]
    iou = _pairwise_iou(anchors, gt, normalized=False)     # [A, G]
    best_iou = jnp.max(iou, axis=1)
    # fg: IoU >= pos_thr, plus the best anchor for each gt
    fg_mask = best_iou >= pos_thr
    best_anchor_per_gt = jnp.argmax(iou, axis=0)
    fg_mask = fg_mask.at[best_anchor_per_gt].set(True)
    bg_mask = (best_iou < neg_thr) & ~fg_mask

    # quotas can't exceed the anchor count (top_k requires k <= size)
    n_fg = min(int(batch * fg_frac), a)
    n_bg = min(batch - n_fg, a)
    key = ctx.rng() if use_random else None

    def sample(mask, k, n_out):
        noise = jax.random.uniform(k, (a,)) if k is not None else \
            -jnp.arange(a, dtype=jnp.float32)
        score = jnp.where(mask, noise, -jnp.inf)
        top_s, top_i = jax.lax.top_k(score, n_out)
        return jnp.where(top_s > -jnp.inf, top_i, -1).astype(jnp.int32)

    if key is not None:
        kf, kb = jax.random.split(key)
    else:
        kf = kb = None
    fg_idx = sample(fg_mask, kf, n_fg)
    bg_idx = sample(bg_mask, kb, n_bg)
    score_idx = jnp.concatenate([fg_idx, bg_idx])
    labels = jnp.concatenate([(fg_idx >= 0).astype(jnp.int32),
                              jnp.zeros((n_bg,), jnp.int32)])
    # regression targets for fg anchors: encode their best gt
    best_gt = jnp.argmax(iou, axis=1)
    anc = anchors[jnp.maximum(fg_idx, 0)]
    g = gt[best_gt[jnp.maximum(fg_idx, 0)]]
    pw = anc[:, 2] - anc[:, 0] + 1.0
    ph = anc[:, 3] - anc[:, 1] + 1.0
    pcx = anc[:, 0] + pw * 0.5
    pcy = anc[:, 1] + ph * 0.5
    gw = g[:, 2] - g[:, 0] + 1.0
    gh = g[:, 3] - g[:, 1] + 1.0
    gcx = g[:, 0] + gw * 0.5
    gcy = g[:, 1] + gh * 0.5
    tgt = jnp.stack([(gcx - pcx) / pw, (gcy - pcy) / ph,
                     jnp.log(gw / pw), jnp.log(gh / ph)], axis=-1)
    tgt = jnp.where((fg_idx >= 0)[:, None], tgt, 0.0)
    return {"LocationIndex": fg_idx, "ScoreIndex": score_idx,
            "TargetBBox": tgt,
            "TargetLabel": labels[:, None],
            "BBoxInsideWeight": (fg_idx >= 0)[:, None]
            .astype(anchors.dtype) * jnp.ones((1, 4), anchors.dtype)}


@register_op("retinanet_detection_output", grad=None)
def retinanet_detection_output(ins, attrs, ctx):
    """reference: detection/retinanet_detection_output_op.cc — decode each
    FPN level's top candidates against its anchors, merge levels, then
    class-wise NMS (reuses the multiclass machinery, static shapes)."""
    bboxes = ins["BBoxes"]                 # list of [N, Ai, 4] deltas
    scores = ins["Scores"]                 # list of [N, Ai, C]
    anchors = ins["Anchors"]               # list of [Ai, 4]
    im_info = ins["ImInfo"][0]
    score_thr = float(attrs.get("score_threshold", 0.05))
    nms_top_k = int(attrs.get("nms_top_k", 1000))
    nms_thr = float(attrs.get("nms_threshold", 0.3))
    keep_top_k = int(attrs.get("keep_top_k", 100))

    all_boxes, all_scores = [], []
    for delta, sc, anc in zip(bboxes, scores, anchors):
        if delta is None:
            continue
        anc = anc.reshape(-1, 4)
        pw = anc[:, 2] - anc[:, 0] + 1.0
        ph = anc[:, 3] - anc[:, 1] + 1.0
        pcx = anc[:, 0] + pw * 0.5
        pcy = anc[:, 1] + ph * 0.5
        d = delta
        ocx = d[..., 0] * pw + pcx
        ocy = d[..., 1] * ph + pcy
        ow = jnp.exp(jnp.minimum(d[..., 2], 10.0)) * pw
        oh = jnp.exp(jnp.minimum(d[..., 3], 10.0)) * ph
        box = jnp.stack([ocx - ow / 2, ocy - oh / 2,
                         ocx + ow / 2 - 1.0, ocy + oh / 2 - 1.0], -1)
        all_boxes.append(box)
        all_scores.append(sc)
    boxes = jnp.concatenate(all_boxes, axis=1)       # [N, A, 4]
    sc = jnp.concatenate(all_scores, axis=1)         # [N, A, C]
    n, a, c = sc.shape
    cap = min(nms_top_k, a)
    sel_k = min(cap, keep_top_k)
    keep_k = min(keep_top_k, c * sel_k)   # can't keep more than the pool

    def one_image(bx, s, info):
        # clip to THIS image's extent
        ih, iw = info[0], info[1]
        bx = jnp.stack([jnp.clip(bx[..., 0], 0, iw - 1),
                        jnp.clip(bx[..., 1], 0, ih - 1),
                        jnp.clip(bx[..., 2], 0, iw - 1),
                        jnp.clip(bx[..., 3], 0, ih - 1)], -1)

        def one_class(cls_scores):
            top_s, top_i = jax.lax.top_k(cls_scores, cap)
            cb = bx[top_i]
            idx, ss = _nms_static(cb, top_s, nms_thr, sel_k,
                                  normalized=False,
                                  score_threshold=score_thr)
            sel = jnp.where(idx >= 0, top_i[jnp.maximum(idx, 0)], -1)
            return sel, ss

        idxs, sss = jax.vmap(one_class)(s.T)          # [C, K]
        labels = jnp.broadcast_to(jnp.arange(c)[:, None], idxs.shape)
        flat_s, flat_i = sss.reshape(-1), idxs.reshape(-1)
        flat_l = labels.reshape(-1)
        top_s, order = jax.lax.top_k(flat_s, keep_k)
        sel_i = flat_i[order]
        valid = (top_s > -jnp.inf) & (sel_i >= 0)
        out = jnp.concatenate([
            jnp.where(valid, flat_l[order], -1).astype(bx.dtype)[:, None],
            jnp.where(valid, top_s, 0.0)[:, None],
            jnp.where(valid[:, None], bx[jnp.maximum(sel_i, 0)], 0.0)],
            axis=1)
        return out, jnp.sum(valid.astype(jnp.int32))

    out, num = jax.vmap(one_image)(boxes, sc, im_info)
    return {"Out": out, "NmsRoisNum": num}


@register_op("yolov3_loss", nondiff_inputs=("GTBox", "GTLabel", "GTScore"))
def yolov3_loss(ins, attrs, ctx):
    """reference: detection/yolov3_loss_op.cc — per-cell YOLOv3 training
    loss: sigmoid x/y + w/h regression for the responsible anchor of each
    gt, objectness BCE with an ignore band, and per-class BCE."""
    x = ins["X"][0]                        # [N, A*(5+C), H, W]
    gtbox = ins["GTBox"][0]                # [N, B, 4] (cx, cy, w, h) / img
    gtlabel = ins["GTLabel"][0]            # [N, B]
    anchors = [float(v) for v in attrs["anchors"]]
    mask = [int(v) for v in attrs.get("anchor_mask",
                                      list(range(len(anchors) // 2)))]
    class_num = int(attrs["class_num"])
    ignore_thresh = float(attrs.get("ignore_thresh", 0.7))
    downsample = int(attrs.get("downsample_ratio", 32))
    use_label_smooth = bool(attrs.get("use_label_smooth", True))
    n, _, h, w = x.shape
    am = len(mask)
    x = x.reshape(n, am, 5 + class_num, h, w)
    input_size = downsample * h
    aw_all = jnp.asarray(anchors[0::2])
    ah_all = jnp.asarray(anchors[1::2])
    aw = aw_all[jnp.asarray(mask)]         # masked anchors on this scale
    ah = ah_all[jnp.asarray(mask)]

    tx = jax.nn.sigmoid(x[:, :, 0])        # [N, A, H, W]
    ty = jax.nn.sigmoid(x[:, :, 1])
    tw = x[:, :, 2]
    th = x[:, :, 3]
    tobj = x[:, :, 4]
    tcls = x[:, :, 5:]                     # [N, A, C, H, W]

    b = gtbox.shape[1]
    gx, gy = gtbox[..., 0], gtbox[..., 1]  # normalized centers
    gw, gh = gtbox[..., 2], gtbox[..., 3]
    valid_gt = (gw > 0) & (gh > 0)
    gi = jnp.clip((gx * w).astype(jnp.int32), 0, w - 1)    # [N, B]
    gj = jnp.clip((gy * h).astype(jnp.int32), 0, h - 1)

    # responsible anchor: best wh-IoU among ALL anchors; loss only if in mask
    gwp = gw * input_size
    ghp = gh * input_size
    inter = jnp.minimum(gwp[..., None], aw_all) * \
        jnp.minimum(ghp[..., None], ah_all)
    union = gwp[..., None] * ghp[..., None] + aw_all * ah_all - inter
    best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-10), -1)  # [N,B]
    mask_arr = jnp.asarray(mask)
    in_mask = (best_anchor[..., None] == mask_arr).any(-1)
    slot = jnp.argmax((best_anchor[..., None] == mask_arr), -1)      # [N,B]
    resp = valid_gt & in_mask

    # gather predictions at (slot, gj, gi) per gt
    def at(v):  # v [N, A, H, W] -> [N, B]
        return v[jnp.arange(n)[:, None], slot, gj, gi]

    # per-gt confidence weight (mixup): reference scales every gt's loss
    # terms by GTScore; defaults to 1
    if ins.get("GTScore") and ins["GTScore"][0] is not None:
        gscore = ins["GTScore"][0].reshape(gw.shape).astype(x.dtype)
    else:
        gscore = jnp.ones_like(gw)

    scale = (2.0 - gw * gh) * gscore      # box-size weighting (reference)
    loss_x = scale * _bce(at(tx), gx * w - gi.astype(gx.dtype))
    loss_y = scale * _bce(at(ty), gy * h - gj.astype(gy.dtype))
    # w/h use L1 loss (yolov3_loss_op.h:133-134)
    loss_w = scale * jnp.abs(at(tw) - jnp.log(jnp.maximum(
        gwp / aw[slot], 1e-9)))
    loss_h = scale * jnp.abs(at(th) - jnp.log(jnp.maximum(
        ghp / ah[slot], 1e-9)))
    loc = jnp.sum(jnp.where(resp, loss_x + loss_y + loss_w + loss_h, 0.0),
                  axis=1)

    # objectness: target 1 at responsible cells; ignore preds whose box IoU
    # with any gt exceeds ignore_thresh; all else target 0
    pbx = (tx + jnp.arange(w)) / w                           # [N,A,H,W]
    pby = (ty + jnp.arange(h)[:, None]) / h
    pbw = jnp.exp(jnp.clip(tw, -10, 10)) * aw[None, :, None, None] / \
        input_size
    pbh = jnp.exp(jnp.clip(th, -10, 10)) * ah[None, :, None, None] / \
        input_size
    px1, py1 = pbx - pbw / 2, pby - pbh / 2
    px2, py2 = pbx + pbw / 2, pby + pbh / 2
    gx1, gy1 = gx - gw / 2, gy - gh / 2
    gx2, gy2 = gx + gw / 2, gy + gh / 2
    ix1 = jnp.maximum(px1[..., None], gx1[:, None, None, None, :])
    iy1 = jnp.maximum(py1[..., None], gy1[:, None, None, None, :])
    ix2 = jnp.minimum(px2[..., None], gx2[:, None, None, None, :])
    iy2 = jnp.minimum(py2[..., None], gy2[:, None, None, None, :])
    iw_ = jnp.maximum(ix2 - ix1, 0.0)
    ih_ = jnp.maximum(iy2 - iy1, 0.0)
    inter_o = iw_ * ih_
    area_p = pbw * pbh
    area_g = (gw * gh)[:, None, None, None, :]
    iou_o = inter_o / jnp.maximum(area_p[..., None] + area_g - inter_o,
                                  1e-10)
    iou_o = jnp.where(valid_gt[:, None, None, None, :], iou_o, 0.0)
    ignore = jnp.max(iou_o, axis=-1) > ignore_thresh         # [N,A,H,W]
    obj_target = jnp.zeros_like(tobj)
    obj_target = obj_target.at[jnp.arange(n)[:, None], slot, gj, gi].max(
        jnp.where(resp, 1.0, 0.0))
    # positive cells carry their gt's mixup score as the BCE weight
    # (scatter-max into zeros — a ones base would absorb scores < 1)
    pos_score = jnp.zeros_like(tobj).at[
        jnp.arange(n)[:, None], slot, gj, gi].max(
        jnp.where(resp, gscore, 0.0))
    obj_w = jnp.where((obj_target > 0) | ~ignore, 1.0, 0.0) * \
        jnp.where(obj_target > 0, pos_score, 1.0)
    obj = jnp.sum(_bce(jax.nn.sigmoid(tobj), obj_target) * obj_w,
                  axis=(1, 2, 3))

    # classification at responsible cells; label smoothing per
    # yolov3_loss_op.h:282-287: pos = 1 - w, neg = w, w = min(1/C, 1/40)
    delta = min(1.0 / class_num, 1.0 / 40.0) if use_label_smooth else 0.0
    cls_t = (gtlabel[..., None] == jnp.arange(class_num)).astype(x.dtype)
    cls_t = cls_t * (1.0 - 2.0 * delta) + delta
    pcls = jax.nn.sigmoid(
        tcls[jnp.arange(n)[:, None], slot, :, gj, gi])       # [N, B, C]
    cls = jnp.sum(jnp.where(resp[..., None],
                            _bce(pcls, cls_t) * gscore[..., None], 0.0),
                  axis=(1, 2))
    return {"Loss": loc + obj + cls,
            "ObjectnessMask": obj_w, "GTMatchMask": resp.astype(jnp.int32)}


def _bce(p, t):
    p = jnp.clip(p, 1e-7, 1.0 - 1e-7)
    return -(t * jnp.log(p) + (1.0 - t) * jnp.log(1.0 - p))


@register_op("generate_proposal_labels", is_random=True, grad=None)
def generate_proposal_labels(ins, attrs, ctx):
    """reference: detection/generate_proposal_labels_op.cc — sample RoIs
    for the RCNN head: fg above fg_thresh (capped at fg_fraction·batch),
    bg in [bg_thresh_lo, bg_thresh_hi), per-class box targets. Static
    shapes: per image exactly batch_size_per_im rows, label -1 padding.
    Inputs are batched dense ([N,R,4] rois, [N,G,4] gt, [N,G] classes,
    gt rows with class 0 = absent)."""
    rois = ins["RpnRois"][0]            # [N, R, 4]
    gt_boxes = ins["GtBoxes"][0]        # [N, G, 4]
    gt_classes = ins["GtClasses"][0]    # [N, G] int (0 = pad)
    if ins.get("IsCrowd") and ins["IsCrowd"][0] is not None:
        is_crowd = ins["IsCrowd"][0].astype(jnp.bool_)
    else:
        is_crowd = jnp.zeros(gt_classes.shape, jnp.bool_)
    if rois.ndim == 2:
        rois, gt_boxes, gt_classes = rois[None], gt_boxes[None], \
            gt_classes[None]
        is_crowd = is_crowd.reshape(gt_classes.shape)
    batch = int(attrs.get("batch_size_per_im", 256))
    fg_frac = float(attrs.get("fg_fraction", 0.25))
    fg_thr = float(attrs.get("fg_thresh", 0.5))
    bg_hi = float(attrs.get("bg_thresh_hi", 0.5))
    bg_lo = float(attrs.get("bg_thresh_lo", 0.0))
    num_classes = int(attrs.get("class_nums", 81))
    weights = [float(v) for v in attrs.get("bbox_reg_weights",
                                           [0.1, 0.1, 0.2, 0.2])]
    use_random = bool(attrs.get("use_random", True))
    n, r, _ = rois.shape
    batch = min(batch, r)
    n_fg_max = int(batch * fg_frac)
    key = ctx.rng() if use_random else None

    def one(rois_i, gt_i, cls_i, crowd_i, k):
        # crowd gt regions are excluded from matching entirely
        # (reference: generate_proposal_labels filters IsCrowd rows)
        valid_gt = (cls_i > 0) & ~crowd_i
        iou = _pairwise_iou(rois_i, gt_i, normalized=False)
        iou = jnp.where(valid_gt[None, :], iou, 0.0)   # [R, G]
        best = jnp.max(iou, axis=1)
        best_gt = jnp.argmax(iou, axis=1)
        fg_mask = best >= fg_thr
        bg_mask = (best < bg_hi) & (best >= bg_lo) & ~fg_mask
        if k is not None:
            kf, kb = jax.random.split(k)
        else:
            kf = kb = None

        def sample(mask, kk, cap):
            noise = jax.random.uniform(kk, (r,)) if kk is not None else \
                -jnp.arange(r, dtype=jnp.float32)
            score = jnp.where(mask, noise, -jnp.inf)
            top_s, top_i = jax.lax.top_k(score, cap)
            return jnp.where(top_s > -jnp.inf, top_i, -1)

        fg_idx = sample(fg_mask, kf, n_fg_max)
        bg_idx = sample(bg_mask, kb, batch - n_fg_max)
        idx = jnp.concatenate([fg_idx, bg_idx])
        ok = idx >= 0
        gather = jnp.maximum(idx, 0)
        out_rois = jnp.where(ok[:, None], rois_i[gather], 0.0)
        is_fg = jnp.concatenate([fg_idx >= 0,
                                 jnp.zeros((batch - n_fg_max,), bool)])
        labels = jnp.where(
            ok,
            jnp.where(is_fg, cls_i[best_gt[gather]].astype(jnp.int32), 0),
            -1)
        # per-class box targets: encode roi -> matched gt in the 4-slot of
        # its class
        anc = rois_i[gather]
        g = gt_i[best_gt[gather]]
        pw = anc[:, 2] - anc[:, 0] + 1.0
        ph = anc[:, 3] - anc[:, 1] + 1.0
        pcx = anc[:, 0] + pw * 0.5
        pcy = anc[:, 1] + ph * 0.5
        gw = g[:, 2] - g[:, 0] + 1.0
        gh = g[:, 3] - g[:, 1] + 1.0
        gcx = g[:, 0] + gw * 0.5
        gcy = g[:, 1] + gh * 0.5
        # BoxToDelta divides by bbox_reg_weights (reference default
        # 0.1/0.1/0.2/0.2 -> 10x/5x scaling)
        wvec = jnp.asarray(weights, rois_i.dtype)
        tgt = jnp.stack([(gcx - pcx) / pw, (gcy - pcy) / ph,
                         jnp.log(gw / pw), jnp.log(gh / ph)], -1) / wvec
        tgt = jnp.where(is_fg[:, None], tgt, 0.0)
        cls_slot = jnp.maximum(labels, 0)
        onehot = (jnp.arange(num_classes)[None, :] ==
                  cls_slot[:, None]).astype(rois_i.dtype)  # [B, C]
        bbox_targets = (onehot[:, :, None] * tgt[:, None, :]).reshape(
            batch, 4 * num_classes)
        inside_w = jnp.repeat(onehot, 4, axis=1) * \
            is_fg[:, None].astype(rois_i.dtype)
        return out_rois, labels, bbox_targets, inside_w

    keys = jax.random.split(key, n) if key is not None else [None] * n
    if key is not None:
        out_rois, labels, tgts, inw = jax.vmap(one)(
            rois, gt_boxes, gt_classes, is_crowd, keys)
    else:
        outs = [one(rois[i], gt_boxes[i], gt_classes[i], is_crowd[i],
                    None) for i in range(n)]
        out_rois, labels, tgts, inw = (jnp.stack(v) for v in zip(*outs))
    return {"Rois": out_rois, "LabelsInt32": labels,
            "BboxTargets": tgts, "BboxInsideWeights": inw,
            "BboxOutsideWeights": inw}


@register_op("generate_mask_labels", grad=None)
def generate_mask_labels(ins, attrs, ctx):
    """reference: detection/generate_mask_labels_op.cc — per fg RoI, crop
    its matched instance mask and resize to resolution². TPU-native: gt
    masks arrive as dense bitmaps GtSegms [G, H, W] (the reference takes
    polygons and rasterizes on the host; bitmaps keep it in-graph), RoIs
    [R, 4] with LabelsInt32 [R] (-1/0 rows skipped), MatchedGts [R]."""
    masks = ins["GtSegms"][0]           # [G, H, W]
    rois = ins["Rois"][0]               # [R, 4]
    labels = ins["LabelsInt32"][0].reshape(-1)
    matched = ins["MatchedGts"][0].reshape(-1).astype(jnp.int32)
    res = int(attrs.get("resolution", 14))
    g, h, w = masks.shape

    def one(roi, gt_idx, lab):
        m = masks[jnp.maximum(gt_idx, 0)].astype(jnp.float32)
        x1, y1, x2, y2 = roi
        ys = y1 + (jnp.arange(res) + 0.5) / res * jnp.maximum(y2 - y1, 1.0)
        xs = x1 + (jnp.arange(res) + 0.5) / res * jnp.maximum(x2 - x1, 1.0)
        yi = jnp.clip(ys.astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(xs.astype(jnp.int32), 0, w - 1)
        crop_m = m[yi][:, xi]
        valid = lab > 0
        return jnp.where(valid, (crop_m > 0.5).astype(jnp.int32), -1)

    out = jax.vmap(one)(rois, matched, labels)
    return {"MaskInt32": out}


@register_op("roi_perspective_transform", grad=None)
def roi_perspective_transform(ins, attrs, ctx):
    """reference: detection/roi_perspective_transform_op.cc — warp each
    quadrilateral ROI (8 coords: 4 corners clockwise) to a fixed
    [H_out, W_out] patch by bilinear sampling along the bilinear
    interpolation of the quad edges."""
    x = ins["X"][0]                     # [1, C, H, W]
    rois = ins["ROIs"][0]               # [R, 8]
    oh = int(attrs.get("transformed_height", 8))
    ow = int(attrs.get("transformed_width", 8))
    scale = float(attrs.get("spatial_scale", 1.0))
    n, c, h, w = x.shape
    _require_single_image("roi_perspective_transform", x, ctx)

    def one(quad):
        q = quad.reshape(4, 2) * scale   # tl, tr, br, bl
        u = (jnp.arange(ow) + 0.5) / ow
        v = (jnp.arange(oh) + 0.5) / oh
        uu, vv = jnp.meshgrid(u, v)      # [oh, ow]
        top = q[0][None, None] * (1 - uu)[..., None] + \
            q[1][None, None] * uu[..., None]
        bot = q[3][None, None] * (1 - uu)[..., None] + \
            q[2][None, None] * uu[..., None]
        pts = top * (1 - vv)[..., None] + bot * vv[..., None]  # [oh,ow,2]
        px, py = pts[..., 0], pts[..., 1]
        x0 = jnp.clip(jnp.floor(px).astype(jnp.int32), 0, w - 1)
        y0 = jnp.clip(jnp.floor(py).astype(jnp.int32), 0, h - 1)
        x1 = jnp.clip(x0 + 1, 0, w - 1)
        y1 = jnp.clip(y0 + 1, 0, h - 1)
        wx = px - jnp.floor(px)
        wy = py - jnp.floor(py)
        img = x[0]                       # [C, H, W]
        f = (img[:, y0, x0] * ((1 - wy) * (1 - wx))[None] +
             img[:, y1, x0] * (wy * (1 - wx))[None] +
             img[:, y0, x1] * ((1 - wy) * wx)[None] +
             img[:, y1, x1] * (wy * wx)[None])
        inside = (px >= 0) & (px <= w - 1) & (py >= 0) & (py <= h - 1)
        return jnp.where(inside[None], f, 0.0)   # [C, oh, ow]

    return {"Out": jax.vmap(one)(rois), "Out2InIdx": None,
            "Out2InWeights": None, "Mask": None, "TransformMatrix": None}


# ---------------------------------------------------------------------------
# detection_map — in-graph streaming mAP (reference: detection_map_op.cc)
# ---------------------------------------------------------------------------


def _np_detection_map_update(dets, gts, pos_count, tps, fps,
                             overlap_threshold, evaluate_difficult,
                             ap_type, class_num, cap):
    """Host kernel: reference detection_map_op.h semantics on padded
    numpy buffers. dets [B,M,6] (label<0 = pad), gts [B,G,6]
    (label,x1,y1,x2,y2,difficult; label<0 = pad). State buffers:
    pos_count [C,1], tps/fps [C,cap,2] with score<0 marking free slots."""
    import numpy as np

    def iou(a, b):
        ix1, iy1 = max(a[0], b[0]), max(a[1], b[1])
        ix2, iy2 = min(a[2], b[2]), min(a[3], b[3])
        iw, ih = max(ix2 - ix1, 0.0), max(iy2 - iy1, 0.0)
        inter = iw * ih
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    pos_count = pos_count.copy()
    lists = {c: ([list(p) for p in tps[c] if p[0] >= 0],
                 [list(p) for p in fps[c] if p[0] >= 0])
             for c in range(class_num)}

    for b in range(dets.shape[0]):
        # rows with label < 0 are padding; labels >= class_num are invalid
        # and dropped (a crash inside pure_callback would surface as an
        # opaque XlaRuntimeError)
        img_gts = [g for g in gts[b] if 0 <= g[0] < class_num]
        img_dets = [d for d in dets[b] if 0 <= d[0] < class_num]
        # per-class gt count (difficult excluded unless evaluate_difficult)
        for g in img_gts:
            c = int(g[0])
            difficult = bool(g[5]) if g.shape[0] > 5 else False
            if evaluate_difficult or not difficult:
                pos_count[c, 0] += 1
        by_class = {}
        for d in img_dets:
            by_class.setdefault(int(d[0]), []).append(d)
        for c, ds in by_class.items():
            cgts = [[tuple(g[1:5]),
                     bool(g[5]) if g.shape[0] > 5 else False, False]
                    for g in img_gts if int(g[0]) == c]
            tp_l, fp_l = lists.setdefault(c, ([], []))
            for d in sorted(ds, key=lambda r: -r[1]):
                score, box = float(d[1]), tuple(d[2:6])
                best, best_g = 0.0, None
                for g in cgts:
                    i = iou(box, g[0])
                    if i > best:
                        best, best_g = i, g
                if best >= overlap_threshold and best_g is not None:
                    if not evaluate_difficult and best_g[1]:
                        continue           # difficult gt: ignored
                    if not best_g[2]:
                        best_g[2] = True
                        tp_l.append([score, 1.0])
                        fp_l.append([score, 0.0])
                    else:
                        tp_l.append([score, 0.0])
                        fp_l.append([score, 1.0])
                else:
                    tp_l.append([score, 0.0])
                    fp_l.append([score, 1.0])

    # mAP over classes with positives
    aps = []
    for c in range(class_num):
        npos = pos_count[c, 0]
        tp_l, fp_l = lists.get(c, ([], []))
        if npos == 0:
            continue
        if not tp_l:
            aps.append(0.0)
            continue
        order = np.argsort([-p[0] for p in tp_l], kind="stable")
        tp = np.cumsum([tp_l[i][1] for i in order])
        fp = np.cumsum([fp_l[i][1] for i in order])
        rec = tp / npos
        prec = tp / np.maximum(tp + fp, 1e-9)
        if ap_type == "11point":
            ap = sum((prec[rec >= t].max() if (rec >= t).any() else 0.0)
                     for t in np.linspace(0, 1, 11)) / 11.0
        else:
            ap, prev_rec = 0.0, 0.0
            for i in range(len(rec)):
                ap += prec[i] * (rec[i] - prev_rec)
                prev_rec = rec[i]
        aps.append(ap)
    m_ap = float(np.mean(aps)) if aps else 0.0

    def pack(ls):
        out = np.full((class_num, cap, 2), -1.0, np.float32)
        over = []
        for c in range(class_num):
            rows = lists.get(c, ([], []))[ls]
            if len(rows) > cap:
                over.append((c, len(rows)))
                rows = rows[:cap]
            for i, r in enumerate(rows):
                out[c, i] = r
        if over:
            import warnings

            warnings.warn(
                f"detection_map: {len(over)} classes exceeded "
                f"max_dets={cap} (worst: class {max(over, key=lambda t: t[1])[0]} "
                f"with {max(o[1] for o in over)} detections); streaming "
                f"state is truncated and mAP will drift — raise max_dets",
                RuntimeWarning)
        return out

    return (np.array([m_ap], np.float32), pos_count.astype(np.int32),
            pack(0), pack(1))


@register_op("detection_map", grad=None,
             nondiff_inputs=("DetectRes", "Label", "HasState", "PosCount",
                             "TruePos", "FalsePos"))
def detection_map(ins, attrs, ctx):
    """reference: detection_map_op.cc — in-graph streaming mAP.
    Static-shape redesign: DetectRes [B,M,6]/[M,6] and Label
    [B,G,6]/[G,6] are zero-padded with label=-1 rows; the accumulator
    state is fixed-capacity (attr `max_dets`, score<0 = free slot)
    instead of the reference's LoD-grown lists. The matching/AP math runs
    host-side through jax.pure_callback (the reference computes on CPU
    too)."""
    dets = ins["DetectRes"][0]
    gts = ins["Label"][0]
    if dets.ndim == 2:
        dets = dets[None]
    if gts.ndim == 2:
        gts = gts[None]
    class_num = int(attrs["class_num"])
    cap = int(attrs.get("max_dets", 256))
    thr = float(attrs.get("overlap_threshold", 0.5))
    ed = bool(attrs.get("evaluate_difficult", True))
    ap_type = str(attrs.get("ap_type", "integral"))

    pc_in = (ins.get("PosCount") or [None])[0]
    tp_in = (ins.get("TruePos") or [None])[0]
    fp_in = (ins.get("FalsePos") or [None])[0]
    has_state = (ins.get("HasState") or [None])[0]
    if pc_in is None:
        pc_in = jnp.zeros((class_num, 1), jnp.int32)
    if tp_in is None:
        tp_in = jnp.full((class_num, cap, 2), -1.0, jnp.float32)
    if fp_in is None:
        fp_in = jnp.full((class_num, cap, 2), -1.0, jnp.float32)
    if has_state is not None:
        # HasState==0 resets the accumulators (reference out_states init)
        keep = (has_state.reshape(()) != 0)
        pc_in = jnp.where(keep, pc_in, jnp.zeros_like(pc_in))
        tp_in = jnp.where(keep, tp_in, jnp.full_like(tp_in, -1.0))
        fp_in = jnp.where(keep, fp_in, jnp.full_like(fp_in, -1.0))

    result_shapes = (
        jax.ShapeDtypeStruct((1,), jnp.float32),
        jax.ShapeDtypeStruct((class_num, 1), jnp.int32),
        jax.ShapeDtypeStruct((class_num, cap, 2), jnp.float32),
        jax.ShapeDtypeStruct((class_num, cap, 2), jnp.float32),
    )

    def host(d, g, pc, tp, fp):
        import numpy as np
        return _np_detection_map_update(
            np.asarray(d, np.float64), np.asarray(g, np.float64),
            np.asarray(pc, np.int64), np.asarray(tp), np.asarray(fp),
            thr, ed, ap_type, class_num, cap)

    m_ap, pc, tp, fp = jax.pure_callback(
        host, result_shapes, dets, gts, pc_in, tp_in, fp_in)
    return {"MAP": m_ap, "AccumPosCount": pc, "AccumTruePos": tp,
            "AccumFalsePos": fp}


@register_op("ssd_loss", nondiff_inputs=("GtBox", "GtLabel", "PriorBox",
                                         "PriorBoxVar"))
def ssd_loss(ins, attrs, ctx):
    """reference: layers/detection.py `ssd_loss` (:1389) — fused here as
    one op (the reference composes iou_similarity → bipartite_match →
    target_assign → mine_hard_examples → softmax-CE + smooth-L1; XLA
    fuses the same dataflow without materializing the intermediates).
    Static shapes: GtBox [N,G,4] zero-padded, GtLabel [N,G] with -1 pad
    rows. Output Loss [N,P] = conf_w*conf + loc_w*loc per prior,
    normalized by total positives when `normalize`."""
    loc = ins["Location"][0]               # [N, P, 4]
    conf = ins["Confidence"][0]            # [N, P, C]
    gt_box = ins["GtBox"][0]               # [N, G, 4]
    gt_label = ins["GtLabel"][0]           # [N, G]
    prior = ins["PriorBox"][0]             # [P, 4]
    pvar = (ins.get("PriorBoxVar") or [None])[0]
    bg = int(attrs.get("background_label", 0))
    ovt = float(attrs.get("overlap_threshold", 0.5))
    npr = float(attrs.get("neg_pos_ratio", 3.0))
    neg_ov = float(attrs.get("neg_overlap", 0.5))
    loc_w = float(attrs.get("loc_loss_weight", 1.0))
    conf_w = float(attrs.get("conf_loss_weight", 1.0))
    normalize = bool(attrs.get("normalize", True))
    match_type = str(attrs.get("match_type", "per_prediction"))
    n, p, c = conf.shape
    if gt_label.ndim == 3:
        gt_label = gt_label[..., 0]
    gt_valid = gt_label >= 0                        # [N, G]

    def one(lb, cb, gb, gl, gv):
        # iou [G, P]; invalid gts can never win a prior
        area_g = (gb[:, 2] - gb[:, 0]) * (gb[:, 3] - gb[:, 1])
        area_p = (prior[:, 2] - prior[:, 0]) * (prior[:, 3] - prior[:, 1])
        lt = jnp.maximum(gb[:, None, :2], prior[None, :, :2])
        rb = jnp.minimum(gb[:, None, 2:], prior[None, :, 2:])
        wh = jnp.maximum(rb - lt, 0.0)
        inter = wh[..., 0] * wh[..., 1]
        iou = inter / (area_g[:, None] + area_p[None, :] - inter + 1e-10)
        iou = jnp.where(gv[:, None], iou, -1.0)
        # per-prediction match: each prior takes its best gt at >= ovt;
        # plus each gt's best prior is forced positive (bipartite seed)
        best_gt = jnp.argmax(iou, axis=0)           # [P]
        best_iou = jnp.max(iou, axis=0)
        if match_type == "per_prediction":
            match = jnp.where(best_iou >= ovt, best_gt, -1)
        else:
            # pure bipartite: only each gt's best prior is positive
            match = jnp.full((p,), -1, best_gt.dtype)
        best_prior = jnp.argmax(iou, axis=1)        # [G]
        # padded gts scatter out of range (dropped) so they can never
        # clobber a real gt's forced-positive prior
        scatter_at = jnp.where(gv, best_prior, p)
        forced = jnp.zeros((p,), jnp.int32).at[scatter_at].set(
            jnp.arange(iou.shape[0], dtype=jnp.int32) + 1, mode="drop") - 1
        match = jnp.where(forced >= 0, forced, match)
        pos = match >= 0                            # [P]

        # conf targets + full CE (for mining and the loss)
        tgt_label = jnp.where(pos, gl[jnp.maximum(match, 0)], bg)
        logp = jax.nn.log_softmax(cb.astype(jnp.float32), axis=-1)
        ce = -jnp.take_along_axis(
            logp, tgt_label[:, None].astype(jnp.int32), 1)[:, 0]  # [P]

        # max_negative mining: top ce among negatives with iou < neg_ov
        n_pos = jnp.sum(pos.astype(jnp.int32))
        n_neg_want = (npr * n_pos).astype(jnp.int32)
        neg_cand = (~pos) & (best_iou < neg_ov)
        neg_score = jnp.where(neg_cand, ce, -jnp.inf)
        order = jnp.argsort(-neg_score)
        rank = jnp.argsort(order)
        neg_sel = neg_cand & (rank < n_neg_want)

        conf_loss = ce * (pos | neg_sel).astype(ce.dtype)

        # smooth-L1 on encoded offsets, positives only
        gbm = gb[jnp.maximum(match, 0)]             # matched gt per prior
        one_ = 0.0
        pw = prior[:, 2] - prior[:, 0] + one_
        ph = prior[:, 3] - prior[:, 1] + one_
        pcx = prior[:, 0] + pw * 0.5
        pcy = prior[:, 1] + ph * 0.5
        tw = gbm[:, 2] - gbm[:, 0] + one_
        th = gbm[:, 3] - gbm[:, 1] + one_
        tcx = gbm[:, 0] + tw * 0.5
        tcy = gbm[:, 1] + th * 0.5
        enc = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                         jnp.log(jnp.maximum(tw / pw, 1e-10)),
                         jnp.log(jnp.maximum(th / ph, 1e-10))], axis=-1)
        if pvar is not None:
            enc = enc / pvar
        d = lb.astype(jnp.float32) - enc
        ad = jnp.abs(d)
        sl1 = jnp.where(ad < 1.0, 0.5 * d * d, ad - 0.5).sum(-1)
        loc_loss = sl1 * pos.astype(sl1.dtype)
        return conf_w * conf_loss + loc_w * loc_loss, n_pos

    loss, n_pos = jax.vmap(one)(loc, conf, gt_box, gt_label, gt_valid)
    if normalize:
        loss = loss / jnp.maximum(jnp.sum(n_pos), 1).astype(loss.dtype)
    return {"Loss": loss}


@register_op("retinanet_target_assign", is_random=True, grad=None)
def retinanet_target_assign(ins, attrs, ctx):
    """reference: detection/rpn_target_assign_op.cc:1030
    RetinanetTargetAssign — RetinaNet anchor assignment: positives are
    IoU>=positive_overlap anchors plus each gt's best anchor; negatives
    IoU<negative_overlap; remaining anchors ignored. Unlike RPN there is
    no subsampling (focal loss uses all), labels are CLASS ids (1-based,
    0=background), and ForegroundNumber is emitted for focal-loss
    normalization. Static shapes: fixed-capacity index outputs padded
    with -1."""
    anchors = ins["Anchor"][0].reshape(-1, 4)
    gt = ins["GtBoxes"][0].reshape(-1, 4)
    gt_labels = ins["GtLabels"][0].reshape(-1)
    pos_thr = float(attrs.get("positive_overlap", 0.5))
    neg_thr = float(attrs.get("negative_overlap", 0.4))
    a = anchors.shape[0]
    valid_gt = gt_labels > 0
    iou = _pairwise_iou(anchors, gt, normalized=False)
    iou = jnp.where(valid_gt[None, :], iou, -1.0)
    best_iou = jnp.max(iou, axis=1)
    best_gt = jnp.argmax(iou, axis=1)
    fg = best_iou >= pos_thr
    best_anchor = jnp.argmax(iou, axis=0)
    fg = fg.at[jnp.where(valid_gt, best_anchor, a)].set(
        True, mode="drop")
    bg = (best_iou < neg_thr) & ~fg
    loc_index = jnp.where(fg, jnp.arange(a), -1)
    loc_index = jnp.sort(jnp.where(loc_index >= 0, loc_index,
                                   jnp.iinfo(jnp.int32).max))
    loc_index = jnp.where(loc_index < a, loc_index, -1).astype(jnp.int32)
    score_sel = fg | bg
    score_index = jnp.where(score_sel, jnp.arange(a), -1)
    score_index = jnp.sort(jnp.where(score_index >= 0, score_index,
                                     jnp.iinfo(jnp.int32).max))
    score_index = jnp.where(score_index < a, score_index,
                            -1).astype(jnp.int32)
    labels = jnp.where(fg, gt_labels[best_gt], 0)
    target_label = jnp.where(
        score_index >= 0,
        labels[jnp.maximum(score_index, 0)], -1).astype(jnp.int32)
    tb = _encode_rpn_targets(anchors, gt, best_gt)
    target_bbox = jnp.where((loc_index >= 0)[:, None],
                            tb[jnp.maximum(loc_index, 0)], 0.0)
    fg_num = jnp.sum(fg.astype(jnp.int32)).reshape(1)
    bbox_inside_weight = (loc_index >= 0).astype(
        anchors.dtype)[:, None] * jnp.ones((1, 4), anchors.dtype)
    return {"LocationIndex": loc_index, "ScoreIndex": score_index,
            "TargetLabel": target_label[:, None],
            "TargetBBox": target_bbox,
            "BBoxInsideWeight": bbox_inside_weight,
            "ForegroundNumber": fg_num}


def _encode_rpn_targets(anchors, gt, best_gt):
    """Center-size encode of each anchor's matched gt (RPN/Retina
    convention, no variances)."""
    g = gt[best_gt]
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + aw * 0.5
    acy = anchors[:, 1] + ah * 0.5
    gw = g[:, 2] - g[:, 0] + 1.0
    gh = g[:, 3] - g[:, 1] + 1.0
    gcx = g[:, 0] + gw * 0.5
    gcy = g[:, 1] + gh * 0.5
    return jnp.stack([(gcx - acx) / aw, (gcy - acy) / ah,
                      jnp.log(jnp.maximum(gw / aw, 1e-10)),
                      jnp.log(jnp.maximum(gh / ah, 1e-10))], axis=-1)


# reference registers multiclass_nms2 as its own op type (same kernel +
# the Index output, multiclass_nms_op.cc)
register_op("multiclass_nms2", grad=None)(_multiclass_nms_alias)
