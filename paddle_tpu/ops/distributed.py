"""PS send/recv ops — RPC from inside the compiled step.

Reference: operators/distributed_ops/send_op.cc, recv_op.cc,
send_barrier_op.cc, fetch_barrier_op.cc, listen_and_serv_op.cc. The
reference's ops call the gRPC client mid-graph; here they lower to
jax.experimental.io_callback (ordered) so the RPC happens at the same
program point under jit. The active client is process-global state set by
`bind_client` (the reference's RPCClient singleton, rpc_client.h:122).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op

_CLIENT = None
_COMMUNICATOR = None


def bind_client(client):
    """Install the PSClient used by ps_send/ps_recv in this process."""
    global _CLIENT
    _CLIENT = client


def get_client():
    if _CLIENT is None:
        raise RuntimeError(
            "no PSClient bound — call paddle_tpu.ops.distributed.bind_client "
            "(the transpiler-run trainer does this in its startup)")
    return _CLIENT


def bind_communicator(comm):
    """Install the AsyncCommunicator ps_send routes through when the op
    carries use_communicator (reference: Communicator::GetInstance())."""
    global _COMMUNICATOR
    _COMMUNICATOR = comm


def get_communicator():
    if _COMMUNICATOR is None:
        raise RuntimeError(
            "no AsyncCommunicator bound — construct "
            "paddle_tpu.communicator.Communicator(trainer_program) and "
            "start() it before running async-mode steps")
    return _COMMUNICATOR


@register_op("ps_send", grad=None, nondiff_inputs=("X",))
def ps_send(ins, attrs, ctx):
    name = attrs["var_name"]
    x = ins["X"][0]
    use_comm = bool(attrs.get("use_communicator", False))

    def _send(g):
        if use_comm:
            # enqueue to the background merging sender (communicator.h:276)
            get_communicator().push(name, np.asarray(g))
        else:
            get_client().push_grad(name, np.asarray(g))
        return np.zeros((), np.int32)

    token = jax.experimental.io_callback(
        _send, jax.ShapeDtypeStruct((), jnp.int32), x, ordered=True)
    return {"Out": token}


@register_op("ps_send_aux", grad=None, nondiff_inputs=("X",))
def ps_send_aux(ins, attrs, ctx):
    """Refresh trainer-maintained optimizer aux vars (decayed LR, ...) on
    every server before the barrier (reference: the transpiler moves
    lr_decay ops to the pserver; here the trainer stays authoritative and
    ships the values per step). Accepts one var (var_name) or a merged
    list (var_names, one RPC per server for all of them)."""
    names = (list(attrs["var_names"]) if "var_names" in attrs
             else [attrs["var_name"]])
    xs = ins["X"]

    def _send(*vs):
        get_client().set_aux_many(
            {n: np.asarray(v) for n, v in zip(names, vs)})
        return np.zeros((), np.int32)

    token = jax.experimental.io_callback(
        _send, jax.ShapeDtypeStruct((), jnp.int32), *xs, ordered=True)
    return {"Out": token}


@register_op("ps_send_barrier", grad=None)
def ps_send_barrier(ins, attrs, ctx):
    def _barrier():
        get_client().send_barrier()
        return np.zeros((), np.int32)

    token = jax.experimental.io_callback(
        _barrier, jax.ShapeDtypeStruct((), jnp.int32), ordered=True)
    return {"Out": token}


@register_op("ps_send_many", grad=None, nondiff_inputs=("X",))
def ps_send_many(ins, attrs, ctx):
    """Merged dense send (reference: communicator.h:276 merged sends,
    parameter_send.cc): every dense grad bound for the PS leaves in ONE
    io_callback → PSClient.push_grads packs one RPC per target server,
    amortizing the measured ~0.21 ms per-RPC floor across the model's
    whole dense parameter set."""
    names = list(attrs["var_names"])
    xs = [x for x in ins["X"]]
    use_comm = bool(attrs.get("use_communicator", False))

    def _send(*gs):
        if use_comm:
            comm = get_communicator()
            for n, g in zip(names, gs):
                comm.push(n, np.asarray(g))
        else:
            get_client().push_grads(
                {n: np.asarray(g) for n, g in zip(names, gs)})
        return np.zeros((), np.int32)

    token = jax.experimental.io_callback(
        _send, jax.ShapeDtypeStruct((), jnp.int32), *xs, ordered=True)
    return {"Out": token}


@register_op("ps_recv_many", grad=None)
def ps_recv_many(ins, attrs, ctx):
    """Merged dense recv (reference: parameter_recv.cc): one io_callback
    pulls every param in one RPC per owning server (PSClient.pull_many).
    Under the communicator, params already refreshed by the recv thread
    are read from its host-side cache; only the missing ones ride an
    RPC."""
    names = list(attrs["var_names"])
    out_names = ctx.op.outputs.get("Out", [])
    specs = [_var_spec(ctx, on, "ps_recv_many") for on in out_names]
    do_not_run = bool(attrs.get("do_not_run", False))

    def _pull():
        vals: dict = {}
        missing = list(names)
        if do_not_run:
            comm = get_communicator()
            missing = []
            for n in names:
                v = comm.latest.get(n)
                if v is None:
                    missing.append(n)
                else:
                    vals[n] = np.asarray(v)
        if missing:
            vals.update(get_client().pull_many(missing))
        return tuple(np.asarray(vals[n]).astype(s.dtype)
                     for n, s in zip(names, specs))

    outs = jax.experimental.io_callback(_pull, tuple(specs), ordered=True)
    return {"Out": list(outs)}


def _var_spec(ctx, var_name, op_label):
    """Static output shape/dtype from the program's var desc (shared by
    ps_recv / ps_recv_many — recv outputs have no input to infer from)."""
    from ..core.ir import normalize_dtype

    if ctx.program is not None:
        for b in ctx.program.blocks:
            if var_name in b.vars:
                vd = b.vars[var_name]
                return jax.ShapeDtypeStruct(
                    tuple(vd.shape), np.dtype(normalize_dtype(vd.dtype)))
    raise RuntimeError(f"{op_label}: unknown shape for {var_name}")


@register_op("ps_recv", grad=None)
def ps_recv(ins, attrs, ctx):
    name = attrs["var_name"]
    out_names = ctx.op.outputs.get("Out", [])
    if not out_names:
        raise RuntimeError(f"ps_recv: no output var for {name}")
    spec = _var_spec(ctx, out_names[0], "ps_recv")
    shape, dtype = spec.shape, spec.dtype
    do_not_run = bool(attrs.get("do_not_run", False))

    def _pull():
        if do_not_run:
            # communicator mode: the independent recv thread refreshes a
            # host-side numpy cache; the in-graph recv just reads it
            # (reference sets do_not_run on recv ops, communicator.py:42).
            # NEVER read the scope here — its entries may be device arrays
            # and converting one inside a host callback deadlocks.
            v = get_communicator().latest.get(name)
            if v is not None:
                return np.asarray(v).astype(dtype)
        return get_client().pull(name).astype(dtype)

    val = jax.experimental.io_callback(
        _pull, jax.ShapeDtypeStruct(shape, dtype), ordered=True)
    return {"Out": val}


def _sparse_push_token(name, ids, grads, lr, push_fn):
    """Shared io_callback emitter for sparse-grad pushes (dlt + box ops):
    push_fn(name, ids, grads, lr) runs host-side; returns the i32 token
    the callers tie into their outputs so the push cannot be pruned."""

    def _push(ids_v, g_v):
        push_fn(name, np.asarray(ids_v), np.asarray(g_v, np.float32), lr)
        return np.zeros((), np.int32)

    return jax.experimental.io_callback(
        _push, jax.ShapeDtypeStruct((), jnp.int32), ids, grads,
        ordered=True)


def _dlt_grad(ins, attrs, ctx):
    """Backward of distributed_lookup_table: push the sparse row gradients
    straight to the owning pservers (the async sparse-SGD update of the
    reference's table optimize block). The differentiable `Shadow` scalar
    exists only so the backward pass emits this op (the table itself is
    remote); its returned gradient is zero."""
    from ..core.registry import GRAD_PREFIX_IG, GRAD_PREFIX_IN, GRAD_PREFIX_OG

    name = attrs["table_name"]
    lr = float(attrs.get("sparse_lr", 0.01))
    ids = ins[GRAD_PREFIX_IN + "Ids"][0]
    og = ins[GRAD_PREFIX_OG + "Out"][0]

    def _push_fn(n, i, g, r):
        from ..ps.sparse_table import push_row_grads

        push_row_grads(get_client(), n, i, g, r)

    token = _sparse_push_token(name, ids, og, lr, _push_fn)
    shadow = ins[GRAD_PREFIX_IN + "Shadow"][0]
    # tie the push token into the returned grad so it can't be pruned
    return {GRAD_PREFIX_IG + "Shadow": [
        jnp.zeros_like(shadow) + token.astype(shadow.dtype) * 0]}


@register_op("distributed_lookup_table", grad=_dlt_grad,
             nondiff_inputs=("Ids",))
def distributed_lookup_table(ins, attrs, ctx):
    """reference: distributed_ops/distributed_lookup_table_op.cc — prefetch
    the touched rows of a pserver-sharded embedding (parameter_prefetch.cc).
    """
    name = attrs["table_name"]
    dim = int(attrs["emb_dim"])
    dtype = np.dtype(attrs.get("dtype", "float32"))
    ids = ins["Ids"][0]

    def _pull(ids_v):
        from ..ps.sparse_table import pull_rows

        return pull_rows(get_client(), name, np.asarray(ids_v),
                         dim=dim).astype(dtype)

    flat_n = 1
    for s in ids.shape:
        flat_n *= s
    rows = jax.experimental.io_callback(
        _pull, jax.ShapeDtypeStruct((flat_n, dim), dtype), ids,
        ordered=True)
    out = rows.reshape(tuple(ids.shape) + (dim,))
    if ins.get("Shadow") and ins["Shadow"][0] is not None:
        out = out + ins["Shadow"][0].astype(out.dtype) * 0
    return {"Out": out}


def _box_push_fn(name, ids, grads, lr):
    from ..ps.box_cache import get_box_cache

    get_box_cache().push_sparse_grad(name, ids, grads, lr)


def _box_grad(ins, attrs, ctx):
    """Backward of pull_box_sparse = the reference's push_box_sparse op
    (push_box_sparse_op.cc): apply the row grads to the trainer-resident
    box cache (read-your-writes) and flush them to the PS asynchronously
    (box_wrapper.h:46 PushSparseGrad)."""
    from ..core.registry import GRAD_PREFIX_IG, GRAD_PREFIX_IN, GRAD_PREFIX_OG

    name = attrs["table_name"]
    lr = float(attrs.get("sparse_lr", 0.01))
    ids = ins[GRAD_PREFIX_IN + "Ids"][0]
    og = ins[GRAD_PREFIX_OG + "Out"][0]
    token = _sparse_push_token(name, ids, og, lr, _box_push_fn)
    shadow = ins[GRAD_PREFIX_IN + "Shadow"][0]
    return {GRAD_PREFIX_IG + "Shadow": [
        jnp.zeros_like(shadow) + token.astype(shadow.dtype) * 0]}


@register_op("pull_box_sparse", grad=_box_grad, nondiff_inputs=("Ids",))
def pull_box_sparse(ins, attrs, ctx):
    """reference: operators/pull_box_sparse_op.cc + fleet/box_wrapper.h:41
    PullSparse — embedding lookup through the trainer-resident hot-row
    cache (ps/box_cache.py): cache hits never touch the remote PS; misses
    fan out to the sharded servers and populate the LRU. Same Shadow
    convention as distributed_lookup_table (the table is remote; the
    differentiable Shadow scalar carries the backward hook)."""
    name = attrs["table_name"]
    dim = int(attrs["emb_dim"])
    dtype = np.dtype(attrs.get("dtype", "float32"))
    ids = ins["Ids"][0]

    def _pull(ids_v):
        from ..ps.box_cache import get_box_cache

        return get_box_cache().pull_sparse(
            name, np.asarray(ids_v), dim).astype(dtype)

    flat_n = 1
    for s in ids.shape:
        flat_n *= s
    rows = jax.experimental.io_callback(
        _pull, jax.ShapeDtypeStruct((flat_n, dim), dtype), ids,
        ordered=True)
    out = rows.reshape(tuple(ids.shape) + (dim,))
    if ins.get("Shadow") and ins["Shadow"][0] is not None:
        out = out + ins["Shadow"][0].astype(out.dtype) * 0
    return {"Out": out}


@register_op("push_box_sparse", grad=None, nondiff_inputs=("Ids", "Grads"))
def push_box_sparse(ins, attrs, ctx):
    """reference: push_box_sparse_op.cc — standalone push (normally the
    backward of pull_box_sparse emits it implicitly via _box_grad; this
    op exists for programs that schedule the push explicitly)."""
    name = attrs["table_name"]
    lr = float(attrs.get("sparse_lr", 0.01))
    token = _sparse_push_token(name, ins["Ids"][0], ins["Grads"][0], lr,
                               _box_push_fn)
    return {"Out": token}


@register_op("listen_and_serv", grad=None)
def listen_and_serv(ins, attrs, ctx):
    raise RuntimeError(
        "listen_and_serv cannot be jit-compiled; Executor.run detects it "
        "and runs the server loop on the host (core/executor.py)")


@register_op("checkpoint_notify", grad=None)
def checkpoint_notify_op(ins, attrs, ctx):
    """reference: checkpoint_notify_op.cc — in-graph trigger for pserver
    checkpoints (the trainer-side end of the pserver checkpoint block)."""
    dirname = attrs["dirname"]

    def _notify():
        get_client().checkpoint_notify(dirname)
        return np.zeros((), np.int32)

    token = jax.experimental.io_callback(
        _notify, jax.ShapeDtypeStruct((), jnp.int32), ordered=True)
    return {"Out": token}
