"""Program static analysis: validate ProgramDescs before they trace.

The reference front-loads correctness with a graph-IR pass pipeline
(~60 passes over ir::Graph) and an inference Analyzer that validates and
rewrites every program before the executor sees it (AnalysisPredictor →
ir_graph_build → ir_analysis). paddle_tpu lowers whole blocks into one
jit trace, so a malformed program historically died deep inside jax
tracing with an opaque error. This package is the analogous front-load:
a registry of `AnalysisPass`es over the dataclass IR (core/ir.py) that
turn those late failures into structured, op/var-addressed `Finding`s
BEFORE anything is traced.

Wiring (ANALYSIS.md has the full story):

- `PADDLE_TPU_VALIDATE=0|1|2` (off / warn / error) gates pre-run
  validation in `Executor.run`/`run_chained`/`run_stream` and
  `CompiledProgram`. Results are cached per program version + run
  signature, so a steady-state training loop pays for ONE walk and
  every later step is a dict lookup (`walk_count()` is the test hook
  proving that).
- The serving `Engine` validates the loaded program once at boot,
  honoring the same env for raise semantics.
- `tools/analyze.py` runs the suite offline over a saved model dir or
  an in-repo model builder, with table/JSON output and a DOT render.

Every run lands in `paddle_tpu_analysis_findings_total{pass,severity}`
/ `paddle_tpu_analysis_runs_total` and emits an `analysis` event, so a
fleet's validation story is observable like every other subsystem.
"""

from __future__ import annotations

import os
import time
import warnings
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..core import precision as _precision
from ..core.ir import ProgramDesc
from ..observability import telemetry as _telemetry

__all__ = [
    "Finding", "AnalysisPass", "PassContext", "AnalysisError",
    "register_pass", "pass_names", "get_pass", "default_passes",
    "run_passes", "validate_program", "maybe_validate", "validate_level",
    "walk_count", "findings_to_json", "ERROR", "WARNING", "INFO",
    "ENV_VAR",
]

ENV_VAR = "PADDLE_TPU_VALIDATE"

ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEVERITIES = (ERROR, WARNING, INFO)


@dataclass(frozen=True)
class Finding:
    """One structured analysis result, addressed to the op/var it is
    about (op_idx is the index within its block; var the offending
    variable name) — the actionable replacement for a KeyError three
    layers into jax tracing."""

    severity: str
    pass_name: str
    message: str
    block_idx: int = 0
    op_idx: Optional[int] = None
    op_type: Optional[str] = None
    var: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "severity": self.severity,
            "pass": self.pass_name,
            "message": self.message,
            "block_idx": self.block_idx,
        }
        if self.op_idx is not None:
            d["op_idx"] = self.op_idx
        if self.op_type is not None:
            d["op_type"] = self.op_type
        if self.var is not None:
            d["var"] = self.var
        return d

    def where(self) -> str:
        loc = f"block {self.block_idx}"
        if self.op_idx is not None:
            loc += f" op #{self.op_idx}"
        if self.op_type is not None:
            loc += f" ({self.op_type})"
        return loc

    def __str__(self):
        v = f" var '{self.var}'" if self.var else ""
        return (f"[{self.severity}] {self.pass_name}: {self.where()}"
                f"{v}: {self.message}")


def findings_to_json(findings: Sequence[Finding]) -> List[Dict[str, Any]]:
    return [f.to_dict() for f in findings]


class AnalysisError(RuntimeError):
    """Raised at PADDLE_TPU_VALIDATE=2 when a program carries
    error-severity findings; `.findings` holds every finding from the
    walk (errors first) so callers can render all of them at once."""

    def __init__(self, findings: Sequence[Finding]):
        self.findings = list(findings)
        errors = [f for f in self.findings if f.severity == ERROR]
        lines = [f"program failed static analysis with "
                 f"{len(errors)} error(s):"]
        lines += [f"  {f}" for f in errors]
        rest = len(self.findings) - len(errors)
        if rest:
            lines.append(f"  (+{rest} non-error finding(s); run "
                         f"tools/analyze.py for the full report)")
        super().__init__("\n".join(lines))


@dataclass
class PassContext:
    """Everything a pass may consult. feed/fetch names describe the RUN
    binding (executor feed dict / fetch list) and are unioned with any
    feed/fetch ops the program itself carries; policy is the resolved
    precision policy the program would trace under."""

    program_desc: ProgramDesc
    feed_names: frozenset = frozenset()
    fetch_names: Tuple[str, ...] = ()
    policy: Optional["_precision.PrecisionPolicy"] = None
    is_test: bool = False
    # per-walk memo for the context's own derived views (persistable
    # names, program feed/fetch ops) so each is computed once per walk,
    # not once per pass
    shared: Dict[str, Any] = field(default_factory=dict)

    def persistable_names(self) -> frozenset:
        key = "_persistable"
        if key not in self.shared:
            self.shared[key] = frozenset(
                v.name for b in self.program_desc.blocks
                for v in b.vars.values() if v.persistable)
        return self.shared[key]

    def program_feeds_fetches(self) -> Tuple[List[str], List[str]]:
        key = "_prog_feed_fetch"
        if key not in self.shared:
            from ..core.lowering import collect_feed_fetch

            self.shared[key] = collect_feed_fetch(self.program_desc)
        return self.shared[key]

    def all_feed_names(self) -> frozenset:
        return self.feed_names | frozenset(self.program_feeds_fetches()[0])

    def all_fetch_names(self) -> Tuple[str, ...]:
        extra = tuple(n for n in self.program_feeds_fetches()[1]
                      if n not in self.fetch_names)
        return tuple(self.fetch_names) + extra

    def find_var_desc(self, block_idx: int, name: str):
        """Declared VarDesc for `name`, looked up from `block_idx`
        outward through parents (the executor's scoping rule)."""
        desc = self.program_desc
        idx = block_idx
        while idx >= 0:
            b = desc.block(idx)
            v = b.vars.get(name)
            if v is not None:
                return v
            idx = b.parent_idx
        return None


class AnalysisPass:
    """One validation pass over a ProgramDesc. Subclasses set `name`
    (the metrics label and CLI filter) and implement run(ctx) returning
    Findings; raising is a pass bug — the runner converts it into a
    WARNING finding against the pass itself rather than killing (or,
    at level 2, blocking) the run."""

    name = "?"

    def run(self, ctx: PassContext) -> List[Finding]:
        raise NotImplementedError


_PASSES: Dict[str, AnalysisPass] = {}
_ORDER: List[str] = []


def register_pass(cls):
    """Class decorator registering an AnalysisPass (instantiated once;
    passes must be stateless between runs). Registration order is
    execution order."""
    inst = cls()
    if cls.name in _PASSES:
        _ORDER.remove(cls.name)
    _PASSES[cls.name] = inst
    _ORDER.append(cls.name)
    return cls


def pass_names() -> List[str]:
    return list(_ORDER)


def get_pass(name: str) -> AnalysisPass:
    if name not in _PASSES:
        raise KeyError(f"unknown analysis pass {name!r}; choose from "
                       f"{_ORDER}")
    return _PASSES[name]


def default_passes() -> List[AnalysisPass]:
    return [_PASSES[n] for n in _ORDER]


# walker-invocation counter: the per-program-version cache contract
# (zero per-step overhead after the first run) is tested by counting
# full suite walks across repeated identical runs
_walks = 0


def walk_count() -> int:
    return _walks


def run_passes(
    program_desc: ProgramDesc,
    feed_names: Iterable[str] = (),
    fetch_names: Iterable[str] = (),
    policy=None,
    is_test: bool = False,
    passes: Optional[Sequence[str]] = None,
    where: str = "api",
) -> List[Finding]:
    """One full analysis walk: every (selected) pass over the program.
    Returns findings sorted errors-first. Records the run + per-pass
    finding counts in the metrics registry and emits one `analysis`
    event — validation is a fleet behavior worth observing, not just a
    local raise."""
    global _walks
    _walks += 1
    ctx = PassContext(
        program_desc=program_desc,
        feed_names=frozenset(feed_names),
        fetch_names=tuple(fetch_names),
        policy=_precision.get_policy(policy),
        is_test=is_test,
    )
    selected = (default_passes() if passes is None
                else [get_pass(n) for n in passes])
    t0 = time.perf_counter()
    findings: List[Finding] = []
    for p in selected:
        try:
            findings.extend(p.run(ctx))
        except Exception as e:
            # a buggy pass must not kill the run — and must not BLOCK
            # it either: WARNING severity keeps the crash visible in
            # findings/metrics/events without the fail-closed trap of
            # level 2 refusing a valid program because the VALIDATOR
            # broke (validate_level's contract)
            findings.append(Finding(
                severity=WARNING, pass_name=p.name,
                message=f"analysis pass crashed (finding suppressed, "
                        f"not blocking): {type(e).__name__}: {e}"))
    findings.sort(key=lambda f: (_SEVERITIES.index(f.severity),
                                 f.block_idx, f.op_idx or 0))
    n_ops = sum(len(b.ops) for b in program_desc.blocks)
    _telemetry.record_analysis(findings, n_ops=n_ops, where=where,
                               seconds=time.perf_counter() - t0)
    return findings


def validate_level() -> int:
    """PADDLE_TPU_VALIDATE parsed: 0 off (default), 1 warn, 2 error.
    Junk values mean off — validation must never be the thing that
    breaks a run by accident."""
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return 0
    try:
        return max(0, min(2, int(raw)))
    except ValueError:
        return 0


def validate_program(program_desc, feed_names=(), fetch_names=(),
                     policy=None, is_test=False, level: int = 2,
                     where: str = "api") -> List[Finding]:
    """Run the suite and apply `level` semantics: level>=2 raises
    AnalysisError on any error-severity finding, level 1 warns once,
    level 0 still returns the findings (callers wanting a report)."""
    findings = run_passes(program_desc, feed_names, fetch_names,
                          policy=policy, is_test=is_test, where=where)
    _apply_level(findings, level)
    return findings


def _apply_level(findings: List[Finding], level: int):
    errors = [f for f in findings if f.severity == ERROR]
    if errors and level >= 2:
        raise AnalysisError(findings)
    if errors and level == 1:
        warnings.warn(
            f"program failed static analysis with {len(errors)} "
            f"error(s) (PADDLE_TPU_VALIDATE=1 → run anyway): "
            + "; ".join(str(f) for f in errors[:5]),
            stacklevel=3)


# per-Program result cache: {id-keyed on the Program object itself via
# __dict__} — (version, {signature: findings}). Re-validating a hot
# training loop would pay a full IR walk per step; the cache makes every
# post-first step a dict lookup. Bounded per version; a version bump
# (any program mutation) drops everything.
_CACHE_ATTR = "_analysis_cache"
_CACHE_MAX_SIGS = 32


def maybe_validate(program, feed_names=(), fetch_names=(), policy=None,
                   where: str = "executor") -> Optional[List[Finding]]:
    """Env-gated pre-run validation for the executor hot paths: no-op
    at PADDLE_TPU_VALIDATE=0; at 1/2 the first run of a (program
    version, feeds, fetches, policy) signature walks the pass suite and
    later runs replay the cached outcome — including the raise at
    level 2, so a bad program fails every run, not just the first."""
    level = validate_level()
    if level <= 0:
        return None
    pol = _precision.get_policy(policy) if policy is not None \
        else _precision.resolve(program)
    sig = (frozenset(feed_names), tuple(fetch_names), pol.name,
           bool(getattr(program, "_is_test", False)))
    version = getattr(program, "_version", 0)
    cache = program.__dict__.get(_CACHE_ATTR)
    if cache is None or cache[0] != version:
        cache = (version, {})
        program.__dict__[_CACHE_ATTR] = cache
    findings = cache[1].get(sig)
    if findings is None:
        findings = run_passes(
            program.desc, feed_names=feed_names, fetch_names=fetch_names,
            policy=pol, is_test=bool(getattr(program, "_is_test", False)),
            where=where)
        if len(cache[1]) >= _CACHE_MAX_SIGS:
            cache[1].pop(next(iter(cache[1])))
        cache[1][sig] = findings
    _apply_level(findings, level)
    return findings


from . import passes  # noqa: E402,F401  (self-registers the suite)
# The runtime concurrency sanitizer (PADDLE_TPU_LOCKCHECK instrumented
# lock factories + deadlock detection) lives beside the program passes:
# same package, same observability contract, different substrate
# (threads instead of ProgramDescs). Stdlib-only, so importing it here
# costs nothing.
from . import lockcheck  # noqa: E402,F401
