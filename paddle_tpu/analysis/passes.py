"""The built-in analysis pass suite.

Each pass mirrors one class of late failure the executor/lowering stack
produces today and moves it to before-the-trace with an op/var-level
message (module docstring in __init__.py; per-defect examples in
ANALYSIS.md):

  def_use        — LoweringError("input var has no value") → error at
                   the op that reads it; dangling fetches → error.
  unsupported_op — registry KeyError mid-lowering → error naming the op
                   (with close-name suggestions).
  shape_dtype    — jax trace-time shape/dtype blowups → per-op re-run
                   of the generic eval_shape inference, checked against
                   the DECLARED output VarDescs (the reference's
                   InferShape analogue).
  dead_op        — ops whose outputs can never be observed (not
                   fetched, not persistable, never read downstream) and
                   vars nothing consumes.
  alias          — in-place/aliasing hazards: one op writing a var
                   twice, overwrites of fed vars, write-after-write
                   with no read between.
  precision      — programs whose declared dtypes contradict the PR 7
                   autocast white/black lists under bf16/mixed
                   policies (the silent-upcast audit).
"""

from __future__ import annotations

import difflib
from typing import Dict, List, Set, Tuple

from ..core import registry
from ..core.ir import OpDesc, VarDesc, normalize_dtype
from ..core.lowering import STRUCTURAL_OPS
from . import (ERROR, INFO, WARNING, AnalysisPass, Finding, PassContext,
               register_pass)

# Ops the executor interprets host-side or that exist for their side
# effects (RPC sends, barriers, prints): never "dead", never lowered by
# eval_shape.
SIDE_EFFECT_OPS = frozenset({
    "print", "listen_and_serv", "save", "save_combine",
})


def _is_side_effect(op_type: str) -> bool:
    return op_type in SIDE_EFFECT_OPS or op_type.startswith("ps_") \
        or op_type.startswith("c_")  # collectives mutate mesh state


def _attr_declared_names(op: OpDesc) -> Set[str]:
    """Var names a sub-block op binds into its inner env via attrs
    (carry_names / input_names / out_names ... — control_flow.py
    kernels build the sub-env from these string-list attrs)."""
    names: Set[str] = set()
    for v in op.attrs.values():
        if isinstance(v, str):
            names.add(v)
        elif isinstance(v, (list, tuple)) and all(
                isinstance(e, str) for e in v):
            names.update(v)
    return names


# ---------------------------------------------------------------------------
# def-before-use / dangling fetch
# ---------------------------------------------------------------------------


@register_pass
class DefBeforeUsePass(AnalysisPass):
    """Every op input must have a value when the op traces: a feed, a
    persistable scope var, or the output of an earlier op. The lowering
    equivalent failure is LoweringError deep inside the jit trace; here
    it is an error finding naming the op AND the var. Sub-block ops
    (control flow) bind extra names from their attrs and their kernels
    own the inner env, so inner-block violations report at warning
    severity — the outer walk cannot prove them fatal."""

    name = "def_use"

    def run(self, ctx: PassContext) -> List[Finding]:
        findings: List[Finding] = []
        persistable = ctx.persistable_names()
        feeds = ctx.all_feed_names()
        defined: Set[str] = set(feeds) | set(persistable)

        def visit(block_idx: int, defined: Set[str], strict: bool):
            block = ctx.program_desc.block(block_idx)
            for op_idx, op in enumerate(block.ops):
                if op.type == "feed":
                    defined.update(op.output_names())
                    continue
                for n in op.input_names():
                    if n not in defined:
                        findings.append(Finding(
                            severity=ERROR if strict else WARNING,
                            pass_name=self.name,
                            message=(
                                f"input var '{n}' has no value at this "
                                f"op: not fed, not persistable, and not "
                                f"produced by an earlier op"),
                            block_idx=block_idx, op_idx=op_idx,
                            op_type=op.type, var=n))
                subs = op.sub_block_ids()
                if subs:
                    inner = defined | _attr_declared_names(op)
                    for sub in subs:
                        visit(sub, set(inner), strict=False)
                defined.update(op.output_names())

        visit(0, defined, strict=True)
        # dangling fetches: executor raises "fetch var was not produced"
        # only after tracing the whole program; flag it statically
        for n in ctx.all_fetch_names():
            if n not in defined:
                findings.append(Finding(
                    severity=ERROR, pass_name=self.name,
                    message=(f"fetch var '{n}' is never produced: no op "
                             f"writes it and it is neither fed nor "
                             f"persistable"),
                    var=n))
        return findings


# ---------------------------------------------------------------------------
# unsupported op (fail fast with the NAME, not a lowering KeyError)
# ---------------------------------------------------------------------------


@register_pass
class UnsupportedOpPass(AnalysisPass):
    name = "unsupported_op"

    def run(self, ctx: PassContext) -> List[Finding]:
        findings: List[Finding] = []
        for bi, block in enumerate(ctx.program_desc.blocks):
            for oi, op in enumerate(block.ops):
                if op.type in STRUCTURAL_OPS:
                    continue
                if registry.has_op(op.type):
                    continue
                close = difflib.get_close_matches(
                    op.type, registry.registered_ops(), n=3)
                hint = f" (did you mean: {', '.join(close)}?)" \
                    if close else ""
                findings.append(Finding(
                    severity=ERROR, pass_name=self.name,
                    message=(f"op type '{op.type}' is not registered — "
                             f"lowering would fail{hint}"),
                    block_idx=bi, op_idx=oi, op_type=op.type))
        return findings


# ---------------------------------------------------------------------------
# shape/dtype inference walker (reference InferShape analogue)
# ---------------------------------------------------------------------------


@register_pass
class ShapeDtypePass(AnalysisPass):
    """Re-run the generic eval_shape inference per op, feeding each op
    the *inferred* descs of its upstream ops, and check the result
    against the DECLARED output VarDescs. Catches programs whose descs
    were mutated/hand-built/deserialized into inconsistency — exactly
    the mismatch that today dies mid-trace with a jax shape error.

    Skipped (documented limits): structural ops, sub-block (control
    flow) ops whose kernels own their env, grad ops (grad var shapes
    are the forward shapes by construction — core/backward.py), ops
    whose input shapes are undeclared, and unregistered ops (the
    unsupported_op pass already flagged those)."""

    name = "shape_dtype"

    def run(self, ctx: PassContext) -> List[Finding]:
        findings: List[Finding] = []
        inferred_descs: Dict[str, VarDesc] = {}
        block = ctx.program_desc.block(0)
        for oi, op in enumerate(block.ops):
            if op.type in STRUCTURAL_OPS or op.sub_block_ids() \
                    or op.type.endswith("_grad") \
                    or not registry.has_op(op.type):
                continue
            input_descs: Dict[str, VarDesc] = {}
            ok = True
            for n in op.input_names():
                d = inferred_descs.get(n) or ctx.find_var_desc(0, n)
                if d is None or d.shape is None:
                    ok = False  # def_use/undeclared: nothing to check
                    break
                input_descs[n] = d
            if not ok:
                continue
            try:
                out = registry.infer_op_outputs(
                    op, input_descs, program=ctx.program_desc)
            except (TypeError, ValueError) as e:
                findings.append(Finding(
                    severity=ERROR, pass_name=self.name,
                    message=(f"shape/dtype inference failed: "
                             f"{type(e).__name__}: {e}"),
                    block_idx=0, op_idx=oi, op_type=op.type))
                continue
            except Exception as e:
                findings.append(Finding(
                    severity=INFO, pass_name=self.name,
                    message=(f"could not statically infer "
                             f"({type(e).__name__}: {e}); skipped"),
                    block_idx=0, op_idx=oi, op_type=op.type))
                continue
            for name, sds in out.items():
                shape = tuple(int(s) for s in sds.shape)
                dtype = normalize_dtype(sds.dtype)
                declared = ctx.find_var_desc(0, name)
                if declared is not None and declared.shape is not None:
                    want = tuple(int(s) for s in declared.shape)
                    if want != shape:
                        findings.append(Finding(
                            severity=ERROR, pass_name=self.name,
                            message=(f"declared shape {list(want)} but "
                                     f"the op infers {list(shape)}"),
                            block_idx=0, op_idx=oi, op_type=op.type,
                            var=name))
                    if normalize_dtype(declared.dtype) != dtype:
                        findings.append(Finding(
                            severity=ERROR, pass_name=self.name,
                            message=(f"declared dtype "
                                     f"{normalize_dtype(declared.dtype)}"
                                     f" but the op infers {dtype}"),
                            block_idx=0, op_idx=oi, op_type=op.type,
                            var=name))
                inferred_descs[name] = VarDesc(
                    name=name, shape=shape, dtype=dtype)
        return findings


# ---------------------------------------------------------------------------
# dead ops / unused vars
# ---------------------------------------------------------------------------


@register_pass
class DeadOpPass(AnalysisPass):
    """Backward liveness over block 0: an op is live iff some output is
    observable (fetched or persistable) or feeds a live op; everything
    else is wasted trace/compile work (XLA DCEs it, but silently —
    usually it means a mis-specified fetch list). Warning severity:
    dead code is waste, not a wrong answer."""

    name = "dead_op"

    def run(self, ctx: PassContext) -> List[Finding]:
        findings: List[Finding] = []
        persistable = ctx.persistable_names()
        block = ctx.program_desc.block(0)
        live: Set[str] = set(ctx.all_fetch_names())
        consumed: Set[str] = set()
        for op in block.ops:
            consumed.update(op.input_names())
            if op.sub_block_ids():
                consumed.update(_attr_declared_names(op))
        for oi in reversed(range(len(block.ops))):
            op = block.ops[oi]
            if op.type in STRUCTURAL_OPS or _is_side_effect(op.type) \
                    or op.sub_block_ids():
                live.update(op.input_names())
                if op.sub_block_ids():
                    # sub-block kernels bind outer vars through string
                    # attrs (carry_names/input_names/...), not input
                    # slots — those reads keep their producers live
                    live.update(_attr_declared_names(op))
                continue
            outs = op.output_names()
            if not outs:
                live.update(op.input_names())  # side effect by shape
                continue
            if any(o in live or o in persistable for o in outs):
                live.update(op.input_names())
            else:
                findings.append(Finding(
                    severity=WARNING, pass_name=self.name,
                    message=(f"dead op: outputs "
                             f"{sorted(set(outs))} are never fetched, "
                             f"never persisted, and never read by a "
                             f"live op"),
                    block_idx=0, op_idx=oi, op_type=op.type))
        produced: Set[str] = set()
        for op in block.ops:
            produced.update(op.output_names())
        for name in block.vars:
            if name in consumed or name in persistable \
                    or name in ctx.all_feed_names() \
                    or name in ctx.all_fetch_names():
                continue
            if name not in produced:
                findings.append(Finding(
                    severity=INFO, pass_name=self.name,
                    message=("unused var: declared but never produced, "
                             "consumed, fed, or fetched"),
                    var=name))
        return findings


# ---------------------------------------------------------------------------
# in-place / aliasing hazards
# ---------------------------------------------------------------------------


@register_pass
class AliasPass(AnalysisPass):
    """The functional env makes sequential overwrites well-defined, but
    three aliasing shapes are still hazards: one op writing the same
    var from two output slots (one result silently lost — error), an op
    overwriting a FED var (the caller's input is shadowed mid-program —
    warning), and write-after-write with no read between (the first
    write is unobservable — warning; frequently a renamed-var bug)."""

    name = "alias"

    def run(self, ctx: PassContext) -> List[Finding]:
        findings: List[Finding] = []
        persistable = ctx.persistable_names()
        feeds = ctx.all_feed_names()
        fetches = set(ctx.all_fetch_names())
        block = ctx.program_desc.block(0)
        last_write: Dict[str, Tuple[int, str]] = {}
        read_since: Set[str] = set()
        for oi, op in enumerate(block.ops):
            if op.type in STRUCTURAL_OPS:
                continue
            for n in op.input_names():
                read_since.add(n)
            if op.sub_block_ids():
                # attr-declared bindings are reads the outer slots
                # don't show (same modeling as def_use/dead_op)
                read_since.update(_attr_declared_names(op))
            outs = op.output_names()
            seen: Set[str] = set()
            for n in outs:
                if n in seen:
                    findings.append(Finding(
                        severity=ERROR, pass_name=self.name,
                        message=(f"var '{n}' is written by two output "
                                 f"slots of the same op — one result "
                                 f"is silently lost"),
                        block_idx=0, op_idx=oi, op_type=op.type, var=n))
                seen.add(n)
                if n in feeds:
                    findings.append(Finding(
                        severity=WARNING, pass_name=self.name,
                        message=(f"op overwrites fed var '{n}' — later "
                                 f"ops read the rewritten value, not "
                                 f"the caller's feed"),
                        block_idx=0, op_idx=oi, op_type=op.type, var=n))
                prev = last_write.get(n)
                if prev is not None and n not in read_since \
                        and n not in persistable and n not in fetches:
                    findings.append(Finding(
                        severity=WARNING, pass_name=self.name,
                        message=(f"write-after-write: op "
                                 f"#{prev[0]} ({prev[1]}) wrote '{n}' "
                                 f"and nothing read it before this "
                                 f"rewrite — the first write is "
                                 f"unobservable"),
                        block_idx=0, op_idx=oi, op_type=op.type, var=n))
                last_write[n] = (oi, op.type)
                read_since.discard(n)
        return findings


# ---------------------------------------------------------------------------
# precision-policy audit (PR 7 autocast white/black lists)
# ---------------------------------------------------------------------------


@register_pass
class PrecisionAuditPass(AnalysisPass):
    """Under a non-f32 policy, audit the program's declared dtypes
    against the autocast op classes (amp/fp16_lists):

    - mixed policies force black-list ops (reductions/norms/softmax) to
      f32 at trace time; a black-list op DECLARING a sub-f32 float
      output contradicts the program's own IR — downstream shape/dtype
      reasoning (and checkpoint manifests) would be wrong → error.
    - white-list ops fed declared float64 inputs silently downcast to
      the compute dtype → warning.
    - the pure bf16 policy has NO autocast: black-list ops run their
      reductions in bf16 → warning (use mixed_bf16 for f32 stats).

    A no-op under f32 (every in-repo model validates clean by
    default)."""

    name = "precision"

    _NARROW = ("bfloat16", "float16")

    def run(self, ctx: PassContext) -> List[Finding]:
        pol = ctx.policy
        if pol is None or pol.compute_dtype is None:
            return []
        from ..amp import fp16_lists

        white = fp16_lists.white_list
        black = fp16_lists.black_list
        findings: List[Finding] = []
        for bi, block in enumerate(ctx.program_desc.blocks):
            for oi, op in enumerate(block.ops):
                base = op.type
                while base.endswith("_grad"):
                    base = base[:-len("_grad")]
                if pol.op_autocast and base in black:
                    for n in op.output_names():
                        d = ctx.find_var_desc(bi, n)
                        if d is not None and \
                                normalize_dtype(d.dtype) in self._NARROW:
                            findings.append(Finding(
                                severity=ERROR, pass_name=self.name,
                                message=(
                                    f"black-list op declares "
                                    f"{normalize_dtype(d.dtype)} output "
                                    f"'{n}' but policy "
                                    f"'{pol.name}' computes it in "
                                    f"float32 — the declared IR dtype "
                                    f"contradicts the trace"),
                                block_idx=bi, op_idx=oi,
                                op_type=op.type, var=n))
                if pol.op_autocast and base in white:
                    for n in op.input_names():
                        d = ctx.find_var_desc(bi, n)
                        if d is not None and \
                                normalize_dtype(d.dtype) == "float64":
                            findings.append(Finding(
                                severity=WARNING, pass_name=self.name,
                                message=(
                                    f"white-list op input '{n}' is "
                                    f"declared float64; policy "
                                    f"'{pol.name}' downcasts it to "
                                    f"{pol.compute_dtype} — precision "
                                    f"silently lost"),
                                block_idx=bi, op_idx=oi,
                                op_type=op.type, var=n))
                if pol.cast_state and not pol.op_autocast \
                        and base in black:
                    findings.append(Finding(
                        severity=WARNING, pass_name=self.name,
                        message=(
                            f"reduction/norm op runs in "
                            f"{pol.compute_dtype} under the pure "
                            f"'{pol.name}' policy — its statistics "
                            f"lose precision; mixed_bf16 keeps "
                            f"black-list ops in f32"),
                        block_idx=bi, op_idx=oi, op_type=op.type))
        return findings
