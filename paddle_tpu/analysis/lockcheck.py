"""Runtime concurrency sanitizer: instrumented lock factories.

The static prong (`tools/lockgraph.py`) proves lock-ORDER safety by
walking the AST; this module is the runtime prong that catches what
statics cannot see — the deadlock that actually forms, the inversion a
dynamic call path takes, the lock a hot thread sits on for seconds.
The repo's worst recent bugs were exactly this class (the PR 10
rendezvous cross-generation deadlock, the half-open breaker probe-slot
wedge), all found by chaos runs or review instead of tooling.

`PADDLE_TPU_LOCKCHECK` gates everything:

  0 (default)  the factories return RAW `threading` primitives —
               zero overhead, zero behavior change.
  1            instrumented: per-thread acquisition stacks, held-
               seconds / contention metrics, observed lock-order edges
               checked against the committed `tools/lock_order.json`
               ledger (an edge the ledger orders the OTHER way counts
               as an inversion).
  2            level 1 plus live deadlock detection: a blocking
               `acquire()` registers in a waits-for graph and polls; a
               cycle raises `DeadlockError` naming every thread and
               held lock in it INSTEAD of hanging forever.

Our own modules create their contended locks through these factories
(the monkeypatch hook — `self._cv = lockcheck.Condition(name=...)`),
passing the same canonical site id `tools/lockgraph.py` infers
statically (`<module>.<Class>.<attr>`, e.g.
`serving.batcher.Batcher._cv`), so the static ledger and the runtime
observations speak one naming scheme.

Metrics (through the PR 1 registry, lazily — this module stays
importable before the package finishes initializing):

  paddle_tpu_lock_held_seconds{site}            histogram
  paddle_tpu_lock_contention_total{site}        counter
  paddle_tpu_lock_inversions_total{first,second} counter
  paddle_tpu_lock_deadlocks_total               counter

Known limits (documented, not hidden): the checker's own bookkeeping
uses one raw mutex; `Condition.wait()` re-acquisition blocks inside the
stdlib so a deadlock formed THERE is not detected; RLock re-entry
observes a held-span per acquire/release pair.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "ENV_VAR", "level", "Lock", "RLock", "Condition", "DeadlockError",
    "set_ledger", "ledger_order", "observed_edges",
    "observed_inversions", "deadlock_count", "note_held", "reset",
]

ENV_VAR = "PADDLE_TPU_LOCKCHECK"
LEDGER_ENV_VAR = "PADDLE_TPU_LOCK_ORDER"

# how often a level-2 blocked acquire re-runs cycle detection; also the
# bound on how long a freshly-formed deadlock goes unnoticed
_POLL_S = 0.05

_DEFAULT_LEDGER = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tools", "lock_order.json")


def level() -> int:
    """PADDLE_TPU_LOCKCHECK parsed: 0 off (default), 1 observe,
    2 observe + deadlock detection. Junk values mean off — the
    sanitizer must never be the thing that breaks a run by accident."""
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return 0
    try:
        return max(0, min(2, int(raw)))
    except ValueError:
        return 0


class DeadlockError(RuntimeError):
    """Raised (level 2) from a blocking `acquire()` whose waits-for
    graph closed into a cycle. `.cycle` holds one dict per thread in
    the cycle: {thread, waits_for, held}."""

    def __init__(self, cycle: List[dict]):
        self.cycle = list(cycle)
        lines = [f"deadlock detected: {len(self.cycle)} thread(s) in "
                 f"a lock cycle:"]
        for hop in self.cycle:
            held = ", ".join(hop["held"]) or "<nothing>"
            lines.append(
                f"  thread '{hop['thread']}' waits for lock "
                f"'{hop['waits_for']}' while holding: {held}")
        super().__init__("\n".join(lines))


# ---------------------------------------------------------------------------
# metrics (lazy: the registry may not be importable yet when an early
# module creates its first lock)
# ---------------------------------------------------------------------------

_metrics: Optional[dict] = None


def _get_metrics() -> Optional[dict]:
    global _metrics
    if _metrics is None:
        try:
            from ..observability import metrics as _m
        except ImportError:
            return None  # package still booting; retry on next event
        _metrics = {
            "held": _m.histogram(
                "paddle_tpu_lock_held_seconds",
                "Seconds a lock was held, per acquisition",
                labelnames=("site",),
                buckets=_m.exponential_buckets(0.0001, 4, 10)),
            "contention": _m.counter(
                "paddle_tpu_lock_contention_total",
                "Blocking acquires that found the lock already held",
                labelnames=("site",)),
            "inversions": _m.counter(
                "paddle_tpu_lock_inversions_total",
                "Acquisitions whose held->acquired edge contradicts the "
                "lock_order.json ledger",
                labelnames=("first", "second")),
            "deadlocks": _m.counter(
                "paddle_tpu_lock_deadlocks_total",
                "Deadlock cycles detected (and broken) by DeadlockError"),
        }
    return _metrics


def note_held(site: str, seconds: float, contended: bool = False):
    """Record a held-span for a lock NOT built by these factories (the
    cross-process tpu_lock file lease uses this so the single-flight
    lock's hold time shows up in the same table)."""
    m = _get_metrics()
    if m is None:
        return
    m["held"].observe(seconds, site=site)
    if contended:
        m["contention"].inc(site=site)


# ---------------------------------------------------------------------------
# the ledger (blessed global lock order, shared with tools/lockgraph.py)
# ---------------------------------------------------------------------------

_ledger_index: Optional[Dict[str, int]] = None
_ledger_exempt: Optional[set] = None
_ledger_override: Optional[List[str]] = None
_ledger_exempt_override: Optional[set] = None


def _load_ledger() -> Dict[str, int]:
    global _ledger_index, _ledger_exempt
    if _ledger_index is not None:
        return _ledger_index
    if _ledger_override is not None:
        _ledger_index = {s: i for i, s in enumerate(_ledger_override)}
        _ledger_exempt = set(_ledger_exempt_override or ())
        return _ledger_index
    path = os.environ.get(LEDGER_ENV_VAR) or _DEFAULT_LEDGER
    order: List[str] = []
    exempt: set = set()
    try:
        with open(path) as f:
            data = json.load(f)
        order = list(data.get("order", []))
        # exempt_edges suppress justified edges from BOTH prongs — a
        # blessed edge must not fail the runtime gate either
        exempt = {(e.get("first"), e.get("second"))
                  for e in data.get("exempt_edges", [])}
    except (OSError, ValueError):
        pass  # no ledger -> no inversion checks, everything else works
    _ledger_index = {s: i for i, s in enumerate(order)}
    _ledger_exempt = exempt
    return _ledger_index


def _exempt_pairs() -> set:
    _load_ledger()
    return _ledger_exempt or set()


def set_ledger(order: Optional[List[str]],
               exempt_edges: Optional[List[dict]] = None):
    """Test hook: replace (list) or restore (None) the blessed order
    (and, optionally, the exempt edge pairs)."""
    global _ledger_override, _ledger_index, _ledger_exempt
    global _ledger_exempt_override
    _ledger_override = list(order) if order is not None else None
    _ledger_exempt_override = (
        {(e.get("first"), e.get("second")) for e in exempt_edges}
        if exempt_edges is not None else None)
    _ledger_index = None
    _ledger_exempt = None


def ledger_order() -> List[str]:
    idx = _load_ledger()
    return sorted(idx, key=idx.get)


# ---------------------------------------------------------------------------
# the checker: one process-global waits-for/held bookkeeper
# ---------------------------------------------------------------------------


class _Checker:
    """All maps guarded by one raw mutex (`_mu`) held only for dict
    surgery — never across a blocking call, never across a metric
    observation (the registry has its own lock)."""

    def __init__(self):
        self._mu = threading.Lock()
        # id(ilock) -> {thread_ident: recursion count}
        self.holders: Dict[int, Dict[int, int]] = {}
        # thread_ident -> ilock it is blocked acquiring
        self.waiting: Dict[int, "_InstrumentedLock"] = {}
        # thread_ident -> [(ilock, t_acquired)] acquisition stack
        self.held: Dict[int, List[Tuple["_InstrumentedLock", float]]] = {}
        # observed order edges: (first_site, second_site) -> count
        self.edges: Dict[Tuple[str, str], int] = {}
        # inverted edges: (first_site, second_site) -> count
        self.inversions: Dict[Tuple[str, str], int] = {}
        self.deadlocks = 0

    # -- acquisition bookkeeping --------------------------------------

    def on_acquired(self, ilock: "_InstrumentedLock"):
        tid = threading.get_ident()
        new_inversions: List[Tuple[str, str]] = []
        with self._mu:
            self.holders.setdefault(id(ilock), {})
            self.holders[id(ilock)][tid] = \
                self.holders[id(ilock)].get(tid, 0) + 1
            stack = self.held.setdefault(tid, [])
            for prev, _t0 in stack:
                if prev is ilock or prev.name == ilock.name:
                    continue  # re-entry / per-instance same-site locks
                edge = (prev.name, ilock.name)
                self.edges[edge] = self.edges.get(edge, 0) + 1
                idx = _load_ledger()
                ia, ib = idx.get(prev.name), idx.get(ilock.name)
                if ia is not None and ib is not None and ia > ib \
                        and edge not in _exempt_pairs():
                    self.inversions[edge] = \
                        self.inversions.get(edge, 0) + 1
                    new_inversions.append(edge)
            stack.append((ilock, time.perf_counter()))
        m = _get_metrics()
        if m is not None:
            for first, second in new_inversions:
                m["inversions"].inc(first=first, second=second)

    def on_released(self, ilock: "_InstrumentedLock"):
        tid = threading.get_ident()
        span = None
        with self._mu:
            stack = self.held.get(tid, [])
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] is ilock:
                    span = time.perf_counter() - stack[i][1]
                    del stack[i]
                    break
            counts = self.holders.get(id(ilock))
            if counts and tid in counts:
                counts[tid] -= 1
                if counts[tid] <= 0:
                    del counts[tid]
                if not counts:
                    del self.holders[id(ilock)]
        if span is not None:
            m = _get_metrics()
            if m is not None:
                m["held"].observe(span, site=ilock.name)

    def on_contention(self, ilock: "_InstrumentedLock"):
        m = _get_metrics()
        if m is not None:
            m["contention"].inc(site=ilock.name)

    # -- waits-for graph ----------------------------------------------

    def set_waiting(self, ilock: "_InstrumentedLock"):
        with self._mu:
            self.waiting[threading.get_ident()] = ilock

    def clear_waiting(self):
        with self._mu:
            self.waiting.pop(threading.get_ident(), None)

    def find_cycle(self) -> Optional[List[dict]]:
        """Follow me -> lock I wait for -> its holder -> lock THAT
        thread waits for -> ... Returns the hop list when the walk
        closes back on the calling thread, else None."""
        start = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        with self._mu:
            hops: List[dict] = []
            tid, seen = start, set()
            while True:
                lk = self.waiting.get(tid)
                if lk is None:
                    return None
                owners = [h for h in self.holders.get(id(lk), {})
                          if h != tid]
                if not owners:
                    return None
                hops.append({
                    "thread": names.get(tid, str(tid)),
                    "waits_for": lk.name,
                    "held": [h.name for h, _t in self.held.get(tid, [])],
                })
                nxt = owners[0]
                if nxt == start:
                    return hops
                if nxt in seen:
                    return None  # a cycle, but not through this thread
                seen.add(nxt)
                tid = nxt

    def on_deadlock(self):
        with self._mu:
            self.deadlocks += 1
        m = _get_metrics()
        if m is not None:
            m["deadlocks"].inc()

    # -- views / reset ------------------------------------------------

    def snapshot_edges(self) -> Dict[Tuple[str, str], int]:
        with self._mu:
            return dict(self.edges)

    def snapshot_inversions(self) -> Dict[Tuple[str, str], int]:
        with self._mu:
            return dict(self.inversions)

    def reset(self):
        """Clear observed state. Only meaningful while no instrumented
        lock is held (tests between cases); holders/waiting are cleared
        too so a leaked lock cannot poison the next case."""
        with self._mu:
            self.holders.clear()
            self.waiting.clear()
            self.held.clear()
            self.edges.clear()
            self.inversions.clear()
            self.deadlocks = 0


_checker = _Checker()


def observed_edges() -> Dict[Tuple[str, str], int]:
    """(first, second) -> times that held->acquired order was seen."""
    return _checker.snapshot_edges()


def observed_inversions() -> List[dict]:
    """Edges contradicting the ledger, with counts and the blessed
    order they violate — the obsdump `locks` inversion list."""
    idx = _load_ledger()
    out = []
    for (first, second), n in sorted(_checker.snapshot_inversions().items()):
        out.append({"first": first, "second": second, "count": n,
                    "ledger_says": f"{second} < {first}",
                    "ledger_index": [idx.get(second), idx.get(first)]})
    return out


def deadlock_count() -> int:
    return _checker.deadlocks


def reset():
    _checker.reset()


# ---------------------------------------------------------------------------
# instrumented primitives
# ---------------------------------------------------------------------------


def _site_from_caller(depth: int = 2) -> str:
    """Fallback site id when the factory caller passed no name."""
    import sys

    try:
        f = sys._getframe(depth)
        return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
    except (ValueError, AttributeError):
        return "<unknown>"


class _InstrumentedLock:
    """Lock/RLock wrapper: context-manager + acquire/release compatible
    with `threading`'s, feeding the checker on every transition. The
    level-2 blocking path polls the raw lock so it can interleave
    waits-for cycle detection with the wait."""

    def __init__(self, name: str, raw):
        self.name = name
        self._raw = raw

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if self._raw.acquire(False):      # uncontended fast path
            _checker.on_acquired(self)
            return True
        if not blocking:
            return False
        _checker.on_contention(self)
        deadline = (None if timeout is None or timeout < 0
                    else time.monotonic() + timeout)
        detect = level() >= 2
        if not detect and deadline is None:
            self._raw.acquire()           # plain blocking wait
            _checker.on_acquired(self)
            return True
        _checker.set_waiting(self)
        try:
            while True:
                if detect:
                    cycle = _checker.find_cycle()
                    if cycle:
                        _checker.on_deadlock()
                        raise DeadlockError(cycle)
                wait = _POLL_S
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return False
                    wait = min(wait, left)
                if self._raw.acquire(True, wait):
                    _checker.on_acquired(self)
                    return True
        finally:
            _checker.clear_waiting()

    def release(self):
        _checker.on_released(self)        # while still the owner
        self._raw.release()

    def locked(self):
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<lockcheck {type(self._raw).__name__} '{self.name}'>"


class _InstrumentedCondition:
    """Condition sharing an instrumented lock's raw primitive, so
    `with cv:` and `with the_lock:` are ONE identity to the checker
    (mirroring how lockgraph aliases `Condition(self._lock)`
    statically). wait() un-books the hold for its release window and
    re-books on return."""

    def __init__(self, ilock: _InstrumentedLock, name: str):
        self.name = name
        self._ilock = ilock
        self._cond = threading.Condition(ilock._raw)

    def acquire(self, *args, **kwargs):
        return self._ilock.acquire(*args, **kwargs)

    def release(self):
        self._ilock.release()

    def __enter__(self):
        self._ilock.acquire()
        return self

    def __exit__(self, *exc):
        self._ilock.release()
        return False

    def wait(self, timeout: Optional[float] = None):
        _checker.on_released(self._ilock)
        try:
            # lint-exempt:condwait: pass-through wrapper — the CALLER owns the predicate loop
            return self._cond.wait(timeout)
        finally:
            # the stdlib re-acquired the raw lock before returning;
            # deadlocks formed in THAT window are outside our reach
            _checker.on_acquired(self._ilock)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        end = None if timeout is None else time.monotonic() + timeout
        result = predicate()
        while not result:
            left = None
            if end is not None:
                left = end - time.monotonic()
                if left <= 0:
                    break
            self.wait(left)
            result = predicate()
        return result

    def notify(self, n: int = 1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()

    def __repr__(self):
        return f"<lockcheck Condition '{self.name}'>"


# ---------------------------------------------------------------------------
# the factories (what our modules call)
# ---------------------------------------------------------------------------


def Lock(name: Optional[str] = None):
    """threading.Lock at level 0, instrumented wrapper at level >= 1.
    `name` is the canonical site id (match tools/lockgraph.py's
    `<module>.<Class>.<attr>` derivation so the ledger applies)."""
    if level() == 0:
        return threading.Lock()
    return _InstrumentedLock(name or _site_from_caller(), threading.Lock())


def RLock(name: Optional[str] = None):
    if level() == 0:
        return threading.RLock()
    return _InstrumentedLock(name or _site_from_caller(),
                             threading.RLock())


def Condition(lock=None, name: Optional[str] = None):
    """threading.Condition at level 0. At level >= 1 the instrumented
    condition shares `lock`'s identity when `lock` is itself an
    instrumented lock (one site, like the static alias), wraps a raw
    lock under the condition's own name otherwise."""
    if level() == 0:
        return threading.Condition(lock)
    site = name or _site_from_caller()
    if isinstance(lock, _InstrumentedLock):
        ilock = lock
    elif lock is None:
        # stdlib Condition() defaults to an RLock — owners may re-enter
        # (`with cv:` nested under `with cv:`); a plain Lock here would
        # turn that legitimate pattern into a self-deadlock
        ilock = _InstrumentedLock(site, threading.RLock())
    else:
        ilock = _InstrumentedLock(site, lock)
    return _InstrumentedCondition(ilock, site)
