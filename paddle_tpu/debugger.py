"""Program visualization (reference: python/paddle/fluid/debugger.py
draw_block_graphviz + ir/graph_viz_pass.cc — emit a DOT graph of ops and
variables for debugging)."""

from __future__ import annotations

from typing import Optional, Sequence

from .observability import metrics as _m

DOT_NODES = _m.gauge(
    "paddle_tpu_debugger_dot_nodes",
    "Node count of the most recently rendered DOT graph",
    labelnames=("kind",))


def _esc(s: str) -> str:
    return s.replace('"', '\\"')


def block_to_dot(block, skip_vars: Sequence[str] = (),
                 highlight: Sequence[str] = ()) -> str:
    """DOT source for one block: op nodes (boxes) wired through var nodes
    (ellipses); parameters shaded."""
    lines = ["digraph program {", "  rankdir=TB;",
             '  node [fontsize=10];']
    skip = set(skip_vars)
    hi = set(highlight)
    vars_seen = set()

    def var_node(name):
        if name in vars_seen or name in skip:
            return
        vars_seen.add(name)
        v = block.desc.vars.get(name)
        shape = list(v.shape) if v is not None and v.shape else "?"
        attrs = [f'label="{_esc(name)}\\n{shape}"', "shape=ellipse"]
        if name in hi:
            attrs.append('style=filled, fillcolor="#ffd0d0"')
        elif v is not None and v.is_parameter:
            attrs.append('style=filled, fillcolor="#e0e0ff"')
        # a plain var adds no style attr — joining only what exists keeps
        # the attr list valid DOT (no dangling comma before "];")
        lines.append(f'  "v_{_esc(name)}" [{", ".join(attrs)}];')

    for i, op in enumerate(block.desc.ops):
        lines.append(f'  "op_{i}" [label="{_esc(op.type)}", shape=box, '
                     f'style=filled, fillcolor="#d0ffd0"];')
        for names in op.inputs.values():
            for n in names:
                if n and n not in skip:
                    var_node(n)
                    lines.append(f'  "v_{_esc(n)}" -> "op_{i}";')
        for names in op.outputs.values():
            for n in names:
                if n and n not in skip:
                    var_node(n)
                    lines.append(f'  "op_{i}" -> "v_{_esc(n)}";')
    lines.append("}")
    DOT_NODES.set(len(block.desc.ops), kind="op")
    DOT_NODES.set(len(vars_seen), kind="var")
    return "\n".join(lines)


def draw_block_graphviz(block, highlights: Optional[Sequence[str]] = None,
                        path: str = "/tmp/temp.dot"):
    """reference: debugger.py draw_block_graphviz — write DOT to `path`
    (render with `dot -Tpng`; atomic so a half-written DOT never
    reaches the renderer)."""
    from .resilience import atomic as _atomic

    _atomic.write_text(path, block_to_dot(block, highlight=highlights or ()))
    return path


def draw_program(program, path: str = "/tmp/program.dot"):
    return draw_block_graphviz(program.global_block(), path=path)
