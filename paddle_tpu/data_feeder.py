"""DataFeeder (reference: python/paddle/fluid/data_feeder.py) — converts
per-sample minibatch lists into the feed dict of batched numpy arrays."""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from .core.framework import Variable
from .core.ir import normalize_dtype

__all__ = ["DataFeeder"]


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None, program=None):
        self.feed_vars = feed_list
        self.place = place

    def feed(self, iterable) -> Dict[str, np.ndarray]:
        columns: List[List] = [[] for _ in self.feed_vars]
        for sample in iterable:
            assert len(sample) == len(self.feed_vars), \
                f"sample has {len(sample)} slots, expected {len(self.feed_vars)}"
            for i, v in enumerate(sample):
                columns[i].append(np.asarray(v))
        out = {}
        for var, col in zip(self.feed_vars, columns):
            name = var.name if isinstance(var, Variable) else str(var)
            dtype = normalize_dtype(var.dtype) if isinstance(var, Variable) else None
            arr = np.stack(col)
            # match declared rank: e.g. label declared [-1,1] but fed scalars
            if isinstance(var, Variable) and var.shape is not None:
                want_rank = len(var.shape)
                while arr.ndim < want_rank:
                    arr = arr[..., None]
                if arr.ndim == want_rank + 1 and arr.shape[-1] == 1 and \
                        var.shape[-1] != 1:
                    arr = arr[..., 0]
            if dtype is not None:
                arr = arr.astype(dtype)
            out[name] = arr
        return out
