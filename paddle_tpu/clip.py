"""Gradient clipping (reference: python/paddle/fluid/clip.py —
GradientClipByValue / ByNorm / ByGlobalNorm, set_gradient_clip)."""

from __future__ import annotations

from typing import List, Tuple

from .core.framework import OpRole, default_main_program, op_role_guard, unique_name

__all__ = ["GradientClipByValue", "GradientClipByNorm",
           "GradientClipByGlobalNorm", "set_gradient_clip",
           "append_gradient_clip_ops", "ErrorClipByValue"]

_clip_attr_name = "gradient_clip_attr"


class BaseGradientClipAttr:
    def _process(self, params_grads):
        raise NotImplementedError

    def __call__(self, params_grads):
        return self._process(params_grads)


class NullGradientClipAttr(BaseGradientClipAttr):
    def _process(self, params_grads):
        return params_grads


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _process(self, params_grads):
        block = default_main_program().global_block()
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            ng = block.create_var(name=unique_name.generate(g.name + "_clip"),
                                  shape=g.shape, dtype=g.dtype)
            block.append_op(type="clip", inputs={"X": g}, outputs={"Out": ng},
                            attrs={"min": self.min, "max": self.max})
            out.append((p, ng))
        return out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _process(self, params_grads):
        block = default_main_program().global_block()
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            ng = block.create_var(name=unique_name.generate(g.name + "_clip"),
                                  shape=g.shape, dtype=g.dtype)
            block.append_op(type="clip_by_norm", inputs={"X": g},
                            outputs={"Out": ng},
                            attrs={"max_norm": self.clip_norm})
            out.append((p, ng))
        return out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """reference: clip.py GradientClipByGlobalNorm — scale all grads by
    clip_norm / max(global_norm, clip_norm)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process(self, params_grads):
        from .layers import ops as _lops
        from .layers import tensor as _lt
        from .layers.nn import squared_l2_norm

        block = default_main_program().global_block()
        sq_norms = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            sq_norms.append(squared_l2_norm(g))
        if not sq_norms:
            return params_grads
        total = sq_norms[0]
        for s in sq_norms[1:]:
            total = _lops.elementwise_add(total, s)
        global_norm = _lops.sqrt(total)
        clip_var = _lt.fill_constant([1], "float32", self.clip_norm)
        scale = _lops.elementwise_div(
            clip_var, _lops.elementwise_max(global_norm, clip_var))
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            ng = block.create_var(name=unique_name.generate(g.name + "_gclip"),
                                  shape=g.shape, dtype=g.dtype)
            block.append_op(type="elementwise_mul", inputs={"X": g, "Y": scale},
                            outputs={"Out": ng})
            out.append((p, ng))
        return out


class ErrorClipByValue:
    """reference: clip.py ErrorClipByValue (clips activations' grads)."""

    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max


def set_gradient_clip(clip, param_list=None, program=None):
    program = program or default_main_program()
    if param_list is None:
        param_list = program.all_parameters()
    for p in param_list:
        if isinstance(p, str):
            p = program.global_block().var(p)
        p.gradient_clip_attr = clip


def append_gradient_clip_ops(params_grads):
    clips = set()
    for p, g in params_grads:
        c = getattr(p, "gradient_clip_attr", None)
        if c is not None:
            clips.add(c)
    if not clips:
        return params_grads
    if len(clips) > 1:
        # apply each clip only to its own params
        out = []
        for p, g in params_grads:
            c = getattr(p, "gradient_clip_attr", None)
            if c is None:
                out.append((p, g))
            else:
                out.extend(c([(p, g)]))
        return out
    with op_role_guard(OpRole.Backward):
        return next(iter(clips))(params_grads)
