"""Trainer stack entry points (reference: Executor.train_from_dataset →
TrainerFactory → C++ MultiTrainer/DistMultiTrainer + DeviceWorkers,
framework/trainer.h:38, device_worker.h:103, SURVEY §3.6).

Round-1: a host-side trainer loop over a Dataset's file shards feeding the
compiled step (HogwildWorker semantics, hogwild_worker.cc:163); the C++
datafeed library (paddle_tpu/data/) supplies the pipelined batch source.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

from .observability import events as _events
from .observability import health as _health
from .observability import telemetry as _telemetry
from .resilience import faults as _faults
from .resilience import preemption as _preempt


def _fetch_names(fetch_list, fetch_info=None):
    return list(fetch_info) if fetch_info else [
        getattr(f, "name", str(f)) for f in (fetch_list or [])]


def _batch_examples(feed) -> int:
    """Leading dim of the first feed tensor — the examples-per-step count
    every throughput metric is denominated in."""
    try:
        for v in feed.values():
            shape = getattr(v, "shape", None)
            if shape:
                return int(shape[0])
    except (AttributeError, TypeError):
        pass
    return 0


def train_from_dataset(executor, program=None, dataset=None, scope=None,
                       thread=0, debug=False, fetch_list=None,
                       fetch_info=None, print_period=100):
    from .core import framework

    program = program or framework.default_main_program()
    if dataset is None:
        raise ValueError("dataset is required")
    fetch_list = fetch_list or []
    names = _fetch_names(fetch_list, fetch_info)
    step = 0
    examples = 0
    run_t0 = time.perf_counter()
    batches = dataset._iter_batches() if hasattr(dataset, "_iter_batches") \
        else iter(dataset)
    _preempt.maybe_install_from_env()
    stop = "completed"
    for feed in batches:
        # step boundary: the only safe stop/injection point (see
        # parallel.train.train_loop for the full fault-tolerant driver)
        _faults.check("step", step=step)
        if _preempt.stop_requested():
            stop = "preempted"
            break
        t0 = time.perf_counter()
        vals = executor.run(program, feed=feed, fetch_list=fetch_list,
                            scope=scope)
        if fetch_list and _health.check_level():
            # the fetched losses are the trainer's divergence canary
            _health.check_numerics("trainer_loss", zip(names, vals),
                                   step=step)
        n = _batch_examples(feed)
        examples += n
        _telemetry.record_trainer_step(time.perf_counter() - t0, n)
        if debug and fetch_list and step % print_period == 0:
            print(f"step {step}: " + ", ".join(
                f"{n}={v}" for n, v in zip(names, vals)))
        step += 1
    seconds = time.perf_counter() - run_t0
    _telemetry.record_trainer_run(seconds, examples)
    _events.emit("step_summary", site="train_from_dataset", steps=step,
                 examples=examples, seconds=round(seconds, 6),
                 examples_per_sec=round(examples / seconds, 3)
                 if seconds > 0 else 0.0, stop=stop)
    return None


def infer_from_dataset(executor, program=None, dataset=None, scope=None,
                       thread=0, debug=False, fetch_list=None,
                       fetch_info=None, print_period=100):
    infer_prog = (program or __import__("paddle_tpu.core.framework",
                                        fromlist=["default_main_program"]
                                        ).default_main_program()).clone(for_test=True)
    return train_from_dataset(executor, infer_prog, dataset, scope, thread,
                              debug, fetch_list, fetch_info, print_period)


class TrainerDesc:
    """Trainer configuration (reference: trainer_desc.proto:21 — class
    names MultiTrainer/DistMultiTrainer + device worker choice and
    thread_num; here the XLA step is the device worker, so the desc keeps
    the scheduling knobs only)."""

    def __init__(self, thread_num: int = 1, trainer_class: str = "MultiTrainer",
                 fetch_list=None, fetch_info=None, print_period: int = 100):
        self.thread_num = max(1, int(thread_num))
        self.trainer_class = trainer_class
        self.fetch_list = fetch_list or []
        self.fetch_info = fetch_info
        self.print_period = print_period


class HogwildWorker:
    """One training thread: pull batches from its dataset shard, run the
    compiled step against the SHARED scope (reference:
    hogwild_worker.cc:163 TrainFiles). The device step itself is
    serialized by a shared lock — the XLA step donates parameter buffers
    for the in-place update, so two in-flight steps would race on freed
    buffers; threads overlap on the C++ reader pipeline and host-side
    batch prep instead (one chip executes one step at a time anyway)."""

    def __init__(self, worker_id, executor, program, dataset, scope,
                 desc: TrainerDesc, step_lock=None):
        self.worker_id = worker_id
        self.executor = executor
        self.program = program
        self.dataset = dataset
        self.scope = scope
        self.desc = desc
        self.step_lock = step_lock
        self.steps = 0
        self.last_fetch = None

    def train(self):
        import contextlib

        names = _fetch_names(self.desc.fetch_list, self.desc.fetch_info)
        run_t0 = time.perf_counter()
        examples = 0
        for feed in self.dataset._iter_batches() if hasattr(
                self.dataset, "_iter_batches") else iter(self.dataset):
            _faults.check("step", step=self.steps)
            if _preempt.stop_requested():
                break  # graceful stop at the step boundary
            t0 = time.perf_counter()
            with self.step_lock if self.step_lock is not None else \
                    contextlib.nullcontext():
                vals = self.executor.run(self.program, feed=feed,
                                         fetch_list=self.desc.fetch_list,
                                         scope=self.scope)
            if self.desc.fetch_list and _health.check_level():
                _health.check_numerics("trainer_loss", zip(names, vals),
                                       step=self.steps)
            n = _batch_examples(feed)
            examples += n
            _telemetry.record_trainer_step(time.perf_counter() - t0, n)
            self.steps += 1
            if self.desc.fetch_list:
                self.last_fetch = vals
                if self.steps % self.desc.print_period == 0:
                    print(f"worker {self.worker_id} step {self.steps}: " +
                          ", ".join(f"{n}={v}" for n, v in
                                    zip(names, vals)))
        seconds = time.perf_counter() - run_t0
        _telemetry.record_trainer_run(seconds, examples)
        _events.emit("step_summary", site="hogwild_worker",
                     worker=self.worker_id, steps=self.steps,
                     examples=examples, seconds=round(seconds, 6))


class MultiTrainer:
    """Thread-pool trainer (reference: trainer.h:64 MultiTrainer — one
    DeviceWorker thread per shard, shared root scope, exceptions funneled
    like details/exception_holder.h)."""

    def __init__(self, desc: TrainerDesc):
        self.desc = desc
        self.workers = []

    def train(self, executor, program, datasets, scope=None):
        """datasets: one per thread (shard with NativeDataset
        trainer_id/num_trainers or per-thread filelists)."""
        import threading

        from .core import executor as executor_mod

        scope = scope or executor_mod.global_scope()
        if len(datasets) != self.desc.thread_num:
            raise ValueError(
                f"need {self.desc.thread_num} dataset shards, got "
                f"{len(datasets)}")
        step_lock = threading.Lock()
        self.workers = [
            HogwildWorker(i, executor, program, ds, scope, self.desc,
                          step_lock=step_lock)
            for i, ds in enumerate(datasets)]
        errors = []

        def run(w):
            try:
                w.train()
            except BaseException as e:  # exception_holder semantics
                errors.append(e)

        threads = [threading.Thread(target=run, args=(w,), daemon=True)
                   for w in self.workers]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return sum(w.steps for w in self.workers)


def train_from_dataset_multithread(executor, program, dataset_factory,
                                   thread_num: int = 2, fetch_list=None,
                                   fetch_info=None, print_period=100,
                                   scope=None):
    """Thread-pool train_from_dataset (reference: Executor.
    train_from_dataset with TrainerDesc.thread_num > 1 → MultiTrainer).

    `dataset_factory(worker_id, num_workers)` builds each thread's shard
    — with NativeDataset, pass trainer_id=worker_id,
    num_trainers=num_workers so the C++ reader shards the filelist.
    """
    desc = TrainerDesc(thread_num=thread_num, fetch_list=fetch_list,
                       fetch_info=fetch_info, print_period=print_period)
    datasets = [dataset_factory(i, desc.thread_num)
                for i in range(desc.thread_num)]
    return MultiTrainer(desc).train(executor, program, datasets,
                                    scope=scope)
