"""Trainer stack entry points (reference: Executor.train_from_dataset →
TrainerFactory → C++ MultiTrainer/DistMultiTrainer + DeviceWorkers,
framework/trainer.h:38, device_worker.h:103, SURVEY §3.6).

Round-1: a host-side trainer loop over a Dataset's file shards feeding the
compiled step (HogwildWorker semantics, hogwild_worker.cc:163); the C++
datafeed library (paddle_tpu/data/) supplies the pipelined batch source.

Since the host-overlap PR the default driver is STREAMING: batches are
micro-chained into windows of PADDLE_TPU_STREAM_WINDOW steps (default 8,
1 restores the per-step loop), dispatched as one cached executable each
(core/executor.run_stream), with losses fetched lazily — the host only
blocks on the device when a window's values are actually needed (debug
prints, health checks) or when the bounded in-flight window applies
backpressure. Preemption is honored at window boundaries; an active
PADDLE_TPU_FAULT_SPEC drops the window to 1 so per-step fault schedules
keep their exact step semantics (see RESILIENCE.md §Streaming windows).
"""

from __future__ import annotations

import contextlib
import time
from collections import deque
from typing import Optional, Sequence

from .core import async_exec as _async
from .observability import events as _events
from .observability import health as _health
from .observability import telemetry as _telemetry
from .resilience import faults as _faults
from .resilience import preemption as _preempt


def _fetch_names(fetch_list, fetch_info=None):
    return list(fetch_info) if fetch_info else [
        getattr(f, "name", str(f)) for f in (fetch_list or [])]


def _batch_examples(feed) -> int:
    """Leading dim of the first feed tensor — the examples-per-step count
    every throughput metric is denominated in."""
    try:
        for v in feed.values():
            shape = getattr(v, "shape", None)
            if shape:
                return int(shape[0])
    except (AttributeError, TypeError):
        pass
    return 0


def _stream_window() -> int:
    """Effective streaming window: the env default, forced to 1 while a
    per-step consumer is active. A fault spec (step=N:crash) must see a
    check at every step, not every window boundary; raise-level
    numerics checking (PADDLE_TPU_CHECK_NUMERICS=2 or the legacy
    FLAGS_check_nan_inf) must stop BEFORE the next step dispatches —
    a windowed driver would let window-1 further steps mutate the
    scope on NaN state before the boundary check raised."""
    window = _async.stream_window_default()
    if window <= 1:
        return window
    if _faults.active() or _health.check_level() >= 2:
        return 1
    from .core.flags import get_flag

    if get_flag("FLAGS_check_nan_inf"):
        return 1
    return window


def _preempting_feed_src(batches, ex_pending, on_preempt=None):
    """Feed generator shared by the streaming drivers: checks for a
    graceful-stop request before each batch (so a preemption landing
    mid-window cuts the window short at a step boundary) and records
    per-batch example counts for the telemetry split."""
    for feed in batches:
        if _preempt.stop_requested():
            if on_preempt is not None:
                on_preempt()
            return
        ex_pending.append(_batch_examples(feed))
        yield feed


def _record_window_steps(n, dt, ex_pending) -> int:
    """Per-STEP telemetry for an n-step window that took dt wall
    seconds — counters stay driver-independent. Returns the window's
    example count."""
    total = 0
    for _ in range(n):
        ex = ex_pending.popleft() if ex_pending else 0
        total += ex
        _telemetry.record_trainer_step(dt / n, ex)
    return total


def _check_window_numerics(names, vals, n, step_base):
    """Per-step slices keep the anomaly's step attribution exact even
    though the window resolved as one stacked fetch."""
    for i in range(n):
        _health.check_numerics(
            "trainer_loss",
            [(nm, v[i]) for nm, v in zip(names, vals)],
            step=step_base + i)


def train_from_dataset(executor, program=None, dataset=None, scope=None,
                       thread=0, debug=False, fetch_list=None,
                       fetch_info=None, print_period=100):
    from .core import framework

    program = program or framework.default_main_program()
    if dataset is None:
        raise ValueError("dataset is required")
    fetch_list = fetch_list or []
    names = _fetch_names(fetch_list, fetch_info)
    batches = dataset._iter_batches() if hasattr(dataset, "_iter_batches") \
        else iter(dataset)
    _preempt.maybe_install_from_env()
    window = _stream_window()
    # duck-typed executors (tests, remote stubs) without the streaming
    # surface get the classic per-step loop, as do CompiledProgram-like
    # inputs (no .desc — they carry their own sharded run path that
    # executor.run delegates to)
    if window > 1 and hasattr(executor, "run_stream") \
            and hasattr(program, "desc"):
        return _train_streaming(executor, program, batches, scope, debug,
                                fetch_list, names, print_period, window)
    step = 0
    examples = 0
    run_t0 = time.perf_counter()
    stop = "completed"
    for feed in batches:
        # step boundary: the only safe stop/injection point (see
        # parallel.train.train_loop for the full fault-tolerant driver)
        _faults.check("step", step=step)
        if _preempt.stop_requested():
            stop = "preempted"
            break
        t0 = time.perf_counter()
        vals = executor.run(program, feed=feed, fetch_list=fetch_list,
                            scope=scope)
        if fetch_list and _health.check_level():
            # the fetched losses are the trainer's divergence canary
            _health.check_numerics("trainer_loss", zip(names, vals),
                                   step=step)
        n = _batch_examples(feed)
        examples += n
        _telemetry.record_trainer_step(time.perf_counter() - t0, n)
        if debug and fetch_list and step % print_period == 0:
            print(f"step {step}: " + ", ".join(
                f"{n}={v}" for n, v in zip(names, vals)))
        step += 1
    seconds = time.perf_counter() - run_t0
    _telemetry.record_trainer_run(seconds, examples)
    _events.emit("step_summary", site="train_from_dataset", steps=step,
                 examples=examples, seconds=round(seconds, 6),
                 examples_per_sec=round(examples / seconds, 3)
                 if seconds > 0 else 0.0, stop=stop)
    return None


def _train_streaming(executor, program, batches, scope, debug, fetch_list,
                     names, print_period, window):
    """Windowed driver behind train_from_dataset: one chained dispatch
    per window, lazy fetches, preemption honored between batches (so a
    request lands at a step boundary mid-window: the current window is
    cut short and flushed). Step/example telemetry stays per-step so
    counters are driver-independent."""
    step = 0
    examples = 0
    run_t0 = time.perf_counter()
    outcome = {"stop": "completed"}
    ex_pending = deque()

    def on_preempt():
        outcome["stop"] = "preempted"

    check_lvl = _health.check_level()
    want_vals = bool(fetch_list) and bool(debug or check_lvl)
    t_last = time.perf_counter()
    for h in executor.run_stream(
            program, _preempting_feed_src(batches, ex_pending, on_preempt),
            fetch_list=fetch_list, window=window, scope=scope):
        n = h.n_steps
        now = time.perf_counter()
        dt = now - t_last
        t_last = now
        examples += _record_window_steps(n, dt, ex_pending)
        if want_vals:
            vals = h.result()  # stacked [n, ...] per fetch name
            if check_lvl:
                _check_window_numerics(names, vals, n, h.start_step)
            if debug:
                for i in range(n):
                    s = h.start_step + i
                    if s % print_period == 0:
                        print(f"step {s}: " + ", ".join(
                            f"{nm}={v[i]}"
                            for nm, v in zip(names, vals)))
        step += n
    seconds = time.perf_counter() - run_t0
    _telemetry.record_trainer_run(seconds, examples)
    _events.emit("step_summary", site="train_from_dataset", steps=step,
                 examples=examples, seconds=round(seconds, 6),
                 examples_per_sec=round(examples / seconds, 3)
                 if seconds > 0 else 0.0, stop=outcome["stop"],
                 window=window)
    return None


def infer_from_dataset(executor, program=None, dataset=None, scope=None,
                       thread=0, debug=False, fetch_list=None,
                       fetch_info=None, print_period=100):
    infer_prog = (program or __import__("paddle_tpu.core.framework",
                                        fromlist=["default_main_program"]
                                        ).default_main_program()).clone(for_test=True)
    return train_from_dataset(executor, infer_prog, dataset, scope, thread,
                              debug, fetch_list, fetch_info, print_period)


class TrainerDesc:
    """Trainer configuration (reference: trainer_desc.proto:21 — class
    names MultiTrainer/DistMultiTrainer + device worker choice and
    thread_num; here the XLA step is the device worker, so the desc keeps
    the scheduling knobs only)."""

    def __init__(self, thread_num: int = 1, trainer_class: str = "MultiTrainer",
                 fetch_list=None, fetch_info=None, print_period: int = 100):
        self.thread_num = max(1, int(thread_num))
        self.trainer_class = trainer_class
        self.fetch_list = fetch_list or []
        self.fetch_info = fetch_info
        self.print_period = print_period


class HogwildWorker:
    """One training thread: pull batches from its dataset shard, run the
    compiled step against the SHARED scope (reference:
    hogwild_worker.cc:163 TrainFiles). Dispatch is serialized by a shared
    lock — the XLA step donates parameter buffers for the in-place
    update; with the streaming driver the lock covers the window
    dispatch (next() on the stream) while execution itself overlaps via
    jax async dispatch, and threads additionally overlap on the C++
    reader pipeline and host-side batch prep."""

    def __init__(self, worker_id, executor, program, dataset, scope,
                 desc: TrainerDesc, step_lock=None):
        self.worker_id = worker_id
        self.executor = executor
        self.program = program
        self.dataset = dataset
        self.scope = scope
        self.desc = desc
        self.step_lock = step_lock
        self.steps = 0
        self.last_fetch = None

    def _batches(self):
        return self.dataset._iter_batches() if hasattr(
            self.dataset, "_iter_batches") else iter(self.dataset)

    def train(self):
        window = _stream_window()
        if window > 1 and hasattr(self.executor, "run_stream") \
                and hasattr(self.program, "desc"):
            return self._train_streaming(window)
        names = _fetch_names(self.desc.fetch_list, self.desc.fetch_info)
        run_t0 = time.perf_counter()
        examples = 0
        for feed in self._batches():
            _faults.check("step", step=self.steps)
            if _preempt.stop_requested():
                break  # graceful stop at the step boundary
            t0 = time.perf_counter()
            with self.step_lock if self.step_lock is not None else \
                    contextlib.nullcontext():
                vals = self.executor.run(self.program, feed=feed,
                                         fetch_list=self.desc.fetch_list,
                                         scope=self.scope)
            if self.desc.fetch_list and _health.check_level():
                _health.check_numerics("trainer_loss", zip(names, vals),
                                       step=self.steps)
            n = _batch_examples(feed)
            examples += n
            _telemetry.record_trainer_step(time.perf_counter() - t0, n)
            self.steps += 1
            if self.desc.fetch_list:
                self.last_fetch = vals
                if self.steps % self.desc.print_period == 0:
                    print(f"worker {self.worker_id} step {self.steps}: " +
                          ", ".join(f"{n}={v}" for n, v in
                                    zip(names, vals)))
        seconds = time.perf_counter() - run_t0
        _telemetry.record_trainer_run(seconds, examples)
        _events.emit("step_summary", site="hogwild_worker",
                     worker=self.worker_id, steps=self.steps,
                     examples=examples, seconds=round(seconds, 6))

    def _train_streaming(self, window):
        from .core.executor import (_UNROLL_WINDOW_MAX, _feed_signature,
                                    _stack_feed_window)

        names = _fetch_names(self.desc.fetch_list, self.desc.fetch_info)
        fetch_list = self.desc.fetch_list
        check_lvl = _health.check_level()
        run_t0 = time.perf_counter()
        state = {"examples": 0, "t_last": run_t0, "last": None}
        ex_pending = deque()
        lock = self.step_lock if self.step_lock is not None \
            else contextlib.nullcontext()
        win = _async.InFlightWindow(limit=_async.DEFAULT_IN_FLIGHT,
                                    site="hogwild")

        def consume(h, n):
            now = time.perf_counter()
            dt = now - state["t_last"]
            state["t_last"] = now
            state["examples"] += _record_window_steps(n, dt, ex_pending)
            want_print = fetch_list and any(
                (self.steps + i + 1) % self.desc.print_period == 0
                for i in range(n))
            if fetch_list and (check_lvl or want_print):
                vals = h.result()
                if check_lvl:
                    _check_window_numerics(names, vals, n, self.steps)
                if want_print:
                    for i in range(n):
                        s = self.steps + i + 1
                        if s % self.desc.print_period == 0:
                            print(f"worker {self.worker_id} step {s}: " +
                                  ", ".join(f"{nm}={v[i]}" for nm, v in
                                            zip(names, vals)))
            self.steps += n
            state["last"] = h

        def dispatch(feeds):
            # collate and backpressure-resolve OUTSIDE the shared lock
            # (another worker's dispatch must not wait on our input or
            # on the device draining our previous window); only the
            # dispatch itself — donated-buffer territory — serializes.
            n = len(feeds)
            stacked = _stack_feed_window(feeds)
            win.reserve()
            with lock:
                h = self.executor.run_chained(
                    self.program, feed=stacked, fetch_list=fetch_list,
                    n_steps=n, per_step_feeds=True, scope=self.scope,
                    sync=False, unroll=n <= _UNROLL_WINDOW_MAX)
            win.admit(h)
            consume(h, n)

        buf, sig = [], None
        try:
            # batch pull happens on THIS thread, outside the lock, so a
            # slow dataset shard starves only its own worker
            for feed in _preempting_feed_src(self._batches(), ex_pending):
                feed = dict(feed)
                s = _feed_signature(feed)
                if buf and s != sig:
                    dispatch(buf)
                    buf = []
                sig = s
                buf.append(feed)
                if len(buf) >= window:
                    dispatch(buf)
                    buf = []
            if buf:
                dispatch(buf)
        finally:
            win.drain()
            if fetch_list and state["last"] is not None:
                # decimated fetch: only the last COMPLETED window's
                # final step materializes for last_fetch (per-step
                # values stay lazy) — in the finally so a mid-run
                # error still leaves the last good fetch readable
                self.last_fetch = [v[-1] for v in state["last"].result()]
        seconds = time.perf_counter() - run_t0
        _telemetry.record_trainer_run(seconds, state["examples"])
        _events.emit("step_summary", site="hogwild_worker",
                     worker=self.worker_id, steps=self.steps,
                     examples=state["examples"],
                     seconds=round(seconds, 6), window=window)


class MultiTrainer:
    """Thread-pool trainer (reference: trainer.h:64 MultiTrainer — one
    DeviceWorker thread per shard, shared root scope, exceptions funneled
    like details/exception_holder.h)."""

    def __init__(self, desc: TrainerDesc):
        self.desc = desc
        self.workers = []

    def train(self, executor, program, datasets, scope=None):
        """datasets: one per thread (shard with NativeDataset
        trainer_id/num_trainers or per-thread filelists)."""
        import threading

        from .core import executor as executor_mod

        scope = scope or executor_mod.global_scope()
        if len(datasets) != self.desc.thread_num:
            raise ValueError(
                f"need {self.desc.thread_num} dataset shards, got "
                f"{len(datasets)}")
        step_lock = threading.Lock()
        self.workers = [
            HogwildWorker(i, executor, program, ds, scope, self.desc,
                          step_lock=step_lock)
            for i, ds in enumerate(datasets)]
        errors = []

        def run(w):
            try:
                w.train()
            except BaseException as e:  # exception_holder semantics
                errors.append(e)

        threads = [threading.Thread(target=run, args=(w,), daemon=True)
                   for w in self.workers]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return sum(w.steps for w in self.workers)


def train_from_dataset_multithread(executor, program, dataset_factory,
                                   thread_num: int = 2, fetch_list=None,
                                   fetch_info=None, print_period=100,
                                   scope=None):
    """Thread-pool train_from_dataset (reference: Executor.
    train_from_dataset with TrainerDesc.thread_num > 1 → MultiTrainer).

    `dataset_factory(worker_id, num_workers)` builds each thread's shard
    — with NativeDataset, pass trainer_id=worker_id,
    num_trainers=num_workers so the C++ reader shards the filelist.
    """
    desc = TrainerDesc(thread_num=thread_num, fetch_list=fetch_list,
                       fetch_info=fetch_info, print_period=print_period)
    datasets = [dataset_factory(i, desc.thread_num)
                for i in range(desc.thread_num)]
    return MultiTrainer(desc).train(executor, program, datasets,
                                    scope=scope)
