"""Trainer stack entry points (reference: Executor.train_from_dataset →
TrainerFactory → C++ MultiTrainer/DistMultiTrainer + DeviceWorkers,
framework/trainer.h:38, device_worker.h:103, SURVEY §3.6).

Round-1: a host-side trainer loop over a Dataset's file shards feeding the
compiled step (HogwildWorker semantics, hogwild_worker.cc:163); the C++
datafeed library (paddle_tpu/data/) supplies the pipelined batch source.
"""

from __future__ import annotations

from typing import Optional, Sequence


def train_from_dataset(executor, program=None, dataset=None, scope=None,
                       thread=0, debug=False, fetch_list=None,
                       fetch_info=None, print_period=100):
    from .core import framework

    program = program or framework.default_main_program()
    if dataset is None:
        raise ValueError("dataset is required")
    fetch_list = fetch_list or []
    step = 0
    for feed in dataset._iter_batches():
        vals = executor.run(program, feed=feed, fetch_list=fetch_list,
                            scope=scope)
        if debug and fetch_list and step % print_period == 0:
            names = fetch_info or [getattr(f, "name", str(f)) for f in fetch_list]
            print(f"step {step}: " + ", ".join(
                f"{n}={v}" for n, v in zip(names, vals)))
        step += 1
    return None


def infer_from_dataset(executor, program=None, dataset=None, scope=None,
                       thread=0, debug=False, fetch_list=None,
                       fetch_info=None, print_period=100):
    infer_prog = (program or __import__("paddle_tpu.core.framework",
                                        fromlist=["default_main_program"]
                                        ).default_main_program()).clone(for_test=True)
    return train_from_dataset(executor, infer_prog, dataset, scope, thread,
                              debug, fetch_list, fetch_info, print_period)
