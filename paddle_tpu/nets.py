"""Composite networks (reference: python/paddle/fluid/nets.py —
simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention)."""

from __future__ import annotations

from . import layers

__all__ = ["simple_img_conv_pool", "img_conv_group", "glu",
           "scaled_dot_product_attention", "sequence_conv_pool"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    conv_out = layers.conv2d(input, num_filters, filter_size,
                             stride=conv_stride, padding=conv_padding,
                             dilation=conv_dilation, groups=conv_groups,
                             param_attr=param_attr, bias_attr=bias_attr, act=act)
    return layers.pool2d(conv_out, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride, pool_padding=pool_padding,
                         global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    tmp = input
    if isinstance(conv_num_filter, int):
        conv_num_filter = [conv_num_filter]
    n = len(conv_num_filter)

    def per(v):
        return v if isinstance(v, (list, tuple)) else [v] * n

    padding, fsize, acts, pattrs = (per(conv_padding), per(conv_filter_size),
                                    per(conv_act), per(param_attr))
    drops = per(conv_batchnorm_drop_rate)
    for i in range(n):
        act = acts[i]
        local_act = None if conv_with_batchnorm else act
        tmp = layers.conv2d(tmp, conv_num_filter[i], fsize[i],
                            padding=padding[i], param_attr=pattrs[i],
                            act=local_act)
        if conv_with_batchnorm:
            tmp = layers.batch_norm(tmp, act=act)
            if drops[i] > 0:
                tmp = layers.dropout(tmp, dropout_prob=drops[i])
    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """reference: nets.py scaled_dot_product_attention — THE attention
    primitive; multi-head split/recombine + softmax(QK^T/sqrt(d))V. On TPU
    this whole block fuses into MXU matmuls; the pallas flash-attention
    kernel (paddle_tpu/ops/pallas/) is the long-sequence fast path."""
    d_key = queries.shape[-1] // num_heads

    def split_heads(x):
        if num_heads == 1:
            return x
        b, t, d = x.shape[0], x.shape[1], x.shape[2]
        x = layers.reshape(x, [0, t, num_heads, d // num_heads])
        return layers.transpose(x, [0, 2, 1, 3])

    def combine_heads(x):
        if num_heads == 1:
            return x
        x = layers.transpose(x, [0, 2, 1, 3])
        return layers.reshape(x, [0, x.shape[1], x.shape[2] * x.shape[3]])

    q, k, v = split_heads(queries), split_heads(keys), split_heads(values)
    product = layers.matmul(q, k, transpose_y=True, alpha=d_key ** -0.5)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    ctx = layers.matmul(weights, v)
    return combine_heads(ctx)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None):
    """reference: nets.py:251 `sequence_conv_pool` — sequence_conv over
    the padded [N, T, D] batch followed by sequence_pool."""
    from .layers.sequence import sequence_conv, sequence_pool

    conv_out = sequence_conv(input, num_filters=num_filters,
                             filter_size=filter_size,
                             param_attr=param_attr, bias_attr=bias_attr,
                             act=act)
    return sequence_pool(conv_out, pool_type=pool_type)
