"""TPU-native parallelism.

This package is the rebuild of the reference's entire multi-device stack —
ParallelExecutor + multi_devices_graph_pass + NCCL op handles
(paddle/fluid/framework/parallel_executor.cc, details/all_reduce_op_handle.cc)
and the distributed frontend (python/paddle/fluid/transpiler/,
incubate/fleet/) — on top of jax.sharding:

- mesh.py      : device Mesh management (dp/tp/pp/sp/ep axes; ICI×DCN
                 factorization replaces hierarchical allreduce)
- sharding.py  : logical-axis sharding rules (the BuildStrategy equivalent)
- train.py     : sharded train-step builder (the ParallelExecutor equivalent)
- strategy.py  : fleet DistributedStrategy parity object
- fleet.py     : fleet facade (init / distributed_optimizer / barriers)
- launch.py    : multi-host launcher over jax.distributed.initialize
"""

from .mesh import (  # noqa: F401
    MeshConfig, auto_mesh, current_mesh, get_mesh, make_hybrid_mesh,
    mesh_guard, make_mesh, resize_mesh,
)
from .sharding import (  # noqa: F401
    LogicalRules, NO_SHARD, in_manual_region, logical_to_mesh, shard,
    shard_params_spec, with_rules, current_rules,
)
from . import collective  # noqa: F401
from .strategy import DistributedStrategy  # noqa: F401
from .role_maker import (  # noqa: F401
    PaddleCloudRoleMaker, Role, RoleMakerBase, UserDefinedRoleMaker,
)
from .fleet import fleet, Fleet, DistributedOptimizer  # noqa: F401
from .spmd_executor import SPMDRunner  # noqa: F401
from .checkpoint import (  # noqa: F401
    latest_step_dir, restore_train_state, save_train_state,
)
from .train import train_loop  # noqa: F401
