"""Device mesh management.

Reference equivalents: NCCLContextMap/NCCLCommunicator ring construction
(paddle/fluid/platform/nccl_helper.h:90,179) and the num_trainers/trainer_id
rank math (parallel_executor.cc:469). On TPU a single `jax.sharding.Mesh`
with named axes replaces all ring bookkeeping; XLA chooses the collective
algorithm per axis.

Axis conventions (used by models/ and __graft_entry__):
  dp — data parallel (batch dim)         ↔ reference AllReduce builder
  tp — tensor parallel (hidden dims)     ↔ absent in reference (free on TPU)
  sp — sequence/context parallel         ↔ absent in reference
  pp — pipeline stages                   ↔ PipelineTrainer/SectionWorker
  ep — expert parallel (MoE)             ↔ absent in reference

The hierarchical-allreduce knob (BuildStrategy.use_hierarchical_allreduce,
nccl_helper.h:246) maps to mesh factorization: put DCN-connected hosts on the
outer axis of `create_hybrid_device_mesh` so 'dp' gradients reduce
intra-slice over ICI first.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("pp", "dp", "ep", "sp", "tp")  # outer (slow, DCN-ish) → inner (ICI)


@dataclasses.dataclass
class MeshConfig:
    """Named axis sizes; -1 on one axis = absorb remaining devices."""

    dp: int = -1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        fixed = [a for a, s in sizes.items() if s != -1]
        free = [a for a, s in sizes.items() if s == -1]
        prod = math.prod(sizes[a] for a in fixed)
        if free:
            if len(free) > 1:
                raise ValueError("at most one mesh axis may be -1")
            if n_devices % prod:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {sizes}")
            sizes[free[0]] = n_devices // prod
        elif prod != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {prod} devices, have {n_devices}")
        return sizes


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence] = None,
              **axis_sizes) -> Mesh:
    """Build a Mesh with the standard axis order. `make_mesh(dp=4, tp=2)`."""
    if config is None:
        config = MeshConfig(**axis_sizes) if axis_sizes else MeshConfig()
    devices = list(devices) if devices is not None else jax.devices()
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXIS_ORDER)


def make_hybrid_mesh(config: Optional[MeshConfig] = None, **axis_sizes) -> Mesh:
    """Multi-host mesh with DCN×ICI factorization: the OUTER axes (pp, dp —
    AXIS_ORDER) ride the slow inter-host network, inner axes stay on ICI.
    This is the reference's hierarchical allreduce (nccl_helper.h:246) as a
    mesh shape instead of hand-built two-level rings."""
    from jax.experimental import mesh_utils

    config = config or (MeshConfig(**axis_sizes) if axis_sizes else MeshConfig())
    if jax.process_count() == 1:
        return make_mesh(config)
    sizes = config.resolve(jax.device_count())
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    # split each axis into (dcn, ici) factors greedily from the outside;
    # one DCN granule per process (process_is_granule)
    dcn = [1] * len(shape)
    remaining_hosts = jax.process_count()
    ici = list(shape)
    for i, s in enumerate(shape):
        g = math.gcd(s, remaining_hosts)
        dcn[i] = g
        ici[i] = s // g
        remaining_hosts //= g
        if remaining_hosts == 1:
            break
    if remaining_hosts != 1:
        raise ValueError(
            f"cannot factor {jax.process_count()} hosts into mesh {sizes}")
    # the host factor must be fully absorbed by the OUTER (pp/dp/ep) axes —
    # a DCN factor on sp/tp would put per-layer collectives on the slow
    # network, defeating the point of the hierarchy
    inner_start = AXIS_ORDER.index("sp")
    if any(d > 1 for d in dcn[inner_start:]):
        raise ValueError(
            f"hybrid mesh would place a DCN factor on an inner axis "
            f"(dcn={dict(zip(AXIS_ORDER, dcn))}); grow pp/dp/ep to cover "
            f"{jax.process_count()} hosts or use make_mesh()")
    devices = mesh_utils.create_hybrid_device_mesh(
        tuple(ici), tuple(dcn), devices=jax.devices(),
        process_is_granule=True)
    return Mesh(devices, AXIS_ORDER)


def resize_mesh(mesh: Mesh, n_devices: int,
                devices: Optional[Sequence] = None,
                absorb: str = "dp") -> Mesh:
    """Re-form `mesh` for a new world size (elastic scale-in/out,
    ROADMAP item 3): every axis keeps its size except `absorb` (default
    'dp'), which expands or shrinks to cover `n_devices`. Raises
    ValueError when the fixed axes cannot divide the new world — e.g.
    a tp=2 mesh cannot re-form on 3 devices; the elastic driver
    surfaces that as a refusal instead of building a broken mesh.

    Executables compiled against the old mesh are world-size-keyed
    (SPMDRunner caches, _JitDispatch signatures, the PR 6 persistent
    compile cache), so nothing stale can run on the new mesh — callers
    drop/rebuild their step functions after a resize
    (`SPMDRunner.resize`, `distributed.elastic.elastic_train_loop`)."""
    if n_devices < 1:
        raise ValueError(f"cannot resize mesh to {n_devices} devices")
    if absorb not in mesh.axis_names:
        raise ValueError(f"absorb axis {absorb!r} not in {mesh.axis_names}")
    sizes = {a: (-1 if a == absorb else int(mesh.shape[a]))
             for a in mesh.axis_names}
    config = MeshConfig(**{a: sizes.get(a, 1) for a in AXIS_ORDER})
    devices = list(devices) if devices is not None else jax.devices()
    if len(devices) < n_devices:
        raise ValueError(
            f"resize to {n_devices} devices but only {len(devices)} "
            f"are available")
    return make_mesh(config, devices=devices[:n_devices])


def auto_mesh(n_devices: Optional[int] = None, model_parallel: int = 1) -> Mesh:
    """Data-parallel mesh with optional inner tensor-parallel axis —
    the default the reference's ParallelExecutor gives you."""
    devs = jax.devices()[:n_devices] if n_devices else jax.devices()
    return make_mesh(MeshConfig(dp=-1, tp=model_parallel), devices=devs)


_mesh_stack: List[Mesh] = []


def current_mesh() -> Optional[Mesh]:
    return _mesh_stack[-1] if _mesh_stack else None


def get_mesh() -> Mesh:
    m = current_mesh()
    if m is None:
        m = auto_mesh()
        _mesh_stack.append(m)
    return m


@contextlib.contextmanager
def mesh_guard(mesh: Mesh):
    _mesh_stack.append(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        _mesh_stack.pop()
