"""Role makers: who am I in the cluster?

Reference: incubate/fleet/base/role_maker.py — PaddleCloudRoleMaker reads
PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS / TRAINING_ROLE env vars set by
paddle.distributed.launch; UserDefinedRoleMaker takes them explicitly.

TPU-native: the same env contract (so launch scripts port unchanged), plus
the JAX coordinator address for jax.distributed.initialize.
"""

from __future__ import annotations

import os
from typing import List, Optional


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._worker_index = 0
        self._worker_num = 1
        self._server_endpoints: List[str] = []
        self._worker_endpoints: List[str] = []
        self._role = Role.WORKER

    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return self._role == Role.SERVER

    def is_first_worker(self) -> bool:
        return self.is_worker() and self._worker_index == 0

    def worker_index(self) -> int:
        return self._worker_index

    def worker_num(self) -> int:
        return self._worker_num

    def server_num(self) -> int:
        return len(self._server_endpoints)

    def get_trainer_endpoints(self) -> List[str]:
        return self._worker_endpoints

    def get_pserver_endpoints(self) -> List[str]:
        return self._server_endpoints

    def coordinator_address(self) -> Optional[str]:
        if self._worker_endpoints:
            return self._worker_endpoints[0]
        return None


class PaddleCloudRoleMaker(RoleMakerBase):
    """Env-var driven (reference role_maker.py PaddleCloudRoleMaker)."""

    def __init__(self, is_collective: bool = True):
        super().__init__()
        self._is_collective = is_collective
        self._worker_index = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = [e for e in eps.split(",") if e]
        self._worker_num = max(1, len(self._worker_endpoints)) \
            if self._worker_endpoints else int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
        pservers = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints = [e for e in pservers.split(",") if e]
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._role = Role.SERVER if role == "PSERVER" else Role.WORKER


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id: int = 0, role: int = Role.WORKER,
                 worker_num: int = 1, server_endpoints: Optional[List[str]] = None,
                 worker_endpoints: Optional[List[str]] = None):
        super().__init__()
        self._worker_index = current_id
        self._role = role
        self._worker_num = worker_num
        self._server_endpoints = server_endpoints or []
        self._worker_endpoints = worker_endpoints or []
