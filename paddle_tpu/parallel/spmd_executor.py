"""SPMD (per-device-graph) program execution — the collective-transpiler
runtime.

Reference execution model: transpiler/collective.py rewrites the single-device
program with explicit c_allreduce ops, then EACH process runs its own graph
and the collectives synchronize (multi-process NCCL2 mode, SURVEY §2.5).

TPU-native: one process runs the program under jax.shard_map with the 'dp'
axis manual — each device traces the same op sequence on its batch shard, and
the program's explicit collective ops (ops/collective.py) lower to real
lax.psum/all_gather over the axis. This is the runtime that makes the c_*
collective op family first-class (under plain pjit GSPMD they'd be
redundant)."""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..observability import health as _health
from ..observability import perfwatch as _perfwatch
from ..observability import telemetry as _telemetry
from ..observability import tracing as _tracing
from ..core import framework, lowering
from ..core import precision as _precision
from ..core.executor import (RNG_STATE_VAR, Scope, _as_fetch_name,
                             _finish_fetches, _JitDispatch, _health_scan,
                             mesh_device_kind, _normalize_feed,
                             _record_live_device_memory, global_scope)
from ..core.framework import Program


def _repatriate(v, mesh, mesh_devs):
    """Move a value committed to devices OUTSIDE `mesh` back under it.
    After an elastic resize (SPMDRunner.resize), persistable state and
    the rng var in the scope were written by the old-mesh executable
    and live on the old device set — dispatching them into the new
    mesh's shard_map would fail with an incompatible-devices error.
    Replicated re-placement is correct here because SPMD state vars and
    the rng are replicated by construction (in_specs P()).

    Only values carrying a NamedSharding on a DIFFERENT mesh move:
    single-device/default placements were always accepted by jit (the
    pre-elastic behavior, kept untouched and transfer-free), while an
    old-mesh NamedSharding fails jit's committed-device consistency
    check in BOTH directions — scale-in (old set ⊃ new) and scale-out
    (old set ⊂ new) alike, hence mesh equality, not subset. `mesh_devs`
    is the mesh's frozenset of devices, precomputed by the caller; the
    `sharding.mesh is mesh` fast path is the common case (state written
    back by THIS mesh's executable) and runs per state var per step."""
    sharding = getattr(v, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return v
    if sharding.mesh is mesh or \
            frozenset(sharding.mesh.devices.flat) == mesh_devs:
        return v
    return jax.device_put(v, NamedSharding(mesh, P()))


def _shard_map(f, mesh, in_specs, out_specs, axis_names, check_vma):
    """jax.shard_map with a fallback to the pre-0.5 experimental API
    (jax 0.4.x ships it as jax.experimental.shard_map without the
    axis_names/check_vma kwargs; check_rep is the old name for the
    replication check we disable)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, axis_names=axis_names,
                             check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _esm

    return _esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=bool(check_vma))


class SPMDRunner:
    """Run a (collective-transpiled) Program with the 'dp' axis manualized.

    Feeds are split on dim 0 across 'dp'; persistable state is replicated.
    Fetches are averaged over devices unless reduce='first'.
    """

    def __init__(self, program: Program, mesh: Mesh, axis: str = "dp",
                 reduce: str = "mean"):
        self.program = program
        self.mesh = mesh
        self.axis = axis
        self.reduce = reduce
        self._cache: Dict[Any, Any] = {}
        self._mesh_devs = frozenset(mesh.devices.flat)

    def resize(self, mesh: Mesh) -> "SPMDRunner":
        """Point the runner at a re-formed mesh (elastic scale-in/out).
        Compiled steps capture the mesh at build time, so the step
        cache is dropped whenever the mesh object changes; returning to
        a PREVIOUS world size re-pays only compile-cache I/O, not a
        fresh XLA compile (PR 6's persistent cache keys on the lowered
        module, which embeds the mesh shape)."""
        if mesh is not self.mesh:
            self.mesh = mesh
            self._mesh_devs = frozenset(mesh.devices.flat)
            self._cache.clear()
        return self

    def run(self, executor, feed=None, fetch_list=None, scope: Optional[Scope] = None,
            return_numpy: bool = True, sync: bool = True):
        # timer covers feed normalization + cache lookup + dispatch,
        # matching Executor.run's span
        t0 = time.perf_counter()
        host0 = _telemetry.host_blocked_total()
        program = self.program
        scope = scope if scope is not None else global_scope()
        feed = dict(feed or {})
        fetch_names = tuple(_as_fetch_name(f) for f in (fetch_list or []))

        policy = _precision.resolve(program)
        norm_feed = _normalize_feed(program, feed, policy)
        sig = tuple(sorted((k, tuple(v.shape), str(v.dtype))
                           for k, v in norm_feed.items()))
        key = (program._version, sig, fetch_names, policy.name)
        step = self._cache.get(key)
        if step is None:
            step = self._build(tuple(norm_feed), fetch_names, policy)
            self._cache[key] = step

        rng = _repatriate(executor._get_rng(scope, program), self.mesh,
                          self._mesh_devs)
        with _tracing.step_span("spmd.step", cat="step", axis=self.axis):
            fetches, new_states, new_rng = step(scope, norm_feed, rng)
        for n, v in new_states.items():
            scope.set_var(n, v)
        scope.set_var(RNG_STATE_VAR, new_rng)
        level = _health.check_level()
        if level:
            # a NaN produced on ANY shard reaches the reduced/concatenated
            # fetch, so this one scan attributes shard divergence to the
            # fetched variable at site "spmd_fetch"
            _health_scan("spmd_fetch", zip(fetch_names, fetches), level)
        if _health.introspection_enabled():
            # multi-device runs are where buffer leaks hurt most — the
            # live-bytes gauge must not go dark on the SPMD-only path
            _record_live_device_memory()
        out = _finish_fetches(fetches, return_numpy, sync, site="spmd")
        wall = time.perf_counter() - t0
        _telemetry.record_spmd_step(self.axis, wall,
                                    step.collective_counts)
        # live-MFU sample: retained cost_analysis FLOPs of the SPMD
        # executable over this step's wall window, plus the step-time
        # breakdown (measured host-blocked delta; ring-allreduce
        # collective ESTIMATE from the mutable-state payload)
        n_dev = self.mesh.size
        dev_kind = mesh_device_kind(self.mesh)
        cost = step.dispatch.current_cost() or {}
        host = max(0.0, _telemetry.host_blocked_total() - host0)
        coll = _perfwatch.estimate_collective_seconds(
            dev_kind, n_dev, getattr(step, "payload_bytes", 0),
            sum(step.collective_counts.values()))
        _perfwatch.record_step(
            "spmd", wall, flops=cost.get("flops"),
            host_blocked=min(host, wall), collective_seconds=coll,
            device_kind=dev_kind, n_devices=n_dev)
        return out

    def _build(self, feed_names: Tuple[str, ...],
               fetch_names: Tuple[str, ...],
               policy: Optional["_precision.PrecisionPolicy"] = None):
        policy = policy if policy is not None \
            else _precision.resolve(self.program)
        desc = self.program.desc
        axis = self.axis
        n_dev = self.mesh.shape[axis]
        reads, writes = lowering.analyze_state_vars(desc, set(feed_names))
        persistable = {v.name for b in desc.blocks for v in b.vars.values()
                       if v.persistable}
        for n in fetch_names:
            if n in persistable and n not in reads and n not in writes:
                reads.append(n)
        const_reads = tuple(n for n in reads if n not in writes)
        mut_reads = tuple(n for n in reads if n in writes)
        writes = tuple(writes)
        is_test = self.program._is_test
        reduce = self.reduce

        # classify fetches statically by their inferred var shapes: scalar
        # fetches (loss-like) reduce across devices; batched fetches
        # concatenate shards (reference: FetchOpHandle merges per-device
        # results)
        def _is_scalar_fetch(n):
            vd = None
            for b in desc.blocks:
                if n in b.vars:
                    vd = b.vars[n]
                    break
            shp = vd.shape if vd is not None else None
            return shp is None or len(shp) == 0 or \
                (len(shp) == 1 and shp[0] == 1)

        scalar_fetch = {n: _is_scalar_fetch(n) for n in fetch_names}

        def device_step(feeds, const_states, mut_states, rng):
            env = dict(const_states)
            env.update(mut_states)
            env.update(feeds)
            if policy.cast_state:
                env = {k: _precision.cast_floating(v,
                                                   policy.compute_dtype)
                       for k, v in env.items()}
            # per-device rng stream (reference: different seed per trainer)
            rng_local = jax.random.fold_in(rng, jax.lax.axis_index(axis))
            step_key, new_rng = jax.random.split(rng_local)
            with _precision.autocast(policy):
                lowering.lower_block(desc, 0, env, rng_key=step_key,
                                     is_test=is_test)
            fetches = []
            for n in fetch_names:
                if n not in env:
                    raise lowering.LoweringError(
                        f"fetch var '{n}' was not produced by the program")
                v = env[n]
                if scalar_fetch[n] and reduce == "mean":
                    v = jax.lax.pmean(v.astype(jnp.float32),
                                      axis).astype(v.dtype)
                fetches.append(v)
            new_states = {n: env[n] for n in writes if n in env}
            # advance the global rng identically on all devices
            new_global_rng = jax.random.split(rng)[1]
            return fetches, new_states, new_global_rng

        feed_specs = {n: P(axis) for n in feed_names}
        fetch_specs = [P() if scalar_fetch[n] else P(axis)
                       for n in fetch_names]
        sm = _shard_map(
            device_step,
            mesh=self.mesh,
            in_specs=(feed_specs,
                      {n: P() for n in const_reads},
                      {n: P() for n in mut_reads},
                      P()),
            out_specs=(fetch_specs,
                       {n: P() for n in writes},
                       P()),
            axis_names={axis},
            check_vma=False)
        jitted = _JitDispatch(jax.jit(sm), "spmd",
                              meta={"axis": axis, "devices": int(n_dev),
                                    "device_kind":
                                        mesh_device_kind(self.mesh)},
                              policy=policy.name)

        mesh = self.mesh  # pinned: resize() clears the cache, so a step
        # never outlives the mesh it was built for
        mesh_devs = self._mesh_devs

        def step(scope: Scope, feed, rng):
            def _state(n):
                v = scope.find_var(n)
                if v is None:
                    raise RuntimeError(
                        f"variable '{n}' missing from scope — run the "
                        f"startup program first")
                return _repatriate(v, mesh, mesh_devs)

            const_states = {n: _state(n) for n in const_reads}
            mut_states = {n: _state(n) for n in mut_reads}
            for n, v in feed.items():
                if v.shape and v.shape[0] % n_dev:
                    raise ValueError(
                        f"feed '{n}' batch {v.shape[0]} not divisible by "
                        f"{n_dev} devices on axis '{axis}'")
            # allreduce payload ≈ the mutable (gradient-updated) state:
            # what run()'s collective-time estimate is grounded on
            step.payload_bytes = sum(
                int(getattr(v, "nbytes", 0))
                for v in mut_states.values())
            return jitted(feed, const_states, mut_states, rng)

        # static per-program collective census: the c_* ops the transpiler
        # inserted, charged to the registry once per executed step
        counts: Dict[str, int] = {}
        for b in desc.blocks:
            for op in b.ops:
                if op.type.startswith("c_"):
                    counts[op.type] = counts.get(op.type, 0) + 1
        step.collective_counts = counts
        step.dispatch = jitted  # cost_analysis access for the MFU gauge
        step.payload_bytes = 0
        return step
